"""Pluggable consensus vote policies — the registry and the wire contract.

The vote step of every kernel wire (dense XLA ``ops.consensus_tpu``,
Pallas ``ops.consensus_pallas``, member-stream ``ops.consensus_segment``)
reduces a padded ``(family, position)`` pair of member planes into one
consensus ``(position,)`` base/quality pair.  This module turns the
*rule* applied to those planes into a pluggable :class:`VotePolicy`:

- ``decide(counts, quals, lengths) -> (bases, phreds, fail_mask)`` is the
  plane-level protocol: ``counts`` is the effective one-hot vote plane
  ``(F, L, NUM_BASES)`` (bool; quality-demoted members vote the N lane,
  padded member slots vote no lane), ``quals`` the member-masked Phred
  plane ``(F, L)`` int32, ``lengths`` the family's true member count.
  ``fail_mask`` marks positions the policy abstains on (emitted as N/0).
- :meth:`VotePolicy.family_vote_fn` adapts ``decide`` to the per-family
  callable signature the kernels ``vmap``/gather over — the single
  entry point behind the dense-XLA, Pallas-fallback, and stream wires.

Selection mirrors ``ops.consensus_tpu.set_kernel_policy``: a module
global installed once per stage/gang (``set_vote_policy``) and read by
every kernel call site (``get_vote_policy``), so the choice applies to
stages, serve gangs, and bench without threading a parameter through
every signature.  The default is always ``majority`` — the reference
rational-cutoff vote, byte-identical to the committed goldens.
"""

from __future__ import annotations

import jax.numpy as jnp

from consensuscruncher_tpu.utils.phred import N, NUM_BASES, PAD

#: The policy every wire runs when nothing was installed — the reference
#: rational-cutoff majority vote (golden-pinned).
DEFAULT_POLICY = "majority"


def family_planes(bases, quals, fam_size, *, qual_threshold):
    """Member planes -> the plane-level ``decide`` operands.

    Reproduces exactly the effective-vote construction of the reference
    kernel (``policies.majority.majority_family_vote``): members below
    the quality threshold vote N, padded member slots vote nothing (PAD
    matches no lane), and the qual plane is masked to real members.
    """
    fam_cap, _length = bases.shape
    member = (jnp.arange(fam_cap, dtype=jnp.int32) < fam_size)[:, None]  # (F, 1)
    eff = jnp.where(quals >= qual_threshold, bases, jnp.uint8(N))
    eff = jnp.where(member, eff, jnp.uint8(PAD))
    lanes = jnp.arange(NUM_BASES, dtype=jnp.uint8)
    onehot = eff[:, :, None] == lanes  # (F, L, NUM_BASES) bool
    mq = jnp.where(member, quals.astype(jnp.int32), 0)  # (F, L)
    return onehot, mq


def modal_with_tiebreak(votes):
    """Shared lexicographic (count desc, first-seen asc) modal pick over a
    ``(F, L, NUM_BASES)`` bool vote plane -> ``(modal, max_count)``.

    Same tie-break as the reference (CPython ``Counter.most_common``
    insertion order): among bases at the max count, the one first voted
    by the earliest member wins.  Int32-safe (no combined score product).
    """
    fam_cap = votes.shape[0]
    counts = votes.sum(axis=0, dtype=jnp.int32)  # (L, NUM_BASES)
    member_idx = jnp.arange(fam_cap, dtype=jnp.int32)[:, None, None]
    first_seen = jnp.where(votes, member_idx, fam_cap).min(axis=0)
    max_count = counts.max(axis=1)  # (L,)
    cand_first = jnp.where(counts == max_count[:, None], first_seen, fam_cap + 1)
    modal = cand_first.argmin(axis=1).astype(jnp.int32)  # (L,)
    return modal, max_count


class VotePolicy:
    """One consensus vote rule over the family count/qual planes.

    Subclasses set :attr:`name` and implement :meth:`decide`.  Policies
    must be pure jnp (they run inside the kernels' jitted programs) and
    deterministic — the serve plane's result cache and journal key on
    the policy *name*, so a name must always produce the same bytes.
    """

    #: registry key; also the ``--policy`` CLI value and the closed obs
    #: label value (``obs.registry.POLICY_NAMES``)
    name: str = "?"

    def decide(self, counts, quals, lengths, *, num, den, qual_threshold,
               qual_cap):
        """Plane-level vote: ``(F, L, B)`` one-hot counts + ``(F, L)``
        masked quals + family size -> ``(bases, phreds, fail_mask)``
        (each ``(L,)``; fail positions are masked to N/0 by the wire
        adapters)."""
        raise NotImplementedError

    def family_vote_fn(self, *, num, den, qual_threshold, qual_cap,
                       with_qc=False):
        """Per-family kernel callable ``(bases, quals, fam_size) ->
        (out_base, out_qual[, votes, disagree])`` — the signature every
        wire (dense vmap, stream gather, Pallas fallback) consumes.

        The QC rider (total votes / disagree-with-modal per position) is
        a property of the member planes, not of the policy's choice, so
        it stays policy-independent — per-policy QC spectra remain
        comparable in ``cct qc report``.
        """

        def fn(bases, quals, fam_size):
            onehot, mq = family_planes(bases, quals, fam_size,
                                       qual_threshold=qual_threshold)
            out_b, out_q, fail = self.decide(
                onehot, mq, fam_size, num=num, den=den,
                qual_threshold=qual_threshold, qual_cap=qual_cap)
            out_b = jnp.where(fail, jnp.uint8(N), out_b).astype(jnp.uint8)
            out_q = jnp.where(fail, 0, out_q).astype(jnp.uint8)
            if with_qc:
                counts = onehot.sum(axis=0, dtype=jnp.int32)
                votes = counts.sum(axis=1)
                return out_b, out_q, votes, votes - counts.max(axis=1)
            return out_b, out_q

        return fn


# ------------------------------------------------------------- registry

_REGISTRY: dict[str, VotePolicy] = {}


def register_policy(policy: VotePolicy) -> VotePolicy:
    """Register a policy instance under its name (import-time; the three
    built-ins register when ``consensuscruncher_tpu.policies`` loads)."""
    if not policy.name or policy.name == "?":
        raise ValueError("vote policy must set a name")
    _REGISTRY[policy.name] = policy
    return policy


def _ensure_builtins() -> None:
    """Load the built-in policy modules for their registration side
    effects — kernels import only this module, so resolution by name
    must not depend on who imported the package first."""
    from consensuscruncher_tpu.policies import (  # noqa: F401
        delegation,
        distilled,
        majority,
    )


def available_policies() -> tuple[str, ...]:
    """Registered policy names, sorted (the ``--policy`` vocabulary)."""
    _ensure_builtins()
    return tuple(sorted(_REGISTRY))


def get_policy(name: str) -> VotePolicy:
    """Resolve a policy by name; unknown names raise the ValueError the
    serve admission path surfaces as a typed ``bad_request`` refusal."""
    _ensure_builtins()
    policy = _REGISTRY.get(str(name))
    if policy is None:
        raise ValueError(
            f"unknown vote policy {name!r}; expected one of "
            f"{available_policies()}")
    return policy


# ------------------------------------------- module-global selection hook
#
# Same shape as ``ops.consensus_tpu.set_kernel_policy``: installed once
# (stage entry, serve gang dispatch) and read by every kernel call site.
# ``None`` means the golden-pinned default.

_vote_policy: VotePolicy | None = None


def set_vote_policy(policy) -> None:
    """Install the active vote policy: a name, a :class:`VotePolicy`, or
    ``None`` to restore the majority default."""
    global _vote_policy
    if policy is None or isinstance(policy, VotePolicy):
        _vote_policy = policy
    else:
        _vote_policy = get_policy(str(policy))


def get_vote_policy() -> VotePolicy:
    """The active policy (the majority default when none installed)."""
    if _vote_policy is not None:
        return _vote_policy
    return get_policy(DEFAULT_POLICY)


def installed_vote_policy() -> VotePolicy | None:
    """The raw installed hook value (``None`` = default) — for callers
    that install temporarily and must restore the prior state exactly."""
    return _vote_policy
