"""Pluggable consensus vote policies.

Importing this package registers the three built-ins (majority,
delegation, distilled) and exposes the registry + selection hook the
kernel wires dispatch through.  See ``policies/base.py`` for the plane
protocol and README "Consensus policies" for when each policy wins.
"""

from consensuscruncher_tpu.policies.base import (
    DEFAULT_POLICY,
    VotePolicy,
    available_policies,
    family_planes,
    get_policy,
    get_vote_policy,
    modal_with_tiebreak,
    register_policy,
    set_vote_policy,
)
from consensuscruncher_tpu.policies.majority import (
    MajorityPolicy,
    majority_family_vote,
)
from consensuscruncher_tpu.policies.delegation import (
    DELEGATE_THRESHOLD,
    DelegationPolicy,
    delegated_weights,
)
from consensuscruncher_tpu.policies.distilled import (
    CHECKPOINT_ENV,
    DistilledPolicy,
    checkpoint_path,
    load_checkpoint,
)

__all__ = [
    "DEFAULT_POLICY",
    "DELEGATE_THRESHOLD",
    "CHECKPOINT_ENV",
    "VotePolicy",
    "MajorityPolicy",
    "DelegationPolicy",
    "DistilledPolicy",
    "available_policies",
    "checkpoint_path",
    "delegated_weights",
    "family_planes",
    "get_policy",
    "get_vote_policy",
    "load_checkpoint",
    "majority_family_vote",
    "modal_with_tiebreak",
    "register_policy",
    "set_vote_policy",
]
