"""Delegation policy: low-quality reads delegate their vote weight to
high-quality same-family reads.

Motivation ("When Does Delegation Beat Majority?"): when a family mixes
a few trustworthy reads with many degraded ones, plain majority either
drops the position (the noisy votes dilute the modal fraction below the
cutoff) or — worse — passes a coordinated noise base.  Delegation keeps
every member's unit of vote *weight* (so the cutoff denominator still
reflects the whole family) but lets members below a quality floor hand
their weight to the members above it:

- each member holds weight 1;
- members with Phred >= ``delegate_threshold`` ("high") keep their
  weight and vote their own base;
- members below it ("low") split their weight equally across the high
  members, voting whatever those delegates vote;
- when a position has NO high member, nobody can receive weight, so
  every member keeps its own vote — exact majority semantics (the
  documented all-low fallback).

**Weight conservation invariant**: total weight per position is always
exactly ``fam_size`` (delegation moves weight, never creates or drops
it) — :func:`delegated_weights` exposes the per-member weights so tests
pin the invariant directly.

**Exact integer form**: with equal splitting, every high member's weight
is the same ``1 + n_low / n_high``, so base ``b``'s weighted count is
``count_high[b] * fam_size / n_high`` and the cutoff compare
``weighted >= (num/den) * fam_size`` reduces to

    ``count_high[b] * den >= num * n_high``

— majority among the high members with the rational cutoff applied to
``n_high``.  The decide path computes that integer form (same exactness
discipline as the majority kernel: no float compare anywhere), and the
float weights exist only for the invariant/tests.
"""

from __future__ import annotations

import jax.numpy as jnp

from consensuscruncher_tpu.policies.base import (
    VotePolicy,
    modal_with_tiebreak,
    register_policy,
)
from consensuscruncher_tpu.utils.phred import N

#: Phred floor for a member to keep its own vote.  Chosen between the
#: simulator's degraded-read band (<= 15) and its healthy band (>= 25);
#: part of the policy's identity — changing it changes output bytes, so
#: it is a class constant, not a tunable.
DELEGATE_THRESHOLD = 20


def delegated_weights(quals, member, fam_size, threshold=DELEGATE_THRESHOLD):
    """Per-member, per-position float vote weights ``(F, L)``.

    Documents (and lets tests pin) the conservation invariant:
    ``weights.sum(axis=0) == member.sum(axis=0)`` everywhere — the total
    weight is the member count, delegated or not.
    """
    quals = jnp.asarray(quals)
    member = jnp.asarray(member)
    high = member & (quals >= threshold)
    n_high = high.sum(axis=0)
    n_low = member.sum(axis=0) - n_high
    w_high = 1.0 + n_low / jnp.maximum(n_high, 1)
    weights = jnp.where(high, w_high[None, :], 0.0)
    # all-low fallback: no delegate exists, everyone keeps their weight
    return jnp.where((n_high == 0)[None, :], member * 1.0, weights)


class DelegationPolicy(VotePolicy):
    """Quality-threshold delegation with weight conservation (see module
    docstring for the exact integer reformulation)."""

    name = "delegation"
    delegate_threshold = DELEGATE_THRESHOLD

    def decide(self, counts, quals, lengths, *, num, den, qual_threshold,
               qual_cap):
        fam_cap = counts.shape[0]
        if fam_cap * max(den, num) >= 2**31:
            raise ValueError(
                f"family bucket {fam_cap} with cutoff {num}/{den} would "
                "overflow the int32 cutoff compare")
        member = counts.any(axis=-1)  # (F, L) — padded slots vote no lane
        high = member & (quals >= self.delegate_threshold)
        n_high = high.sum(axis=0, dtype=jnp.int32)  # (L,)
        use_all = n_high == 0
        active = jnp.where(use_all[None, :], member, high)  # (F, L)
        votes = counts & active[:, :, None]  # (F, L, 5)
        modal, max_count = modal_with_tiebreak(votes)
        # exact integer cutoff over the active voter count (== weighted
        # compare over the conserved fam_size total; module docstring)
        n_active = jnp.where(use_all, lengths, n_high)
        passed = (modal != N) & (max_count * den >= num * n_active) & (lengths > 0)
        qsums = (votes * quals[:, :, None]).sum(axis=0)  # (L, 5)
        qsum = jnp.take_along_axis(qsums, modal[:, None], axis=1)[:, 0]
        return (modal.astype(jnp.uint8),
                jnp.minimum(qsum, qual_cap).astype(jnp.uint8),
                ~passed)


register_policy(DelegationPolicy())
