"""Distilled policy: a small pure-JAX error-correction head over the
per-position count/qual features.

Motivation (knowledge distillation for DNA sequence correction): the
count/qual planes the kernels already assemble carry more signal than
the one rational-cutoff compare uses — how the quality mass is split
across bases, how large the family is.  A tiny per-position MLP
(``features -> tanh hidden -> 5 base logits``) is trained offline by
``tools/distill_train.py`` against ``utils.simulate`` truth sets (clean
and degraded-read regimes mixed), and its weights ship as a versioned,
committed checkpoint — the policy is a frozen artifact, not a runtime
learner, so a checkpoint version always produces the same bytes.

Features per position (11): the 5 lane count fractions, the 5 lane
quality-mass fractions (each lane's Phred sum over ``fam_size *
qual_cap``), and the clipped family size.  The head votes the argmax
lane and abstains (fail mask -> N/0) when the softmax confidence falls
below :data:`CONFIDENCE_FLOOR` or the argmax is the N lane — abstention
is what keeps the distilled head's called-base error at or below raw
reads even on families it cannot rescue.

Checkpoint resolution: ``CCT_DISTILLED_CHECKPOINT`` (environment) wins,
else the committed ``policies/checkpoints/distilled_v1.json``.  The
file records its training provenance under ``meta`` (tool, seed,
regime mix, held-out accuracy) — see README "Consensus policies".
"""

from __future__ import annotations

import json
import os
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from consensuscruncher_tpu.policies.base import VotePolicy, register_policy
from consensuscruncher_tpu.utils.phred import N, NUM_BASES

#: Committed checkpoint (see tools/distill_train.py for provenance).
CHECKPOINT_NAME = "distilled_v1.json"
CHECKPOINT_ENV = "CCT_DISTILLED_CHECKPOINT"

#: Softmax confidence below which the head abstains (votes N).  Part of
#: the policy's identity, like the delegation threshold.
CONFIDENCE_FLOOR = 0.5

#: Family-size feature clip (sizes past this carry no extra signal).
FAM_CLIP = 32.0

N_FEATURES = 2 * NUM_BASES + 1


def checkpoint_path() -> str:
    env = os.environ.get(CHECKPOINT_ENV)
    if env:
        return env
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "checkpoints", CHECKPOINT_NAME)


def load_checkpoint(path: str | None = None) -> dict:
    """Parse + validate a checkpoint file into float32 weight arrays.
    Raises ValueError on a structurally unusable file (wrong version or
    shapes) — weight *values* are not attested here; a silently
    corrupted checkpoint is caught downstream by tools/qc_gate.py's
    per-policy accuracy gate (the CI positive control)."""
    path = path or checkpoint_path()
    # cct: allow-effect(checkpoint weights load once at trace time and are baked into the jitted program as constants — deliberate)
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("version") != 1 or doc.get("policy") != "distilled":
        raise ValueError(f"not a distilled-policy checkpoint: {path}")
    params = {}
    for key in ("w1", "b1", "w2", "b2"):
        params[key] = np.asarray(doc[key], dtype=np.float32)
    hidden = params["b1"].shape[0]
    want = {"w1": (N_FEATURES, hidden), "b1": (hidden,),
            "w2": (hidden, NUM_BASES), "b2": (NUM_BASES,)}
    for key, shape in want.items():
        if params[key].shape != shape:
            raise ValueError(
                f"checkpoint {path}: {key} has shape {params[key].shape}, "
                f"want {shape}")
    params["meta"] = doc.get("meta") or {}
    return params


def features(counts, qsums, lengths, *, qual_cap):
    """Per-position feature plane ``(L, 11)`` from the ``(L, 5)`` lane
    counts and Phred sums plus the family size (normalized, clipped).

    ``lengths`` is the family size — a scalar on the kernel path (one
    family per call), or ``(L,)`` when the training tool scores a batch
    of independent positions drawn from different families.
    """
    length = counts.shape[0]
    fam = jnp.broadcast_to(
        jnp.maximum(jnp.asarray(lengths, jnp.float32), 1.0), (length,))
    f_counts = counts.astype(jnp.float32) / fam[:, None]
    f_quals = qsums.astype(jnp.float32) / (fam[:, None] * float(qual_cap))
    f_fam = (jnp.minimum(fam, FAM_CLIP) / FAM_CLIP)[:, None]
    return jnp.concatenate([f_counts, f_quals, f_fam], axis=1)


def forward(params, feats):
    """The head itself: ``(L, 11)`` features -> ``(L, 5)`` base logits."""
    h = jnp.tanh(feats @ jnp.asarray(params["w1"]) + jnp.asarray(params["b1"]))
    return h @ jnp.asarray(params["w2"]) + jnp.asarray(params["b2"])


@lru_cache(maxsize=4)
def _jitted_forward(ckpt_path: str):
    """Standalone jitted forward for host-side callers (the training
    tool's eval loop, determinism tests); the kernel wires instead trace
    :func:`forward` inside their own jitted programs."""
    params = load_checkpoint(ckpt_path)
    return jax.jit(lambda feats: forward(params, feats))


def checkpoint_forward(feats, path: str | None = None):
    return _jitted_forward(path or checkpoint_path())(jnp.asarray(feats))


class DistilledPolicy(VotePolicy):
    """Frozen distilled-NN head (see module docstring)."""

    name = "distilled"

    def __init__(self, checkpoint: str | None = None):
        self._checkpoint = checkpoint
        self._params = None
        self._params_path = None

    def params(self) -> dict:
        # Re-resolve per call-path entry: the env override must win even
        # when it changes after first use (each kernel program is keyed
        # by policy name + config, compiled once per process).
        path = self._checkpoint or checkpoint_path()
        if self._params is None or self._params_path != path:
            self._params = load_checkpoint(path)
            self._params_path = path
        return self._params

    def decide(self, counts, quals, lengths, *, num, den, qual_threshold,
               qual_cap):
        params = self.params()
        c = counts.sum(axis=0, dtype=jnp.int32)  # (L, 5)
        qsums = (counts * quals[:, :, None]).sum(axis=0)  # (L, 5)
        logits = forward(params, features(c, qsums, lengths, qual_cap=qual_cap))
        base = jnp.argmax(logits, axis=1).astype(jnp.int32)  # (L,)
        probs = jax.nn.softmax(logits, axis=1)
        conf = jnp.max(probs, axis=1)
        fail = (base == N) | (conf < CONFIDENCE_FLOOR) | (lengths <= 0)
        qsum = jnp.take_along_axis(qsums, base[:, None], axis=1)[:, 0]
        return (base.astype(jnp.uint8),
                jnp.minimum(qsum, qual_cap).astype(jnp.uint8),
                fail)


register_policy(DistilledPolicy())
