"""Majority policy: the reference rational-cutoff vote, extracted intact.

This is the default everywhere and the only policy pinned byte-identical
to the committed goldens on the staged, streaming, and serve wires.  The
math is the untouched body of the original
``ops.consensus_tpu._consensus_one_family`` (reference parity:
``ConsensusCruncher/consensus_helper.py:consensus_maker``, SURVEY.md
§3.3) — moved here so every policy lives in one subsystem;
``ops.consensus_tpu`` re-exports it under the old name for the segment
and mesh kernels that compose with it directly.

:class:`MajorityPolicy` overrides :meth:`~VotePolicy.family_vote_fn` to
return this exact function rather than routing through the generic
plane adapter, so the default path's program is the same traced jaxpr
as before the policy subsystem existed — golden parity by construction,
not by equivalence argument.  ``decide`` implements the identical rule
over the plane protocol for callers (tests, the distillation teacher)
that work at that level.
"""

from __future__ import annotations

from functools import partial

import jax.numpy as jnp

from consensuscruncher_tpu.policies.base import (
    VotePolicy,
    modal_with_tiebreak,
    register_policy,
)
from consensuscruncher_tpu.utils.phred import N, NUM_BASES, PAD


def majority_family_vote(bases, quals, fam_size, *, num, den, qual_threshold,
                         qual_cap, with_qc=False):
    """Consensus of one padded family: (F, L) uint8 -> (L,) uint8 pair.

    ``with_qc``: additionally return the QC rider — per-position total
    votes and votes disagreeing with the modal base, both pure
    reductions of the ``counts`` plane the vote already built (obs.qc;
    zero extra operands, zero extra h2d).  The consensus outputs are
    bit-identical either way.
    """
    fam_cap, _length = bases.shape
    member = (jnp.arange(fam_cap, dtype=jnp.int32) < fam_size)[:, None]  # (F, 1)

    eff = jnp.where(quals >= qual_threshold, bases, jnp.uint8(N))
    eff = jnp.where(member, eff, jnp.uint8(PAD))  # padded slots never vote

    lanes = jnp.arange(NUM_BASES, dtype=jnp.uint8)
    onehot = eff[:, :, None] == lanes  # (F, L, 5) bool
    counts = onehot.sum(axis=0, dtype=jnp.int32)  # (L, 5)
    member_idx = jnp.arange(fam_cap, dtype=jnp.int32)[:, None, None]
    first_seen = jnp.where(onehot, member_idx, fam_cap).min(axis=0)  # (L, 5)

    # Lexicographic (count desc, first_seen asc) WITHOUT a combined score
    # product (which would overflow int32 for huge family buckets; JAX
    # silently downcasts int64 when x64 is off, so int32-safe algebra is the
    # only reliable form): take the max count, then argmin first-seen among
    # the bases achieving it.
    max_count = counts.max(axis=1)  # (L,)
    cand_first = jnp.where(counts == max_count[:, None], first_seen, fam_cap + 1)
    modal = cand_first.argmin(axis=1).astype(jnp.int32)  # (L,)

    # Static trace-time guard: the rational-cutoff cross-multiply must fit
    # int32 (den <= 1000 from cutoff_fraction, so this allows fam_cap ~2M).
    if fam_cap * max(den, num) >= 2**31:
        raise ValueError(
            f"family bucket {fam_cap} with cutoff {num}/{den} would overflow "
            "the int32 cutoff compare — split the family or coarsen the cutoff"
        )
    passed = (modal != N) & (max_count * den >= num * fam_size) & (fam_size > 0)

    agree = (bases == modal[None, :].astype(jnp.uint8)) & (quals >= qual_threshold) & member
    qsum = jnp.where(agree, quals.astype(jnp.int32), 0).sum(axis=0)  # (L,)

    out_base = jnp.where(passed, modal, N).astype(jnp.uint8)
    out_qual = jnp.where(passed, jnp.minimum(qsum, qual_cap), 0).astype(jnp.uint8)
    if with_qc:
        votes = counts.sum(axis=1)  # (L,) valid member votes (PAD never a lane)
        return out_base, out_qual, votes, votes - max_count
    return out_base, out_qual


class MajorityPolicy(VotePolicy):
    """Exact rational-cutoff majority: modal base with first-seen
    tie-break passes iff ``count * den >= num * fam_size`` (exact integer
    compare, immune to float boundary wobble)."""

    name = "majority"

    def decide(self, counts, quals, lengths, *, num, den, qual_threshold,
               qual_cap):
        fam_cap = counts.shape[0]
        if fam_cap * max(den, num) >= 2**31:
            raise ValueError(
                f"family bucket {fam_cap} with cutoff {num}/{den} would "
                "overflow the int32 cutoff compare")
        modal, max_count = modal_with_tiebreak(counts)
        passed = (modal != N) & (max_count * den >= num * lengths) & (lengths > 0)
        qsums = (counts * quals[:, :, None]).sum(axis=0)  # (L, 5)
        qsum = jnp.take_along_axis(qsums, modal[:, None], axis=1)[:, 0]
        return (modal.astype(jnp.uint8),
                jnp.minimum(qsum, qual_cap).astype(jnp.uint8),
                ~passed)

    def family_vote_fn(self, *, num, den, qual_threshold, qual_cap,
                       with_qc=False):
        # The untouched reference program — identical jaxpr to the
        # pre-policy kernels, so goldens stay byte-identical by
        # construction on every wire.
        return partial(majority_family_vote, num=num, den=den,
                       qual_threshold=qual_threshold, qual_cap=qual_cap,
                       with_qc=with_qc)


register_policy(MajorityPolicy())
