"""First-party BAM reader/writer (binary alignment format, SAM spec §4).

Replaces the pysam/htslib layer the reference leans on (SURVEY.md §2 "Native
components" — this environment has none, so the framework owns the format).
Pure-Python struct codec; the BGZF framing underneath can be served by the
native C++ codec in ``io/native`` when built.

Supported surface (everything the pipeline needs):
- full header (SAM text + reference dictionary) round-trip,
- all record fields: flags, cigar, 4-bit packed seq, qual, mate info, tlen,
- optional tags: A c C s S i I f Z H B (arrays),
- streaming read, streaming write, in-memory/spilled coordinate sort, merge.

Random access lives next door in ``io/bai.py`` (spec BAI build + region
``fetch``), used for ``samtools index`` parity on outputs.  The pipeline
stages themselves stream coordinate-sorted inputs start-to-finish — a
deliberate design difference from the reference's per-chromosome
``pysam.fetch`` loop; the streaming path needs no index files at all.
"""

from __future__ import annotations

import heapq
import os
import struct
import sys
import tempfile
from dataclasses import dataclass, field

import numpy as np

from consensuscruncher_tpu.io import bgzf

BAM_MAGIC = b"BAM\x01"

# 4-bit seq nibble alphabet (SAM spec) and cigar op order.
SEQ_NIBBLES = "=ACMGRSVTWYHKDBN"
_NIB_OF = {c: i for i, c in enumerate(SEQ_NIBBLES)}
CIGAR_OPS = "MIDNSHP=X"
_CIGAR_OP_OF = {c: i for i, c in enumerate(CIGAR_OPS)}

# flag bits
FPAIRED = 0x1
FPROPER = 0x2
FUNMAP = 0x4
FMUNMAP = 0x8
FREVERSE = 0x10
FMREVERSE = 0x20
FREAD1 = 0x40
FREAD2 = 0x80
FSECONDARY = 0x100
FQCFAIL = 0x200
FDUP = 0x400
FSUPPLEMENTARY = 0x800


@dataclass
class BamHeader:
    """SAM header text + reference dictionary."""

    text: str = ""
    refs: list[tuple[str, int]] = field(default_factory=list)

    def __post_init__(self):
        self._ref_ids = {name: i for i, (name, _len) in enumerate(self.refs)}

    def ref_id(self, name: str) -> int:
        if name == "*" or name is None:
            return -1
        return self._ref_ids[name]

    def ref_name(self, rid: int) -> str:
        return "*" if rid < 0 else self.refs[rid][0]

    @classmethod
    def from_refs(cls, refs: list[tuple[str, int]], extra_text: str = "") -> "BamHeader":
        text = "@HD\tVN:1.6\tSO:unsorted\n"
        for name, length in refs:
            text += f"@SQ\tSN:{name}\tLN:{length}\n"
        return cls(text=text + extra_text, refs=list(refs))


@dataclass(eq=False)
class BamRead:
    """One alignment record; mutable, cheap, and duck-compatible with core.tags.

    ``seq`` is an ASCII string; ``qual`` a uint8 Phred array (len == len(seq),
    or size 0 for '*').  ``cigar`` is a list of ``(op_char, length)``.
    ``ref``/``mate_ref`` are reference *names* ("*" when unmapped), resolved
    against the header at codec boundaries.
    """

    qname: str
    flag: int = 0
    ref: str = "*"
    pos: int = -1
    mapq: int = 0
    cigar: list[tuple[str, int]] = field(default_factory=list)
    mate_ref: str = "*"
    mate_pos: int = -1
    tlen: int = 0
    seq: str = ""
    qual: np.ndarray = field(default_factory=lambda: np.zeros(0, dtype=np.uint8))
    tags: dict[str, tuple[str, object]] = field(default_factory=dict)

    @property
    def seq_len(self) -> int:
        """Uniform length accessor shared with the columnar MemberView."""
        return len(self.seq)

    @property
    def codes(self) -> np.ndarray:
        """Pipeline base codes (A=0..N=4) — MemberView-uniform accessor."""
        from consensuscruncher_tpu.utils.phred import encode_seq

        return encode_seq(self.seq)

    def materialize(self) -> "BamRead":
        """MemberView-uniform accessor: a BamRead already is materialized."""
        return self

    # -- flag properties (pysam-compatible names where it matters) --
    @property
    def is_paired(self) -> bool:
        return bool(self.flag & FPAIRED)

    @property
    def is_unmapped(self) -> bool:
        return bool(self.flag & FUNMAP)

    @property
    def mate_is_unmapped(self) -> bool:
        return bool(self.flag & FMUNMAP)

    @property
    def is_reverse(self) -> bool:
        return bool(self.flag & FREVERSE)

    @property
    def mate_is_reverse(self) -> bool:
        return bool(self.flag & FMREVERSE)

    @property
    def is_read1(self) -> bool:
        return bool(self.flag & FREAD1)

    @property
    def is_read2(self) -> bool:
        return bool(self.flag & FREAD2)

    @property
    def is_secondary(self) -> bool:
        return bool(self.flag & FSECONDARY)

    @property
    def is_supplementary(self) -> bool:
        return bool(self.flag & FSUPPLEMENTARY)

    @property
    def is_qcfail(self) -> bool:
        return bool(self.flag & FQCFAIL)

    @property
    def is_duplicate(self) -> bool:
        return bool(self.flag & FDUP)

    def cigar_string(self) -> str:
        return "*" if not self.cigar else "".join(f"{n}{op}" for op, n in self.cigar)

    def __eq__(self, other) -> bool:
        if not isinstance(other, BamRead):
            return NotImplemented
        return (
            self.qname == other.qname
            and self.flag == other.flag
            and self.ref == other.ref
            and self.pos == other.pos
            and self.mapq == other.mapq
            and self.cigar == other.cigar
            and self.mate_ref == other.mate_ref
            and self.mate_pos == other.mate_pos
            and self.tlen == other.tlen
            and self.seq == other.seq
            and np.array_equal(self.qual, other.qual)
            and self.tags == other.tags
        )


def cigar_from_string(s: str) -> list[tuple[str, int]]:
    if s in ("*", ""):
        return []
    out, num = [], ""
    for ch in s:
        if ch.isdigit():
            num += ch
        else:
            out.append((ch, int(num)))
            num = ""
    return out


# ---------------------------------------------------------------- record codec

_CORE = struct.Struct("<iiBBHHHiiii")  # refID..tlen after block_size


def _encode_tags(tags: dict[str, tuple[str, object]]) -> bytes:
    out = bytearray()
    for key, (typ, val) in tags.items():
        out += key.encode("ascii")
        if typ == "A":
            out += b"A" + str(val)[0].encode("ascii")
        elif typ in "cCsSiI":
            out += typ.encode("ascii") + struct.pack("<" + {"c": "b", "C": "B", "s": "h", "S": "H", "i": "i", "I": "I"}[typ], int(val))
        elif typ == "f":
            out += b"f" + struct.pack("<f", float(val))
        elif typ in ("Z", "H"):
            out += typ.encode("ascii") + str(val).encode("ascii") + b"\x00"
        elif typ == "B":
            sub, arr = val
            fmt = {"c": "b", "C": "B", "s": "h", "S": "H", "i": "i", "I": "I", "f": "f"}[sub]
            out += b"B" + sub.encode("ascii") + struct.pack("<I", len(arr))
            out += struct.pack(f"<{len(arr)}{fmt}", *arr)
        else:
            raise ValueError(f"unsupported tag type {typ!r} for {key}")
    return bytes(out)


def _decode_tags(buf: bytes) -> dict[str, tuple[str, object]]:
    tags: dict[str, tuple[str, object]] = {}
    off, end = 0, len(buf)
    while off < end:
        key = buf[off : off + 2].decode("ascii")
        typ = chr(buf[off + 2])
        off += 3
        if typ == "A":
            tags[key] = ("A", chr(buf[off])); off += 1
        elif typ in "cCsSiI":
            fmt = {"c": "b", "C": "B", "s": "h", "S": "H", "i": "i", "I": "I"}[typ]
            (v,) = struct.unpack_from("<" + fmt, buf, off)
            tags[key] = (typ, v); off += struct.calcsize(fmt)
        elif typ == "f":
            (v,) = struct.unpack_from("<f", buf, off)
            tags[key] = ("f", v); off += 4
        elif typ in ("Z", "H"):
            z = buf.index(b"\x00", off)
            tags[key] = (typ, buf[off:z].decode("ascii")); off = z + 1
        elif typ == "B":
            sub = chr(buf[off]); (n,) = struct.unpack_from("<I", buf, off + 1)
            fmt = {"c": "b", "C": "B", "s": "h", "S": "H", "i": "i", "I": "I", "f": "f"}[sub]
            vals = list(struct.unpack_from(f"<{n}{fmt}", buf, off + 5))
            tags[key] = ("B", (sub, vals)); off += 5 + n * struct.calcsize(fmt)
        else:
            raise ValueError(f"unsupported tag type {typ!r} in record")
    return tags


# Unknown characters map to N (nibble 15), matching htslib — never silently
# to '=' (nibble 0), which would corrupt the sequence.
_NIB_LUT = np.full(256, 15, dtype=np.uint8)
for _c, _i in _NIB_OF.items():
    _NIB_LUT[ord(_c)] = _i
    _NIB_LUT[ord(_c.lower())] = _i
_NIB_CHARS = np.frombuffer(SEQ_NIBBLES.encode(), dtype=np.uint8)


def _pack_seq(seq: str) -> bytes:
    codes = _NIB_LUT[np.frombuffer(seq.encode("ascii"), dtype=np.uint8)]
    if len(codes) % 2:
        codes = np.append(codes, 0)
    return ((codes[0::2] << 4) | codes[1::2]).astype(np.uint8).tobytes()


def _unpack_seq(buf: bytes, l_seq: int) -> str:
    arr = np.frombuffer(buf, dtype=np.uint8)
    nibs = np.empty(arr.size * 2, dtype=np.uint8)
    nibs[0::2] = arr >> 4
    nibs[1::2] = arr & 0xF
    return _NIB_CHARS[nibs[:l_seq]].tobytes().decode("ascii")


def encode_record(read: BamRead, header: BamHeader) -> bytes:
    name = read.qname.encode("ascii") + b"\x00"
    l_seq = len(read.seq)
    cigar = b"".join(struct.pack("<I", (n << 4) | _CIGAR_OP_OF[op]) for op, n in read.cigar)
    seq = _pack_seq(read.seq) if l_seq else b""
    if read.qual.size:
        if read.qual.size != l_seq:
            raise ValueError(f"qual length {read.qual.size} != seq length {l_seq} for {read.qname}")
        qual = read.qual.astype(np.uint8).tobytes()
    else:
        qual = b"\xff" * l_seq
    tags = _encode_tags(read.tags)
    # reg2bin of the unclipped interval; 0 is acceptable (only indexers care),
    # but compute the spec value so htslib round-trips byte-identically.
    end = read.pos + max(1, sum(n for op, n in read.cigar if op in "MDN=X"))
    body = _CORE.pack(
        header.ref_id(read.ref),
        read.pos,
        len(name),
        read.mapq,
        _reg2bin(read.pos, end) if read.pos >= 0 else 4680,
        len(read.cigar),
        read.flag,
        l_seq,
        header.ref_id(read.mate_ref),
        read.mate_pos,
        read.tlen,
    ) + name + cigar + seq + qual + tags
    return struct.pack("<i", len(body)) + body


def decode_record(body: bytes, header: BamHeader) -> BamRead:
    (rid, pos, l_name, mapq, _bin, n_cigar, flag, l_seq, mrid, mpos, tlen) = _CORE.unpack_from(body, 0)
    off = _CORE.size
    qname = body[off : off + l_name - 1].decode("ascii")
    off += l_name
    cigar = []
    for _ in range(n_cigar):
        (v,) = struct.unpack_from("<I", body, off)
        cigar.append((CIGAR_OPS[v & 0xF], v >> 4))
        off += 4
    n_seq_bytes = (l_seq + 1) // 2
    seq = _unpack_seq(body[off : off + n_seq_bytes], l_seq)
    off += n_seq_bytes
    qual_raw = np.frombuffer(body[off : off + l_seq], dtype=np.uint8).copy()
    if l_seq and qual_raw.size and qual_raw[0] == 0xFF:
        qual_raw = np.zeros(0, dtype=np.uint8)
    off += l_seq
    return BamRead(
        qname=qname,
        flag=flag,
        ref=header.ref_name(rid),
        pos=pos,
        mapq=mapq,
        cigar=cigar,
        mate_ref=header.ref_name(mrid),
        mate_pos=mpos,
        tlen=tlen,
        seq=seq,
        qual=qual_raw,
        tags=_decode_tags(body[off:]),
    )


def _reg2bin(beg: int, end: int) -> int:
    """SAM spec reg2bin (UCSC binning) — stored per record for indexer parity."""
    end -= 1
    if beg >> 14 == end >> 14:
        return ((1 << 15) - 1) // 7 + (beg >> 14)
    if beg >> 17 == end >> 17:
        return ((1 << 12) - 1) // 7 + (beg >> 17)
    if beg >> 20 == end >> 20:
        return ((1 << 9) - 1) // 7 + (beg >> 20)
    if beg >> 23 == end >> 23:
        return ((1 << 6) - 1) // 7 + (beg >> 23)
    if beg >> 26 == end >> 26:
        return ((1 << 3) - 1) // 7 + (beg >> 26)
    return 0


# ---------------------------------------------------------------- file objects

def read_bam_header(bgzf_reader) -> BamHeader:
    """Parse the BAM magic + header block from an open BGZF stream, leaving
    it positioned at the first alignment record (shared by the object and
    columnar readers so header handling cannot diverge between them)."""
    magic = bgzf_reader.read(4)
    if magic != BAM_MAGIC:
        raise ValueError(f"not a BAM file: magic {magic!r}")
    (l_text,) = struct.unpack("<i", bgzf_reader.read(4))
    text = bgzf_reader.read(l_text).decode("ascii", errors="replace").rstrip("\x00")
    (n_ref,) = struct.unpack("<i", bgzf_reader.read(4))
    refs = []
    for _ in range(n_ref):
        (l_name,) = struct.unpack("<i", bgzf_reader.read(4))
        name = bgzf_reader.read(l_name)[:-1].decode("ascii")
        (l_ref,) = struct.unpack("<i", bgzf_reader.read(4))
        refs.append((name, l_ref))
    return BamHeader(text=text, refs=refs)


class BamReader:
    """Streaming BAM reader: ``for read in BamReader(path): ...``

    ``salvage=True``: recover what a truncated file still holds — the BGZF
    layer stops at the last intact block and this layer stops at the last
    complete record inside it, warning instead of raising.  The header must
    still be intact (nothing is recoverable without it).
    """

    def __init__(self, path, salvage: bool = False):
        self._bgzf = bgzf.BgzfReader(path, salvage=salvage)
        self._salvage = salvage
        self.header = read_bam_header(self._bgzf)

    def __iter__(self):
        while True:
            raw = self._bgzf.read(4)
            if len(raw) == 0:
                return
            if len(raw) < 4:
                # A partial length prefix is never valid — a file truncated at
                # a BGZF block boundary must not read as a complete dataset.
                if self._salvage:
                    print("WARNING: truncated BAM record (partial length "
                          "prefix); stopping at last complete record",
                          file=sys.stderr, flush=True)
                    return
                raise ValueError("truncated BAM record (partial length prefix)")
            (block_size,) = struct.unpack("<i", raw)
            body = self._bgzf.read(block_size)
            if len(body) < block_size:
                if self._salvage:
                    print("WARNING: truncated BAM record; stopping at last "
                          "complete record", file=sys.stderr, flush=True)
                    return
                raise ValueError("truncated BAM record")
            yield decode_record(body, self.header)

    def close(self):
        self._bgzf.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class BamWriter:
    """Streaming BAM writer; atomic if given a final path via ``atomic=True``."""

    def __init__(self, path, header: BamHeader, level: int = 6, atomic: bool = False):
        self._final_path = os.fspath(path) if atomic else None
        self._path = self._final_path + ".tmp" if atomic else path
        self._bgzf = bgzf.BgzfWriter(self._path, level=level)
        self.header = header
        text = header.text.encode("ascii")
        out = bytearray(BAM_MAGIC)
        out += struct.pack("<i", len(text)) + text
        out += struct.pack("<i", len(header.refs))
        for name, length in header.refs:
            bname = name.encode("ascii") + b"\x00"
            out += struct.pack("<i", len(bname)) + bname + struct.pack("<i", length)
        self._bgzf.write(bytes(out))

    def write(self, read: BamRead) -> None:
        self._bgzf.write(encode_record(read, self.header))

    def write_encoded(self, blob) -> None:
        """Append pre-encoded, length-prefixed record bytes (the vectorized
        ``io.encode.encode_records`` output) verbatim."""
        self._bgzf.write(blob.tobytes() if isinstance(blob, np.ndarray) else blob)

    def close(self) -> None:
        self._bgzf.close()
        if self._final_path is not None:
            # Durable commit (fsync + rename + dir fsync): a committed stage
            # output must never fingerprint as complete while partially on
            # disk — --resume trusts what it finds here.
            from consensuscruncher_tpu.utils.manifest import commit_file

            commit_file(self._path, self._final_path)

    def abort(self) -> None:
        """Discard the output: for atomic writers the final path is never
        touched; for plain writers the partial file is left (caller's path)."""
        self._bgzf.close()
        if self._final_path is not None and os.path.exists(self._path):
            os.unlink(self._path)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        # Never promote a partial atomic output over the final path when the
        # with-body raised — that would publish a truncated-but-valid BAM.
        if exc_type is not None:
            self.abort()
        else:
            self.close()


# ---------------------------------------------------------------- sort / merge

def _coord_key(read: BamRead, header: BamHeader):
    rid = header.ref_id(read.ref)
    return (rid if rid >= 0 else 1 << 30, read.pos, read.qname, read.flag)


# Compressed-size ceiling for the in-memory columnar sort (~4x expansion
# plus one gathered copy); larger inputs take the spill/merge object path.
_COLUMNAR_SORT_MAX_BYTES = int(os.environ.get("CCT_COLUMNAR_SORT_MAX_BYTES", 96 << 20))


def sort_bam(in_path, out_path, max_in_memory: int = 2_000_000, level: int = 6) -> None:
    """Coordinate sort (samtools-sort parity). External-sorts via sorted
    temp chunks + a columnar k-way merge when the input exceeds the
    in-memory bounds.

    Inputs whose compressed size fits ``CCT_COLUMNAR_SORT_MAX_BYTES`` take
    the columnar fast path (``io.columnar.sort_bam_columnar``): identical
    total order, but a pure byte shuffle — records are never decoded.  The
    external path is columnar too (chunks sort as byte shuffles, the merge
    is ``io.columnar.merge_sorted_columnar``); the object heap merge
    survives only as the last-resort fallback when even the merge's key
    columns exceed the memory budget."""
    if os.path.getsize(in_path) <= _COLUMNAR_SORT_MAX_BYTES:
        from consensuscruncher_tpu.io.columnar import sort_bam_columnar

        if sort_bam_columnar(in_path, out_path, level=level, max_records=max_in_memory):
            return
    from consensuscruncher_tpu.io.columnar import (
        ColumnarReader,
        SortingBamWriter,
        merge_sorted_columnar,
    )

    reader = ColumnarReader(in_path)
    header = reader.header
    chunks: list[str] = []
    # chunk budget: a fraction of the sort buffer so several chunks' key
    # columns + one chunk's raw bytes coexist comfortably
    from consensuscruncher_tpu.io.columnar import _default_sort_buffer_bytes

    chunk_budget = max(256 << 20, _default_sort_buffer_bytes() // 4)

    def new_chunk_writer() -> SortingBamWriter:
        fd, path = tempfile.mkstemp(suffix=".bam", prefix="ccsort.")
        os.close(fd)
        chunks.append(path)  # registered BEFORE use so cleanup always sees it
        # level 1 + no index: throwaway chunks, read back once
        return SortingBamWriter(path, header, level=1, index=False,
                                max_raw_bytes=chunk_budget * 2)

    def spill(blobs) -> None:
        w = new_chunk_writer()
        try:
            for p in blobs:
                w.write_encoded(p)
        except BaseException:
            w.abort()
            raise
        w.close()

    try:
        pending: list = []  # raw blobs of the chunk being accumulated
        raw = n = 0
        for b in reader.batches():
            blob = b.buf[: int(b.rec_off[-1])]
            pending.append(blob)
            raw += blob.size
            n += b.n
            if raw > chunk_budget or n > max_in_memory:
                spill(pending)
                pending, raw, n = [], 0, 0
        if not chunks:
            # everything fit one buffer: sort + write the output directly
            # (no temp round trip, inline index)
            final = SortingBamWriter(os.fspath(out_path), header, level=level)
            try:
                for p in pending:
                    final.write_encoded(p)
            except BaseException:
                final.abort()
                raise
            final.close()
            return
        if pending:
            spill(pending)
            pending = []
        # our own chunks are full-key-sorted by construction -> skip verify
        if not merge_sorted_columnar(chunks, out_path, header, level=level,
                                     verify_sorted=False):
            _merge_paths(chunks, out_path, header, level=level)
            from consensuscruncher_tpu.io.bai import index_bam

            index_bam(out_path)  # parity with the columnar merge's inline .bai
    finally:
        reader.close()
        for c in chunks:
            if os.path.exists(c):
                os.unlink(c)


def _sorted_header(header: BamHeader) -> BamHeader:
    """Rewrite (only) the @HD line to declare SO:coordinate."""
    lines = header.text.splitlines(keepends=True)
    for i, line in enumerate(lines):
        if line.startswith("@HD"):
            fields = line.rstrip("\n").split("\t")
            fields = [f for f in fields if not f.startswith("SO:")] + ["SO:coordinate"]
            lines[i] = "\t".join(fields) + "\n"
            break
    else:
        lines.insert(0, "@HD\tVN:1.6\tSO:coordinate\n")
    return BamHeader(text="".join(lines), refs=header.refs)


def _merge_paths(paths: list[str], out_path, header: BamHeader, level: int = 6) -> None:
    readers = [BamReader(p) for p in paths]
    streams = [iter(r) for r in readers]
    heap = []
    for si, stream in enumerate(streams):
        read = next(stream, None)
        if read is not None:
            heap.append((_coord_key(read, header), si, read))
    heapq.heapify(heap)
    with BamWriter(out_path, _sorted_header(header), level=level, atomic=True) as w:
        while heap:
            _key, si, read = heapq.heappop(heap)
            w.write(read)
            nxt = next(streams[si], None)
            if nxt is not None:
                heapq.heappush(heap, (_coord_key(nxt, header), si, nxt))
    for r in readers:
        r.close()


def _merge_large(in_paths: list, out_path, header: BamHeader, level: int,
                 index: bool) -> None:
    """Beyond-buffer merge: columnar k-way shuffle, heap-merge fallback."""
    from consensuscruncher_tpu.io.columnar import merge_sorted_columnar

    paths = [os.fspath(p) for p in in_paths]
    if not merge_sorted_columnar(paths, out_path, header, level=level,
                                 index=index):
        _merge_paths(paths, out_path, header, level=level)
        if index:  # parity with the columnar merge's inline .bai
            from consensuscruncher_tpu.io.bai import index_bam

            index_bam(out_path)


def merge_bams(in_paths: list, out_path, level: int = 6, index: bool = True) -> None:
    """samtools-merge parity: merge coordinate-sorted inputs (headers must
    share a reference dictionary).

    Inputs that plausibly fit the in-memory sort buffer stream through a
    ``SortingBamWriter`` as raw blobs (one lexsort + one BGZF write — the
    k-way order over already-sorted inputs is a special case of the full
    coordinate sort, and the writer's key + stable-tie order match the
    object heap merge's exactly).  Larger inputs keep the O(k)-memory
    streaming heap merge — buffering them only to re-sort already-sorted
    data would double the I/O.

    ``level``: BGZF deflate level of the output — pass 0 (stored) or 1 for
    pipeline-internal merges whose content lives on in later outputs (the
    deflate is most of a merge's cost; VERDICT r2 weak #4)."""
    headers = []
    for p in in_paths:
        r = BamReader(p)
        headers.append(r.header)
        r.close()
    for p, h in zip(in_paths[1:], headers[1:]):
        if h.refs != headers[0].refs:
            raise ValueError(
                f"merge_bams: reference dictionary of {os.fspath(p)!r} differs from "
                f"{os.fspath(in_paths[0])!r} — inputs must share @SQ lines"
            )
    from consensuscruncher_tpu.io.columnar import ColumnarReader, SortingBamWriter

    # Bound on ACTUAL raw bytes while reading (compressed size is no proxy —
    # low-complexity reads expand 10-30x); past the writer's buffer the
    # in-memory path would spill-and-resort already-sorted data, so switch
    # to the O(k)-memory streaming heap merge instead.
    writer = SortingBamWriter(os.fspath(out_path), headers[0], level=level,
                              index=index)
    # cheap precheck: genomic BAMs virtually never expand (BGZF framing can
    # exceed raw size only for incompressible records), so compressed-total >
    # buffer means the in-memory path would all but certainly spill —
    # skip straight to the streaming merge; the in-loop raw-bytes bound
    # below remains the authoritative guard either way
    if sum(os.path.getsize(os.fspath(p)) for p in in_paths) > writer._max_raw:
        writer.abort()
        _merge_large(in_paths, out_path, headers[0], level, index)
        return
    raw = 0
    try:
        for p in in_paths:
            with ColumnarReader(p) as reader:
                for b in reader.batches():
                    blob = b.buf[: int(b.rec_off[-1])]
                    raw += blob.size
                    if raw > writer._max_raw:
                        writer.abort()
                        _merge_large(in_paths, out_path, headers[0], level, index)
                        return
                    writer.write_encoded(blob)
    except BaseException:
        writer.abort()
        raise
    writer.close()


def merge_memory_bams(parts: list, out_path=None, level: int = 6,
                      index: bool = True):
    """:func:`merge_bams`' in-memory twin for the streaming pipeline.

    ``parts`` are :class:`~consensuscruncher_tpu.io.columnar.MemoryBam`
    objects; empty ones contribute no records, exactly like a file-based
    merge of header-only BAMs.  The merge streams each
    part's sorted record blobs *in input order* through a fresh
    ``SortingBamWriter`` — the identical construction ``merge_bams`` uses
    on its in-memory path, so output bytes match file-based merges of the
    materialized parts byte for byte.

    ``out_path`` set: write the merged BAM (atomic, inline ``.bai`` when
    ``index``) and return None.  ``out_path`` None: return the merged
    ``MemoryBam`` via ``close_to_memory``.  Raises RuntimeError when the
    combined parts exceed the writer's in-memory budget (callers fall
    back to the staged pipeline rather than spill-resorting sorted data).
    """
    from consensuscruncher_tpu.io.columnar import SortingBamWriter

    parts = list(parts)
    if not parts:
        raise ValueError("merge_memory_bams: no inputs")
    for m in parts[1:]:
        if m.header.refs != parts[0].header.refs:
            raise ValueError(
                "merge_memory_bams: inputs must share a reference dictionary")
    writer = SortingBamWriter(
        os.fspath(out_path) if out_path is not None else "<memory>",
        parts[0].header, level=level, index=index)
    if sum(m.nbytes for m in parts) > writer._max_raw:
        writer.abort()
        raise RuntimeError(
            "merge_memory_bams: inputs exceed the in-memory sort budget")
    try:
        for m in parts:
            for blob in m.record_blobs():
                writer.write_encoded(blob)
        if out_path is None:
            return writer.close_to_memory()
        writer.close()
        return None
    except BaseException:
        writer.abort()
        raise
