"""Vectorized BAM record encoding: struct-of-arrays -> one byte blob.

The per-record ``encode_record`` path (struct pack + nibble pack + tag
encode per read) costs ~12 us/record of pure Python — the dominant term of
consensus OUTPUT writing once everything upstream is vectorized.  This
module encodes a whole batch of records with ~a dozen numpy passes:
fixed-width core fields scatter as one (n, 40) block; every ragged section
(qname, cigar, seq nibbles, qual, tags) scatters with cumulative-offset
index math.  Byte-parity with ``io.bam.encode_record`` is pinned by
tests/test_encode.py (same core struct, same reg2bin, same nibble packing,
same missing-qual convention).
"""

from __future__ import annotations

import numpy as np

from consensuscruncher_tpu.io.bam import SEQ_NIBBLES
from consensuscruncher_tpu.utils.ragged import gather_runs, scatter_runs

# pipeline base code (A=0 C=1 G=2 T=3 N=4) -> BAM seq nibble
_NIB_OF_CHAR = {c: i for i, c in enumerate(SEQ_NIBBLES)}
CODE2NIB = np.array([_NIB_OF_CHAR[c] for c in "ACGTN"], dtype=np.uint8)

# cigar ops consuming reference (MDN=X) by op code index in "MIDNSHP=X"
_REF_CONSUMING = np.array([1, 0, 1, 1, 0, 0, 0, 1, 1], dtype=np.int64)


def reg2bin_vec(beg: np.ndarray, end: np.ndarray) -> np.ndarray:
    """Vectorized SAM-spec reg2bin; beg < 0 yields 4680 (encode_record rule)."""
    beg = beg.astype(np.int64)
    e = end.astype(np.int64) - 1
    out = np.full(len(beg), -1, dtype=np.int64)
    for shift, base in ((14, 4681), (17, 585), (20, 73), (23, 9), (26, 1)):
        hit = (out < 0) & (beg >> shift == e >> shift)
        out[hit] = base + (beg[hit] >> shift)
    out[out < 0] = 0
    out[beg < 0] = 4680
    return out


def encode_records(
    qname_data: np.ndarray, qname_lens: np.ndarray,
    flag: np.ndarray, rid: np.ndarray, pos: np.ndarray, mapq: np.ndarray,
    cigar_words: np.ndarray, cigar_lens: np.ndarray,
    mrid: np.ndarray, mpos: np.ndarray, tlen: np.ndarray,
    codes_data: np.ndarray, codes_lens: np.ndarray,
    qual_data: np.ndarray,
    tag_data: np.ndarray, tag_lens: np.ndarray,
) -> np.ndarray:
    """Encode ``n`` records; every ``*_data`` is the concatenation of the
    per-record runs whose lengths are the matching ``*_lens`` array.

    ``qname_data`` excludes the NUL terminators (added here); ``cigar_words``
    is uint32 (op in low 4 bits); ``codes_data``/``qual_data`` are aligned
    (every record's qual length equals its seq length — consensus reads
    always carry quals); ``tag_data`` is the already-encoded tag block.
    Returns one uint8 blob of length-prefixed records, byte-identical to
    concatenating ``encode_record`` over the same records.
    """
    n = len(flag)
    if n == 0:
        return np.empty(0, dtype=np.uint8)
    qname_lens = qname_lens.astype(np.int64)
    cigar_lens = cigar_lens.astype(np.int64)
    codes_lens = codes_lens.astype(np.int64)
    tag_lens = tag_lens.astype(np.int64)

    lq = qname_lens + 1  # with NUL
    if lq.max(initial=0) > 255:
        raise ValueError(
            "qname longer than 254 bytes cannot be encoded (l_read_name is a "
            "single byte) — encode_record raises on the same input"
        )
    nsb = (codes_lens + 1) // 2
    rec_len = 36 + lq + 4 * cigar_lens + nsb + codes_lens + tag_lens
    starts = np.zeros(n, dtype=np.int64)
    np.cumsum(rec_len[:-1], out=starts[1:])
    total = int(rec_len.sum())
    out = np.zeros(total, dtype=np.uint8)

    # ref span for reg2bin: sum of M/D/N/=/X lengths per record (min 1)
    if len(cigar_words):
        consumes = _REF_CONSUMING[cigar_words & 0xF] * (cigar_words >> 4).astype(np.int64)
        cig_off = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(cigar_lens, out=cig_off[1:])
        span = np.add.reduceat(
            np.concatenate([consumes, [0]]), np.minimum(cig_off[:-1], len(consumes))
        )[:n]
        span[cigar_lens == 0] = 0
    else:
        span = np.zeros(n, dtype=np.int64)
    end = pos.astype(np.int64) + np.maximum(1, span)
    bins = reg2bin_vec(np.asarray(pos), end)

    # (n, 36) fixed block: 4-byte block_size + the 32-byte <iiBBHHHiiii core
    head = np.zeros((n, 36), dtype=np.uint8)
    hv = head.view("<i4")  # (n, 9) int32 view
    hv[:, 0] = (rec_len - 4).astype(np.int32)
    hv[:, 1] = np.asarray(rid, dtype=np.int32)
    hv[:, 2] = np.asarray(pos, dtype=np.int32)
    head[:, 12] = lq.astype(np.uint8)
    head[:, 13] = np.asarray(mapq, dtype=np.uint8)
    hb = head.view("<u2")  # (n, 18) uint16 view
    hb[:, 7] = bins.astype(np.uint16)
    hb[:, 8] = cigar_lens.astype(np.uint16)
    hb[:, 9] = np.asarray(flag, dtype=np.uint16)
    hv[:, 5] = codes_lens.astype(np.int32)
    hv[:, 6] = np.asarray(mrid, dtype=np.int32)
    hv[:, 7] = np.asarray(mpos, dtype=np.int32)
    hv[:, 8] = np.asarray(tlen, dtype=np.int32)
    out[(starts[:, None] + np.arange(36)).ravel()] = head.ravel()

    cur = starts + 36
    scatter_runs(out, cur, np.asarray(qname_data, dtype=np.uint8), qname_lens)
    # NUL terminators land at cur + qname_lens (out is zero-initialized)
    cur = cur + lq
    if len(cigar_words):
        scatter_runs(
            out, cur, cigar_words.astype("<u4").view(np.uint8), 4 * cigar_lens
        )
    cur = cur + 4 * cigar_lens

    # seq: pad odd-length records with a zero nibble, then pack pairs
    pad_lens = codes_lens + (codes_lens & 1)
    padded = np.zeros(int(pad_lens.sum()), dtype=np.uint8)
    pstarts = np.zeros(n, dtype=np.int64)
    np.cumsum(pad_lens[:-1], out=pstarts[1:])
    scatter_runs(padded, pstarts, CODE2NIB[np.asarray(codes_data)], codes_lens)
    packed = (padded[0::2] << 4) | padded[1::2]
    scatter_runs(out, cur, packed, nsb)
    cur = cur + nsb

    scatter_runs(out, cur, np.asarray(qual_data, dtype=np.uint8), codes_lens)
    cur = cur + codes_lens
    scatter_runs(out, cur, np.asarray(tag_data, dtype=np.uint8), tag_lens)
    return out


def cigar_string_to_words(cigar: list[tuple[str, int]]) -> np.ndarray:
    """``[("M", 100)] -> uint32 words`` (op in low nibble)."""
    from consensuscruncher_tpu.io.bam import _CIGAR_OP_OF

    return np.array([(n << 4) | _CIGAR_OP_OF[op] for op, n in cigar], dtype=np.uint32)


class ConsensusRecordWriter:
    """Column-accumulating consensus-record writer.

    ``add`` costs a dozen list appends per record; every ``flush_at``
    records the columns are encoded in one vectorized ``encode_records``
    pass and appended to the underlying ``BamWriter`` via
    ``write_encoded`` — byte-identical to per-record ``encode_record``
    writes in the same order, ~10x cheaper per record.
    """

    def __init__(self, writer, flush_at: int = 8192):
        self._writer = writer
        self._flush_at = flush_at
        self._reset()
        self.n_written = 0

    def _reset(self):
        self._qnames: list[bytes] = []
        self._flag: list[int] = []
        self._rid: list[int] = []
        self._pos: list[int] = []
        self._mapq: list[int] = []
        self._cigars: list[np.ndarray] = []
        self._mrid: list[int] = []
        self._mpos: list[int] = []
        self._tlen: list[int] = []
        self._codes: list[np.ndarray] = []
        self._quals: list[np.ndarray] = []
        self._tags: list[bytes] = []

    def add(self, qname: str, flag: int, rid: int, pos: int, mapq: int,
            cigar_words: np.ndarray, mrid: int, mpos: int, tlen: int,
            codes: np.ndarray, quals: np.ndarray, tag_blob: bytes) -> None:
        self._qnames.append(qname.encode("ascii"))
        self._flag.append(flag)
        self._rid.append(rid)
        self._pos.append(pos)
        self._mapq.append(mapq)
        self._cigars.append(cigar_words)
        self._mrid.append(mrid)
        self._mpos.append(mpos)
        self._tlen.append(tlen)
        self._codes.append(codes)
        self._quals.append(quals)
        self._tags.append(tag_blob)
        if len(self._flag) >= self._flush_at:
            self.flush()

    def add_columns(
        self,
        qname_data: np.ndarray, qname_lens: np.ndarray,
        flag: np.ndarray, rid: np.ndarray, pos: np.ndarray, mapq: np.ndarray,
        cigar_words: np.ndarray, cigar_lens: np.ndarray,
        mrid: np.ndarray, mpos: np.ndarray, tlen: np.ndarray,
        codes_data: np.ndarray, codes_lens: np.ndarray, qual_data: np.ndarray,
        tag_data: np.ndarray, tag_lens: np.ndarray,
    ) -> None:
        """Column-form twin of ``add``: encode a whole group of records in
        one ``encode_records`` pass and write immediately (groups are
        batch-sized — no accumulation needed).  Flushes any scalar-``add``
        backlog first so file order is call order."""
        self.flush()
        n = len(flag)
        if n == 0:
            return
        blob = encode_records(
            np.asarray(qname_data, np.uint8), np.asarray(qname_lens, np.int64),
            np.asarray(flag, np.int64), np.asarray(rid, np.int64),
            np.asarray(pos, np.int64), np.asarray(mapq, np.int64),
            np.asarray(cigar_words, np.uint32), np.asarray(cigar_lens, np.int64),
            np.asarray(mrid, np.int64), np.asarray(mpos, np.int64),
            np.asarray(tlen, np.int64),
            np.asarray(codes_data, np.uint8), np.asarray(codes_lens, np.int64),
            np.asarray(qual_data, np.uint8),
            np.asarray(tag_data, np.uint8), np.asarray(tag_lens, np.int64),
        )
        self._writer.write_encoded(blob)
        self.n_written += n

    def flush(self) -> None:
        n = len(self._flag)
        if n == 0:
            return
        blob = encode_records(
            np.frombuffer(b"".join(self._qnames), np.uint8),
            np.array([len(q) for q in self._qnames], np.int64),
            np.asarray(self._flag, np.int64),
            np.asarray(self._rid, np.int64),
            np.asarray(self._pos, np.int64),
            np.asarray(self._mapq, np.int64),
            (np.concatenate(self._cigars).astype(np.uint32)
             if any(len(c) for c in self._cigars) else np.empty(0, np.uint32)),
            np.array([len(c) for c in self._cigars], np.int64),
            np.asarray(self._mrid, np.int64),
            np.asarray(self._mpos, np.int64),
            np.asarray(self._tlen, np.int64),
            np.concatenate(self._codes) if self._codes else np.empty(0, np.uint8),
            np.array([len(c) for c in self._codes], np.int64),
            (np.concatenate(self._quals).astype(np.uint8)
             if self._quals else np.empty(0, np.uint8)),
            np.frombuffer(b"".join(self._tags), np.uint8),
            np.array([len(t) for t in self._tags], np.int64),
        )
        self._writer.write_encoded(blob)
        self.n_written += n
        self._reset()


class RenameRetagWriter:
    """Batched qname-rename + tag-append over raw columnar records.

    The SSCS singleton path rewrites each size-1 family's read with a
    consensus qname and XT/XF tags; doing that through decode_record +
    encode_record costs ~20 us/read.  This writer performs the rewrite as
    blob surgery: the record's cigar+seq+qual+tags span is one contiguous
    byte slice, so the output is [patched 36-byte head][new qname NUL]
    [original mid slice][appended tag blob] — assembled for a whole batch
    with the same scatter passes as ``encode_records``.  The bin field is
    recomputed from pos + cigar span exactly like ``encode_record``, so
    bytes match the object path (which re-encodes) on self-produced BAMs.

    Caller contract: records must NOT already carry any appended tag key
    (the object path's dict would replace in place; here we only append) —
    the SSCS stage routes reads that already have XT through the object
    fallback.
    """

    def __init__(self, writer, flush_at: int = 8192, max_batches: int = 4):
        self._writer = writer
        self._flush_at = flush_at
        self._max_batches = max_batches
        self._items: list[tuple] = []  # (batch, idx, qname bytes, tag blob)
        self._batch_ids: set[int] = set()

    def add(self, batch, idx: int, qname: str | bytes, tag_blob: bytes) -> None:
        if isinstance(qname, str):
            qname = qname.encode("ascii")
        self._items.append((batch, idx, qname, tag_blob))
        self._batch_ids.add(id(batch))
        # Bound retention in BYTES too: every buffered item pins its whole
        # source batch (tens of MB); sparse singletons would otherwise hold
        # hundreds of batches alive before the count-based flush fires.
        if (len(self._items) >= self._flush_at
                or len(self._batch_ids) > self._max_batches):
            self.flush()

    def flush(self) -> None:
        if not self._items:
            return
        by_batch: dict[int, list[int]] = {}
        batches: list = []
        for k, (batch, *_rest) in enumerate(self._items):
            bid = id(batch)
            if bid not in by_batch:
                by_batch[bid] = []
                batches.append(batch)
            by_batch[bid].append(k)
        # assemble in add order; per-record source columns gathered per batch
        n = len(self._items)
        idx_arr = np.fromiter((it[1] for it in self._items), np.int64, n)
        qnames = [it[2] for it in self._items]
        tags = [it[3] for it in self._items]
        qlen = np.fromiter((len(q) for q in qnames), np.int64, n)
        tglen = np.fromiter((len(t) for t in tags), np.int64, n)

        rec_off = np.empty(n, np.int64)
        rec_end = np.empty(n, np.int64)
        cig_start = np.empty(n, np.int64)
        ncig = np.empty(n, np.int64)
        pos = np.empty(n, np.int64)
        src_of = np.empty(n, np.int64)
        for bi, batch in enumerate(batches):
            rows = np.asarray(by_batch[id(batch)], np.int64)
            ridx = idx_arr[rows]
            rec_off[rows] = batch.rec_off[ridx]
            rec_end[rows] = batch.rec_off[ridx + 1]
            cig_start[rows] = batch.cigar_start[ridx]
            ncig[rows] = batch.n_cigar[ridx]
            pos[rows] = batch.pos[ridx]
            src_of[rows] = bi

        mid_len = rec_end - cig_start
        rec_len = 36 + (qlen + 1) + mid_len + tglen
        starts = np.zeros(n, np.int64)
        np.cumsum(rec_len[:-1], out=starts[1:])
        out = np.zeros(int(rec_len.sum()), np.uint8)

        # heads: original core bytes, then patch block_size/l_qname/bin
        head = np.zeros((n, 36), np.uint8)
        for bi, batch in enumerate(batches):
            rows = np.nonzero(src_of == bi)[0]
            head[rows] = batch.buf[
                rec_off[rows][:, None] + np.arange(36, dtype=np.int64)
            ]
        hv = head.view("<i4")
        hv[:, 0] = (rec_len - 4).astype(np.int32)
        head[:, 12] = (qlen + 1).astype(np.uint8)
        # recompute bin from pos + cigar span (encode_record parity)
        span = np.zeros(n, np.int64)
        for bi, batch in enumerate(batches):
            rows = np.nonzero((src_of == bi) & (ncig > 0))[0]
            if not rows.size:
                continue
            data, off = gather_runs(batch.buf, cig_start[rows], 4 * ncig[rows])
            words = np.ascontiguousarray(data).view("<u4")
            consumes = _REF_CONSUMING[words & 0xF] * (words >> 4).astype(np.int64)
            woff = (off // 4)[:-1]
            span[rows] = np.add.reduceat(
                np.concatenate([consumes, [0]]), np.minimum(woff, len(consumes))
            )[: len(rows)]
        hb = head.view("<u2")
        hb[:, 7] = reg2bin_vec(pos, pos + np.maximum(1, span)).astype(np.uint16)
        out[(starts[:, None] + np.arange(36)).ravel()] = head.ravel()

        cur = starts + 36
        scatter_runs(out, cur, np.frombuffer(b"".join(qnames), np.uint8), qlen)
        cur = cur + qlen + 1  # NUL from zero-init
        for bi, batch in enumerate(batches):
            rows = np.nonzero(src_of == bi)[0]
            data, _ = gather_runs(batch.buf, cig_start[rows], mid_len[rows])
            scatter_runs(out, cur[rows], data, mid_len[rows])
        cur = cur + mid_len
        scatter_runs(out, cur, np.frombuffer(b"".join(tags), np.uint8), tglen)
        self._writer.write_encoded(out)
        self._items.clear()
        self._batch_ids.clear()
