"""SAM text codec — parse/format alignment lines to/from ``BamRead``.

Needed for (a) consuming an external aligner's stdout in the fastq2bam stage
(the reference pipes ``bwa mem`` SAM through ``samtools view -b``, SURVEY.md
§3.1 — here the pipe lands in our own codec), and (b) human-readable debugging
(``view`` parity).
"""

from __future__ import annotations

from typing import Iterable, Iterator, TextIO

import numpy as np

from consensuscruncher_tpu.io.bam import BamHeader, BamRead, cigar_from_string
from consensuscruncher_tpu.utils.phred import qual_string_to_array, array_to_qual_string


def parse_header(lines: Iterable[str]) -> BamHeader:
    """Build a BamHeader from ``@``-lines (caller peels them off the stream)."""
    text, refs = [], []
    for line in lines:
        text.append(line if line.endswith("\n") else line + "\n")
        if line.startswith("@SQ"):
            name, length = None, None
            for fld in line.rstrip("\n").split("\t")[1:]:
                if fld.startswith("SN:"):
                    name = fld[3:]
                elif fld.startswith("LN:"):
                    length = int(fld[3:])
            if name is None or length is None:
                raise ValueError(f"malformed @SQ line: {line!r}")
            refs.append((name, length))
    return BamHeader(text="".join(text), refs=refs)


def _parse_tag(fld: str) -> tuple[str, tuple[str, object]]:
    key, typ, val = fld.split(":", 2)
    if typ == "i":
        return key, ("i", int(val))
    if typ == "f":
        return key, ("f", float(val))
    if typ == "A":
        return key, ("A", val)
    if typ in ("Z", "H"):
        return key, (typ, val)
    if typ == "B":
        sub, *vals = val.split(",")
        conv = float if sub == "f" else int
        return key, ("B", (sub, [conv(v) for v in vals]))
    raise ValueError(f"unsupported SAM tag type in {fld!r}")


def parse_record(line: str) -> BamRead:
    f = line.rstrip("\n").split("\t")
    if len(f) < 11:
        raise ValueError(f"malformed SAM line ({len(f)} fields)")
    qual = np.zeros(0, dtype=np.uint8) if f[10] == "*" else qual_string_to_array(f[10])
    return BamRead(
        qname=f[0],
        flag=int(f[1]),
        ref=f[2],
        pos=int(f[3]) - 1,  # SAM is 1-based, BamRead stores 0-based like BAM
        mapq=int(f[4]),
        cigar=cigar_from_string(f[5]),
        mate_ref=f[2] if f[6] == "=" else f[6],
        mate_pos=int(f[7]) - 1,
        tlen=int(f[8]),
        seq="" if f[9] == "*" else f[9],
        qual=qual,
        tags=dict(_parse_tag(x) for x in f[11:]),
    )


def format_record(read: BamRead) -> str:
    mate = read.mate_ref
    if mate != "*" and mate == read.ref:
        mate = "="
    tags = []
    for key, (typ, val) in read.tags.items():
        if typ in "cCsSiI":
            tags.append(f"{key}:i:{val}")
        elif typ == "B":
            sub, vals = val
            tags.append(f"{key}:B:{sub}," + ",".join(str(v) for v in vals))
        else:
            tags.append(f"{key}:{typ}:{val}")
    fields = [
        read.qname,
        str(read.flag),
        read.ref,
        str(read.pos + 1),
        str(read.mapq),
        read.cigar_string(),
        mate,
        str(read.mate_pos + 1),
        str(read.tlen),
        read.seq or "*",
        array_to_qual_string(read.qual) if read.qual.size else "*",
    ]
    return "\t".join(fields + tags)


def read_sam(fh: TextIO) -> tuple[BamHeader, Iterator[BamRead]]:
    """Split a SAM text stream into (header, record iterator)."""
    header_lines: list[str] = []
    first_record: list[str] = []
    for line in fh:
        if line.startswith("@"):
            header_lines.append(line)
        else:
            first_record.append(line)
            break

    def records() -> Iterator[BamRead]:
        for line in first_record:
            if line.strip():
                yield parse_record(line)
        for line in fh:
            if line.strip():
                yield parse_record(line)

    return parse_header(header_lines), records()
