"""FASTQ reader/writer (gzip-transparent).

Reference parity: the Biopython ``FastqGeneralIterator`` + ``gzip`` usage in
``ConsensusCruncher/extract_barcodes.py`` (SURVEY.md §2; Biopython is absent
here, so the framework owns the parser).  Records are ``(name, seq, qual)``
string triples; ``name`` excludes the leading ``@`` and keeps any comment.
"""

from __future__ import annotations

import gzip
import io
from typing import Iterator, TextIO


def _open_text(path, mode: str):
    p = str(path)
    if p.endswith(".gz"):
        if "w" in mode:
            # mtime=0 keeps writes byte-deterministic (same content -> same
            # .gz bytes), so regenerated fixtures don't dirty VCS history.
            return io.TextIOWrapper(
                gzip.GzipFile(p, "wb", mtime=0), encoding="ascii"
            )
        return gzip.open(p, mode + "t", encoding="ascii")
    return open(p, mode, encoding="ascii")


def read_fastq(path) -> Iterator[tuple[str, str, str]]:
    """Yield ``(name, seq, qual)`` triples; validates 4-line framing."""
    with _open_text(path, "r") as fh:
        while True:
            head = fh.readline()
            if not head:
                return
            if not head.startswith("@"):
                raise ValueError(f"bad FASTQ header line: {head!r}")
            seq = fh.readline().rstrip("\r\n")
            plus = fh.readline()
            qual = fh.readline().rstrip("\r\n")
            if not plus.startswith("+"):
                raise ValueError(f"bad FASTQ separator for {head.strip()!r}")
            if len(seq) != len(qual):
                raise ValueError(f"seq/qual length mismatch for {head.strip()!r}")
            yield head[1:].rstrip("\r\n"), seq, qual


class FastqWriter:
    def __init__(self, path):
        self._fh: TextIO = _open_text(path, "w")

    def write(self, name: str, seq: str, qual: str) -> None:
        self._fh.write(f"@{name}\n{seq}\n+\n{qual}\n")

    def close(self) -> None:
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
