"""FASTQ reader/writer (gzip-transparent).

Reference parity: the Biopython ``FastqGeneralIterator`` + ``gzip`` usage in
``ConsensusCruncher/extract_barcodes.py`` (SURVEY.md §2; Biopython is absent
here, so the framework owns the parser).  Records are ``(name, seq, qual)``
string triples; ``name`` excludes the leading ``@`` and keeps any comment.
"""

from __future__ import annotations

import gzip
from typing import Iterator, TextIO


def _open_text(path, mode: str):
    """Read-side opener (writing goes through :class:`FastqWriter`)."""
    p = str(path)
    if "w" in mode:
        raise ValueError("use FastqWriter for writing")
    if p.endswith(".gz"):
        return gzip.open(p, mode + "t", encoding="ascii")
    return open(p, mode, encoding="ascii")


def read_fastq(path) -> Iterator[tuple[str, str, str]]:
    """Yield ``(name, seq, qual)`` triples; validates 4-line framing."""
    with _open_text(path, "r") as fh:
        while True:
            head = fh.readline()
            if not head:
                return
            if not head.startswith("@"):
                raise ValueError(f"bad FASTQ header line: {head!r}")
            seq = fh.readline().rstrip("\r\n")
            plus = fh.readline()
            qual = fh.readline().rstrip("\r\n")
            if not plus.startswith("+"):
                raise ValueError(f"bad FASTQ separator for {head.strip()!r}")
            if len(seq) != len(qual):
                raise ValueError(f"seq/qual length mismatch for {head.strip()!r}")
            yield head[1:].rstrip("\r\n"), seq, qual


class FastqWriter:
    """Binary-mode writer (gzip-transparent, mtime=0 for deterministic .gz
    bytes); ``write`` takes string triples, ``write_bytes`` pre-assembled
    record blobs (the vectorized extract path) — identical output bytes."""

    def __init__(self, path, level: int = 6):
        # level 6 (the gzip/bgzip CLI default): python's GzipFile default of
        # 9 costs ~2.5x the deflate time for ~1% size on FASTQ — it was 90%
        # of extract_barcodes wall-clock.  Goldens hash decompressed content,
        # so the level is a pure throughput knob.
        #
        # .gz outputs are written as BGZF: still a valid multi-member gzip
        # stream (gunzip/bwa/STAR all read it — bgzip's own trick), but the
        # deflate runs through the native batch codec and its thread pool
        # (io/bgzf.codec_threads), so extraction scales with host cores
        # instead of serializing one zlib stream on the Python thread.
        p = str(path)
        if p.endswith(".gz"):
            from consensuscruncher_tpu.io import bgzf

            self._fh = bgzf.BgzfWriter(p, level=level)
        else:
            self._fh = open(p, "wb")

    def write(self, name: str, seq: str, qual: str) -> None:
        self._fh.write(f"@{name}\n{seq}\n+\n{qual}\n".encode("ascii"))

    def write_bytes(self, blob: bytes) -> None:
        self._fh.write(blob)

    def close(self) -> None:
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# Columnar batch reader: the vectorized extract_barcodes path decodes whole
# chunks of records into a byte pool + offset columns (same design as
# io/columnar.py for BAM).  Framing is validated vectorized; '\r\n' line
# endings are handled by trimming a trailing '\r' per line.

import numpy as np


class FastqBatch:
    """One chunk of records over a shared byte pool ``data``.

    Per record (``(n,)`` int64 columns): ``name_start``/``name_len`` (the
    full header after '@', comment included), ``seq_start``/``seq_len``,
    ``qual_start`` (qual length == seq length, validated).
    """

    __slots__ = ("data", "name_start", "name_len", "seq_start", "seq_len",
                 "qual_start")

    def __init__(self, data, name_start, name_len, seq_start, seq_len, qual_start):
        self.data = data
        self.name_start = name_start
        self.name_len = name_len
        self.seq_start = seq_start
        self.seq_len = seq_len
        self.qual_start = qual_start

    @property
    def n(self) -> int:
        return len(self.name_start)


def _open_binary(path):
    p = str(path)
    if p.endswith(".gz"):
        return gzip.GzipFile(p, "rb")
    return open(p, "rb")


def read_fastq_batches(path, chunk_bytes: int = 32 << 20):
    """Yield :class:`FastqBatch` chunks; same framing validation as
    :func:`read_fastq` (leading '@', '+' separator, equal seq/qual length)."""
    with _open_binary(path) as fh:
        tail = b""
        eof = False
        rec_base = 0  # absolute record number of the chunk's first record
        while not eof:
            chunk = fh.read(chunk_bytes)
            if not chunk:
                eof = True
            blob = tail + chunk
            if not blob:
                return
            if eof and not blob.endswith(b"\n"):
                blob += b"\n"  # files without a final newline parse like readline()
            buf = np.frombuffer(blob, np.uint8)
            nl = np.nonzero(buf == 10)[0]
            n_lines = len(nl)
            if eof and n_lines % 4:
                raise ValueError("FASTQ truncated: record is not 4 lines")
            n_rec = (n_lines // 4)
            if n_rec == 0:
                if eof and len(blob):
                    raise ValueError("FASTQ truncated: record is not 4 lines")
                tail = blob
                continue
            used = int(nl[4 * n_rec - 1]) + 1
            tail = blob[used:]
            nl = nl[: 4 * n_rec]
            starts = np.empty(4 * n_rec, np.int64)
            starts[0] = 0
            starts[1:] = nl[:-1] + 1
            ends = nl.copy()  # exclusive of '\n'
            # trim '\r' of CRLF files
            has_cr = ends > starts
            cr = np.zeros(4 * n_rec, bool)
            cr[has_cr] = buf[ends[has_cr] - 1] == 13
            ends = ends - cr
            l0, l1, l2, l3 = (starts[k::4] for k in range(4))
            e0, e1, e2, e3 = (ends[k::4] for k in range(4))
            if not (buf[l0] == ord("@")).all():
                bad = int(np.nonzero(buf[l0] != ord("@"))[0][0])
                raise ValueError(
                    f"bad FASTQ header line at record {rec_base + bad}: "
                    f"{bytes(buf[l0[bad]:e0[bad]])[:40]!r}"
                )
            if not ((e2 > l2) & (buf[np.minimum(l2, len(buf) - 1)] == ord("+"))).all():
                raise ValueError("bad FASTQ separator line (expected '+')")
            seq_len = e1 - l1
            if not (seq_len == (e3 - l3)).all():
                bad = int(np.nonzero(seq_len != (e3 - l3))[0][0])
                raise ValueError(
                    f"seq/qual length mismatch at record "
                    f"{bytes(buf[l0[bad] + 1:e0[bad]])[:40]!r}"
                )
            yield FastqBatch(
                data=buf,
                name_start=l0 + 1, name_len=e0 - (l0 + 1),
                seq_start=l1, seq_len=seq_len,
                qual_start=l3,
            )
            rec_base += n_rec
        if tail:
            raise ValueError("FASTQ truncated: record is not 4 lines")
