// Native BGZF hot path: multithreaded block inflate/deflate over zlib.
//
// Role in the framework (see io/bgzf.py): BGZF files are sequences of
// independent <=64 KiB gzip members, which makes the codec embarrassingly
// parallel at block granularity.  The reference pipeline gets this layer
// from htslib (via pysam, SURVEY.md §2 "Native components"); this is the
// framework's first-party equivalent.  Python scans block framing (cheap:
// one 18-byte header per 64 KiB) and hands batches of raw-deflate spans to
// these entry points, which fan out across std::thread workers.
//
// C ABI (ctypes-loaded by io/native/__init__.py):
//   cct_inflate_blocks  — batch raw-inflate with CRC32 + ISIZE validation
//   cct_deflate_blocks  — batch payload -> complete BGZF blocks (header +
//                         deflate + CRC32/ISIZE tail), stride-sliced output
//   cct_version         — ABI version stamp so a stale .so is never trusted
//
// Build (done lazily by the Python wrapper):
//   g++ -O3 -shared -fPIC -pthread bgzf_native.cpp -o bgzf_native.so -lz

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <zlib.h>
#ifdef USE_LIBDEFLATE
#include <libdeflate.h>
#endif

namespace {

constexpr int kAbiVersion = 9;
constexpr uint32_t kMaxBlockPayload = 0xFF00;  // htslib payload bound
constexpr uint32_t kOutStride = 0x10400;       // per-block output slot (worst case + slack)

// BGZF block header for a complete block of `block_size` total bytes.
void write_block_header(uint8_t* dst, uint32_t block_size) {
  static const uint8_t fixed[16] = {
      0x1f, 0x8b, 0x08, 0x04,  // gzip magic, deflate, FEXTRA
      0,    0,    0,    0,     // mtime
      0,    0xff,              // XFL, OS=unknown
      6,    0,                 // XLEN = 6
      0x42, 0x43, 2,    0,     // 'B','C', SLEN=2
  };
  std::memcpy(dst, fixed, 16);
  const uint32_t bsize = block_size - 1;
  dst[16] = static_cast<uint8_t>(bsize & 0xff);
  dst[17] = static_cast<uint8_t>((bsize >> 8) & 0xff);
}

void put_le32(uint8_t* dst, uint32_t v) {
  dst[0] = static_cast<uint8_t>(v & 0xff);
  dst[1] = static_cast<uint8_t>((v >> 8) & 0xff);
  dst[2] = static_cast<uint8_t>((v >> 16) & 0xff);
  dst[3] = static_cast<uint8_t>((v >> 24) & 0xff);
}

int clamp_threads(int32_t n_threads, int64_t n_items) {
  int hw = static_cast<int>(std::thread::hardware_concurrency());
  if (hw <= 0) hw = 4;
  int n = n_threads > 0 ? n_threads : hw;
  if (static_cast<int64_t>(n) > n_items) n = static_cast<int>(n_items);
  return n < 1 ? 1 : n;
}

// Run fn(i) over [0, n) on up to n_threads workers; first nonzero return
// (1-based error code) wins.
template <typename Fn>
int parallel_for(int64_t n, int32_t n_threads, Fn fn) {
  if (n <= 0) return 0;
  const int workers = clamp_threads(n_threads, n);
  if (workers == 1) {
    for (int64_t i = 0; i < n; ++i) {
      int rc = fn(i);
      if (rc) return rc;
    }
    return 0;
  }
  std::atomic<int64_t> next(0);
  std::atomic<int> err(0);
  std::vector<std::thread> pool;
  pool.reserve(workers);
  for (int w = 0; w < workers; ++w) {
    pool.emplace_back([&] {
      for (;;) {
        const int64_t i = next.fetch_add(1, std::memory_order_relaxed);
        if (i >= n || err.load(std::memory_order_relaxed)) return;
        int rc = fn(i);
        if (rc) {
          int expected = 0;
          err.compare_exchange_strong(expected, rc);
          return;
        }
      }
    });
  }
  for (auto& t : pool) t.join();
  return err.load();
}

#ifdef USE_LIBDEFLATE
// libdeflate path (htslib uses the same library when available): whole-
// buffer raw-DEFLATE — a perfect fit for <=64 KiB BGZF blocks, measured
// 1.5-2.5x zlib either direction.  Compressor/decompressor handles are
// thread_local: parallel_for spawns fresh workers per call, so this is
// one allocation per worker per BATCH call (not per process — but also
// not per 64 KiB block, which is the cost that matters).
struct CompressorCache {
  libdeflate_compressor* c[13] = {};
  ~CompressorCache() {
    for (auto* p : c)
      if (p) libdeflate_free_compressor(p);
  }
};

libdeflate_compressor* compressor_for(int level) {
  if (level < 0) level = 0;
  if (level > 12) level = 12;
  thread_local CompressorCache cache;
  if (!cache.c[level]) cache.c[level] = libdeflate_alloc_compressor(level);
  return cache.c[level];
}

libdeflate_decompressor* decompressor() {
  struct Holder {
    libdeflate_decompressor* d = libdeflate_alloc_decompressor();
    ~Holder() {
      if (d) libdeflate_free_decompressor(d);
    }
  };
  thread_local Holder h;
  return h.d;
}

// Raw-deflate `src` into `dst`; returns compressed size or 0 on failure.
uint32_t raw_deflate(const uint8_t* src, uint32_t src_len, int level, uint8_t* dst,
                     uint32_t dst_cap) {
  libdeflate_compressor* c = compressor_for(level);
  if (!c) return 0;
  return static_cast<uint32_t>(
      libdeflate_deflate_compress(c, src, src_len, dst, dst_cap));
}

// Raw-inflate `src` into exactly `want` bytes of `dst`; false on failure.
bool raw_inflate(const uint8_t* src, uint32_t src_len, uint8_t* dst, uint32_t want) {
  libdeflate_decompressor* d = decompressor();
  if (!d) return false;
  size_t actual = 0;
  const libdeflate_result rc = libdeflate_deflate_decompress(
      d, src, src_len, dst, want, &actual);
  return rc == LIBDEFLATE_SUCCESS && actual == want;
}

uint32_t payload_crc32(const uint8_t* data, uint32_t len) {
  return static_cast<uint32_t>(libdeflate_crc32(0, data, len));
}
#else
// Raw-deflate `src` into `dst`; returns compressed size or 0 on failure.
uint32_t raw_deflate(const uint8_t* src, uint32_t src_len, int level, uint8_t* dst,
                     uint32_t dst_cap) {
  z_stream zs;
  std::memset(&zs, 0, sizeof(zs));
  if (deflateInit2(&zs, level, Z_DEFLATED, -15, 8, Z_DEFAULT_STRATEGY) != Z_OK) return 0;
  zs.next_in = const_cast<uint8_t*>(src);
  zs.avail_in = src_len;
  zs.next_out = dst;
  zs.avail_out = dst_cap;
  const int rc = deflate(&zs, Z_FINISH);
  const uint32_t produced = dst_cap - zs.avail_out;
  deflateEnd(&zs);
  return rc == Z_STREAM_END ? produced : 0;
}

bool raw_inflate(const uint8_t* src, uint32_t src_len, uint8_t* dst, uint32_t want) {
  z_stream zs;
  std::memset(&zs, 0, sizeof(zs));
  if (inflateInit2(&zs, -15) != Z_OK) return false;
  zs.next_in = const_cast<uint8_t*>(src);
  zs.avail_in = src_len;
  zs.next_out = dst;
  zs.avail_out = want;
  const int rc = inflate(&zs, Z_FINISH);
  const uint32_t produced = want - zs.avail_out;
  inflateEnd(&zs);
  return rc == Z_STREAM_END && produced == want;
}

uint32_t payload_crc32(const uint8_t* data, uint32_t len) {
  return static_cast<uint32_t>(crc32(crc32(0L, Z_NULL, 0), data, len));
}
#endif

}  // namespace

extern "C" {

int cct_version() { return kAbiVersion; }

uint32_t cct_out_stride() { return kOutStride; }

// Inflate n raw-deflate spans of `src` into `out`, validating CRC32 + ISIZE.
//
//   src_off[i]  : offset of block i's deflate data within src
//   comp_len[i] : its length (tail excluded)
//   isize[i]    : expected inflated size (from the block tail)
//   crc[i]      : expected CRC32 of the inflated payload
//   out_off[i]  : where payload i lands in `out` (caller-prefixed cumsum)
//
// Returns 0 on success, i+1 if block i failed (bad stream / CRC / ISIZE).
int cct_inflate_blocks(const uint8_t* src, const uint64_t* src_off, const uint32_t* comp_len,
                       const uint32_t* isize, const uint32_t* crc, int64_t n, uint8_t* out,
                       const uint64_t* out_off, int32_t n_threads) {
  return parallel_for(n, n_threads, [&](int64_t i) -> int {
    uint8_t* dst = out + out_off[i];
    const uint32_t want = isize[i];
    if (want == 0) {
      // Empty block (e.g. EOF marker): nothing to inflate, CRC of "" is 0.
      return crc[i] == 0 ? 0 : static_cast<int>(i + 1);
    }
    if (!raw_inflate(src + src_off[i], comp_len[i], dst, want))
      return static_cast<int>(i + 1);
    if (payload_crc32(dst, want) != crc[i]) return static_cast<int>(i + 1);
    return 0;
  });
}

// Compress `payload` into complete BGZF blocks of <= kMaxBlockPayload bytes
// each.  Output is stride-sliced: block i is written at out + i*kOutStride,
// its total size recorded in out_sizes[i]; the caller compacts the slices.
// Incompressible data that would overflow the 16-bit BSIZE field is retried
// as stored (level 0) deflate, which always fits (htslib does the same).
//
// Returns 0 on success, i+1 if block i failed.
int cct_deflate_blocks(const uint8_t* payload, uint64_t payload_len, int32_t level,
                       int32_t n_threads, uint8_t* out, uint32_t* out_sizes) {
  const int64_t n_blocks =
      payload_len == 0 ? 0
                       : static_cast<int64_t>((payload_len + kMaxBlockPayload - 1) / kMaxBlockPayload);
  return parallel_for(n_blocks, n_threads, [&](int64_t i) -> int {
    const uint64_t start = static_cast<uint64_t>(i) * kMaxBlockPayload;
    const uint32_t len = static_cast<uint32_t>(
        payload_len - start < kMaxBlockPayload ? payload_len - start : kMaxBlockPayload);
    const uint8_t* src = payload + start;
    uint8_t* slot = out + static_cast<uint64_t>(i) * kOutStride;
    uint8_t* data = slot + 18;
    const uint32_t data_cap = kOutStride - 26;
    uint32_t comp = raw_deflate(src, len, level, data, data_cap);
    if (comp == 0 || comp + 26 > 0xFFFF) {
      comp = raw_deflate(src, len, 0, data, data_cap);  // stored: always fits
      if (comp == 0 || comp + 26 > 0xFFFF) return static_cast<int>(i + 1);
    }
    const uint32_t block_size = comp + 26;
    write_block_header(slot, block_size);
    put_le32(data + comp, payload_crc32(src, len));
    put_le32(data + comp + 4, len);
    out_sizes[i] = block_size;
    return 0;
  });
}

// Ragged-run copy: dst[dst_starts[i] : +lens[i]] = src[src_starts[i] : +lens[i]].
//
// The byte-level workhorse behind utils/ragged.py's gather/scatter — the
// numpy fallback builds ~24 bytes of int64 fancy-index per payload byte,
// while this is a straight memcpy loop.  Offsets/lengths are in BYTES; the
// Python wrapper scales element offsets by itemsize and bounds-checks
// before calling (this function trusts its inputs).
void cct_copy_runs(const uint8_t* src, const int64_t* src_starts, uint8_t* dst,
                   const int64_t* dst_starts, const int64_t* lens, int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    std::memcpy(dst + dst_starts[i], src + src_starts[i], static_cast<size_t>(lens[i]));
  }
}

// Fused wire packing (ops/packing.py hot path).  lut is the 256-entry
// qual->codebook-index table; entries of 255 mean "not in codebook".
// Returns 0 on success, 1 if a base code exceeds the bit budget, 2 if a
// qual is not in the codebook.
//
// pack8: out[i] = base[i] | (lut[qual[i]] << 3)          (n bytes out)
// pack4: nibble per position, two positions per byte; odd n padded with a
//        zero nibble.  out must hold (n+1)/2 bytes.
int cct_pack8(const uint8_t* bases, const uint8_t* quals, const uint8_t* lut, int64_t n,
              uint8_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t b = bases[i];
    const uint8_t q = lut[quals[i]];
    if (b > 7) return 1;
    if (q > 15) return 2;
    out[i] = static_cast<uint8_t>(b | (q << 3));
  }
  return 0;
}

int cct_pack4(const uint8_t* bases, const uint8_t* quals, const uint8_t* lut, int64_t n,
              uint8_t* out) {
  const int64_t pairs = n / 2;
  for (int64_t i = 0; i < pairs; ++i) {
    const uint8_t b0 = bases[2 * i], b1 = bases[2 * i + 1];
    const uint8_t q0 = lut[quals[2 * i]], q1 = lut[quals[2 * i + 1]];
    if ((b0 | b1) > 3) return 1;
    if (q0 > 3 || q1 > 3) return 2;
    out[i] = static_cast<uint8_t>((b0 | (q0 << 2)) | ((b1 | (q1 << 2)) << 4));
  }
  if (n & 1) {
    const uint8_t b = bases[n - 1];
    const uint8_t q = lut[quals[n - 1]];
    if (b > 3) return 1;
    if (q > 3) return 2;
    out[pairs] = static_cast<uint8_t>(b | (q << 2));
  }
  return 0;
}

// Scan length-prefixed BAM records in buf[0:limit] (the serial pass the
// columnar reader and the sorting writer both need).  Writes the n+1
// record boundary offsets into out (capacity max_out) and returns n, the
// number of COMPLETE records; -1 signals a corrupt block_size (< 32).
// Little-endian host assumed (true of every deploy target).
int64_t cct_scan_bam_records(const uint8_t* buf, int64_t limit, int64_t* out,
                             int64_t max_out) {
  int64_t o = 0, n = 0;
  if (max_out > 0) out[0] = 0;
  while (o + 4 <= limit) {
    int32_t bs;
    std::memcpy(&bs, buf + o, 4);
    if (bs < 32) return -1;
    if (o + 4 + static_cast<int64_t>(bs) > limit) break;
    o += 4 + bs;
    ++n;
    if (n < max_out) out[n] = o;
  }
  return n;
}

// Expand packed BAM seq bytes (two 4-bit nibbles each) through a
// (256 x 2)-byte LUT: out[2i] = lut[2*src[i]], out[2i+1] = lut[2*src[i]+1].
// The columnar reader's nibble->base-code decode, one pass in C.
void cct_expand_nibbles(const uint8_t* src, int64_t n, const uint8_t* lut2,
                        uint8_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t b = src[i];
    out[2 * i] = lut2[2 * b];
    out[2 * i + 1] = lut2[2 * b + 1];
  }
}

// Gather fixed-width little-endian fields at arbitrary byte offsets:
// out[i*width : (i+1)*width] = src[off[i] : off[i]+width].  The columnar
// reader's per-record header-field decode (width 2/4).
void cct_gather_fixed(const uint8_t* src, const int64_t* off, int64_t n,
                      int32_t width, uint8_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    std::memcpy(out + static_cast<int64_t>(i) * width, src + off[i],
                static_cast<size_t>(width));
  }
}

// Byte-value histogram (256 bins) — the one-pass replacement for
// np.unique over tens-of-MB uint8 wire batches.
void cct_byte_counts(const uint8_t* data, int64_t n, int64_t* counts) {
  std::memset(counts, 0, 256 * sizeof(int64_t));
  for (int64_t i = 0; i < n; ++i) ++counts[data[i]];
}

// Ragged-run fill: dst[starts[i] : +lens[i]] = value (byte fill).
void cct_fill_runs(uint8_t* dst, const int64_t* starts, const int64_t* lens, int64_t n,
                   int32_t value) {
  for (int64_t i = 0; i < n; ++i) {
    std::memset(dst + starts[i], value, static_cast<size_t>(lens[i]));
  }
}

// Windowed equal-range over a sorted int64 array: per key i, search only
// [lo0[i], hi0[i]) (the aligner's prefix-table window) and write the
// first index with arr[j] >= key to out_lo and the first with arr[j] >
// key to out_hi.  Replaces the numpy branchless lockstep search, whose
// fixed-step loop pays ~6 full-array passes per level for every lane —
// here each key's search stays in registers over a cache-resident window.
void cct_equal_range_i64(const int64_t* arr, const int64_t* keys, const int64_t* lo0,
                         const int64_t* hi0, int64_t m, int64_t* out_lo, int64_t* out_hi,
                         int32_t n_threads) {
  constexpr int64_t kChunk = 4096;  // amortize the work-queue atomic
  const int64_t n_chunks = (m + kChunk - 1) / kChunk;
  parallel_for(n_chunks, n_threads, [&](int64_t c) -> int {
    const int64_t end = std::min(m, (c + 1) * kChunk);
    for (int64_t i = c * kChunk; i < end; ++i) {
      const int64_t key = keys[i];
      int64_t a = lo0[i], b = hi0[i];
      while (a < b) {
        const int64_t mid = (a + b) >> 1;
        if (arr[mid] < key) a = mid + 1; else b = mid;
      }
      out_lo[i] = a;
      int64_t x = a, y = hi0[i];
      while (x < y) {
        const int64_t mid = (x + y) >> 1;
        if (arr[mid] <= key) x = mid + 1; else y = mid;
      }
      out_hi[i] = x;
    }
    return 0;
  });
}

}  // extern "C"
