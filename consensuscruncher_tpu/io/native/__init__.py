"""ctypes loader for the native C++ BGZF codec (``bgzf_native.cpp``).

The shared library is compiled lazily with ``g++`` the first time it's
needed and cached next to the source; a content hash in the cache name
means editing the .cpp (or bumping the ABI) transparently rebuilds.  Every
entry point degrades to the pure-Python codec in ``io/bgzf.py`` when the
toolchain is missing or ``CCT_NO_NATIVE=1`` is set — the native layer is a
throughput optimization, never a correctness dependency.

Public surface:
- ``available()`` — is the native codec usable?
- ``inflate_blocks(src, src_off, comp_len, isize, crc)`` — batch raw-inflate
  with CRC/ISIZE checks (metadata arrays from ``io.bgzf.scan_block_metas``)
- ``deflate_payload(data, level)`` — payload bytes -> framed BGZF blocks
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading

import numpy as np

_ABI_VERSION = 9
_SRC = os.path.join(os.path.dirname(__file__), "bgzf_native.cpp")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build_and_load() -> ctypes.CDLL | None:
    with open(_SRC, "rb") as fh:
        digest = hashlib.sha256(fh.read()).hexdigest()[:16]
    cache_dir = os.environ.get("CCT_NATIVE_CACHE", os.path.dirname(_SRC))
    so_path = os.path.join(cache_dir, f"bgzf_native-{digest}.so")
    if not os.path.exists(so_path):
        # Everything filesystem/toolchain-shaped is guarded: an unwritable
        # cache dir or missing g++ must degrade to the pure-Python codec,
        # never crash the open (the module's "optional, not a dependency"
        # contract).
        tmp = None
        try:
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=cache_dir)
            os.close(fd)
            base = ["g++", "-O3", "-shared", "-fPIC", "-pthread", _SRC,
                    "-o", tmp]
            try:
                # libdeflate first (1.5-2.5x zlib on <=64 KiB BGZF blocks;
                # htslib links it the same way when present) ...
                subprocess.run(base + ["-DUSE_LIBDEFLATE", "-ldeflate", "-lz"],
                               check=True, capture_output=True, timeout=300)
            except (OSError, subprocess.SubprocessError):
                # ... plain zlib otherwise — bit-different compressed bytes,
                # identical decompressed content (goldens canonicalize).
                subprocess.run(base + ["-lz"], check=True,
                               capture_output=True, timeout=300)
            os.replace(tmp, so_path)  # atomic: concurrent builders race benignly
        except (OSError, subprocess.SubprocessError):
            if tmp is not None and os.path.exists(tmp):
                os.unlink(tmp)
            return None
    try:
        lib = ctypes.CDLL(so_path)
    except OSError:
        # A cached .so can carry a DT_NEEDED on libdeflate from a build
        # host that had it while this runtime does not — rebuild once
        # against whatever THIS host links instead of silently running
        # pure-Python forever.
        try:
            os.unlink(so_path)
        except OSError:
            return None
        try:
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=cache_dir)
            os.close(fd)
            base = ["g++", "-O3", "-shared", "-fPIC", "-pthread", _SRC,
                    "-o", tmp]
            try:
                subprocess.run(base + ["-DUSE_LIBDEFLATE", "-ldeflate", "-lz"],
                               check=True, capture_output=True, timeout=300)
            except (OSError, subprocess.SubprocessError):
                subprocess.run(base + ["-lz"], check=True,
                               capture_output=True, timeout=300)
            os.replace(tmp, so_path)
            lib = ctypes.CDLL(so_path)
        except (OSError, subprocess.SubprocessError):
            return None
    lib.cct_version.restype = ctypes.c_int
    if lib.cct_version() != _ABI_VERSION:
        return None
    lib.cct_out_stride.restype = ctypes.c_uint32
    lib.cct_inflate_blocks.restype = ctypes.c_int
    lib.cct_inflate_blocks.argtypes = [
        ctypes.c_char_p,                    # src
        ctypes.POINTER(ctypes.c_uint64),    # src_off
        ctypes.POINTER(ctypes.c_uint32),    # comp_len
        ctypes.POINTER(ctypes.c_uint32),    # isize
        ctypes.POINTER(ctypes.c_uint32),    # crc
        ctypes.c_int64,                     # n
        ctypes.c_char_p,                    # out
        ctypes.POINTER(ctypes.c_uint64),    # out_off
        ctypes.c_int32,                     # n_threads
    ]
    lib.cct_deflate_blocks.restype = ctypes.c_int
    lib.cct_deflate_blocks.argtypes = [
        ctypes.c_char_p,                    # payload
        ctypes.c_uint64,                    # payload_len
        ctypes.c_int32,                     # level
        ctypes.c_int32,                     # n_threads
        ctypes.c_char_p,                    # out
        ctypes.POINTER(ctypes.c_uint32),    # out_sizes
    ]
    lib.cct_pack8.restype = ctypes.c_int
    lib.cct_pack8.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p,
    ]
    lib.cct_pack4.restype = ctypes.c_int
    lib.cct_pack4.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p,
    ]
    lib.cct_byte_counts.restype = None
    lib.cct_byte_counts.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
    ]
    lib.cct_scan_bam_records.restype = ctypes.c_int64
    lib.cct_scan_bam_records.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
    ]
    lib.cct_expand_nibbles.restype = None
    lib.cct_expand_nibbles.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_char_p,
    ]
    lib.cct_gather_fixed.restype = None
    lib.cct_gather_fixed.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.c_int32, ctypes.c_char_p,
    ]
    lib.cct_copy_runs.restype = None
    lib.cct_copy_runs.argtypes = [
        ctypes.c_char_p,                    # src
        ctypes.POINTER(ctypes.c_int64),     # src_starts (bytes)
        ctypes.c_char_p,                    # dst
        ctypes.POINTER(ctypes.c_int64),     # dst_starts (bytes)
        ctypes.POINTER(ctypes.c_int64),     # lens (bytes)
        ctypes.c_int64,                     # n
    ]
    lib.cct_fill_runs.restype = None
    lib.cct_fill_runs.argtypes = [
        ctypes.c_char_p,                    # dst
        ctypes.POINTER(ctypes.c_int64),     # starts (bytes)
        ctypes.POINTER(ctypes.c_int64),     # lens (bytes)
        ctypes.c_int64,                     # n
        ctypes.c_int32,                     # value
    ]
    lib.cct_equal_range_i64.restype = None
    lib.cct_equal_range_i64.argtypes = [
        ctypes.POINTER(ctypes.c_int64),     # arr (sorted)
        ctypes.POINTER(ctypes.c_int64),     # keys
        ctypes.POINTER(ctypes.c_int64),     # lo0
        ctypes.POINTER(ctypes.c_int64),     # hi0
        ctypes.c_int64,                     # m
        ctypes.POINTER(ctypes.c_int64),     # out_lo
        ctypes.POINTER(ctypes.c_int64),     # out_hi
        ctypes.c_int32,                     # n_threads
    ]
    return lib


def _get() -> ctypes.CDLL | None:
    global _lib, _tried
    if _tried:
        return _lib
    with _lock:
        if not _tried:
            if os.environ.get("CCT_NO_NATIVE", "") not in ("", "0"):
                _lib = None
            else:
                _lib = _build_and_load()
            _tried = True
    return _lib


def available() -> bool:
    return _get() is not None


def _as_u32_ptr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))


def _as_u64_ptr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def inflate_blocks(
    src: bytes,
    src_off: np.ndarray,
    comp_len: np.ndarray,
    isize: np.ndarray,
    crc: np.ndarray,
    n_threads: int = 0,
) -> bytes:
    """Inflate a batch of raw-deflate spans of ``src`` (CRC/ISIZE-checked).

    The four metadata arrays come from the Python-side framing scan
    (``io.bgzf.scan_block_metas``).  Returns the concatenated payloads as a
    memoryview (zero-copy over the inflate buffer — callers slice/join it).
    Raises ValueError if any block fails validation.
    """
    lib = _get()
    if lib is None:
        raise RuntimeError("native BGZF codec unavailable")
    n = len(src_off)
    out_off = np.zeros(n, dtype=np.uint64)
    if n > 1:
        np.cumsum(isize[:-1].astype(np.uint64), out=out_off[1:])
    total = int(isize.sum(dtype=np.uint64))
    # np.empty (no zero-fill) + one tobytes copy: ctypes.create_string_buffer
    # memsets and its .raw is pathologically slow at tens of MB.
    out = np.empty(max(total, 1), dtype=np.uint8)
    rc = lib.cct_inflate_blocks(
        src,
        _as_u64_ptr(np.ascontiguousarray(src_off, dtype=np.uint64)),
        _as_u32_ptr(np.ascontiguousarray(comp_len, dtype=np.uint32)),
        _as_u32_ptr(np.ascontiguousarray(isize, dtype=np.uint32)),
        _as_u32_ptr(np.ascontiguousarray(crc, dtype=np.uint32)),
        n,
        out.ctypes.data_as(ctypes.c_char_p),
        _as_u64_ptr(out_off),
        int(n_threads),
    )
    if rc != 0:
        raise ValueError(f"BGZF native inflate failed at block {rc - 1} (bad stream or CRC)")
    return out[:total].data


def _i64_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def copy_runs(
    src: np.ndarray,
    src_starts: np.ndarray,
    dst: np.ndarray,
    dst_starts: np.ndarray,
    lens: np.ndarray,
) -> None:
    """``dst[dst_starts[i]:+lens[i]] = src[src_starts[i]:+lens[i]]`` via the
    native memcpy loop.  ``src``/``dst`` are 1-D C-contiguous arrays of the
    same itemsize; offsets/lengths are in ELEMENTS (scaled to bytes here).
    Bounds are validated before the call — the C side trusts its inputs.
    """
    lib = _get()
    if lib is None:
        raise RuntimeError("native codec unavailable")
    n = len(lens)
    if n == 0:
        return
    item = src.dtype.itemsize
    if dst.dtype.itemsize != item or not src.flags.c_contiguous or not dst.flags.c_contiguous:
        raise ValueError("copy_runs needs C-contiguous arrays of equal itemsize")
    ss = np.ascontiguousarray(src_starts, dtype=np.int64)
    ds = np.ascontiguousarray(dst_starts, dtype=np.int64)
    ll = np.ascontiguousarray(lens, dtype=np.int64)
    if len(ss) != n or len(ds) != n:
        raise ValueError("copy_runs: starts/lens length mismatch")
    if ll.min(initial=0) < 0:
        raise ValueError("copy_runs: negative length")
    if n and (
        int((ss + ll).max()) > src.size or int((ds + ll).max()) > dst.size
        or int(ss.min()) < 0 or int(ds.min()) < 0
    ):
        raise ValueError("copy_runs: run out of bounds")
    if item != 1:
        ss, ds, ll = ss * item, ds * item, ll * item
    lib.cct_copy_runs(
        src.ctypes.data_as(ctypes.c_char_p), _i64_ptr(ss),
        dst.ctypes.data_as(ctypes.c_char_p), _i64_ptr(ds),
        _i64_ptr(ll), n,
    )


def byte_counts(data: np.ndarray) -> np.ndarray:
    """256-bin histogram of a uint8 array (one native pass; the np.unique
    replacement for wire-batch codebook discovery)."""
    lib = _get()
    if lib is None:
        raise RuntimeError("native codec unavailable")
    data = np.ascontiguousarray(data.reshape(-1), dtype=np.uint8)
    counts = np.zeros(256, dtype=np.int64)
    lib.cct_byte_counts(data.ctypes.data_as(ctypes.c_char_p), data.size, _i64_ptr(counts))
    return counts


def scan_bam_records(chunk, limit: int) -> np.ndarray:
    """Record boundary offsets (n+1 entries) of length-prefixed BAM records
    in ``chunk[:limit]`` — native replacement for the per-record
    struct.unpack loop.  Raises ValueError on a corrupt block_size."""
    lib = _get()
    if lib is None:
        raise RuntimeError("native codec unavailable")
    cap = limit // 36 + 2
    out = np.zeros(cap, dtype=np.int64)
    if isinstance(chunk, np.ndarray):
        chunk = np.ascontiguousarray(chunk, dtype=np.uint8)
        src = chunk.ctypes.data_as(ctypes.c_char_p)
    else:
        src = bytes(chunk) if not isinstance(chunk, bytes) else chunk
    n = lib.cct_scan_bam_records(src, int(limit), _i64_ptr(out), cap)
    if n < 0:
        raise ValueError("corrupt BAM record: block_size < 32")
    return out[: n + 1]


def expand_nibbles(src: np.ndarray, lut2: np.ndarray) -> np.ndarray:
    """Expand each byte of ``src`` into two bytes via a ``(256, 2)`` LUT
    (the BAM seq nibble decode).  Returns a ``(2 * len(src),)`` array."""
    lib = _get()
    if lib is None:
        raise RuntimeError("native codec unavailable")
    src = np.ascontiguousarray(src, dtype=np.uint8)
    lut2 = np.ascontiguousarray(lut2, dtype=np.uint8)
    if lut2.size != 512:
        raise ValueError("lut2 must be (256, 2) bytes")
    out = np.empty(2 * src.size, dtype=np.uint8)
    lib.cct_expand_nibbles(
        src.ctypes.data_as(ctypes.c_char_p), src.size,
        lut2.ctypes.data_as(ctypes.c_char_p), out.ctypes.data_as(ctypes.c_char_p),
    )
    return out


def gather_fixed(src: np.ndarray, off: np.ndarray, width: int) -> np.ndarray:
    """``(n, width)`` byte gather at arbitrary offsets (bounds-checked)."""
    lib = _get()
    if lib is None:
        raise RuntimeError("native codec unavailable")
    src = np.ascontiguousarray(src, dtype=np.uint8)
    off = np.ascontiguousarray(off, dtype=np.int64)
    n = off.size
    if n and (int(off.min()) < 0 or int(off.max()) + width > src.size):
        raise ValueError("gather_fixed: offset out of bounds")
    out = np.empty(n * width, dtype=np.uint8)
    lib.cct_gather_fixed(
        src.ctypes.data_as(ctypes.c_char_p), _i64_ptr(off), n, int(width),
        out.ctypes.data_as(ctypes.c_char_p),
    )
    return out.reshape(n, width)


def pack_wire(bases: np.ndarray, quals: np.ndarray, lut: np.ndarray, four_bit: bool) -> np.ndarray:
    """Fused base+qual-index wire pack over flattened last axis.

    ``bases``/``quals``: same-shape uint8 arrays; ``lut``: 256-entry
    qual->codebook-index table (255 = absent).  Returns the packed array
    shaped like the input but with the last axis ``ceil(L/2)`` (4-bit mode)
    or ``L`` (8-bit mode).  Raises ValueError on the same bad inputs as the
    numpy path (base out of bit budget / qual not in codebook) — though
    when a batch contains BOTH defects, which one is reported may differ
    (numpy checks all bases first; the native scan is element-wise).
    """
    lib = _get()
    if lib is None:
        raise RuntimeError("native codec unavailable")
    if bases.shape != quals.shape:
        raise ValueError("bases/quals shape mismatch")
    L = bases.shape[-1]
    b = np.ascontiguousarray(bases.reshape(-1), dtype=np.uint8)
    q = np.ascontiguousarray(quals.reshape(-1), dtype=np.uint8)
    lu = np.ascontiguousarray(lut, dtype=np.uint8)
    if lu.size != 256:
        raise ValueError("lut must have 256 entries")
    n = b.size
    if four_bit:
        if L % 2:
            # pad each row's odd tail with a ZERO nibble (base 0, qual
            # index 0 — byte-identical to pack4's concat-a-zero-nibble).
            # The pad qual must hit LUT index 0 even when the codebook is
            # duplicate-padded (a real qual can map to a later duplicate
            # slot), so route it through a spare byte value pinned to 0.
            rows = b.reshape(-1, L)
            qrows = q.reshape(-1, L)
            nr = rows.shape[0]
            pb = np.zeros((nr, L + 1), np.uint8)
            pq = np.zeros((nr, L + 1), np.uint8)
            pb[:, :L] = rows
            pq[:, :L] = qrows
            # The spare byte must not occur in the data: doctoring lut[v]=0
            # for a value the data contains would silently pack an
            # out-of-codebook qual instead of raising like the numpy path.
            present = byte_counts(q) > 0
            spare = np.nonzero((lu == 255) & ~present)[0]
            if not spare.size:
                # every absent-from-codebook byte occurs in the data ->
                # the data necessarily holds an invalid qual
                raise ValueError("quals not in codebook")
            lu = lu.copy()
            lu[spare[0]] = 0
            pq[:, L] = spare[0]
            pb = pb.reshape(-1)
            pq = pq.reshape(-1)
            out = np.empty(pb.size // 2, np.uint8)
            rc = lib.cct_pack4(
                pb.ctypes.data_as(ctypes.c_char_p), pq.ctypes.data_as(ctypes.c_char_p),
                lu.ctypes.data_as(ctypes.c_char_p), pb.size,
                out.ctypes.data_as(ctypes.c_char_p),
            )
        else:
            out = np.empty((n + 1) // 2, np.uint8)
            rc = lib.cct_pack4(
                b.ctypes.data_as(ctypes.c_char_p), q.ctypes.data_as(ctypes.c_char_p),
                lu.ctypes.data_as(ctypes.c_char_p), n,
                out.ctypes.data_as(ctypes.c_char_p),
            )
        out_l = (L + 1) // 2
    else:
        out = np.empty(n, np.uint8)
        rc = lib.cct_pack8(
            b.ctypes.data_as(ctypes.c_char_p), q.ctypes.data_as(ctypes.c_char_p),
            lu.ctypes.data_as(ctypes.c_char_p), n,
            out.ctypes.data_as(ctypes.c_char_p),
        )
        out_l = L
    if rc == 1:
        raise ValueError("base codes exceed the wire bit budget")
    if rc == 2:
        raise ValueError("quals not in codebook")
    return out.reshape(bases.shape[:-1] + (out_l,))


def fill_runs_native(dst: np.ndarray, starts: np.ndarray, lens: np.ndarray, value: int) -> None:
    """Byte-fill runs of a 1-D contiguous uint8 array with ``value``."""
    lib = _get()
    if lib is None:
        raise RuntimeError("native codec unavailable")
    n = len(lens)
    if n == 0:
        return
    if dst.dtype.itemsize != 1 or not dst.flags.c_contiguous:
        raise ValueError("fill_runs_native needs a contiguous 1-byte-item array")
    ss = np.ascontiguousarray(starts, dtype=np.int64)
    ll = np.ascontiguousarray(lens, dtype=np.int64)
    if ll.min(initial=0) < 0 or (n and (int((ss + ll).max()) > dst.size or int(ss.min()) < 0)):
        raise ValueError("fill_runs_native: run out of bounds")
    if not 0 <= int(value) <= 255:  # numpy fallback raises OverflowError too
        raise OverflowError(f"fill value {value} out of bounds for a byte fill")
    lib.cct_fill_runs(
        dst.ctypes.data_as(ctypes.c_char_p), _i64_ptr(ss), _i64_ptr(ll), n, int(value)
    )


def equal_range_windowed(arr: np.ndarray, keys: np.ndarray,
                         lo0: np.ndarray, hi0: np.ndarray,
                         n_threads: int = 1) -> tuple[np.ndarray, np.ndarray]:
    """Per-key equal-range over sorted int64 ``arr``, each key searched only
    within its ``[lo0, hi0)`` window (the aligner's prefix-table bounds).
    Returns ``(lo, hi)`` int64 arrays.  Raises RuntimeError when the native
    library is unavailable — callers keep their vectorized numpy fallback.
    """
    lib = _get()
    if lib is None:
        raise RuntimeError("native codec unavailable")
    a = np.ascontiguousarray(arr, dtype=np.int64)
    k = np.ascontiguousarray(keys, dtype=np.int64)
    lo = np.ascontiguousarray(lo0, dtype=np.int64)
    hi = np.ascontiguousarray(hi0, dtype=np.int64)
    m = len(k)
    out_lo = np.empty(m, np.int64)
    out_hi = np.empty(m, np.int64)
    if m:
        if len(lo) != m or len(hi) != m:
            raise ValueError("equal_range_windowed: window arrays mismatch keys")
        if int(hi.max(initial=0)) > len(a) or int(lo.min(initial=0)) < 0:
            raise ValueError("equal_range_windowed: window out of bounds")
        lib.cct_equal_range_i64(
            _i64_ptr(a), _i64_ptr(k), _i64_ptr(lo), _i64_ptr(hi), m,
            _i64_ptr(out_lo), _i64_ptr(out_hi), int(n_threads))
    return out_lo, out_hi


def deflate_payload_sizes(data: bytes, level: int = 6,
                          n_threads: int = 0) -> tuple[bytes, list[int]]:
    """Compress ``data`` into complete framed BGZF blocks (no EOF marker);
    also return each block's compressed byte length in order (the inline
    BAI builder derives virtual offsets from these)."""
    lib = _get()
    if lib is None:
        raise RuntimeError("native BGZF codec unavailable")
    if not data:
        return b"", []
    stride = int(lib.cct_out_stride())
    from consensuscruncher_tpu.io.bgzf import MAX_BLOCK_PAYLOAD

    n_blocks = (len(data) + MAX_BLOCK_PAYLOAD - 1) // MAX_BLOCK_PAYLOAD
    out = np.empty(n_blocks * stride, dtype=np.uint8)
    sizes = np.zeros(n_blocks, dtype=np.uint32)
    rc = lib.cct_deflate_blocks(
        data, len(data), int(level), int(n_threads),
        out.ctypes.data_as(ctypes.c_char_p), _as_u32_ptr(sizes),
    )
    if rc != 0:
        raise ValueError(f"BGZF native deflate failed at block {rc - 1}")
    mv = memoryview(out)
    szs = [int(s) for s in sizes]
    return b"".join(mv[i * stride : i * stride + szs[i]] for i in range(n_blocks)), szs


def deflate_payload(data: bytes, level: int = 6, n_threads: int = 0) -> bytes:
    """Compress ``data`` into complete framed BGZF blocks (no EOF marker)."""
    return deflate_payload_sizes(data, level, n_threads)[0]
