"""ctypes loader for the native C++ BGZF codec (``bgzf_native.cpp``).

The shared library is compiled lazily with ``g++`` the first time it's
needed and cached next to the source; a content hash in the cache name
means editing the .cpp (or bumping the ABI) transparently rebuilds.  Every
entry point degrades to the pure-Python codec in ``io/bgzf.py`` when the
toolchain is missing or ``CCT_NO_NATIVE=1`` is set — the native layer is a
throughput optimization, never a correctness dependency.

Public surface:
- ``available()`` — is the native codec usable?
- ``inflate_blocks(src, src_off, comp_len, isize, crc)`` — batch raw-inflate
  with CRC/ISIZE checks (metadata arrays from ``io.bgzf.scan_block_metas``)
- ``deflate_payload(data, level)`` — payload bytes -> framed BGZF blocks
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading

import numpy as np

_ABI_VERSION = 3
_SRC = os.path.join(os.path.dirname(__file__), "bgzf_native.cpp")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def _build_and_load() -> ctypes.CDLL | None:
    with open(_SRC, "rb") as fh:
        digest = hashlib.sha256(fh.read()).hexdigest()[:16]
    cache_dir = os.environ.get("CCT_NATIVE_CACHE", os.path.dirname(_SRC))
    so_path = os.path.join(cache_dir, f"bgzf_native-{digest}.so")
    if not os.path.exists(so_path):
        # Everything filesystem/toolchain-shaped is guarded: an unwritable
        # cache dir or missing g++ must degrade to the pure-Python codec,
        # never crash the open (the module's "optional, not a dependency"
        # contract).
        tmp = None
        try:
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=cache_dir)
            os.close(fd)
            cmd = ["g++", "-O3", "-shared", "-fPIC", "-pthread", _SRC, "-o", tmp, "-lz"]
            subprocess.run(cmd, check=True, capture_output=True, timeout=300)
            os.replace(tmp, so_path)  # atomic: concurrent builders race benignly
        except (OSError, subprocess.SubprocessError):
            if tmp is not None and os.path.exists(tmp):
                os.unlink(tmp)
            return None
    try:
        lib = ctypes.CDLL(so_path)
    except OSError:
        return None
    lib.cct_version.restype = ctypes.c_int
    if lib.cct_version() != _ABI_VERSION:
        return None
    lib.cct_out_stride.restype = ctypes.c_uint32
    lib.cct_inflate_blocks.restype = ctypes.c_int
    lib.cct_inflate_blocks.argtypes = [
        ctypes.c_char_p,                    # src
        ctypes.POINTER(ctypes.c_uint64),    # src_off
        ctypes.POINTER(ctypes.c_uint32),    # comp_len
        ctypes.POINTER(ctypes.c_uint32),    # isize
        ctypes.POINTER(ctypes.c_uint32),    # crc
        ctypes.c_int64,                     # n
        ctypes.c_char_p,                    # out
        ctypes.POINTER(ctypes.c_uint64),    # out_off
        ctypes.c_int32,                     # n_threads
    ]
    lib.cct_deflate_blocks.restype = ctypes.c_int
    lib.cct_deflate_blocks.argtypes = [
        ctypes.c_char_p,                    # payload
        ctypes.c_uint64,                    # payload_len
        ctypes.c_int32,                     # level
        ctypes.c_int32,                     # n_threads
        ctypes.c_char_p,                    # out
        ctypes.POINTER(ctypes.c_uint32),    # out_sizes
    ]
    return lib


def _get() -> ctypes.CDLL | None:
    global _lib, _tried
    if _tried:
        return _lib
    with _lock:
        if not _tried:
            if os.environ.get("CCT_NO_NATIVE", "") not in ("", "0"):
                _lib = None
            else:
                _lib = _build_and_load()
            _tried = True
    return _lib


def available() -> bool:
    return _get() is not None


def _as_u32_ptr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32))


def _as_u64_ptr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def inflate_blocks(
    src: bytes,
    src_off: np.ndarray,
    comp_len: np.ndarray,
    isize: np.ndarray,
    crc: np.ndarray,
    n_threads: int = 0,
) -> bytes:
    """Inflate a batch of raw-deflate spans of ``src`` (CRC/ISIZE-checked).

    The four metadata arrays come from the Python-side framing scan
    (``io.bgzf.scan_block_metas``).  Returns the concatenated payloads as a
    memoryview (zero-copy over the inflate buffer — callers slice/join it).
    Raises ValueError if any block fails validation.
    """
    lib = _get()
    if lib is None:
        raise RuntimeError("native BGZF codec unavailable")
    n = len(src_off)
    out_off = np.zeros(n, dtype=np.uint64)
    if n > 1:
        np.cumsum(isize[:-1].astype(np.uint64), out=out_off[1:])
    total = int(isize.sum(dtype=np.uint64))
    # np.empty (no zero-fill) + one tobytes copy: ctypes.create_string_buffer
    # memsets and its .raw is pathologically slow at tens of MB.
    out = np.empty(max(total, 1), dtype=np.uint8)
    rc = lib.cct_inflate_blocks(
        src,
        _as_u64_ptr(np.ascontiguousarray(src_off, dtype=np.uint64)),
        _as_u32_ptr(np.ascontiguousarray(comp_len, dtype=np.uint32)),
        _as_u32_ptr(np.ascontiguousarray(isize, dtype=np.uint32)),
        _as_u32_ptr(np.ascontiguousarray(crc, dtype=np.uint32)),
        n,
        out.ctypes.data_as(ctypes.c_char_p),
        _as_u64_ptr(out_off),
        int(n_threads),
    )
    if rc != 0:
        raise ValueError(f"BGZF native inflate failed at block {rc - 1} (bad stream or CRC)")
    return out[:total].data


def deflate_payload(data: bytes, level: int = 6, n_threads: int = 0) -> bytes:
    """Compress ``data`` into complete framed BGZF blocks (no EOF marker)."""
    lib = _get()
    if lib is None:
        raise RuntimeError("native BGZF codec unavailable")
    if not data:
        return b""
    stride = int(lib.cct_out_stride())
    from consensuscruncher_tpu.io.bgzf import MAX_BLOCK_PAYLOAD

    n_blocks = (len(data) + MAX_BLOCK_PAYLOAD - 1) // MAX_BLOCK_PAYLOAD
    out = np.empty(n_blocks * stride, dtype=np.uint8)
    sizes = np.zeros(n_blocks, dtype=np.uint32)
    rc = lib.cct_deflate_blocks(
        data, len(data), int(level), int(n_threads),
        out.ctypes.data_as(ctypes.c_char_p), _as_u32_ptr(sizes),
    )
    if rc != 0:
        raise ValueError(f"BGZF native deflate failed at block {rc - 1}")
    mv = memoryview(out)
    return b"".join(mv[i * stride : i * stride + int(sizes[i])] for i in range(n_blocks))
