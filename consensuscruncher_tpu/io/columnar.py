"""Columnar BAM decode + sort: the host-side Amdahl fix (SURVEY.md §7 #3).

The per-record object path (``BamReader`` -> ``BamRead`` dataclasses) costs
~12 us/record in pure-Python struct work, which dominates the whole pipeline
once the consensus vote runs on an accelerator (measured: the XLA vote is
~2% of SSCS stage wall-clock; decode+group+sort are ~80%).  This module is
the TPU-first answer on the host side: decode a whole batch of records into
**columns** (numpy arrays) with a single serial offset scan plus vectorized
gathers, so per-record Python work disappears from the hot path.

Layout per batch (record fields per SAM spec §4.2):

- fixed-width columns: ``ref_id pos flag mapq mate_ref_id mate_pos tlen
  l_seq n_cigar l_qname`` — one numpy array each, shape ``(n,)``.
- ragged payloads are *views into the undecoded buffer* described by
  ``(start, length)`` column pairs; materialized on demand via
  :func:`ragged_gather` (qnames, cigars, tags) or the nibble-expanding
  :func:`seq_codes` (sequence -> pipeline base codes A=0..N=4).
- ``raw`` record blobs (length-prefixed, byte-exact) remain addressable via
  ``rec_off`` for passthrough writes — a coordinate sort is then a pure
  byte shuffle (lexsort + gather), never a decode/re-encode round trip.

Parity: every field agrees bit-for-bit with ``BamReader`` (tests/
test_columnar.py proves it record-by-record), and :func:`sort_bam_columnar`
reproduces ``io.bam.sort_bam``'s exact total order — the same
``(ref_id, pos, qname, flag)`` key, stable for equal keys (np.lexsort and
Python sort are both stable).
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass
from functools import cached_property
from typing import NamedTuple

import numpy as np

from consensuscruncher_tpu.io import bgzf
from consensuscruncher_tpu.io.bam import (
    BAM_MAGIC,
    BamHeader,
    CIGAR_OPS,
    SEQ_NIBBLES,
    decode_record,
    read_bam_header,
)
from consensuscruncher_tpu.utils.manifest import commit_file
from consensuscruncher_tpu.utils.phred import N as CODE_N, encode_seq
from consensuscruncher_tpu.utils.ragged import gather_runs

# nibble (0-15, spec '=ACMGRSVTWYHKDBN') -> pipeline base code (A=0..N=4);
# every ambiguity code collapses to N exactly like decode->encode_seq does.
NIB2CODE = encode_seq(SEQ_NIBBLES)
# byte -> its two nibbles' codes (high nibble first), for paired expansion
NIB2CODE_PAIR = np.stack(
    [NIB2CODE[np.arange(256) >> 4], NIB2CODE[np.arange(256) & 0xF]], axis=1
).astype(np.uint8)


def _qname_key_matrix(buf: np.ndarray, starts: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """``(n, W)`` zero-padded qname byte matrix scattered straight from the
    record buffer, with ``W`` rounded up to a multiple of 8 so
    :func:`coord_sort_perm`'s big-endian uint64 key view is zero-copy.
    Shared by ``ColumnarBatch.qname_matrix`` and ``SortingBamWriter``."""
    from consensuscruncher_tpu.utils.ragged import scatter_runs

    n = len(starts)
    w = int(lens.max()) if n else 0
    w8 = -(-w // 8) * 8
    out = np.zeros((n, w8), dtype=np.uint8)
    if w:
        scatter_runs(out.reshape(-1), np.arange(n, dtype=np.int64) * w8,
                     buf, lens, src_starts=starts)
    return out


def _gather_view(buf: np.ndarray, off: np.ndarray, width: int, dtype: str) -> np.ndarray:
    """Vectorized unaligned little-endian field gather at ``off`` (n,)."""
    from consensuscruncher_tpu.io import native

    if native.available():
        return native.gather_fixed(buf, off, width).view(dtype).ravel()
    raw = buf[off[:, None] + np.arange(width, dtype=np.int64)]
    return np.ascontiguousarray(raw).view(dtype).ravel()


def ragged_gather(buf: np.ndarray, starts: np.ndarray, lengths: np.ndarray):
    """Gather ``n`` variable-length byte runs into one packed array — the
    shared :func:`utils.ragged.gather_runs` under its historical name."""
    return gather_runs(buf, starts, lengths)


@dataclass
class ColumnarBatch:
    """One decoded batch; all arrays share the record axis ``(n,)``."""

    header: BamHeader
    buf: np.ndarray  # uint8: the uncompressed bytes these records live in
    rec_off: np.ndarray  # (n+1,) int64 record starts (at the length prefix)
    ref_id: np.ndarray
    pos: np.ndarray
    flag: np.ndarray
    mapq: np.ndarray
    mate_ref_id: np.ndarray
    mate_pos: np.ndarray
    tlen: np.ndarray
    l_seq: np.ndarray
    n_cigar: np.ndarray
    l_qname: np.ndarray  # includes the trailing NUL

    @property
    def n(self) -> int:
        return len(self.rec_off) - 1

    # ---- derived ragged payload geometry (all (n,) int64) ----

    @cached_property
    def qname_start(self) -> np.ndarray:
        return self.rec_off[:-1] + 36

    @cached_property
    def cigar_start(self) -> np.ndarray:
        return self.qname_start + self.l_qname

    @cached_property
    def seq_start(self) -> np.ndarray:
        return self.cigar_start + 4 * self.n_cigar.astype(np.int64)

    @cached_property
    def qual_start(self) -> np.ndarray:
        return self.seq_start + (self.l_seq.astype(np.int64) + 1) // 2

    @cached_property
    def tags_start(self) -> np.ndarray:
        return self.qual_start + self.l_seq

    # ---- materialized payloads ----

    @cached_property
    def qnames(self):
        """``(data, offsets)`` of qname bytes (no trailing NUL)."""
        return ragged_gather(self.buf, self.qname_start, self.l_qname - 1)

    @cached_property
    def qname_matrix(self) -> np.ndarray:
        """``(n, W)`` uint8, zero-padded past the batch's longest qname to a
        multiple of 8 — the vectorized-lexicographic form (NUL pads sort
        before any ascii byte, exactly like Python's shorter-string-first
        comparison; the 8-alignment makes the sort-key uint64 view free)."""
        return _qname_key_matrix(self.buf, self.qname_start, self.l_qname - 1)

    @cached_property
    def _seq_codes_cache(self):
        return self._seq_codes_impl()

    def seq_codes(self):
        """``(codes, offsets)``: 4-bit seq fields nibble-expanded straight to
        pipeline base codes (A=0..N=4) — no string round trip.  Cached: the
        block producer touches a batch from several sources."""
        return self._seq_codes_cache

    def _seq_codes_impl(self):
        l = self.l_seq.astype(np.int64)
        off = np.zeros(self.n + 1, dtype=np.int64)
        np.cumsum(l, out=off[1:])
        total = int(off[-1])
        if total == 0:
            return np.empty(0, dtype=np.uint8), off
        # Fast path (reads overwhelmingly have even lengths): gather each
        # record's seq BYTES once and expand byte -> two codes via a (256, 2)
        # LUT — half the index math of per-nibble gathering.  A pad nibble
        # from an odd-length read would misalign everything after it, so any
        # odd length falls back to the per-nibble form.
        if not (l & 1).any():
            data, _ = ragged_gather(self.buf, self.seq_start, l >> 1)
            from consensuscruncher_tpu.io import native

            if native.available():
                return native.expand_nibbles(data, NIB2CODE_PAIR), off
            return NIB2CODE_PAIR[data].reshape(-1), off
        rel = np.arange(total, dtype=np.int64) - np.repeat(off[:-1], l)
        byte_idx = np.repeat(self.seq_start, l) + rel // 2
        b = self.buf[byte_idx]
        nib = np.where(rel % 2 == 0, b >> 4, b & 0xF)
        return NIB2CODE[nib], off

    @cached_property
    def _quals_cache(self):
        return self._quals_impl()

    def quals(self):
        """``(quals, offsets)``; a read whose FIRST qual byte is the spec's
        0xFF missing marker decodes as all-zero — exactly ``decode_record``'s
        whole-read-missing rule (a stray mid-read 0xFF stays 255, so the
        columnar and object paths can never diverge on malformed input).
        Cached, like :meth:`seq_codes`."""
        return self._quals_cache

    def _quals_impl(self):
        data, off = ragged_gather(self.buf, self.qual_start, self.l_seq)
        l = self.l_seq.astype(np.int64)
        nonempty = l > 0
        first = np.zeros(self.n, dtype=np.uint8)
        first[nonempty] = self.buf[self.qual_start[nonempty]]
        missing = np.repeat(nonempty & (first == 0xFF), l)
        return np.where(missing, 0, data).astype(np.uint8), off

    def cigar_string(self, i: int) -> str:
        """Cigar of record ``i`` as text ('*' when empty)."""
        nc = int(self.n_cigar[i])
        if nc == 0:
            return "*"
        start = int(self.cigar_start[i])
        words = (
            np.ascontiguousarray(self.buf[start : start + 4 * nc]).view("<u4")
        )
        return "".join(f"{int(w) >> 4}{CIGAR_OPS[int(w) & 0xF]}" for w in words)

    def record_blob(self, i: int) -> bytes:
        """Byte-exact record ``i`` including the length prefix."""
        return self.buf[self.rec_off[i] : self.rec_off[i + 1]].tobytes()

    def materialize(self, i: int):
        """Full ``BamRead`` for record ``i`` (slow path: bad reads,
        singletons — anything that needs the object surface)."""
        body = self.buf[self.rec_off[i] + 4 : self.rec_off[i + 1]].tobytes()
        return decode_record(body, self.header)


def _scan_offsets(chunk: bytes, limit: int) -> np.ndarray:
    """Record boundaries in ``chunk[:limit]`` — the single serial pass
    (native C loop when the codec library is available)."""
    from consensuscruncher_tpu.io import native

    if native.available():
        return native.scan_bam_records(chunk, limit)
    offs = [0]
    o = 0
    unpack_from = struct.unpack_from
    while o + 4 <= limit:
        (bs,) = unpack_from("<i", chunk, o)
        if bs < 32:
            raise ValueError(f"corrupt BAM record: block_size {bs} at offset {o}")
        if o + 4 + bs > limit:
            break
        o += 4 + bs
        offs.append(o)
    return np.asarray(offs, dtype=np.int64)


def _make_batch(header: BamHeader, buf: np.ndarray, rec_off: np.ndarray) -> ColumnarBatch:
    off = rec_off[:-1]
    return ColumnarBatch(
        header=header,
        buf=buf,
        rec_off=rec_off,
        ref_id=_gather_view(buf, off + 4, 4, "<i4"),
        pos=_gather_view(buf, off + 8, 4, "<i4"),
        l_qname=buf[off + 12].astype(np.int64),
        mapq=buf[off + 13].copy(),
        n_cigar=_gather_view(buf, off + 16, 2, "<u2").astype(np.int32),
        flag=_gather_view(buf, off + 18, 2, "<u2").astype(np.int32),
        l_seq=_gather_view(buf, off + 20, 4, "<i4"),
        mate_ref_id=_gather_view(buf, off + 24, 4, "<i4"),
        mate_pos=_gather_view(buf, off + 28, 4, "<i4"),
        tlen=_gather_view(buf, off + 32, 4, "<i4"),
    )


# Packed (rid, pos) ordering key for coordinate-sorted BAMs.  Unplaced
# records (rid < 0) sort last and all share the sentinel, so a range
# boundary can never split the unplaced tail.
UNPLACED_KEY = np.int64(1) << 62


def pack_coord_key(rid: int, pos: int) -> int:
    """Scalar (rid, pos) -> int64 ordering key (rid < 0 -> UNPLACED_KEY).
    pos clamps at 0: a placed-but-POS-less record (rid >= 0, pos == -1, the
    SAM-legal unmapped-with-RNAME shape) must not key below its rid."""
    return int(UNPLACED_KEY) if rid < 0 else ((int(rid) << 32) | max(int(pos), 0))


def pack_coord_keys(rid: np.ndarray, pos: np.ndarray) -> np.ndarray:
    rid64 = rid.astype(np.int64)
    return np.where(rid64 < 0, UNPLACED_KEY,
                    (rid64 << 32) | np.maximum(pos.astype(np.int64), 0))


class BamRange(NamedTuple):
    """Half-open coordinate range of a sorted BAM for direct index reads.

    ``start_voffset`` is a BAI virtual offset at or before the first record
    with key >= ``start_key`` (records before it are skipped); reading
    stops at the first record with key >= ``end_key`` (None = EOF,
    including the unplaced tail).  Used by ``--host_workers`` to read
    worker ranges straight out of the shared input (VERDICT r3 item 4 —
    no materialized slice files).
    """

    start_voffset: int
    start_key: int
    end_key: int | None


def _slice_batch(header, batch, i: int, j: int):
    off = batch.rec_off
    lo, hi = int(off[i]), int(off[j])
    return _make_batch(header, batch.buf[lo:hi], off[i:j + 1] - off[i])


class ColumnarReader:
    """Streaming columnar BAM reader: ``for batch in reader.batches(): ...``

    ``batch_bytes`` bounds memory (uncompressed bytes per batch); records
    never split across batches.

    ``bam_range``: read only a :class:`BamRange` of a coordinate-sorted,
    path-addressed BAM — the header is decoded from the file start, then
    the stream re-opens at the range's virtual offset and batches are
    trimmed to the key range.
    """

    def __init__(self, path, batch_bytes: int = 64 << 20,
                 bam_range: BamRange | None = None):
        self._bgzf = bgzf.BgzfReader(path)
        self._batch_bytes = batch_bytes
        self.header = read_bam_header(self._bgzf)
        self._carry = b""
        self._range = bam_range
        self._start_pending = bam_range is not None
        if bam_range is not None and bam_range.start_voffset:
            # voffset 0 means "from the first record": the sequential
            # reader is already positioned right after the header.
            if not isinstance(path, (str, bytes, os.PathLike)):
                raise ValueError("bam_range requires a path-addressed BAM")
            self._bgzf.close()
            self._bgzf = bgzf.BgzfReader(path, start_voffset=bam_range.start_voffset)

    def batches(self):
        while True:
            chunk = self._carry + self._bgzf.read(self._batch_bytes)
            if not chunk:
                return
            offs = _scan_offsets(chunk, len(chunk))
            end = int(offs[-1])
            if end == 0:
                # no complete record in the window: either a giant record
                # (grow the read) or EOF mid-record (truncation)
                more = self._bgzf.read(self._batch_bytes)
                if not more:
                    raise ValueError("truncated BAM record at end of file")
                self._carry = chunk + more
                continue
            self._carry = chunk[end:]
            buf = np.frombuffer(chunk, dtype=np.uint8, count=end)
            batch = _make_batch(self.header, buf, offs)
            if self._range is not None:
                batch, done = self._trim(batch)
                if batch is not None and batch.n:
                    yield batch
                if done:
                    return
                continue
            yield batch

    def _trim(self, batch):
        """Apply the range's start/end key bounds to one batch.  Returns
        ``(trimmed_batch_or_None, done)``."""
        keys = pack_coord_keys(batch.ref_id, batch.pos)
        i = 0
        if self._start_pending:
            # keys ascend in a coordinate-sorted file; skip the prefix the
            # linear-index voffset conservatively included
            i = int(np.searchsorted(keys, self._range.start_key))
            if i < batch.n:
                self._start_pending = False
        if self._range.end_key is not None:
            j = int(np.searchsorted(keys, self._range.end_key))
            if j < batch.n:
                if j <= i:
                    return None, True
                return _slice_batch(self.header, batch, i, j), True
        if i >= batch.n:
            return None, False
        if i:
            return _slice_batch(self.header, batch, i, batch.n), False
        return batch, False

    def close(self) -> None:
        self._bgzf.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ------------------------------------------------------------------ sort

def sort_bam_columnar(
    in_path,
    out_path,
    level: int = 6,
    max_records: int = 2_000_000,
    max_raw_bytes: int = 768 << 20,
) -> bool:
    """In-memory coordinate sort as a pure byte shuffle.

    Same total order as ``io.bam.sort_bam`` — key ``(ref_id_or_last, pos,
    qname, flag)``, stable — but the records are never decoded: lexsort the
    key columns, then gather the raw length-prefixed blobs in permuted
    order and stream them through BGZF.  Returns ``True`` on success,
    ``False`` when the input exceeds the in-memory bounds (record count or
    UNCOMPRESSED bytes — compressed size is no proxy: low-complexity reads
    BGZF-compress 10-30x), in which case the caller falls back to the
    bounded spill/merge object sort.
    """
    from consensuscruncher_tpu.io.bam import _sorted_header

    reader = ColumnarReader(in_path, batch_bytes=64 << 20)
    batches = []
    n_total = 0
    raw_total = 0
    try:
        header = reader.header
        for b in reader.batches():
            batches.append(b)
            n_total += b.n
            raw_total += len(b.buf)
            if n_total > max_records or raw_total > max_raw_bytes:
                return False  # let the spill/merge path handle it
    finally:
        reader.close()

    # key columns across batches
    if n_total:
        rid = np.concatenate([b.ref_id for b in batches])
        pos = np.concatenate([b.pos for b in batches])
        flag = np.concatenate([b.flag for b in batches])
        w = max(b.qname_matrix.shape[1] for b in batches)
        qm = np.zeros((n_total, w), dtype=np.uint8)
        row = 0
        for b in batches:
            m = b.qname_matrix
            qm[row : row + b.n, : m.shape[1]] = m
            row += b.n
        perm = coord_sort_perm(rid, pos, qm, flag)
    else:
        perm = np.empty(0, dtype=np.int64)

    if n_total:
        starts = np.concatenate([b.rec_off[:-1] for b in batches])
        lengths = np.concatenate([np.diff(b.rec_off) for b in batches])
        # per-batch buffers -> one global buffer for the gather
        if len(batches) == 1:
            big = batches[0].buf
        else:
            base = np.zeros(len(batches), dtype=np.int64)
            sizes = [len(b.buf) for b in batches]
            base[1:] = np.cumsum(sizes[:-1])
            big = np.concatenate([b.buf for b in batches])
            rec_base = np.repeat(base, [b.n for b in batches])
            starts = starts + rec_base
        sp, lp = starts[perm], lengths[perm]
    else:
        big = np.empty(0, np.uint8)
        sp = lp = np.empty(0, np.int64)
    _write_bam_records(out_path, _sorted_header(header), big, sp, lp, level)
    return True


def coord_sort_perm(rid: np.ndarray, pos: np.ndarray, qname_matrix: np.ndarray,
                    flag: np.ndarray) -> np.ndarray:
    """THE samtools-parity coordinate total order, as a lexsort permutation:
    ``(ref_id with unmapped last, pos, qname bytes, flag)``, stable — the
    single columnar definition shared by ``sort_bam_columnar`` and
    ``SortingBamWriter`` (scalar twin: ``io.bam._coord_key``)."""
    rid = np.where(np.asarray(rid) < 0, 1 << 30, rid)
    n, w = qname_matrix.shape
    # Pack the zero-padded qname bytes into big-endian uint64 words: numeric
    # word order == lexicographic byte order, and the lexsort runs over
    # ~w/8 keys instead of w (measured 253s -> tens of seconds on a 25M-row
    # sort at qname width ~45).
    w8 = max(8, -(-w // 8) * 8)
    if w8 == w and qname_matrix.flags.c_contiguous:
        qp = qname_matrix
    else:
        qp = np.zeros((n, w8), dtype=np.uint8)
        qp[:, :w] = qname_matrix
    packed = qp.view(">u8")
    # significance (most -> least): rid, pos, qname bytes, flag;
    # np.lexsort's primary key is the LAST element.
    keys = [flag] + [packed[:, i] for i in range(packed.shape[1] - 1, -1, -1)] + [pos, rid]
    return np.lexsort(keys)


class _ChunkRecordStream:
    """Sequential record-blob fetcher over a coordinate-sorted chunk BAM.

    ``fetch(n)`` returns the next ``n`` records' raw length-prefixed bytes
    as ``(data, lengths)`` — batches decode lazily, so only a window of the
    chunk is ever resident.  Building block of the columnar k-way merge.
    """

    def __init__(self, path):
        self._reader = ColumnarReader(path)
        self._batches = self._reader.batches()
        self._cur: list[tuple[np.ndarray, np.ndarray, int]] = []  # buf, off, ptr

    def fetch(self, n: int) -> tuple[np.ndarray, np.ndarray]:
        pieces: list[np.ndarray] = []
        lens: list[np.ndarray] = []
        need = n
        while need:
            if not self._cur:
                b = next(self._batches)  # StopIteration = caller bug
                self._cur.append((b.buf, b.rec_off, 0))
            buf, off, ptr = self._cur[0]
            avail = len(off) - 1 - ptr
            take = min(avail, need)
            lo, hi = int(off[ptr]), int(off[ptr + take])
            pieces.append(buf[lo:hi])
            lens.append(np.diff(off[ptr : ptr + take + 1]))
            need -= take
            if take == avail:
                self._cur.pop(0)
            else:
                self._cur[0] = (buf, off, ptr + take)
        data = pieces[0] if len(pieces) == 1 else np.concatenate(pieces)
        lengths = lens[0] if len(lens) == 1 else np.concatenate(lens)
        return data, lengths

    def close(self) -> None:
        self._reader.close()


_MERGE_WRITE_BLOCK = 65536  # records interleaved per output write


def merge_sorted_columnar(paths: list, out_path, header: BamHeader,
                          level: int = 6, index: bool = True,
                          key_budget: int | None = None,
                          verify_sorted: bool = True) -> bool:
    """K-way merge of coordinate-sorted BAMs as a columnar byte shuffle.

    Replaces the object heap merge (BamReader -> BamRead -> heapq -> encode,
    measured ~6x slower end-to-end on 25M-record merges): load every
    input's KEY columns only (rid/pos/qname/flag + the BAI span columns),
    one stable global lexsort — np.lexsort over the concatenated keys
    reproduces the heap's earlier-input-wins tie order — then stream each
    input's raw record blobs sequentially and interleave them into the
    output in vectorized blocks.  Record bytes are never decoded; the
    ``.bai`` builds inline from the permuted span columns.

    Returns False (caller falls back to the heap merge) when the key
    columns would exceed ``key_budget`` bytes (default:
    :func:`_default_merge_key_budget` — independent of the record-buffer
    cap) — record bytes are streamed regardless, so the budget bounds only
    ~90 B/record of keys — or when ``verify_sorted`` finds an input whose
    physical order is not its full-key order (legal for samtools-sorted
    foreign BAMs with arbitrary coordinate-tie order; the interleave
    would corrupt such a file, the heap merge handles it).  Callers
    merging THIS framework's own outputs (full-key-sorted by
    construction) may pass ``verify_sorted=False`` to skip the check.
    """
    from consensuscruncher_tpu.io.bam import _sorted_header
    from consensuscruncher_tpu.utils.ragged import scatter_runs

    if key_budget is None:
        key_budget = _default_merge_key_budget()
    n_chunks = len(paths)
    rid_l, pos_l, flag_l, qm_l, lens_l = [], [], [], [], []
    end_l, mapped_l = [], []
    counts = np.zeros(n_chunks, dtype=np.int64)
    key_bytes = 0
    batch_bounds = [0]  # per-chunk [start, end) into the per-batch lists
    for ci, p in enumerate(paths):
        with ColumnarReader(p) as r:
            for b in r.batches():
                off = b.rec_off[:-1]
                rid_l.append(b.ref_id.astype(np.int64))
                pos_l.append(b.pos.astype(np.int64))
                flag_l.append(b.flag.astype(np.int64))
                qm_l.append(b.qname_matrix)
                lens_l.append(np.diff(b.rec_off))
                if index:
                    _rid, _pos, end, mapped = _record_spans_columnar(b.buf, off)
                    end_l.append(end)
                    mapped_l.append(mapped)
                counts[ci] += b.n
                key_bytes += b.n * 40 + b.qname_matrix.size + (9 * b.n if index else 0)
                if key_bytes > key_budget:
                    return False
        batch_bounds.append(len(rid_l))
    n_total = int(counts.sum())
    qw = max((m.shape[1] for m in qm_l), default=0)
    # Charge the REAL peak, not just the per-batch sum: the zero-padded
    # global qname matrix coexists with the per-batch pieces while filling,
    # and perm/src/out_lens/chunk_of add ~28 B/record.
    if key_bytes + n_total * (qw + 28) > key_budget:
        return False

    if verify_sorted and n_total:
        # The interleave assumes each input's PHYSICAL record order is its
        # full (rid, pos, qname, flag) key order — true for every BAM this
        # framework writes, but samtools guarantees only (rid, pos) order
        # with arbitrary tie order, and a tie-misordered foreign input
        # would get other records' lengths scattered over its blobs (a
        # corrupt BAM, not just a misordering).  Verify per input; any
        # violation -> decline, the record-decoding heap merge handles it.
        for ci in range(n_chunks):
            n_c = int(counts[ci])
            if n_c <= 1:
                continue
            i0, i1 = batch_bounds[ci], batch_bounds[ci + 1]
            rid_c = np.concatenate(rid_l[i0:i1])
            pos_c = np.concatenate(pos_l[i0:i1])
            flag_c = np.concatenate(flag_l[i0:i1])
            w_c = max(m.shape[1] for m in qm_l[i0:i1])
            qm_c = np.zeros((n_c, w_c), dtype=np.uint8)
            r = 0
            for m in qm_l[i0:i1]:
                qm_c[r : r + len(m), : m.shape[1]] = m
                r += len(m)
            if not np.array_equal(coord_sort_perm(rid_c, pos_c, qm_c, flag_c),
                                  np.arange(n_c)):
                return False

    tmp = os.fspath(out_path) + ".tmp"
    out_header = _sorted_header(header)
    writer = bgzf.BgzfWriter(tmp, level=level, collect_blocks=index)
    streams: list[_ChunkRecordStream] = []
    try:
        text = out_header.text.encode("ascii")
        head = bytearray(BAM_MAGIC)
        head += struct.pack("<i", len(text)) + text
        head += struct.pack("<i", len(out_header.refs))
        for name, length in out_header.refs:
            bname = name.encode("ascii") + b"\x00"
            head += struct.pack("<i", len(bname)) + bname + struct.pack("<i", length)
        writer.write(bytes(head))

        if n_total:
            rid = np.concatenate(rid_l)
            pos = np.concatenate(pos_l)
            flag = np.concatenate(flag_l)
            lengths = np.concatenate(lens_l)
            w = max(m.shape[1] for m in qm_l)
            qm = np.zeros((n_total, w), dtype=np.uint8)
            row = 0
            for m in qm_l:
                qm[row : row + len(m), : m.shape[1]] = m
                row += len(m)
            del qm_l
            perm = coord_sort_perm(rid, pos, qm, flag)
            del qm
            chunk_of = np.repeat(np.arange(n_chunks), counts).astype(np.int32)
            src = chunk_of[perm]
            out_lens = lengths[perm]

            streams = [_ChunkRecordStream(p) for p in paths]
            for i0 in range(0, n_total, _MERGE_WRITE_BLOCK):
                i1 = min(i0 + _MERGE_WRITE_BLOCK, n_total)
                src_b = src[i0:i1]
                lens_b = out_lens[i0:i1]
                starts_b = np.zeros(len(lens_b), dtype=np.int64)
                np.cumsum(lens_b[:-1], out=starts_b[1:])
                out_buf = np.empty(int(lens_b.sum()), dtype=np.uint8)
                for ci in range(n_chunks):
                    slots = np.nonzero(src_b == ci)[0]
                    if not slots.size:
                        continue
                    # slots appear in chunk-sequential order (the global
                    # sort preserves each sorted input's internal order)
                    data, dlens = streams[ci].fetch(len(slots))
                    scatter_runs(out_buf, starts_b[slots], data, dlens)
                writer.write(out_buf.tobytes())
        writer.close()
        commit_file(tmp, out_path)
    except BaseException:
        # cleanup must not mask the root cause: an async writer close()
        # re-raises its deferred worker error — suppress it here, the
        # original exception is the one that matters
        try:
            writer.close()
        except Exception:
            pass
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    finally:
        for s in streams:
            s.close()

    if index:
        from consensuscruncher_tpu.io.bai import write_bai_from_columns

        if n_total:
            end = np.concatenate(end_l)
            mapped = np.concatenate(mapped_l)
            rid_p, pos_p = rid[perm], pos[perm]
            end_p, mapped_p = end[perm], mapped[perm]
            ustart = len(head) + np.concatenate(
                [[0], np.cumsum(out_lens[:-1], dtype=np.int64)])
        else:
            rid_p = pos_p = end_p = ustart = np.zeros(0, np.int64)
            mapped_p = np.zeros(0, bool)
            out_lens = np.zeros(0, np.int64)
        write_bai_from_columns(
            os.fspath(out_path) + ".bai", len(out_header.refs),
            rid_p, pos_p, end_p, mapped_p, ustart, ustart + out_lens,
            writer.block_sizes,
        )
    return True


def _record_spans_columnar(big: np.ndarray, starts: np.ndarray):
    """(rid, pos, end, mapped) per record, vectorized (the columnar twin of
    ``io.bai._record_span``): end = pos + ref-consumed cigar length (min 1),
    pos + 1 for unmapped or cigar-less records."""
    off = starts
    rid = _gather_view(big, off + 4, 4, "<i4").astype(np.int64)
    pos = _gather_view(big, off + 8, 4, "<i4").astype(np.int64)
    flag = _gather_view(big, off + 18, 2, "<u2")
    n_cig = _gather_view(big, off + 16, 2, "<u2").astype(np.int64)
    l_qname = big[off + 12].astype(np.int64)
    mapped = (flag & 0x4) == 0
    end = pos + 1
    use = mapped & (n_cig > 0)
    if use.any():
        data, coff = ragged_gather(big, (off + 36 + l_qname)[use], 4 * n_cig[use])
        words = np.ascontiguousarray(data).view("<u4").astype(np.int64)
        ops = words & 0xF
        # ref-consuming ops: M, D, N, =, X  (0, 2, 3, 7, 8)
        contrib = np.where(
            (ops == 0) | (ops == 2) | (ops == 3) | (ops == 7) | (ops == 8),
            words >> 4, 0,
        )
        cs = np.concatenate([[0], np.cumsum(contrib)])
        wb = coff // 4
        ref_len = cs[wb[1:]] - cs[wb[:-1]]
        end[use] = pos[use] + np.maximum(ref_len, 1)
    return rid, pos, end, mapped


def _write_bam_records(out_path, header: BamHeader, big: np.ndarray,
                       starts: np.ndarray, lengths: np.ndarray, level: int,
                       index: bool = True) -> None:
    """Atomically write header + the records ``big[starts[i]:+lengths[i]]``
    (already in final order) as a BGZF BAM.

    With ``index=True`` (default) the ``.bai`` sidecar is built inline from
    the same in-memory columns and the writer's block layout — measured
    ~30% of full-pipeline wall used to go to ``index_bam``'s re-read +
    per-record Python scan of files this function had just written.
    """
    tmp = os.fspath(out_path) + ".tmp"
    writer = bgzf.BgzfWriter(tmp, level=level, collect_blocks=index)
    try:
        text = header.text.encode("ascii")
        out = bytearray(BAM_MAGIC)
        out += struct.pack("<i", len(text)) + text
        out += struct.pack("<i", len(header.refs))
        for name, length in header.refs:
            bname = name.encode("ascii") + b"\x00"
            out += struct.pack("<i", len(bname)) + bname + struct.pack("<i", length)
        writer.write(bytes(out))
        header_len = len(out)
        n_total = len(starts)
        if n_total:
            # Gather + write in bounded record chunks: ragged_gather builds
            # per-record index state, so one whole-file gather would
            # transiently need far more memory than the data itself.
            csum = np.cumsum(lengths)
            target = 8 << 20
            i0 = 0
            while i0 < n_total:
                floor = int(csum[i0 - 1]) if i0 else 0
                i1 = int(np.searchsorted(csum, floor + target)) + 1
                i1 = min(max(i1, i0 + 1), n_total)
                data, _ = ragged_gather(big, starts[i0:i1], lengths[i0:i1])
                writer.write(data.tobytes())
                i0 = i1
        writer.close()
        commit_file(tmp, out_path)
    except BaseException:
        # cleanup must not mask the root cause: an async writer close()
        # re-raises its deferred worker error — suppress it here, the
        # original exception is the one that matters
        try:
            writer.close()
        except Exception:
            pass
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    if index:
        from consensuscruncher_tpu.io.bai import write_bai_from_columns

        rid, pos, end, mapped = _record_spans_columnar(big, starts)
        ustart = header_len + np.concatenate(
            [[0], np.cumsum(lengths[:-1], dtype=np.int64)]
        ) if len(starts) else np.zeros(0, np.int64)
        write_bai_from_columns(
            os.fspath(out_path) + ".bai", len(header.refs),
            rid, pos, end, mapped, ustart, ustart + lengths,
            writer.block_sizes,
        )


def _mem_available_bytes() -> int:
    try:
        with open("/proc/meminfo") as fh:
            for line in fh:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 0


def _sort_buffer_bytes(divisor: int) -> int:
    """Shared CCT_SORT_BUFFER_MAX_BYTES semantics: env override wins
    outright, else MemAvailable/divisor with a 4 GiB floor."""
    env = os.environ.get("CCT_SORT_BUFFER_MAX_BYTES")
    if env:
        return int(env)
    return max(4 << 30, _mem_available_bytes() // divisor)


def single_writer_sort_buffer_bytes() -> int:
    """Sort budget for a stage that holds exactly ONE sorting writer (the
    fastq2bam align leg): the multi-writer /8 headroom of
    :func:`_default_sort_buffer_bytes` is over-conservative there — a
    123M-read align (27 GB raw) spilled on a 125 GB host.  /3 keeps the
    ~2x close() transient inside available RAM with margin.
    """
    return _sort_buffer_bytes(3)


def _default_sort_buffer_bytes() -> int:
    """Per-writer in-memory sort budget: env override, else RAM-aware.

    Spilling is DRAMATICALLY slower than buffering (the spill path finishes
    through the chunked merge — the old object-heap form measured 1,707 s
    vs ~250 s for the in-memory sort on the same 25M-record output), so the
    cap should be as high as the host can actually afford, not a fixed
    conservative number.  Budget: a stage holds 2-3 sorting writers at once
    and close() transiently needs ~2x the buffered bytes (concat + key
    columns + gathered output chunks), so a per-writer cap of
    MemAvailable/8 keeps a worst-case stage within available RAM.  Floor
    4 GiB (the old fixed default); the env var wins outright when set.
    """
    return _sort_buffer_bytes(8)


def _default_merge_key_budget() -> int:
    """Key-column budget for :func:`merge_sorted_columnar` — deliberately
    INDEPENDENT of CCT_SORT_BUFFER_MAX_BYTES: keys are ~30x smaller than
    raw record bytes, so a host too small to buffer records in full can
    still afford the columnar merge (that's exactly when it matters)."""
    env = os.environ.get("CCT_MERGE_KEY_BUDGET_BYTES")
    if env:
        return int(env)
    return max(1 << 30, _mem_available_bytes() // 8)


class SortingBamWriter:
    """Coordinate-sorting BAM writer: records buffer in memory as raw
    length-prefixed blobs and are key-decoded + lexsorted + written once at
    ``close()`` — no unsorted temp file, no BGZF round trip (the stage
    pattern this replaces was write-L1-tmp -> inflate -> sort -> deflate-L6).

    Same total order as ``io.bam.sort_bam`` (rid-with-unmapped-last, pos,
    qname bytes, flag; stable).  Inputs beyond ``max_raw_bytes`` of raw
    record data spill to an L1 temp BAM and finish through ``sort_bam``'s
    bounded merge path, so memory stays bounded on any input.

    Drop-in for the ``BamWriter`` surface the stages use: ``write``,
    ``write_encoded``, ``close``, ``abort`` (abort discards everything; the
    final path is never touched before a successful close).
    """

    def __init__(self, path, header: BamHeader, level: int = 6,
                 max_raw_bytes: int | None = None, index: bool = True):
        from consensuscruncher_tpu.io.bam import _sorted_header

        if max_raw_bytes is None:
            max_raw_bytes = _default_sort_buffer_bytes()
        self._path = os.fspath(path)
        self.header = _sorted_header(header)
        self._level = level
        self._index = index
        self._max_raw = max_raw_bytes
        self._chunks: list[np.ndarray] = []
        self._raw = 0
        self._spill = None
        self._spill_path = self._path + ".unsorted.tmp"
        self._closed = False

    def write(self, read) -> None:
        from consensuscruncher_tpu.io.bam import encode_record

        self.write_encoded(encode_record(read, self.header))

    def write_encoded(self, blob) -> None:
        if isinstance(blob, np.ndarray):
            arr = np.ascontiguousarray(blob, dtype=np.uint8)
        else:
            arr = np.frombuffer(blob, dtype=np.uint8)
        if arr.size == 0:
            return
        if self._spill is not None:
            self._spill.write_encoded(arr)
            return
        self._chunks.append(arr)
        self._raw += arr.size
        if self._raw > self._max_raw:
            self._start_spill()

    def _start_spill(self) -> None:
        from consensuscruncher_tpu.io.bam import BamWriter

        self._spill = BamWriter(self._spill_path, self.header, level=1)
        for c in self._chunks:
            self._spill.write_encoded(c)
        self._chunks = []
        self._raw = 0

    def _sorted_columns(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Concatenate the buffered chunks and resolve the final write
        order: ``(big, starts, lengths)`` with records at
        ``big[starts[i] : starts[i] + lengths[i]]`` already sorted."""
        if not self._chunks:
            big = np.empty(0, np.uint8)
        elif len(self._chunks) == 1:
            big = self._chunks[0]
        else:
            big = np.concatenate(self._chunks)
        self._chunks = []
        rec_off = _scan_offsets(big, len(big))
        if int(rec_off[-1]) != len(big):
            raise ValueError("SortingBamWriter received a partial record")
        off = rec_off[:-1]
        n = len(off)
        if n:
            rid = _gather_view(big, off + 4, 4, "<i4").astype(np.int64)
            pos = _gather_view(big, off + 8, 4, "<i4")
            flag = _gather_view(big, off + 18, 2, "<u2")
            l_qname = big[off + 12].astype(np.int64)  # incl. NUL
            qm = _qname_key_matrix(big, off + 36, l_qname - 1)
            perm = coord_sort_perm(rid, pos, qm, flag)
            starts, lengths = off[perm], np.diff(rec_off)[perm]
        else:
            starts = lengths = np.empty(0, np.int64)
        return big, starts, lengths

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._spill is not None:
            from consensuscruncher_tpu.io.bam import sort_bam

            self._spill.close()
            try:
                sort_bam(self._spill_path, self._path, level=self._level)
            finally:
                if os.path.exists(self._spill_path):
                    os.unlink(self._spill_path)
            return
        big, starts, lengths = self._sorted_columns()
        _write_bam_records(self._path, self.header, big, starts, lengths,
                           self._level, index=self._index)

    def close_to_memory(self) -> "MemoryBam":
        """Finish the sort WITHOUT writing the file: the streaming
        pipeline's stage hand-off.  The returned :class:`MemoryBam` holds
        the records in exactly the order and bytes :meth:`close` would
        have written, so materializing it later (final output or debug
        tap) is byte-identical to the staged path.

        Raises RuntimeError when the writer spilled — past the in-memory
        budget the staged sort/merge path is the only bounded one, and
        the CLI treats the raise as its fall-back-to-staged trigger.
        """
        if self._closed:
            raise RuntimeError("SortingBamWriter is already closed")
        if self._spill is not None:
            raise RuntimeError(
                "sort buffer spilled to disk; in-memory stage hand-off "
                "unavailable (falling back to the staged pipeline)")
        self._closed = True
        big, starts, lengths = self._sorted_columns()
        return MemoryBam(self.header, big, starts, lengths)

    def abort(self) -> None:
        self._closed = True
        self._chunks = []
        if self._spill is not None:
            self._spill.abort()
            if os.path.exists(self._spill_path):
                os.unlink(self._spill_path)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.abort()
        else:
            self.close()


class MemoryBam:
    """A sorted BAM held as in-memory columns — the streaming pipeline's
    inter-stage currency.

    Produced by :meth:`SortingBamWriter.close_to_memory`; consumed either
    as record batches (``.batches()`` — duck-compatible with
    :class:`ColumnarReader` so unchanged stage code reads it), as raw
    sorted record blobs (``.record_blobs()`` — what in-memory merges feed
    to ``write_encoded``), or materialized to disk (``.write()`` — the
    exact ``_write_bam_records`` call the staged path makes, hence
    byte-identical files).  Re-iterable and read-only; ``close()`` is a
    no-op so sources can be consumed more than once (e.g. SSCS feeds both
    singleton rescue and the final all-unique merge).
    """

    def __init__(self, header: BamHeader, big: np.ndarray,
                 starts: np.ndarray, lengths: np.ndarray):
        self.header = header
        self._big = big
        self._starts = starts
        self._lengths = lengths

    @property
    def n(self) -> int:
        return len(self._starts)

    @property
    def nbytes(self) -> int:
        return int(self._lengths.sum()) if len(self._lengths) else 0

    def _chunk_ranges(self, target: int):
        n = len(self._starts)
        if not n:
            return
        csum = np.cumsum(self._lengths)
        i0 = 0
        while i0 < n:
            floor = int(csum[i0 - 1]) if i0 else 0
            i1 = int(np.searchsorted(csum, floor + target)) + 1
            yield i0, min(max(i1, i0 + 1), n)
            i0 = min(max(i1, i0 + 1), n)

    def batches(self, batch_bytes: int = 64 << 20):
        """Yield :class:`ColumnarBatch` views in sorted order, bounded at
        ``batch_bytes`` of record data per batch."""
        for i0, i1 in self._chunk_ranges(batch_bytes):
            data, off = ragged_gather(
                self._big, self._starts[i0:i1], self._lengths[i0:i1])
            yield _make_batch(self.header, data, off)

    def record_blobs(self, chunk_bytes: int = 8 << 20):
        """Yield the sorted records as contiguous uint8 chunks (record
        boundaries never split) — the ``write_encoded`` feed shape."""
        for i0, i1 in self._chunk_ranges(chunk_bytes):
            data, _ = ragged_gather(
                self._big, self._starts[i0:i1], self._lengths[i0:i1])
            yield data

    def write(self, path, level: int = 6, index: bool = True) -> None:
        """Materialize to ``path`` exactly as the staged writer would have
        (atomic tmp+rename; inline ``.bai`` when ``index``)."""
        _write_bam_records(path, self.header, self._big, self._starts,
                           self._lengths, level, index=index)

    def close(self) -> None:
        pass


def open_batch_source(src, batch_bytes: int = 64 << 20):
    """A path OR an in-memory source -> something with ``.header`` /
    ``.batches()`` / ``.close()``.

    Stage code calls this instead of constructing :class:`ColumnarReader`
    directly, so the streaming pipeline can hand stages a
    :class:`MemoryBam` (or a read-ahead ``BatchStream`` over one)
    transparently while the staged path keeps passing file paths.
    """
    if hasattr(src, "batches") and hasattr(src, "header"):
        return src
    return ColumnarReader(src, batch_bytes=batch_bytes)
