"""BGZF (Blocked GNU Zip Format) codec — the container framing of BAM files.

First-party implementation: this environment has no pysam/htslib, so the
framework ships its own codec (reference parity: the htslib layer under
pysam, SURVEY.md §2 "Native components").  A native C++ hot path lives in
``io/native`` (ctypes-loaded); this module is the pure-Python fallback and the
single place that defines the framing.

Format (htslib SAM spec §4.1): a BGZF file is a series of gzip members, each
at most 64 KiB of payload, carrying a ``BC`` extra subfield whose 16-bit value
``BSIZE`` is (total block length - 1).  The file ends with a fixed 28-byte
empty block (EOF marker).  Because every block is a valid gzip member, plain
``gzip`` tools can read BGZF — but not vice versa, so the writer here always
emits real blocks + EOF marker for htslib compatibility.
"""

from __future__ import annotations

import functools
import io
import os
import struct
import sys
import threading
import time
import zlib
from typing import BinaryIO, Iterator

import numpy as np

from consensuscruncher_tpu.io import native
from consensuscruncher_tpu.utils import faults

MAX_BLOCK_PAYLOAD = 0xFF00  # htslib convention: keep compressed block < 64 KiB


class TruncatedBgzfError(ValueError):
    """The stream ended mid-block: the file was cut short (died-mid-copy
    upload, full disk, killed writer).  Distinct from generic corruption so
    callers can offer salvage — re-reading with ``salvage=True`` recovers
    every record up to the last intact block instead of raising."""


def _salvage_warn(context: str) -> None:
    print(f"WARNING: {context}; salvaging records up to the last intact "
          "BGZF block", file=sys.stderr, flush=True)

BGZF_EOF = bytes.fromhex("1f8b08040000000000ff0600424302001b0003000000000000000000")

_TAIL = struct.Struct("<2I")  # CRC32, ISIZE


def _is_pathlike(x) -> bool:
    return isinstance(x, (str, bytes, os.PathLike))


def _block_header(block_size: int) -> bytes:
    return struct.pack(
        "<4BIBBHBBHH",
        0x1F, 0x8B, 0x08, 0x04,  # gzip magic, deflate, FEXTRA
        0,                        # mtime
        0, 0xFF,                  # XFL, OS=unknown
        6,                        # XLEN
        0x42, 0x43, 2,            # 'B', 'C', SLEN=2
        block_size - 1,           # BSIZE
    )


def compress_block(payload: bytes, level: int = 6) -> bytes:
    """One payload of at most MAX_BLOCK_PAYLOAD bytes -> one complete BGZF block.

    The cap leaves headroom for deflate's worst-case expansion on
    incompressible data: compressed size + 26 framing bytes must fit the
    16-bit BSIZE field (htslib uses the same 0xFF00 payload bound).
    """
    if len(payload) > MAX_BLOCK_PAYLOAD:
        raise ValueError(
            f"BGZF payload too large: {len(payload)} > {MAX_BLOCK_PAYLOAD} "
            "(incompressible data must still fit the 16-bit BSIZE field)"
        )
    comp = zlib.compressobj(level, zlib.DEFLATED, -15)
    data = comp.compress(payload) + comp.flush()
    block_size = len(data) + 26  # 18 header + data + 8 tail
    return _block_header(block_size) + data + _TAIL.pack(zlib.crc32(payload), len(payload))


def read_block(fh: BinaryIO) -> bytes | None:
    """Read ONE BGZF block from ``fh``: decompressed payload (b"" for empty
    blocks, e.g. the EOF marker), or None at clean EOF.  Validates framing +
    CRC exactly like :func:`iter_blocks` (which is built on this)."""
    if faults.fire("bgzf.truncated_eof"):
        raise TruncatedBgzfError("truncated BGZF block (injected)")
    faults.fault_point("bgzf.read_stall")
    header = fh.read(18)
    if len(header) == 0:
        return None  # clean EOF (tolerated even without the marker block)
    if len(header) < 18:
        raise TruncatedBgzfError("truncated BGZF block header")
    if header[0] != 0x1F or header[1] != 0x8B:
        raise ValueError("not a BGZF/gzip stream (bad magic)")
    if header[3] & 0x04 == 0:
        raise ValueError("gzip member lacks the BGZF BC extra subfield")
    # Scan the extra field for the BC subfield (SAM spec §4.1 allows other
    # subfields alongside it, so the 18-byte fast layout is not assumed).
    (xlen,) = struct.unpack_from("<H", header, 10)
    extra = header[12:18]
    if xlen > 6:
        extra += fh.read(xlen - 6)
        if len(extra) < xlen:
            raise TruncatedBgzfError("truncated BGZF extra field")
    bsize = None
    off = 0
    while off + 4 <= xlen:
        si1, si2, slen = extra[off], extra[off + 1], struct.unpack_from("<H", extra, off + 2)[0]
        if si1 == 0x42 and si2 == 0x43 and slen == 2:
            (bsize,) = struct.unpack_from("<H", extra, off + 4)
            break
        off += 4 + slen
    if bsize is None:
        raise ValueError("gzip member lacks the BGZF BC extra subfield")
    block_size = bsize + 1
    consumed = 12 + xlen
    rest = fh.read(block_size - consumed)
    if len(rest) < block_size - consumed:
        raise TruncatedBgzfError("truncated BGZF block")
    data, (crc, isize) = rest[:-8], _TAIL.unpack(rest[-8:])
    payload = zlib.decompress(data, -15) if isize else b""
    if len(payload) != isize:
        raise ValueError(f"BGZF ISIZE mismatch: {len(payload)} != {isize}")
    if zlib.crc32(payload) != crc:
        raise ValueError("BGZF CRC mismatch")
    return payload


def iter_blocks(fh: BinaryIO, salvage: bool = False) -> Iterator[bytes]:
    """Yield decompressed payloads block by block, validating framing + CRC.

    ``salvage=True``: a truncated/corrupt block ends iteration with a
    warning instead of raising — every intact leading block is served."""
    while True:
        try:
            payload = read_block(fh)
        except ValueError as e:
            if not salvage:
                raise
            _salvage_warn(str(e))
            return
        if payload is None:
            return
        if payload:
            yield payload


def scan_block_metas(buf: bytes, tolerant: bool = False) -> tuple[tuple, int]:
    """Scan complete BGZF blocks at the head of ``buf`` (framing only).

    Returns ``((src_off, comp_len, isize, crc), consumed)`` where the four
    uint arrays describe each complete block's raw-deflate span and expected
    payload, and ``consumed`` is the byte offset of the first incomplete
    block (callers carry the tail into the next scan).  Raises ValueError on
    malformed framing — the same conditions ``iter_blocks`` rejects — unless
    ``tolerant``, which stops the scan there instead (salvage mode).
    """
    offs, lens, sizes, crcs = [], [], [], []
    pos, end = 0, len(buf)
    while True:
        if pos + 18 > end:
            break
        if buf[pos] != 0x1F or buf[pos + 1] != 0x8B:
            if tolerant:
                break
            raise ValueError("not a BGZF/gzip stream (bad magic)")
        if buf[pos + 3] & 0x04 == 0:
            if tolerant:
                break
            raise ValueError("gzip member lacks the BGZF BC extra subfield")
        (xlen,) = struct.unpack_from("<H", buf, pos + 10)
        if pos + 12 + xlen > end:
            break
        bsize = None
        off = pos + 12
        while off + 4 <= pos + 12 + xlen:
            si1, si2, slen = buf[off], buf[off + 1], struct.unpack_from("<H", buf, off + 2)[0]
            if si1 == 0x42 and si2 == 0x43 and slen == 2:
                (bsize,) = struct.unpack_from("<H", buf, off + 4)
                break
            off += 4 + slen
        if bsize is None:
            if tolerant:
                break
            raise ValueError("gzip member lacks the BGZF BC extra subfield")
        block_size = bsize + 1
        if pos + block_size > end:
            break
        data_off = pos + 12 + xlen
        data_len = block_size - (12 + xlen) - 8
        if data_len < 0:
            if tolerant:
                break
            raise ValueError("corrupt BGZF block (BSIZE smaller than framing)")
        crc, isize = _TAIL.unpack_from(buf, pos + block_size - 8)
        offs.append(data_off)
        lens.append(data_len)
        sizes.append(isize)
        crcs.append(crc)
        pos += block_size
    metas = (
        np.asarray(offs, dtype=np.uint64),
        np.asarray(lens, dtype=np.uint32),
        np.asarray(sizes, dtype=np.uint32),
        np.asarray(crcs, dtype=np.uint32),
    )
    return metas, pos


# --------------------------------------------------------------- knobs
#
# config.ini ``[io]`` values land here via :func:`configure` (the CLI
# folds them in before any writer is built).  Environment variables
# still win so operators can override a config file per-invocation.
_cfg: dict[str, object] = {"threads": None, "async_write": None}

# ---------------------------------------------------------- write stats
#
# Process-wide accumulator for what the writer layer actually spent:
# wall microseconds inside deflate+compressed-write and compressed bytes
# emitted (EOF markers included).  Stages snapshot before/after their
# commit sections and publish the DELTA through the registered
# ``deflate_wall_us`` / ``bytes_bam_written`` counters — giving bench
# the per-stage deflate fraction without threading a stats object
# through every writer construction site.  Lock-protected because async
# writers deflate on worker threads.
_stats_lock = threading.Lock()
_stats = {"deflate_wall_us": 0, "bytes_written": 0}


def _stats_add(wall_us: int, nbytes: int) -> None:
    with _stats_lock:
        _stats["deflate_wall_us"] += int(wall_us)
        _stats["bytes_written"] += int(nbytes)


def write_stats() -> dict[str, int]:
    """Snapshot of the process-wide writer stats (cumulative; callers
    diff two snapshots to attribute cost to a code region)."""
    with _stats_lock:
        return dict(_stats)


def configure(threads: int | None = None, async_write: bool | None = None) -> None:
    """Fold config-file ``[io]`` knobs into the codec defaults.

    ``threads``: deflate pool size (native pthread pool AND the pure-
    Python block pool); ``async_write``: default for the writer's
    background deflate thread.  CCT_BGZF_THREADS / CCT_ASYNC_WRITER
    environment overrides still win over values set here.
    """
    global _python_pool_obj
    with _python_pool_lock:
        if threads is not None:
            _cfg["threads"] = max(0, int(threads))
            if _python_pool_obj is not None:
                _python_pool_obj.shutdown(wait=False)
                _python_pool_obj = None
        if async_write is not None:
            _cfg["async_write"] = bool(async_write)


def codec_threads() -> int:
    """Worker threads for the deflate pools (native per-batch pthread
    pool and the pure-Python per-block thread pool).

    Blocks within one batch compress/decompress independently, so output
    bytes are IDENTICAL at any pool size — threads are pure wall-clock
    leverage on multi-core hosts (the north-star v5e host has ~112 vCPUs;
    zlib is the single largest host cost after the columnar passes).
    Default: cpu_count-1 capped at 8; 0 (inline) on single-core hosts.
    Override with CCT_BGZF_THREADS (wins) or config.ini ``[io]
    bgzf_threads`` via :func:`configure`.
    """
    env = os.environ.get("CCT_BGZF_THREADS")
    if env:
        try:
            return max(0, int(env))
        except ValueError:
            pass
    if _cfg["threads"] is not None:
        return int(_cfg["threads"])  # type: ignore[arg-type]
    n = os.cpu_count() or 1
    return 0 if n <= 1 else min(8, n - 1)


# Shared pure-Python deflate pool: per-block compression is order-
# independent (writeback below is ordered), so one process-wide pool
# serves every writer.  Created lazily; resized by dropping it when
# :func:`configure` changes the thread count.
_python_pool_lock = threading.Lock()
_python_pool_obj = None


def _python_pool():
    n = codec_threads()
    if n <= 1:
        return None
    global _python_pool_obj
    with _python_pool_lock:
        if _python_pool_obj is None:
            from concurrent.futures import ThreadPoolExecutor
            _python_pool_obj = ThreadPoolExecutor(
                max_workers=n, thread_name_prefix="bgzf-deflate")
        return _python_pool_obj


_NATIVE_READ_CHUNK = 8 << 20  # compressed bytes per native inflate batch


def _iter_native_batches(fh: BinaryIO,
                         salvage: bool = False) -> Iterator[tuple[int, tuple, bytes]]:
    """Yield ``(base_offset, metas, payload)`` per native inflate batch:
    ``metas`` is the :func:`scan_block_metas` tuple for the batch's blocks
    (offsets relative to ``base_offset``) and ``payload`` their concatenated
    decompressed bytes.  The single native read loop — every consumer of
    batch inflation goes through here so framing/tail handling lives once.
    ``salvage=True``: a truncated or corrupt tail ends iteration (with a
    warning) after every intact leading block has been served."""
    base = fh.tell()
    tail = b""
    while True:
        if faults.fire("bgzf.truncated_eof"):
            raise TruncatedBgzfError("truncated BGZF block (injected)")
        faults.fault_point("bgzf.read_stall")
        metas, consumed = scan_block_metas(tail, tolerant=salvage)
        while consumed == 0:
            more = fh.read(_NATIVE_READ_CHUNK)
            if not more:
                if tail:
                    if salvage:
                        _salvage_warn("truncated BGZF block at EOF")
                        return
                    raise TruncatedBgzfError("truncated BGZF block")
                return
            tail += more
            metas, consumed = scan_block_metas(tail, tolerant=salvage)
        try:
            payload = native.inflate_blocks(tail, *metas, n_threads=codec_threads())
        except Exception as e:
            if not salvage:
                raise
            # Inflate the batch block-by-block instead, keeping every block
            # up to the first bad one — the best a cut/corrupt file allows.
            offs, lens, sizes, crcs = metas
            goods = []
            for k in range(len(sizes)):
                span = tail[int(offs[k]): int(offs[k]) + int(lens[k])]
                try:
                    p = zlib.decompress(span, -15) if int(sizes[k]) else b""
                except zlib.error:
                    break
                if len(p) != int(sizes[k]) or zlib.crc32(p) != int(crcs[k]):
                    break
                goods.append(p)
            _salvage_warn(f"BGZF batch inflate failed ({e}); "
                          f"kept {len(goods)}/{len(sizes)} block(s)")
            if goods:
                yield base, tuple(m[:len(goods)] for m in metas), b"".join(goods)
            return
        yield base, metas, payload
        base += consumed
        tail = tail[consumed:]


def _iter_chunks_native(fh: BinaryIO, salvage: bool = False) -> Iterator[bytes]:
    """Yield decompressed chunks via the native batch codec (multi-block)."""
    for _base, _metas, payload in _iter_native_batches(fh, salvage=salvage):
        if payload:
            yield payload


def iter_blocks_with_offsets(fh: BinaryIO) -> Iterator[tuple[int, bytes]]:
    """Yield ``(file_offset, payload)`` per BGZF block — the shape indexers
    need (virtual offsets are built from block starts).  Uses the native
    batch codec when available, else the per-block Python path."""
    if not native.available():
        while True:
            off = fh.tell()
            payload = read_block(fh)
            if payload is None:
                return
            yield off, payload
        return
    for base, metas, payload in _iter_native_batches(fh):
        data_offs, comp_lens, isizes, _crcs = metas
        # Block k starts where k-1 ended: data_off points at the raw-deflate
        # span, so start_{k+1} = data_off_k + comp_len_k + 8 (CRC + ISIZE
        # tail); start_0 = 0 within the batch window.
        u = 0
        start = 0
        for k in range(len(isizes)):
            size = int(isizes[k])
            yield base + start, payload[u : u + size]
            u += size
            start = int(data_offs[k]) + int(comp_lens[k]) + 8


class BgzfReader(io.RawIOBase):
    """File-like sequential reader over BGZF blocks.

    When the native C++ codec (``io/native``) is available, blocks are
    inflated in parallel batches; otherwise the pure-Python ``iter_blocks``
    path serves identical bytes.
    """

    def __init__(self, path_or_fh, start_voffset: int | None = None,
                 salvage: bool = False):
        """``start_voffset``: begin mid-file at a BAI virtual offset
        (``coffset << 16 | within``) — seek to the block boundary and
        discard the intra-block prefix.  The caller owns pointing at a
        record boundary (BAI offsets do).  ``salvage``: serve bytes up to
        the last intact block of a truncated file instead of raising
        :class:`TruncatedBgzfError`."""
        self._own = _is_pathlike(path_or_fh)
        self._fh = open(path_or_fh, "rb") if self._own else path_or_fh
        if start_voffset is not None:
            self._fh.seek(start_voffset >> 16)
        if native.available():
            self._blocks = _iter_chunks_native(self._fh, salvage=salvage)
        else:
            self._blocks = iter_blocks(self._fh, salvage=salvage)
        self._buf = b""
        self._pos = 0
        if start_voffset is not None and start_voffset & 0xFFFF:
            self.read(start_voffset & 0xFFFF)

    def readable(self) -> bool:
        return True

    def read(self, n: int = -1) -> bytes:
        chunks = []
        if n < 0:
            chunks.append(self._buf[self._pos:])
            self._buf, self._pos = b"", 0
            for payload in self._blocks:
                chunks.append(payload)
            return b"".join(chunks)
        need = n
        while need > 0:
            avail = len(self._buf) - self._pos
            if avail == 0:
                nxt = next(self._blocks, None)
                if nxt is None:
                    break
                self._buf, self._pos = nxt, 0
                continue
            take = min(avail, need)
            chunks.append(self._buf[self._pos : self._pos + take])
            self._pos += take
            need -= take
        return b"".join(chunks)

    def close(self) -> None:
        if self._own:
            self._fh.close()
        super().close()


_NATIVE_WRITE_TARGET = 4 << 20  # payload bytes buffered per native deflate batch


def async_write_default() -> bool:
    """Should BgzfWriter offload deflate+write to a worker thread?

    Overlapping output compression with the stage loop is free throughput
    wherever the producing thread spends time in GIL-releasing work (device
    dispatch/waits, native codec legs, numpy passes) — on the multi-core
    deployment target that is most of the pipeline (VERDICT r3 weak 6).  On
    a single-core host the deflate contends for the same core, so default
    off there.  Override with CCT_ASYNC_WRITER=0/1 (wins) or config.ini
    ``[io] async_writer`` via :func:`configure`.
    """
    env = os.environ.get("CCT_ASYNC_WRITER")
    if env in ("0", "1"):
        return env == "1"
    if _cfg["async_write"] is not None:
        return bool(_cfg["async_write"])
    return (os.cpu_count() or 1) > 1


class BgzfWriter(io.RawIOBase):
    """File-like writer that emits proper BGZF blocks + EOF marker on close.

    With the native C++ codec available, payload is buffered and deflated in
    parallel multi-block batches; block boundaries (every MAX_BLOCK_PAYLOAD
    bytes) match the pure-Python path, so both produce the same block
    STRUCTURE and decompressed content.  Compressed bytes are codec-
    specific: the native codec links libdeflate when the build host has it
    (a different, equally valid DEFLATE producer than zlib), so cross-codec
    byte identity is NOT a contract — within one run every output is
    written by one codec, and goldens canonicalize content.

    ``async_write`` (default: :func:`async_write_default`) moves the
    deflate+file-write onto a single worker thread behind a bounded queue:
    the producer never blocks on compression (until the queue is full), and
    because ONE worker consumes chunks in enqueue order with the same block
    boundaries and level, the output bytes are identical in every mode.
    """

    _QUEUE_CHUNKS = 8  # bound: ~8 x 4 MiB payload in flight per writer

    def __init__(self, path_or_fh, level: int = 6, collect_blocks: bool = False,
                 async_write: bool | None = None):
        self._own = _is_pathlike(path_or_fh)
        self._fh = open(path_or_fh, "wb") if self._own else path_or_fh
        self._level = level
        self._buf = bytearray()
        self._native = native.available()
        # When asked, record every payload block's COMPRESSED byte length in
        # write order (payload lengths are implied: MAX_BLOCK_PAYLOAD for
        # all but the final block).  The inline BAI builder turns these into
        # virtual offsets without ever re-reading the file.
        self.block_sizes: list[int] | None = [] if collect_blocks else None
        self._eof_written = False
        self._queue = None
        self._worker = None
        self._worker_err: BaseException | None = None
        if async_write if async_write is not None else async_write_default():
            import queue as _queue
            import threading

            self._queue = _queue.Queue(maxsize=self._QUEUE_CHUNKS)
            self._worker = threading.Thread(
                target=self._drain, name="bgzf-writer", daemon=True)
            self._worker.start()

    def writable(self) -> bool:
        return True

    # -- worker thread ----------------------------------------------------
    def _drain(self) -> None:
        while True:
            payload = self._queue.get()
            if payload is None:
                return
            try:
                # A failed writer is POISONED: once any payload errored,
                # every later payload is dropped — writing past a hole
                # would produce a structurally-valid file with silently
                # missing middle bytes.
                if self._worker_err is None:
                    self._deflate_and_write(payload)
            except BaseException as e:  # sticky; surfaced on write()/close()
                self._worker_err = e
            finally:
                self._queue.task_done()

    def _raise_worker_err(self) -> None:
        if self._worker_err is not None:
            raise RuntimeError(
                "BGZF writer worker failed; output is truncated"
            ) from self._worker_err

    # -- deflate (runs on the worker thread when async, else inline) ------
    def _deflate_and_write(self, payload: bytes) -> None:
        # cct: allow-nondet(deflate wall-clock feeds the write-stats counters only, never output bytes)
        t0 = time.perf_counter_ns()
        nbytes = 0
        if self._native:
            threads = codec_threads()
            if self.block_sizes is not None:
                data, sizes = native.deflate_payload_sizes(payload, self._level,
                                                           threads)
                self.block_sizes.extend(sizes)
            else:
                data = native.deflate_payload(payload, self._level, threads)
            self._fh.write(data)
            nbytes = len(data)
        else:
            # Per-block deflate is embarrassingly parallel AND bit-
            # reproducible: each block is an independent zlib stream at a
            # fixed level, and writeback below preserves enqueue order, so
            # the output bytes are identical at any pool size (same
            # guarantee the native batch codec makes).
            chunks = [payload[off:off + MAX_BLOCK_PAYLOAD]
                      for off in range(0, len(payload), MAX_BLOCK_PAYLOAD)]
            pool = _python_pool() if len(chunks) > 1 else None
            if pool is not None:
                blocks = list(pool.map(
                    functools.partial(compress_block, level=self._level), chunks))
            else:
                blocks = [compress_block(c, self._level) for c in chunks]
            for block in blocks:
                if self.block_sizes is not None:
                    self.block_sizes.append(len(block))
                self._fh.write(block)
                nbytes += len(block)
        # cct: allow-nondet(elapsed wall goes to the write-stats counters only, never output bytes)
        _stats_add((time.perf_counter_ns() - t0) // 1000, nbytes)

    def _emit(self, size: int) -> None:
        payload, self._buf = bytes(self._buf[:size]), self._buf[size:]
        if self._queue is not None:
            self._raise_worker_err()
            self._queue.put(payload)
        else:
            self._deflate_and_write(payload)

    def write(self, data) -> int:
        self._buf += data
        target = _NATIVE_WRITE_TARGET if (self._native or self._queue is not None) \
            else MAX_BLOCK_PAYLOAD
        if len(self._buf) >= target:
            n_full = (len(self._buf) // MAX_BLOCK_PAYLOAD) * MAX_BLOCK_PAYLOAD
            self._emit(n_full)
        return len(data)

    def close(self) -> None:
        # Idempotent by construction: ``super().close()`` is guaranteed to
        # run on the FIRST attempt (nested finally below), so ``closed``
        # sticks even when flushing or the fh close raises — a retry-close
        # after a fault-site trip is a no-op instead of stamping a valid
        # EOF marker onto a truncated stream, and a clean double close
        # emits the marker exactly once.
        if self.closed:
            return
        try:
            if self._buf:
                payload, self._buf = bytes(self._buf), bytearray()
                if self._queue is not None:
                    self._queue.put(payload)  # worker drops it if poisoned
                else:
                    self._deflate_and_write(payload)
            if self._worker is not None:
                self._queue.put(None)
                self._worker.join()
                self._worker = None
            if self._worker_err is not None:
                # Never stamp a valid EOF marker onto a truncated stream.
                self._raise_worker_err()
            if not self._eof_written:
                self._fh.write(BGZF_EOF)
                self._eof_written = True
                _stats_add(0, len(BGZF_EOF))
        finally:
            try:
                if self._own:
                    self._fh.close()
            finally:
                super().close()


def total_isize(path) -> int:
    """Total UNCOMPRESSED size of a BGZF file by framing hops only.

    Parses each block's header exactly like :func:`read_block` (the BC
    subfield may sit anywhere in the gzip extra field — SAM spec §4.1
    allows neighbours, so the 18-byte fast layout is not assumed), seeks
    past the deflate payload, reads the 4-byte ISIZE tail — never
    inflates.  One buffered sequential pass; used to plan balanced splits.
    """
    total = 0
    with open(path, "rb") as fh:
        while True:
            header = fh.read(18)
            if not header:
                return total
            if len(header) < 18 or header[0] != 0x1F or header[1] != 0x8B:
                raise ValueError(f"{os.fspath(path)!r}: bad BGZF framing")
            (xlen,) = struct.unpack_from("<H", header, 10)
            extra = header[12:18]
            if xlen > 6:
                extra += fh.read(xlen - 6)
                if len(extra) < xlen:
                    raise TruncatedBgzfError(
                        f"{os.fspath(path)!r}: truncated BGZF extra field")
            bsize = None
            off = 0
            while off + 4 <= xlen:
                si1, si2 = extra[off], extra[off + 1]
                (slen,) = struct.unpack_from("<H", extra, off + 2)
                if si1 == 0x42 and si2 == 0x43 and slen == 2:
                    (bsize,) = struct.unpack_from("<H", extra, off + 4)
                    break
                off += 4 + slen
            if bsize is None:
                raise ValueError(
                    f"{os.fspath(path)!r}: gzip member lacks the BGZF BC subfield")
            # consumed so far: 12 fixed + xlen extra; ISIZE = last 4 bytes
            fh.seek(bsize + 1 - 12 - xlen - 4, 1)
            isize = fh.read(4)
            if len(isize) < 4:
                raise TruncatedBgzfError(f"{os.fspath(path)!r}: truncated BGZF block")
            total += struct.unpack("<I", isize)[0]


def decompress_file(path) -> bytes:
    """Whole-file BGZF -> bytes (convenience for small files/tests)."""
    with open(path, "rb") as fh:
        return b"".join(iter_blocks(fh))
