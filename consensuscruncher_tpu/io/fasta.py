"""Minimal FASTA reader (plain or gzipped).

Feeds the built-in test aligner (``stages.align``) — the reference
pipeline hands the FASTA straight to ``bwa`` and never parses it itself,
so this has no upstream counterpart; it exists because this framework can
run its full ``fastq2bam`` flow without external binaries.
"""

from __future__ import annotations

import gzip
from typing import Iterator


def _open_text(path):
    p = str(path)
    return gzip.open(p, "rt") if p.endswith(".gz") else open(p)


def iter_fasta(path) -> Iterator[tuple[str, str]]:
    """Yield ``(name, sequence)`` per record; name is the first token."""
    name, parts = None, []
    with _open_text(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            if line.startswith(">"):
                if name is not None:
                    yield name, "".join(parts)
                name, parts = line[1:].split()[0], []
            else:
                if name is None:
                    raise ValueError("FASTA content before first '>' header")
                parts.append(line.upper())
        if name is not None:
            yield name, "".join(parts)


def read_fasta(path) -> dict[str, str]:
    """Whole-file load: ``{name: sequence}`` (small/test genomes)."""
    out: dict[str, str] = {}
    for name, seq in iter_fasta(path):
        if name in out:
            raise ValueError(f"duplicate FASTA record {name!r}")
        out[name] = seq
    return out


def write_fasta(path, records: dict[str, str], width: int = 70) -> None:
    with open(path, "w") as fh:
        for name, seq in records.items():
            fh.write(f">{name}\n")
            for i in range(0, len(seq), width):
                fh.write(seq[i : i + width] + "\n")
