"""BAI index: build (``samtools index`` parity) and random-access fetch
(``pysam.AlignmentFile.fetch`` parity).

The reference pipeline runs ``samtools index`` after every sort and then
streams regions per chromosome through ``pysam.fetch``
(SURVEY.md §1 "External tools", §3.2).  Neither tool exists in this
image, and the rebuild's reader is first-party — so the index is too.
Format: SAM spec §5.2 (UCSC R-tree binning + 16 kb linear index, virtual
file offsets ``coffset << 16 | uoffset``), including the samtools
metadata pseudo-bin 37450 and the trailing no-coordinate count.

Parity is SEMANTIC (identical fetch results, fuzz-tested against a linear
scan), not byte-level vs ``samtools index``: a record starting exactly at a
BGZF block boundary is anchored here as ``(next_coffset << 16) | 0`` while
htslib records ``(prev_coffset << 16) | prev_block_len`` — both address the
same byte; the only observable difference is that htslib's chunk coalescing
fires slightly more often across block boundaries.

Everything here is host-side I/O; nothing touches the device.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass, field

from consensuscruncher_tpu.io import bgzf
from consensuscruncher_tpu.io.bam import BAM_MAGIC, BamHeader, decode_record
from consensuscruncher_tpu.utils.manifest import commit_file

BAI_MAGIC = b"BAI\x01"
_PSEUDO_BIN = 37450  # samtools metadata bin (bin(4681,8191) + 1 + ...)
_LINEAR_SHIFT = 14  # 16 kb linear-index windows
# CIGAR ops that consume reference: M, D, N, =, X  (spec order MIDNSHP=X)
_REF_CONSUMING = frozenset(b"MDN=X".decode())
_CIGAR_OPS = "MIDNSHP=X"


def reg2bin(beg: int, end: int) -> int:
    """SAM spec §5.3 bin for a [beg, end) interval."""
    end -= 1
    if beg >> 14 == end >> 14:
        return ((1 << 15) - 1) // 7 + (beg >> 14)
    if beg >> 17 == end >> 17:
        return ((1 << 12) - 1) // 7 + (beg >> 17)
    if beg >> 20 == end >> 20:
        return ((1 << 9) - 1) // 7 + (beg >> 20)
    if beg >> 23 == end >> 23:
        return ((1 << 6) - 1) // 7 + (beg >> 23)
    if beg >> 26 == end >> 26:
        return ((1 << 3) - 1) // 7 + (beg >> 26)
    return 0


def reg2bins(beg: int, end: int) -> list[int]:
    """All bins that may hold records overlapping [beg, end) (spec §5.3)."""
    bins = [0]
    end -= 1
    for shift, base in ((26, 1), (23, 9), (20, 73), (17, 585), (14, 4681)):
        bins.extend(range(base + (beg >> shift), base + (end >> shift) + 1))
    return bins


@dataclass
class _RefIndex:
    bins: dict[int, list[list[int]]] = field(default_factory=dict)  # bin -> [[beg,end]...]
    linear: list[int] = field(default_factory=list)  # 16kb window -> min voffset
    n_mapped: int = 0
    n_unmapped: int = 0
    off_beg: int = -1
    off_end: int = 0

    def add(self, beg: int, end: int, vbeg: int, vend: int, mapped: bool) -> None:
        if self.off_beg < 0:
            self.off_beg = vbeg
        self.off_end = vend
        if mapped:
            self.n_mapped += 1
        else:
            self.n_unmapped += 1
        chunks = self.bins.setdefault(reg2bin(beg, end), [])
        # htslib merge rule: coalesce with the previous chunk when the new
        # one starts in the same compressed block the previous one ends in.
        if chunks and chunks[-1][1] >> 16 == vbeg >> 16:
            chunks[-1][1] = vend
        else:
            chunks.append([vbeg, vend])
        w_beg, w_end = beg >> _LINEAR_SHIFT, max(beg, end - 1) >> _LINEAR_SHIFT
        if len(self.linear) <= w_end:
            self.linear.extend([0] * (w_end + 1 - len(self.linear)))
        for w in range(w_beg, w_end + 1):
            if self.linear[w] == 0:
                self.linear[w] = vbeg


class _VoffsetTracker:
    """Maps global uncompressed offsets to virtual file offsets while
    streaming blocks in order.  Blocks are registered monotonically; lookups
    are monotonic too, so spent anchors are dropped as we go."""

    def __init__(self):
        self._anchors: list[tuple[int, int, int]] = []  # (u_start, coffset, len)

    def add_block(self, u_start: int, coffset: int, length: int) -> None:
        self._anchors.append((u_start, coffset, length))

    def voffset(self, u: int) -> int:
        """Virtual offset of global uncompressed position ``u``.  Positions
        at a block boundary resolve into the LATER block (a record never
        starts in the spent tail of a block)."""
        while len(self._anchors) > 1 and self._anchors[1][0] <= u:
            self._anchors.pop(0)
        u_start, coffset, _len = self._anchors[0]
        if u < u_start:
            raise ValueError("voffset lookups must be monotonic")
        return (coffset << 16) | (u - u_start)

    def voffset_end(self, u_end: int) -> int:
        """Virtual offset just past a record ending at global position
        ``u_end`` — stays in the block holding the record's last byte (so a
        record ending exactly at a block boundary gets uoffset == block
        length, matching htslib's post-read file-pointer convention)."""
        while len(self._anchors) > 1 and self._anchors[1][0] <= u_end - 1:
            self._anchors.pop(0)
        u_start, coffset, _len = self._anchors[0]
        return (coffset << 16) | (u_end - u_start)


def _record_span(body: bytes) -> tuple[int, int, int, bool]:
    """(ref_id, pos, end, mapped) from a raw record body (no full decode)."""
    ref_id, pos = struct.unpack_from("<ii", body, 0)
    l_read_name = body[8]
    (n_cigar,) = struct.unpack_from("<H", body, 12)
    (flag,) = struct.unpack_from("<H", body, 14)
    mapped = (flag & 0x4) == 0
    end = pos + 1
    if mapped and n_cigar:
        off = 32 + l_read_name
        ref_len = 0
        for i in range(n_cigar):
            (v,) = struct.unpack_from("<I", body, off + 4 * i)
            if _CIGAR_OPS[v & 0xF] in _REF_CONSUMING:
                ref_len += v >> 4
        end = pos + max(ref_len, 1)
    return ref_id, pos, end, mapped


def index_bam(bam_path, bai_path=None, skip_if_fresh: bool = False) -> str:
    """Build ``<bam>.bai`` for a coordinate-sorted BAM.  Returns the path.

    ``skip_if_fresh``: return without re-reading the BAM when the index
    already exists and is at least as new as it (the --resume fast path —
    indexing re-inflates the whole file, so it must not defeat skip-if-
    intact runs)."""
    bam_path = os.fspath(bam_path)
    bai_path = bai_path or bam_path + ".bai"
    if (skip_if_fresh and os.path.exists(bai_path)
            and os.path.getmtime(bai_path) >= os.path.getmtime(bam_path)):
        return bai_path

    refs: list[_RefIndex] = []
    n_no_coor = 0
    tracker = _VoffsetTracker()
    last_ref, last_pos = -1, -1

    with open(bam_path, "rb") as fh:
        # Walk raw blocks so every record's virtual offset is known.
        blocks = bgzf.iter_blocks_with_offsets(fh)
        buf = bytearray()
        buf_u = 0  # global uncompressed offset of buf[0]
        eof = False

        def fill(need: int) -> bool:
            nonlocal eof
            while len(buf) < need and not eof:
                try:
                    coffset, payload = next(blocks)
                except StopIteration:
                    eof = True
                    return len(buf) >= need
                tracker.add_block(buf_u + len(buf), coffset, len(payload))
                buf.extend(payload)
            return len(buf) >= need

        def take(n: int) -> bytes:
            nonlocal buf, buf_u
            out = bytes(buf[:n])
            del buf[:n]
            buf_u += n
            return out

        # Header: magic, text, refs — indexed content starts after it.
        if not fill(12):
            raise ValueError("truncated BAM header")
        if bytes(buf[:4]) != BAM_MAGIC:
            raise ValueError(f"not a BAM file: {bam_path!r}")
        (l_text,) = struct.unpack_from("<i", buf, 4)
        if not fill(12 + l_text):
            raise ValueError("truncated BAM header")
        take(8 + l_text)
        (n_ref,) = struct.unpack("<i", take(4))
        for _ in range(n_ref):
            if not fill(8):
                raise ValueError("truncated BAM header")
            (l_name,) = struct.unpack("<i", take(4))
            if not fill(l_name + 4):
                raise ValueError("truncated BAM header")
            take(l_name + 4)
            refs.append(_RefIndex())

        while True:
            if not fill(4):
                break
            (block_size,) = struct.unpack("<i", bytes(buf[:4]))
            if not fill(4 + block_size):
                raise ValueError("truncated BAM record")
            vbeg = tracker.voffset(buf_u)
            body = take(4 + block_size)[4:]
            vend = tracker.voffset_end(buf_u)
            ref_id, pos, end, mapped = _record_span(body)
            if ref_id < 0:
                n_no_coor += 1
                continue
            if ref_id < last_ref or (ref_id == last_ref and pos < last_pos):
                raise ValueError(
                    f"{bam_path!r} is not coordinate-sorted "
                    f"(ref {ref_id} pos {pos} after ref {last_ref} pos {last_pos})"
                )
            last_ref, last_pos = ref_id, pos
            refs[ref_id].add(pos, end, vbeg, vend, mapped)

    return _finish_and_write_bai(refs, n_no_coor, bai_path)


def _reg2bin_vec(beg, end):
    """Vectorized :func:`reg2bin` over (beg, end) column arrays."""
    import numpy as np

    e = end - 1
    conds = [beg >> 14 == e >> 14, beg >> 17 == e >> 17, beg >> 20 == e >> 20,
             beg >> 23 == e >> 23, beg >> 26 == e >> 26]
    choices = [4681 + (beg >> 14), 585 + (beg >> 17), 73 + (beg >> 20),
               9 + (beg >> 23), 1 + (beg >> 26)]
    return np.select(conds, choices, default=0)


def write_bai_from_columns(
    bai_path,
    n_ref: int,
    rid,
    pos,
    end,
    mapped,
    ustart,
    uend,
    block_csizes,
) -> str:
    """Build a .bai directly from write-time columns — no file re-read.

    The columnar writers (`io.columnar._write_bam_records`) know every
    record's byte range in the uncompressed stream and the BGZF block
    layout they produced (all payload blocks are exactly MAX_BLOCK_PAYLOAD
    bytes except the final one, so virtual offsets are pure arithmetic over
    the per-block compressed sizes).  ``index_bam``'s re-read + per-record
    Python scan was the single largest host cost of the CLI pipeline after
    the stages themselves (measured ~30% of a full consensus run).

    Args: ``rid``/``pos``/``end``/``mapped`` per record IN FILE ORDER
    (coordinate-sorted; rid < 0 = unplaced, counted into n_no_coor),
    ``end`` the reference-consumed end (pos+1 minimum), ``ustart``/``uend``
    the record's absolute uncompressed byte span (header included),
    ``block_csizes`` the compressed payload-block sizes in order.

    Semantics identical to :func:`index_bam` by the parity test suite.
    """
    import numpy as np

    P = bgzf.MAX_BLOCK_PAYLOAD
    rid = np.asarray(rid, dtype=np.int64)
    pos = np.asarray(pos, dtype=np.int64)
    end = np.asarray(end, dtype=np.int64)
    mapped = np.asarray(mapped, dtype=bool)
    ustart = np.asarray(ustart, dtype=np.int64)
    uend = np.asarray(uend, dtype=np.int64)

    coff = np.zeros(len(block_csizes) + 1, dtype=np.int64)
    np.cumsum(np.asarray(block_csizes, dtype=np.int64), out=coff[1:])
    # The voffset math below assumes every non-final BGZF payload block is
    # exactly P uncompressed bytes (BgzfWriter's flush invariant).  Nothing
    # else cross-checks it at runtime, and a future writer flush change
    # would silently corrupt every inline index — fail loudly instead
    # (ADVICE r3).
    if len(uend) and len(block_csizes):
        total_u = int(uend.max())
        nb = len(block_csizes)
        if not ((nb - 1) * P < total_u <= nb * P):
            raise ValueError(
                f"BGZF block layout violates the fixed-payload invariant: "
                f"{nb} blocks x {P} B payload cannot span the {total_u} B "
                "uncompressed stream — writer flush logic changed; "
                "write_bai_from_columns voffsets would be corrupt")
    bi = ustart // P  # every non-final payload block is exactly P bytes
    vbeg = (coff[bi] << 16) | (ustart - bi * P)
    be = np.maximum(uend - 1, 0) // P
    vend = (coff[be] << 16) | (uend - be * P)

    n_no_coor = int((rid < 0).sum())
    refs = [_RefIndex() for _ in range(n_ref)]
    placed = int(len(rid) - n_no_coor)  # sort puts rid<0 last
    bins_all = _reg2bin_vec(pos, np.maximum(end, pos + 1))

    # rid ascending over the placed prefix -> per-ref contiguous runs
    bounds = np.searchsorted(rid[:placed], np.arange(n_ref + 1))
    for r in range(n_ref):
        i0, i1 = int(bounds[r]), int(bounds[r + 1])
        if i1 <= i0:
            continue
        ref = refs[r]
        ref.off_beg = int(vbeg[i0])
        ref.off_end = int(vend[i1 - 1])
        m = mapped[i0:i1]
        ref.n_mapped = int(m.sum())
        ref.n_unmapped = int((~m).sum())
        vb, ve = vbeg[i0:i1], vend[i0:i1]
        bins = bins_all[i0:i1]
        pp, ee = pos[i0:i1], end[i0:i1]

        # ---- bins: stable sort by bin keeps ascending voffsets per bin;
        # merge consecutive chunks that share a compressed block.
        order = np.argsort(bins, kind="stable")
        b_s, vb_s, ve_s = bins[order], vb[order], ve[order]
        new_bin = np.empty(len(b_s), dtype=bool)
        new_bin[0] = True
        np.not_equal(b_s[1:], b_s[:-1], out=new_bin[1:])
        new_chunk = new_bin.copy()
        np.logical_or(new_chunk[1:], (vb_s[1:] >> 16) != (ve_s[:-1] >> 16),
                      out=new_chunk[1:])
        cidx = np.nonzero(new_chunk)[0]
        chunk_beg = vb_s[cidx]
        chunk_end = ve_s[np.concatenate([cidx[1:] - 1, [len(b_s) - 1]])]
        chunk_bin = b_s[cidx]
        first_of_bin = np.nonzero(new_bin[cidx])[0]
        bin_bounds = np.concatenate([first_of_bin, [len(cidx)]])
        for k in range(len(first_of_bin)):
            c0, c1 = int(bin_bounds[k]), int(bin_bounds[k + 1])
            ref.bins[int(chunk_bin[c0])] = [
                [int(chunk_beg[c]), int(chunk_end[c])] for c in range(c0, c1)
            ]

        # ---- linear index: first vbeg per 16 kb window spanned by each
        # record — voffsets ascend in file order, so "first write wins" ==
        # plain minimum (sentinel-initialized; 0 = empty in the format).
        w_beg = pp >> _LINEAR_SHIFT
        w_end = np.maximum(pp, ee - 1) >> _LINEAR_SHIFT
        sentinel = np.iinfo(np.int64).max
        lin = np.full(int(w_end.max()) + 1, sentinel, dtype=np.int64)
        d = 0
        alive = np.arange(len(pp))
        while len(alive):
            np.minimum.at(lin, w_beg[alive] + d, vb[alive])
            d += 1
            alive = alive[w_beg[alive] + d <= w_end[alive]]
        ref.linear = [0 if v == sentinel else int(v) for v in lin]

    return _finish_and_write_bai(refs, n_no_coor, os.fspath(bai_path))


def _finish_and_write_bai(refs: list[_RefIndex], n_no_coor: int,
                          bai_path: str) -> str:
    """Forward-fill linear indexes, serialize, atomically place the .bai."""
    for r in refs:
        # Forward-fill empty 16 kb windows with the previous window's offset
        # (htslib carries values forward in hts_idx_finish) so fetch's
        # linear floor never degrades to 0 when beg lands in a coverage gap.
        # Leading zeros (windows before the first record) stay 0.
        last = 0
        for i, v in enumerate(r.linear):
            if v == 0:
                r.linear[i] = last
            else:
                last = v

    tmp = bai_path + ".tmp"
    with open(tmp, "wb") as out:
        out.write(BAI_MAGIC)
        out.write(struct.pack("<i", len(refs)))
        for r in refs:
            has_meta = r.off_beg >= 0
            out.write(struct.pack("<i", len(r.bins) + (1 if has_meta else 0)))
            for b in sorted(r.bins):
                chunks = r.bins[b]
                out.write(struct.pack("<Ii", b, len(chunks)))
                for beg, end in chunks:
                    out.write(struct.pack("<QQ", beg, end))
            if has_meta:
                out.write(struct.pack("<Ii", _PSEUDO_BIN, 2))
                out.write(struct.pack("<QQ", r.off_beg, r.off_end))
                out.write(struct.pack("<QQ", r.n_mapped, r.n_unmapped))
            out.write(struct.pack("<i", len(r.linear)))
            for v in r.linear:
                out.write(struct.pack("<Q", v))
        out.write(struct.pack("<Q", n_no_coor))
    commit_file(tmp, bai_path)
    return bai_path


@dataclass
class BaiIndex:
    """Loaded .bai: per-ref bins/linear + metadata."""

    bins: list[dict[int, list[tuple[int, int]]]]
    linear: list[list[int]]
    meta: list[tuple[int, int, int, int] | None]  # (off_beg, off_end, mapped, unmapped)
    n_no_coor: int

    @classmethod
    def load(cls, path) -> "BaiIndex":
        with open(path, "rb") as fh:
            data = fh.read()
        if data[:4] != BAI_MAGIC:
            raise ValueError(f"not a BAI index: {os.fspath(path)!r}")
        off = 4
        (n_ref,) = struct.unpack_from("<i", data, off)
        off += 4
        bins, linear, meta = [], [], []
        for _ in range(n_ref):
            (n_bin,) = struct.unpack_from("<i", data, off)
            off += 4
            ref_bins: dict[int, list[tuple[int, int]]] = {}
            ref_meta = None
            for _ in range(n_bin):
                b, n_chunk = struct.unpack_from("<Ii", data, off)
                off += 8
                chunks = []
                for _ in range(n_chunk):
                    beg, end = struct.unpack_from("<QQ", data, off)
                    off += 16
                    chunks.append((beg, end))
                if b == _PSEUDO_BIN:
                    ref_meta = (chunks[0][0], chunks[0][1], chunks[1][0], chunks[1][1])
                else:
                    ref_bins[b] = chunks
            (n_intv,) = struct.unpack_from("<i", data, off)
            off += 4
            ref_linear = list(struct.unpack_from(f"<{n_intv}Q", data, off))
            off += 8 * n_intv
            bins.append(ref_bins)
            linear.append(ref_linear)
            meta.append(ref_meta)
        n_no_coor = struct.unpack_from("<Q", data, off)[0] if off + 8 <= len(data) else 0
        return cls(bins=bins, linear=linear, meta=meta, n_no_coor=n_no_coor)


class IndexedBamReader:
    """Random-access BAM reader over a .bai (``pysam.fetch`` parity).

    ``fetch(ref, beg, end)`` yields exactly the records overlapping
    [beg, end) on ``ref``, in file (coordinate) order, touching only the
    compressed blocks the index points at.
    """

    def __init__(self, bam_path, bai_path=None):
        bam_path = os.fspath(bam_path)
        bai_path = bai_path or bam_path + ".bai"
        if not os.path.exists(bai_path):
            index_bam(bam_path, bai_path)
        self.index = BaiIndex.load(bai_path)
        # Header decode first (pins the ref name -> id mapping); the raw
        # handle opens last so a parse failure can't leak it.
        from consensuscruncher_tpu.io.bam import BamReader

        with BamReader(bam_path) as r:
            self.header: BamHeader = r.header
        self._fh = open(bam_path, "rb")

    def close(self) -> None:
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ------------------------------------------------------------- internals

    def _read_from(self, voffset: int):
        """Yield (vbeg, body) record stream starting at ``voffset``."""
        self._fh.seek(voffset >> 16)
        buf = bytearray()
        u = 0
        skip = voffset & 0xFFFF

        tracker = _VoffsetTracker()
        eof = False

        def fill(need: int) -> bool:
            nonlocal eof
            while len(buf) - skip < need and not eof:
                coffset = self._fh.tell()
                payload = bgzf.read_block(self._fh)
                if payload is None:
                    eof = True
                    break
                tracker.add_block(u + len(buf), coffset, len(payload))
                buf.extend(payload)
            return len(buf) - skip >= need

        # Drop the intra-block skip once, keeping anchor math consistent.
        if not fill(0) and not buf:
            return
        while True:
            if skip:
                del buf[:skip]
                # anchors track global u; advancing u by skip keeps them valid
                u += skip
                skip = 0
            if not fill(4):
                return
            vbeg = tracker.voffset(u)
            (block_size,) = struct.unpack_from("<i", buf, 0)
            if not fill(4 + block_size):
                raise ValueError("truncated BAM record")
            body = bytes(buf[4 : 4 + block_size])
            del buf[: 4 + block_size]
            u += 4 + block_size
            yield vbeg, body

    def fetch(self, ref: str, beg: int = 0, end: int | None = None):
        """Yield decoded records overlapping [beg, end) on ``ref``."""
        rid = self.header.ref_id(ref)
        if end is None:
            end = self.header.refs[rid][1]
        if end <= beg:
            return  # [beg, end) is empty — nothing can overlap it
        ref_bins = self.index.bins[rid]
        chunks: list[tuple[int, int]] = []
        for b in reg2bins(beg, end):
            chunks.extend(ref_bins.get(b, ()))
        if not chunks:
            return
        # Linear-index floor: skip chunks that end before the first record
        # that could overlap beg.
        lin = self.index.linear[rid]
        w = beg >> _LINEAR_SHIFT
        min_off = lin[w] if w < len(lin) else (lin[-1] if lin else 0)
        chunks = sorted(c for c in chunks if c[1] > min_off)
        if not chunks:
            return
        # Merge overlapping/adjacent chunk runs to avoid re-reading blocks.
        merged = [list(chunks[0])]
        for cb, ce in chunks[1:]:
            if cb <= merged[-1][1]:
                merged[-1][1] = max(merged[-1][1], ce)
            else:
                merged.append([cb, ce])
        for cb, ce in merged:
            start = max(cb, min_off)
            for vbeg, body in self._read_from(start):
                if vbeg >= ce:
                    break
                ref_id, pos, rec_end, _mapped = _record_span(body)
                if ref_id != rid or pos >= end:
                    break
                if rec_end > beg:
                    yield decode_record(body, self.header)
