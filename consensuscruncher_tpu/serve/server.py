"""The daemon's wire front-end: newline-delimited JSON over a socket.

One request per line, one JSON reply per line; a connection may carry any
number of requests (the blocking ``result`` op holds its line open until
the job finishes).  Transport is either a unix socket (``socket_path``) or
localhost TCP — both are single-host by design: the daemon is a *device
host* process, remote fan-in belongs to a reverse proxy.

Ops (all replies carry ``"ok"``):

  {"op": "submit", "spec": {...}}       -> {"ok": true, "job_id": N,
                                            "key": "...", "duplicate": bool}
  {"op": "status", "job_id": N}         -> {"ok": true, "job": {...}}
  {"op": "result", "job_id": N,
   "timeout": seconds|null}             -> blocks; {"ok": true, "job": {...}}
  {"op": "healthz"}                     -> {"ok": true, "health": {...}}
  {"op": "metrics"}                     -> {"ok": true, "metrics": {...}}
  {"op": "metrics",
   "format": "prometheus"}              -> {"ok": true, "prometheus": "..."}
                                           (text exposition, histograms
                                           with cumulative le buckets)
  {"op": "drain", "timeout": s|null}    -> blocks; {"ok": true, "drained": true}
  {"op": "trace"}                       -> {"ok": true, "trace": {"node",
                                            "pid", "events": [...]}}
                                           (this process's span buffer,
                                           for ``cct trace fleet``)

Causal tracing: any request may carry a ``"trace"`` context
(``{"trace_id", "span", "pid", "hop"}`` — stamped automatically by
``ServeClient``); the submit path links the accepted job's span tree to
it and the ack reply echoes the job's own durable context back.

Failure containment replies: a submit for a quarantined key (poison
containment — fleet retry budget exhausted or breaker open) comes back
``refused: true, quarantined: true`` with a human ``reason``; during a
resource-exhaustion brownout (journal appends failing ENOSPC) fresh
admissions reply ``refused: true, brownout: true`` while polls and
cache-hit submits keep working.  ``{"op": "release", "key": ...}``
lifts a key's quarantine (``cct route --release``) and re-queues the
parked job.  A forwarded submit may carry ``"attempts"`` — the router's
fleet attempt lineage for the key, max-merged into the scheduler's
budget gate before admission.

``status``/``result`` accept ``"key"`` (the submit reply's idempotency
key) in place of ``"job_id"`` — keys survive a daemon restart, ids are
only as durable as the journal, so restart-invisible polling uses keys.
A submit whose spec hashes to an already-tracked job returns that job
with ``"duplicate": true``.  A job evicted from memory (result TTL)
replies ``state: "expired"`` with the on-disk output path.  A submit shed
for its deadline replies ``refused: true, shed: true``; one refused by a
per-tenant quota replies ``refused: true, quota: true``.  A request
carrying a fleet-router ``epoch`` below the highest this worker has
accepted replies ``fenced: true, epoch: <live>`` (see
:meth:`Scheduler.fence` — zombie-router protection after a standby
takeover; epoch-less requests are never fenced).

Errors reply ``{"ok": false, "error": "..."}`` and keep the connection
usable; a malformed line closes the connection.  The ``serve.accept``
fault site fires per accepted connection (chaos tests turn accept-path
failures into clean error replies, never daemon death); ``serve.sigterm``
fires inside the shutdown path (a fault there degrades to an immediate
stop — journal replay makes even that lossless).

Lifecycle: handler threads are tracked in a bounded registry and joined
in :meth:`ServeServer.close`, so shutdown never leaks a socket mid-reply.
:func:`install_signal_handlers` wires SIGTERM/SIGINT to
:func:`request_shutdown`: stop admission, journal a ``drain`` marker,
break the accept loop — the serve CLI then finishes in-flight work within
``CCT_SERVE_DRAIN_S`` and exits 0.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import sys
import threading
import time

from consensuscruncher_tpu.obs import history as obs_history
from consensuscruncher_tpu.obs import prof as obs_prof
from consensuscruncher_tpu.obs import trace as obs_trace
from consensuscruncher_tpu.obs.metrics import render_prometheus
from consensuscruncher_tpu.serve import wire
from consensuscruncher_tpu.serve.scheduler import (
    AdmissionRefused, BrownoutRefused, DeadlineShed, QuarantineRefused,
    QuotaRefused, RouterFenced, Scheduler,
)
from consensuscruncher_tpu.utils import faults, sanitize

MAX_LINE = 1 << 20  # 1 MiB per request line; specs are tiny


class ServeServer:
    """Accept loop + per-connection handler threads over a Scheduler."""

    def __init__(self, scheduler: Scheduler, host: str = "127.0.0.1",
                 port: int = 0, socket_path: str | None = None,
                 max_conns: int | None = None,
                 read_timeout_s: float | None = None,
                 idle_timeout_s: float | None = None):
        self.scheduler = scheduler
        self.socket_path = socket_path
        if max_conns is None:
            max_conns = int(os.environ.get("CCT_SERVE_MAX_CONNS", "128"))
        self.max_conns = max(1, int(max_conns))
        # per-connection deadlines: read_timeout bounds a *half-frame*
        # stall (bytes buffered, rest never arrives), idle_timeout bounds
        # a connected-but-silent peer.  Either expiring reaps the
        # connection and recovers its max_conns slot (``conns_reaped``).
        # 0 disables a deadline (the legacy unbounded behavior).
        if read_timeout_s is None:
            read_timeout_s = float(
                os.environ.get("CCT_SERVE_READ_TIMEOUT_S", "30"))
        if idle_timeout_s is None:
            idle_timeout_s = float(
                os.environ.get("CCT_SERVE_IDLE_TIMEOUT_S", "300"))
        self.read_timeout_s = max(0.0, float(read_timeout_s))
        self.idle_timeout_s = max(0.0, float(idle_timeout_s))
        if socket_path:
            if os.path.exists(socket_path):
                os.unlink(socket_path)  # stale socket from a dead daemon
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.bind(socket_path)
            self.address: object = socket_path
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._sock.bind((host, port))
            self.address = self._sock.getsockname()  # (host, real port)
        self._sock.listen(16)
        self._closed = False
        self._accept_thread: threading.Thread | None = None
        # bounded registry of live connection handlers: close() joins them
        # so shutdown cannot leak a socket mid-reply
        self._conn_lock = sanitize.tracked_lock("server.conns")
        self._conns: dict[int, tuple[socket.socket, threading.Thread]] = {}
        self._next_conn = 0

    def describe(self) -> str:
        if self.socket_path:
            return f"unix:{self.socket_path}"
        host, port = self.address
        return f"tcp:{host}:{port}"

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        """Run the accept loop on a background thread (tests, embedding)."""
        self._accept_thread = threading.Thread(
            target=self.serve_forever, name="serve-accept", daemon=True)
        self._accept_thread.start()

    def serve_forever(self) -> None:
        while not self._closed:
            try:
                # cct: allow-wire(shutdown closes the listener to break accept; per-connection deadlines start in _handle_conn)
                conn, _addr = self._sock.accept()
            except OSError:
                return  # socket closed under us: clean shutdown
            busy = False
            with self._conn_lock:
                if len(self._conns) >= self.max_conns:
                    busy = True
                else:
                    self._next_conn += 1
                    cid = self._next_conn
                    t = threading.Thread(
                        target=self._handle_conn, args=(conn, cid),
                        name=f"serve-conn-{cid}", daemon=True)
                    self._conns[cid] = (conn, t)
            if busy:
                # reply outside the lock: sendall can block
                self._reply(conn, {"ok": False, "busy": True,
                                   "error": f"server busy "
                                            f"({self.max_conns} connections)"})
                conn.close()
                continue
            t.start()

    def shutdown(self) -> None:
        """Break the accept loop without joining handlers — the signal-safe
        half of close() (callable from a signal handler)."""
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass

    def close(self, timeout: float = 10.0) -> None:
        self.shutdown()
        try:
            # half-close live connections: no new requests are read, but
            # in-flight replies still flush before the join below
            with self._conn_lock:
                live = list(self._conns.values())
            for conn, _t in live:
                try:
                    conn.shutdown(socket.SHUT_RD)
                except OSError:
                    pass
            deadline = time.monotonic() + timeout
            for _conn, t in live:
                t.join(timeout=max(0.0, deadline - time.monotonic()))
            # stragglers (e.g. a result waiter mid-poll): force the socket
            # closed and give each thread a moment to unwind
            with self._conn_lock:
                stuck = list(self._conns.values())
            for conn, _t in stuck:
                try:
                    conn.close()
                except OSError:
                    pass
            for _conn, t in stuck:
                t.join(timeout=1.0)
        finally:
            if self.socket_path and os.path.exists(self.socket_path):
                try:
                    os.unlink(self.socket_path)
                except OSError:
                    pass

    # ----------------------------------------------------------- connection

    def _handle_conn(self, conn: socket.socket, cid: int) -> None:
        counters = getattr(self.scheduler, "counters", None)
        replay = wire.ReplayCache()
        try:
            try:
                faults.fault_point("serve.accept")
            except faults.FaultError as e:
                self._reply(conn, {"ok": False, "error": str(e)})
                return
            try:
                buf = b""
                last_activity = time.monotonic()
                while True:
                    # a partial frame in the buffer puts the connection on
                    # the (short) read deadline — a half-frame-then-stall
                    # peer must finish its line or lose the slot; an empty
                    # buffer is merely idle and gets the longer deadline
                    limit = self.read_timeout_s if buf else self.idle_timeout_s
                    if limit > 0:
                        remaining = (last_activity + limit) - time.monotonic()
                        if remaining <= 0:
                            if counters is not None:
                                counters.add("conns_reaped")
                            self._reply(conn, {
                                "ok": False, "transport": True,
                                "reaped": True,
                                "error": "connection reaped "
                                         f"({'read' if buf else 'idle'} "
                                         "deadline exceeded)"})
                            return
                        conn.settimeout(remaining)
                    else:
                        conn.settimeout(None)  # deadline disabled
                    try:
                        chunk = conn.recv(65536)
                    except socket.timeout:
                        continue  # loop re-checks the deadline and reaps
                    if not chunk:
                        return
                    last_activity = time.monotonic()
                    buf += chunk
                    if len(buf) > MAX_LINE:
                        self._reply(conn, {"ok": False,
                                           "error": "request too large"})
                        return
                    while b"\n" in buf:
                        line, buf = buf.split(b"\n", 1)
                        if not line.strip():
                            continue
                        try:
                            req = json.loads(line)
                        except ValueError:
                            # an unparseable line IS a corrupted frame —
                            # the crc gate never got a chance.  Answer as
                            # retryable transport loss so the sender
                            # re-sends instead of giving up, then close
                            # (the stream offset can no longer be trusted)
                            if counters is not None:
                                counters.add("wire_crc_errors")
                            self._reply(conn, {
                                "ok": False, "transport": True,
                                "crc_error": True,
                                "error": "bad JSON (corrupted frame)"})
                            return
                        self._reply(conn, self._respond(req, replay, counters))
                        last_activity = time.monotonic()
            except (OSError, BrokenPipeError):
                pass  # client went away mid-exchange; nothing to clean up
        finally:
            conn.close()
            with self._conn_lock:
                self._conns.pop(cid, None)

    def _respond(self, req, replay: wire.ReplayCache, counters) -> dict:
        """Envelope gate around :meth:`_dispatch`: verify the crc of an
        enveloped request (a mismatch is answered as retryable transport
        loss, never dispatched), absorb duplicated frames from the
        per-connection seq replay cache, and seal replies to enveloped
        requests with their own seq echo + crc.  Legacy requests carry
        neither field and pass straight through untouched."""
        if not isinstance(req, dict):
            return {"ok": False, "error": "request must be a JSON object"}
        if not wire.verify(req):
            if counters is not None:
                counters.add("wire_crc_errors")
            return {"ok": False, "transport": True, "crc_error": True,
                    "error": "request failed its crc (corrupted in flight)"}
        seq = req.get("seq")
        if seq is not None:
            cached = replay.check(seq)
            if cached is not None:
                # a duplicated delivery of a frame already answered on
                # this connection: re-answer, never re-dispatch
                if counters is not None:
                    counters.add("wire_dup_dropped")
                return cached
            req = {k: v for k, v in req.items() if k not in ("seq", "crc")}
        reply = self._dispatch(req)
        if seq is not None:
            reply = wire.seal(reply, seq)
            replay.remember(seq, reply)
        return reply

    @staticmethod
    def _reply(conn: socket.socket, doc: dict) -> None:
        try:
            conn.sendall(json.dumps(doc).encode() + b"\n")
        except (OSError, BrokenPipeError):
            pass

    # ------------------------------------------------------------- dispatch

    def _lookup(self, req: dict):
        return self.scheduler.lookup(job_id=req.get("job_id"),
                                     key=req.get("key"))

    @staticmethod
    def _expired_reply(info: dict) -> dict:
        return {"ok": True, "job": {
            "job_id": info["job_id"], "key": info["key"], "state": "expired",
            "final_state": info["final_state"],
            "outputs": {"base": info["base"]},
            "error": f"result expired; outputs on disk at {info['base']}",
        }}

    def _wait_result(self, req: dict) -> dict:
        """Blocking result with shutdown awareness: the scheduler wait runs
        in bounded slices so a close() never wedges behind a parked waiter
        — the client sees ``shutdown: true`` and retries after restart."""
        found = self._lookup(req)
        if found is None:
            return {"ok": False, "error": "unknown job_id",
                    "unknown": True}
        kind, obj = found
        if kind == "expired":
            return self._expired_reply(obj)
        job = obj
        timeout = req.get("timeout")
        deadline = None if timeout is None else time.monotonic() + float(timeout)
        while job.state not in ("done", "failed", "quarantined"):
            if self._closed:
                return {"ok": False, "error": "server shutting down",
                        "shutdown": True}
            remaining = 0.5
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"job {job.id} still {job.state}")
            try:
                self.scheduler.wait(job.id, timeout=min(0.5, remaining))
            except TimeoutError:
                continue
            except KeyError:
                break  # evicted mid-wait: only terminal jobs evict
        return {"ok": True, "job": job.describe()}

    def _dispatch(self, req: dict) -> dict:
        if not isinstance(req, dict):
            return {"ok": False, "error": "request must be a JSON object"}
        op = req.get("op")
        try:
            if "epoch" in req and op in ("submit", "status", "result",
                                         "drain", "release"):
                # fleet-HA fencing: a router-forwarded request carries the
                # sender's ring-view epoch; a stale (pre-takeover) epoch
                # is rejected so a zombie router cannot double-dispatch.
                # healthz/metrics stay unfenced — observability must keep
                # answering even to a demoted router.
                self.scheduler.fence(req.get("epoch"), req.get("router"))
            if op == "submit":
                attempts = req.get("attempts")
                job, created = self.scheduler.submit_info(
                    req.get("spec") or {}, trace=req.get("trace"),
                    fleet_attempts=int(attempts) if attempts else None)
                # the ack echoes the accepted job's durable wire trace
                # context so the submitter (client or router) can link
                # follow-up spans to the ack span it just caused
                return {"ok": True, "job_id": job.id, "state": job.state,
                        "key": job.key, "duplicate": not created,
                        "trace": job.trace_ctx}
            if op == "status":
                found = self._lookup(req)
                if found is None:
                    return {"ok": False, "error": "unknown job_id",
                            "unknown": True}
                kind, obj = found
                if kind == "expired":
                    return self._expired_reply(obj)
                return {"ok": True, "job": obj.describe()}
            if op == "result":
                return self._wait_result(req)
            if op == "healthz":
                return {"ok": True, "health": self.scheduler.healthz()}
            if op == "metrics":
                doc = self.scheduler.metrics()
                if req.get("format") == "prometheus":
                    # text exposition for scrapers; same doc, rendered
                    return {"ok": True,
                            "prometheus": render_prometheus(doc)}
                return {"ok": True, "metrics": doc}
            if op == "drain":
                self.scheduler.drain(timeout=req.get("timeout"))
                return {"ok": True, "drained": True}
            if op == "release":
                # lift a key's quarantine (``cct route --release`` lands
                # here through the router); fenced like submit — only
                # the live epoch's router may re-open a poison key
                out = self.scheduler.release_quarantine(
                    str(req.get("key") or ""))
                return {"ok": True, **out}
            if op == "trace":
                # fleet trace collection: hand over this process's span
                # buffer (flushed shard when CCT_TRACE_DIR is set, else
                # the in-memory ring).  Unfenced like healthz/metrics —
                # a post-mortem must be collectable through a demoted
                # router too.
                return {"ok": True, "trace": {
                    "node": self.scheduler.node, "pid": os.getpid(),
                    "events": obs_trace.collect_events()}}
            if op == "prof":
                # profiler collection: this process's sampled-stack
                # shard lines + wall attribution.  Unfenced like
                # healthz/metrics/trace — perf postmortems must stay
                # collectable through a demoted router.
                return {"ok": True,
                        "prof": obs_prof.collect(node=self.scheduler.node)}
            if op == "history":
                # telemetry-history collection: this process's durable
                # NDJSON shard read back.  Unfenced like trace/prof —
                # "what changed over the last hour" must stay
                # answerable through a demoted router.
                return {"ok": True,
                        "history": obs_history.collect(
                            node=self.scheduler.node)}
            return {"ok": False, "error": f"unknown op {op!r}"}
        except RouterFenced as e:
            return {"ok": False, "error": str(e), "fenced": True,
                    "epoch": e.epoch}
        except DeadlineShed as e:
            return {"ok": False, "error": str(e), "refused": True,
                    "shed": True}
        except QuotaRefused as e:
            return {"ok": False, "error": str(e), "refused": True,
                    "quota": True}
        except QuarantineRefused as e:
            # poison containment: the key is quarantined (budget
            # exhausted / breaker open) — a typed refusal the client
            # must NOT retry (retrying is what poison jobs weaponize)
            return {"ok": False, "error": str(e), "refused": True,
                    "quarantined": True, "reason": e.reason or str(e),
                    "key": e.key}
        except BrownoutRefused as e:
            # resource exhaustion, not load: admissions refuse while the
            # daemon stays up for polls and cache hits
            return {"ok": False, "error": str(e), "refused": True,
                    "brownout": True}
        except AdmissionRefused as e:
            return {"ok": False, "error": str(e), "refused": True}
        except ValueError as e:
            # malformed spec — unknown qos class, unknown vote policy,
            # missing required fields: a typed bad_request the client
            # must fix, never retry (the spec hashes identically again)
            return {"ok": False, "error": str(e), "refused": True,
                    "bad_request": True}
        except TimeoutError as e:
            return {"ok": False, "error": str(e), "timeout": True}
        except Exception as e:  # surface, never kill the daemon
            print(f"WARNING: serve op {op!r} failed: {e}",
                  file=sys.stderr, flush=True)
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}


# -------------------------------------------------------------- lifecycle

def request_shutdown(server: ServeServer, scheduler: Scheduler,
                     journal=None) -> None:
    """Initiate a supervised shutdown: stop admission, journal a ``drain``
    marker, break the accept loop.  The serve CLI then runs the bounded
    drain and exits.  Unit-testable outside a real signal delivery; the
    ``serve.sigterm`` fault site degrades it to an immediate stop (queued
    jobs stay journaled, so even the degraded path loses nothing)."""
    try:
        faults.fault_point("serve.sigterm")
    except faults.FaultError as e:
        print(f"WARNING: serve shutdown handler fault ({e}); stopping "
              "immediately — queued jobs stay journaled for replay",
              file=sys.stderr, flush=True)
        server.shutdown()
        return
    scheduler.stop_admission()
    if journal is not None:
        try:
            n = journal.append_marker("drain")
            scheduler.counters.add("journal_bytes", n)
        except Exception as e:
            print(f"WARNING: drain marker write failed ({e})",
                  file=sys.stderr, flush=True)
    server.shutdown()


def install_signal_handlers(server: ServeServer, scheduler: Scheduler,
                            journal=None) -> None:
    """SIGTERM/SIGINT -> graceful drain.  Closing the listening socket
    makes the (PEP 475 auto-retrying) ``accept`` call in serve_forever
    return, handing control back to the CLI's drain/exit sequence."""
    def _handler(signum, _frame):
        print(f"serve: caught signal {signum}; draining",
              file=sys.stderr, flush=True)
        request_shutdown(server, scheduler, journal)

    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, _handler)
