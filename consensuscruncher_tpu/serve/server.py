"""The daemon's wire front-end: newline-delimited JSON over a socket.

One request per line, one JSON reply per line; a connection may carry any
number of requests (the blocking ``result`` op holds its line open until
the job finishes).  Transport is either a unix socket (``socket_path``) or
localhost TCP — both are single-host by design: the daemon is a *device
host* process, remote fan-in belongs to a reverse proxy.

Ops (all replies carry ``"ok"``):

  {"op": "submit", "spec": {...}}       -> {"ok": true, "job_id": N}
  {"op": "status", "job_id": N}         -> {"ok": true, "job": {...}}
  {"op": "result", "job_id": N,
   "timeout": seconds|null}             -> blocks; {"ok": true, "job": {...}}
  {"op": "healthz"}                     -> {"ok": true, "health": {...}}
  {"op": "metrics"}                     -> {"ok": true, "metrics": {...}}
  {"op": "drain", "timeout": s|null}    -> blocks; {"ok": true, "drained": true}

Errors reply ``{"ok": false, "error": "..."}`` and keep the connection
usable; a malformed line closes the connection.  The ``serve.accept``
fault site fires per accepted connection (chaos tests turn accept-path
failures into clean error replies, never daemon death).
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading

from consensuscruncher_tpu.serve.scheduler import AdmissionRefused, Scheduler
from consensuscruncher_tpu.utils import faults

MAX_LINE = 1 << 20  # 1 MiB per request line; specs are tiny


class ServeServer:
    """Accept loop + per-connection handler threads over a Scheduler."""

    def __init__(self, scheduler: Scheduler, host: str = "127.0.0.1",
                 port: int = 0, socket_path: str | None = None):
        self.scheduler = scheduler
        self.socket_path = socket_path
        if socket_path:
            if os.path.exists(socket_path):
                os.unlink(socket_path)  # stale socket from a dead daemon
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.bind(socket_path)
            self.address: object = socket_path
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._sock.bind((host, port))
            self.address = self._sock.getsockname()  # (host, real port)
        self._sock.listen(16)
        self._closed = False
        self._accept_thread: threading.Thread | None = None

    def describe(self) -> str:
        if self.socket_path:
            return f"unix:{self.socket_path}"
        host, port = self.address
        return f"tcp:{host}:{port}"

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        """Run the accept loop on a background thread (tests, embedding)."""
        self._accept_thread = threading.Thread(
            target=self.serve_forever, name="serve-accept", daemon=True)
        self._accept_thread.start()

    def serve_forever(self) -> None:
        while not self._closed:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return  # socket closed under us: clean shutdown
            t = threading.Thread(
                target=self._handle_conn, args=(conn,), daemon=True)
            t.start()

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.close()
        finally:
            if self.socket_path and os.path.exists(self.socket_path):
                try:
                    os.unlink(self.socket_path)
                except OSError:
                    pass

    # ----------------------------------------------------------- connection

    def _handle_conn(self, conn: socket.socket) -> None:
        try:
            faults.fault_point("serve.accept")
        except faults.FaultError as e:
            self._reply(conn, {"ok": False, "error": str(e)})
            conn.close()
            return
        try:
            buf = b""
            while True:
                chunk = conn.recv(65536)
                if not chunk:
                    return
                buf += chunk
                if len(buf) > MAX_LINE:
                    self._reply(conn, {"ok": False, "error": "request too large"})
                    return
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        continue
                    try:
                        req = json.loads(line)
                    except ValueError:
                        self._reply(conn, {"ok": False, "error": "bad JSON"})
                        return
                    self._reply(conn, self._dispatch(req))
        except (OSError, BrokenPipeError):
            pass  # client went away mid-exchange; nothing to clean up
        finally:
            conn.close()

    @staticmethod
    def _reply(conn: socket.socket, doc: dict) -> None:
        try:
            conn.sendall(json.dumps(doc).encode() + b"\n")
        except (OSError, BrokenPipeError):
            pass

    # ------------------------------------------------------------- dispatch

    def _dispatch(self, req: dict) -> dict:
        if not isinstance(req, dict):
            return {"ok": False, "error": "request must be a JSON object"}
        op = req.get("op")
        try:
            if op == "submit":
                job = self.scheduler.submit(req.get("spec") or {})
                return {"ok": True, "job_id": job.id, "state": job.state}
            if op == "status":
                job = self.scheduler.get(req.get("job_id", -1))
                if job is None:
                    return {"ok": False, "error": "unknown job_id"}
                return {"ok": True, "job": job.describe()}
            if op == "result":
                if self.scheduler.get(req.get("job_id", -1)) is None:
                    return {"ok": False, "error": "unknown job_id"}
                job = self.scheduler.wait(req["job_id"], timeout=req.get("timeout"))
                return {"ok": True, "job": job.describe()}
            if op == "healthz":
                return {"ok": True, "health": self.scheduler.healthz()}
            if op == "metrics":
                return {"ok": True, "metrics": self.scheduler.metrics()}
            if op == "drain":
                self.scheduler.drain(timeout=req.get("timeout"))
                return {"ok": True, "drained": True}
            return {"ok": False, "error": f"unknown op {op!r}"}
        except AdmissionRefused as e:
            return {"ok": False, "error": str(e), "refused": True}
        except TimeoutError as e:
            return {"ok": False, "error": str(e), "timeout": True}
        except Exception as e:  # surface, never kill the daemon
            print(f"WARNING: serve op {op!r} failed: {e}",
                  file=sys.stderr, flush=True)
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}
