"""Blocking client for the serve daemon (used by ``submit`` and tests).

One connection per request keeps the client stateless and retry-friendly;
the blocking ``result`` op simply holds its connection open until the
daemon replies (the server waits on the scheduler's condition, not the
socket, so a long job costs one idle descriptor, not a busy loop).
"""

from __future__ import annotations

import json
import socket


class ServeClientError(RuntimeError):
    """The daemon replied ``ok: false`` (error text attached)."""

    def __init__(self, message: str, reply: dict | None = None):
        super().__init__(message)
        self.reply = reply or {}


class ServeClient:
    """``address`` is a unix socket path (str) or a ``(host, port)`` pair."""

    def __init__(self, address, connect_timeout: float = 10.0):
        self.address = address
        self.connect_timeout = connect_timeout

    def _request(self, doc: dict, timeout: float | None = None) -> dict:
        if isinstance(self.address, str):
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        else:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            sock.settimeout(self.connect_timeout)
            sock.connect(self.address if isinstance(self.address, str)
                         else tuple(self.address))
            # after connect, the read deadline is the op's own timeout
            sock.settimeout(timeout)
            sock.sendall(json.dumps(doc).encode() + b"\n")
            buf = b""
            while b"\n" not in buf:
                chunk = sock.recv(65536)
                if not chunk:
                    raise ServeClientError("daemon closed the connection")
                buf += chunk
            reply = json.loads(buf.split(b"\n", 1)[0])
        finally:
            sock.close()
        if not reply.get("ok"):
            raise ServeClientError(reply.get("error", "daemon error"), reply)
        return reply

    # ----------------------------------------------------------------- ops

    def submit(self, spec: dict) -> int:
        return int(self._request({"op": "submit", "spec": spec})["job_id"])

    def status(self, job_id: int) -> dict:
        return self._request({"op": "status", "job_id": job_id})["job"]

    def result(self, job_id: int, timeout: float | None = None) -> dict:
        """Block until the job is done/failed; returns the job description.
        ``timeout`` bounds both the server-side wait and the socket read."""
        sock_timeout = None if timeout is None else timeout + 10.0
        return self._request(
            {"op": "result", "job_id": job_id, "timeout": timeout},
            timeout=sock_timeout,
        )["job"]

    def healthz(self) -> dict:
        return self._request({"op": "healthz"})["health"]

    def metrics(self) -> dict:
        return self._request({"op": "metrics"})["metrics"]

    def drain(self, timeout: float | None = None) -> None:
        sock_timeout = None if timeout is None else timeout + 10.0
        self._request({"op": "drain", "timeout": timeout}, timeout=sock_timeout)

    def run(self, spec: dict, timeout: float | None = None) -> dict:
        """submit + blocking result; raises on a failed job."""
        job = self.result(self.submit(spec), timeout=timeout)
        if job["state"] != "done":
            raise ServeClientError(
                f"job {job['job_id']} {job['state']}: {job.get('error')}", job)
        return job
