"""Blocking client for the serve daemon (used by ``submit`` and tests).

One connection per request keeps the client stateless and retry-friendly;
the blocking ``result`` op simply holds its connection open until the
daemon replies (the server waits on the scheduler's condition, not the
socket, so a long job costs one idle descriptor, not a busy loop).

Restart-invisible polling: every transport failure (connection refused
while the supervisor restarts the daemon, connection reset by a crash,
``shutdown: true`` replies during a drain) is retried with capped
exponential backoff, and ``status``/``result`` can poll by the submit
reply's **idempotency key** instead of the job id.  The key is derived
from the spec, so it resolves against the restarted daemon's journal-
replayed jobs; resubmitting the same spec is also safe (the daemon
dedupes on the key).  A polling client therefore survives a daemon
kill/restart without ever learning it happened.
"""

from __future__ import annotations

import itertools
import json
import os
import socket
import sys
import time

from consensuscruncher_tpu.obs import trace as obs_trace
from consensuscruncher_tpu.serve import wire
from consensuscruncher_tpu.utils import faults, netchaos


class ServeClientError(RuntimeError):
    """The daemon replied ``ok: false`` (error text attached)."""

    def __init__(self, message: str, reply: dict | None = None):
        super().__init__(message)
        self.reply = reply or {}


class JobQuarantined(ServeClientError):
    """The fleet quarantined this job's key (poison containment: fleet
    retry budget exhausted, or the fault-domain breaker is open).  This
    is a *verdict*, not a transient — it is never retried (retry loops
    are exactly what poison jobs weaponize); an operator lifts it with
    ``cct route --release KEY``."""

    def __init__(self, message: str, reply: dict | None = None):
        super().__init__(message, reply)
        self.reason = str((reply or {}).get("reason") or message)
        self.key = (reply or {}).get("key")


class ServeClient:
    """``address`` is a unix socket path (str), a ``(host, port)`` pair,
    or a *list* of such addresses — an HA router pair's front doors.  The
    client talks to the first; on a retryable failure it rotates to the
    next (a standby router answers ``standby: true, busy: true``, which
    is retryable by design, so rotation finds the active automatically —
    the client survives a router failover without configuration).

    ``retries`` transport-level reconnect attempts (default
    ``CCT_SERVE_CLIENT_RETRIES`` or 5) with ``backoff_delay``-capped
    sleeps between them; every op is idempotent so a blind resend is safe.

    ``router`` (optional) is the fleet router's address — or a list of
    router addresses — for clients polling a *worker* directly: the key's
    current owner is re-resolved through a router when the worker stops
    answering, and the resolution itself walks the router list on
    **every** reconnect attempt, so a router failover happening in the
    middle of the client's retry loop is survived too (the responsive
    router is promoted to the front of the list).
    """

    def __init__(self, address, connect_timeout: float = 10.0,
                 retries: int | None = None,
                 retry_base_s: float | None = None,
                 router=None, counters=None):
        self.addresses = self._address_list(address)
        if not self.addresses:
            raise ValueError("serve client: empty address")
        self.address = self.addresses[0]
        self.routers = self._address_list(router)
        self.connect_timeout = connect_timeout
        if retries is None:
            retries = int(os.environ.get("CCT_SERVE_CLIENT_RETRIES", "5"))
        self.retries = max(0, int(retries))
        if retry_base_s is None:
            retry_base_s = float(os.environ.get("CCT_RETRY_BASE_S", "0.5"))
        self.retry_base_s = float(retry_base_s)
        # optional Counters sink (the router passes its own) for wire
        # health: crc mismatches on replies, request deadline hits
        self.counters = counters
        # per-client monotone seq for the wire envelope; next() is atomic,
        # so a client shared across handler threads stays collision-free
        self._seq = itertools.count(1)

    @property
    def router(self):
        """First configured router address (back-compat accessor)."""
        return self.routers[0] if self.routers else None

    @staticmethod
    def _address_list(value) -> list:
        """Normalize an address argument into a list of addresses.  A
        tuple, a string, or a 2-list ``[host, port]`` is ONE address;
        any other list is many (each element normalized likewise)."""
        if value is None:
            return []
        if isinstance(value, (str, tuple)):
            return [value]
        if isinstance(value, list):
            if len(value) == 2 and isinstance(value[0], str) \
                    and isinstance(value[1], int):
                return [(value[0], int(value[1]))]
            out = []
            for v in value:
                if isinstance(v, list) and len(v) == 2:
                    out.append((v[0], int(v[1])))
                else:
                    out.append(v)
            return out
        return [value]

    def _rotate_address(self) -> None:
        """Point at the next configured address (wrapping); a re-resolved
        off-list worker address simply falls back to the first router."""
        if len(self.addresses) < 2 and self.address in self.addresses:
            return
        try:
            i = self.addresses.index(self.address)
        except ValueError:
            i = -1
        nxt = self.addresses[(i + 1) % len(self.addresses)]
        if nxt != self.address:
            print(f"WARNING: serve client: rotating to {nxt}",
                  file=sys.stderr, flush=True)
            self.address = nxt

    def _request_once(self, doc: dict, timeout: float | None = None) -> dict:
        if isinstance(self.address, str):
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        else:
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        # every fleet socket is opened here (client->router, router->worker,
        # standby probes), so this one wrap point puts the whole fleet's
        # traffic behind the netchaos fault layer when a spec is armed
        sock = netchaos.maybe_wrap(sock, self.address)
        try:
            sock.settimeout(self.connect_timeout)
            sock.connect(self.address if isinstance(self.address, str)
                         else tuple(self.address))
            # after connect, the read deadline is the op's own timeout
            sock.settimeout(timeout)
            sealed = wire.seal(doc, next(self._seq))
            sock.sendall(json.dumps(sealed).encode() + b"\n")
            buf = b""
            while b"\n" not in buf:
                chunk = sock.recv(65536)
                if not chunk:
                    # a crash/restart mid-request: retryable transport loss
                    raise ServeClientError("daemon closed the connection",
                                           {"transport": True})
                buf += chunk
            reply = json.loads(buf.split(b"\n", 1)[0])
        finally:
            sock.close()
        if not wire.verify(reply):
            # a corrupted reply is transport loss, not data: drop it and
            # let the retry loop re-fetch (every op is idempotent by key)
            if self.counters is not None:
                self.counters.add("wire_crc_errors")
            raise ServeClientError("reply failed its crc (corrupted in "
                                   "flight)", {"transport": True,
                                               "crc_error": True})
        if not reply.get("ok"):
            if reply.get("quarantined"):
                raise JobQuarantined(
                    reply.get("error", "job quarantined"), reply)
            raise ServeClientError(reply.get("error", "daemon error"), reply)
        return reply

    @staticmethod
    def _retryable(exc: Exception) -> bool:
        if isinstance(exc, ServeClientError):
            # connection died mid-exchange, or the daemon is drain-restarting
            return bool(exc.reply.get("transport") or exc.reply.get("shutdown")
                        or exc.reply.get("busy"))
        # refused/reset while the supervisor restarts the daemon, read
        # timeouts against a wedged process, missing unix socket, ...
        return isinstance(exc, OSError)

    def _reresolve(self, doc: dict) -> bool:
        """Ask a router where this request's key lives *now* and repoint
        ``self.address`` there.  Walks the whole router list on EVERY
        attempt — a failover mid-retry just means the standby-turned-
        active answers instead; the responsive router is promoted to the
        front so later attempts hit it first.  Best-effort: all routers
        unreachable (or a keyless request) keeps the current address —
        the normal retry loop still covers a same-address daemon restart.
        Returns True when a router answered."""
        key = doc.get("key")
        if not key:
            return False
        for r in list(self.routers):
            try:
                reply = ServeClient(r, retries=0).request(
                    {"op": "locate", "key": key}, timeout=10.0)
            except Exception as e:
                print(f"WARNING: serve client: router {r} locate failed "
                      f"({e}); trying next", file=sys.stderr, flush=True)
                continue
            if r != self.routers[0]:
                self.routers.remove(r)
                self.routers.insert(0, r)
            address = reply.get("address")
            if isinstance(address, list):
                address = (address[0], int(address[1]))
            if address and address != self.address:
                print(f"WARNING: serve client: key {key} now owned by "
                      f"{reply.get('node')} at {address}; re-pointing",
                      file=sys.stderr, flush=True)
                self.address = address
            return True
        return False

    def _request(self, doc: dict, timeout: float | None = None) -> dict:
        if "trace" not in doc:
            # wire trace propagation: stamp the caller's open span as the
            # message's causal context (client -> router -> worker).  The
            # router's forward path flows through here too, so its route
            # span rides to the worker with no extra plumbing.  Computed
            # once: a retried resend continues the same causal chain.
            ctx = obs_trace.wire_context()
            if ctx is not None:
                doc = dict(doc, trace=ctx)
        attempts = self.retries + 1
        for attempt in range(attempts):
            try:
                return self._request_once(doc, timeout)
            except Exception as e:
                if isinstance(e, (socket.timeout, TimeoutError)) \
                        and self.counters is not None:
                    self.counters.add("wire_timeouts")
                if attempt + 1 >= attempts or not self._retryable(e):
                    raise
                delay = faults.backoff_delay(attempt + 1, self.retry_base_s, 5.0)
                print(f"WARNING: serve client: {e}; reconnecting in "
                      f"{delay:.1f}s (attempt {attempt + 2}/{attempts})",
                      file=sys.stderr, flush=True)
                time.sleep(delay)
                repointed = False
                if self.routers:
                    repointed = self._reresolve(doc)
                if not repointed and len(self.addresses) > 1:
                    self._rotate_address()
        raise AssertionError("unreachable")

    def request(self, doc: dict, timeout: float | None = None) -> dict:
        """One raw NDJSON request/reply with the full retry + router
        re-resolution discipline (the fleet router forwards through
        this; ops below are typed conveniences over it)."""
        return self._request(doc, timeout)

    # ----------------------------------------------------------------- ops

    @staticmethod
    def _ref(job_id, key) -> dict:
        if key is not None:
            return {"key": key}
        return {"job_id": job_id}

    def submit(self, spec: dict) -> int:
        return int(self.submit_full(spec)["job_id"])

    def submit_full(self, spec: dict, trace: dict | None = None) -> dict:
        """Submit and return the full reply (``job_id``, ``key``,
        ``duplicate``) — poll by ``key`` to survive daemon restarts.
        ``trace`` is the wire trace context a *logical* re-submit should
        continue (the ``trace`` field of the original ack): the dedup key
        makes the job the same job, and passing its context back keeps
        the causal timeline one tree instead of minting a fresh trace."""
        doc = {"op": "submit", "spec": spec}
        if isinstance(trace, dict):
            doc["trace"] = trace
        return self._request(doc)

    def submit_nowait(self, spec: dict) -> dict:
        """Submit without raising on admission refusal: a refused reply
        (queue full, deadline shed, tenant quota) comes back as the raw
        reply dict with ``ok: false`` plus ``refused``/``shed``/``quota``
        flags.  Under deliberate overload — the loadgen's open-loop
        traffic — refusal is data, not an error."""
        try:
            return self.submit_full(spec)
        except ServeClientError as e:
            if e.reply.get("refused"):
                return dict(e.reply)
            raise

    def status(self, job_id: int | None = None, *, key: str | None = None) -> dict:
        return self._request({"op": "status", **self._ref(job_id, key)})["job"]

    def result(self, job_id: int | None = None, timeout: float | None = None,
               *, key: str | None = None) -> dict:
        """Block until the job is done/failed; returns the job description.
        ``timeout`` bounds both the server-side wait and the socket read."""
        sock_timeout = None if timeout is None else timeout + 10.0
        return self._request(
            {"op": "result", "timeout": timeout, **self._ref(job_id, key)},
            timeout=sock_timeout,
        )["job"]

    def healthz(self) -> dict:
        return self._request({"op": "healthz"})["health"]

    def metrics(self) -> dict:
        return self._request({"op": "metrics"})["metrics"]

    def metrics_prometheus(self) -> str:
        """The same metrics doc rendered as Prometheus text exposition."""
        return self._request(
            {"op": "metrics", "format": "prometheus"})["prometheus"]

    def drain(self, timeout: float | None = None) -> None:
        sock_timeout = None if timeout is None else timeout + 10.0
        self._request({"op": "drain", "timeout": timeout}, timeout=sock_timeout)

    def run(self, spec: dict, timeout: float | None = None) -> dict:
        """submit + blocking result; raises on a failed job.  Polls by the
        idempotency key, so the job is found again even if the daemon
        restarted between the submit and the result."""
        sub = self.submit_full(spec)
        job = self.result(timeout=timeout, key=sub["key"])
        if job["state"] == "quarantined":
            raise JobQuarantined(
                f"job {job['job_id']} quarantined: {job.get('error')}",
                {"quarantined": True, "reason": job.get("error"),
                 "key": job.get("key")})
        if job["state"] != "done":
            raise ServeClientError(
                f"job {job['job_id']} {job['state']}: {job.get('error')}", job)
        return job
