"""Daemon supervisor: restart a crashed serve daemon with capped backoff.

``ConsensusCruncher.py serve --supervise`` runs this loop instead of the
daemon itself: the daemon runs as a child process, and when it dies with
a nonzero status (segfault, OOM-kill, kill -9, an injected ``exit``
fault) the supervisor respawns it after a capped exponential backoff.
Combined with the write-ahead journal this closes the crash loop: the
restarted daemon replays the journal, re-enqueues every acknowledged job,
and finishes each one byte-identically through ``--resume`` — a client
polling by idempotency key never notices.

Policy:

- exit 0 means the daemon drained cleanly (SIGTERM path): the supervisor
  exits 0 too, it never restarts a *deliberate* shutdown;
- SIGTERM/SIGINT to the supervisor forward to the child and stop the
  restart loop (the child drains, both exit);
- crashes restart after ``backoff_delay(streak, base, cap)``; a child
  that stayed up ``healthy_s`` before dying resets the streak, so a
  once-a-day crasher restarts promptly while a crash loop backs off;
- ``max_restarts`` (``CCT_SERVE_MAX_RESTARTS``, default 10) bounds the
  total restarts, after which the supervisor gives up with the child's
  last exit status — a persistent crash must page a human, not spin.

The loop is dependency-injectable (``spawn``/``sleep``) so the unit tests
drive it with fake children and virtual time.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

from consensuscruncher_tpu.obs import flight as obs_flight
from consensuscruncher_tpu.utils import faults

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def child_command(serve_argv: list[str]) -> list[str]:
    """The daemon child's command line: this interpreter running the CLI
    with ``serve_argv`` (the serve subcommand flags, minus --supervise).
    sys.path bootstrap instead of ``-m``: the package is run from a repo
    checkout, not necessarily an installed distribution."""
    boot = (
        "import sys; "
        f"sys.path.insert(0, {_REPO_ROOT!r}); "
        "from consensuscruncher_tpu.cli import main; "
        "sys.exit(main(sys.argv[1:]))"
    )
    return [sys.executable, "-c", boot] + list(serve_argv)


def run_supervised(cmd: list[str], max_restarts: int | None = None,
                   base_s: float | None = None, cap_s: float = 30.0,
                   healthy_s: float = 30.0, spawn=None, sleep=time.sleep) -> int:
    """Spawn ``cmd`` and keep it alive (see module docstring).  Returns the
    final exit status: 0 for a clean drain, the child's last nonzero
    status once the restart budget is exhausted."""
    if spawn is None:
        spawn = subprocess.Popen
    if max_restarts is None:
        max_restarts = int(os.environ.get("CCT_SERVE_MAX_RESTARTS", "10"))
    if base_s is None:
        base_s = float(os.environ.get("CCT_RETRY_BASE_S", "0.5"))

    state = {"child": None, "stop": False}

    def _forward(signum, _frame):
        state["stop"] = True
        child = state["child"]
        if child is not None and child.poll() is None:
            try:
                child.send_signal(signal.SIGTERM)  # child drains + exits 0
            except OSError:
                pass

    previous = {}
    try:
        for sig in (signal.SIGTERM, signal.SIGINT):
            previous[sig] = signal.signal(sig, _forward)
    except ValueError:
        pass  # not the main thread (embedded/test use): no forwarding

    try:
        restarts = 0
        streak = 0
        while True:
            started = time.monotonic()
            child = state["child"] = spawn(cmd)
            print(f"supervise: daemon started (pid {child.pid})",
                  file=sys.stderr, flush=True)
            rc = child.wait()
            alive_s = time.monotonic() - started
            if state["stop"] or rc == 0:
                print(f"supervise: daemon exited rc={rc}; done",
                      file=sys.stderr, flush=True)
                return int(rc or 0)
            if alive_s >= healthy_s:
                streak = 0  # a long healthy run restarts from the base delay
            restarts += 1
            streak += 1
            # the supervisor outlives the crash, so its flight ring is the
            # one place the restart history accumulates across child lives
            obs_flight.record("child_crash", rc=int(rc),
                              alive_s=round(alive_s, 3), restart=restarts)
            if restarts > max_restarts:
                print(f"ERROR: daemon crashed rc={rc}; restart budget "
                      f"({max_restarts}) exhausted — giving up",
                      file=sys.stderr, flush=True)
                return int(rc) if rc else 1
            delay = faults.backoff_delay(streak, base_s, cap_s)
            print(f"WARNING: daemon crashed rc={rc} after {alive_s:.1f}s; "
                  f"restart {restarts}/{max_restarts} in {delay:.2f}s "
                  "(journal replay will re-enqueue accepted jobs)",
                  file=sys.stderr, flush=True)
            sleep(delay)
    finally:
        for sig, handler in previous.items():
            try:
                signal.signal(sig, handler)
            except ValueError:
                pass
