"""Write-ahead job journal: the daemon's crash-durability spine.

Every accepted job appends one fsync'd NDJSON record BEFORE the submit
reply leaves the daemon, and appends again on every lifecycle transition
(``accepted -> dispatched -> done | failed``).  A crash — SIGKILL, OOM,
power loss — therefore never loses an acknowledged job: on startup the
scheduler replays the journal and re-enqueues every job not provably
terminal.  Replay is **exactly-once at the output level** even though a
job may *run* more than once, because each replayed job finishes through
the per-job manifest ``--resume`` path: stages whose atomically-committed
outputs are intact are skipped, and the rest re-run byte-identically
(PR 1's commit_file discipline guarantees no partial output exists to
resume over).

Record format (one JSON object per line, ``sort_keys`` + compact
separators so the bytes are deterministic):

  {"deadline_s": null, "id": 3, "key": "9c0f...", "rec": "job",
   "spec": {...}, "state": "accepted", "v": 1}
  {"id": 3, "rec": "job", "state": "dispatched", "v": 1}
  {"id": 3, "outputs": {...}, "rec": "job", "state": "done",
   "v": 1, "wall_s": 4.21}
  {"kind": "drain", "rec": "marker", "v": 1}

Later records for an id merge over earlier ones, so transition records
carry only the delta.  The ``drain`` marker distinguishes a clean
SIGTERM shutdown from a crash in post-mortem reads (replay semantics are
identical either way — only what the journal *proves* matters).

Durability mechanics:

- appends go through a single pre-opened ``O_APPEND`` fd with
  ``os.fsync`` after every record — a submit is acknowledged only once
  its record is on disk;
- rotation (checkpointing) writes a compacted snapshot to a temp file
  and swaps it in via ``manifest.commit_file`` (fsync + rename +
  dir-fsync), the same atomic-commit primitive the stage writers use;
- replay tolerates a torn final record (a crash mid-append leaves a
  truncated last line): it is logged and skipped, never fatal.  A torn
  *accepted* record means the submit reply cannot have been sent, so
  dropping it is correct, not lossy.

Fault sites: ``serve.journal_write`` (append path — an armed fault makes
the submit refuse instead of acknowledging an unjournaled job) and
``serve.journal_replay`` (per-record replay — a corrupt record is
skipped and logged, the rest of the journal still recovers).
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import tempfile

from consensuscruncher_tpu import __version__
from consensuscruncher_tpu.obs import trace as obs_trace
from consensuscruncher_tpu.serve import wire
from consensuscruncher_tpu.utils import faults, sanitize
from consensuscruncher_tpu.utils.manifest import commit_file

#: Spec fields that define a job's identity for idempotent resubmit.
#: ``deadline_s`` is deliberately excluded: resubmitting the same work
#: with a different deadline must still dedupe onto the running job.
#: ``tenant``/``qos`` ARE identity: two tenants submitting the same
#: paths are distinct jobs (quotas and SLO accounting must not cross),
#: but both fields are omitted when absent so pre-tenancy specs keep
#: their historical keys.  ``input_range`` is identity too: two shards
#: of the same input are different jobs with different outputs.
#: ``policy`` (the consensus vote policy, ISSUE 17) is identity — it
#: changes the output bytes — and is likewise omitted when absent, so a
#: default (majority) submit keeps its pre-policy key.
KEY_FIELDS = ("input", "output", "name", "cutoff", "qualscore", "scorrect",
              "max_mismatch", "bdelim", "compress_level", "tenant", "qos",
              "input_range", "policy")

#: The pre-v2 field set (no ``input_range``, no version pin) — kept so
#: :func:`legacy_idempotency_key` can resolve keys written by journals
#: from before the cache plane landed.
_LEGACY_KEY_FIELDS = ("input", "output", "name", "cutoff", "qualscore",
                      "scorrect", "max_mismatch", "bdelim", "compress_level",
                      "tenant", "qos")


def _key_over(spec: dict, fields, version: str | None) -> str:
    ident = {k: spec.get(k) for k in fields if spec.get(k) is not None}
    if version is not None:
        ident["__v"] = version
    blob = json.dumps(ident, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def idempotency_key(spec: dict) -> str:
    """Stable identity of a job spec: sha256 over the sorted-keys compact
    JSON of the normalized identity fields.  Two submits of the same work
    hash identically regardless of field order or extra protocol keys.

    v2: the digest pins the package ``__version__`` (a code upgrade can
    change output bytes, so a stale pre-upgrade key must not claim the
    post-upgrade job) and includes ``input_range`` (shards of one input
    are distinct jobs).  Journals written under v1 keys still replay:
    the scheduler's recovery path registers replayed jobs under BOTH the
    journaled key and the recomputed one (see ``Scheduler._recover``),
    and :func:`legacy_idempotency_key` reproduces the v1 digest."""
    return _key_over(spec, KEY_FIELDS, __version__)


def legacy_idempotency_key(spec: dict) -> str:
    """The pre-cache-plane (v1) key of a spec: no version pin, no
    ``input_range``.  Migration shim only — used at journal replay so a
    client still polling a v1 key resolves against the replayed job."""
    return _key_over(spec, _LEGACY_KEY_FIELDS, None)


def job_record(job_id: int, state: str, *, key: str | None = None,
               spec: dict | None = None, deadline_s: float | None = None,
               outputs: dict | None = None, error: str | None = None,
               wall_s: float | None = None,
               trace_id: str | None = None,
               trace: dict | None = None,
               qc: dict | None = None) -> dict:
    """One journal record; only non-None fields are written (transition
    records carry just the delta, replay merges by id).  ``trace_id`` is
    the correlation id minted at submit — journaled so a replayed job's
    spans stitch onto the pre-crash trace.  ``trace`` is the full wire
    trace context of the submit-ack span ({"trace_id", "span", "pid",
    "hop"}): persisted on the accepted record so a failover resubmit or
    journal adoption can emit a ``follows_from`` edge back to the dead
    owner's durable ack span — the trace survives kill -9 and replay.
    ``qc`` is the job's consensus-quality doc, journaled on the terminal
    record so QC attribution survives a restart too."""
    rec: dict = {"v": 1, "rec": "job", "id": int(job_id), "state": state}
    for field, value in (("key", key), ("spec", spec),
                         ("deadline_s", deadline_s), ("outputs", outputs),
                         ("error", error), ("wall_s", wall_s),
                         ("trace_id", trace_id), ("trace", trace),
                         ("qc", qc)):
        if value is not None:
            rec[field] = value
    return rec


def _encode(doc: dict) -> bytes:
    """One journal line: sorted-keys compact JSON plus a ``crc`` field
    (CRC32 over the record minus the crc itself — the wire envelope's
    canonical digest).  Replay verifies it, so a mid-file bit flip is
    skipped-and-counted instead of silently mis-replaying a job; legacy
    (v1) records without the field replay unchanged.  The record version
    bumps to 2 *because* of the crc: a v2 record missing the field means
    the crc itself was corrupted away, so replay must not mistake it for
    legacy (the crc cannot protect its own key name)."""
    out = {k: v for k, v in doc.items() if k != "crc"}
    out["v"] = 2
    out["crc"] = wire.crc_of(out)
    return json.dumps(out, sort_keys=True, separators=(",", ":")).encode() + b"\n"


class Journal:
    """Append-only fsync'd NDJSON journal with atomic checkpoint rotation.

    ``max_bytes`` is advisory: the owner checks :meth:`size` and calls
    :meth:`rotate` with a compacted snapshot when the file outgrows it.
    """

    def __init__(self, path: str, max_bytes: int | None = None):
        self.path = str(path)
        self.max_bytes = max_bytes
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        # lock-order asserted under CCT_SANITIZE=1; the fd + fsync happen
        # under it so concurrent appends cannot interleave half-records
        self._lock = sanitize.tracked_lock("journal.lock")
        self._fd = os.open(self.path, os.O_APPEND | os.O_CREAT | os.O_WRONLY,
                           0o644)
        self._size = os.fstat(self._fd).st_size

    # ------------------------------------------------------------- appends

    def append(self, doc: dict) -> int:
        """Append one record and fsync; returns bytes written.  Raises on
        any write/fsync failure (the caller must NOT acknowledge work whose
        record did not reach disk).  ``serve.journal_write`` fires here.
        The write+fsync is timed into the ``journal_fsync_s`` histogram —
        fsync latency is the admission path's floor."""
        faults.fault_point("serve.journal_write")
        line = _encode(doc)
        with obs_trace.span("journal.append", histogram="journal_fsync_s",
                            bytes=len(line)):
            with self._lock:
                if self._fd < 0:
                    raise OSError("journal is closed")
                os.write(self._fd, line)
                os.fsync(self._fd)
                self._size += len(line)
        return len(line)

    def append_job(self, job_id: int, state: str, **fields) -> int:
        return self.append(job_record(job_id, state, **fields))

    def append_marker(self, kind: str, **fields) -> int:
        rec = {"v": 1, "rec": "marker", "kind": kind}
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        return self.append(rec)

    def size(self) -> int:
        with self._lock:
            return self._size

    # ------------------------------------------------------------ rotation

    def rotate(self, records: list[dict]) -> None:
        """Checkpoint: replace the journal with a compacted snapshot (one
        full-state record per live job), committed atomically via the same
        fsync+rename+dir-fsync primitive as stage outputs.  A crash during
        rotation leaves either the old journal or the new one — never a
        mix, never a hole."""
        with self._lock:
            fd, tmp = tempfile.mkstemp(
                prefix=os.path.basename(self.path) + ".rot.",
                dir=os.path.dirname(os.path.abspath(self.path)))
            try:
                with os.fdopen(fd, "wb") as fh:
                    for rec in records:
                        fh.write(_encode(rec))
                commit_file(tmp, self.path)
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise
            # the O_APPEND fd still points at the renamed-away inode:
            # reopen on the new file
            os.close(self._fd)
            self._fd = os.open(self.path,
                               os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
            self._size = os.fstat(self._fd).st_size

    def close(self) -> None:
        with self._lock:
            if self._fd >= 0:
                os.close(self._fd)
                self._fd = -1


# ------------------------------------------------------------------ replay

def replay(path: str) -> tuple[dict[int, dict], dict]:
    """Read a journal into per-job merged state.

    Returns ``(jobs, info)``: ``jobs`` maps job id -> merged record (the
    union of every record for that id, later fields winning), ``info``
    carries ``{"records", "skipped", "torn_tail", "clean_drain",
    "adopted_by", "fence_epoch"}``.

    Four marker kinds carry fleet-HA state through replay:

    - an ``adopted`` tombstone (written by the router after it resubmits
      a dead member's non-terminal jobs to their ring successors) tags
      every job recorded *before* it with ``"adopted": True`` — a
      returning zombie worker must not re-run work that now lives
      elsewhere; ``info["adopted_by"]`` names the adopting router;
    - a ``fence`` marker persists the highest router epoch this worker
      has accepted, so a restart cannot be tricked into honoring a
      demoted router's forwards (``info["fence_epoch"]``);
    - a ``suspect`` marker (written BEFORE each dispatch) attributes an
      in-flight job to this node: ``info["suspects"]`` maps key -> the
      highest attempt ordinal journaled, so replay after kill -9 can
      blame the job that was running when the process died;
    - a ``quarantined`` marker folds last-wins per key into
      ``info["quarantined"]`` (key -> reason) — duplicates are
      idempotent, and a later ``released: true`` marker for the key
      removes it (the release re-opens the key for dispatch).

    Tolerant by design: a torn final record (crash mid-append) is logged
    and skipped; any other undecodable or fault-injected record is logged,
    counted in ``skipped``, and the rest of the journal still replays.
    ``serve.journal_replay`` fires per record.
    """
    jobs: dict[int, dict] = {}
    info = {"records": 0, "skipped": 0, "crc_skipped": 0, "torn_tail": False,
            "clean_drain": False, "adopted_by": None, "fence_epoch": None,
            "suspects": {}, "quarantined": {}}
    # schedule point: a zombie's replay racing an adopter's tombstone
    # append is exactly the interleaving the model checker explores here
    sanitize.yield_point("journal.replay")
    if not os.path.exists(path):
        return jobs, info
    with open(path, "rb") as fh:
        raw = fh.read()
    lines = raw.split(b"\n")
    # a well-formed journal ends with a newline -> last split element empty;
    # anything else is a torn tail from a crash mid-append
    tail = lines.pop() if lines else b""
    if tail.strip():
        lines.append(tail)
    for idx, line in enumerate(lines):
        if not line.strip():
            continue
        last = idx == len(lines) - 1
        try:
            faults.fault_point("serve.journal_replay")
            rec = json.loads(line)
            if not isinstance(rec, dict):
                raise ValueError("record is not an object")
        except (ValueError, faults.FaultError) as e:
            info["skipped"] += 1
            if last and isinstance(e, ValueError) and line == tail:
                info["torn_tail"] = True
                print(f"WARNING: journal {path}: torn final record "
                      f"({len(line)} bytes) — crash mid-append; dropping it "
                      "(its submit was never acknowledged)",
                      file=sys.stderr, flush=True)
            else:
                print(f"WARNING: journal {path}: skipping unreadable record "
                      f"at line {idx + 1} ({e})", file=sys.stderr, flush=True)
            continue
        crc_bad = not wire.verify(rec)
        if not crc_bad and isinstance(rec.get("v"), int) and rec["v"] >= 2 \
                and "crc" not in rec:
            # v2 records ALWAYS carry a crc; one without it had the crc
            # (or its key name) corrupted away and must not pass as legacy
            crc_bad = True
        if crc_bad:
            # the line parses but its integrity check fails: a mid-file
            # bit flip that happened to keep the JSON well-formed.  Acting
            # on it could resurrect a different job state than was acked —
            # skip it, count it, keep replaying the rest.  (Records from
            # pre-crc v1 journals carry no ``crc`` and verify trivially.)
            info["skipped"] += 1
            info["crc_skipped"] += 1
            print(f"WARNING: journal {path}: record at line {idx + 1} "
                  "failed its crc (mid-file corruption); skipping it",
                  file=sys.stderr, flush=True)
            continue
        info["records"] += 1
        if rec.get("rec") == "marker":
            # markers only matter as the journal's last word: any job
            # record after a drain marker belongs to a newer daemon life
            info["clean_drain"] = rec.get("kind") == "drain"
            if rec.get("kind") == "adopted":
                # tombstone: every job recorded so far was handed to its
                # ring successor; a replaying zombie must not re-run them
                info["adopted_by"] = str(rec.get("router") or "?")
                for merged in jobs.values():
                    merged["adopted"] = True
            elif rec.get("kind") == "fence":
                try:
                    epoch = int(rec.get("epoch"))
                except (TypeError, ValueError):
                    epoch = None
                if epoch is not None:
                    info["fence_epoch"] = max(
                        info["fence_epoch"] or 0, epoch)
            elif rec.get("kind") == "suspect":
                key = rec.get("key")
                try:
                    attempt = int(rec.get("attempt"))
                except (TypeError, ValueError):
                    attempt = None
                if isinstance(key, str) and attempt is not None:
                    info["suspects"][key] = max(
                        info["suspects"].get(key, 0), attempt)
            elif rec.get("kind") == "quarantined":
                key = rec.get("key")
                if isinstance(key, str):
                    if rec.get("released"):
                        # release re-opens the key; last marker wins
                        info["quarantined"].pop(key, None)
                    else:
                        info["quarantined"][key] = \
                            str(rec.get("reason") or "quarantined")
            continue
        info["clean_drain"] = False
        try:
            job_id = int(rec["id"])
        except (KeyError, TypeError, ValueError):
            info["skipped"] += 1
            print(f"WARNING: journal {path}: job record without id "
                  f"at line {idx + 1}", file=sys.stderr, flush=True)
            continue
        merged = jobs.setdefault(job_id, {})
        merged.update({k: v for k, v in rec.items()
                       if k not in ("v", "rec", "crc")})
    return jobs, info
