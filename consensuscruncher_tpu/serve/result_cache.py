"""Fleet-wide content-addressed result cache (the serve plane's hit-rate
lever).

At fleet scale the traffic is dominated by overlapping questions: the
same BAM, the same consensus policy, submitted by many tenants.  The
per-worker journal already dedupes *exact resubmits within one journal*
(``journal.idempotency_key``), but identity there includes ``tenant``/
``qos``/``output`` — correct for quota accounting, useless for sharing
work.  This module keys results by what actually determines the bytes:

  content digest = sha256 over the sorted-keys compact JSON of
    {input fingerprint (``manifest.fingerprint``: size + head/tail
     hashes), derived job name, the consensus vote *parameters*
     (cutoff, qualscore, scorrect, max_mismatch, bdelim,
     compress_level), the vote *policy* name (when non-default),
     input_range (when sharded), package ``__version__``}

``tenant``, ``qos``, ``output`` and ``deadline_s`` are deliberately
EXCLUDED: two tenants asking the same question hit the same entry (the
whole point), and the payload is materialized into *their* output tree.
``__version__`` is INCLUDED: a code upgrade invalidates every entry by
construction — no epoch bookkeeping, no stale-result window.

Store layout (``<root>`` is the cache plane dir, shared or per-member)::

    <root>/<shard>/<digest[:2]>/<digest>/payload/<relpath...>
    <root>/<shard>/<digest[:2]>/<digest>/entry.json

``shard`` is the owning member's name — placement rides the same
consistent-hash ring as job routing (the router passes the digest's
ring owner as ``preferred_shard``), so a cache entry lives where the
job that produced it ran, and lookups check the ring home first before
sweeping peers.

Durability discipline (enforced by cctlint's cache-store pass, CCT9xx):
every byte that lands under ``<root>`` goes through
``manifest.commit_file`` (fsync + rename + dir-fsync).  ``entry.json``
is committed LAST — it is the linearization point.  A reader that finds
``entry.json`` is guaranteed every payload file is durable and complete;
a crash mid-insert leaves at worst an invisible partial payload that a
later insert of the same digest simply overwrites.  There is no
read-repair and no locking between processes: inserts of the same
digest are idempotent byte-identical writes.

Negative entries: a run that provably produced zero consensus families
(an empty ``--input_range`` slice, a filtered-out input) is cached with
``negative: true``.  The payload (empty outputs) still materializes
byte-identically; the flag exists so hits on known-empty work are
counted separately (``cache_negative_hits``) and so range planners can
skip slices that are known-empty without reading BAM bytes.

Fault site ``serve.cache``: fired on every lookup and insert.  The
cache is an optimization, never a correctness dependency — callers
catch the fault (and any real IO error) and degrade to recomputing.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import tempfile
import time

from consensuscruncher_tpu import __version__
from consensuscruncher_tpu.utils import faults, sanitize
from consensuscruncher_tpu.utils.manifest import commit_file, fingerprint

#: Spec fields folded into the content digest.  Together with the input
#: fingerprint and ``__version__`` these determine the output bytes;
#: nothing else does (tenant/qos/output/deadline are routing and
#: accounting concerns, not identity).  ``policy`` — the consensus vote
#: policy (ISSUE 17) — changes the bytes and so is identity, but like
#: every field here it folds in only when present: a default (majority)
#: spec keeps its pre-policy digest, so entries written before the
#: policy subsystem still hit.
DIGEST_FIELDS = ("cutoff", "qualscore", "scorrect", "max_mismatch",
                 "bdelim", "compress_level", "input_range", "policy")

ENTRY_NAME = "entry.json"
LOCAL_SHARD = "local"

#: corrupt entries are moved here (never served, kept for post-mortem);
#: excluded from the shard walk so lookups can't wander into it
QUARANTINE_DIR = "quarantine"


def _sha256_file(path: str) -> str | None:
    """Streaming sha256 of a file, or ``None`` when unreadable."""
    h = hashlib.sha256()
    try:
        with open(path, "rb") as fh:
            while True:
                chunk = fh.read(1 << 20)
                if not chunk:
                    break
                h.update(chunk)
    except OSError:
        return None
    return h.hexdigest()


def content_digest(spec: dict) -> str | None:
    """Content digest of a job spec, or ``None`` when the input cannot be
    fingerprinted (missing/unreadable file -> not cacheable; the submit
    path will surface the real error).  The derived job *name* is part
    of the digest because output filenames embed it — two names produce
    byte-identical content under different paths, which is not the
    byte-identical contract the cache promises."""
    path = spec.get("input")
    if not path:
        return None
    fp = fingerprint(str(path))
    if fp is None:
        return None
    name = spec.get("name") or os.path.basename(str(path)).split(".")[0]
    ident: dict = {"fp": fp, "name": name, "v": __version__}
    for k in DIGEST_FIELDS:
        if spec.get(k) is not None:
            ident[k] = spec.get(k)
    blob = json.dumps(ident, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:32]


def _walk_files(base: str) -> list[str]:
    """Relative paths of every regular file under ``base``, sorted for a
    deterministic entry doc (symlinks and special files are skipped —
    the pipeline never writes them)."""
    out = []
    for dirpath, _dirnames, filenames in os.walk(base):
        for fn in filenames:
            full = os.path.join(dirpath, fn)
            if os.path.isfile(full) and not os.path.islink(full):
                out.append(os.path.relpath(full, base))
    return sorted(out)


def _copy_committed(src: str, dest: str) -> tuple[int, str]:
    """Copy one file into place via tmp + ``commit_file``; returns
    ``(bytes, sha256)`` — the digest is computed over the same bytes the
    commit made durable, so the entry doc can pin the payload's identity.
    The tmp file lives in the destination directory so the final rename
    is same-filesystem atomic."""
    dest_dir = os.path.dirname(os.path.abspath(dest))
    os.makedirs(dest_dir, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=".cache.", dir=dest_dir)
    try:
        n = 0
        h = hashlib.sha256()
        with os.fdopen(fd, "wb") as out, open(src, "rb") as inp:
            while True:
                chunk = inp.read(1 << 20)
                if not chunk:
                    break
                out.write(chunk)
                h.update(chunk)
                n += len(chunk)
        commit_file(tmp, dest)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return n, h.hexdigest()


class ResultCache:
    """One process's handle on the cache plane rooted at ``root``.

    ``node`` names this process's shard (where its inserts land);
    lookups read every shard, preferring ``preferred_shard`` (the ring
    owner) so the common case is one directory probe.  ``max_bytes``
    bounds THIS shard's payload bytes; eviction is oldest-entry-first
    and only ever touches the local shard (peers own theirs).
    """

    def __init__(self, root: str, node: str | None = None,
                 max_bytes: int | None = None, counters=None):
        self.root = str(root)
        self.node = str(node or LOCAL_SHARD)
        self.max_bytes = int(max_bytes) if max_bytes else None
        # optional Counters sink: integrity-degraded hits are counted
        # (``cache_integrity_misses``) when the owner wires one in
        self.counters = counters
        os.makedirs(os.path.join(self.root, self.node), exist_ok=True)
        self._lock = sanitize.tracked_lock("result_cache.lock")

    # ------------------------------------------------------------ layout

    def entry_dir(self, digest: str, shard: str | None = None) -> str:
        return os.path.join(self.root, shard or self.node,
                            digest[:2], digest)

    def _shards(self) -> list[str]:
        try:
            names = [d for d in sorted(os.listdir(self.root))
                     if os.path.isdir(os.path.join(self.root, d))
                     and d != QUARANTINE_DIR]
        except OSError:
            return [self.node]
        return names

    # ------------------------------------------------------------ lookup

    def lookup(self, digest: str,
               preferred_shard: str | None = None) -> dict | None:
        """Find a committed entry for ``digest`` anywhere in the plane.
        Returns the entry doc (with ``shard`` and ``dir`` annotated) or
        ``None``.  ``serve.cache`` fires here: an armed fault makes the
        lookup miss, never fail the caller."""
        try:
            faults.fault_point("serve.cache")
        except faults.FaultError as e:
            print(f"WARNING: result cache: lookup degraded to miss ({e})",
                  file=sys.stderr, flush=True)
            return None
        shards = self._shards()
        if preferred_shard and preferred_shard in shards:
            shards.remove(preferred_shard)
            shards.insert(0, preferred_shard)
        elif self.node in shards:
            shards.remove(self.node)
            shards.insert(0, self.node)
        for shard in shards:
            entry = self._read_entry(digest, shard)
            if entry is None:
                continue
            err = self._integrity_error(entry)
            if err is not None:
                # the payload no longer matches the sha256 the insert
                # pinned: NEVER serve it.  Degrade to a counted miss,
                # move the corpse aside for post-mortem, keep probing
                # the other shards (a peer may hold a good copy).
                if self.counters is not None:
                    self.counters.add("cache_integrity_misses")
                moved = self.quarantine(entry)
                print(f"WARNING: result cache: entry {digest} in shard "
                      f"{shard} failed integrity ({err}); quarantined to "
                      f"{moved or '<unmovable>'} and degraded to a miss",
                      file=sys.stderr, flush=True)
                continue
            return entry
        return None

    def _integrity_error(self, entry: dict) -> str | None:
        """Re-hash every payload file against the sha256 the entry doc
        pinned at insert.  ``None`` means clean; entries from before the
        integrity field (no ``sha256`` on any file) have nothing to
        check and pass unchanged."""
        payload_dir = os.path.join(entry["dir"], "payload")
        for f in entry.get("files", []):
            want = f.get("sha256")
            if want is None:
                continue
            got = _sha256_file(os.path.join(payload_dir, f["path"]))
            if got != want:
                return (f"{f['path']}: sha256 "
                        f"{got or 'unreadable'} != {want}")
        return None

    def quarantine(self, entry: dict) -> str | None:
        """Move a corrupt entry's directory to ``<root>/quarantine/``.
        ``entry.json`` is unlinked FIRST — the entry disappears for every
        reader before anything else moves (the exact reverse of insert's
        entry-last commit order), so no lookup can race into a half-moved
        dir.  Returns the quarantine path, or ``None`` if the move
        failed (the entry is still invisible: its doc is gone)."""
        edir = entry["dir"]
        try:
            os.unlink(os.path.join(edir, ENTRY_NAME))
        except OSError:
            pass
        qroot = os.path.join(self.root, QUARANTINE_DIR)
        try:
            os.makedirs(qroot, exist_ok=True)
            dest = os.path.join(
                qroot, f"{entry.get('shard') or self.node}-{entry['digest']}")
            n = 0
            while os.path.exists(dest):
                n += 1
                dest = os.path.join(
                    qroot, f"{entry.get('shard') or self.node}-"
                           f"{entry['digest']}.{n}")
            # not a cache-plane write: the entry doc is already gone, so
            # no reader can observe this dir; the move only relocates a
            # corpse out of the shard tree for post-mortem
            os.rename(edir, dest)  # cct: allow-cache-store(quarantine move of an already-invisible entry)
        except OSError:
            return None
        return dest

    def scrub(self) -> dict:
        """Offline integrity sweep (``cct cache scrub``): re-hash every
        committed entry's payload across every shard; corrupt entries
        are quarantined.  Returns ``{"entries", "intact", "legacy",
        "corrupt", "quarantined": [...]}`` (``legacy`` counts entries
        from before the sha256 field — nothing to verify)."""
        out: dict = {"entries": 0, "intact": 0, "legacy": 0, "corrupt": 0,
                     "quarantined": []}
        for shard in self._shards():
            shard_dir = os.path.join(self.root, shard)
            for dirpath, _dirnames, filenames in os.walk(shard_dir):
                if ENTRY_NAME not in filenames:
                    continue
                entry = self._read_entry(os.path.basename(dirpath), shard)
                if entry is None:
                    continue
                out["entries"] += 1
                if not any(f.get("sha256")
                           for f in entry.get("files", [])):
                    out["legacy"] += 1
                    continue
                err = self._integrity_error(entry)
                if err is None:
                    out["intact"] += 1
                    continue
                out["corrupt"] += 1
                if self.counters is not None:
                    self.counters.add("cache_integrity_misses")
                moved = self.quarantine(entry)
                out["quarantined"].append({
                    "digest": entry["digest"], "shard": shard,
                    "error": err, "moved_to": moved})
        return out

    def _read_entry(self, digest: str, shard: str) -> dict | None:
        edir = self.entry_dir(digest, shard)
        path = os.path.join(edir, ENTRY_NAME)
        try:
            with open(path) as fh:
                entry = json.load(fh)
        except (OSError, ValueError):
            return None
        if not isinstance(entry, dict) or entry.get("digest") != digest:
            return None
        entry["shard"] = shard
        entry["dir"] = edir
        return entry

    # ------------------------------------------------------------ insert

    def insert(self, digest: str, base_dir: str, *,
               negative: bool = False, meta: dict | None = None) -> dict | None:
        """Commit the finished job's output tree under ``base_dir`` as a
        cache entry in this process's shard.  Payload files first (each
        via ``commit_file``), ``entry.json`` last — the entry is visible
        only once every payload byte is durable.  Idempotent: an entry
        that already exists is left alone (same digest -> same bytes).
        Returns the committed entry doc, or ``None`` when the insert was
        skipped or degraded (armed fault / IO error)."""
        try:
            faults.fault_point("serve.cache")
        except faults.FaultError as e:
            print(f"WARNING: result cache: insert skipped ({e})",
                  file=sys.stderr, flush=True)
            return None
        existing = self._read_entry(digest, self.node)
        if existing is not None:
            return existing
        if not os.path.isdir(base_dir):
            return None
        edir = self.entry_dir(digest, self.node)
        payload_dir = os.path.join(edir, "payload")
        files = []
        total = 0
        try:
            for rel in _walk_files(base_dir):
                n, sha = _copy_committed(os.path.join(base_dir, rel),
                                         os.path.join(payload_dir, rel))
                files.append({"path": rel, "size": n, "sha256": sha})
                total += n
            entry = {"v": 1, "digest": digest, "negative": bool(negative),
                     "bytes": total, "files": files, "node": self.node,
                     "t": time.time()}
            if meta:
                entry["meta"] = dict(meta)
            fd, tmp = tempfile.mkstemp(prefix=".entry.", dir=edir)
            try:
                with os.fdopen(fd, "w") as fh:
                    json.dump(entry, fh, sort_keys=True)
                commit_file(tmp, os.path.join(edir, ENTRY_NAME))
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise
        except OSError as e:
            print(f"WARNING: result cache: insert of {digest} failed ({e}); "
                  "recompute path unaffected", file=sys.stderr, flush=True)
            return None
        entry["shard"] = self.node
        entry["dir"] = edir
        return entry

    # -------------------------------------------------------- materialize

    def materialize(self, entry: dict, dest_base: str) -> int:
        """Copy a committed entry's payload into ``dest_base`` (the
        requesting job's own output tree), each file via ``commit_file``
        so a crash mid-materialize never leaves a partial output a
        ``--resume`` run would trust.  Returns bytes written."""
        payload_dir = os.path.join(entry["dir"], "payload")
        total = 0
        for f in entry.get("files", []):
            rel = f["path"]
            n, _sha = _copy_committed(os.path.join(payload_dir, rel),
                                      os.path.join(dest_base, rel))
            total += n
        return total

    # ----------------------------------------------------------- eviction

    def shard_stats(self) -> dict:
        """``{"entries", "bytes"}`` for THIS shard (committed entries
        only — invisible partial payloads don't count)."""
        entries = 0
        total = 0
        shard_dir = os.path.join(self.root, self.node)
        for dirpath, _dirnames, filenames in os.walk(shard_dir):
            if ENTRY_NAME not in filenames:
                continue
            entry = self._read_entry(os.path.basename(dirpath), self.node)
            if entry is None:
                continue
            entries += 1
            total += int(entry.get("bytes", 0))
        return {"entries": entries, "bytes": total}

    def evict_to_budget(self, emergency: bool = False) -> list[dict]:
        """Drop oldest committed entries from the local shard until its
        payload bytes fit ``max_bytes``.  The entry doc is unlinked
        FIRST (the entry disappears atomically for readers), payload
        files after — the reverse of insert order, so no reader ever
        sees a visible entry with missing payload.  Returns the evicted
        entry docs.

        ``emergency=True`` is the ENOSPC first responder: the disk the
        journal fsyncs to is full, and cache bytes are the cheapest on
        the box (every entry is re-computable by construction) — evict
        the oldest half of the shard (at least one entry) regardless of
        ``max_bytes`` so the brownout path gets one append's worth of
        space back."""
        if not self.max_bytes and not emergency:
            return []
        with self._lock:
            live = []
            shard_dir = os.path.join(self.root, self.node)
            for dirpath, _dirnames, filenames in os.walk(shard_dir):
                if ENTRY_NAME not in filenames:
                    continue
                entry = self._read_entry(os.path.basename(dirpath), self.node)
                if entry is not None:
                    live.append(entry)
            total = sum(int(e.get("bytes", 0)) for e in live)
            live.sort(key=lambda e: e.get("t", 0.0))
            evicted = []
            budget = self.max_bytes or float("inf")
            keep = len(live)
            if emergency:
                keep = len(live) // 2
            while live and (total > budget or len(live) > keep):
                entry = live.pop(0)
                try:
                    os.unlink(os.path.join(entry["dir"], ENTRY_NAME))
                except OSError:
                    continue
                for f in entry.get("files", []):
                    try:
                        os.unlink(os.path.join(entry["dir"], "payload",
                                               f["path"]))
                    except OSError:
                        pass
                total -= int(entry.get("bytes", 0))
                evicted.append(entry)
            return evicted
