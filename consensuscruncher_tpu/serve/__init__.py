"""serve/ — persistent consensus daemon with cross-request batching.

One-shot CLI runs pay the full XLA compile/warmup cost (~16 s measured:
20.8 s cold vs 4.2 s warm on the same input) on EVERY invocation and leave
the device idle between samples — fatal for multi-user traffic.  This
subsystem is the standard inference-stack answer:

- :mod:`.server`    — long-lived daemon (unix socket or localhost TCP,
  newline-delimited JSON) exposing ``submit`` / ``status`` / ``result`` /
  ``healthz`` / ``metrics`` / ``drain``; started by the new
  ``ConsensusCruncher.py serve`` subcommand.
- :mod:`.scheduler` — admission-controlled bounded job queue with
  continuous batching: families from several queued jobs are merged
  (``parallel.batching.interleave_sources``) into ONE device stream so a
  single dispatch serves multiple requests, with per-job outputs staying
  bit-identical to the one-shot CLI path (the sorting writers' total order
  is content-keyed, never batch order).
- :mod:`.warmup`    — shape-bucket precompilation at startup + a
  persistent JAX compilation cache directory, so cold-compile is paid once
  per server lifetime, not per sample.
- :mod:`.client`    — blocking client used by the ``submit`` subcommand
  and the tests; reconnects with capped backoff and polls by idempotency
  key, so a daemon restart is invisible to a waiting client.
- :mod:`.journal`   — write-ahead job journal (fsync'd NDJSON, atomic
  checkpoint rotation): every accepted job survives a daemon crash and
  replays byte-identically through ``--resume`` on restart.
- :mod:`.supervisor`— ``serve --supervise`` restart loop with capped
  exponential backoff for crashed daemons.

The subsystem composes with the fault-tolerance layer rather than
duplicating it: outputs commit through ``utils.manifest.commit_file``
(via the stage writers), failed jobs are retried through the existing
``--resume`` path, and the ``serve.accept`` / ``serve.dispatch`` /
``serve.worker`` sites in ``utils.faults`` make the whole daemon
chaos-testable.
"""

from consensuscruncher_tpu.serve.scheduler import (
    AdmissionRefused, DeadlineShed, Job, Scheduler,
)

__all__ = ["AdmissionRefused", "DeadlineShed", "Job", "Scheduler"]
