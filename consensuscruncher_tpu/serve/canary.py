"""Golden canary prober: active end-to-end correctness + latency watch.

Metrics notice a daemon that stops answering; nothing notices a daemon
that keeps answering *wrong* — silent correctness rot (a bad deploy, a
corrupted cache shard, a device numerics regression) only surfaces when
a tenant complains.  The prober closes that gap from inside the serve
plane: on a cadence it submits a tiny synthetic job (deterministic
``simulate_bam`` input, scavenger QoS, the reserved ``_canary`` tenant
that is excluded from tenant quotas and the QC series), waits for it,
and verifies the output BAM bytes against a pinned golden digest plus a
latency bound.  The first honest probe self-mints the golden (the input
is seeded, the pipeline is byte-deterministic — the digest is a
constant); ``CCT_CANARY_GOLDEN`` pins it explicitly, which is also the
ci positive control: a corrupted pin MUST flip the gauge.

A failed probe — digest mismatch, latency breach, or probe error —
flips the ``cct_canary_ok`` gauge to 0, counts ``canary_fail``, and
dumps the flight ring while the evidence is fresh.  An admission
refusal (the scavenger probe is the first thing shed under real
overload, by design) is a *skip*, not a failure: the canary watches for
rot, not for load.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import threading
import time

from consensuscruncher_tpu.obs import flight as obs_flight
from consensuscruncher_tpu.obs import trace as obs_trace
from consensuscruncher_tpu.serve.scheduler import (
    CANARY_TENANT,
    AdmissionRefused,
)

#: fixed simulation shape: tiny (8 fragments) so a probe costs
#: milliseconds of device time, seeded so the output bytes are constants
CANARY_SEED = 107
CANARY_FRAGMENTS = 8


def enabled() -> bool:
    return os.environ.get("CCT_CANARY", "") == "1"


def _interval_s() -> float:
    try:
        return max(0.5, float(os.environ.get("CCT_CANARY_INTERVAL_S",
                                             "60")))
    except ValueError:
        return 60.0


def _latency_s() -> float:
    try:
        return max(1.0, float(os.environ.get("CCT_CANARY_LATENCY_S",
                                             "120")))
    except ValueError:
        return 120.0


def output_digest(base: str) -> str:
    """sha256 over every output BAM's relative path + raw bytes (sorted
    walk).  BGZF layout is deterministic at a fixed compress level, so
    this is a constant for the seeded canary input — the sidecars
    (manifest, metrics, qc) carry walls and are deliberately excluded."""
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in sorted(os.walk(base)):
        dirnames.sort()
        for name in sorted(filenames):
            if not name.endswith(".bam"):
                continue
            path = os.path.join(dirpath, name)
            h.update(os.path.relpath(path, base).encode())
            h.update(b"\0")
            try:
                with open(path, "rb") as fh:
                    for chunk in iter(lambda: fh.read(1 << 20), b""):
                        h.update(chunk)
            except OSError:
                h.update(b"<unreadable>")
            h.update(b"\0")
    return h.hexdigest()


class CanaryProber(threading.Thread):
    """Daemon thread probing ``scheduler`` on a cadence.  ``status()``
    is attached as ``scheduler.canary_info`` so /metrics exposes the
    gauges; ``probe_once()`` runs one synchronous probe (tests, ci)."""

    def __init__(self, scheduler, workdir: str,
                 interval_s: float | None = None,
                 latency_s: float | None = None,
                 golden: str | None = None):
        super().__init__(name="cct-canary", daemon=True)
        self.scheduler = scheduler
        self.workdir = workdir
        self.interval = interval_s if interval_s is not None \
            else _interval_s()
        self.latency_s = latency_s if latency_s is not None \
            else _latency_s()
        self.golden = golden \
            or os.environ.get("CCT_CANARY_GOLDEN", "") or None
        self.stop_event = threading.Event()
        self._lock = threading.Lock()
        self._ok = True
        self._last_done_t: float | None = None
        self._last_error: str | None = None
        self._runs = self._passes = self._fails = 0
        self._n = 0

    # ------------------------------------------------------------ status

    def status(self) -> dict:
        with self._lock:
            age = None if self._last_done_t is None \
                else round(time.monotonic() - self._last_done_t, 3)
            return {"ok": self._ok, "age_s": age, "runs": self._runs,
                    "pass": self._passes, "fail": self._fails,
                    "golden": self.golden, "last_error": self._last_error}

    # ------------------------------------------------------------- probe

    def _input_path(self) -> str:
        """The seeded synthetic input, simulated once per workdir."""
        path = os.path.join(self.workdir, "canary.bam")
        if not os.path.exists(path):
            from consensuscruncher_tpu.utils.simulate import (
                SimConfig,
                simulate_bam,
            )
            os.makedirs(self.workdir, exist_ok=True)
            simulate_bam(path, SimConfig(n_fragments=CANARY_FRAGMENTS,
                                         seed=CANARY_SEED))
        return path

    def _fail(self, why: str) -> None:
        with self._lock:
            self._ok = False
            self._fails += 1
            self._last_error = why
            self._last_done_t = time.monotonic()
        self.scheduler.counters.add("canary_fail")
        obs_trace.event("serve.canary", ok=False, error=why)
        obs_flight.record("canary_fail", error=why,
                          golden=self.golden)
        obs_flight.dump(reason="canary-fail")

    def probe_once(self) -> bool | None:
        """One synchronous probe.  True = pass, False = fail, None =
        skipped (admission refused the scavenger probe — an overloaded
        daemon shedding the canary first is working as designed)."""
        self._n += 1
        out = os.path.join(self.workdir, f"run{self._n}")
        spec = {
            "input": self._input_path(), "output": out,
            "name": "canary", "tenant": CANARY_TENANT,
            "qos": "scavenger", "cutoff": 0.7, "qualscore": 0,
        }
        with self._lock:
            self._runs += 1
        self.scheduler.counters.add("canary_runs")
        t0 = time.monotonic()
        try:
            job, _created = self.scheduler.submit_info(spec)
        except AdmissionRefused as e:
            with self._lock:
                self._last_error = f"skipped: {e}"
            return None
        except Exception as e:
            self._fail(f"submit error: {type(e).__name__}: {e}")
            return False
        try:
            self.scheduler.wait(job.id, timeout=self.latency_s)
        except TimeoutError:
            self._fail(f"latency bound breached: probe still "
                       f"{job.state} after {self.latency_s:g}s")
            return False
        latency = time.monotonic() - t0
        if job.state != "done":
            self._fail(f"probe {job.state}: {job.error}")
            return False
        base = (job.outputs or {}).get("base") or out
        digest = output_digest(base)
        self._cleanup(keep=out)
        if self.golden is None:
            # first honest probe mints the golden: the seeded input and
            # byte-deterministic pipeline make the digest a constant
            self.golden = digest
        elif digest != self.golden:
            self._fail(f"golden digest mismatch: got {digest[:16]}.., "
                       f"want {self.golden[:16]}..")
            return False
        if latency > self.latency_s:
            self._fail(f"latency {latency:.1f}s > bound "
                       f"{self.latency_s:g}s")
            return False
        with self._lock:
            self._ok = True
            self._passes += 1
            self._last_error = None
            self._last_done_t = time.monotonic()
        self.scheduler.counters.add("canary_pass")
        obs_trace.event("serve.canary", ok=True,
                        latency_ms=round(latency * 1e3, 3))
        return True

    def _cleanup(self, keep: str) -> None:
        """Bound the probe scratch: drop every older run dir."""
        try:
            for name in sorted(os.listdir(self.workdir)):
                path = os.path.join(self.workdir, name)
                if name.startswith("run") and os.path.isdir(path) \
                        and os.path.abspath(path) != os.path.abspath(keep):
                    shutil.rmtree(path, ignore_errors=True)
        except OSError:
            pass

    # -------------------------------------------------------------- loop

    def run(self) -> None:
        while not self.stop_event.wait(self.interval):
            try:
                self.probe_once()
            except Exception as e:
                # the prober must never take down the daemon it watches
                self._fail(f"probe crashed: {type(e).__name__}: {e}")

    def stop(self, timeout: float = 2.0) -> None:
        self.stop_event.set()
        if self.is_alive():
            self.join(timeout)


def maybe_start(scheduler, workdir: str) -> CanaryProber | None:
    """Boot the prober iff ``CCT_CANARY=1``; attaches ``status`` to the
    scheduler's ``canary_info`` hook either way it starts."""
    if not enabled():
        return None
    prober = CanaryProber(scheduler, workdir)
    scheduler.canary_info = prober.status
    prober.start()
    return prober
