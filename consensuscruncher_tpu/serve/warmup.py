"""Startup warmup: persistent compile cache + shape-bucket precompilation.

BENCH_r05 measured ~16 s of XLA compile/warmup per one-shot run (20.8 s
cold vs 4.2 s warm on the same input).  The daemon pays it once:

- :func:`setup_compilation_cache` points JAX's persistent compilation
  cache at a directory, so even a daemon *restart* reuses compiled
  programs instead of re-tracing from scratch.
- :func:`warm_shapes` force-compiles the dense vote kernel for a
  configured list of ``BxFxL`` bucket shapes (the continuous-batching
  gang wire), so the first request never eats a cold compile.

Both degrade gracefully: an unavailable cache backend or a failed shape
warm logs a warning and serving proceeds cold.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np


def setup_compilation_cache(cache_dir: str) -> bool:
    """Enable JAX's persistent compilation cache under ``cache_dir``.
    Returns True when active; logs + returns False when the running JAX
    can't (version without the knob, read-only dir, ...)."""
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache even fast-compiling programs: the daemon's point is that
        # NO request ever re-compiles
        for knob, value in (
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
            ("jax_persistent_cache_min_entry_size_bytes", 0),
        ):
            try:
                jax.config.update(knob, value)
            except (AttributeError, ValueError):
                pass  # older jax: defaults are fine
        return True
    except Exception as e:
        print(f"WARNING: persistent compile cache unavailable ({e}); "
              "serving with in-process cache only", file=sys.stderr, flush=True)
        return False


def parse_shapes(text: str) -> list[tuple[int, int, int]]:
    """Parse ``"8x4x96,16x8x160"`` into ``[(B, F, L), ...]``; empty -> []."""
    shapes = []
    for part in (text or "").split(","):
        part = part.strip()
        if not part:
            continue
        dims = part.lower().split("x")
        if len(dims) != 3:
            raise ValueError(f"bad warmup shape {part!r} (want BxFxL)")
        b, f, l = (int(d) for d in dims)
        if b < 1 or f < 1 or l < 1:
            raise ValueError(f"bad warmup shape {part!r} (dims must be >= 1)")
        shapes.append((b, f, l))
    return shapes


def warm_shapes(shapes, config=None, budget_s: float | None = None) -> int:
    """Force-compile the dense consensus vote for each (B, F, L) bucket.
    Returns how many shapes compiled; a failed shape warns and continues.
    ``budget_s`` bounds the total warmup wall — a supervised restart must
    get back to accepting (journal-replayed) jobs quickly, and skipped
    shapes just compile lazily on first use."""
    from consensuscruncher_tpu.ops.consensus_tpu import (
        ConsensusConfig, consensus_batch,
    )
    from consensuscruncher_tpu.utils.phred import PAD

    if config is None:
        config = ConsensusConfig()
    done = 0
    t0 = time.monotonic()
    for i, (b, f, l) in enumerate(shapes):
        if budget_s is not None and time.monotonic() - t0 >= budget_s:
            print(f"WARNING: warmup budget {budget_s:g}s spent after {done} "
                  f"shape(s); skipping {len(shapes) - i} remaining (they "
                  "compile lazily on first use)", file=sys.stderr, flush=True)
            break
        try:
            bases = np.full((b, f, l), PAD, dtype=np.uint8)
            quals = np.zeros((b, f, l), dtype=np.uint8)
            sizes = np.zeros(b, dtype=np.int32)
            out_b, out_q = consensus_batch(bases, quals, sizes, config)
            out_b.block_until_ready()
            out_q.block_until_ready()
            done += 1
        except Exception as e:
            print(f"WARNING: warmup shape {b}x{f}x{l} failed ({e}); skipping",
                  file=sys.stderr, flush=True)
    return done
