"""Startup warmup: persistent compile cache + shape-bucket precompilation.

BENCH_r05 measured ~16 s of XLA compile/warmup per one-shot run (20.8 s
cold vs 4.2 s warm on the same input).  The daemon pays it once:

- :func:`setup_compilation_cache` points JAX's persistent compilation
  cache at a directory, so even a daemon *restart* reuses compiled
  programs instead of re-tracing from scratch.
- :func:`warm_shapes` force-compiles the dense vote kernel for a
  configured list of ``BxFxL`` bucket shapes (the continuous-batching
  gang wire), so the first request never eats a cold compile.

Both degrade gracefully: an unavailable cache backend or a failed shape
warm logs a warning and serving proceeds cold.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np


def setup_compilation_cache(cache_dir: str) -> bool:
    """Enable JAX's persistent compilation cache under ``cache_dir``.
    Returns True when active; logs + returns False when the running JAX
    can't (version without the knob, read-only dir, ...)."""
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache even fast-compiling programs: the daemon's point is that
        # NO request ever re-compiles
        for knob, value in (
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
            ("jax_persistent_cache_min_entry_size_bytes", 0),
        ):
            try:
                jax.config.update(knob, value)
            except (AttributeError, ValueError):
                pass  # older jax: defaults are fine
        return True
    except Exception as e:
        print(f"WARNING: persistent compile cache unavailable ({e}); "
              "serving with in-process cache only", file=sys.stderr, flush=True)
        return False


def parse_shapes(text: str) -> list[tuple[int, int, int]]:
    """Parse ``"8x4x96,16x8x160"`` into ``[(B, F, L), ...]``; empty -> []."""
    shapes = []
    for part in (text or "").split(","):
        part = part.strip()
        if not part:
            continue
        dims = part.lower().split("x")
        if len(dims) != 3:
            raise ValueError(f"bad warmup shape {part!r} (want BxFxL)")
        b, f, l = (int(d) for d in dims)
        if b < 1 or f < 1 or l < 1:
            raise ValueError(f"bad warmup shape {part!r} (dims must be >= 1)")
        shapes.append((b, f, l))
    return shapes


def warm_shapes(shapes, config=None, budget_s: float | None = None) -> int:
    """Force-compile the dense consensus vote for each (B, F, L) bucket.
    Returns how many shapes compiled; a failed shape warns and continues.
    ``budget_s`` bounds the total warmup wall — a supervised restart must
    get back to accepting (journal-replayed) jobs quickly, and skipped
    shapes just compile lazily on first use."""
    from consensuscruncher_tpu.ops.consensus_tpu import (
        ConsensusConfig, consensus_batch,
    )
    from consensuscruncher_tpu.utils.phred import PAD

    if config is None:
        config = ConsensusConfig()
    done = 0
    t0 = time.monotonic()
    for i, (b, f, l) in enumerate(shapes):
        if budget_s is not None and time.monotonic() - t0 >= budget_s:
            print(f"WARNING: warmup budget {budget_s:g}s spent after {done} "
                  f"shape(s); skipping {len(shapes) - i} remaining (they "
                  "compile lazily on first use)", file=sys.stderr, flush=True)
            break
        try:
            bases = np.full((b, f, l), PAD, dtype=np.uint8)
            quals = np.zeros((b, f, l), dtype=np.uint8)
            sizes = np.zeros(b, dtype=np.int32)
            out_b, out_q = consensus_batch(bases, quals, sizes, config)
            out_b.block_until_ready()
            out_q.block_until_ready()
            done += 1
        except Exception as e:
            print(f"WARNING: warmup shape {b}x{f}x{l} failed ({e}); skipping",
                  file=sys.stderr, flush=True)
    return done


# ---------------------------------------------------------------------------
# Occupancy-driven bucket autotuning (ROADMAP item 3 follow-through).
#
# The warmup shape list above is static configuration; the autotuner makes
# it EARNED: ``parallel.batching`` records every (B, F, L) bucket the live
# job mix actually dispatches, the learn loop folds those counts into a
# JSON table persisted next to the compile cache (atomic publish via
# ``utils.manifest.commit_file``), and on the next daemon start the table
# doubles as the warmup shape source — so a warmed daemon sees ZERO
# unexpected recompiles under its steady-state mix (policed by the
# ``recompiles`` obs counter; tools/ci_check.sh asserts it in the loadgen
# smoke).  Per shape the tuner also decides dense-XLA vs the Pallas vote
# kernel by measuring both on real silicon; off-TPU the Pallas interpreter
# is not a meaningful timer, so the CPU-fallback row picks dense and says
# why (the row is still emitted — CPU runs keep the full table schema).
# ---------------------------------------------------------------------------

DEFAULT_TABLE_NAME = "autotune_table.json"
_TABLE_VERSION = 1


def load_autotune_config(config_path) -> dict:
    """Parse the ``[autotune]`` block of a config.ini (missing file or
    section -> all defaults).  Keys: ``table`` (bucket table path),
    ``learn_window`` (seconds between live learn passes), ``backend``
    (``auto`` | ``dense`` | ``pallas`` override)."""
    import configparser

    out = {"table_path": None, "learn_window": 30.0, "backend": "auto"}
    if not config_path or not os.path.exists(config_path):
        return out
    cp = configparser.ConfigParser()
    try:
        cp.read(config_path)
    except configparser.Error as e:
        print(f"WARNING: config {config_path} unreadable for [autotune] ({e}); "
              "using defaults", file=sys.stderr, flush=True)
        return out
    if not cp.has_section("autotune"):
        return out
    sec = cp["autotune"]
    out["table_path"] = sec.get("table", fallback=None) or None
    out["learn_window"] = sec.getfloat("learn_window", fallback=30.0)
    out["backend"] = (sec.get("backend", fallback="auto") or "auto").strip().lower()
    return out


class BucketAutotuner:
    """Learned (B, F, L) bucket table: shape occupancy + per-shape kernel
    choice, persisted as JSON and installable as the consensus kernel
    policy (``ops.consensus_tpu.set_kernel_policy``)."""

    def __init__(self, table_path: str | None = None,
                 learn_window: float = 30.0, backend: str = "auto"):
        if backend not in ("auto", "dense", "pallas"):
            raise ValueError(
                f"[autotune] backend must be auto|dense|pallas, got {backend!r}")
        import threading

        self.table_path = table_path
        self.learn_window = max(1.0, float(learn_window))
        self.backend = backend
        self.table: dict[str, dict] = {}  # "BxFxL" -> entry
        self._lock = threading.Lock()
        self._recompiles_baseline: int | None = None

    @staticmethod
    def _key(shape, policy: str = "majority") -> str:
        """Table row key: ``BxFxL`` under the majority default (the
        committed-table back-compat form), ``BxFxL@policy`` otherwise —
        a kernel choice measured under one vote policy must never apply
        to a job running another (Pallas only exists for majority; a
        majority-learned "pallas" row would silently reroute to dense
        for delegation/distilled jobs)."""
        base = "x".join(str(int(d)) for d in shape)
        return base if policy == "majority" else f"{base}@{policy}"

    @staticmethod
    def _shape(key: str) -> tuple[int, int, int]:
        b, f, l = (int(d) for d in key.split("@", 1)[0].split("x"))
        return (b, f, l)

    @staticmethod
    def _active_policy() -> str:
        from consensuscruncher_tpu.policies.base import get_vote_policy

        return get_vote_policy().name

    # ------------------------------------------------------------ persist

    def load(self) -> bool:
        if not self.table_path:
            return False
        try:
            import json

            with open(self.table_path) as fh:
                doc = json.load(fh)
            if doc.get("version") != _TABLE_VERSION:
                return False
            with self._lock:
                self.table = dict(doc.get("shapes", {}))
            return True
        except (OSError, ValueError):
            return False

    def save(self) -> bool:
        if not self.table_path:
            return False
        import json

        from consensuscruncher_tpu.utils.manifest import commit_file

        with self._lock:
            doc = {"version": _TABLE_VERSION, "shapes": dict(self.table)}
        tmp = self.table_path + ".tmp"
        os.makedirs(os.path.dirname(os.path.abspath(self.table_path)),
                    exist_ok=True)
        with open(tmp, "w") as fh:
            json.dump(doc, fh, indent=2, sort_keys=True)
        commit_file(tmp, self.table_path)
        return True

    # -------------------------------------------------------------- learn

    def learn_from_live(self) -> list[tuple[int, int, int]]:
        """Fold the batching layer's live shape counts into the table.
        Returns shapes seen live that have no kernel decision yet."""
        from consensuscruncher_tpu.parallel import batching

        counts = batching.bucket_shape_counts(reset=True)
        policy = self._active_policy()
        fresh = []
        with self._lock:
            for shape, n in counts.items():
                key = self._key(shape, policy)
                ent = self.table.setdefault(key, {"count": 0, "backend": None})
                ent["count"] = int(ent.get("count", 0)) + int(n)
                if ent.get("backend") is None:
                    fresh.append(self._shape(key))
        return fresh

    # ------------------------------------------------------------ measure

    def measure(self, shape, config=None, reps: int = 3) -> dict:
        """Time dense-XLA vs Pallas at one (B, F, L) bucket and record the
        winner.  Off-TPU the Pallas interpreter can't be timed meaningfully
        -> dense with reason ``cpu_fallback`` (row still emitted)."""
        import jax

        from consensuscruncher_tpu.ops.consensus_tpu import (
            ConsensusConfig, consensus_batch_host,
        )

        b, f, l = (int(d) for d in shape)
        if config is None:
            config = ConsensusConfig()
        rng = np.random.default_rng(0)
        bases = rng.integers(0, 5, (b, f, l), dtype=np.uint8)
        quals = rng.integers(0, 41, (b, f, l), dtype=np.uint8)
        sizes = rng.integers(1, f + 1, b).astype(np.int32)

        def best_of(fn):
            fn()  # compile + warm outside the timed reps
            times = []
            for _ in range(max(1, reps)):
                t0 = time.perf_counter()
                fn()
                times.append(time.perf_counter() - t0)
            return min(times)

        policy = self._active_policy()
        entry: dict = {}
        entry["dense_s"] = best_of(
            lambda: consensus_batch_host(bases, quals, sizes, config))
        if policy != "majority":
            # Pallas hard-codes the majority vote program; under any
            # other policy the pallas wrapper reroutes to dense, so
            # there is nothing to race — record the only legal choice.
            entry["pallas_s"] = None
            entry["backend"] = "dense"
            entry["reason"] = "non_majority_policy"
        elif jax.default_backend() == "tpu":
            from consensuscruncher_tpu.ops.consensus_pallas import (
                consensus_batch_pallas_host,
            )

            entry["pallas_s"] = best_of(
                lambda: consensus_batch_pallas_host(bases, quals, sizes, config))
            entry["backend"] = (
                "pallas" if entry["pallas_s"] < entry["dense_s"] else "dense")
        else:
            entry["pallas_s"] = None
            entry["backend"] = "dense"
            entry["reason"] = "cpu_fallback"
        with self._lock:
            ent = self.table.setdefault(self._key(shape, policy),
                                        {"count": 0})
            ent.update(entry)
            return dict(ent)

    def tune(self, shapes=None, budget_s: float | None = None,
             config=None) -> int:
        """Measure every undecided table shape (or ``shapes``); returns how
        many were measured.  A failed measurement records a dense fallback
        so the shape is never re-measured in a hot loop."""
        if shapes is None:
            with self._lock:
                shapes = [self._shape(k) for k, e in self.table.items()
                          if e.get("backend") is None]
        done = 0
        t0 = time.monotonic()
        for shape in shapes:
            if budget_s is not None and time.monotonic() - t0 >= budget_s:
                break
            try:
                self.measure(shape, config=config)
                done += 1
            except Exception as e:
                print(f"WARNING: autotune measure {shape} failed ({e}); "
                      "recording dense fallback", file=sys.stderr, flush=True)
                with self._lock:
                    self.table.setdefault(
                        self._key(shape, self._active_policy()),
                        {"count": 0}).update(
                        {"backend": "dense", "reason": f"measure_failed: {e}"})
        return done

    # -------------------------------------------------------------- apply

    def choose_backend(self, shape) -> str:
        """Backend for one padded shape under the ACTIVE vote policy.

        The policy is part of the decision, not just the row key: Pallas
        implements only the majority program (``consensus_pallas``
        reroutes everything else back to dense), so any other policy
        pins dense — even under an explicit ``backend = pallas``
        override, and even when a majority-learned table row says
        pallas for the same shape."""
        policy = self._active_policy()
        if self.backend != "auto":
            if self.backend == "pallas" and policy != "majority":
                return "dense"
            return self.backend
        with self._lock:
            ent = self.table.get(self._key(shape, policy))
        backend = (ent or {}).get("backend") or "dense"
        if backend == "pallas" and policy != "majority":
            return "dense"  # stale pre-policy table row
        return backend

    def policy(self, shape) -> str:
        """``ops.consensus_tpu`` kernel-policy callable (only "pallas"
        reroutes; anything else keeps the dense-XLA path)."""
        return self.choose_backend(shape)

    def install(self) -> None:
        from consensuscruncher_tpu.ops import consensus_tpu

        consensus_tpu.set_kernel_policy(self.policy)

    def warmup_shapes(self, top: int = 16) -> list[tuple[int, int, int]]:
        """Most-seen learned shapes, for :func:`warm_shapes` at startup."""
        with self._lock:
            items = sorted(self.table.items(),
                           key=lambda kv: -int(kv[1].get("count", 0)))
        return [self._shape(k) for k, _ in items[:top]]

    def ladder_shapes(self, min_b: int = 8) -> list[tuple[int, int, int]]:
        """The pow2-B sub-ladder of the learned buckets: continuous
        batching dispatches the same (F, L) bucket at ANY pow2 batch count
        up to the largest learned B (gang composition decides which), so a
        daemon that wants zero steady-state recompiles warms them all."""
        with self._lock:
            shapes = [self._shape(k) for k in self.table]
        out = set()
        for b, f, l in shapes:
            bb = max(1, min_b)
            while bb <= b:
                out.add((bb, f, l))
                bb *= 2
            out.add((b, f, l))
        return sorted(out)

    # -------------------------------------------------------------- police

    def snapshot_recompiles(self) -> None:
        """Mark the end of warmup: compiles after this point are
        unexpected under the learned table."""
        from consensuscruncher_tpu.obs import metrics as obs_metrics

        self._recompiles_baseline = obs_metrics.recompiles()

    def unexpected_recompiles(self) -> int | None:
        from consensuscruncher_tpu.obs import metrics as obs_metrics

        if self._recompiles_baseline is None:
            return None
        return obs_metrics.recompiles() - self._recompiles_baseline


def warm_duplex_ladder(b_max: int, lengths, qual_cap: int = 60) -> int:
    """Force-compile the pow2 duplex-vote ladder at each table length.
    The vote is elementwise (compiles are cheap); warming the ladder is
    what lets a served DCS flush of ANY pair count hit a warm kernel."""
    from consensuscruncher_tpu.ops.duplex_tpu import duplex_batch

    done = 0
    for l in sorted({int(x) for x in lengths}):
        b = 1
        while b <= max(1, int(b_max)):
            z = np.zeros((b, l), np.uint8)
            duplex_batch(z, z, z, z, qual_cap).block_until_ready()
            done += 1
            b *= 2
    return done


def start_learn_loop(autotuner: BucketAutotuner, interval_s: float | None = None):
    """Run ``learn_from_live`` + ``save`` on a daemon thread every
    ``interval_s`` (default: the tuner's learn_window).  Returns the
    thread; set its ``stop_event`` to end it deterministically."""
    import threading

    stop = threading.Event()
    period = float(interval_s if interval_s is not None
                   else autotuner.learn_window)

    def loop():
        while not stop.wait(period):
            try:
                autotuner.learn_from_live()
                autotuner.save()
            except Exception as e:
                print(f"WARNING: autotune learn pass failed ({e})",
                      file=sys.stderr, flush=True)

    thread = threading.Thread(target=loop, daemon=True, name="cct-autotune")
    thread.stop_event = stop
    thread.start()
    return thread
