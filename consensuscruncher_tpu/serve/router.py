"""Fleet router: one stateless front door over N serve daemons.

``cct route`` turns the single-host daemon into a horizontally scaled
fleet: submits are **consistent-hashed by idempotency key** onto worker
daemons (each keeping its own journal, warm compile cache, autotune table
and device set), and the router itself holds no durable state — every
byte that matters for exactly-once recovery already lives in the workers'
write-ahead journals and per-job manifests.  Kill the router and restart
it with the same member list: keys hash to the same owners, keyed polls
resolve against the workers' journal-replayed jobs, nothing is lost.

Routing discipline:

- **Sticky placement.** :class:`HashRing` maps ``idempotency_key(spec)``
  to a member through ``vnodes`` virtual points per member, so a resubmit
  of the same spec always lands on the same worker (whose journal dedup
  collapses it onto the tracked job) and membership changes remap only
  ~1/N of the key space (pinned by the ring unit tests).
- **Replay-aware failover.** A member that fails a forward (or
  ``down_after`` consecutive health probes) is marked down; requests walk
  the ring to the next *up* owner.  For a job the router has seen, the
  cached spec is **resubmitted by key** to the new owner — the workers
  share a filesystem, so the new owner's ``--resume`` path completes the
  dead node's partial work byte-identically, and the journal dedup makes
  the whole dance exactly-once.  A recovered member rejoins the ring
  automatically on its next healthy probe (rebalance: its keys simply
  resolve home again; the stand-in owner's copy of any in-flight job is
  a terminal no-op thanks to idempotent outputs).
- **Bounded work stealing.** A batch/scavenger submit whose home node has
  ``steal_threshold``-deep queues may be steered to the least-loaded up
  member when that member is at least ``steal_margin`` jobs shallower —
  interactive jobs never move (stickiness is their latency warranty), and
  a steal is an optimization only: the ``route.steal`` fault site forces
  the job home, never fails it.

Router HA (:class:`RingView`): the router is no longer a single point of
failure.  An **epoch-numbered ring-view document** — NDJSON records
appended with fsync and compacted through ``manifest.commit_file``, torn-
write tolerant exactly like the job journal — is shared by an active
router and any number of standbys.  A standby health-probes the active's
advertised address; after ``takeover_after`` consecutive failed probes it
takes over by bumping the epoch and publishing itself.  Every forward a
router sends carries its ``(epoch, router_id)``; workers **fence** stale
routers by rejecting forwards whose epoch is below the highest they have
accepted (persisted via a journal ``fence`` marker), so a zombie router
that wakes up after a takeover cannot double-dispatch — its first forward
comes back ``fenced`` and it demotes itself to a refusing standby.

Journal adoption: a member down past ``adopt_after_s`` is permanently
lost as far as its journaled jobs are concerned — so the active router
(or ``cct route --adopt NODE``) replays the dead member's journal,
resubmits every non-terminal job **by idempotency key** to its ring
successor (worker journal dedup + manifest ``--resume`` keep that
exactly-once and byte-identical), and appends an ``adopted`` tombstone
marker to the dead journal.  A returning zombie worker replays the
tombstone, drops the adopted jobs instead of re-running them, and counts
each drop in ``fencing_rejections``.

Poison containment: every failover/adoption/steal path above *re-runs*
a job somewhere else, which is exactly how a deterministic crasher
becomes a fleet-wide crash loop.  The ring view therefore carries a
per-key **fleet attempt lineage** (``attempts``): failover resubmit,
adoption, journal recovery and work stealing all consult and increment
it, and every submit forward hands the count to the worker (whose
scheduler journals a ``suspect`` marker before each dispatch), so
``CCT_SERVE_MAX_FLEET_ATTEMPTS`` caps a key's total attempts across
routers — including a standby that takes over mid-crash-loop, which
inherits the lineage from the view doc.  Past the cap the key comes
back ``{"quarantined": true}`` and the owning worker journals a durable
``quarantined`` marker; ``cct route --release KEY`` (the ``release``
op) lifts it fleet-wide and resets the lineage.

Fault sites (registered in ``tools/cctlint/fault_sites.py``, armed by the
chaos tests): ``route.member_down`` (a forward hits a dead member),
``route.steal`` (the steal decision itself), ``route.resubmit`` (the
failover resubmission), ``route.router_down`` (the standby's probe of the
active router), ``route.adopt`` (the adoption sweep), ``route.fence``
(worker-side epoch admission).

Wire protocol: the same NDJSON ops as :mod:`serve.server`, plus
``{"op": "locate", "key": ...}`` -> the member currently owning the key
(clients use it to re-resolve a direct worker connection after a kill).
``status``/``result`` through the router are **key-addressed** — worker
job ids are per-daemon and collide across the fleet.

Metrics: the router's ``metrics`` op merges every member's labeled
series (so per-qos dashboards keep working unchanged), nests each
member's full doc under ``nodes.<name>``, and the Prometheus rendering
(:func:`obs.metrics.render_fleet_prometheus`) adds ``cct_fleet_*``
gauges plus node-labeled per-member series.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import tempfile
import threading
import time
from bisect import bisect_right
from collections import OrderedDict

from consensuscruncher_tpu.obs import flight as obs_flight
from consensuscruncher_tpu.obs import history as obs_history
from consensuscruncher_tpu.obs import metrics as obs_metrics
from consensuscruncher_tpu.obs import prof as obs_prof
from consensuscruncher_tpu.obs import trace as obs_trace
from consensuscruncher_tpu.serve import journal as journal_mod
from consensuscruncher_tpu.serve.client import ServeClient, ServeClientError
from consensuscruncher_tpu.serve.journal import idempotency_key
from consensuscruncher_tpu.serve.server import ServeServer
from consensuscruncher_tpu.utils import faults, sanitize
from consensuscruncher_tpu.utils.manifest import commit_file
from consensuscruncher_tpu.utils.profiling import Counters

# qos classes eligible for cross-node stealing: latency-insensitive work
# whose gang compatibility survives the move (gangs key on cutoff and
# qualscore, which travel with the spec)
STEALABLE_QOS = ("batch", "scavenger")


def _forward_timeout_s() -> float | None:
    """Default deadline for a member forward that did not bring its own:
    a blackholed worker must cost a bounded wait, never a wedged router
    thread.  0 restores the legacy unbounded behavior."""
    v = float(os.environ.get("CCT_ROUTE_FORWARD_TIMEOUT_S", "60"))
    return None if v <= 0 else v


def _probe_timeout_s() -> float:
    """Deadline for health probes (member sweeps, standby->active)."""
    return float(os.environ.get("CCT_ROUTE_PROBE_TIMEOUT_S", "5"))


class HashRing:
    """Deterministic consistent-hash ring with virtual nodes.

    ``vnodes`` points per member, positioned by sha256 of
    ``"<member>#<i>"`` — no process seeding anywhere, so every router
    (and every restart) builds the identical ring from the same member
    list.  ``owner`` walks clockwise from the key's position to the
    first member present in ``up`` (ring stability: a down member's keys
    fall to its clockwise successors; everyone else's keys do not move).
    """

    def __init__(self, members, vnodes: int = 64):
        self.vnodes = max(1, int(vnodes))
        self.members = tuple(dict.fromkeys(members))  # ordered, unique
        points = []
        for m in self.members:
            for i in range(self.vnodes):
                h = hashlib.sha256(f"{m}#{i}".encode()).digest()
                points.append((int.from_bytes(h[:8], "big"), m))
        points.sort()
        self._hashes = [p[0] for p in points]
        self._owners = [p[1] for p in points]

    @staticmethod
    def key_position(key: str) -> int:
        return int.from_bytes(
            hashlib.sha256(str(key).encode()).digest()[:8], "big")

    def owner(self, key: str, up=None) -> str | None:
        """The member owning ``key`` among ``up`` (default: all members);
        None when no candidate is up."""
        if not self._hashes:
            return None
        allowed = set(self.members if up is None else up)
        if not allowed:
            return None
        start = bisect_right(self._hashes, self.key_position(key))
        n = len(self._owners)
        for step in range(n):
            m = self._owners[(start + step) % n]
            if m in allowed:
                return m
        return None

    def preference(self, key: str) -> list[str]:
        """All members in ring-walk order from the key (first = owner,
        rest = failover order) — handy for tests and debugging."""
        out: list[str] = []
        if not self._hashes:
            return out
        start = bisect_right(self._hashes, self.key_position(key))
        n = len(self._owners)
        for step in range(n):
            m = self._owners[(start + step) % n]
            if m not in out:
                out.append(m)
                if len(out) == len(self.members):
                    break
        return out


class RingView:
    """Epoch-numbered ring-view document shared by the router pair.

    NDJSON, one record per epoch publication::

      {"address": ..., "epoch": 3, "members": [["w0", "/run/w0.sock"], ...],
       "router": "r1", "t": 1722900000.0, "v": 1}

    Durability mirrors the job journal: every :meth:`publish` appends one
    fsync'd record (open/append/fsync/close — epoch changes are rare), and
    once the file outgrows ``max_records`` it is compacted to just the
    current record through ``manifest.commit_file`` (fsync + rename +
    dir-fsync), so a crash mid-compaction leaves the old doc or the new
    one, never a mix.  :meth:`load` is torn-write tolerant: a truncated
    final record — a crash mid-append, or the byte-boundary truncations
    the torn-doc test applies — is skipped and the highest *committed*
    epoch wins.
    """

    def __init__(self, path: str, max_records: int = 256):
        self.path = str(path)
        self.max_records = max(2, int(max_records))
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        self._lock = sanitize.tracked_lock("ringview.lock")

    def scan(self) -> tuple[list[dict], dict]:
        """All decodable records plus ``{"records", "skipped",
        "torn_tail"}`` (the torn-doc test asserts on the info)."""
        records: list[dict] = []
        info = {"records": 0, "skipped": 0, "torn_tail": False}
        sanitize.yield_point("ringview.scan")
        if not os.path.exists(self.path):
            return records, info
        with open(self.path, "rb") as fh:
            raw = fh.read()
        lines = raw.split(b"\n")
        tail = lines.pop() if lines else b""
        if tail.strip():
            lines.append(tail)
        for idx, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
                if not isinstance(rec, dict) or "epoch" not in rec:
                    raise ValueError("not a ring-view record")
                rec["epoch"] = int(rec["epoch"])
            except (ValueError, TypeError):
                info["skipped"] += 1
                if idx == len(lines) - 1 and line == tail:
                    info["torn_tail"] = True
                continue
            records.append(rec)
            info["records"] += 1
        return records, info

    def load(self) -> dict | None:
        """The committed record with the highest epoch, or None."""
        records, _info = self.scan()
        if not records:
            return None
        return max(records, key=lambda r: r["epoch"])

    def publish(self, epoch: int, router: str, address,
                members: list[tuple[str, object]],
                journals: dict | None = None,
                warm: dict | None = None,
                attempts: dict | None = None) -> dict:
        """Append one fsync'd epoch record (compacting first when the doc
        has grown past ``max_records``); returns the record.  ``warm`` is
        the fleet's warm-join state — paths to the shared XLA compile
        cache dir, the autotune table and the result-cache plane — so a
        member spawned later reads ONE document and joins hot.
        ``attempts`` is the fleet-wide per-key attempt lineage (key ->
        count): riding the epoch doc makes the retry budget survive a
        router takeover — the standby inherits exactly the counts the
        dead active had spent."""
        rec = {
            "v": 1, "epoch": int(epoch), "router": str(router),
            "address": (list(address)
                        if isinstance(address, tuple) else address),
            "members": [[name, (list(addr) if isinstance(addr, tuple)
                                else addr)] for name, addr in members],
            "t": round(time.time(), 3),
        }
        if journals:
            rec["journals"] = dict(journals)
        if warm:
            rec["warm"] = {k: v for k, v in warm.items() if v}
        if attempts:
            rec["attempts"] = {str(k): int(v) for k, v in attempts.items()}
        line = json.dumps(rec, sort_keys=True,
                          separators=(",", ":")).encode() + b"\n"
        with self._lock:
            records, _info = self.scan()
            if len(records) >= self.max_records:
                self._compact(records)
            fd = os.open(self.path,
                         os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
            try:
                os.write(fd, line)
                os.fsync(fd)
            finally:
                os.close(fd)
        return rec

    def _compact(self, records: list[dict]) -> None:
        keep = max(records, key=lambda r: r["epoch"])
        fd, tmp = tempfile.mkstemp(
            prefix=os.path.basename(self.path) + ".cmp.",
            dir=os.path.dirname(os.path.abspath(self.path)))
        try:
            with os.fdopen(fd, "wb") as fh:
                fh.write(json.dumps(keep, sort_keys=True,
                                    separators=(",", ":")).encode() + b"\n")
            commit_file(tmp, self.path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise


class _Member:
    """Router-side view of one worker daemon (soft state only)."""

    def __init__(self, name: str, address, client):
        self.name = name
        self.address = address
        self.client = client
        self.up = True
        self.fails = 0          # consecutive failed health probes
        self.queued = 0
        self.running = 0
        self.quarantined = 0    # parked poison keys (healthz-reported)
        self.draining = False
        self.last_seen = 0.0
        self.down_since: float | None = None   # wall clock of the outage
        self.adopted_at: float | None = None   # journal adopted this outage

    def describe(self) -> dict:
        return {
            "name": self.name,
            "address": (list(self.address)
                        if isinstance(self.address, tuple) else self.address),
            "up": self.up,
            "queued": self.queued,
            "running": self.running,
            "quarantined": self.quarantined,
            "draining": self.draining,
        }


def parse_members(text: str) -> list[tuple[str, object]]:
    """``'n0=/tmp/a.sock,n1=host:port'`` (or bare addresses, auto-named
    ``n0..``) -> ``[(name, address), ...]`` with tuple TCP addresses."""
    out: list[tuple[str, object]] = []
    for i, part in enumerate(str(text or "").split(",")):
        part = part.strip()
        if not part:
            continue
        if "=" in part and os.sep not in part.split("=", 1)[0]:
            name, addr = part.split("=", 1)
            name = name.strip()
        else:
            name, addr = f"n{i}", part
        addr = addr.strip()
        if ":" in addr and os.sep not in addr:
            host, port = addr.rsplit(":", 1)
            out.append((name, (host, int(port))))
        else:
            out.append((name, addr))
    if not out:
        raise ValueError("router: empty member list")
    names = [n for n, _ in out]
    if len(set(names)) != len(names):
        raise ValueError(f"router: duplicate member names in {names}")
    return out


class Router:
    """Stateless routing core (the :class:`RouterServer` wire shell and
    the ``cct route`` CLI both drive this).

    ``members``: ``[(name, address), ...]``.  ``client_factory`` is
    dependency injection for the unit tests (anything with the
    ``ServeClient.request`` shape works).

    HA knobs (all optional; without ``ring_view`` the router behaves
    exactly like the PR-9 single router — no epochs on forwards, so
    pre-HA fleets keep working):

    - ``router_id`` names this router in the ring-view doc and on every
      forward;
    - ``ring_view`` is the shared epoch document (path or
      :class:`RingView`);
    - ``standby=True`` starts in the refusing-standby role: ops are
      rejected ``{"standby": true, "busy": true}`` (clients rotate to
      the active) while the monitor probes the active's advertised
      address and takes over after ``takeover_after`` failed probes;
    - ``advertise`` is the address published in the ring view (what
      standbys probe and what takeover replaces);
    - ``adopt_after_s`` + ``journals`` (member name -> journal path)
      arm the adoption sweep for permanently lost members.
    """

    def __init__(self, members, *, vnodes: int = 64,
                 steal_threshold: int = 4, steal_margin: int = 2,
                 health_interval_s: float = 2.0, down_after: int = 3,
                 spec_cache_max: int = 4096, client_factory=None,
                 start_monitor: bool = True,
                 router_id: str = "r0", ring_view=None,
                 standby: bool = False, takeover_after: int = 3,
                 advertise=None, adopt_after_s: float | None = None,
                 journals: dict | None = None,
                 result_cache=None, cache_journal: str | None = None,
                 warm_state: dict | None = None):
        self.counters = Counters()
        if client_factory is None:
            counters = self.counters

            def client_factory(address):
                # the router's own counters ride every member client so
                # forward timeouts / corrupted replies are visible in
                # this router's metrics (``wire_timeouts`` etc.)
                return ServeClient(address, connect_timeout=10.0,
                                   retries=1, retry_base_s=0.1,
                                   counters=counters)
        self._client_factory = client_factory
        self._members: dict[str, _Member] = OrderedDict()
        for name, address in members:
            self._members[name] = _Member(name, address,
                                          client_factory(address))
        self.ring = HashRing(list(self._members), vnodes=vnodes)
        self.vnodes = max(1, int(vnodes))
        self.steal_threshold = max(1, int(steal_threshold))
        self.steal_margin = max(1, int(steal_margin))
        self.health_interval_s = float(health_interval_s)
        self.down_after = max(1, int(down_after))
        self.closing = False
        self._draining = False
        self._started_at = time.time()
        self._lock = sanitize.tracked_lock("router.lock")
        # ---------------------------------------------------------- HA role
        self.router_id = str(router_id)
        if isinstance(ring_view, str):
            ring_view = RingView(ring_view)
        self.ring_view: RingView | None = ring_view
        self.standby = bool(standby)
        self.takeover_after = max(1, int(takeover_after))
        self.advertise = advertise
        self.adopt_after_s = None if adopt_after_s is None \
            else float(adopt_after_s)
        self.journals = dict(journals or {})
        # ------------------------------------- content-addressed cache
        # consult-before-dispatch: a committed entry for a submit's
        # content digest answers the submit without touching a worker.
        # ``warm_state`` (compile cache dir, autotune table, cache root)
        # rides every ring-view publish so late joiners start hot.
        if isinstance(result_cache, str):
            from consensuscruncher_tpu.serve.result_cache import ResultCache
            result_cache = ResultCache(result_cache,
                                       node=f"router-{router_id}",
                                       counters=self.counters)
        self.result_cache = result_cache
        self.warm_state = dict(warm_state or {})
        # key -> terminal job doc for answers already served from the
        # cache; journaled (append-fsync'd, like a terminal journal
        # answer) BEFORE the reply leaves, so a keyed poll arriving
        # after a router kill -9 still resolves against the replayed map
        self._cache_answers: dict[str, dict] = {}
        self._cache_journal: journal_mod.Journal | None = None
        if cache_journal:
            self._load_cache_journal(cache_journal)
            self._cache_journal = journal_mod.Journal(
                cache_journal, max_bytes=int(os.environ.get(
                    "CCT_ROUTE_CACHE_JOURNAL_MAX_BYTES", str(1 << 20))))
        self.fenced = False         # a worker rejected our epoch: demoted
        self._active_fails = 0      # standby's failed probes of the active
        # fleet retry budget: per-key attempt lineage spent by failover
        # resubmit / adoption / journal recovery / stealing, carried in
        # the ring view so a takeover (or restart) inherits the spend
        self.max_fleet_attempts = int(os.environ.get(
            "CCT_SERVE_MAX_FLEET_ATTEMPTS", "3"))
        self._attempts: dict[str, int] = {}
        if self.ring_view is not None:
            doc = self.ring_view.load()
            self.epoch = int((doc or {}).get("epoch") or 0)
            self._merge_attempts(doc)
            if not self.standby:
                self._claim_active()
        else:
            self.epoch = 0
        # bounded key -> {"spec", "node"} soft state; the ONLY thing the
        # failover resubmission needs, and it is reconstructible: a keyed
        # poll for an unknown key still resolves to the ring owner, whose
        # journal has the job if it was ever acknowledged anywhere
        self._placed: OrderedDict[str, dict] = OrderedDict()
        self._placed_max = max(16, int(spec_cache_max))
        self._monitor: threading.Thread | None = None
        if start_monitor:
            self.start_monitor()

    def start_monitor(self) -> None:
        if self._monitor is not None:
            return
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="route-health", daemon=True)
        self._monitor.start()

    # ------------------------------------------------------------ members

    def members(self) -> list[_Member]:
        with self._lock:
            return list(self._members.values())

    def _member(self, name: str) -> _Member:
        with self._lock:
            return self._members[name]

    def _mark_down(self, member: _Member, why: str) -> None:
        with self._lock:
            was_up = member.up
            member.up = False
            if member.down_since is None:
                member.down_since = time.time()
        if was_up:
            self.counters.add("member_down_events", 1)
            print(f"route: member {member.name} DOWN ({why}); "
                  "failing its keys over to the next ring owners",
                  file=sys.stderr, flush=True)

    def _mark_up(self, member: _Member, health: dict) -> None:
        with self._lock:
            was_down = not member.up
            member.up = True
            member.fails = 0
            member.queued = int(health.get("queued", 0))
            member.running = int(health.get("running", 0))
            member.quarantined = int(health.get("quarantined", 0) or 0)
            member.draining = health.get("status") == "draining"
            member.last_seen = time.time()
            member.down_since = None
            member.adopted_at = None
        if was_down:
            print(f"route: member {member.name} UP again; its ring range "
                  "rebalances home", file=sys.stderr, flush=True)

    def _monitor_loop(self) -> None:
        while not self.closing:
            if self.standby:
                self.probe_active()
            else:
                self.probe_members()
                self.adoption_sweep()
            deadline = time.monotonic() + self.health_interval_s
            while not self.closing and time.monotonic() < deadline:
                time.sleep(min(0.2, self.health_interval_s))

    def probe_members(self) -> None:
        """One health sweep (the monitor loop calls this; tests call it
        directly for deterministic timing)."""
        with obs_trace.span("route.probe",
                            members=len(self.members())) as sp:
            down = 0
            for member in self.members():
                try:
                    health = member.client.request(
                        {"op": "healthz"},
                        timeout=_probe_timeout_s())["health"]
                except Exception as e:
                    member.fails += 1
                    down += 1
                    if member.fails >= self.down_after and member.up:
                        self._mark_down(
                            member, f"{member.fails} failed probes: {e}")
                    continue
                self._mark_up(member, health)
            sp.note(failed_probes=down)

    # --------------------------------------------------------- HA: epochs

    def _member_list(self) -> list[tuple[str, object]]:
        with self._lock:
            return [(m.name, m.address) for m in self._members.values()]

    def _claim_active(self) -> None:
        """Become (or confirm ourselves as) the active router: bump the
        epoch past anything the ring view has seen and publish."""
        doc = self.ring_view.load()
        self.epoch = max(self.epoch, int((doc or {}).get("epoch") or 0)) + 1
        self._merge_attempts(doc)
        self.ring_view.publish(self.epoch, self.router_id,
                               self.advertise, self._member_list(),
                               journals=self.journals,
                               warm=self.warm_state,
                               attempts=self._attempts_snapshot())
        self.standby = False
        self.fenced = False
        self._active_fails = 0

    def _publish_view(self) -> None:
        """Re-publish after a membership change.  Epoch bumps so every
        observer (standby, workers via fencing) sees one total order of
        ring views; a no-ring-view router is a silent no-op."""
        if self.ring_view is None or self.standby:
            return
        self.epoch += 1
        try:
            faults.fault_point("route.view_publish")
            self.ring_view.publish(self.epoch, self.router_id,
                                   self.advertise, self._member_list(),
                                   journals=self.journals,
                                   warm=self.warm_state,
                                   attempts=self._attempts_snapshot())
        except (faults.FaultError, OSError) as e:
            # the in-memory membership change is already live and the
            # epoch bump is kept: the view doc is advertisement state for
            # standbys, and the NEXT successful publish (any membership
            # change or takeover) carries this epoch forward — a failed
            # write degrades standby visibility, never routing
            print(f"WARNING: route[{self.router_id}]: ring-view publish "
                  f"failed ({e}); epoch {self.epoch} will be advertised "
                  "by the next publish", file=sys.stderr, flush=True)

    def start(self, advertise=None) -> None:
        """Late activation for the CLI: the advertised address may only be
        known once the server socket is bound.  Claims the active role
        (unless standby), then starts the monitor."""
        if advertise is not None:
            self.advertise = advertise
        if self.ring_view is not None and not self.standby:
            self._claim_active()
        self.start_monitor()

    def probe_active(self) -> None:
        """Standby's half of the monitor: health-probe the active router's
        advertised address; ``takeover_after`` consecutive failures (or an
        armed ``route.router_down`` fault) trigger :meth:`take_over`.  An
        answering active with a *higher* epoch resets our view (we may
        have been demoted while partitioned)."""
        if self.ring_view is None:
            return
        doc = self.ring_view.load()
        if doc is None:
            # nobody has ever published: claim the fleet
            self._active_fails += 1
            if self._active_fails >= self.takeover_after:
                self.take_over("ring view empty")
            return
        if doc.get("router") == self.router_id:
            # the view says we are active (e.g. a restart after takeover)
            self.epoch = max(self.epoch, int(doc.get("epoch") or 0))
            self.standby = False
            return
        # mirror the active's published membership so a takeover inherits
        # mid-life member_add/member_remove (the ring view is the one
        # authority on who is in the fleet)
        self._sync_members(doc)
        address = doc.get("address")
        if isinstance(address, list):
            address = (address[0], int(address[1]))
        try:
            faults.fault_point("route.router_down")
            health = ServeClient(address,
                                 connect_timeout=_probe_timeout_s(),
                                 retries=0, counters=self.counters).request(
                {"op": "healthz"}, timeout=_probe_timeout_s())["health"]
        except (faults.FaultError, ServeClientError, OSError, TypeError) as e:
            self._active_fails += 1
            print(f"route[{self.router_id}]: active router "
                  f"{doc.get('router')} probe failed "
                  f"({self._active_fails}/{self.takeover_after}): {e}",
                  file=sys.stderr, flush=True)
            if self._active_fails >= self.takeover_after:
                self.take_over(f"{self._active_fails} failed probes: {e}")
            return
        self._active_fails = 0
        self.epoch = max(self.epoch, int(doc.get("epoch") or 0),
                         int(health.get("epoch") or 0))

    def _sync_members(self, doc: dict) -> None:
        """Standby's membership mirror: adopt the member list the active
        last published.  Down/adoption bookkeeping for members we already
        track is preserved — only the set of names changes."""
        published = doc.get("members") or []
        if not published:
            return
        want: dict[str, object] = {}
        for name, address in published:
            if isinstance(address, list):
                address = (address[0], int(address[1]))
            want[str(name)] = address
        # journal paths ride along so a takeover can still adopt members
        # that were member_add'ed after this standby was configured
        for name, path in (doc.get("journals") or {}).items():
            self.journals.setdefault(str(name), str(path))
        # the fleet attempt lineage rides along too: a takeover must not
        # grant a crash-looping key a fresh retry budget
        self._merge_attempts(doc)
        with self._lock:
            changed = False
            for name, address in want.items():
                if name not in self._members:
                    self._members[name] = _Member(
                        name, address, self._client_factory(address))
                    changed = True
            for name in [n for n in self._members if n not in want]:
                del self._members[name]
                changed = True
            if changed:
                self.ring = HashRing(list(self._members), vnodes=self.vnodes)

    def take_over(self, why: str) -> None:
        """Standby -> active: bump the epoch, publish, and immediately
        probe the members so routing state is warm.  The old active is
        fenced out by the workers the moment our first forward lands (its
        lower epoch is rejected from then on)."""
        old_epoch = self.epoch
        self._claim_active()
        self.counters.add("router_failovers", 1)
        obs_trace.event("route.takeover", router=self.router_id,
                        old_epoch=old_epoch, epoch=self.epoch, why=why)
        obs_trace.flush()  # durable before the first fencing forward
        obs_flight.set_identity(epoch=self.epoch)
        print(f"route[{self.router_id}]: TAKEOVER epoch {old_epoch} -> "
              f"{self.epoch} ({why})", file=sys.stderr, flush=True)
        # the takeover is the incident the flight ring exists for: what
        # the standby observed leading up to it survives in the dump
        obs_flight.record("router_takeover", router=self.router_id,
                          epoch=self.epoch, why=why)
        obs_flight.dump(reason="router-takeover")
        self.probe_members()

    def _standby_refusal(self) -> dict | None:
        """Non-None when this router must not serve: standby role, or
        demoted by a worker's fencing rejection.  ``busy`` makes the
        client's retry loop rotate to its next router address."""
        if self.standby:
            return {"ok": False, "standby": True, "busy": True,
                    "router": self.router_id, "epoch": self.epoch,
                    "error": f"router {self.router_id} is standby"}
        if self.fenced:
            return {"ok": False, "standby": True, "busy": True,
                    "fenced": True, "router": self.router_id,
                    "epoch": self.epoch,
                    "error": f"router {self.router_id} was fenced "
                             f"(a newer epoch than {self.epoch} is live)"}
        return None

    def _check_active(self) -> None:
        refusal = self._standby_refusal()
        if refusal is not None:
            raise ServeClientError(refusal["error"], refusal)

    # --------------------------------------------- fleet retry budget

    def _merge_attempts(self, doc: dict | None) -> None:
        """Max-merge a ring-view doc's ``attempts`` lineage into ours
        (counts only grow: two routers that each saw part of a key's
        history converge on the larger spend, never a reset)."""
        if not doc:
            return
        published = doc.get("attempts") or {}
        if not isinstance(published, dict):
            return
        with self._lock:
            for key, n in published.items():
                try:
                    n = int(n)
                except (TypeError, ValueError):
                    continue
                if n > self._attempts.get(str(key), 0):
                    self._attempts[str(key)] = n

    def _attempts_snapshot(self) -> dict:
        with self._lock:
            return dict(self._attempts)

    def _budget_spend(self, key: str, what: str, strict: bool = True) -> bool:
        """Spend one fleet attempt for ``key``; the redispatch paths
        (failover resubmit, adoption, journal recovery, steal) all come
        through here.  Past ``CCT_SERVE_MAX_FLEET_ATTEMPTS`` nothing is
        spent: ``strict`` raises the quarantined refusal (polls and
        resubmits answer it to the client), non-strict returns False so
        the caller degrades (a steal goes home, an adoption forwards the
        exhausted lineage for the worker to quarantine durably)."""
        if self.max_fleet_attempts <= 0 or not key:
            return True
        with self._lock:
            n = self._attempts.get(key, 0) + 1
            if n <= self.max_fleet_attempts:
                self._attempts[key] = n
                return True
            spent = n - 1
        self.counters.add("fleet_attempts_exhausted", 1)
        reason = (f"fleet retry budget exhausted for key {key} "
                  f"({spent}/{self.max_fleet_attempts} attempts across "
                  f"the fleet; {what} refused)")
        obs_flight.record("fleet_budget_exhausted", key=key, what=what,
                          attempts=spent, router=self.router_id)
        if strict:
            raise ServeClientError(reason, {
                "ok": False, "error": reason, "refused": True,
                "quarantined": True, "reason": reason, "key": key})
        print(f"route[{self.router_id}]: {reason}",
              file=sys.stderr, flush=True)
        return False

    def _prune_attempts(self, key: str, reply: dict) -> None:
        """A key observed ``done`` no longer needs its lineage — the
        dedup cache answers any re-submit, so the budget entry is dead
        weight (and the map must not grow with every honest steal)."""
        if (reply.get("job") or {}).get("state") == "done":
            with self._lock:
                self._attempts.pop(key, None)

    def _submit_doc(self, spec: dict, key: str) -> dict:
        """Submit forward doc with the key's fleet lineage riding along:
        the worker max-merges it, so its own budget gate (and the
        ``suspect`` ordinals it journals) continue the fleet-wide count
        instead of restarting from zero on every node."""
        doc = {"op": "submit", "spec": spec}
        with self._lock:
            n = self._attempts.get(key, 0)
        if n:
            doc["attempts"] = n
        return doc

    def release(self, key: str) -> dict:
        """Lift a quarantine fleet-wide (``cct route --release KEY``):
        reset the ring-carried attempt lineage, then ask every up member
        to release the key — the durable marker usually lives on the
        ring owner, but a failover may have left it on a previous
        incarnation's node, so all of them are asked.  The reset lineage
        is published immediately: a router restart must not resurrect
        the spent budget and re-quarantine the key on its next attempt."""
        self._check_active()
        key = str(key)
        with self._lock:
            self._attempts.pop(key, None)
        released = []
        for member in self.members():
            if not member.up:
                continue
            try:
                reply = self._forward(member, {"op": "release", "key": key})
            except ServeClientError:
                continue
            if reply.get("released"):
                released.append(member.name)
        if released:
            self.counters.add("quarantine_released", 1)
        self._publish_view()
        return {"key": key, "released": bool(released),
                "node": released[0] if released else None}

    # ------------------------------------------------------- HA: adoption

    def adoption_sweep(self) -> None:
        """Adopt the journal of every member down past ``adopt_after_s``
        (once per outage).  Failures are logged and retried next sweep —
        adoption is idempotent end to end (resubmits dedup by key, the
        tombstone is only written after every resubmit was acked)."""
        if self.adopt_after_s is None or not self.journals:
            return
        now = time.time()
        for member in self.members():
            if member.up or member.down_since is None \
                    or member.adopted_at is not None:
                continue
            if now - member.down_since < self.adopt_after_s:
                continue
            if member.name not in self.journals:
                continue
            try:
                self.adopt(member.name)
            except Exception as e:
                print(f"WARNING: route[{self.router_id}]: adoption of "
                      f"{member.name} failed ({e}); retrying next sweep",
                      file=sys.stderr, flush=True)

    def adopt(self, node: str, force: bool = False) -> dict:
        """Replay a dead member's journal, resubmit every non-terminal job
        by idempotency key to its ring successor, then tombstone the
        journal with an ``adopted`` marker.

        Exactly-once: resubmits dedup on the successor's journal, the
        successor's ``--resume`` completes any partial stage outputs
        byte-identically, and the tombstone is appended only after every
        resubmit was acknowledged — a failure anywhere aborts without the
        tombstone, so the next sweep (or a returning member) retries with
        nothing lost and nothing doubled."""
        self._check_active()
        with self._lock:
            member = self._members.get(str(node))
        if member is None:
            raise ServeClientError(f"unknown member {node!r}",
                                   {"bad_request": True})
        path = self.journals.get(member.name)
        if not path:
            raise ServeClientError(
                f"no journal path configured for member {node!r}",
                {"bad_request": True})
        if member.up and not force:
            raise ServeClientError(
                f"member {node!r} is up; refusing to adopt a live journal "
                "(pass force to override)", {"bad_request": True})
        faults.fault_point("route.adopt")
        jobs, info = journal_mod.replay(path)
        quarantined = info.get("quarantined") or {}
        pending = []
        skipped_quarantined = 0
        for jid in sorted(jobs):
            rec = jobs[jid]
            if rec.get("state") in ("done", "failed"):
                continue
            if rec.get("adopted"):
                continue  # an earlier adoption already moved it
            if rec.get("key") in quarantined:
                # the dead member had already condemned this key: moving
                # it to a successor would restart the crash loop the
                # quarantine exists to stop — it stays parked until an
                # operator releases it
                skipped_quarantined += 1
                continue
            spec = rec.get("spec")
            if not isinstance(spec, dict) or not spec.get("input") \
                    or not spec.get("output"):
                continue  # rotated-away accepted record: nothing to move
            pending.append((jid, spec, rec))
        if skipped_quarantined:
            print(f"route[{self.router_id}]: adoption of {member.name}: "
                  f"{skipped_quarantined} quarantined job(s) left parked "
                  "(release to retry)", file=sys.stderr, flush=True)
        adopted_keys = []
        for jid, spec, rec in pending:
            # fleet budget: an adoption resubmit is one more attempt on
            # this key's lineage.  Non-strict past the cap — the job is
            # still forwarded (carrying the exhausted count) so the
            # successor's scheduler quarantines it DURABLY instead of
            # the router silently dropping it
            self._budget_spend(str(rec.get("key") or ""),
                               "adoption resubmit", strict=False)
            # the adoption span continues the DEAD member's trace: it
            # links to the ack context persisted on the journal record,
            # and the nested route.submit span inherits that trace_id —
            # so the successor's spans land on the original timeline
            ctx = rec.get("trace") if isinstance(rec.get("trace"), dict) \
                else None
            if ctx is None and obs_trace.enabled():
                obs_trace.note_orphan()
            with obs_trace.span("route.adopt_job", link=ctx,
                                trace_id=rec.get("trace_id"),
                                node=member.name, job_id=jid):
                reply = self.submit(spec)
            if not reply.get("ok"):
                if reply.get("quarantined"):
                    # the successor already holds a quarantine for this
                    # key: containment won, the job stays parked there
                    skipped_quarantined += 1
                    continue
                raise ServeClientError(
                    f"adoption resubmit of {member.name} job {jid} "
                    f"refused: {reply.get('error')}", dict(reply))
            adopted_keys.append(reply.get("key"))
            print(f"route[{self.router_id}]: adopted {member.name} "
                  f"job {jid} -> {reply.get('node')} "
                  f"(key {reply.get('key')}, "
                  f"duplicate={reply.get('duplicate')})",
                  file=sys.stderr, flush=True)
        # every non-terminal job is acked on a live successor: tombstone
        # the dead journal so a returning zombie drops them at replay
        tomb = journal_mod.Journal(path)
        try:
            tomb.append_marker("adopted", router=self.router_id,
                               epoch=self.epoch or None)
        finally:
            tomb.close()
        with self._lock:
            member.adopted_at = time.time()
        self.counters.add("journals_adopted", 1)
        if adopted_keys:
            self.counters.add("jobs_adopted", len(adopted_keys))
        obs_flight.record("journal_adopted", node=member.name,
                          jobs=len(adopted_keys), router=self.router_id)
        print(f"route[{self.router_id}]: journal of {member.name} adopted "
              f"({len(adopted_keys)} job(s) resubmitted, "
              f"{info['records']} record(s) replayed)",
              file=sys.stderr, flush=True)
        return {"node": member.name, "jobs_adopted": len(adopted_keys),
                "keys": adopted_keys}

    # ---------------------------------------------------- HA: membership

    def member_add(self, name: str, address, journal=None) -> dict:
        """Grow the ring by one member (the chaos conductor's membership
        events drive this).  ~1/N of the key space remaps to the new
        member; everything else stays sticky.  ``journal`` registers the
        member's journal path so a later decommission can still adopt
        what it acknowledged."""
        self._check_active()
        name = str(name)
        if isinstance(address, list):
            address = (address[0], int(address[1]))
        with self._lock:
            if name in self._members:
                raise ServeClientError(f"member {name!r} already exists",
                                       {"bad_request": True})
            self._members[name] = _Member(name, address,
                                          self._client_factory(address))
            self.ring = HashRing(list(self._members), vnodes=self.vnodes)
            fleet_size = len(self._members)
        if journal:
            self.journals[name] = str(journal)
        self._publish_view()
        return {"node": name, "fleet_size": fleet_size}

    def member_remove(self, name: str) -> dict:
        """Shrink the ring: the member's keys fall to their ring
        successors.  Its journal path is kept so a later adopt can still
        drain what it had acknowledged."""
        self._check_active()
        name = str(name)
        with self._lock:
            if name not in self._members:
                raise ServeClientError(f"unknown member {name!r}",
                                       {"bad_request": True})
            if len(self._members) == 1:
                raise ServeClientError("refusing to remove the last member",
                                       {"bad_request": True})
            del self._members[name]
            self.ring = HashRing(list(self._members), vnodes=self.vnodes)
            fleet_size = len(self._members)
        self._publish_view()
        return {"node": name, "fleet_size": fleet_size}

    # ------------------------------------------------------------ routing

    def _owner_for(self, key: str, exclude: set | None = None):
        with self._lock:
            up = [m.name for m in self._members.values()
                  if m.up and (not exclude or m.name not in exclude)]
            name = self.ring.owner(key, up=up)
            return None if name is None else self._members.get(name)

    def _remember(self, key: str, spec: dict, node: str,
                  trace: dict | None = None) -> None:
        """Placement cache entry; ``trace`` is the owning worker's ack
        span wire context (from its submit reply) so a later failover
        resubmit can ``follows_from`` the span the dead owner durably
        recorded."""
        with self._lock:
            self._placed[key] = {"spec": dict(spec), "node": node,
                                 "trace": trace if isinstance(trace, dict)
                                 else None}
            self._placed.move_to_end(key)
            while len(self._placed) > self._placed_max:
                self._placed.popitem(last=False)

    def _placed_info(self, key: str) -> dict | None:
        with self._lock:
            info = self._placed.get(key)
            return dict(info) if info else None

    def _forward(self, member: _Member, doc: dict,
                 timeout: float | None = None) -> dict:
        """One member RPC; a transport-level loss (or an armed
        ``route.member_down`` fault) marks the member down and raises
        ``ServeClientError(transport=True)`` for the caller's failover.

        With a ring view configured every forward is stamped with this
        router's ``(epoch, router_id)``; a ``fenced`` rejection from the
        worker means a newer epoch is live — we demote ourselves on the
        spot (no zombie-router double-dispatch) and re-raise."""
        try:
            faults.fault_point("route.member_down")
        except faults.FaultError as e:
            self._mark_down(member, f"injected: {e}")
            raise ServeClientError(str(e), {"transport": True}) from e
        if self.ring_view is not None:
            doc = dict(doc)
            doc["epoch"] = self.epoch
            doc["router"] = self.router_id
        if timeout is None:
            timeout = _forward_timeout_s()
        try:
            # the forward span is the wire context the worker links to:
            # ServeClient stamps the innermost open span onto the doc
            with obs_trace.span("route.forward", op=doc.get("op"),
                                node=member.name):
                return member.client.request(doc, timeout=timeout)
        except ServeClientError as e:
            if e.reply.get("fenced"):
                self._demote(member.name, e.reply)
            if e.reply.get("transport"):
                self._mark_down(member, str(e))
            raise
        except OSError as e:
            self._mark_down(member, str(e))
            raise ServeClientError(str(e), {"transport": True}) from e

    def _demote(self, worker: str, reply: dict) -> None:
        """A worker fenced us: a takeover happened while we thought we
        were active.  Stop serving (clients rotate to the new active) —
        the flight dump records what this zombie saw before it learned."""
        if self.fenced:
            return
        self.fenced = True
        newer = reply.get("epoch")
        print(f"route[{self.router_id}]: FENCED by worker {worker} "
              f"(our epoch {self.epoch} < live {newer}); demoting to "
              "standby-refusal", file=sys.stderr, flush=True)
        obs_flight.record("router_fenced", router=self.router_id,
                          epoch=self.epoch, live_epoch=newer, worker=worker)
        obs_flight.dump(reason="router-fenced")

    def _pick_target(self, key: str, qos: str) -> tuple[_Member, bool]:
        """Home member for the key, or a steal target for deep-queued
        batch/scavenger work.  Returns ``(member, stolen)``."""
        home = self._owner_for(key)
        if home is None:
            raise ServeClientError("no fleet member is up", {"transport": True})
        if qos not in STEALABLE_QOS:
            return home, False
        with self._lock:
            candidates = [m for m in self._members.values()
                          if m.up and not m.draining and m.name != home.name]
            if (home.queued < self.steal_threshold) or not candidates:
                return home, False
            thief = min(candidates, key=lambda m: (m.queued, m.name))
            if thief.queued + self.steal_margin > home.queued:
                return home, False
        try:
            faults.fault_point("route.steal")
        except faults.FaultError as e:
            print(f"WARNING: route: steal fault ({e}); keeping job on "
                  f"home node {home.name}", file=sys.stderr, flush=True)
            return home, False
        # a steal re-homes the key, which is one more place a poison job
        # can take a worker down: it spends from the same fleet lineage.
        # Past the budget the job simply goes home (no amplification;
        # the home scheduler's own gate quarantines it durably there)
        if not self._budget_spend(key, "steal", strict=False):
            return home, False
        return thief, True

    # ---------------------------------------------------------------- ops

    def submit(self, spec: dict, trace: dict | None = None) -> dict:
        """Route one submit; returns the member's wire reply annotated
        with ``node``/``node_address`` (refusals pass through so the
        client's shed/quota handling keeps working).  ``trace`` is the
        submitter's wire trace context: the route-decision span links to
        it, and the span itself rides the forward to the worker, so the
        client -> router -> worker timeline is one connected tree."""
        refusal = self._standby_refusal()
        if refusal is not None:
            return refusal
        if self._draining:
            return {"ok": False, "refused": True,
                    "error": "router is draining; not accepting jobs"}
        spec = dict(spec or {})
        try:
            key = idempotency_key(spec)
        except Exception as e:
            return {"ok": False, "error": f"bad spec: {e}"}
        qos = str(spec.get("qos") or "interactive")
        if not isinstance(trace, dict):
            # a trace-less re-submit of a key this router already placed
            # (client retry after a crash, the chaos conductor's dedup
            # probes) continues the placed job's timeline: the dedup key
            # makes it the same job, so minting a fresh trace here would
            # split one causal tree into two
            info = self._placed_info(key)
            if info is not None and isinstance(info.get("trace"), dict):
                trace = info["trace"]
        cached = self._cache_answer(key, spec, trace)
        if cached is not None:
            return cached
        tried: set[str] = set()
        stolen = False
        with obs_trace.span("route.submit",
                            link=trace if isinstance(trace, dict) else None,
                            key=key, qos=qos) as sp:
            while True:
                if not tried:
                    try:
                        member, stolen = self._pick_target(key, qos)
                    except ServeClientError as e:
                        return {"ok": False, "error": str(e)}
                else:
                    member = self._owner_for(key, exclude=tried)
                    if member is None:
                        return {"ok": False,
                                "error": "no fleet member is up",
                                "transport": True}
                try:
                    reply = self._forward(member, self._submit_doc(spec, key))
                except ServeClientError as e:
                    if e.reply.get("transport"):
                        # forward-time death: fail over around the ring.
                        # The hop to the next owner is a redispatch — it
                        # spends from the key's fleet lineage, so a
                        # crash-looping key stops walking the ring and
                        # the submitter gets the quarantined refusal
                        # (as a reply dict: submit's refusal contract)
                        try:
                            self._budget_spend(key, "ring failover")
                        except ServeClientError as qe:
                            return dict(qe.reply)
                        tried.add(member.name)
                        stolen = False
                        continue
                    if e.reply.get("refused"):
                        if e.reply.get("shed"):
                            # Digest-keyed shed bypass, router half: a
                            # member shed this key under load, but the
                            # answer journal may hold a committed answer
                            # (landed after the pre-forward check) — a
                            # cached submit is answered, never shed.
                            cached = self._cache_answer(key, spec, trace)
                            if cached is not None:
                                self.counters.add("cache_shed_bypass")
                                return cached
                        return dict(e.reply)
                    return {"ok": False, "error": str(e)}
                with self._lock:
                    member.queued += 1  # soft estimate until the next probe
                self._remember(key, spec, member.name,
                               trace=reply.get("trace"))
                self.counters.add("jobs_routed", 1)
                obs_metrics.inc("node_jobs_routed", node=member.name)
                if stolen:
                    self.counters.add("route_steals", 1)
                    obs_metrics.inc("node_steals", node=member.name)
                # route decision, recorded late (the target is only final
                # once a forward actually landed)
                sp.note(node=member.name, stolen=stolen,
                        trace_id=reply.get("trace") and
                        reply["trace"].get("trace_id"))
                reply = dict(reply)
                reply["node"] = member.name
                reply["node_address"] = member.describe()["address"]
                reply["stolen"] = stolen
                return reply

    def resolve(self, key: str) -> _Member:
        """The member a keyed poll should talk to *right now*: the cached
        placement while that node is up, else the current ring owner —
        resubmitting the cached spec there first, so the poll finds the
        job (replay-aware failover).  Raises when no member is up."""
        self._check_active()
        info = self._placed_info(key)
        if info is not None:
            with self._lock:
                member = self._members.get(info["node"])
            if member is not None and member.up:
                return member
        member = self._owner_for(key)
        if member is None:
            raise ServeClientError("no fleet member is up", {"transport": True})
        if info is not None and info["node"] != member.name \
                and info.get("spec"):
            # spec-less entries (locate-sweep re-primes) can't resubmit;
            # the poll falls through to the owner and sweeps again
            self._failover_resubmit(key, info, member)
        return member

    def _failover_resubmit(self, key: str, info: dict,
                           member: _Member) -> None:
        """Resubmit a dead node's job to its new owner.  Exactly-once by
        construction: the new owner's journal dedups on the key, and the
        shared-filesystem ``--resume`` manifest skips any stage the dead
        node already committed — outputs stay byte-identical.

        The resubmit span ``follows_from`` the dead owner's ack span
        (its wire context was cached at placement, or recovered from its
        journal's accepted record), so the job's trace stays one
        connected tree across the kill.  No stored context — e.g. a
        placement inherited from a pre-tracing router — counts a
        ``trace_orphans`` tally instead of fabricating a link."""
        faults.fault_point("route.resubmit")
        # fleet budget, strict: a failover resubmit past the cap raises
        # the quarantined refusal instead of re-running the job — the
        # keyed poll that triggered us answers it to the client
        self._budget_spend(key, "failover resubmit")
        ctx = info.get("trace") if isinstance(info.get("trace"), dict) \
            else None
        if ctx is None and obs_trace.enabled():
            obs_trace.note_orphan()
        with obs_trace.span("route.resubmit", link=ctx, key=key,
                            node=member.name,
                            trace_id=(ctx or {}).get("trace_id")):
            reply = self._forward(member, self._submit_doc(info["spec"], key))
        self._remember(key, info["spec"], member.name,
                       trace=reply.get("trace"))
        self.counters.add("jobs_routed", 1)
        self.counters.add("route_resubmits", 1)
        obs_metrics.inc("node_jobs_routed", node=member.name)
        obs_metrics.inc("node_resubmits", node=member.name)
        print(f"route: resubmitted key {key} to {member.name} "
              f"(job {reply.get('job_id')}, duplicate="
              f"{reply.get('duplicate')})", file=sys.stderr, flush=True)

    def locate(self, key: str) -> dict:
        member = self.resolve(key)
        return {"node": member.name,
                "address": member.describe()["address"]}

    def _locate_sweep(self, key: str, skip: str | None = None):
        """A keyed poll hit ``unknown job`` at the ring owner.  Two HA
        situations produce that without any job being lost: the placement
        cache died with a failed-over active (this router never saw the
        submit), or a membership change moved the key's ring home away
        from the node that actually ran it.  Ask every other up member
        before giving up; a hit re-primes the placement cache so
        subsequent polls go straight there.  Returns the member or None."""
        for member in self.members():
            if not member.up or member.name == skip:
                continue
            try:
                reply = self._forward(member, {"op": "status", "key": key})
            except ServeClientError:
                continue
            if reply.get("ok"):
                # no spec on hand (the submit predates this router), so
                # the cache entry only pins placement; resolve() skips
                # the spec-needing resubmit path for spec-less entries
                self._remember(key, {}, member.name,
                               trace=(reply.get("job") or {}).get("trace"))
                self.counters.add("route_locate_sweeps", 1)
                obs_trace.event("route.locate_sweep", key=key,
                                node=member.name,
                                trace_id=(reply.get("job") or {})
                                .get("trace_id"))
                print(f"route: located key {key} on {member.name} after "
                      "an unknown-job miss; placement cache re-primed",
                      file=sys.stderr, flush=True)
                return member
        return None

    def _journal_resubmit(self, key: str) -> bool:
        """Last resort after a locate-sweep miss: the job's node is down
        and this router never saw the submit (post-takeover), so no live
        member knows the key — but the configured journal of a down
        member still holds the acked spec.  Recover it read-only and
        resubmit to the live ring successor (journal dedup + manifest
        ``--resume`` keep the eventual double replay exactly-once in its
        effects, same as every failover resubmit)."""
        spec = ctx = None
        for name, path in (self.journals or {}).items():
            with self._lock:
                member = self._members.get(name)
            if member is not None and member.up:
                continue  # live members already answered the sweep
            try:
                jobs, jinfo = journal_mod.replay(path)
            except (OSError, ValueError):
                continue
            qreason = (jinfo.get("quarantined") or {}).get(key)
            if qreason is not None:
                # the down member had condemned this key: the poll gets
                # the quarantine verdict, never a restarted crash loop
                reason = (f"key {key} is quarantined on down node "
                          f"{name}: {qreason}")
                raise ServeClientError(reason, {
                    "ok": False, "error": reason, "refused": True,
                    "quarantined": True, "reason": str(qreason),
                    "key": key})
            for rec in jobs.values():
                # terminal records are answered from the journal instead
                # (resubmitting one would re-run a finished job just to
                # satisfy a status poll)
                if rec.get("key") == key and rec.get("spec") \
                        and not rec.get("adopted") \
                        and rec.get("state") not in ("done", "failed"):
                    spec = dict(rec["spec"])
                    # the accepted record's persisted ack-span context:
                    # the resubmit links to the dead node's trace even
                    # though this router never saw the original submit
                    ctx = rec.get("trace")
                    break
            if spec is not None:
                break
        if spec is None:
            return False
        owner = self._owner_for(key)
        if owner is None:
            return False
        try:
            self._failover_resubmit(key, {"spec": spec, "trace": ctx}, owner)
        except ServeClientError as e:
            if e.reply.get("quarantined"):
                raise  # the poll answers the quarantine, not "unknown"
            print(f"route: journal-recovered resubmit of key {key} "
                  f"failed ({e}); next poll retries", file=sys.stderr,
                  flush=True)
            return False
        print(f"route: recovered key {key} from a down member's journal; "
              f"resubmitted to {owner.name}", file=sys.stderr, flush=True)
        return True

    def _journal_answer(self, key: str) -> dict | None:
        """Terminal fallback after both the sweep and the resubmit miss:
        a ``done``/``failed`` record in a down member's journal is
        authoritative — the outputs are already durable on the shared
        filesystem — so answer the keyed poll from it.  Without this, a
        job that finished *before* its node was perm-killed and adopted
        is unresolvable until the zombie returns: adoption resubmits
        only non-terminal jobs, and the tombstone makes the resubmit
        path skip the record entirely."""
        for name, path in (self.journals or {}).items():
            with self._lock:
                member = self._members.get(name)
            if member is not None and member.up:
                continue  # live members already answered the sweep
            try:
                jobs, _info = journal_mod.replay(path)
            except (OSError, ValueError):
                continue
            for rec in jobs.values():
                if rec.get("key") != key \
                        or rec.get("state") not in ("done", "failed"):
                    continue
                spec = rec.get("spec") or {}
                self.counters.add("route_journal_answers", 1)
                # the answer joins the job's timeline: a span linked to
                # the dead node's persisted ack context, carrying the
                # ORIGINAL trace_id (not a fresh one) so the poll reply
                # and the job's spans correlate
                rctx = rec.get("trace") if isinstance(rec.get("trace"),
                                                      dict) else None
                with obs_trace.span("route.journal_answer", link=rctx,
                                    trace_id=rec.get("trace_id"),
                                    key=key, node=name,
                                    state=rec["state"]):
                    pass
                print(f"route: answered keyed poll {key} from {name}'s "
                      f"journal (terminal state '{rec['state']}', node "
                      "down)", file=sys.stderr, flush=True)
                return {"ok": True, "trace": rctx, "job": {
                    "job_id": rec.get("id"), "key": key,
                    "state": rec["state"], "error": rec.get("error"),
                    "outputs": rec.get("outputs"),
                    "wall_s": rec.get("wall_s"),
                    "attempts": rec.get("attempts"),
                    "gang_size": rec.get("gang_size"),
                    "input": spec.get("input"),
                    "deadline_s": rec.get("deadline_s"),
                    "trace_id": rec.get("trace_id"),
                    "tenant": spec.get("tenant"),
                    "qos": spec.get("qos"),
                }}
        return None

    # ------------------------------------------ content-addressed cache

    def _load_cache_journal(self, path: str) -> None:
        """Replay the cache-answer journal into ``_cache_answers``.
        Same NDJSON + torn-tail discipline as the job journal: a torn
        final record is an answer whose reply never left, dropping it is
        correct.  Runs before the append fd opens (router construction)."""
        if not os.path.exists(path):
            return
        with open(path, "rb") as fh:
            raw = fh.read()
        lines = raw.split(b"\n")
        tail = lines.pop() if lines else b""
        if tail.strip():
            lines.append(tail)
        loaded = 0
        for line in lines:
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail / unreadable: that reply never left
            if not isinstance(rec, dict) or rec.get("kind") != "cache_answer":
                continue
            key, job = rec.get("key"), rec.get("job")
            if isinstance(key, str) and isinstance(job, dict):
                self._cache_answers[key] = job
                loaded += 1
        if loaded:
            print(f"route: cache-answer journal replay: {loaded} keyed "
                  "answer(s) restored", file=sys.stderr, flush=True)

    def _cache_answer(self, key: str, spec: dict,
                      trace: dict | None) -> dict | None:
        """Consult the result cache before dispatch.  On a hit the
        payload is materialized into the submitter's own output tree,
        the answer is journaled (fsync'd) BEFORE the reply leaves — the
        exactly-once discipline a terminal journal-answer gets — and a
        submit-ack-shaped reply comes back with ``cached: true``.
        Returns None on a miss or any degradation (the normal dispatch
        path is always correct)."""
        if self.result_cache is None:
            return None
        prior = self._cache_answers.get(key)
        if prior is not None:
            # an idempotent re-submit of an already-answered key: the
            # payload is already materialized and journaled
            return {"ok": True, "job_id": prior.get("job_id", 0),
                    "state": prior.get("state", "done"), "key": key,
                    "duplicate": True, "cached": True, "node": "cache",
                    "trace": prior.get("trace")}
        from consensuscruncher_tpu.serve import result_cache as rc_mod
        try:
            digest = rc_mod.content_digest(spec)
            if digest is None:
                return None
            # placement rides the job ring: the digest's ring owner is
            # where the producing job ran, so probe that shard first
            with self._lock:
                shard = self.ring.owner(digest)
            entry = self.result_cache.lookup(digest, preferred_shard=shard)
        except Exception as e:
            print(f"WARNING: route: cache lookup failed ({e}); "
                  "dispatching normally", file=sys.stderr, flush=True)
            return None
        if entry is None:
            self.counters.add("cache_misses")
            return None
        name = spec.get("name") \
            or os.path.basename(str(spec.get("input"))).split(".")[0]
        base = os.path.join(str(spec.get("output")), name)
        trace_id = (trace or {}).get("trace_id") or obs_trace.mint_trace_id()
        try:
            with obs_trace.span("route.cache_answer", link=trace,
                                trace_id=trace_id, key=key,
                                digest=digest, shard=entry.get("shard"),
                                negative=bool(entry.get("negative"))):
                n = self.result_cache.materialize(entry, base)
                # the answer span's wire context: echoed on the ack (and
                # on duplicate re-submits of the same key) so the
                # submitter links follow-up spans to the cache answer
                ctx = obs_trace.wire_context()
        except Exception as e:
            print(f"WARNING: route: cache materialize of {digest} failed "
                  f"({e}); dispatching normally", file=sys.stderr, flush=True)
            return None
        job = {"job_id": 0, "key": key, "state": "done", "error": None,
               "outputs": {"base": base}, "wall_s": 0.0, "attempts": 0,
               "gang_size": 0, "input": spec.get("input"),
               "deadline_s": None, "trace_id": trace_id, "trace": ctx,
               "tenant": spec.get("tenant"), "qos": spec.get("qos"),
               "cached": True}
        if self._cache_journal is not None:
            try:
                # journaled-before-ack, exactly like a submit: a crash
                # after this line replays the answer, a crash before it
                # means the reply never left and the cache re-answers
                self._cache_journal.append_marker(
                    "cache_answer", key=key, digest=digest, job=job)
            except Exception as e:
                print(f"WARNING: route: cache-answer journal write failed "
                      f"({e}); dispatching normally", file=sys.stderr,
                      flush=True)
                return None
        self._cache_answers[key] = job
        self.counters.add("route_cache_answers", 1)
        self.counters.add("cache_hits", 1)
        if entry.get("negative"):
            self.counters.add("cache_negative_hits", 1)
        print(f"route: answered submit {key} from the result cache "
              f"(digest {digest[:12]}, {n} bytes materialized)",
              file=sys.stderr, flush=True)
        return {"ok": True, "job_id": 0, "state": "done", "key": key,
                "duplicate": False, "cached": True, "node": "cache",
                "trace": ctx}

    def _keyed(self, req: dict) -> str:
        key = req.get("key")
        if not key:
            raise ServeClientError(
                "the router is key-addressed: poll with 'key' (worker "
                "job ids are per-daemon)", {"bad_request": True})
        return str(key)

    def status(self, req: dict) -> dict:
        key = self._keyed(req)
        answered = self._cache_answers.get(key)
        if answered is not None:
            return {"ok": True, "job": dict(answered)}
        tried: set[str] = set()
        swept = False
        while True:
            member = self.resolve(key)
            try:
                reply = self._forward(member, {"op": "status", "key": key})
                self._prune_attempts(key, reply)
                return reply
            except ServeClientError as e:
                if e.reply.get("unknown") and not swept:
                    swept = True  # one fleet sweep per call
                    if self._locate_sweep(key, skip=member.name) is not None \
                            or self._journal_resubmit(key):
                        continue
                    answer = self._journal_answer(key)
                    if answer is not None:
                        return answer
                if not e.reply.get("transport") or member.name in tried:
                    raise
                tried.add(member.name)  # one failover hop per member

    def result(self, req: dict, slice_s: float = 5.0) -> dict:
        """Blocking keyed result with failover: the member-side wait runs
        in bounded slices so a node death mid-poll is noticed within
        ``slice_s`` and the poll continues against the new owner."""
        key = self._keyed(req)
        answered = self._cache_answers.get(key)
        if answered is not None:
            return {"ok": True, "job": dict(answered)}
        timeout = req.get("timeout")
        deadline = (None if timeout is None
                    else time.monotonic() + float(timeout))
        swept = False
        while True:
            if self.closing:
                return {"ok": False, "error": "router shutting down",
                        "shutdown": True}
            remaining = slice_s
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"job {key} still pending")
            member = self.resolve(key)
            try:
                reply = self._forward(
                    member,
                    {"op": "result", "key": key,
                     "timeout": min(slice_s, remaining)},
                    timeout=min(slice_s, remaining) + 10.0)
                self._prune_attempts(key, reply)
                return reply
            except ServeClientError as e:
                if e.reply.get("unknown") and not swept:
                    swept = True  # one fleet sweep per call
                    if self._locate_sweep(key, skip=member.name) is not None \
                            or self._journal_resubmit(key):
                        continue
                    answer = self._journal_answer(key)
                    if answer is not None:
                        return answer
                if e.reply.get("timeout") or e.reply.get("shutdown") \
                        or e.reply.get("transport"):
                    continue  # next slice (possibly on a new owner)
                raise

    # -------------------------------------------------- lifecycle / fleet

    def stop_admission(self) -> None:
        self._draining = True

    def drain(self, timeout: float | None = None, node: str | None = None):
        """Drain one member (``node``) or the whole fleet (admission off
        everywhere first, then every member drains in parallel)."""
        if node:
            with self._lock:
                targets = [self._members[node]]
        else:
            targets = list(self.members())
        if node is None:
            self.stop_admission()
        errors: dict[str, str] = {}

        def _drain_one(member: _Member):
            try:
                member.client.drain(timeout=timeout)
                with self._lock:
                    member.draining = True
            except Exception as e:
                errors[member.name] = str(e)

        threads = [threading.Thread(target=_drain_one, args=(m,), daemon=True)
                   for m in targets]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return {"drained": sorted(m.name for m in targets
                                  if m.name not in errors),
                "errors": errors}

    def close(self) -> None:
        self.closing = True
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
        if self._cache_journal is not None:
            self._cache_journal.close()

    def shutdown(self, timeout: float | None = None) -> None:
        self.close()

    def healthz(self) -> dict:
        members = [m.describe() for m in self.members()]
        up = [m for m in members if m["up"]]
        if self.standby or self.fenced:
            status = "standby"
        elif self._draining:
            status = "draining"
        else:
            status = "serving" if up else "degraded"
        return {
            "status": status,
            "role": "router",
            "router_id": self.router_id,
            "epoch": self.epoch,
            "ha_state": ("fenced" if self.fenced else
                         ("standby" if self.standby else "active")),
            "queued": sum(m["queued"] for m in up),
            "running": sum(m["running"] for m in up),
            "quarantined": sum(m.get("quarantined", 0) for m in up),
            "uptime_s": round(time.time() - self._started_at, 3),
            "pid": os.getpid(),
            "fleet": {"size": len(members), "up": len(up),
                      "members": members},
        }

    def metrics(self) -> dict:
        """Fleet metrics doc: the router's own counters/labeled series,
        each reachable member's full doc under ``nodes.<name>``, and a
        cross-node merge of the labeled series (so per-qos consumers of
        the single-daemon doc keep working against the router)."""
        nodes: dict[str, dict] = {}
        for member in self.members():
            if not member.up:
                nodes[member.name] = None
                continue
            try:
                nodes[member.name] = member.client.request(
                    {"op": "metrics"}, timeout=15.0)["metrics"]
            except Exception:
                nodes[member.name] = None  # telemetry never fails routing
        merged = obs_metrics.labeled_snapshot()  # router's own node_* series
        for doc in nodes.values():
            labeled = (doc or {}).get("labeled") or {}
            for kind in ("counters", "histograms"):
                for name, entries in (labeled.get(kind) or {}).items():
                    merged.setdefault(kind, {}).setdefault(
                        name, []).extend(entries)
        health = self.healthz()
        cumulative = self.counters.snapshot()
        # the router's own trace-plane tallies (spans / links / orphans)
        # and profiler tallies (samples / drops / shards)
        cumulative.update(obs_trace.counter_snapshot())
        cumulative.update(obs_prof.counter_snapshot())
        cumulative.update(obs_history.counter_snapshot())
        return {
            "stage": "route",
            "phases_s": {"uptime": time.time() - self._started_at},
            "draining": self._draining,
            "router_id": self.router_id,
            "epoch": self.epoch,
            "ha_state": health["ha_state"],
            "cumulative": cumulative,
            "labeled": merged,
            "fleet": health["fleet"],
            "nodes": nodes,
        }

    def trace_fleet(self) -> list[dict]:
        """Every process's span buffer, for ``cct trace fleet``: the
        router's own events plus each up member's ``trace`` op reply.
        Down members are skipped (their flushed shards are still
        collectable from ``CCT_TRACE_DIR`` — that is the point of the
        on-disk shards); collection never fails routing."""
        groups: list[dict] = [{"node": self.router_id, "pid": os.getpid(),
                               "events": obs_trace.collect_events()}]
        for member in self.members():
            if not member.up:
                continue
            try:
                reply = member.client.request({"op": "trace"}, timeout=15.0)
            except Exception:
                continue
            buf = reply.get("trace")
            if isinstance(buf, dict):
                groups.append(buf)
        return groups

    def prof_fleet(self) -> list[dict]:
        """Every process's profile, for ``cct prof``: the router's own
        shard lines + wall attribution plus each up member's ``prof``
        op reply.  Down members' flushed ``prof-*.ndjson`` shards stay
        collectable from ``CCT_PROF_DIR`` — same discipline as traces;
        collection never fails routing."""
        docs: list[dict] = [obs_prof.collect(node=self.router_id)]
        for member in self.members():
            if not member.up:
                continue
            try:
                reply = member.client.request({"op": "prof"}, timeout=15.0)
            except Exception:
                continue
            doc = reply.get("prof")
            if isinstance(doc, dict):
                docs.append(doc)
        return docs

    def history_fleet(self) -> list[dict]:
        """Every process's telemetry history, for ``cct history``: the
        router's own shard lines plus each up member's ``history`` op
        reply.  Down members' flushed ``history-*.ndjson`` shards stay
        collectable from ``CCT_HISTORY_DIR`` — same discipline as
        trace/prof; collection never fails routing."""
        docs: list[dict] = [obs_history.collect(node=self.router_id)]
        for member in self.members():
            if not member.up:
                continue
            try:
                reply = member.client.request({"op": "history"},
                                              timeout=15.0)
            except Exception:
                continue
            doc = reply.get("history")
            if isinstance(doc, dict):
                docs.append(doc)
        return docs


class RouterServer(ServeServer):
    """The router's wire shell: :class:`serve.server.ServeServer`'s
    socket/connection machinery with the dispatch table swapped for the
    fleet ops (submit/status/result/locate/healthz/metrics/drain).  The
    router object rides in the ``scheduler`` slot — ``request_shutdown``
    and ``install_signal_handlers`` work unchanged because the router
    speaks the same ``stop_admission``/``drain`` lifecycle."""

    def __init__(self, router: Router, host: str = "127.0.0.1",
                 port: int = 0, socket_path: str | None = None,
                 max_conns: int | None = None):
        super().__init__(router, host=host, port=port,
                         socket_path=socket_path, max_conns=max_conns)
        self.router = router

    def shutdown(self) -> None:
        self.router.closing = True  # unpark sliced result waiters
        super().shutdown()

    def _dispatch(self, req: dict) -> dict:
        if not isinstance(req, dict):
            return {"ok": False, "error": "request must be a JSON object"}
        op = req.get("op")
        try:
            if op == "submit":
                return self.router.submit(req.get("spec") or {},
                                          trace=req.get("trace"))
            if op == "status":
                return self.router.status(req)
            if op == "result":
                return self.router.result(req)
            if op == "locate":
                loc = self.router.locate(str(req.get("key") or ""))
                return {"ok": True, **loc}
            if op == "healthz":
                return {"ok": True, "health": self.router.healthz()}
            if op == "metrics":
                doc = self.router.metrics()
                if req.get("format") == "prometheus":
                    return {"ok": True,
                            "prometheus": obs_metrics.render_fleet_prometheus(
                                doc)}
                return {"ok": True, "metrics": doc}
            if op == "drain":
                out = self.router.drain(timeout=req.get("timeout"),
                                        node=req.get("node"))
                return {"ok": True, "drained": True, **out}
            if op == "adopt":
                out = self.router.adopt(str(req.get("node") or ""),
                                        force=bool(req.get("force")))
                return {"ok": True, "adopted": True, **out}
            if op == "release":
                out = self.router.release(str(req.get("key") or ""))
                return {"ok": True, **out}
            if op == "member_add":
                out = self.router.member_add(req.get("name"),
                                             req.get("address"),
                                             journal=req.get("journal"))
                return {"ok": True, **out}
            if op == "member_remove":
                out = self.router.member_remove(req.get("name"))
                return {"ok": True, **out}
            if op == "trace":
                # fleet trace collection; works from standbys and fenced
                # zombies too (post-mortems outlive the HA role)
                if req.get("fleet"):
                    return {"ok": True, "trace": self.router.trace_fleet()}
                return {"ok": True, "trace": {
                    "node": self.router.router_id, "pid": os.getpid(),
                    "events": obs_trace.collect_events()}}
            if op == "prof":
                # fleet profile collection; unfenced for the same
                # reason as trace — perf postmortems outlive HA roles
                if req.get("fleet"):
                    return {"ok": True, "prof": self.router.prof_fleet()}
                return {"ok": True,
                        "prof": obs_prof.collect(node=self.router.router_id)}
            if op == "history":
                # fleet history collection; unfenced for the same
                # reason as trace/prof — "what changed over the last
                # hour" outlives HA roles
                if req.get("fleet"):
                    return {"ok": True,
                            "history": self.router.history_fleet()}
                return {"ok": True,
                        "history": obs_history.collect(
                            node=self.router.router_id)}
            return {"ok": False, "error": f"unknown op {op!r}"}
        except ServeClientError as e:
            # a member refusal / ``ok: false`` travels back verbatim
            reply = dict(e.reply) if e.reply else {}
            reply.setdefault("error", str(e))
            reply["ok"] = False
            return reply
        except TimeoutError as e:
            return {"ok": False, "error": str(e), "timeout": True}
        except Exception as e:  # surface, never kill the router
            print(f"WARNING: route op {op!r} failed: {e}",
                  file=sys.stderr, flush=True)
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}
