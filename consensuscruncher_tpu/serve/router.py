"""Fleet router: one stateless front door over N serve daemons.

``cct route`` turns the single-host daemon into a horizontally scaled
fleet: submits are **consistent-hashed by idempotency key** onto worker
daemons (each keeping its own journal, warm compile cache, autotune table
and device set), and the router itself holds no durable state — every
byte that matters for exactly-once recovery already lives in the workers'
write-ahead journals and per-job manifests.  Kill the router and restart
it with the same member list: keys hash to the same owners, keyed polls
resolve against the workers' journal-replayed jobs, nothing is lost.

Routing discipline:

- **Sticky placement.** :class:`HashRing` maps ``idempotency_key(spec)``
  to a member through ``vnodes`` virtual points per member, so a resubmit
  of the same spec always lands on the same worker (whose journal dedup
  collapses it onto the tracked job) and membership changes remap only
  ~1/N of the key space (pinned by the ring unit tests).
- **Replay-aware failover.** A member that fails a forward (or
  ``down_after`` consecutive health probes) is marked down; requests walk
  the ring to the next *up* owner.  For a job the router has seen, the
  cached spec is **resubmitted by key** to the new owner — the workers
  share a filesystem, so the new owner's ``--resume`` path completes the
  dead node's partial work byte-identically, and the journal dedup makes
  the whole dance exactly-once.  A recovered member rejoins the ring
  automatically on its next healthy probe (rebalance: its keys simply
  resolve home again; the stand-in owner's copy of any in-flight job is
  a terminal no-op thanks to idempotent outputs).
- **Bounded work stealing.** A batch/scavenger submit whose home node has
  ``steal_threshold``-deep queues may be steered to the least-loaded up
  member when that member is at least ``steal_margin`` jobs shallower —
  interactive jobs never move (stickiness is their latency warranty), and
  a steal is an optimization only: the ``route.steal`` fault site forces
  the job home, never fails it.

Fault sites (registered in ``tools/cctlint/fault_sites.py``, armed by the
chaos tests): ``route.member_down`` (a forward hits a dead member),
``route.steal`` (the steal decision itself), ``route.resubmit`` (the
failover resubmission).

Wire protocol: the same NDJSON ops as :mod:`serve.server`, plus
``{"op": "locate", "key": ...}`` -> the member currently owning the key
(clients use it to re-resolve a direct worker connection after a kill).
``status``/``result`` through the router are **key-addressed** — worker
job ids are per-daemon and collide across the fleet.

Metrics: the router's ``metrics`` op merges every member's labeled
series (so per-qos dashboards keep working unchanged), nests each
member's full doc under ``nodes.<name>``, and the Prometheus rendering
(:func:`obs.metrics.render_fleet_prometheus`) adds ``cct_fleet_*``
gauges plus node-labeled per-member series.
"""

from __future__ import annotations

import hashlib
import os
import sys
import threading
import time
from bisect import bisect_right
from collections import OrderedDict

from consensuscruncher_tpu.obs import metrics as obs_metrics
from consensuscruncher_tpu.serve.client import ServeClient, ServeClientError
from consensuscruncher_tpu.serve.journal import idempotency_key
from consensuscruncher_tpu.serve.server import ServeServer
from consensuscruncher_tpu.utils import faults
from consensuscruncher_tpu.utils.profiling import Counters

# qos classes eligible for cross-node stealing: latency-insensitive work
# whose gang compatibility survives the move (gangs key on cutoff and
# qualscore, which travel with the spec)
STEALABLE_QOS = ("batch", "scavenger")


class HashRing:
    """Deterministic consistent-hash ring with virtual nodes.

    ``vnodes`` points per member, positioned by sha256 of
    ``"<member>#<i>"`` — no process seeding anywhere, so every router
    (and every restart) builds the identical ring from the same member
    list.  ``owner`` walks clockwise from the key's position to the
    first member present in ``up`` (ring stability: a down member's keys
    fall to its clockwise successors; everyone else's keys do not move).
    """

    def __init__(self, members, vnodes: int = 64):
        self.vnodes = max(1, int(vnodes))
        self.members = tuple(dict.fromkeys(members))  # ordered, unique
        points = []
        for m in self.members:
            for i in range(self.vnodes):
                h = hashlib.sha256(f"{m}#{i}".encode()).digest()
                points.append((int.from_bytes(h[:8], "big"), m))
        points.sort()
        self._hashes = [p[0] for p in points]
        self._owners = [p[1] for p in points]

    @staticmethod
    def key_position(key: str) -> int:
        return int.from_bytes(
            hashlib.sha256(str(key).encode()).digest()[:8], "big")

    def owner(self, key: str, up=None) -> str | None:
        """The member owning ``key`` among ``up`` (default: all members);
        None when no candidate is up."""
        if not self._hashes:
            return None
        allowed = set(self.members if up is None else up)
        if not allowed:
            return None
        start = bisect_right(self._hashes, self.key_position(key))
        n = len(self._owners)
        for step in range(n):
            m = self._owners[(start + step) % n]
            if m in allowed:
                return m
        return None

    def preference(self, key: str) -> list[str]:
        """All members in ring-walk order from the key (first = owner,
        rest = failover order) — handy for tests and debugging."""
        out: list[str] = []
        if not self._hashes:
            return out
        start = bisect_right(self._hashes, self.key_position(key))
        n = len(self._owners)
        for step in range(n):
            m = self._owners[(start + step) % n]
            if m not in out:
                out.append(m)
                if len(out) == len(self.members):
                    break
        return out


class _Member:
    """Router-side view of one worker daemon (soft state only)."""

    def __init__(self, name: str, address, client):
        self.name = name
        self.address = address
        self.client = client
        self.up = True
        self.fails = 0          # consecutive failed health probes
        self.queued = 0
        self.running = 0
        self.draining = False
        self.last_seen = 0.0

    def describe(self) -> dict:
        return {
            "name": self.name,
            "address": (list(self.address)
                        if isinstance(self.address, tuple) else self.address),
            "up": self.up,
            "queued": self.queued,
            "running": self.running,
            "draining": self.draining,
        }


def parse_members(text: str) -> list[tuple[str, object]]:
    """``'n0=/tmp/a.sock,n1=host:port'`` (or bare addresses, auto-named
    ``n0..``) -> ``[(name, address), ...]`` with tuple TCP addresses."""
    out: list[tuple[str, object]] = []
    for i, part in enumerate(str(text or "").split(",")):
        part = part.strip()
        if not part:
            continue
        if "=" in part and os.sep not in part.split("=", 1)[0]:
            name, addr = part.split("=", 1)
            name = name.strip()
        else:
            name, addr = f"n{i}", part
        addr = addr.strip()
        if ":" in addr and os.sep not in addr:
            host, port = addr.rsplit(":", 1)
            out.append((name, (host, int(port))))
        else:
            out.append((name, addr))
    if not out:
        raise ValueError("router: empty member list")
    names = [n for n, _ in out]
    if len(set(names)) != len(names):
        raise ValueError(f"router: duplicate member names in {names}")
    return out


class Router:
    """Stateless routing core (the :class:`RouterServer` wire shell and
    the ``cct route`` CLI both drive this).

    ``members``: ``[(name, address), ...]``.  ``client_factory`` is
    dependency injection for the unit tests (anything with the
    ``ServeClient.request`` shape works).
    """

    def __init__(self, members, *, vnodes: int = 64,
                 steal_threshold: int = 4, steal_margin: int = 2,
                 health_interval_s: float = 2.0, down_after: int = 3,
                 spec_cache_max: int = 4096, client_factory=None,
                 start_monitor: bool = True):
        if client_factory is None:
            def client_factory(address):
                return ServeClient(address, connect_timeout=10.0,
                                   retries=1, retry_base_s=0.1)
        self._members: dict[str, _Member] = OrderedDict()
        for name, address in members:
            self._members[name] = _Member(name, address,
                                          client_factory(address))
        self.ring = HashRing(list(self._members), vnodes=vnodes)
        self.steal_threshold = max(1, int(steal_threshold))
        self.steal_margin = max(1, int(steal_margin))
        self.health_interval_s = float(health_interval_s)
        self.down_after = max(1, int(down_after))
        self.counters = Counters()
        self.closing = False
        self._draining = False
        self._started_at = time.time()
        self._lock = threading.Lock()
        # bounded key -> {"spec", "node"} soft state; the ONLY thing the
        # failover resubmission needs, and it is reconstructible: a keyed
        # poll for an unknown key still resolves to the ring owner, whose
        # journal has the job if it was ever acknowledged anywhere
        self._placed: OrderedDict[str, dict] = OrderedDict()
        self._placed_max = max(16, int(spec_cache_max))
        self._monitor: threading.Thread | None = None
        if start_monitor:
            self._monitor = threading.Thread(
                target=self._monitor_loop, name="route-health", daemon=True)
            self._monitor.start()

    # ------------------------------------------------------------ members

    def members(self) -> list[_Member]:
        with self._lock:
            return list(self._members.values())

    def _up_names(self) -> list[str]:
        with self._lock:
            return [m.name for m in self._members.values() if m.up]

    def _member(self, name: str) -> _Member:
        return self._members[name]

    def _mark_down(self, member: _Member, why: str) -> None:
        with self._lock:
            was_up = member.up
            member.up = False
        if was_up:
            self.counters.add("member_down_events", 1)
            print(f"route: member {member.name} DOWN ({why}); "
                  "failing its keys over to the next ring owners",
                  file=sys.stderr, flush=True)

    def _mark_up(self, member: _Member, health: dict) -> None:
        with self._lock:
            was_down = not member.up
            member.up = True
            member.fails = 0
            member.queued = int(health.get("queued", 0))
            member.running = int(health.get("running", 0))
            member.draining = health.get("status") == "draining"
            member.last_seen = time.time()
        if was_down:
            print(f"route: member {member.name} UP again; its ring range "
                  "rebalances home", file=sys.stderr, flush=True)

    def _monitor_loop(self) -> None:
        while not self.closing:
            self.probe_members()
            deadline = time.monotonic() + self.health_interval_s
            while not self.closing and time.monotonic() < deadline:
                time.sleep(min(0.2, self.health_interval_s))

    def probe_members(self) -> None:
        """One health sweep (the monitor loop calls this; tests call it
        directly for deterministic timing)."""
        for member in self.members():
            try:
                health = member.client.request({"op": "healthz"},
                                               timeout=5.0)["health"]
            except Exception as e:
                member.fails += 1
                if member.fails >= self.down_after and member.up:
                    self._mark_down(member, f"{member.fails} failed probes: {e}")
                continue
            self._mark_up(member, health)

    # ------------------------------------------------------------ routing

    def _owner_for(self, key: str, exclude: set | None = None):
        up = [n for n in self._up_names() if not exclude or n not in exclude]
        name = self.ring.owner(key, up=up)
        return None if name is None else self._member(name)

    def _remember(self, key: str, spec: dict, node: str) -> None:
        with self._lock:
            self._placed[key] = {"spec": dict(spec), "node": node}
            self._placed.move_to_end(key)
            while len(self._placed) > self._placed_max:
                self._placed.popitem(last=False)

    def _placed_info(self, key: str) -> dict | None:
        with self._lock:
            info = self._placed.get(key)
            return dict(info) if info else None

    def _forward(self, member: _Member, doc: dict,
                 timeout: float | None = None) -> dict:
        """One member RPC; a transport-level loss (or an armed
        ``route.member_down`` fault) marks the member down and raises
        ``ServeClientError(transport=True)`` for the caller's failover."""
        try:
            faults.fault_point("route.member_down")
        except faults.FaultError as e:
            self._mark_down(member, f"injected: {e}")
            raise ServeClientError(str(e), {"transport": True}) from e
        try:
            return member.client.request(doc, timeout=timeout)
        except ServeClientError as e:
            if e.reply.get("transport"):
                self._mark_down(member, str(e))
            raise
        except OSError as e:
            self._mark_down(member, str(e))
            raise ServeClientError(str(e), {"transport": True}) from e

    def _pick_target(self, key: str, qos: str) -> tuple[_Member, bool]:
        """Home member for the key, or a steal target for deep-queued
        batch/scavenger work.  Returns ``(member, stolen)``."""
        home = self._owner_for(key)
        if home is None:
            raise ServeClientError("no fleet member is up", {"transport": True})
        if qos not in STEALABLE_QOS:
            return home, False
        with self._lock:
            candidates = [m for m in self._members.values()
                          if m.up and not m.draining and m.name != home.name]
            if (home.queued < self.steal_threshold) or not candidates:
                return home, False
            thief = min(candidates, key=lambda m: (m.queued, m.name))
            if thief.queued + self.steal_margin > home.queued:
                return home, False
        try:
            faults.fault_point("route.steal")
        except faults.FaultError as e:
            print(f"WARNING: route: steal fault ({e}); keeping job on "
                  f"home node {home.name}", file=sys.stderr, flush=True)
            return home, False
        return thief, True

    # ---------------------------------------------------------------- ops

    def submit(self, spec: dict) -> dict:
        """Route one submit; returns the member's wire reply annotated
        with ``node``/``node_address`` (refusals pass through so the
        client's shed/quota handling keeps working)."""
        if self._draining:
            return {"ok": False, "refused": True,
                    "error": "router is draining; not accepting jobs"}
        spec = dict(spec or {})
        try:
            key = idempotency_key(spec)
        except Exception as e:
            return {"ok": False, "error": f"bad spec: {e}"}
        qos = str(spec.get("qos") or "interactive")
        tried: set[str] = set()
        stolen = False
        while True:
            if not tried:
                try:
                    member, stolen = self._pick_target(key, qos)
                except ServeClientError as e:
                    return {"ok": False, "error": str(e)}
            else:
                member = self._owner_for(key, exclude=tried)
                if member is None:
                    return {"ok": False,
                            "error": "no fleet member is up",
                            "transport": True}
            try:
                reply = self._forward(member, {"op": "submit", "spec": spec})
            except ServeClientError as e:
                if e.reply.get("transport"):
                    # forward-time death: fail over around the ring
                    tried.add(member.name)
                    stolen = False
                    continue
                if e.reply.get("refused"):
                    return dict(e.reply)
                return {"ok": False, "error": str(e)}
            with self._lock:
                member.queued += 1  # soft estimate until the next probe
            self._remember(key, spec, member.name)
            self.counters.add("jobs_routed", 1)
            obs_metrics.inc("node_jobs_routed", node=member.name)
            if stolen:
                self.counters.add("route_steals", 1)
                obs_metrics.inc("node_steals", node=member.name)
            reply = dict(reply)
            reply["node"] = member.name
            reply["node_address"] = member.describe()["address"]
            reply["stolen"] = stolen
            return reply

    def resolve(self, key: str) -> _Member:
        """The member a keyed poll should talk to *right now*: the cached
        placement while that node is up, else the current ring owner —
        resubmitting the cached spec there first, so the poll finds the
        job (replay-aware failover).  Raises when no member is up."""
        info = self._placed_info(key)
        if info is not None:
            member = self._members.get(info["node"])
            if member is not None and member.up:
                return member
        member = self._owner_for(key)
        if member is None:
            raise ServeClientError("no fleet member is up", {"transport": True})
        if info is not None and info["node"] != member.name:
            self._failover_resubmit(key, info, member)
        return member

    def _failover_resubmit(self, key: str, info: dict,
                           member: _Member) -> None:
        """Resubmit a dead node's job to its new owner.  Exactly-once by
        construction: the new owner's journal dedups on the key, and the
        shared-filesystem ``--resume`` manifest skips any stage the dead
        node already committed — outputs stay byte-identical."""
        faults.fault_point("route.resubmit")
        reply = self._forward(member, {"op": "submit",
                                       "spec": info["spec"]})
        self._remember(key, info["spec"], member.name)
        self.counters.add("jobs_routed", 1)
        self.counters.add("route_resubmits", 1)
        obs_metrics.inc("node_jobs_routed", node=member.name)
        obs_metrics.inc("node_resubmits", node=member.name)
        print(f"route: resubmitted key {key} to {member.name} "
              f"(job {reply.get('job_id')}, duplicate="
              f"{reply.get('duplicate')})", file=sys.stderr, flush=True)

    def locate(self, key: str) -> dict:
        member = self.resolve(key)
        return {"node": member.name,
                "address": member.describe()["address"]}

    def _keyed(self, req: dict) -> str:
        key = req.get("key")
        if not key:
            raise ServeClientError(
                "the router is key-addressed: poll with 'key' (worker "
                "job ids are per-daemon)", {"bad_request": True})
        return str(key)

    def status(self, req: dict) -> dict:
        key = self._keyed(req)
        tried: set[str] = set()
        while True:
            member = self.resolve(key)
            try:
                return self._forward(member, {"op": "status", "key": key})
            except ServeClientError as e:
                if not e.reply.get("transport") or member.name in tried:
                    raise
                tried.add(member.name)  # one failover hop per member

    def result(self, req: dict, slice_s: float = 5.0) -> dict:
        """Blocking keyed result with failover: the member-side wait runs
        in bounded slices so a node death mid-poll is noticed within
        ``slice_s`` and the poll continues against the new owner."""
        key = self._keyed(req)
        timeout = req.get("timeout")
        deadline = (None if timeout is None
                    else time.monotonic() + float(timeout))
        while True:
            if self.closing:
                return {"ok": False, "error": "router shutting down",
                        "shutdown": True}
            remaining = slice_s
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"job {key} still pending")
            member = self.resolve(key)
            try:
                return self._forward(
                    member,
                    {"op": "result", "key": key,
                     "timeout": min(slice_s, remaining)},
                    timeout=min(slice_s, remaining) + 10.0)
            except ServeClientError as e:
                if e.reply.get("timeout") or e.reply.get("shutdown") \
                        or e.reply.get("transport"):
                    continue  # next slice (possibly on a new owner)
                raise

    # -------------------------------------------------- lifecycle / fleet

    def stop_admission(self) -> None:
        self._draining = True

    def drain(self, timeout: float | None = None, node: str | None = None):
        """Drain one member (``node``) or the whole fleet (admission off
        everywhere first, then every member drains in parallel)."""
        targets = ([self._members[node]] if node
                   else list(self.members()))
        if node is None:
            self.stop_admission()
        errors: dict[str, str] = {}

        def _drain_one(member: _Member):
            try:
                member.client.drain(timeout=timeout)
                with self._lock:
                    member.draining = True
            except Exception as e:
                errors[member.name] = str(e)

        threads = [threading.Thread(target=_drain_one, args=(m,), daemon=True)
                   for m in targets]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return {"drained": sorted(m.name for m in targets
                                  if m.name not in errors),
                "errors": errors}

    def close(self) -> None:
        self.closing = True
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)

    def shutdown(self, timeout: float | None = None) -> None:
        self.close()

    def healthz(self) -> dict:
        members = [m.describe() for m in self.members()]
        up = [m for m in members if m["up"]]
        return {
            "status": "draining" if self._draining else
                      ("serving" if up else "degraded"),
            "role": "router",
            "queued": sum(m["queued"] for m in up),
            "running": sum(m["running"] for m in up),
            "uptime_s": round(time.time() - self._started_at, 3),
            "pid": os.getpid(),
            "fleet": {"size": len(members), "up": len(up),
                      "members": members},
        }

    def metrics(self) -> dict:
        """Fleet metrics doc: the router's own counters/labeled series,
        each reachable member's full doc under ``nodes.<name>``, and a
        cross-node merge of the labeled series (so per-qos consumers of
        the single-daemon doc keep working against the router)."""
        nodes: dict[str, dict] = {}
        for member in self.members():
            if not member.up:
                nodes[member.name] = None
                continue
            try:
                nodes[member.name] = member.client.request(
                    {"op": "metrics"}, timeout=15.0)["metrics"]
            except Exception:
                nodes[member.name] = None  # telemetry never fails routing
        merged = obs_metrics.labeled_snapshot()  # router's own node_* series
        for doc in nodes.values():
            labeled = (doc or {}).get("labeled") or {}
            for kind in ("counters", "histograms"):
                for name, entries in (labeled.get(kind) or {}).items():
                    merged.setdefault(kind, {}).setdefault(
                        name, []).extend(entries)
        return {
            "stage": "route",
            "phases_s": {"uptime": time.time() - self._started_at},
            "draining": self._draining,
            "cumulative": self.counters.snapshot(),
            "labeled": merged,
            "fleet": self.healthz()["fleet"],
            "nodes": nodes,
        }


class RouterServer(ServeServer):
    """The router's wire shell: :class:`serve.server.ServeServer`'s
    socket/connection machinery with the dispatch table swapped for the
    fleet ops (submit/status/result/locate/healthz/metrics/drain).  The
    router object rides in the ``scheduler`` slot — ``request_shutdown``
    and ``install_signal_handlers`` work unchanged because the router
    speaks the same ``stop_admission``/``drain`` lifecycle."""

    def __init__(self, router: Router, host: str = "127.0.0.1",
                 port: int = 0, socket_path: str | None = None,
                 max_conns: int | None = None):
        super().__init__(router, host=host, port=port,
                         socket_path=socket_path, max_conns=max_conns)
        self.router = router

    def shutdown(self) -> None:
        self.router.closing = True  # unpark sliced result waiters
        super().shutdown()

    def _dispatch(self, req: dict) -> dict:
        if not isinstance(req, dict):
            return {"ok": False, "error": "request must be a JSON object"}
        op = req.get("op")
        try:
            if op == "submit":
                return self.router.submit(req.get("spec") or {})
            if op == "status":
                return self.router.status(req)
            if op == "result":
                return self.router.result(req)
            if op == "locate":
                loc = self.router.locate(str(req.get("key") or ""))
                return {"ok": True, **loc}
            if op == "healthz":
                return {"ok": True, "health": self.router.healthz()}
            if op == "metrics":
                doc = self.router.metrics()
                if req.get("format") == "prometheus":
                    return {"ok": True,
                            "prometheus": obs_metrics.render_fleet_prometheus(
                                doc)}
                return {"ok": True, "metrics": doc}
            if op == "drain":
                out = self.router.drain(timeout=req.get("timeout"),
                                        node=req.get("node"))
                return {"ok": True, "drained": True, **out}
            return {"ok": False, "error": f"unknown op {op!r}"}
        except ServeClientError as e:
            # a member refusal / ``ok: false`` travels back verbatim
            reply = dict(e.reply) if e.reply else {}
            reply.setdefault("error", str(e))
            reply["ok"] = False
            return reply
        except TimeoutError as e:
            return {"ok": False, "error": str(e), "timeout": True}
        except Exception as e:  # surface, never kill the router
            print(f"WARNING: route op {op!r} failed: {e}",
                  file=sys.stderr, flush=True)
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}
