"""Admission-controlled job queue with cross-request continuous batching.

The scheduler owns a bounded queue of consensus jobs and a single
dispatcher thread.  Each dispatch round pops a *gang* of compatible queued
jobs (same cutoff/qualscore/vote policy — the compile-time consensus
parameters) and
runs their SSCS stage as ONE merged device stream: every job's family
events are interleaved round-robin (``parallel.batching.interleave_sources``)
into a single ``ops.consensus_tpu.consensus_families`` call, so one bucket
dispatch carries families from several requests — the continuous-batching
discipline that keeps an accelerator saturated under many small inputs.

Bit-identity with the one-shot CLI path holds by construction:

- packed family *content* is source-local (``rectangularize`` sees one
  family at a time), so interleaving changes batch composition but never
  the per-family vote inputs — and dense-vs-stream wire parity is already
  pinned by the test suite;
- record bytes are produced by the same ``stages.sscs_maker`` helpers
  (``write_singleton`` / ``emit_consensus``) the one-shot stage uses;
- every sorting writer orders output by content-keyed sort, never batch
  order, so cross-request batch composition cannot leak into file bytes.

After the gang SSCS, each job's "sscs" manifest entry is recorded exactly
as ``cli._consensus_impl`` would record it, and the job finishes through
``cli.main(["consensus", ..., "--resume", "True"])`` — the existing resume
path skips the recorded stage and runs the rest warm.  A failed job
retries through the same resume path (bounded, ``CCT_SERVE_RETRIES``),
which PR-1's atomic stage commits make safe: a death mid-stage never
leaves a partial output to resume over.

Durability (``serve.journal``): when constructed with a journal, every
admission is acknowledged only after its ``accepted`` record is fsync'd,
every transition is journaled, and ``__init__`` replays the journal before
the dispatcher starts — any job not provably terminal is re-enqueued and
finishes via ``--resume`` (exactly-once at the output level, byte-identical
to an uninterrupted run).  Duplicate submits dedupe on the spec's
idempotency key, so a client resubmitting across a daemon restart gets the
existing job instead of double-running it.

Overload robustness: a submit may carry ``deadline_s``.  Admission sheds
jobs that cannot meet their deadline at the observed per-job service rate
(EWMA), and dispatch sheds queued jobs whose deadline already expired
while waiting — both counted in ``jobs_shed``.  Completed-job records are
evicted after ``CCT_SERVE_RESULT_TTL_S`` (or beyond ``CCT_SERVE_RESULT_MAX``)
so a long-lived daemon's memory stays bounded; an evicted job's result
points at its on-disk outputs.

Multi-tenancy (``tenant``/``qos`` spec fields): every job belongs to a
tenant (default ``"default"``) and a qos class (``interactive`` /
``batch`` / ``scavenger``).  Each class has its own FIFO queue and the
dispatcher picks the next class by **stride scheduling** — the class
with the least accumulated virtual "pass", advanced by ``1/weight`` per
dispatched job — which is deterministic weighted-fair sharing: with
weights 8/3/1 a saturated daemon gives the classes 8:3:1 of its dispatch
slots, an idle class costs nothing, and a class waking from idle cannot
bank credit (its pass is clamped to the current leader).  Gangs never
span classes, so fairness accounting stays exact.  Per-tenant admission
quotas (``tenant_queue_cap`` queued slots, ``tenant_inflight_cap``
queued+running) raise :class:`QuotaRefused` so one tenant cannot starve
the rest of the queue.  Deadline shedding generalizes to per-class SLO
targets: a job without an explicit ``deadline_s`` inherits its class
target (when configured), and every terminal/shed event feeds the
:class:`~consensuscruncher_tpu.obs.slo.SloMonitor` (p50/p99, shed rate,
multi-window burn rates on ``metrics``/``healthz``).  The default path —
no tenant/qos in the spec, no targets configured — is byte-identical to
the single-tenant scheduler: one nonempty interactive queue is plain
FIFO and the monitor only aggregates.

Failure containment (defense in depth against poison jobs and resource
exhaustion):

- **fleet retry budget** — a ``suspect`` journal marker (key, attempt
  ordinal, node) is fsync'd BEFORE each dispatch, so replay after a
  kill -9 can blame the job that was in flight; the per-key attempt
  lineage is capped by ``CCT_SERVE_MAX_FLEET_ATTEMPTS`` and a job whose
  budget is spent is **quarantined** (near-terminal, durable via a
  ``quarantined`` marker, releasable with ``cct route --release KEY``)
  instead of crash-looping the fleet;
- **circuit breaker** — ``CCT_SERVE_BREAKER_QUARANTINES`` quarantines
  within ``CCT_SERVE_BREAKER_WINDOW_S`` from one input fingerprint open
  the breaker for that fault domain: admission refuses the fingerprint
  early (``breaker_open`` counter + flight dump);
- **brownout** — an OSError (ENOSPC) on the admission journal append
  first triggers the result cache's emergency ``evict_to_budget`` sweep
  and one retry; if the disk is still full the daemon flips into
  read-only brownout: polls and committed cache hits are still served,
  new admissions are refused with ``{"brownout": true}`` until an
  append succeeds again;
- **watermark shedding** — when queued spec bytes or process RSS
  approach ``CCT_SERVE_QUEUE_BYTES_WATERMARK`` /
  ``CCT_SERVE_RSS_WATERMARK_MB``, admissions shed lowest class first
  (scavenger at 80%, batch at 90%, interactive at 100%).

Fault sites: ``serve.dispatch`` (gang dispatch — jobs fall back to solo
runs), ``serve.worker`` (per-job execution — retried via resume),
``serve.shed`` (admission shedding — forced refusal), ``serve.poison``
(fires only for poison-labeled jobs — a deterministically crashing
input without touching honest jobs), ``serve.enospc`` (disk-full on the
journal append — brownout path), ``serve.oom`` (forces the resource
watermark to 100% — class-ordered shedding), plus
``serve.journal_write`` / ``serve.journal_replay`` in :mod:`.journal`.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque

from consensuscruncher_tpu.obs import flight as obs_flight
from consensuscruncher_tpu.obs import history as obs_history
from consensuscruncher_tpu.obs import metrics as obs_metrics
from consensuscruncher_tpu.obs import prof as obs_prof
from consensuscruncher_tpu.obs import trace as obs_trace
from consensuscruncher_tpu.obs.registry import (
    DEFAULT_QOS,
    DEFAULT_TENANT,
    POLICY_NAMES,
    QOS_CLASSES,
)
from consensuscruncher_tpu.obs.slo import SloMonitor
from consensuscruncher_tpu.serve import journal as journal_mod
from consensuscruncher_tpu.utils import faults, sanitize
from consensuscruncher_tpu.utils.profiling import Counters, metrics_doc


class AdmissionRefused(RuntimeError):
    """Queue full or server draining — the caller should retry later."""


class DeadlineShed(AdmissionRefused):
    """Admission refused because the job cannot meet its deadline."""


class QuotaRefused(AdmissionRefused):
    """Per-tenant queue-slot or in-flight quota exceeded."""


class BrownoutRefused(AdmissionRefused):
    """Journal appends are failing (disk full) — the daemon is in
    read-only brownout: polls and cache hits still served, admissions
    refused with ``{"brownout": true}`` until an append succeeds."""


class QuarantineRefused(AdmissionRefused):
    """The key (or its whole fault domain, via the circuit breaker) is
    quarantined as a poison job — the wire layer answers
    ``{"quarantined": true, "reason": ...}`` instead of retrying."""

    def __init__(self, message: str, reason: str | None = None,
                 key: str | None = None):
        super().__init__(message)
        self.reason = reason or message
        self.key = key


class RouterFenced(RuntimeError):
    """A forward carried a router epoch below the highest this worker has
    accepted: the sender is a zombie router from before a takeover.  The
    wire layer turns this into ``{"fenced": true, "epoch": <live>}`` so
    the stale router demotes itself instead of double-dispatching."""

    def __init__(self, live_epoch: int, message: str):
        super().__init__(message)
        self.epoch = int(live_epoch)


_STATES = ("queued", "running", "done", "failed", "quarantined")

#: reserved tenant for the serve-side golden canary prober: excluded from
#: per-tenant admission quotas and the tenant QC series so synthetic
#: heartbeat probes can never distort real-tenant accounting
CANARY_TENANT = "_canary"


class Job:
    """One submitted consensus request and its lifecycle."""

    _next_id = 0
    # lock-order asserted under CCT_SANITIZE=1 (utils.sanitize); plain
    # threading.Lock semantics otherwise
    _id_lock = sanitize.tracked_lock("job.id_lock")

    def __init__(self, spec: dict, job_id: int | None = None,
                 key: str | None = None, deadline_s: float | None = None,
                 trace_id: str | None = None):
        with Job._id_lock:
            if job_id is None:
                Job._next_id += 1
                job_id = Job._next_id
            else:
                # journal replay preserves ids; fresh jobs continue after
                # the highest replayed one so ids never collide
                job_id = int(job_id)
                Job._next_id = max(Job._next_id, job_id)
            self.id = job_id
        self.spec = dict(spec)
        self.tenant = str(spec.get("tenant") or DEFAULT_TENANT)
        # submit_info validates qos before Job construction; folding an
        # unknown class here (journal replay of a foreign record) keeps
        # recovery from crashing on a single bad row
        qos = str(spec.get("qos") or DEFAULT_QOS)
        self.qos = qos if qos in QOS_CLASSES else DEFAULT_QOS
        self.key = key
        self.deadline_s = deadline_s
        # correlation id minted at submit; every span this job produces —
        # admission, journal append, gang dispatch, device batches, writer
        # commit — carries it, so one grep of the exported trace follows
        # the job end to end
        self.trace_id = trace_id or obs_trace.mint_trace_id()
        # wire trace context of the submit-ack span ({"trace_id", "span",
        # "pid", "hop"}): echoed on the submit reply so the router can
        # link failover resubmits back to this ack, and journaled on the
        # accepted record so adoption can do the same after a kill -9
        self.trace_ctx: dict | None = None
        self.state = "queued"
        self.error: str | None = None
        self.outputs: dict | None = None
        self.wall_s: float | None = None
        # admission -> dispatch wait, fixed at dispatch time; the job
        # span reports it (queue_wait_ms) so the profiler's attribution
        # can split wall into queue vs run without re-deriving it
        self.queue_wait_s: float | None = None
        self.attempts = 0
        self.gang_size = 1  # how many jobs shared this job's SSCS dispatch
        # True when the content-addressed result cache answered this job
        # (materialized bytes, no pipeline run) — surfaced in describe()
        # so clients can split hit/miss latency
        self.cached = False
        # compact consensus-quality summary from the run's qc.json (yields
        # + rates + disagree_rate, never the full plane vectors) — rides
        # describe() and the journal's done record (replay tolerates
        # absence: pre-QC journals simply leave it None)
        self.qc: dict | None = None
        # compact-JSON size of the spec: the unit the queue-byte
        # watermark meters (cheap, computed once at admission)
        try:
            self.spec_bytes = len(json.dumps(
                self.spec, sort_keys=True, separators=(",", ":")))
        except (TypeError, ValueError):
            self.spec_bytes = 0
        self.submitted_t = time.monotonic()
        self.finished_t: float | None = None
        # critpath boundary stamps (absolute monotonic): admit / journal /
        # ack / gang / dispatch / run.  Emitted as ms-from-submit offsets
        # on the terminal ``serve.critpath`` event; obs/critpath.py owns
        # the segment math, the scheduler only records evidence.
        self.stamps: dict[str, float] = {}
        # per-lock wait_us totals at admission (CCT_LOCK_LEDGER=1 only):
        # the baseline the antagonist view deltas against at terminal
        self._lock_wait0: dict[str, int] | None = None

    def stamp(self, name: str) -> None:
        self.stamps[name] = time.monotonic()

    def describe(self) -> dict:
        return {
            "job_id": self.id, "state": self.state, "error": self.error,
            "outputs": self.outputs, "wall_s": self.wall_s,
            "attempts": self.attempts, "gang_size": self.gang_size,
            "input": self.spec.get("input"), "key": self.key,
            "deadline_s": self.deadline_s, "trace_id": self.trace_id,
            "tenant": self.tenant, "qos": self.qos, "cached": self.cached,
            "qc": self.qc, "queue_wait_s": self.queue_wait_s,
        }


def _rss_mb() -> float | None:
    """Process resident-set size in MB via /proc/self/statm (None where
    procfs is unavailable — the RSS watermark simply never engages)."""
    try:
        with open("/proc/self/statm") as fh:
            rss_pages = int(fh.read().split()[1])
        return rss_pages * os.sysconf("SC_PAGE_SIZE") / 1e6
    except (OSError, ValueError, IndexError):
        return None


def job_paths(spec: dict) -> dict:
    """Output-tree paths for a job spec — the same naming authority as
    ``cli._consensus_impl`` (``<output>/<name>/{sscs,singleton,...}``)."""
    from consensuscruncher_tpu.stages import sscs_maker

    name = spec.get("name") or os.path.basename(spec["input"]).split(".")[0]
    base = os.path.join(spec["output"], name)
    dirs = {k: os.path.join(base, k)
            for k in ("sscs", "singleton", "dcs", "all_unique", "plots")}
    prefix = os.path.join(dirs["sscs"], name)
    return {"name": name, "base": base, "dirs": dirs, "sscs_prefix": prefix,
            "sscs": sscs_maker.output_paths(prefix)}


class _GangJobState:
    """Per-job state for one gang-SSCS run: reader, writers, stats; the
    exact one-shot ``run_sscs`` wiring, opened once per job so the merged
    stream can demux results back to the owning job."""

    def __init__(self, spec: dict):
        from consensuscruncher_tpu.io.bam import BamWriter
        from consensuscruncher_tpu.io.columnar import ColumnarReader, SortingBamWriter
        from consensuscruncher_tpu.io.encode import ConsensusRecordWriter
        from consensuscruncher_tpu.stages.grouping import stream_families_columnar
        from consensuscruncher_tpu.utils.stats import (
            FamilySizeHistogram, StageStats, TimeTracker,
        )

        self.spec = spec
        p = job_paths(spec)
        for d in p["dirs"].values():
            os.makedirs(d, exist_ok=True)
        self.base = p["base"]
        self.prefix = p["sscs_prefix"]
        self.paths = p["sscs"]
        level = int(spec.get("compress_level", 6))
        self.level = level
        self.stream_handoff: dict | None = None
        self.reader = ColumnarReader(spec["input"])
        header = self.reader.header
        self.bad_writer = BamWriter(self.paths["bad"], header, atomic=True)
        self.sscs_writer = SortingBamWriter(self.paths["sscs"], header, level=level)
        self.singleton_writer = SortingBamWriter(
            self.paths["singleton"], header, level=level)
        self.rec_writer = ConsensusRecordWriter(self.sscs_writer)
        self.stats = StageStats("SSCS")
        self.hist = FamilySizeHistogram()
        self.tracker = TimeTracker()
        self.cum = Counters()
        self.pending: dict[int, tuple] = {}
        self.source = stream_families_columnar(
            self.reader, header, spec.get("bdelim", "|"))

    def events(self, job_idx: int):
        """Yield ``((job_idx, fid), seqs, quals)`` consensus work items;
        route bad reads and singletons inline (same accounting as the
        one-shot ``run_sscs`` events loop)."""
        from consensuscruncher_tpu.stages.sscs_maker import (
            _member_arrays, write_singleton,
        )

        next_id = 0
        for kind, a, b in self.source:
            if kind == "bad":
                self.stats.incr("total_reads")
                self.stats.incr(f"bad_{b}")
                self.stats.incr("bad_reads")
                self.bad_writer.write(a)
                continue
            tag, members = a, b
            self.stats.incr("total_reads", len(members))
            self.hist.add(len(members))
            self.stats.incr("families")
            if len(members) == 1:
                self.stats.incr("singletons")
                write_singleton(self.singleton_writer, tag, members)
                continue
            seqs, quals = _member_arrays(members)
            self.pending[next_id] = (tag, members)
            self.cum.add("families_in")
            yield (job_idx, next_id), seqs, quals
            next_id += 1

    def emit(self, fid: int, codes, quals) -> None:
        from consensuscruncher_tpu.stages.sscs_maker import emit_consensus

        tag, members = self.pending.pop(fid)
        emit_consensus(self.rec_writer, self.sscs_writer, tag, members, codes, quals)
        self.stats.incr("sscs_written")

    def seal(self) -> None:
        self.rec_writer.flush()

    def abort(self) -> None:
        for w in (self.bad_writer, self.sscs_writer, self.singleton_writer):
            w.abort()

    def close_outputs(self) -> None:
        self.tracker.mark("consensus")
        self.bad_writer.close()
        if str(self.spec.get("pipeline", "")) == "streaming":
            # Streaming continuation: finish each sort in memory, then
            # materialize the same file synchronously — durability and the
            # manifest record are unchanged, but the sorted records also
            # ride to ``_run_job`` in memory so the rest of the chain skips
            # the BGZF re-read.  A spilled sort buffer just closes normally
            # (no hand-off; the job's CLI run re-reads the files).
            def commit(writer, path):
                try:
                    mem = writer.close_to_memory()
                except RuntimeError:
                    writer.close()
                    return None
                mem.write(path, level=self.level, index=True)
                return mem

            sscs_mem = commit(self.sscs_writer, self.paths["sscs"])
            singleton_mem = commit(self.singleton_writer, self.paths["singleton"])
            if sscs_mem is not None and singleton_mem is not None:
                self.stream_handoff = {"sscs": sscs_mem,
                                       "singleton": singleton_mem}
        else:
            self.sscs_writer.close()
            self.singleton_writer.close()
        self.tracker.mark("sort")

    def record(self, cutoff: float, qual_threshold: int, backend: str,
               policy: str = "majority") -> None:
        """Stats sidecars + the manifest "sscs" entry, mirroring the
        one-shot CLI byte-for-byte so ``--resume`` skips the stage."""
        from consensuscruncher_tpu.utils.backend_probe import record_backend
        from consensuscruncher_tpu.utils.manifest import RunManifest
        from consensuscruncher_tpu.utils.profiling import write_metrics

        record_backend(self.stats, backend)
        jax_backend = self.stats.get("jax_backend")
        self.stats.set("cutoff", cutoff)
        if policy != "majority":
            # non-default only, mirroring run_sscs: default-run stats
            # sidecars stay byte-identical to the pre-policy goldens
            self.stats.set("policy", policy)
        self.stats.write(self.paths["stats_txt"])
        self.hist.write(self.paths["families"])
        self.tracker.write(self.paths["time_tracker"])
        self.cum.add("families_out", self.stats.get("sscs_written"))
        write_metrics(
            f"{self.prefix}.metrics.json", "SSCS", self.tracker.as_phases(),
            {"backend": backend, "jax_backend": jax_backend,
             "n_families": self.stats.get("families"),
             "n_reads": self.stats.get("total_reads")},
            cumulative=self.cum.snapshot(),
        )
        manifest = RunManifest(os.path.join(self.base, "manifest.json"))
        manifest.record(
            "sscs", [self.spec["input"]],
            [self.paths[k] for k in
             ("sscs", "singleton", "stats_txt", "stats_json", "families")],
            {"cutoff": float(self.spec.get("cutoff", 0.7)),
             "qualscore": int(self.spec.get("qualscore", 0)),
             "bdelim": self.spec.get("bdelim", "|"),
             "input_range": None,
             **({"policy": policy} if policy != "majority" else {})},
        )


def gang_sscs(specs: list[dict], counters: Counters | None = None,
              max_batch: int = 1024,
              trace_ids: list[str] | None = None) -> list:
    """Run the SSCS stage for several jobs as ONE merged device stream.

    Families from every job are interleaved round-robin into a single
    ``consensus_families`` call (dense wire) keyed ``(job_idx, fid)``; the
    results demux back to per-job writers.  Records each job's manifest
    entry on success; aborts every job's writers on failure (no partial
    outputs — the caller retries jobs solo via resume).

    ``trace_ids`` (one per spec, positional) lets each shared device batch
    be attributed: the per-batch trace event lists the trace_id of every
    job whose families rode that dispatch.

    Returns one entry per spec: the in-memory SSCS/singleton hand-off for
    jobs whose spec asks for ``pipeline: streaming`` (None for staged jobs
    or when the sort spilled), so the caller can continue those jobs'
    chains without re-reading the stage files.
    """
    from consensuscruncher_tpu.ops.consensus_tpu import (
        ConsensusConfig, consensus_families,
    )
    from consensuscruncher_tpu.parallel.batching import interleave_sources

    from consensuscruncher_tpu.policies import base as policies_mod

    cutoff = float(specs[0].get("cutoff", 0.7))
    qualscore = int(specs[0].get("qualscore", 0))
    policy = str(specs[0].get("policy") or "majority")
    for s in specs[1:]:
        if (float(s.get("cutoff", 0.7)), int(s.get("qualscore", 0))) != (cutoff, qualscore):
            raise ValueError("gang jobs must share cutoff/qualscore")
        if str(s.get("policy") or "majority") != policy:
            raise ValueError("gang jobs must share a vote policy")
    cfg = ConsensusConfig(cutoff=cutoff, qual_threshold=qualscore)
    vote_policy = policies_mod.get_policy(policy)

    states = [_GangJobState(s) for s in specs]
    tracing = obs_trace.enabled() and trace_ids is not None

    def on_batch(batch):
        if counters is not None:
            counters.add("batches_dispatched")
        if tracing:
            # which jobs' families share this device dispatch — distinct
            # trace_ids on one batch span is the whole point of tracing a
            # continuous-batching scheduler
            owners = sorted({k[0] for k in batch.keys})
            obs_trace.event(
                "device.batch", n_real=batch.n_real,
                trace_ids=[trace_ids[i] for i in owners])

    ok = False
    # the gang's shared device dispatch runs under the gang's (validated-
    # shared) vote policy; restore the prior install afterwards so the
    # daemon's warmup choice survives dispatch rounds
    prev_policy = policies_mod.installed_vote_policy()
    policies_mod.set_vote_policy(vote_policy)
    try:
        stream = consensus_families(
            interleave_sources([st.events(i) for i, st in enumerate(states)]),
            cfg, max_batch=max_batch, on_batch=on_batch,
        )
        try:
            for (ji, fid), codes, quals in stream:
                states[ji].emit(fid, codes, quals)
        finally:
            # join the prefetch producer (it writes to the per-job writers)
            # BEFORE the writers are closed/aborted below
            stream.close()
        for st in states:
            st.seal()
        ok = True
    finally:
        policies_mod.set_vote_policy(prev_policy)
        for st in states:
            st.reader.close()
        if not ok:
            for st in states:
                st.abort()
    for i, st in enumerate(states):
        with obs_trace.span(
                "writer.commit",
                trace_id=trace_ids[i] if trace_ids else None):
            st.close_outputs()
            st.record(cutoff, qualscore, "tpu", policy=vote_policy.name)
    return [st.stream_handoff for st in states]


class Scheduler:
    """Bounded job queue + single dispatcher thread (see module docstring).

    ``queue_bound`` caps ADMITTED-but-unfinished work: submit refuses when
    the queue is full (backpressure to the client, never OOM).
    ``gang_size`` caps how many compatible jobs one dispatch round merges.
    ``paused`` holds dispatch so tests can pile up a gang deterministically.
    ``journal`` (a :class:`.journal.Journal` or a path) makes admissions
    durable: the journal is replayed before the dispatcher starts.
    ``result_ttl_s`` / ``result_max`` bound completed-job retention.
    ``class_weights`` sets the stride-scheduling share per qos class;
    ``slo_targets`` sets per-class latency targets (seconds, None = no
    target) that double as implicit deadlines for shedding;
    ``tenant_queue_cap`` / ``tenant_inflight_cap`` bound one tenant's
    queued / queued+running jobs (None = unlimited).
    """

    DEFAULT_CLASS_WEIGHTS = {"interactive": 8.0, "batch": 3.0,
                             "scavenger": 1.0}

    def __init__(self, queue_bound: int = 16, gang_size: int = 4,
                 backend: str = "tpu", max_batch: int = 1024,
                 start: bool = True, paused: bool = False,
                 journal: journal_mod.Journal | str | None = None,
                 result_ttl_s: float | None = None,
                 result_max: int | None = None,
                 class_weights: dict | None = None,
                 slo_targets: dict | None = None,
                 tenant_queue_cap: int | None = None,
                 tenant_inflight_cap: int | None = None,
                 node: str | None = None,
                 result_cache=None):
        # fleet identity: the member name a router knows this daemon by
        # (empty for a standalone daemon); surfaced in healthz/metrics so
        # node-labeled fleet dashboards can be cross-checked per worker
        self.node = str(node or os.environ.get("CCT_SERVE_NODE") or "")
        self.queue_bound = int(queue_bound)
        self.gang_size = max(1, int(gang_size))
        self.backend = backend
        self.max_batch = int(max_batch)
        if result_ttl_s is None:
            result_ttl_s = float(os.environ.get("CCT_SERVE_RESULT_TTL_S", "600"))
        self.result_ttl_s = float(result_ttl_s)
        if result_max is None:
            result_max = int(os.environ.get("CCT_SERVE_RESULT_MAX", "512"))
        self.result_max = max(1, int(result_max))
        self._expired_cap = max(64, 4 * self.result_max)
        if isinstance(journal, str):
            journal = journal_mod.Journal(
                journal, max_bytes=int(os.environ.get(
                    "CCT_SERVE_JOURNAL_MAX_BYTES", str(1 << 20))))
        self._journal = journal
        # fleet content-addressed result cache: a ResultCache instance or
        # a cache-plane root dir (str); None disables caching entirely
        self.counters = Counters()
        if isinstance(result_cache, str):
            from consensuscruncher_tpu.serve.result_cache import ResultCache
            result_cache = ResultCache(
                result_cache, node=self.node or None,
                max_bytes=int(os.environ.get(
                    "CCT_SERVE_CACHE_MAX_BYTES", "0")) or None,
                counters=self.counters)
        self.result_cache = result_cache
        weights = dict(self.DEFAULT_CLASS_WEIGHTS)
        for qos, w in (class_weights or {}).items():
            if qos not in weights:
                raise KeyError(f"unknown qos class {qos!r} in class_weights")
            w = float(w)
            if w <= 0:
                raise ValueError(f"class weight for {qos!r} must be > 0")
            weights[qos] = w
        self.class_weights = weights
        self.slo_targets = {qos: None for qos in QOS_CLASSES}
        for qos, t in (slo_targets or {}).items():
            if qos not in self.slo_targets:
                raise KeyError(f"unknown qos class {qos!r} in slo_targets")
            self.slo_targets[qos] = None if t is None else float(t)
        self.tenant_queue_cap = \
            None if tenant_queue_cap is None else max(1, int(tenant_queue_cap))
        self.tenant_inflight_cap = None if tenant_inflight_cap is None \
            else max(1, int(tenant_inflight_cap))
        self.slo = SloMonitor(targets=self.slo_targets)
        # optional callable set by serve_cmd: surfaces the bucket
        # autotuner's state (table size, unexpected recompiles) in /metrics
        self.autotune_info = None
        # optional callable set by the canary prober: {"ok", "age_s", ...}
        # surfaced in /metrics as the cct_canary_ok / cct_canary_age_s
        # gauges (same read-time attachment idiom as autotune_info)
        self.canary_info = None
        # recent gang-run intervals ({"t0", "t1", "jobs"}) for the
        # critpath antagonist view: "who was the dispatcher busy on while
        # this job sat queued".  Bounded; appended outside the lock's hot
        # path (once per gang)
        self._gang_log: deque = deque(maxlen=64)
        self._cond = sanitize.tracked_condition("scheduler.cond")
        # one FIFO per qos class; stride state drives weighted-fair picks
        self._queues: dict[str, deque[Job]] = \
            {qos: deque() for qos in QOS_CLASSES}
        self._stride = {qos: 1.0 / weights[qos] for qos in QOS_CLASSES}
        self._pass = {qos: 0.0 for qos in QOS_CLASSES}
        self._jobs: dict[int, Job] = {}
        self._by_key: dict[str, int] = {}
        self._expired: dict[int, dict] = {}  # evicted-job tombstones (FIFO)
        self._running: list[Job] = []
        self._draining = False
        self._paused = bool(paused)
        self._stop = False
        self._started_at = time.time()
        self._ewma_job_s: float | None = None
        # highest router epoch this worker has accepted; restored from the
        # journal's fence marker in _recover so a restart cannot be talked
        # into honoring a demoted router (0 = never fenced / no fleet HA)
        self._fence_epoch = 0
        # ---- failure containment (poison quarantine / brownout) knobs --
        # fleet-wide retry budget: max dispatch attempts for one key
        # across crashes, restarts, and (via the ring view) every
        # failover/adoption/steal path; 0 disables the budget
        self.max_fleet_attempts = int(
            os.environ.get("CCT_SERVE_MAX_FLEET_ATTEMPTS", "3"))
        # circuit breaker: this many quarantines inside the window from
        # one input fingerprint refuse that fault domain at admission
        self.breaker_quarantines = int(
            os.environ.get("CCT_SERVE_BREAKER_QUARANTINES", "3"))
        self.breaker_window_s = float(
            os.environ.get("CCT_SERVE_BREAKER_WINDOW_S", "300"))
        # resource watermarks (0 disables): queued spec bytes, process RSS
        self.queue_bytes_watermark = int(
            os.environ.get("CCT_SERVE_QUEUE_BYTES_WATERMARK", "0"))
        self.rss_watermark_mb = float(
            os.environ.get("CCT_SERVE_RSS_WATERMARK_MB", "0"))
        self._fleet_attempts: dict[str, int] = {}  # key -> dispatch count
        self._quarantined: dict[str, str] = {}     # key -> reason
        self._breaker_hits: dict[str, deque] = {}  # fingerprint -> times
        self._breaker_open_t: dict[str, float] = {}
        self._brownout = False
        self._thread = threading.Thread(
            target=self._loop, name="serve-dispatcher", daemon=True)
        if self._journal is not None:
            self._recover()
        if start:
            self._thread.start()

    # ----------------------------------------------------------- admission

    def submit(self, spec: dict) -> Job:
        job, _created = self.submit_info(spec)
        return job

    def submit_info(self, spec: dict,
                    trace: dict | None = None,
                    fleet_attempts: int | None = None) -> tuple[Job, bool]:
        """Admit a job; returns ``(job, created)``.  A duplicate submit
        (same idempotency key, job still tracked) returns the existing job
        with ``created=False`` instead of double-running the work.

        ``trace`` is the inbound wire trace context (client or router
        hop): the job adopts its trace id instead of minting, and the
        submit span records a ``follows_from`` edge to the sender — the
        causal chain survives the router hop instead of dying at it.

        ``fleet_attempts`` is the router-carried attempt lineage for the
        key (the ``attempts`` rider on a forwarded submit): max-merged
        into the local count BEFORE admission, so this node's budget
        gate — and the ``suspect`` ordinals it journals — continue the
        fleet-wide lineage instead of granting a fresh budget."""
        for req in ("input", "output"):
            if not spec.get(req):
                raise ValueError(f"job spec missing {req!r}")
        qos = str(spec.get("qos") or DEFAULT_QOS)
        if qos not in QOS_CLASSES:
            raise ValueError(
                f"unknown qos class {qos!r}; expected one of {QOS_CLASSES}")
        # vote-policy admission (ISSUE 17): normalize BEFORE the key is
        # computed — an explicit default ("majority") is stripped so it
        # hashes identically to an absent field (legacy-stable keys and
        # cache digests) — and unknown names are refused here with the
        # registry's ValueError (the server's typed bad_request reply)
        # rather than failing on a warm device mid-dispatch.
        if spec.get("policy") in ("", "majority"):
            spec.pop("policy", None)
        elif spec.get("policy") is not None:
            from consensuscruncher_tpu.policies.base import get_policy

            get_policy(str(spec["policy"]))
        tenant = str(spec.get("tenant") or DEFAULT_TENANT)
        key = journal_mod.idempotency_key(spec)
        deadline_s = spec.get("deadline_s")
        deadline_s = None if deadline_s is None else float(deadline_s)
        # the trace_id rides in on the wire context (or is minted HERE,
        # before admission can refuse, so shed decisions and journal-write
        # failures are traceable too); an admitted Job adopts it for life
        ctx = trace if isinstance(trace, dict) else None
        trace_id = (ctx or {}).get("trace_id") or obs_trace.mint_trace_id()
        with obs_trace.span("serve.submit", trace_id=trace_id, link=ctx,
                            input=spec.get("input"), key=key,
                            tenant=tenant, qos=qos), self._cond:
            if fleet_attempts:
                self._fleet_attempts[key] = max(
                    self._fleet_attempts.get(key, 0), int(fleet_attempts))
            qreason = self._quarantined.get(key)
            if qreason is not None:
                raise QuarantineRefused(
                    f"key {key} is quarantined: {qreason}",
                    reason=qreason, key=key)
            existing = self._by_key.get(key)
            if existing is not None and existing in self._jobs:
                return self._jobs[existing], False
            if self._draining:
                raise AdmissionRefused("server is draining; not accepting jobs")
            self._breaker_check_locked(spec, tenant, key)
            self._quota_check_locked(tenant, qos)
            self._shed_check_locked(deadline_s, tenant, qos, spec=spec)
            self._watermark_check_locked(tenant, qos)
            self._evict_locked(time.monotonic())
            queued = self._queued_locked()
            if queued >= self.queue_bound:
                raise AdmissionRefused(
                    f"queue full ({queued}/{self.queue_bound})")
            job = Job(spec, key=key, deadline_s=deadline_s, trace_id=trace_id)
            # admission checks all passed: everything before this stamp is
            # the critpath "admit" segment
            job.stamp("admit")
            # the ack span's own wire context: echoed on the reply and
            # journaled below, so every later continuation (failover
            # resubmit, adoption) can follows_from this durable anchor
            job.trace_ctx = obs_trace.wire_context()
            if self._journal is not None:
                # the accepted record must be on disk BEFORE the job is
                # acknowledged: a refused-but-unjournaled submit is safe to
                # retry, an acknowledged-but-unjournaled one would be lost
                # by a crash
                try:
                    n = self._journal_append_guarded(
                        journal_mod.job_record(
                            job.id, "accepted", key=job.key, spec=job.spec,
                            deadline_s=job.deadline_s, trace_id=job.trace_id,
                            trace=job.trace_ctx))
                except OSError as e:
                    # disk full (or any filesystem failure) even after the
                    # cache's emergency eviction: flip into read-only
                    # brownout.  A committed cache entry IS durable —
                    # admitting a hit costs a file copy, not journal disk
                    # — so cache hits are the one admission class a
                    # brownout keeps serving (journal-less; their bytes
                    # already survive a crash in the store).
                    self._trip_brownout_locked(e)
                    if not self._cache_shed_bypass_locked(spec, tenant, qos):
                        self.counters.add("brownout_refusals")
                        raise BrownoutRefused(
                            f"journal write failed ({e}); daemon is in "
                            "read-only brownout (polls and cache hits "
                            "still served; admissions refused until "
                            "appends succeed)")
                    n = 0
                except Exception as e:
                    raise AdmissionRefused(
                        f"journal write failed ({e}); job not accepted")
                else:
                    if self._brownout:
                        # the probe append above succeeded: disk pressure
                        # is gone, leave brownout
                        self._brownout = False
                        obs_flight.record("brownout_cleared")
                        print("serve: journal append succeeded again; "
                              "leaving brownout", file=sys.stderr, flush=True)
                self.counters.add("journal_bytes", n)
            # journal-ack fsync done (or no journal: zero-width segment)
            job.stamp("journal")
            if sanitize.ledger_enabled():
                job._lock_wait0 = {
                    name: row["wait_us"]
                    for name, row in sanitize.ledger_snapshot().items()}
            self._enqueue_locked(job)
            self._jobs[job.id] = job
            self._by_key[key] = job.id
            self.counters.high_water("queue_depth_hwm", self._queued_locked())
            obs_metrics.inc("tenant_jobs_admitted",
                            tenant=job.tenant, qos=job.qos)
            self._cond.notify_all()
        # flush the ack span to the trace shard before acknowledging: an
        # acked job's submit span must survive a kill -9 exactly like its
        # journal record does (the trace-completeness invariant's anchor)
        job.stamp("ack")
        obs_trace.flush()
        # schedule point at the ack boundary: everything durable happened
        # under the lock above; the caller's acknowledgement is next
        sanitize.yield_point("serve.ack")
        return job, True

    # -------------------------------------------------- per-class queues

    def _queued_locked(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def _any_queued_locked(self) -> bool:
        return any(self._queues.values())

    def _enqueue_locked(self, job: Job) -> None:
        queue = self._queues[job.qos]
        if not queue:
            # a class waking from idle must not have banked credit while
            # asleep — clamp its pass forward to the current leader so it
            # gets its fair share from NOW, not a monopoly first
            active = [self._pass[q] for q in QOS_CLASSES if self._queues[q]]
            if active:
                self._pass[job.qos] = max(self._pass[job.qos], min(active))
        queue.append(job)

    def _quota_check_locked(self, tenant: str, qos: str) -> None:
        """Per-tenant admission quotas: a tenant may hold at most
        ``tenant_queue_cap`` queue slots and ``tenant_inflight_cap``
        queued+running jobs; past either the submit is refused (the
        per-tenant analogue of ``queue_bound`` backpressure)."""
        if self.tenant_queue_cap is None and self.tenant_inflight_cap is None:
            return
        if tenant == CANARY_TENANT:
            # the heartbeat probe must not consume (or be refused by) a
            # real tenant's quota — it rides scavenger qos and the queue
            # bound only
            return
        queued = sum(1 for q in self._queues.values()
                     for j in q if j.tenant == tenant)
        if self.tenant_queue_cap is not None \
                and queued >= self.tenant_queue_cap:
            obs_metrics.inc("tenant_jobs_quota_refused",
                            tenant=tenant, qos=qos)
            raise QuotaRefused(
                f"tenant {tenant!r} queue quota exhausted "
                f"({queued}/{self.tenant_queue_cap})")
        if self.tenant_inflight_cap is not None:
            inflight = queued + sum(
                1 for j in self._running if j.tenant == tenant)
            if inflight >= self.tenant_inflight_cap:
                obs_metrics.inc("tenant_jobs_quota_refused",
                                tenant=tenant, qos=qos)
                raise QuotaRefused(
                    f"tenant {tenant!r} in-flight quota exhausted "
                    f"({inflight}/{self.tenant_inflight_cap})")

    def _shed_check_locked(self, deadline_s: float | None,
                           tenant: str, qos: str,
                           spec: dict | None = None) -> None:
        """Deadline-aware admission: refuse work that cannot finish in time
        at the observed service rate (EWMA of per-job wall).  A job with no
        explicit deadline inherits its qos class SLO target (when one is
        configured).  The ``serve.shed`` fault site forces a shed for
        chaos tests.

        Digest-keyed bypass (ROADMAP item 5 follow-through): a submit
        whose ``content_digest`` is already committed in the result cache
        costs a file copy, not a pipeline run — the EWMA that justified
        the shed does not apply, so it is admitted instead of refused
        (counted ``cache_shed_bypass``).  The cache probe happens only
        when a shed WOULD fire, so the unloaded admission path never pays
        a lookup."""
        try:
            faults.fault_point("serve.shed")
        except faults.FaultError as e:
            if self._cache_shed_bypass_locked(spec, tenant, qos):
                return
            self._count_shed_locked(tenant, qos)
            self._flight_shed(f"injected: {e}", tenant, qos)
            raise DeadlineShed(f"shed: {e}")
        effective = deadline_s if deadline_s is not None \
            else self.slo_targets[qos]
        if effective is None or self._ewma_job_s is None:
            return
        backlog = self._queued_locked() + len(self._running)
        eta = (backlog + 1) * self._ewma_job_s / max(1, self.gang_size)
        if eta > effective:
            if self._cache_shed_bypass_locked(spec, tenant, qos):
                return
            self._count_shed_locked(tenant, qos)
            self._flight_shed(f"eta {eta:.1f}s > deadline_s={effective:g} "
                              f"(backlog={backlog})", tenant, qos)
            raise DeadlineShed(
                f"shed: estimated completion {eta:.1f}s exceeds "
                f"deadline_s={effective:g} (backlog={backlog}, "
                f"ewma_job_s={self._ewma_job_s:.2f})")

    def _cache_shed_bypass_locked(self, spec: dict | None,
                                  tenant: str, qos: str) -> bool:
        """True when ``spec``'s content digest has a committed result-cache
        entry — the admission bypass for would-be sheds.  Returns False
        fast with no cache configured (existing shed behavior is
        untouched); any probe failure also answers False (the cache is an
        optimization, never an admission authority)."""
        if self.result_cache is None or not spec:
            return False
        from consensuscruncher_tpu.serve import result_cache as rc_mod
        try:
            digest = rc_mod.content_digest(spec)
            if digest is None or self.result_cache.lookup(digest) is None:
                return False
        except Exception:
            return False
        self.counters.add("cache_shed_bypass")
        obs_trace.event("serve.cache_shed_bypass", tenant=tenant, qos=qos)
        return True

    def _count_shed_locked(self, tenant: str, qos: str) -> None:
        self.counters.add("jobs_shed")
        obs_metrics.inc("tenant_jobs_shed", tenant=tenant, qos=qos)
        self.slo.note(qos, shed=True)

    @staticmethod
    def _flight_shed(why: str, tenant: str, qos: str) -> None:
        """A shed is an anomaly worth a post-mortem: record it and dump the
        flight ring so the overload's lead-up survives the incident."""
        obs_flight.record("shed", why=why, tenant=tenant, qos=qos)
        obs_flight.dump(reason="shed")

    # ------------------------------------- poison quarantine / brownout

    #: watermark pressure at which each qos class starts shedding:
    #: scavenger first, interactive only when the watermark is breached
    _WATERMARK_SHED_AT = {"scavenger": 0.8, "batch": 0.9, "interactive": 1.0}

    @staticmethod
    def _fault_domain(spec: dict, tenant: str) -> str:
        """Breaker fingerprint: one crashing input must trip the breaker
        for every submit of that input regardless of output path — the
        content digest when computable, else tenant + input path."""
        from consensuscruncher_tpu.serve import result_cache as rc_mod
        try:
            digest = rc_mod.content_digest(spec or {})
        except Exception:
            digest = None
        return digest or f"{tenant}:{(spec or {}).get('input')}"

    def _breaker_check_locked(self, spec: dict, tenant: str,
                              key: str) -> None:
        """Per-fault-domain circuit breaker: a fingerprint that produced
        ``breaker_quarantines`` quarantines inside the window is refused
        at admission — the poison input cannot even enter the queue.  An
        open breaker half-closes after one quiet window."""
        if not self._breaker_open_t:
            return
        fp = self._fault_domain(spec, tenant)
        opened = self._breaker_open_t.get(fp)
        if opened is None:
            return
        if time.monotonic() - opened > self.breaker_window_s:
            del self._breaker_open_t[fp]
            return
        reason = (f"circuit breaker open for fault domain {fp!r}: "
                  f"{self.breaker_quarantines} quarantine(s) within "
                  f"{self.breaker_window_s:g}s")
        raise QuarantineRefused(reason, reason=reason, key=key)

    def _breaker_note_locked(self, job: Job) -> None:
        """Record one quarantine against the job's fault domain; open the
        breaker when the window fills (``breaker_open`` + flight dump)."""
        if self.breaker_quarantines <= 0:
            return
        fp = self._fault_domain(job.spec, job.tenant)
        now = time.monotonic()
        hits = self._breaker_hits.setdefault(fp, deque())
        hits.append(now)
        while hits and now - hits[0] > self.breaker_window_s:
            hits.popleft()
        if len(hits) >= self.breaker_quarantines \
                and fp not in self._breaker_open_t:
            self._breaker_open_t[fp] = now
            self.counters.add("breaker_open")
            obs_flight.record("breaker_open", fingerprint=fp,
                              quarantines=len(hits),
                              window_s=self.breaker_window_s)
            obs_flight.dump(reason="breaker-open")

    def _watermark_check_locked(self, tenant: str, qos: str) -> None:
        """Resource-exhaustion shedding: when queued spec bytes or
        process RSS approach their watermark, shed admissions lowest
        class first (scavenger at 80%, batch at 90%, interactive only at
        100%) so memory pressure degrades throughput before the OOM
        killer picks for us.  ``serve.oom`` forces 100% pressure."""
        pressure = 0.0
        try:
            faults.fault_point("serve.oom")
        except faults.FaultError:
            pressure = 1.0
        if self.queue_bytes_watermark > 0 and pressure < 1.0:
            qbytes = sum(j.spec_bytes
                         for q in self._queues.values() for j in q)
            pressure = max(pressure, qbytes / self.queue_bytes_watermark)
        if self.rss_watermark_mb > 0 and pressure < 1.0:
            rss = _rss_mb()
            if rss is not None:
                pressure = max(pressure, rss / self.rss_watermark_mb)
        if pressure >= self._WATERMARK_SHED_AT[qos]:
            self.counters.add("watermark_sheds")
            self.slo.note(qos, shed=True)
            obs_flight.record("watermark_shed", qos=qos, tenant=tenant,
                              pressure=round(pressure, 3))
            obs_flight.dump(reason="watermark-shed")
            raise DeadlineShed(
                f"shed: resource watermark at {pressure:.0%} "
                f"(class {qos!r} sheds at "
                f"{self._WATERMARK_SHED_AT[qos]:.0%})")

    def _journal_append_guarded(self, rec: dict) -> int:
        """Append with the ENOSPC first responder: a failed append
        triggers one emergency result-cache eviction sweep (reclaiming
        cache bytes is the cheapest disk on the box) and one retry
        before the failure propagates.  ``serve.enospc`` injects the
        disk-full OSError chaos tests arm."""
        try:
            faults.fault_point("serve.enospc")
        except faults.FaultError as e:
            raise OSError(28, f"No space left on device (injected: {e})")
        try:
            return self._journal.append(rec)
        except OSError:
            if self.result_cache is None:
                raise
            try:
                evicted = self.result_cache.evict_to_budget(emergency=True)
            except Exception:
                evicted = []
            for ev in evicted:
                self.counters.add("cache_evictions")
                self.counters.add("cache_bytes", -int(ev.get("bytes", 0)))
            if not evicted:
                raise
            return self._journal.append(rec)

    def _trip_brownout_locked(self, err: Exception) -> None:
        if not self._brownout:
            self._brownout = True
            obs_flight.record("brownout", error=str(err))
            obs_flight.dump(reason="brownout")
            print(f"WARNING: serve: journal append failing ({err}); "
                  "entering read-only brownout (polls + cache hits only)",
                  file=sys.stderr, flush=True)

    def _predispatch_locked(self, job: Job) -> bool:
        """Budget gate + crash attribution, run just before a job's
        dispatch record.  Quarantines the job (returns True = do NOT
        dispatch) when its key is already quarantined or its fleet
        attempt budget is spent; otherwise fsyncs the ``suspect`` marker
        (key, attempt ordinal, node) FIRST, so a kill -9 during the run
        is attributable on replay."""
        key = job.key or ""
        reason = self._quarantined.get(key)
        if reason is not None:
            job.state = "quarantined"
            job.error = reason
            job.finished_t = time.monotonic()
            # rejected work still spent real queue time — critpath must
            # account for it, not just for dispatched jobs
            job.queue_wait_s = job.finished_t - job.submitted_t
            self._critpath_emit_locked(job)
            return True
        attempt = self._fleet_attempts.get(key, 0) + 1
        if self.max_fleet_attempts and attempt > self.max_fleet_attempts:
            self.counters.add("fleet_attempts_exhausted")
            self._quarantine_locked(
                job, f"fleet retry budget exhausted "
                     f"({attempt - 1}/{self.max_fleet_attempts} attempts)")
            return True
        self._fleet_attempts[key] = attempt
        if self._journal is not None:
            try:
                n = self._journal.append_marker(
                    "suspect", key=key, attempt=attempt,
                    node=self.node or None)
                self.counters.add("journal_bytes", n)
            except Exception as e:
                print(f"WARNING: suspect marker write failed ({e}); a "
                      "crash during this run will not be attributable",
                      file=sys.stderr, flush=True)
        return False

    def _quarantine_locked(self, job: Job, reason: str) -> None:
        """Poison containment: park the job in the near-terminal
        ``quarantined`` state — durable via a journal marker so replay
        and zombie restarts honor it — instead of letting another
        dispatch amplify a deterministic crasher.  Feeds the
        per-fingerprint circuit breaker."""
        key = job.key or ""
        job.state = "quarantined"
        job.error = reason
        job.finished_t = time.monotonic()
        job.queue_wait_s = job.finished_t - job.submitted_t
        self._quarantined[key] = reason
        self.counters.add("jobs_quarantined")
        if self._journal is not None:
            try:
                n = self._journal.append_marker(
                    "quarantined", key=key, reason=reason,
                    node=self.node or None)
                self.counters.add("journal_bytes", n)
            except Exception as e:
                print(f"WARNING: quarantine marker write failed ({e}); "
                      "the quarantine will not survive a restart",
                      file=sys.stderr, flush=True)
        obs_trace.event("serve.quarantine", trace_id=job.trace_id,
                        job_id=job.id, key=key, reason=reason)
        self._critpath_emit_locked(job)
        obs_flight.record("quarantine", job_id=job.id, key=key,
                          reason=reason, tenant=job.tenant, qos=job.qos)
        obs_flight.dump(reason="quarantine")
        self._breaker_note_locked(job)
        self._cond.notify_all()

    def release_quarantine(self, key: str) -> dict:
        """``cct route --release KEY``: lift a key's quarantine, zero its
        fleet attempt lineage, and re-queue the parked job (if still
        tracked).  Journaled (``quarantined`` marker with ``released``)
        so the release survives restarts."""
        key = str(key)
        with self._cond:
            reason = self._quarantined.pop(key, None)
            if reason is None:
                return {"released": False, "key": key}
            self._fleet_attempts.pop(key, None)
            if self._journal is not None:
                try:
                    n = self._journal.append_marker(
                        "quarantined", key=key, released=True,
                        node=self.node or None)
                    self.counters.add("journal_bytes", n)
                except Exception as e:
                    print(f"WARNING: release marker write failed ({e}); "
                          "the release will not survive a restart",
                          file=sys.stderr, flush=True)
            self.counters.add("quarantine_released")
            job_id = self._by_key.get(key)
            job = self._jobs.get(job_id) if job_id is not None else None
            requeued = False
            if job is not None and job.state == "quarantined":
                job.state = "queued"
                job.error = None
                job.finished_t = None
                job.submitted_t = time.monotonic()
                # the release restarts the job's clock; stale boundary
                # stamps from the quarantined life would corrupt critpath
                job.stamps = {}
                job.queue_wait_s = None
                self._enqueue_locked(job)
                requeued = True
                self._cond.notify_all()
            obs_flight.record("quarantine_released", key=key,
                              requeued=requeued)
            return {"released": True, "key": key, "requeued": requeued}

    def quarantined_keys(self) -> dict[str, str]:
        with self._cond:
            return dict(self._quarantined)

    def fleet_attempts(self, key: str) -> int:
        with self._cond:
            return self._fleet_attempts.get(str(key), 0)

    def note_fleet_attempts(self, key: str, attempts: int) -> None:
        """Fold a ring-view-carried attempt count for ``key`` into the
        local lineage (max-merge: lineages only ever grow) — how a
        router's failover resubmit hands the budget across nodes."""
        with self._cond:
            key = str(key)
            self._fleet_attempts[key] = max(
                self._fleet_attempts.get(key, 0), int(attempts))

    def get(self, job_id: int) -> Job | None:
        with self._cond:
            return self._jobs.get(int(job_id))

    def lookup(self, job_id=None, key: str | None = None):
        """Resolve a job by id or idempotency key, including evicted ones.
        Returns ``("job", Job)``, ``("expired", tombstone)`` or ``None``."""
        with self._cond:
            if job_id is None and key is not None:
                job_id = self._by_key.get(str(key))
                if job_id is None:
                    for info in self._expired.values():
                        if info["key"] == key:
                            return ("expired", dict(info))
                    return None
            if job_id is None:
                return None
            job_id = int(job_id)
            job = self._jobs.get(job_id)
            if job is not None:
                return ("job", job)
            info = self._expired.get(job_id)
            if info is not None:
                return ("expired", dict(info))
            return None

    def wait(self, job_id: int, timeout: float | None = None) -> Job:
        """Block until the job reaches a terminal state (or timeout)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            job = self._jobs[int(job_id)]
            while job.state not in ("done", "failed", "quarantined"):
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(f"job {job.id} still {job.state}")
                self._cond.wait(timeout=remaining)
        return job

    # --------------------------------------------------------------- fencing

    def fence(self, epoch, router=None) -> None:
        """Epoch admission for router-forwarded requests.

        A forward whose epoch is *below* the highest accepted one is a
        zombie router's — reject it (``fencing_rejections``) by raising
        :class:`RouterFenced`.  A *higher* epoch means a takeover
        happened: adopt it and persist a journal ``fence`` marker so the
        floor survives a worker restart.  The ``route.fence`` fault site
        fires here (an armed fault is indistinguishable from a stale
        forward — the router-side demotion path runs for real)."""
        try:
            faults.fault_point("route.fence")
        except faults.FaultError as e:
            self.counters.add("fencing_rejections")
            raise RouterFenced(self.fence_epoch, f"injected: {e}")
        try:
            epoch = int(epoch)
        except (TypeError, ValueError):
            return  # epoch-less request: pre-HA router or direct client
        with self._cond:
            if epoch < self._fence_epoch:
                self.counters.add("fencing_rejections")
                who = f" from router {router!r}" if router else ""
                raise RouterFenced(
                    self._fence_epoch,
                    f"stale forward{who}: epoch {epoch} < accepted "
                    f"{self._fence_epoch}")
            if epoch > self._fence_epoch:
                self._fence_epoch = epoch
                # flight dumps carry the epoch this worker last honored
                obs_flight.set_identity(epoch=epoch)
                if self._journal is not None:
                    try:
                        n = self._journal.append_marker(
                            "fence", epoch=epoch,
                            router=None if router is None else str(router))
                        self.counters.add("journal_bytes", n)
                    except Exception as e:
                        print(f"WARNING: fence marker write failed ({e}); "
                              "the epoch floor will not survive a restart",
                              file=sys.stderr, flush=True)

    @property
    def fence_epoch(self) -> int:
        with self._cond:
            return self._fence_epoch

    # --------------------------------------------------------------- journal

    def _journal_update_locked(self, job: Job, state: str, **fields) -> None:
        """Journal a lifecycle transition.  Post-admission journal failures
        degrade durability, not availability: log and keep running (the
        job's manifest still proves completed stages on replay).

        Trace-completeness ordering: every transition record carries the
        job's ``trace_id``, and a *terminal* transition emits (and
        flushes) a ``serve.terminal`` trace event BEFORE the journal
        append — so "the journal proves the job terminal" implies "the
        trace has a durable terminal span", even under kill -9 right
        after the fsync."""
        if self._journal is None:
            return
        fields.setdefault("trace_id", job.trace_id)
        if state in ("done", "failed"):
            obs_trace.event("serve.terminal", trace_id=job.trace_id,
                            job_id=job.id, key=job.key, state=state)
            obs_trace.flush()
        try:
            n = self._journal_append_guarded(
                journal_mod.job_record(job.id, state, **fields))
        except Exception as e:
            if isinstance(e, OSError):
                # post-admission disk-full: durability degrades AND the
                # admission path must stop promising it — brownout
                self._trip_brownout_locked(e)
            print(f"WARNING: journal append ({state}, job {job.id}) "
                  f"failed: {e}", file=sys.stderr, flush=True)
            return
        self.counters.add("journal_bytes", n)
        self._maybe_rotate_locked()

    def _snapshot_records_locked(self) -> list[dict]:
        """One full-state record per tracked job, for checkpoint rotation,
        plus the marker state rotation must not lose: the fence floor,
        the per-key suspect lineage, and every quarantined key (a rotated
        journal that forgot a quarantine would re-dispatch the poison)."""
        to_journal = {"queued": "accepted", "running": "dispatched",
                      "quarantined": "accepted"}
        recs = []
        for jid in sorted(self._jobs):
            j = self._jobs[jid]
            recs.append(journal_mod.job_record(
                j.id, to_journal.get(j.state, j.state), key=j.key,
                spec=j.spec, deadline_s=j.deadline_s, outputs=j.outputs,
                error=j.error, wall_s=j.wall_s, trace_id=j.trace_id,
                trace=j.trace_ctx))
        if self._fence_epoch:
            recs.append({"v": 1, "rec": "marker", "kind": "fence",
                         "epoch": self._fence_epoch})
        for key in sorted(self._fleet_attempts):
            recs.append({"v": 1, "rec": "marker", "kind": "suspect",
                         "key": key,
                         "attempt": self._fleet_attempts[key],
                         **({"node": self.node} if self.node else {})})
        for key in sorted(self._quarantined):
            recs.append({"v": 1, "rec": "marker", "kind": "quarantined",
                         "key": key, "reason": self._quarantined[key],
                         **({"node": self.node} if self.node else {})})
        return recs

    def _maybe_rotate_locked(self) -> None:
        if self._journal is None or self._journal.max_bytes is None:
            return
        if self._journal.size() <= self._journal.max_bytes:
            return
        try:
            self._journal.rotate(self._snapshot_records_locked())
        except Exception as e:
            print(f"WARNING: journal rotation failed ({e}); appends continue "
                  "on the unrotated file", file=sys.stderr, flush=True)

    def _recover(self) -> None:
        """Replay the journal: re-enqueue every job not provably terminal.
        Each replayed job re-runs through the per-job manifest ``--resume``
        path, so completed stages are skipped and outputs stay
        byte-identical — exactly-once at the output level."""
        jobs, info = journal_mod.replay(self._journal.path)
        if info.get("crc_skipped"):
            # mid-file bit flips the replay refused to act on — surfaced
            # as a counter so a corrupted disk shows up in metrics, not
            # just a startup warning line
            self.counters.add("journal_crc_skipped", int(info["crc_skipped"]))
        requeued = finished = dropped = adopted = quarantined = 0
        with self._cond:
            if info.get("fence_epoch"):
                self._fence_epoch = max(self._fence_epoch,
                                        int(info["fence_epoch"]))
            # crash attribution survives the crash: suspect markers carry
            # the per-key attempt lineage, quarantined markers the parked
            # keys (max-merge / last-wins — both replay-idempotent)
            for k, n in (info.get("suspects") or {}).items():
                self._fleet_attempts[k] = max(
                    self._fleet_attempts.get(k, 0), int(n))
            self._quarantined.update(info.get("quarantined") or {})
            for jid in sorted(jobs):
                rec = jobs[jid]
                spec = rec.get("spec")
                if not isinstance(spec, dict) or not spec.get("input") \
                        or not spec.get("output"):
                    dropped += 1
                    print(f"WARNING: journal replay: job {jid} has no usable "
                          "spec (rotated-away accepted record?); dropping",
                          file=sys.stderr, flush=True)
                    continue
                if rec.get("adopted") \
                        and rec.get("state") not in ("done", "failed"):
                    # this journal was tombstoned while we were down: the
                    # job now lives on its ring successor.  Re-running it
                    # here is the zombie double-run the tombstone exists
                    # to prevent — drop it and count the fencing
                    adopted += 1
                    self.counters.add("fencing_rejections")
                    print(f"serve: journal replay: job {jid} was adopted by "
                          f"router {info.get('adopted_by')!r} while this "
                          "node was down; dropping (its ring successor "
                          "owns it now)", file=sys.stderr, flush=True)
                    continue
                job = Job(spec, job_id=jid,
                          key=rec.get("key") or journal_mod.idempotency_key(spec),
                          deadline_s=rec.get("deadline_s"),
                          trace_id=rec.get("trace_id"))
                ctx = rec.get("trace")
                job.trace_ctx = ctx if isinstance(ctx, dict) else None
                self._jobs[job.id] = job
                self._by_key[job.key] = job.id
                # migration shim: journals written before the v2 key
                # (version-pinned, input_range-aware) carry v1 keys.
                # Register the replayed job under every identity it has
                # ever had, so a client still polling the journaled key
                # AND a fresh dedupe on the recomputed key both resolve
                # to this job (setdefault: a live key never loses to an
                # alias).
                for alias in (journal_mod.idempotency_key(spec),
                              journal_mod.legacy_idempotency_key(spec)):
                    self._by_key.setdefault(alias, job.id)
                if rec.get("state") in ("done", "failed"):
                    job.state = rec["state"]
                    job.outputs = rec.get("outputs")
                    job.error = rec.get("error")
                    job.wall_s = rec.get("wall_s")
                    qc = rec.get("qc")
                    job.qc = qc if isinstance(qc, dict) else None
                    job.finished_t = time.monotonic()
                    finished += 1
                elif job.key in self._quarantined:
                    # the marker said it all: the job stays parked, polls
                    # keep answering, no dispatch until a release
                    job.state = "quarantined"
                    job.error = self._quarantined[job.key]
                    job.finished_t = time.monotonic()
                    quarantined += 1
                elif self.max_fleet_attempts and \
                        self._fleet_attempts.get(job.key, 0) \
                        >= self.max_fleet_attempts:
                    # suspect blame: this key was in flight at every one
                    # of its budgeted attempts and the process still died
                    # — quarantine NOW, before replay re-dispatches it
                    self.counters.add("suspect_blames")
                    self.counters.add("fleet_attempts_exhausted")
                    obs_flight.record(
                        "suspect_blamed", key=job.key, job_id=job.id,
                        attempts=self._fleet_attempts.get(job.key, 0))
                    self._quarantine_locked(
                        job, f"fleet retry budget exhausted "
                             f"({self._fleet_attempts.get(job.key, 0)}/"
                             f"{self.max_fleet_attempts} attempts; blamed "
                             "by replay crash attribution)")
                    quarantined += 1
                else:
                    if rec.get("state") == "dispatched" \
                            and self._fleet_attempts.get(job.key):
                        # crash attribution: the suspect marker proves
                        # this job was in flight when the process died
                        self.counters.add("suspect_blames")
                        obs_flight.record(
                            "suspect_blamed", key=job.key, job_id=job.id,
                            attempts=self._fleet_attempts[job.key])
                    # accepted or dispatched: not provably done -> re-run.
                    # The deadline clock restarts here — the daemon being
                    # down must not shed every queued job on every restart.
                    job.state = "queued"
                    job.submitted_t = time.monotonic()
                    self._enqueue_locked(job)
                    self.counters.add("jobs_replayed")
                    # stitch the restarted process onto the pre-crash
                    # trace: a follows_from edge back to the dead
                    # incarnation's durable ack span (persisted on the
                    # accepted record) keeps the job's span tree
                    # connected across kill -9 + replay
                    with obs_trace.span("serve.replay", trace_id=job.trace_id,
                                        link=job.trace_ctx, key=job.key,
                                        job_id=job.id):
                        job.trace_ctx = obs_trace.wire_context() \
                            or job.trace_ctx
                    requeued += 1
            self.counters.high_water("queue_depth_hwm", self._queued_locked())
            self._cond.notify_all()
        if requeued or finished or dropped or adopted or quarantined \
                or info["skipped"]:
            print(f"serve: journal replay: {requeued} job(s) re-enqueued, "
                  f"{finished} already terminal, "
                  f"{quarantined} quarantined, "
                  f"{adopted} adopted elsewhere, "
                  f"{dropped + info['skipped']} record(s) skipped"
                  + (" (previous shutdown was a clean drain)"
                     if info["clean_drain"] else ""),
                  file=sys.stderr, flush=True)
        if adopted:
            obs_flight.record("zombie_fenced", adopted_jobs=adopted,
                              adopted_by=info.get("adopted_by"))
        if (requeued or dropped or info["skipped"] or info["torn_tail"]) \
                and not info["clean_drain"]:
            # the previous daemon died uncleanly with work in flight: this
            # dump is the post-mortem a kill -9 itself could never write
            obs_flight.record(
                "journal_replay", requeued=requeued, finished=finished,
                skipped=dropped + info["skipped"],
                torn_tail=info["torn_tail"])
            obs_flight.dump(reason="journal-replay")

    # ------------------------------------------------------------- retention

    def _evict_locked(self, now: float) -> int:
        """Drop terminal jobs past the TTL or beyond ``result_max``; their
        outputs stay on disk and a bounded tombstone keeps ``result``
        replies informative."""
        terminal = [j for j in self._jobs.values()
                    if j.state in ("done", "failed")
                    and j.finished_t is not None]
        doomed = [j for j in terminal if now - j.finished_t > self.result_ttl_s]
        doomed_ids = {j.id for j in doomed}
        survivors = sorted((j for j in terminal if j.id not in doomed_ids),
                           key=lambda j: j.finished_t)
        over = len(survivors) - self.result_max
        if over > 0:
            doomed += survivors[:over]
        for j in doomed:
            del self._jobs[j.id]
            base = (j.outputs or {}).get("base") or job_paths(j.spec)["base"]
            self._expired[j.id] = {"job_id": j.id, "key": j.key,
                                   "final_state": j.state, "base": base}
            self.counters.add("evicted_jobs")
        while len(self._expired) > self._expired_cap:
            old_id = next(iter(self._expired))
            old = self._expired.pop(old_id)
            if self._by_key.get(old["key"]) == old_id:
                del self._by_key[old["key"]]
        return len(doomed)

    def evict_now(self) -> int:
        """Run one eviction pass immediately (tests, ops tooling)."""
        with self._cond:
            return self._evict_locked(time.monotonic())

    # ----------------------------------------------------- test/drain hooks

    def pause(self) -> None:
        with self._cond:
            self._paused = True

    def release(self) -> None:
        with self._cond:
            self._paused = False
            self._cond.notify_all()

    def stop_admission(self) -> None:
        """Signal-safe drain entry: stop accepting, wake the dispatcher,
        return immediately (the bounded wait happens in the CLI's drain
        step, never inside a signal handler)."""
        with self._cond:
            self._draining = True
            self._paused = False
            self._cond.notify_all()

    def drain(self, timeout: float | None = None) -> None:
        """Stop admitting; block until queued + running work finishes."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            self._draining = True
            self._paused = False
            self._cond.notify_all()
            while self._any_queued_locked() or self._running:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError("drain timed out")
                self._cond.wait(timeout=remaining)

    def shutdown(self, timeout: float | None = 5.0) -> None:
        """Stop the dispatcher WITHOUT waiting for queued work — queued
        jobs stay journaled and replay on the next start."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)

    def close(self, timeout: float | None = 60.0) -> None:
        try:
            self.drain(timeout=timeout)
        finally:
            self.shutdown(timeout=5.0)

    # ------------------------------------------------------------ critpath

    #: canonical boundary order; consecutive present stamps telescope into
    #: the segment chain obs/critpath.py renders
    _CRITPATH_ORDER = ("admit", "journal", "ack", "gang", "dispatch", "run")

    def _critpath_emit_locked(self, job: Job) -> None:
        """Emit the job's terminal ``serve.critpath`` event: boundary
        stamps as ms-from-submit offsets plus the queue-segment
        antagonist.  Raw evidence only — obs/critpath.py owns the
        decomposition math, so the event schema stays small and stable."""
        end = job.finished_t if job.finished_t is not None \
            else time.monotonic()
        stamps = {"submit": 0.0}
        for name in self._CRITPATH_ORDER:
            t = job.stamps.get(name)
            if t is not None:
                stamps[name] = round((t - job.submitted_t) * 1e3, 3)
        obs_trace.event(
            "serve.critpath", trace_id=job.trace_id, job_id=job.id,
            key=job.key, state=job.state, tenant=job.tenant, qos=job.qos,
            gang_size=job.gang_size, cached=job.cached,
            wall_ms=round((end - job.submitted_t) * 1e3, 3),
            queue_wait_ms=round((job.queue_wait_s or 0.0) * 1e3, 3),
            stamps=stamps, antagonist=self._antagonist_locked(job))

    def _antagonist_locked(self, job: Job) -> dict:
        """Who made this job wait: overlap of its queue window (ack ->
        gang pop) with recent gang runs names the dispatcher's victim
        jobs; the contention ledger's per-lock wait growth over the
        job's lifetime names the hottest lock (CCT_LOCK_LEDGER=1); the
        unexplained remainder is admission idle — the dispatcher was
        parked, nothing to blame but arrival order."""
        q0 = job.stamps.get("ack", job.submitted_t)
        q1 = job.stamps.get("gang")
        if q1 is None:
            q1 = job.finished_t if job.finished_t is not None \
                else time.monotonic()
        span = max(0.0, q1 - q0)
        busy = 0.0
        busiest_jobs: list[int] = []
        busiest_ov = 0.0
        for g in self._gang_log:
            ov = min(q1, g["t1"]) - max(q0, g["t0"])
            if ov <= 0:
                continue
            busy += ov
            if ov > busiest_ov:
                busiest_ov = ov
                busiest_jobs = list(g["jobs"])
        busy = min(busy, span)
        lock_name = None
        lock_wait_us = 0
        if job._lock_wait0 is not None:
            for name, row in sanitize.ledger_snapshot().items():
                d = row["wait_us"] - job._lock_wait0.get(name, 0)
                if d > lock_wait_us:
                    lock_wait_us = d
                    lock_name = name
        out = {"queue_ms": round(span * 1e3, 3),
               "dispatcher_busy_ms": round(busy * 1e3, 3),
               "idle_ms": round((span - busy) * 1e3, 3)}
        if busiest_jobs:
            out["busy_on_jobs"] = busiest_jobs[:8]
        if lock_name:
            out["lock"] = lock_name
            out["lock_wait_ms"] = round(lock_wait_us / 1e3, 3)
            holder = sanitize.current_holders().get(lock_name)
            if holder:
                out["lock_holder"] = holder
        # the dominant cause — what the fleet antagonist table keys on
        if span <= 0:
            out["kind"] = "none"
        elif busy >= span / 2:
            out["kind"] = "dispatcher"
        elif lock_wait_us / 1e6 >= span / 2:
            out["kind"] = "lock"
        else:
            out["kind"] = "idle"
        return out

    # ------------------------------------------------------------- metrics

    def metrics(self) -> dict:
        with self._cond:
            jobs = [j.describe() for j in self._jobs.values()]
            states = {s: sum(1 for j in self._jobs.values() if j.state == s)
                      for s in _STATES}
            cumulative = self.counters.snapshot()
            # recompiles live process-globally (the jit cache is per
            # process, not per Counters instance): folded in at read time
            cumulative["recompiles"] = obs_metrics.recompiles()
            # the trace plane owns its own tallies (spans/links/orphans
            # recorded by any thread, not just the scheduler): overlay
            # them so one metrics doc carries the whole process; same
            # for the profiler's sample/drop/shard tallies
            cumulative.update(obs_trace.counter_snapshot())
            cumulative.update(obs_prof.counter_snapshot())
            cumulative.update(obs_history.counter_snapshot())
            doc = metrics_doc(
                "serve", {"uptime": time.time() - self._started_at},
                {"n_jobs": len(jobs), "queue_bound": self.queue_bound,
                 "gang_size": self.gang_size, "draining": self._draining,
                 "brownout": self._brownout,
                 "quarantined_keys": len(self._quarantined),
                 "breakers_open": len(self._breaker_open_t),
                 "jobs_by_state": states},
                cumulative=cumulative,
            )
            doc["node"] = self.node
            doc["jobs"] = jobs
            doc["histograms"] = obs_metrics.histograms_snapshot()
            doc["labeled"] = obs_metrics.labeled_snapshot()
            # the lock-contention ledger composes in at READ time (never
            # via obs_metrics.inc — incrementing on every acquire would
            # put a metrics call on the hottest path in the process)
            if sanitize.ledger_enabled():
                led = sanitize.ledger_snapshot()
                if led:
                    lc = doc["labeled"].setdefault("counters", {})
                    for metric, field in (("lock_wait_us", "wait_us"),
                                          ("lock_hold_us", "hold_us"),
                                          ("lock_waits", "waits")):
                        lc[metric] = [
                            {"labels": {"lock": name}, "value": row[field]}
                            for name, row in led.items()]
            doc["slo"] = self.slo.snapshot()
            if self.autotune_info is not None:
                try:
                    doc["autotune"] = self.autotune_info()
                except Exception:
                    pass  # telemetry must never take down /metrics
            if self.canary_info is not None:
                try:
                    doc["canary"] = self.canary_info()
                except Exception:
                    pass
            doc["queued_by_class"] = \
                {qos: len(self._queues[qos]) for qos in QOS_CLASSES}
            doc["class_weights"] = dict(self.class_weights)
            if self._journal is not None:
                doc["journal"] = {"path": self._journal.path,
                                  "size_bytes": self._journal.size()}
            return doc

    def history_doc(self) -> dict:
        """Supplier for the :mod:`obs.history` recorder: the cumulative
        counters (deltas are taken on the history side) plus the gauges a
        delta cannot express."""
        m = self.metrics()
        gauges: dict = {
            "queued": sum((m.get("queued_by_class") or {}).values()),
            "n_jobs": m.get("n_jobs"),
        }
        canary = m.get("canary")
        if isinstance(canary, dict):
            gauges["canary_ok"] = 1 if canary.get("ok") else 0
            if canary.get("age_s") is not None:
                gauges["canary_age_s"] = canary["age_s"]
        return {"cum": m.get("cumulative") or {}, "gauges": gauges}

    def healthz(self) -> dict:
        with self._cond:
            return {
                "status": ("draining" if self._draining
                           else "brownout" if self._brownout
                           else "serving"),
                "node": self.node,
                "quarantined": len(self._quarantined),
                "queued": self._queued_locked(),
                "queued_by_class":
                    {qos: len(self._queues[qos]) for qos in QOS_CLASSES},
                "running": len(self._running),
                "uptime_s": round(time.time() - self._started_at, 3),
                "pid": os.getpid(),
                "fence_epoch": self._fence_epoch,
                "slo": self.slo.health(),
            }

    # ----------------------------------------------------------- dispatcher

    def _next_class_locked(self) -> str:
        """Stride pick: the backlogged class with the least accumulated
        virtual pass wins; registry class order breaks exact ties so the
        schedule is fully deterministic."""
        ready = [qos for qos in QOS_CLASSES if self._queues[qos]]
        return min(ready,
                   key=lambda qos: (self._pass[qos], QOS_CLASSES.index(qos)))

    def _pop_gang_locked(self) -> list[Job]:
        """Pop up to ``gang_size`` queued jobs sharing the compile-time
        consensus parameters (cutoff/qualscore/vote policy) from the
        stride-chosen qos class (gangs never span classes — fairness
        accounting stays exact).  Called under the lock."""
        qos = self._next_class_locked()
        queue = self._queues[qos]
        gang = [queue.popleft()]
        key = (float(gang[0].spec.get("cutoff", 0.7)),
               int(gang[0].spec.get("qualscore", 0)),
               str(gang[0].spec.get("policy") or "majority"))
        kept = deque()
        while queue and len(gang) < self.gang_size:
            job = queue.popleft()
            jkey = (float(job.spec.get("cutoff", 0.7)),
                    int(job.spec.get("qualscore", 0)),
                    str(job.spec.get("policy") or "majority"))
            if jkey == key:
                gang.append(job)
            else:
                kept.append(job)
        queue.extendleft(reversed(kept))
        # each dispatched job advances the class pass by one stride, so a
        # weight-8 class earns 8 dispatch slots per weight-1 slot
        self._pass[qos] += self._stride[qos] * len(gang)
        return gang

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._stop and \
                        (self._paused or not self._any_queued_locked()):
                    # parked time is the critpath "admission idle"
                    # denominator: queue waits that overlap neither a
                    # gang run nor a lock hold happened while the
                    # dispatcher had nothing to do
                    t_idle = time.monotonic()
                    self._cond.wait()
                    self.counters.add(
                        "dispatcher_idle_us",
                        int((time.monotonic() - t_idle) * 1e6))
                if self._stop:
                    return
                gang = self._pop_gang_locked()
                now = time.monotonic()
                live = []
                for job in gang:
                    # explicit deadline wins; otherwise the class SLO
                    # target acts as the implicit deadline (None = never)
                    effective = job.deadline_s if job.deadline_s is not None \
                        else self.slo_targets[job.qos]
                    if effective is not None and \
                            now - job.submitted_t > effective:
                        # dispatch-time shed: the deadline expired while the
                        # job sat in the queue; running it would waste device
                        # time on an answer nobody is waiting for
                        job.state = "failed"
                        job.error = (f"shed: deadline_s={effective:g} "
                                     f"expired after "
                                     f"{now - job.submitted_t:.1f}s in queue")
                        job.finished_t = now
                        # shed work carries its queue wait too — the whole
                        # point of critpath is accounting for the waits
                        # that did NOT end in a dispatch
                        job.queue_wait_s = now - job.submitted_t
                        self._count_shed_locked(job.tenant, job.qos)
                        self._critpath_emit_locked(job)
                        self._journal_update_locked(job, "failed",
                                                    error=job.error)
                    else:
                        # only survivors crossed the gang boundary: a shed
                        # job's critpath tail stays "queue" — it died
                        # waiting, it never joined a gang
                        job.stamps["gang"] = now
                        live.append(job)
                # budget gate: a quarantined (or budget-exhausted) job
                # must not reach another dispatch; survivors get their
                # suspect marker fsync'd before any work starts
                live = [job for job in live
                        if not self._predispatch_locked(job)]
                if not live:
                    self._cond.notify_all()
                    continue
                for job in live:
                    job.state = "running"
                    job.gang_size = len(live)
                    job.queue_wait_s = now - job.submitted_t
                    obs_metrics.observe("queue_wait_s", now - job.submitted_t)
                    obs_metrics.observe_labeled(
                        "tenant_queue_wait_s", now - job.submitted_t,
                        tenant=job.tenant, qos=job.qos)
                    self._journal_update_locked(job, "dispatched")
                    job.stamp("dispatch")
                self._running = list(live)
                self._cond.notify_all()
            t_busy = time.monotonic()
            try:
                self._run_gang(live)
            finally:
                t_end = time.monotonic()
                self.counters.add("dispatcher_busy_us",
                                  int((t_end - t_busy) * 1e6))
                with self._cond:
                    self._gang_log.append({"t0": t_busy, "t1": t_end,
                                           "jobs": [j.id for j in live]})
                    self._running = []
                    self._cond.notify_all()

    def _run_gang(self, gang: list[Job]) -> None:
        t0 = time.monotonic()
        # consult the content-addressed result cache BEFORE gang dispatch:
        # a hit job must not cost a single device batch.  The lookup is
        # purely an optimization — any failure degrades to recomputing.
        hits: dict[int, dict] = {}
        for job in gang:
            entry = self._cache_lookup(job)
            if entry is not None:
                hits[job.id] = entry
        # range-sharded sub-jobs run solo through the CLI (the gang reader
        # consumes whole inputs; ``--input_range`` only exists down the
        # one-shot path), and cache hits must not cost a device batch
        live = [j for j in gang
                if j.id not in hits and not j.spec.get("input_range")]
        if len(live) > 1:
            try:
                faults.fault_point("serve.dispatch")
                with obs_trace.span("serve.gang", n_jobs=len(live),
                                    trace_id=live[0].trace_id):
                    handoffs = gang_sscs([j.spec for j in live], self.counters,
                                         max_batch=self.max_batch,
                                         trace_ids=[j.trace_id for j in live])
                for j, h in zip(live, handoffs):
                    j._stream_handoff = h
            except Exception as e:
                # Gang failure granularity is the gang: fall back to solo
                # runs — each job's resume path re-runs whatever its own
                # (atomically committed) outputs can't prove done.
                print(f"WARNING: serve gang dispatch failed ({e}); "
                      "running jobs solo", file=sys.stderr, flush=True)
        for job in gang:
            jt0 = t0 if len(gang) > 1 else time.monotonic()
            job.stamps["run"] = jt0
            try:
                with obs_trace.span("serve.job", trace_id=job.trace_id,
                                    job_id=job.id, tenant=job.tenant,
                                    qos=job.qos, cached=job.id in hits,
                                    queue_wait_ms=round(
                                        (job.queue_wait_s or 0.0) * 1e3, 3)):
                    if job.id in hits:
                        self._cache_materialize(job, hits[job.id])
                    else:
                        self._run_job(job)
                outcome = "done"
            except Exception as e:
                job.error = f"{type(e).__name__}: {e}"
                outcome = "failed"
                # unhandled worker death (retries exhausted): dump the ring
                # while the evidence — fault firings, retry lineage — is
                # still in memory
                obs_flight.record("worker_death", job_id=job.id,
                                  trace_id=job.trace_id, error=job.error,
                                  tenant=job.tenant, qos=job.qos)
                obs_flight.dump(reason="worker-death")
            if outcome == "done" and job.id not in hits:
                self.aggregate_job_metrics(job)
                self._cache_insert(job)
            if outcome == "done":
                # cache hits carry a qc.json too (it is part of the
                # materialized payload) — quality attribution must not
                # have a hit-shaped hole
                self.aggregate_job_qc(job)
            with self._cond:
                # gang jobs count from dispatch start: the shared SSCS wall
                # belongs to every member's end-to-end latency
                job.wall_s = round(time.monotonic() - jt0, 6)
                obs_metrics.observe("job_wall_s", job.wall_s)
                # the tenant-facing latency (and what SLO targets are
                # judged against) includes queue wait: submit -> terminal
                latency = time.monotonic() - job.submitted_t
                obs_metrics.observe_labeled(
                    "tenant_job_wall_s", latency,
                    tenant=job.tenant, qos=job.qos)
                obs_metrics.inc(
                    "tenant_jobs_done" if outcome == "done"
                    else "tenant_jobs_failed",
                    tenant=job.tenant, qos=job.qos)
                self.slo.note(job.qos, wall_s=latency)
                job.state = outcome
                if outcome == "done":
                    # a finished key's attempt lineage is dead weight —
                    # only still-failing keys keep consuming budget
                    self._fleet_attempts.pop(job.key or "", None)
                job.finished_t = time.monotonic()
                self._ewma_job_s = job.wall_s if self._ewma_job_s is None \
                    else 0.8 * self._ewma_job_s + 0.2 * job.wall_s
                # the critpath event rides the terminal flush below: a
                # journaled-terminal job always has durable stamps
                self._critpath_emit_locked(job)
                self._journal_update_locked(
                    job, outcome, outputs=job.outputs, error=job.error,
                    wall_s=job.wall_s, qc=job.qc)
                self._evict_locked(time.monotonic())
                self._cond.notify_all()

    # ------------------------------------------- content-addressed cache

    def _cache_lookup(self, job: Job):
        """Find a committed cache entry for this job's content digest.
        Counts hits/misses (misses only for cacheable jobs — an
        unfingerprintable input is not a miss, it is about to be a real
        error).  Never raises: the cache is an optimization."""
        if self.result_cache is None:
            return None
        from consensuscruncher_tpu.serve import result_cache as rc_mod
        try:
            digest = rc_mod.content_digest(job.spec)
            if digest is None:
                return None
            entry = self.result_cache.lookup(digest)
        except Exception as e:
            print(f"WARNING: serve: cache lookup failed ({e}); recomputing",
                  file=sys.stderr, flush=True)
            return None
        if entry is None:
            self.counters.add("cache_misses")
            return None
        return entry

    def _cache_materialize(self, job: Job, entry: dict) -> None:
        """Serve a job straight from a committed cache entry: copy the
        payload into the job's own output tree (every file through
        ``commit_file``) and mark it done.  Raises on failure — the
        caller's normal failed-job path applies (the entry's payload is
        immutable, so a partial materialize never corrupts the store)."""
        base = job_paths(job.spec)["base"]
        n = self.result_cache.materialize(entry, base)
        job.outputs = {"base": base}
        job.cached = True
        self.counters.add("cache_hits")
        if entry.get("negative"):
            self.counters.add("cache_negative_hits")
        obs_trace.event("serve.cache_hit", trace_id=job.trace_id,
                        job_id=job.id, digest=entry.get("digest"),
                        bytes=n, negative=bool(entry.get("negative")))

    def _cache_insert(self, job: Job) -> None:
        """Commit a finished job's outputs as a cache entry (idempotent;
        best-effort — a failed insert costs a future hit, nothing else).
        A run that produced zero consensus families is flagged negative
        so known-empty work (an empty ``--input_range`` slice) is counted
        as such on later hits."""
        if self.result_cache is None:
            return
        from consensuscruncher_tpu.serve import result_cache as rc_mod
        try:
            digest = rc_mod.content_digest(job.spec)
            if digest is None or not job.outputs:
                return
            entry = self.result_cache.insert(
                digest, job.outputs["base"],
                negative=self._job_is_negative(job),
                meta={"key": job.key, "node": self.node or None})
        except Exception as e:
            print(f"WARNING: serve: cache insert failed ({e}); "
                  "result still served from the job's own outputs",
                  file=sys.stderr, flush=True)
            return
        if entry is None:
            return
        self.counters.add("cache_inserts")
        self.counters.add("cache_bytes", int(entry.get("bytes", 0)))
        for ev in self.result_cache.evict_to_budget():
            self.counters.add("cache_evictions")
            self.counters.add("cache_bytes", -int(ev.get("bytes", 0)))

    def _job_is_negative(self, job: Job) -> bool:
        """True when the job's own metrics sidecar proves zero consensus
        families came out — the cacheable-negative condition."""
        sidecar = f"{job_paths(job.spec)['sscs_prefix']}.metrics.json"
        try:
            with open(sidecar) as fh:
                cum = json.load(fh).get("cumulative", {})
        except (OSError, ValueError):
            return False
        return int(cum.get("families_out", -1)) == 0

    def _argv(self, spec: dict, resume: bool) -> list[str]:
        argv = [
            "consensus",
            "--input", spec["input"],
            "--output", spec["output"],
            "--cutoff", repr(float(spec.get("cutoff", 0.7))),
            "--qualscore", str(int(spec.get("qualscore", 0))),
            "--scorrect", str(bool(spec.get("scorrect", True))),
            "--max_mismatch", str(int(spec.get("max_mismatch", 0))),
            "--backend", self.backend,
            "--bdelim", spec.get("bdelim", "|"),
            "--compress_level", str(int(spec.get("compress_level", 6))),
        ]
        if spec.get("name"):
            argv += ["--name", spec["name"]]
        if spec.get("input_range"):
            # sub-job sharding: the range string rides the spec verbatim;
            # the CLI's manifest records it per stage, so overlapping
            # resubmits reuse committed outputs via RunManifest.can_skip
            argv += ["--input_range", str(spec["input_range"])]
        if spec.get("pipeline"):
            argv += ["--pipeline", str(spec["pipeline"])]
        if spec.get("policy"):
            # absent == majority (admission normalized the default away)
            argv += ["--policy", str(spec["policy"])]
        if "intermediate_taps" in spec:
            argv += ["--intermediate_taps", str(bool(spec["intermediate_taps"]))]
        if resume:
            argv += ["--resume", "True"]
        return argv

    def _run_job(self, job: Job) -> None:
        """Finish one job via the one-shot CLI with ``--resume`` (skips any
        stage the gang already recorded), retried with backoff on failure.
        The ``serve.worker`` fault site fires at each attempt's top."""
        from consensuscruncher_tpu import cli

        attempts = int(os.environ.get("CCT_SERVE_RETRIES", "1")) + 1
        base = float(os.environ.get("CCT_RETRY_BASE_S", "0.5"))
        argv = self._argv(job.spec, resume=True)
        # Streaming jobs: the first attempt runs the streaming chain (with
        # the gang's in-memory SSCS hand-off when the dispatch produced
        # one); --resume retries always take the staged path — the CLI's
        # own streaming guard enforces that, this loop just stops passing
        # the hand-off, whose memory is released after the first use.
        streaming = str(job.spec.get("pipeline", "")) == "streaming"
        handoff = getattr(job, "_stream_handoff", None) if streaming else None
        job._stream_handoff = None
        for attempt in range(attempts):
            job.attempts += 1
            try:
                faults.fault_point("serve.worker")
                if "poison" in str(job.spec.get("name") or ""):
                    # poison-labeled jobs only: a fleet-wide armed
                    # ``serve.poison`` (kill/exit kinds) simulates one
                    # deterministically crashing input without touching
                    # honest jobs sharing the daemon
                    faults.fault_point("serve.poison")
                if streaming and attempt == 0:
                    rc = cli.main(self._argv(job.spec, resume=False),
                                  _sscs_handoff=handoff)
                    handoff = None
                else:
                    rc = cli.main(argv)
                if rc not in (0, None):
                    raise RuntimeError(f"consensus exited rc={rc}")
                job.outputs = {"base": job_paths(job.spec)["base"]}
                return
            except Exception as e:
                if attempt + 1 >= attempts:
                    raise
                self.counters.add("retries_fired")
                # retry lineage: attempt ordinal + error on the job's trace
                obs_trace.event("serve.retry", trace_id=job.trace_id,
                                job_id=job.id, attempt=attempt + 1,
                                error=f"{type(e).__name__}: {e}")
                obs_flight.record("retry", job_id=job.id, attempt=attempt + 1,
                                  trace_id=job.trace_id,
                                  error=f"{type(e).__name__}: {e}")
                delay = faults.backoff_delay(attempt + 1, base, 30.0)
                print(f"WARNING: serve job {job.id} attempt "
                      f"{attempt + 1}/{attempts} failed ({e}); retrying via "
                      f"--resume in {delay:.1f}s", file=sys.stderr, flush=True)
                time.sleep(delay)

    def aggregate_job_metrics(self, job: Job) -> None:
        """Fold a finished job's per-stage metrics sidecar into the daemon
        counters — the one-shot CLI and the daemon share one schema, so
        aggregation is literally reading the stage's own cumulative block."""
        sidecar = f"{job_paths(job.spec)['sscs_prefix']}.metrics.json"
        try:
            with open(sidecar) as fh:
                cum = json.load(fh).get("cumulative", {})
        except (OSError, ValueError):
            return
        for key in ("families_in", "families_out", "batches_dispatched"):
            self.counters.add(key, int(cum.get(key, 0)))

    #: qc.json yield key -> per-tenant labeled series (registry QC_SERIES;
    #: cctlint CCT605 checks registration <-> emission both ways)
    _QC_YIELD_SERIES = (
        ("families", "tenant_qc_families"),
        ("sscs_written", "tenant_qc_sscs_written"),
        ("singletons", "tenant_qc_singletons"),
        ("dcs_written", "tenant_qc_dcs_written"),
    )

    def aggregate_job_qc(self, job: Job) -> None:
        """Fold a finished job's ``qc.json`` into the daemon's per-tenant
        quality series, attach a compact summary to the job (describe() +
        journal done record), and mark the ``serve.job`` trace.  Best-
        effort: a pre-QC or CCT_QC=0 run simply has no doc."""
        if not job.outputs:
            return
        doc_path = os.path.join(job.outputs.get("base") or "", "qc.json")
        try:
            with open(doc_path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return
        if not isinstance(doc, dict):
            return
        yields = doc.get("yields") or {}
        rates = doc.get("rates") or {}
        plane = doc.get("plane") or {}
        disagree = plane.get("disagree_rate")
        # synthetic canary probes keep their qc summary (describe() +
        # prober verification) but never touch the per-tenant QC series —
        # a heartbeat must not move real quality attribution
        if job.tenant != CANARY_TENANT:
            rescued = (int(yields.get("rescued_by_sscs", 0))
                       + int(yields.get("rescued_by_singleton", 0)))
            for key, series in self._QC_YIELD_SERIES:
                obs_metrics.inc(series, int(yields.get(key, 0)),
                                tenant=job.tenant, qos=job.qos)
            obs_metrics.inc("tenant_qc_rescued", rescued,
                            tenant=job.tenant, qos=job.qos)
            # per-policy quality attribution (ISSUE 17): ``policy`` is a
            # CLOSED label — docs stamped with a name outside POLICY_NAMES
            # (a foreign plugin, a corrupt doc) skip the per-policy series
            # rather than widening the exposition or failing the job
            policy = str(doc.get("policy") or "majority")
            if policy in POLICY_NAMES:
                obs_metrics.inc("tenant_qc_policy_jobs", 1,
                                tenant=job.tenant, qos=job.qos,
                                policy=policy)
                obs_metrics.inc("tenant_qc_policy_sscs_written",
                                int(yields.get("sscs_written", 0)),
                                tenant=job.tenant, qos=job.qos,
                                policy=policy)
            if disagree is not None:
                obs_metrics.observe_labeled("tenant_qc_disagreement",
                                            float(disagree),
                                            tenant=job.tenant, qos=job.qos)
        job.qc = {"yields": {k: int(v) for k, v in yields.items()},
                  "rates": rates,
                  "disagree_rate": disagree,
                  "spectrum": doc.get("spectrum") or {}}
        self.counters.add("qc_docs_committed")
        obs_trace.event("serve.qc", trace_id=job.trace_id, job_id=job.id,
                        tenant=job.tenant, qos=job.qos,
                        families=int(yields.get("families", 0)),
                        sscs_written=int(yields.get("sscs_written", 0)),
                        dcs_written=int(yields.get("dcs_written", 0)),
                        disagree_rate=disagree)
