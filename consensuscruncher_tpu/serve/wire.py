"""Per-message wire envelope: ``{"seq", "crc"}`` over the NDJSON frames.

The serve protocol is one JSON document per line.  That survives process
death (the journal replays) but not the wire itself: a duplicated frame
after a router retry is invisible below the idempotency layer, and a
flipped bit inside a frame parses as a *different* request.  The envelope
closes both holes without breaking old peers:

- a client stamps every request with a per-connection monotone ``seq``
  and a ``crc`` (CRC32 of the canonical encoding of the document minus
  the ``crc`` field itself);
- a server that sees ``crc`` verifies it — a mismatch is answered
  ``{"ok": false, "transport": true, "crc_error": true}`` (counted in
  ``wire_crc_errors``) so the client's transport-retry loop re-sends,
  instead of the server acting on a corrupted document;
- a server that sees ``seq`` remembers its last replies per connection:
  a *duplicated* frame (same seq on the same connection) is answered
  from that replay cache (counted in ``wire_dup_dropped``) instead of
  re-dispatching;
- replies to enveloped requests echo ``seq`` and carry their own
  ``crc``, which the client verifies before trusting the reply.

Negotiation is per-message and implicit: a legacy peer simply never
sends the fields and never gets them back — nothing in the grammar
changed for it (``seq``/``crc`` are registered reply keys in
``tools/cctlint/protocols.py``).
"""

from __future__ import annotations

import json
import zlib

#: replies remembered per connection for duplicate-frame absorption;
#: small on purpose — a duplicate arrives hot on the heels of the
#: original, never 33 requests later
REPLAY_CACHE_MAX = 32


def crc_of(doc: dict) -> int:
    """CRC32 of the canonical (sorted, compact) encoding of ``doc``
    minus any ``crc`` field — both sides compute over identical bytes
    regardless of key order or whitespace on the wire."""
    body = {k: v for k, v in doc.items() if k != "crc"}
    raw = json.dumps(body, sort_keys=True, separators=(",", ":")).encode()
    return zlib.crc32(raw) & 0xFFFFFFFF


def seal(doc: dict, seq: int) -> dict:
    """Return a copy of ``doc`` carrying the envelope fields.  A doc the
    canonical encoding cannot represent (exotic key types) degrades to
    seq-only — the peer's verify treats a missing crc as legacy, so the
    envelope never turns a deliverable message into an error."""
    out = dict(doc)
    out["seq"] = int(seq)
    try:
        out["crc"] = crc_of(out)
    except (TypeError, ValueError):
        pass
    return out


def verify(doc: dict) -> bool:
    """True when ``doc`` has no crc (legacy peer: nothing to check) or
    its crc matches the payload."""
    crc = doc.get("crc")
    if crc is None:
        return True
    try:
        return int(crc) == crc_of(doc)
    except (TypeError, ValueError):
        return False


class ReplayCache:
    """Per-connection seq -> reply memory (bounded, insertion-ordered).

    ``check(seq)`` returns the remembered reply for a duplicated frame,
    or None for a fresh seq; ``remember(seq, reply)`` stores the reply
    after dispatch so the next duplicate is answered without side
    effects."""

    def __init__(self, max_entries: int = REPLAY_CACHE_MAX):
        self.max_entries = max(1, int(max_entries))
        self._replies: dict[int, dict] = {}

    def check(self, seq) -> dict | None:
        try:
            return self._replies.get(int(seq))
        except (TypeError, ValueError):
            return None

    def remember(self, seq, reply: dict) -> None:
        try:
            seq = int(seq)
        except (TypeError, ValueError):
            return
        self._replies[seq] = reply
        while len(self._replies) > self.max_entries:
            self._replies.pop(next(iter(self._replies)))
