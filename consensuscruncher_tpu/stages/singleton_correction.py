"""Singleton correction: rescue size-1 families against the complementary strand.

Reference parity: ``ConsensusCruncher/singleton_correction.py`` (SURVEY.md
§3.5).  A singleton is rescued when a complementary-strand partner exists at
the same genomic anchor — either an SSCS (singleton–SSCS rescue, stronger
evidence) or another singleton (singleton–singleton rescue).  Outputs:

- ``<p>.sscs.rescue.sorted.bam``       singletons corrected against an SSCS
- ``<p>.singleton.rescue.sorted.bam``  singletons corrected against a singleton
- ``<p>.remaining.singleton.sorted.bam``  uncorrected singletons
- ``<p>.singleton_stats.txt|.json``

Matching is **exact** complementary-tag matching by default — a host-side
merge-join: both inputs are coordinate-sorted and a partner shares the
singleton's own ``(ref, pos)`` anchor, so the join streams one position
window at a time (no whole-BAM dicts).  SURVEY.md §2 notes BASELINE.json
describes Hamming-tolerant rescue; that generalization is available via
``max_mismatch > 0``, which routes barcode matching through the vectorized
device matcher (``ops.singleton_tpu.best_matches``), refusing ambiguous ties.

Correction formula (pinned): the rescued read's bases/quals are the duplex
vote of singleton vs partner (``core.duplex_cpu.correct_singleton``) —
agreement keeps the base with summed-capped quality, disagreement yields N.
Partners of unequal read length are not rescued (documented tightening).
In singleton–singleton rescue BOTH reads are corrected and written.

Host-side-by-design (measured, round 4 — VERDICT r3 weak 3): this stage is
0.9% of consensus stage wall at the ultra-deep shape (mean family 50,
where the device mesh pays) and 8.2% at the typical cfDNA shape — and its
cost is the hash/merge-join itself, not the per-base vote, so sharding it
over the chip mesh cannot repay a wire round trip.  It parallelizes with
the rest of the pipeline through ``--host_workers`` (each worker rescues
its own coordinate range); the ``max_mismatch > 0`` barcode matcher is the
one compute-shaped piece and already runs on the device.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator

import numpy as np

from consensuscruncher_tpu.core import tags as tags_mod
from consensuscruncher_tpu.core.duplex_cpu import correct_singleton
from consensuscruncher_tpu.io import bgzf
from consensuscruncher_tpu.io.bam import BamReader, BamRead
from consensuscruncher_tpu.ops.singleton_tpu import best_matches
from consensuscruncher_tpu.stages.grouping import consensus_windows
from consensuscruncher_tpu.utils.backend_probe import record_backend
from consensuscruncher_tpu.utils.phred import decode_seq, encode_seq
from consensuscruncher_tpu.utils.stats import StageStats


@dataclass
class SingletonResult:
    sscs_rescue_bam: str
    singleton_rescue_bam: str
    remaining_bam: str
    stats: StageStats | None  # None when reconstructed from a resume skip

    @classmethod
    def from_prefix(cls, out_prefix: str) -> "SingletonResult":
        """Path-only result for a stage skipped by --resume."""
        p = output_paths(out_prefix)
        return cls(p["sscs_rescue"], p["singleton_rescue"], p["remaining"], None)


def output_paths(out_prefix: str) -> dict[str, str]:
    """Canonical output paths for a prefix — the single naming authority
    shared by the stage body and the CLI's resume manifest."""
    return {
        "sscs_rescue": f"{out_prefix}.sscs.rescue.sorted.bam",
        "singleton_rescue": f"{out_prefix}.singleton.rescue.sorted.bam",
        "remaining": f"{out_prefix}.remaining.singleton.sorted.bam",
        "stats_txt": f"{out_prefix}.singleton_stats.txt",
        "stats_json": f"{out_prefix}.singleton_stats.json",
    }


def _merge_windows(a: Iterator, b: Iterator) -> Iterator[tuple[dict, dict]]:
    """Lockstep position-join of two sorted window streams."""
    wa = next(a, None)
    wb = next(b, None)
    while wa is not None or wb is not None:
        if wb is None or (wa is not None and wa[0] < wb[0]):
            yield wa[1], {}
            wa = next(a, None)
        elif wa is None or wb[0] < wa[0]:
            yield {}, wb[1]
            wb = next(b, None)
        else:
            yield wa[1], wb[1]
            wa = next(a, None)
            wb = next(b, None)


def _corrected(read: BamRead, partner: BamRead) -> BamRead:
    s, q = correct_singleton(
        encode_seq(read.seq),
        read.qual if read.qual.size else np.zeros(len(read.seq), dtype=np.uint8),
        encode_seq(partner.seq),
        partner.qual if partner.qual.size else np.zeros(len(partner.seq), dtype=np.uint8),
    )
    out = BamRead(
        qname=read.qname, flag=read.flag, ref=read.ref, pos=read.pos, mapq=read.mapq,
        cigar=read.cigar, mate_ref=read.mate_ref, mate_pos=read.mate_pos, tlen=read.tlen,
        seq=decode_seq(s), qual=q, tags=dict(read.tags),
    )
    out.tags["XR"] = ("Z", "sscs" if "XF" in partner.tags and partner.tags["XF"][1] > 1 else "singleton")
    return out


def _hamming_partner(tag, candidates: dict, max_mismatch: int, device: bool):
    """Barcode-tolerant partner lookup among same-anchor candidates whose
    non-barcode tag fields match the mirrored tag exactly."""
    mirror = tags_mod.duplex_tag(tag)
    pool = [
        t for t in candidates
        if (t.ref, t.pos, t.mate_ref, t.mate_pos, t.read_number, t.orientation)
        == (mirror.ref, mirror.pos, mirror.mate_ref, mirror.mate_pos, mirror.read_number, mirror.orientation)
        and len(t.barcode) == len(mirror.barcode)
    ]
    if not pool:
        return None
    a = encode_seq(mirror.barcode.replace(tags_mod.BARCODE_SEP, ""))[None, :]
    b = np.stack([encode_seq(t.barcode.replace(tags_mod.BARCODE_SEP, "")) for t in pool])
    idx = best_matches(a, b, max_mismatch=max_mismatch, device=device)[0]
    return pool[idx] if idx >= 0 else None


def _run_rescue_blocks(singleton_bam, sscs_bam, writers, stats, backend,
                       resident=None, cum=None) -> None:
    """Vectorized exact-match rescue: RescueBlock decisions -> batched duplex
    votes -> columnar record rebuild (original record + new seq/qual +
    appended XR tag).  Byte-parity with the object walk is pinned by
    tests/test_singleton_vec.py.

    ``resident``: the SSCS stage's device-resident plane store.  On the
    singleton-vs-SSCS route the partner half is gathered on device instead
    of re-uploaded, and the rescue OUTPUT planes are registered back into
    the store under the singleton's qname so the later DCS pass gathers
    rescued records too.  Misses/broken store fall back to the staged vote
    — identical bytes either way.

    Contract: consumes this pipeline's own SSCS-stage outputs (XT/XF-led tag
    blocks, no preexisting XR tag) — foreign layouts raise and the caller
    falls back to the object walk."""
    from consensuscruncher_tpu.core.consensus_cpu import DEFAULT_QUAL_CAP
    from consensuscruncher_tpu.io.columnar import open_batch_source
    from consensuscruncher_tpu.io.encode import encode_records
    from consensuscruncher_tpu.stages.dcs_maker import _duplex_vote_batch, _qname_bytes
    from consensuscruncher_tpu.stages.grouping import singleton_rescue_blocks
    from consensuscruncher_tpu.utils.ragged import gather_runs

    _XR_SSCS = np.frombuffer(b"XRZsscs\x00", np.uint8)
    _XR_SINGLE = np.frombuffer(b"XRZsingleton\x00", np.uint8)
    s_reader = open_batch_source(singleton_bam)
    x_reader = open_batch_source(sscs_bam)
    try:
        header = s_reader.header
        for blk in singleton_rescue_blocks(s_reader, x_reader, header):
            # guard zero increments: the object walk only creates counter
            # keys it actually touches, and stats files are parity artifacts
            for key, val in (
                ("singletons_total", blk.stats_total),
                ("rescued_by_sscs", blk.stats_sscs),
                ("rescued_by_singleton", blk.stats_singleton),
                ("remaining", blk.stats_remaining),
                ("length_mismatch", blk.stats_mismatch),
            ):
                if val:
                    stats.incr(key, val)

            # remaining singletons: raw blob passthrough
            k = 0
            nr = len(blk.remaining_row)
            while k < nr:
                si = int(blk.remaining_src[k])
                k2 = k
                while k2 < nr and blk.remaining_src[k2] == si:
                    k2 += 1
                batch = blk.sources[si]
                rows = blk.remaining_row[k:k2]
                data, _ = gather_runs(
                    batch.buf, batch.rec_off[rows],
                    batch.rec_off[rows + 1] - batch.rec_off[rows],
                )
                writers["remaining"].write_encoded(data)
                k = k2

            n_resc = len(blk.rescue_row)
            if n_resc == 0:
                continue
            # per-rescue READ columns gathered per source batch (the partner
            # contributes only its seq/qual, via member_mat below)
            flag = np.empty(n_resc, np.int64)
            rid = np.empty(n_resc, np.int64)
            posc = np.empty(n_resc, np.int64)
            mridc = np.empty(n_resc, np.int64)
            mposc = np.empty(n_resc, np.int64)
            tlenc = np.empty(n_resc, np.int64)
            mapqc = np.empty(n_resc, np.int64)
            lseqc = np.empty(n_resc, np.int64)
            for si, batch in enumerate(blk.sources):
                m = blk.rescue_src == si
                if not m.any():
                    continue
                rows = blk.rescue_row[m]
                flag[m] = batch.flag[rows]
                rid[m] = batch.ref_id[rows]
                posc[m] = batch.pos[rows]
                mridc[m] = batch.mate_ref_id[rows]
                mposc[m] = batch.mate_pos[rows]
                tlenc[m] = batch.tlen[rows]
                mapqc[m] = batch.mapq[rows]
                lseqc[m] = batch.l_seq[rows]

            def member_mat(src_arr, row_arr, sel, L):
                out_c = np.empty((int(sel.sum()), L), np.uint8)
                out_q = np.empty_like(out_c)
                pos_sel = np.nonzero(sel)[0]
                for si, batch in enumerate(blk.sources):
                    m = src_arr[pos_sel] == si
                    if not m.any():
                        continue
                    rows = row_arr[pos_sel[m]]
                    codes, coff = batch.seq_codes()
                    quals, _ = batch.quals()
                    out_c[m] = codes[coff[rows][:, None] + np.arange(L)]
                    out_q[m] = quals[coff[rows][:, None] + np.arange(L)]
                return out_c, out_q

            for route_name, route in (("sscs_rescue", 0), ("singleton_rescue", 1)):
                rmask = blk.rescue_route == route
                if not rmask.any():
                    continue
                for L in np.unique(lseqc[rmask]):
                    L = int(L)
                    sel = rmask & (lseqc == L)
                    ps = np.nonzero(sel)[0]
                    s1m, q1m = member_mat(blk.rescue_src, blk.rescue_row, sel, L)
                    out_b = out_q = None
                    if route == 0 and resident is not None and not resident.broken:
                        # singleton-vs-SSCS: the partner IS an SSCS record —
                        # gather its plane from the resident store instead
                        # of re-uploading it from BAM bytes
                        qn2 = _qname_bytes(blk.sources, blk.partner_src,
                                           blk.partner_row, ps)
                        idx2 = resident.rows_for(qn2, L)
                        if idx2 is not None:
                            hit = idx2 >= 0
                            if hit.any():
                                qn1 = _qname_bytes(blk.sources, blk.rescue_src,
                                                   blk.rescue_row, ps[hit])
                                res = resident.duplex_against(
                                    s1m[hit], q1m[hit], idx2[hit], L,
                                    register_qnames=qn1,
                                    qual_cap=DEFAULT_QUAL_CAP)
                                if res is not None:
                                    out_b = np.empty_like(s1m)
                                    out_q = np.empty_like(q1m)
                                    out_b[hit], out_q[hit] = res
                                    if cum is not None:
                                        cum.add("resident_pair_votes",
                                                int(hit.sum()))
                                    if not hit.all():
                                        sel_miss = np.zeros_like(sel)
                                        sel_miss[ps[~hit]] = True
                                        s2m, q2m = member_mat(
                                            blk.partner_src, blk.partner_row,
                                            sel_miss, L)
                                        mb, mq = _duplex_vote_batch(
                                            s1m[~hit], q1m[~hit], s2m, q2m,
                                            DEFAULT_QUAL_CAP, backend)
                                        out_b[~hit], out_q[~hit] = mb, mq
                                        if cum is not None:
                                            cum.add("staged_pair_votes",
                                                    int((~hit).sum()))
                    if out_b is None:
                        s2m, q2m = member_mat(blk.partner_src, blk.partner_row, sel, L)
                        out_b, out_q = _duplex_vote_batch(
                            s1m, q1m, s2m, q2m, DEFAULT_QUAL_CAP, backend
                        )
                        if cum is not None:
                            cum.add("staged_pair_votes", len(ps))
                    kk = len(ps)
                    # original qname / cigar / tag bytes, gathered per source
                    qn_start = np.empty(kk, np.int64)
                    qn_len = np.empty(kk, np.int64)
                    cg_start = np.empty(kk, np.int64)
                    cg_len = np.empty(kk, np.int64)
                    tg_start = np.empty(kk, np.int64)
                    tg_len = np.empty(kk, np.int64)
                    src_of = np.empty(kk, np.int64)
                    for si, batch in enumerate(blk.sources):
                        m = blk.rescue_src[ps] == si
                        if not m.any():
                            continue
                        rows = blk.rescue_row[ps[m]]
                        qn_start[m] = batch.qname_start[rows]
                        qn_len[m] = batch.l_qname[rows] - 1
                        cg_start[m] = batch.cigar_start[rows]
                        cg_len[m] = batch.n_cigar[rows]
                        tg_start[m] = batch.tags_start[rows]
                        tg_len[m] = batch.rec_off[rows + 1] - batch.tags_start[rows]
                        src_of[m] = si
                    from consensuscruncher_tpu.utils.ragged import scatter_runs

                    def gath(starts, lens):
                        data = np.empty(int(lens.sum()), np.uint8)
                        doff = np.zeros(kk, np.int64)
                        np.cumsum(lens[:-1], out=doff[1:])
                        for si, batch in enumerate(blk.sources):
                            m = src_of == si
                            if not m.any():
                                continue
                            scatter_runs(data, doff[m], batch.buf, lens[m],
                                         src_starts=starts[m])
                        return data
                    qn_data = gath(qn_start, qn_len)
                    cg_data = gath(cg_start, 4 * cg_len)
                    tg_old = gath(tg_start, tg_len)
                    # append XR:Z per record — value from the PARTNER's
                    # family size (object rule: XF > 1 -> "sscs")
                    xr_is_sscs = blk.partner_xf[ps] > 1
                    xr_len = np.where(xr_is_sscs, len(_XR_SSCS), len(_XR_SINGLE))
                    new_len = tg_len + xr_len
                    new_off = np.zeros(kk, np.int64)
                    np.cumsum(new_len[:-1], out=new_off[1:])
                    tg_new = np.empty(int(new_len.sum()), np.uint8)
                    scatter_runs(tg_new, new_off, tg_old, tg_len)
                    for m, blob_arr in ((xr_is_sscs, _XR_SSCS),
                                        (~xr_is_sscs, _XR_SINGLE)):
                        if not m.any():
                            continue
                        mat = np.broadcast_to(blob_arr, (int(m.sum()), len(blob_arr)))
                        scatter_runs(tg_new, (new_off + tg_len)[m],
                                     np.ascontiguousarray(mat).reshape(-1),
                                     np.full(int(m.sum()), len(blob_arr), np.int64))
                    blob = encode_records(
                        qn_data, qn_len,
                        flag[ps], rid[ps], posc[ps], mapqc[ps],
                        np.ascontiguousarray(cg_data).view("<u4"), cg_len,
                        mridc[ps], mposc[ps], tlenc[ps],
                        out_b.reshape(-1), np.full(kk, L, np.int64),
                        out_q.reshape(-1),
                        tg_new, new_len,
                    )
                    writers[route_name].write_encoded(blob)
    finally:
        s_reader.close()
        x_reader.close()


def run_singleton_correction(
    singleton_bam: str,
    sscs_bam: str,
    out_prefix: str,
    max_mismatch: int = 0,
    backend: str = "tpu",
    _force_object: bool = False,
    level: int = 6,
    residency=None,
    stream_out=None,
) -> SingletonResult:
    """``backend="cpu"`` keeps the Hamming matcher in numpy — a cpu run
    must never touch (or wait on) a device backend.

    ``residency``: the SSCS stage's ``ops.packing.resident_planes()`` store
    (vectorized path only — the object walk never sees self-produced BAMs
    at device scale).

    ``max_mismatch == 0`` (exact complementary-tag matching, the default)
    runs the vectorized RescueBlock path; ``max_mismatch > 0`` (and foreign
    tag layouts) use the object window walk.  ``_force_object`` exists for
    the byte-parity test suite.

    ``stream_out``: a ``core.streamgraph.StreamOut``; outputs hand off in
    memory — remaining singletons stay a final output (write-behind
    materialization), the two rescue BAMs become debug taps.  Requires
    the vectorized path (``max_mismatch == 0``); ``singleton_bam`` /
    ``sscs_bam`` may then be in-memory batch sources, and a foreign-
    layout fallback (which needs file re-reads) raises instead."""
    from consensuscruncher_tpu.utils.profiling import write_metrics
    from consensuscruncher_tpu.utils.stats import TimeTracker

    tracker = TimeTracker()
    use_device = backend == "tpu"
    stats = StageStats("singleton_correction")
    all_paths = output_paths(out_prefix)
    paths = {k: all_paths[k] for k in ("sscs_rescue", "singleton_rescue", "remaining")}

    from consensuscruncher_tpu.io.columnar import SortingBamWriter

    from consensuscruncher_tpu.obs import metrics as obs_metrics
    from consensuscruncher_tpu.utils.profiling import Counters

    if stream_out is not None and (max_mismatch != 0 or _force_object):
        raise RuntimeError(
            "streaming hand-off requires the vectorized rescue path")
    cum = Counters()
    recompiles_before = obs_metrics.recompiles()
    transfers_before = obs_metrics.transfer_bytes()
    io_before = bgzf.write_stats()
    if max_mismatch == 0 and not _force_object:
        if hasattr(singleton_bam, "header"):
            header = singleton_bam.header
        else:
            hdr_reader = BamReader(singleton_bam)
            header = hdr_reader.header
            hdr_reader.close()
        writers = {k: SortingBamWriter(p, header, level=level) for k, p in paths.items()}
        ok = False
        try:
            try:
                _run_rescue_blocks(singleton_bam, sscs_bam, writers, stats,
                                   backend, resident=residency, cum=cum)
                ok = True
            except ValueError as e:
                if "foreign tag layout" not in str(e) or stream_out is not None:
                    # in-memory sources can't re-read as files for the
                    # object walk — surface to the staged-fallback path
                    raise
        finally:
            if not ok:
                for w in writers.values():
                    w.abort()
        if ok:
            if stream_out is not None:
                stream_out.capture(
                    "remaining", writers["remaining"].close_to_memory(),
                    file_path=paths["remaining"], level=level)
                for k in ("sscs_rescue", "singleton_rescue"):
                    stream_out.capture(
                        k, writers[k].close_to_memory(),
                        file_path=paths[k] if stream_out.taps else None,
                        level=level)
            else:
                for w in writers.values():
                    w.close()
            stats.set("max_mismatch", max_mismatch)
            record_backend(stats, backend)
            stats.write(all_paths["stats_txt"])
            tracker.mark("rescue")
            tracker.write(f"{out_prefix}.singleton.time_tracker.txt")
            cum.add("recompiles", obs_metrics.recompiles() - recompiles_before)
            transfers = obs_metrics.transfer_bytes()
            cum.add("bytes_h2d", transfers["h2d"] - transfers_before["h2d"])
            cum.add("bytes_d2h", transfers["d2h"] - transfers_before["d2h"])
            iostat = bgzf.write_stats()
            cum.add("deflate_wall_us",
                    iostat["deflate_wall_us"] - io_before["deflate_wall_us"])
            cum.add("bytes_bam_written",
                    iostat["bytes_written"] - io_before["bytes_written"])
            write_metrics(
                f"{out_prefix}.singleton.metrics.json", "singleton_correction",
                tracker.as_phases(),
                {"backend": backend, "jax_backend": stats.get("jax_backend"),
                 "singletons": stats.get("singletons_total")},
                cumulative=cum.snapshot(),
            )
            return SingletonResult(
                paths["sscs_rescue"], paths["singleton_rescue"],
                paths["remaining"], stats,
            )
        # foreign layout: restart cleanly on the object walk below
        stats = StageStats("singleton_correction")

    s_reader = BamReader(singleton_bam)
    x_reader = BamReader(sscs_bam)
    writers = {k: SortingBamWriter(p, s_reader.header, level=level) for k, p in paths.items()}

    try:
        for singles, sscses in _merge_windows(
            consensus_windows(s_reader), consensus_windows(x_reader)
        ):
            done: set = set()
            for tag in sorted(singles, key=str):
                if tag in done:
                    continue
                stats.incr("singletons_total")
                read = singles[tag]
                mirror = tags_mod.duplex_tag(tag)

                partner_tag, pool = None, None
                if mirror in sscses:
                    partner_tag, pool = mirror, sscses
                elif mirror in singles and mirror != tag and mirror not in done:
                    partner_tag, pool = mirror, singles
                elif max_mismatch > 0:
                    partner_tag = _hamming_partner(tag, sscses, max_mismatch, use_device)
                    pool = sscses
                    if partner_tag is None:
                        # exclude self AND already-consumed singletons — a
                        # singleton may be corrected at most once
                        avail = {t: r for t, r in singles.items() if t != tag and t not in done}
                        partner_tag = _hamming_partner(tag, avail, max_mismatch, use_device)
                        pool = singles

                partner = pool.get(partner_tag) if partner_tag is not None else None
                if partner is None or len(partner.seq) != len(read.seq):
                    if partner is not None:
                        stats.incr("length_mismatch")
                    stats.incr("remaining")
                    writers["remaining"].write(read)
                    continue

                if pool is sscses:
                    stats.incr("rescued_by_sscs")
                    writers["sscs_rescue"].write(_corrected(read, partner))
                else:
                    # symmetric singleton-singleton rescue: correct both now
                    stats.incr("rescued_by_singleton", 2)
                    stats.incr("singletons_total")
                    writers["singleton_rescue"].write(_corrected(read, partner))
                    writers["singleton_rescue"].write(_corrected(partner, read))
                    done.add(partner_tag)
    except BaseException:
        for w in writers.values():
            w.abort()
        raise
    finally:
        s_reader.close()
        x_reader.close()

    for w in writers.values():
        w.close()  # lexsort + final BGZF write happen here
    stats.set("max_mismatch", max_mismatch)
    record_backend(stats, backend)
    stats.write(all_paths["stats_txt"])
    tracker.mark("rescue")
    tracker.write(f"{out_prefix}.singleton.time_tracker.txt")
    write_metrics(
        f"{out_prefix}.singleton.metrics.json", "singleton_correction",
        tracker.as_phases(),
        {"backend": backend, "jax_backend": stats.get("jax_backend"),
         "singletons": stats.get("singletons_total")},
    )
    return SingletonResult(paths["sscs_rescue"], paths["singleton_rescue"], paths["remaining"], stats)


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description="Rescue singletons against the complementary strand")
    p.add_argument("--singleton", required=True, help="sorted singleton BAM")
    p.add_argument("--bamfile", required=True, help="sorted SSCS BAM")
    p.add_argument("--outfile", required=True, help="output prefix")
    p.add_argument("--max-mismatch", type=int, default=0,
                   help="barcode Hamming tolerance (0 = exact complementary match)")
    p.add_argument("--backend", choices=("cpu", "tpu"), default="tpu")
    args = p.parse_args(argv)
    if args.max_mismatch > 0:
        from consensuscruncher_tpu.utils.backend_probe import ensure_backend

        ensure_backend(args.backend)
    run_singleton_correction(args.singleton, args.bamfile, args.outfile,
                             args.max_mismatch, backend=args.backend)


if __name__ == "__main__":
    main()
