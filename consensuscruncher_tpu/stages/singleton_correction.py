"""Singleton correction: rescue size-1 families against the complementary strand.

Reference parity: ``ConsensusCruncher/singleton_correction.py`` (SURVEY.md
§3.5).  A singleton is rescued when a complementary-strand partner exists at
the same genomic anchor — either an SSCS (singleton–SSCS rescue, stronger
evidence) or another singleton (singleton–singleton rescue).  Outputs:

- ``<p>.sscs.rescue.sorted.bam``       singletons corrected against an SSCS
- ``<p>.singleton.rescue.sorted.bam``  singletons corrected against a singleton
- ``<p>.remaining.singleton.sorted.bam``  uncorrected singletons
- ``<p>.singleton_stats.txt|.json``

Matching is **exact** complementary-tag matching by default — a host-side
merge-join: both inputs are coordinate-sorted and a partner shares the
singleton's own ``(ref, pos)`` anchor, so the join streams one position
window at a time (no whole-BAM dicts).  SURVEY.md §2 notes BASELINE.json
describes Hamming-tolerant rescue; that generalization is available via
``max_mismatch > 0``, which routes barcode matching through the vectorized
device matcher (``ops.singleton_tpu.best_matches``), refusing ambiguous ties.

Correction formula (pinned): the rescued read's bases/quals are the duplex
vote of singleton vs partner (``core.duplex_cpu.correct_singleton``) —
agreement keeps the base with summed-capped quality, disagreement yields N.
Partners of unequal read length are not rescued (documented tightening).
In singleton–singleton rescue BOTH reads are corrected and written.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator

import numpy as np

from consensuscruncher_tpu.core import tags as tags_mod
from consensuscruncher_tpu.core.duplex_cpu import correct_singleton
from consensuscruncher_tpu.io.bam import BamReader, BamRead
from consensuscruncher_tpu.ops.singleton_tpu import best_matches
from consensuscruncher_tpu.stages.grouping import consensus_windows
from consensuscruncher_tpu.utils.phred import decode_seq, encode_seq
from consensuscruncher_tpu.utils.stats import StageStats


@dataclass
class SingletonResult:
    sscs_rescue_bam: str
    singleton_rescue_bam: str
    remaining_bam: str
    stats: StageStats | None  # None when reconstructed from a resume skip

    @classmethod
    def from_prefix(cls, out_prefix: str) -> "SingletonResult":
        """Path-only result for a stage skipped by --resume."""
        p = output_paths(out_prefix)
        return cls(p["sscs_rescue"], p["singleton_rescue"], p["remaining"], None)


def output_paths(out_prefix: str) -> dict[str, str]:
    """Canonical output paths for a prefix — the single naming authority
    shared by the stage body and the CLI's resume manifest."""
    return {
        "sscs_rescue": f"{out_prefix}.sscs.rescue.sorted.bam",
        "singleton_rescue": f"{out_prefix}.singleton.rescue.sorted.bam",
        "remaining": f"{out_prefix}.remaining.singleton.sorted.bam",
        "stats_txt": f"{out_prefix}.singleton_stats.txt",
        "stats_json": f"{out_prefix}.singleton_stats.json",
    }


def _merge_windows(a: Iterator, b: Iterator) -> Iterator[tuple[dict, dict]]:
    """Lockstep position-join of two sorted window streams."""
    wa = next(a, None)
    wb = next(b, None)
    while wa is not None or wb is not None:
        if wb is None or (wa is not None and wa[0] < wb[0]):
            yield wa[1], {}
            wa = next(a, None)
        elif wa is None or wb[0] < wa[0]:
            yield {}, wb[1]
            wb = next(b, None)
        else:
            yield wa[1], wb[1]
            wa = next(a, None)
            wb = next(b, None)


def _corrected(read: BamRead, partner: BamRead) -> BamRead:
    s, q = correct_singleton(
        encode_seq(read.seq),
        read.qual if read.qual.size else np.zeros(len(read.seq), dtype=np.uint8),
        encode_seq(partner.seq),
        partner.qual if partner.qual.size else np.zeros(len(partner.seq), dtype=np.uint8),
    )
    out = BamRead(
        qname=read.qname, flag=read.flag, ref=read.ref, pos=read.pos, mapq=read.mapq,
        cigar=read.cigar, mate_ref=read.mate_ref, mate_pos=read.mate_pos, tlen=read.tlen,
        seq=decode_seq(s), qual=q, tags=dict(read.tags),
    )
    out.tags["XR"] = ("Z", "sscs" if "XF" in partner.tags and partner.tags["XF"][1] > 1 else "singleton")
    return out


def _hamming_partner(tag, candidates: dict, max_mismatch: int, device: bool):
    """Barcode-tolerant partner lookup among same-anchor candidates whose
    non-barcode tag fields match the mirrored tag exactly."""
    mirror = tags_mod.duplex_tag(tag)
    pool = [
        t for t in candidates
        if (t.ref, t.pos, t.mate_ref, t.mate_pos, t.read_number, t.orientation)
        == (mirror.ref, mirror.pos, mirror.mate_ref, mirror.mate_pos, mirror.read_number, mirror.orientation)
        and len(t.barcode) == len(mirror.barcode)
    ]
    if not pool:
        return None
    a = encode_seq(mirror.barcode.replace(tags_mod.BARCODE_SEP, ""))[None, :]
    b = np.stack([encode_seq(t.barcode.replace(tags_mod.BARCODE_SEP, "")) for t in pool])
    idx = best_matches(a, b, max_mismatch=max_mismatch, device=device)[0]
    return pool[idx] if idx >= 0 else None


def run_singleton_correction(
    singleton_bam: str,
    sscs_bam: str,
    out_prefix: str,
    max_mismatch: int = 0,
    backend: str = "tpu",
) -> SingletonResult:
    """``backend="cpu"`` keeps the Hamming matcher in numpy — a cpu run
    must never touch (or wait on) a device backend."""
    use_device = backend == "tpu"
    stats = StageStats("singleton_correction")
    all_paths = output_paths(out_prefix)
    paths = {k: all_paths[k] for k in ("sscs_rescue", "singleton_rescue", "remaining")}

    from consensuscruncher_tpu.io.columnar import SortingBamWriter

    s_reader = BamReader(singleton_bam)
    x_reader = BamReader(sscs_bam)
    writers = {k: SortingBamWriter(p, s_reader.header) for k, p in paths.items()}

    try:
        for singles, sscses in _merge_windows(
            consensus_windows(s_reader), consensus_windows(x_reader)
        ):
            done: set = set()
            for tag in sorted(singles, key=str):
                if tag in done:
                    continue
                stats.incr("singletons_total")
                read = singles[tag]
                mirror = tags_mod.duplex_tag(tag)

                partner_tag, pool = None, None
                if mirror in sscses:
                    partner_tag, pool = mirror, sscses
                elif mirror in singles and mirror != tag and mirror not in done:
                    partner_tag, pool = mirror, singles
                elif max_mismatch > 0:
                    partner_tag = _hamming_partner(tag, sscses, max_mismatch, use_device)
                    pool = sscses
                    if partner_tag is None:
                        # exclude self AND already-consumed singletons — a
                        # singleton may be corrected at most once
                        avail = {t: r for t, r in singles.items() if t != tag and t not in done}
                        partner_tag = _hamming_partner(tag, avail, max_mismatch, use_device)
                        pool = singles

                partner = pool.get(partner_tag) if partner_tag is not None else None
                if partner is None or len(partner.seq) != len(read.seq):
                    if partner is not None:
                        stats.incr("length_mismatch")
                    stats.incr("remaining")
                    writers["remaining"].write(read)
                    continue

                if pool is sscses:
                    stats.incr("rescued_by_sscs")
                    writers["sscs_rescue"].write(_corrected(read, partner))
                else:
                    # symmetric singleton-singleton rescue: correct both now
                    stats.incr("rescued_by_singleton", 2)
                    stats.incr("singletons_total")
                    writers["singleton_rescue"].write(_corrected(read, partner))
                    writers["singleton_rescue"].write(_corrected(partner, read))
                    done.add(partner_tag)
    except BaseException:
        for w in writers.values():
            w.abort()
        raise
    finally:
        s_reader.close()
        x_reader.close()

    for w in writers.values():
        w.close()  # lexsort + final BGZF write happen here
    stats.set("max_mismatch", max_mismatch)
    stats.write(all_paths["stats_txt"])
    return SingletonResult(paths["sscs_rescue"], paths["singleton_rescue"], paths["remaining"], stats)


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description="Rescue singletons against the complementary strand")
    p.add_argument("--singleton", required=True, help="sorted singleton BAM")
    p.add_argument("--bamfile", required=True, help="sorted SSCS BAM")
    p.add_argument("--outfile", required=True, help="output prefix")
    p.add_argument("--max-mismatch", type=int, default=0,
                   help="barcode Hamming tolerance (0 = exact complementary match)")
    p.add_argument("--backend", choices=("cpu", "tpu"), default="tpu")
    args = p.parse_args(argv)
    if args.max_mismatch > 0:
        from consensuscruncher_tpu.utils.backend_probe import ensure_backend

        ensure_backend(args.backend)
    run_singleton_correction(args.singleton, args.bamfile, args.outfile,
                             args.max_mismatch, backend=args.backend)


if __name__ == "__main__":
    main()
