"""Barcode extraction: move inline UMIs from read sequence into the qname.

Reference parity: ``ConsensusCruncher/extract_barcodes.py`` (SURVEY.md §2).
Supported modes, mirroring the reference surface:

- ``--bpattern`` e.g. ``NNT``: applied to the 5' end of BOTH mates; ``N``
  positions are UMI bases (extracted), any other letter is a spacer position
  (trimmed, not validated — the reference trims without checking).  The whole
  pattern length is removed from seq+qual.
- ``--blist``: whitelist file (one barcode per line).  With a pattern, each
  mate's extracted UMI must be in the list; without a pattern, the UMI length
  is taken from the list entries (which must share one length).
- Reads whose UMI fails the whitelist go to ``<p>_r1_bad.fastq.gz`` /
  ``<p>_r2_bad.fastq.gz`` with original sequence intact.

Output qname (pinned format): ``<original first token><bdelim><UMI1>.<UMI2>``
— both mates get the identical pair so downstream grouping sees one barcode.
Emits ``<p>_r1.fastq.gz`` / ``<p>_r2.fastq.gz``, a barcode-distribution file
``<p>.barcode_distribution.txt`` (barcode<TAB>count) and stats.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass

from consensuscruncher_tpu.core.tags import BARCODE_SEP, DEFAULT_BDELIM
from consensuscruncher_tpu.io.fastq import FastqWriter, read_fastq
from consensuscruncher_tpu.utils.stats import StageStats


@dataclass(frozen=True)
class BarcodePattern:
    """Compiled ``--bpattern``: which prefix positions are UMI vs spacer."""

    pattern: str

    def __post_init__(self):
        if not self.pattern or not self.pattern.isalpha():
            raise ValueError(f"invalid barcode pattern {self.pattern!r}")
        if "N" not in self.pattern.upper():
            raise ValueError(
                f"barcode pattern {self.pattern!r} has no N (UMI) positions — "
                "every read would get an empty UMI and families would collapse "
                "by position alone"
            )

    @property
    def length(self) -> int:
        return len(self.pattern)

    @property
    def umi_positions(self) -> tuple[int, ...]:
        return tuple(i for i, c in enumerate(self.pattern) if c.upper() == "N")

    def extract(self, seq: str) -> str:
        return "".join(seq[i] for i in self.umi_positions)


def load_blist(path) -> set[str]:
    barcodes = set()
    with open(path) as fh:
        for line in fh:
            bc = line.strip().upper()
            if bc:
                barcodes.add(bc)
    if not barcodes:
        raise ValueError(f"empty barcode list: {path}")
    lengths = {len(b) for b in barcodes}
    if len(lengths) != 1:
        raise ValueError(f"barcode list {path} mixes lengths {sorted(lengths)}")
    return barcodes


@dataclass
class ExtractResult:
    r1_out: str
    r2_out: str
    stats: StageStats


def output_paths(out_prefix: str) -> dict[str, str]:
    """Every file :func:`run_extract` writes for ``out_prefix`` — the single
    naming authority shared with the CLI's resume manifest (all are
    deterministic for a given input; stats carry no timestamps)."""
    return {
        "r1": f"{out_prefix}_r1.fastq.gz",
        "r2": f"{out_prefix}_r2.fastq.gz",
        "r1_bad": f"{out_prefix}_r1_bad.fastq.gz",
        "r2_bad": f"{out_prefix}_r2_bad.fastq.gz",
        "distribution": f"{out_prefix}.barcode_distribution.txt",
        "stats": f"{out_prefix}.extract_stats.txt",
        "stats_json": f"{out_prefix}.extract_stats.json",
    }


def _batch_zipper(read1: str, read2: str):
    """Yield aligned column slices from both FASTQs; raises on unequal
    record counts (the object path's ``zip(strict=True)`` contract)."""
    from consensuscruncher_tpu.io.fastq import read_fastq_batches

    def cols(b, lo, hi):
        return (b.data, b.name_start[lo:hi], b.name_len[lo:hi],
                b.seq_start[lo:hi], b.seq_len[lo:hi], b.qual_start[lo:hi])

    it1, it2 = read_fastq_batches(read1), read_fastq_batches(read2)
    b1 = b2 = None
    o1 = o2 = 0
    while True:
        if b1 is None or o1 >= b1.n:
            b1, o1 = next(it1, None), 0
        if b2 is None or o2 >= b2.n:
            b2, o2 = next(it2, None), 0
        if b1 is None or b2 is None:
            break
        k = min(b1.n - o1, b2.n - o2)
        yield cols(b1, o1, o1 + k), cols(b2, o2, o2 + k)
        o1 += k
        o2 += k
    leftover1 = (b1 is not None and o1 < b1.n) or next(it1, None) is not None
    leftover2 = (b2 is not None and o2 < b2.n) or next(it2, None) is not None
    if leftover1 or leftover2:
        raise ValueError("R1/R2 FASTQ record counts differ")


_UPPER = None


def _upper_lut():
    global _UPPER
    if _UPPER is None:
        import numpy as np

        lut = np.arange(256, dtype=np.uint8)
        lut[ord("a"):ord("z") + 1] -= 32
        _UPPER = lut
    return _UPPER


def tok_matrix(data, starts, lens):
    """(matrix, tok_len): name bytes up to the first whitespace (space or
    tab — the object paths' str.split() contract).  Shared by the extract
    stage and the columnar aligner (stages/align.py)."""
    import numpy as np

    from consensuscruncher_tpu.utils.ragged import scatter_runs

    w = int(lens.max()) if len(lens) else 0
    mat = np.zeros((len(starts), max(w, 1)), np.uint8)
    if w:
        scatter_runs(mat.reshape(-1),
                     np.arange(len(starts), dtype=np.int64) * mat.shape[1],
                     data, lens.astype(np.int64),
                     src_starts=starts.astype(np.int64))
    ws = (mat == 32) | (mat == 9)
    has = ws.any(axis=1)
    tok_len = np.where(has, np.argmax(ws, axis=1), lens)
    # zero out beyond the token so row equality == token equality
    mat[np.arange(mat.shape[1])[None, :] >= tok_len[:, None]] = 0
    return mat, tok_len


def _run_extract_vectorized(
    read1, read2, pattern, whitelist, bdelim, stats, distribution, writers
) -> None:
    """Columnar extract: one pass of array ops per aligned batch pair.
    Byte-parity with the object loop is pinned by tests/test_extract_vec.py."""
    import numpy as np

    from consensuscruncher_tpu.core.qnames import build_strings, const, fixed, ragged

    P = pattern.length
    upos = np.asarray(pattern.umi_positions, dtype=np.int64)
    U = len(upos)
    upper = _upper_lut()
    wl_arr = None
    if whitelist is not None:
        wl_arr = np.array(sorted(w.encode("ascii") for w in whitelist),
                          dtype=f"S{U}")
    sep_b = np.frombuffer(BARCODE_SEP.encode(), np.uint8)

    for c1, c2 in _batch_zipper(read1, read2):
        d1, ns1, nl1, ss1, sl1, qs1 = c1
        d2, ns2, nl2, ss2, sl2, qs2 = c2
        k = len(ns1)
        stats.incr("read_pairs", k)
        t1, tl1 = tok_matrix(d1, ns1, nl1)
        t2, tl2 = tok_matrix(d2, ns2, nl2)
        wmin = min(t1.shape[1], t2.shape[1])
        agree = (tl1 == tl2) & (t1[:, :wmin] == t2[:, :wmin]).all(axis=1)
        if t1.shape[1] > wmin:
            agree &= (t1[:, wmin:] == 0).all(axis=1)
        if t2.shape[1] > wmin:
            agree &= (t2[:, wmin:] == 0).all(axis=1)
        if not agree.all():
            i = int(np.argmin(agree))
            a = bytes(t1[i, : tl1[i]]).decode("ascii", "replace")
            b = bytes(t2[i, : tl2[i]]).decode("ascii", "replace")
            raise ValueError(f"R1/R2 qname mismatch: {a!r} vs {b!r}")

        too_short = (sl1 < P) | (sl2 < P)
        u1 = upper[d1[np.minimum(ss1[:, None] + upos[None, :], len(d1) - 1)]]
        u2 = upper[d2[np.minimum(ss2[:, None] + upos[None, :], len(d2) - 1)]]
        if wl_arr is not None:
            in1 = np.isin(np.ascontiguousarray(u1).view(f"S{U}").ravel(), wl_arr)
            in2 = np.isin(np.ascontiguousarray(u2).view(f"S{U}").ravel(), wl_arr)
            bad_bc = ~too_short & ~(in1 & in2)
        else:
            bad_bc = np.zeros(k, bool)
        good = ~too_short & ~bad_bc
        # guard zero increments: the object loop only creates counter keys
        # it touches, and stats files are parity artifacts
        for key, val in (("too_short", int(too_short.sum())),
                         ("bad_barcode", int(bad_bc.sum())),
                         ("extracted", int(good.sum()))):
            if val:
                stats.incr(key, val)

        bad = ~good
        if bad.any():
            for (d, ns, nl, ss, sl, qs, wkey) in (
                (d1, ns1, nl1, ss1, sl1, qs1, "r1_bad"),
                (d2, ns2, nl2, ss2, sl2, qs2, "r2_bad"),
            ):
                data, off = build_strings(int(bad.sum()), [
                    const(b"@"),
                    ragged(d, nl[bad], starts=ns[bad]),
                    const(b"\n"),
                    ragged(d, sl[bad], starts=ss[bad]),
                    const(b"\n+\n"),
                    ragged(d, sl[bad], starts=qs[bad]),
                    const(b"\n"),
                ])
                writers[wkey].write_bytes(data.tobytes())
        if good.any():
            g = np.nonzero(good)[0]
            bc = np.empty((len(g), 2 * U + len(sep_b)), np.uint8)
            bc[:, :U] = u1[g]
            bc[:, U:U + len(sep_b)] = sep_b
            bc[:, U + len(sep_b):] = u2[g]
            # distribution (vectorized unique over the barcode matrix)
            uq, counts = np.unique(
                np.ascontiguousarray(bc).view(f"S{bc.shape[1]}").ravel(),
                return_counts=True,
            )
            for ub, cnt in zip(uq, counts):
                distribution[ub.decode("ascii")] += int(cnt)
            for (d, ss, sl, qs, tok, tok_l, wkey) in (
                (d1, ss1, sl1, qs1, t1, tl1, "r1"),
                (d2, ss2, sl2, qs2, t2, tl2, "r2"),
            ):
                data, off = build_strings(len(g), [
                    const(b"@"),
                    ragged(tok.reshape(-1), tok_l[g],
                           starts=g.astype(np.int64) * tok.shape[1]),
                    const(bdelim.encode("ascii")),
                    fixed(bc),
                    const(b"\n"),
                    ragged(d, sl[g] - P, starts=ss[g] + P),
                    const(b"\n+\n"),
                    ragged(d, sl[g] - P, starts=qs[g] + P),
                    const(b"\n"),
                ])
                writers[wkey].write_bytes(data.tobytes())


def run_extract(
    read1: str,
    read2: str,
    out_prefix: str,
    bpattern: str | None = None,
    blist: str | None = None,
    bdelim: str = DEFAULT_BDELIM,
    level: int = 6,
    bad_level: int | None = None,
    _force_object: bool = False,
) -> ExtractResult:
    if bpattern is None and blist is None:
        raise ValueError("need --bpattern and/or --blist to locate UMIs")
    whitelist = load_blist(blist) if blist else None
    if bpattern is None:
        umi_len = len(next(iter(whitelist)))
        pattern = BarcodePattern("N" * umi_len)
    else:
        pattern = BarcodePattern(bpattern)
        if whitelist is not None:
            wl_len = len(next(iter(whitelist)))
            if wl_len != len(pattern.umi_positions):
                raise ValueError(
                    f"--bpattern extracts {len(pattern.umi_positions)}-base UMIs but "
                    f"--blist contains {wl_len}-base barcodes — every read would be rejected"
                )

    stats = StageStats("extract_barcodes")
    distribution: Counter = Counter()
    all_paths = output_paths(out_prefix)
    paths = {k: all_paths[k] for k in ("r1", "r2", "r1_bad", "r2_bad")}
    # The bad-read FASTQs are kept outputs even when the tag FASTQs are
    # downshifted as soon-deleted intermediates — separate level knob.
    bl = level if bad_level is None else bad_level
    writers = {k: FastqWriter(p, level=bl if k.endswith("_bad") else level)
               for k, p in paths.items()}
    if not _force_object:
        try:
            _run_extract_vectorized(
                read1, read2, pattern, whitelist, bdelim, stats, distribution, writers
            )
        finally:
            for w in writers.values():
                w.close()
        with open(f"{out_prefix}.barcode_distribution.txt", "w") as fh:
            fh.write("barcode\tcount\n")
            for bc, count in sorted(distribution.items()):
                fh.write(f"{bc}\t{count}\n")
        stats.set("unique_barcodes", len(distribution))
        stats.write(f"{out_prefix}.extract_stats.txt")
        return ExtractResult(paths["r1"], paths["r2"], stats)
    try:
        for (n1, s1, q1), (n2, s2, q2) in zip(
            read_fastq(read1), read_fastq(read2), strict=True
        ):
            stats.incr("read_pairs")
            tok1, tok2 = n1.split()[0], n2.split()[0]
            if tok1 != tok2:
                raise ValueError(f"R1/R2 qname mismatch: {tok1!r} vs {tok2!r}")
            if len(s1) < pattern.length or len(s2) < pattern.length:
                stats.incr("too_short")
                writers["r1_bad"].write(n1, s1, q1)
                writers["r2_bad"].write(n2, s2, q2)
                continue
            umi1 = pattern.extract(s1).upper()
            umi2 = pattern.extract(s2).upper()
            if whitelist is not None and (umi1 not in whitelist or umi2 not in whitelist):
                stats.incr("bad_barcode")
                writers["r1_bad"].write(n1, s1, q1)
                writers["r2_bad"].write(n2, s2, q2)
                continue
            barcode = f"{umi1}{BARCODE_SEP}{umi2}"
            distribution[barcode] += 1
            stats.incr("extracted")
            qname = f"{tok1}{bdelim}{barcode}"
            writers["r1"].write(qname, s1[pattern.length :], q1[pattern.length :])
            writers["r2"].write(qname, s2[pattern.length :], q2[pattern.length :])
    finally:
        for w in writers.values():
            w.close()

    with open(f"{out_prefix}.barcode_distribution.txt", "w") as fh:
        fh.write("barcode\tcount\n")
        for bc, count in sorted(distribution.items()):
            fh.write(f"{bc}\t{count}\n")
    stats.set("unique_barcodes", len(distribution))
    stats.write(f"{out_prefix}.extract_stats.txt")
    return ExtractResult(paths["r1"], paths["r2"], stats)


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description="Extract UMIs from FASTQ into read names")
    p.add_argument("--read1", required=True)
    p.add_argument("--read2", required=True)
    p.add_argument("--outfile", required=True, help="output prefix")
    p.add_argument("--bpattern", default=None, help="e.g. NNT (N=UMI base, else spacer)")
    p.add_argument("--blist", default=None, help="barcode whitelist file")
    p.add_argument("--bdelim", default=DEFAULT_BDELIM)
    args = p.parse_args(argv)
    run_extract(args.read1, args.read2, args.outfile, args.bpattern, args.blist, args.bdelim)


if __name__ == "__main__":
    main()
