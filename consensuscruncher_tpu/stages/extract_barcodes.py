"""Barcode extraction: move inline UMIs from read sequence into the qname.

Reference parity: ``ConsensusCruncher/extract_barcodes.py`` (SURVEY.md §2).
Supported modes, mirroring the reference surface:

- ``--bpattern`` e.g. ``NNT``: applied to the 5' end of BOTH mates; ``N``
  positions are UMI bases (extracted), any other letter is a spacer position
  (trimmed, not validated — the reference trims without checking).  The whole
  pattern length is removed from seq+qual.
- ``--blist``: whitelist file (one barcode per line).  With a pattern, each
  mate's extracted UMI must be in the list; without a pattern, the UMI length
  is taken from the list entries (which must share one length).
- Reads whose UMI fails the whitelist go to ``<p>_r1_bad.fastq.gz`` /
  ``<p>_r2_bad.fastq.gz`` with original sequence intact.

Output qname (pinned format): ``<original first token><bdelim><UMI1>.<UMI2>``
— both mates get the identical pair so downstream grouping sees one barcode.
Emits ``<p>_r1.fastq.gz`` / ``<p>_r2.fastq.gz``, a barcode-distribution file
``<p>.barcode_distribution.txt`` (barcode<TAB>count) and stats.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass

from consensuscruncher_tpu.core.tags import BARCODE_SEP, DEFAULT_BDELIM
from consensuscruncher_tpu.io.fastq import FastqWriter, read_fastq
from consensuscruncher_tpu.utils.stats import StageStats


@dataclass(frozen=True)
class BarcodePattern:
    """Compiled ``--bpattern``: which prefix positions are UMI vs spacer."""

    pattern: str

    def __post_init__(self):
        if not self.pattern or not self.pattern.isalpha():
            raise ValueError(f"invalid barcode pattern {self.pattern!r}")
        if "N" not in self.pattern.upper():
            raise ValueError(
                f"barcode pattern {self.pattern!r} has no N (UMI) positions — "
                "every read would get an empty UMI and families would collapse "
                "by position alone"
            )

    @property
    def length(self) -> int:
        return len(self.pattern)

    @property
    def umi_positions(self) -> tuple[int, ...]:
        return tuple(i for i, c in enumerate(self.pattern) if c.upper() == "N")

    def extract(self, seq: str) -> str:
        return "".join(seq[i] for i in self.umi_positions)


def load_blist(path) -> set[str]:
    barcodes = set()
    with open(path) as fh:
        for line in fh:
            bc = line.strip().upper()
            if bc:
                barcodes.add(bc)
    if not barcodes:
        raise ValueError(f"empty barcode list: {path}")
    lengths = {len(b) for b in barcodes}
    if len(lengths) != 1:
        raise ValueError(f"barcode list {path} mixes lengths {sorted(lengths)}")
    return barcodes


@dataclass
class ExtractResult:
    r1_out: str
    r2_out: str
    stats: StageStats


def run_extract(
    read1: str,
    read2: str,
    out_prefix: str,
    bpattern: str | None = None,
    blist: str | None = None,
    bdelim: str = DEFAULT_BDELIM,
) -> ExtractResult:
    if bpattern is None and blist is None:
        raise ValueError("need --bpattern and/or --blist to locate UMIs")
    whitelist = load_blist(blist) if blist else None
    if bpattern is None:
        umi_len = len(next(iter(whitelist)))
        pattern = BarcodePattern("N" * umi_len)
    else:
        pattern = BarcodePattern(bpattern)
        if whitelist is not None:
            wl_len = len(next(iter(whitelist)))
            if wl_len != len(pattern.umi_positions):
                raise ValueError(
                    f"--bpattern extracts {len(pattern.umi_positions)}-base UMIs but "
                    f"--blist contains {wl_len}-base barcodes — every read would be rejected"
                )

    stats = StageStats("extract_barcodes")
    distribution: Counter = Counter()
    paths = {
        "r1": f"{out_prefix}_r1.fastq.gz",
        "r2": f"{out_prefix}_r2.fastq.gz",
        "r1_bad": f"{out_prefix}_r1_bad.fastq.gz",
        "r2_bad": f"{out_prefix}_r2_bad.fastq.gz",
    }
    writers = {k: FastqWriter(p) for k, p in paths.items()}
    try:
        for (n1, s1, q1), (n2, s2, q2) in zip(
            read_fastq(read1), read_fastq(read2), strict=True
        ):
            stats.incr("read_pairs")
            tok1, tok2 = n1.split()[0], n2.split()[0]
            if tok1 != tok2:
                raise ValueError(f"R1/R2 qname mismatch: {tok1!r} vs {tok2!r}")
            if len(s1) < pattern.length or len(s2) < pattern.length:
                stats.incr("too_short")
                writers["r1_bad"].write(n1, s1, q1)
                writers["r2_bad"].write(n2, s2, q2)
                continue
            umi1 = pattern.extract(s1).upper()
            umi2 = pattern.extract(s2).upper()
            if whitelist is not None and (umi1 not in whitelist or umi2 not in whitelist):
                stats.incr("bad_barcode")
                writers["r1_bad"].write(n1, s1, q1)
                writers["r2_bad"].write(n2, s2, q2)
                continue
            barcode = f"{umi1}{BARCODE_SEP}{umi2}"
            distribution[barcode] += 1
            stats.incr("extracted")
            qname = f"{tok1}{bdelim}{barcode}"
            writers["r1"].write(qname, s1[pattern.length :], q1[pattern.length :])
            writers["r2"].write(qname, s2[pattern.length :], q2[pattern.length :])
    finally:
        for w in writers.values():
            w.close()

    with open(f"{out_prefix}.barcode_distribution.txt", "w") as fh:
        fh.write("barcode\tcount\n")
        for bc, count in sorted(distribution.items()):
            fh.write(f"{bc}\t{count}\n")
    stats.set("unique_barcodes", len(distribution))
    stats.write(f"{out_prefix}.extract_stats.txt")
    return ExtractResult(paths["r1"], paths["r2"], stats)


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description="Extract UMIs from FASTQ into read names")
    p.add_argument("--read1", required=True)
    p.add_argument("--read2", required=True)
    p.add_argument("--outfile", required=True, help="output prefix")
    p.add_argument("--bpattern", default=None, help="e.g. NNT (N=UMI base, else spacer)")
    p.add_argument("--blist", default=None, help="barcode whitelist file")
    p.add_argument("--bdelim", default=DEFAULT_BDELIM)
    args = p.parse_args(argv)
    run_extract(args.read1, args.read2, args.outfile, args.bpattern, args.blist, args.bdelim)


if __name__ == "__main__":
    main()
