"""SSCS stage: collapse UMI families into single-strand consensus sequences.

Reference parity: ``ConsensusCruncher/SSCS_maker.py`` (SURVEY.md §2/§3.2).
Outputs (pinned names; ``<p>`` = output prefix):

- ``<p>.sscs.sorted.bam``       consensus reads (families of size ≥ 2)
- ``<p>.singleton.sorted.bam``  size-1 families (renamed to consensus qname,
  barcode preserved in ``XT``, for downstream correction/pairing)
- ``<p>.badReads.bam``          unmapped/secondary/supplementary/qcfail/
  mate-unmapped/barcode-less reads
- ``<p>.sscs_stats.txt|.json``  stage stats
- ``<p>.read_families.txt``     family-size histogram
- ``<p>.time_tracker.txt``      wall-clock marks

Backends (bit-identical by the parity test suite):
- ``tpu``: families stream through ``ops.consensus_tpu.consensus_families``
  (bucketed, batched, jitted device kernel).
- ``cpu``: vectorized numpy oracle per family.

Both produce consensus reads in bucket/stream order; the sorting writers
buffer them in memory and lexsort + write the final coordinate-sorted BAMs
atomically at close — the reference reaches the same state via
``samtools sort`` subprocesses over temp files (SURVEY.md §3.2).
"""

from __future__ import annotations

import struct
import sys
from dataclasses import dataclass

import numpy as np

from consensuscruncher_tpu.core import tags as tags_mod
from consensuscruncher_tpu.obs import metrics as obs_metrics
from consensuscruncher_tpu.obs import trace as obs_trace
from consensuscruncher_tpu.utils import faults, sanitize
from consensuscruncher_tpu.core.consensus_cpu import consensus_maker_numpy
from consensuscruncher_tpu.core.consensus_read import (
    _KEEP_FLAGS,
    build_consensus_read,
    modal_cigar,
)
from consensuscruncher_tpu.io import bgzf
from consensuscruncher_tpu.io.bam import BamReader, BamWriter
from consensuscruncher_tpu.io.encode import (
    ConsensusRecordWriter,
    RenameRetagWriter,
    cigar_string_to_words,
)
from consensuscruncher_tpu.policies import base as policies_mod
from consensuscruncher_tpu.stages.grouping import MemberView
from consensuscruncher_tpu.ops.consensus_tpu import ConsensusConfig, consensus_families
from consensuscruncher_tpu.parallel.batching import rectangularize
from consensuscruncher_tpu.stages.grouping import stream_families
from consensuscruncher_tpu.utils.backend_probe import record_backend
from consensuscruncher_tpu.utils.profiling import Counters, write_metrics
from consensuscruncher_tpu.utils.stats import FamilySizeHistogram, StageStats, TimeTracker


@dataclass
class SscsResult:
    sscs_bam: str
    singleton_bam: str
    bad_bam: str
    stats: StageStats | None  # None when reconstructed from a resume skip
    histogram: FamilySizeHistogram | None

    @classmethod
    def from_prefix(cls, out_prefix: str) -> "SscsResult":
        """Path-only result for a stage skipped by --resume."""
        p = output_paths(out_prefix)
        return cls(p["sscs"], p["singleton"], p["bad"], None, None)


def output_paths(out_prefix: str) -> dict[str, str]:
    """Canonical output paths for a prefix — the single naming authority
    shared by the stage body and the CLI's resume manifest."""
    return {
        "sscs": f"{out_prefix}.sscs.sorted.bam",
        "singleton": f"{out_prefix}.singleton.sorted.bam",
        "bad": f"{out_prefix}.badReads.bam",
        "stats_txt": f"{out_prefix}.sscs_stats.txt",
        "stats_json": f"{out_prefix}.sscs_stats.json",
        "families": f"{out_prefix}.read_families.txt",
        "time_tracker": f"{out_prefix}.time_tracker.txt",
    }


@dataclass
class PrestagedBlocks:
    """An input's decode already running on a producer thread.

    Built by :func:`prestage_blocks` for the multi-sample batch: while
    sample N's pipeline drains the device, sample N+1's columnar decode +
    family grouping fills a bounded queue, so its SSCS stage starts with
    blocks already in hand.  ``close()`` is idempotent and safe on an
    unconsumed prestage (a resume-skipped stage must not leak the producer
    thread or the open reader).
    """

    header: object
    reader: object
    events: object  # parallel.prefetch.start_prefetch iterator

    def close(self) -> None:
        try:
            self.events.close()
        finally:
            self.reader.close()


def prestage_blocks(in_bam: str, bdelim: str = tags_mod.DEFAULT_BDELIM,
                    depth: int = 4) -> PrestagedBlocks:
    """Start decoding ``in_bam`` into FamilyBlock events NOW, on a
    background thread behind a ``depth``-bounded queue (memory bound:
    blocks are the unit).  Consume via ``run_sscs(..., prestaged=...)``
    with the same ``bdelim``."""
    from consensuscruncher_tpu.io.columnar import ColumnarReader
    from consensuscruncher_tpu.parallel.prefetch import start_prefetch
    from consensuscruncher_tpu.stages.grouping import stream_family_blocks

    reader = ColumnarReader(in_bam)
    events = start_prefetch(
        stream_family_blocks(reader, reader.header, bdelim), depth=depth)
    return PrestagedBlocks(reader.header, reader, events)


def write_singleton(singleton_writer, tag, members) -> None:
    """Route a size-1 family: rename to the consensus qname, preserve the
    barcode in ``XT``/``XF`` tags.  Shared by the one-shot stage and the
    serve/ gang path so singleton bytes stay identical by construction."""
    out = members[0].materialize()  # BamRead: identity
    out.qname = tags_mod.sscs_qname(tag)
    out.tags = dict(out.tags)
    out.tags["XT"] = ("Z", tag.barcode)
    out.tags["XF"] = ("i", 1)
    singleton_writer.write(out)


def emit_consensus(rec_writer, sscs_writer, tag, members, codes, quals) -> None:
    """Encode one consensus read (columnar fast path or BamRead fallback).
    Shared by the one-shot stage and the serve/ gang path — consensus
    record bytes are produced by exactly one code path."""
    t = members[0]
    if isinstance(t, MemberView):
        # Columnar fast path: identical record bytes to
        # build_consensus_read + encode_record, built column-wise.
        L = codes.shape[0]
        cand = [m for m in members if m.seq_len == L]
        first = cand[0].cigar_bytes() if cand else None
        if first is not None and all(
            np.array_equal(m.cigar_bytes(), first) for m in cand[1:]
        ):
            # np.array copy: a zero-copy view would pin the whole source
            # batch buffer inside the record writer until its next flush
            words = np.array(np.ascontiguousarray(first).view("<u4"))
        else:  # mixed cigars / all-truncated: exact modal_cigar semantics
            words = cigar_string_to_words(modal_cigar(members, L))
        tag_blob = (
            b"XTZ" + tag.barcode.encode("ascii")
            + b"\x00XFi" + struct.pack("<i", len(members))
        )
        rec_writer.add(
            tags_mod.sscs_qname(tag), t.flag & _KEEP_FLAGS, t.rid, t.pos,
            max(m.mapq for m in members), words, t.mrid, t.mate_pos,
            t.tlen, codes, quals, tag_blob,
        )
    else:
        read = build_consensus_read(
            tag, members, codes, quals, qname=tags_mod.sscs_qname(tag),
            extra_tags={"XT": ("Z", tag.barcode)},
        )
        sscs_writer.write(read)


def _member_arrays(members):
    seqs, quals = [], []
    for m in members:
        s = m.codes  # uniform across BamRead and columnar MemberView
        q = m.qual if m.qual.size else np.zeros(s.shape[0], dtype=np.uint8)
        seqs.append(s)
        quals.append(q)
    return seqs, quals


def run_sscs(
    in_bam: str,
    out_prefix: str,
    cutoff: float = 0.7,
    qual_threshold: int = 0,
    qual_cap: int = 60,
    backend: str = "tpu",
    bdelim: str = tags_mod.DEFAULT_BDELIM,
    max_batch: int = 1024,
    devices: int | None = None,
    wire: str = "stream",
    level: int = 6,
    input_range=None,
    prestaged: "PrestagedBlocks | None" = None,
    residency=None,
    stream_out=None,
    qc=None,
    policy: str = "majority",
) -> SscsResult:
    """``devices``: shard each family batch across this many chips
    (``parallel.mesh`` family-data-parallel path); None/1 = single device.
    Only meaningful with ``backend="tpu"``.

    ``wire``: device wire layout for the tpu backend — ``"stream"`` (packed
    member stream, the production default: ~8-16x fewer h2d bytes, which
    dominates stage wall-clock on tunneled devices) or ``"dense"`` (padded
    ``(B, F, L)`` batches).  Both are bit-identical by the parity suite,
    and both shard over the ``devices`` mesh (the stream wire keeps its
    byte advantage there: whole families per device, no collectives).

    ``prestaged``: an eagerly-started decode of THIS input from
    :func:`prestage_blocks` — the multi-sample batch overlap (sample N+1's
    columnar decode runs behind sample N's device compute).  Requires the
    block path (tpu backend + stream wire); byte-identical outputs.

    ``residency``: an ``ops.packing.resident_planes()`` store; the block
    path registers each device batch's still-on-device consensus plane in
    it (keyed by SSCS qname) so the downstream rescue/DCS stages can vote
    by device gather instead of re-uploading these bytes.  Ignored on
    non-block paths (cpu/reference/dense/mesh — those fall back to staged
    duplex votes downstream, byte-identical).

    ``stream_out``: a ``core.streamgraph.StreamOut``; when given, the
    sorted SSCS/singleton outputs are handed off in memory
    (``close_to_memory``) instead of committed to disk here — the SSCS
    BAM still materializes (final output, via the write-behind pool) but
    the singleton BAM becomes a debug tap, written only when the stream
    asked for taps.  ``in_bam`` may then also be an in-memory batch
    source instead of a path.

    ``qc``: an ``obs.qc.QcAccumulator``; when given, the tpu vote kernels
    accumulate per-position vote/disagreement planes into it as a rider on
    the operands they already upload (zero extra h2d passes, bit-identical
    consensus outputs).  The sink is armed only around this stage's device
    loop so concurrent gang jobs never mix batches into a foreign
    accumulator.  Ignored on cpu/reference backends and mesh runs (the
    per-run yields/spectrum still come from the stats sidecars).

    ``policy``: registered consensus vote policy (``policies/``);
    installed for this stage's device loop and restored on exit.  The
    ``majority`` default is the golden-pinned reference vote; other
    policies require the tpu backend (the cpu/reference twins implement
    only the reference rule) and run single-device."""
    if backend not in ("cpu", "tpu", "reference"):
        raise ValueError(
            f"unknown backend {backend!r} (expected 'cpu', 'tpu', or 'reference')"
        )
    vote_policy = policies_mod.get_policy(policy)
    if vote_policy.name != "majority":
        if backend != "tpu":
            raise ValueError(
                f"vote policy {vote_policy.name!r} requires the tpu backend")
        if devices is not None and devices > 1:
            raise ValueError(
                f"vote policy {vote_policy.name!r} is single-device only")
    if wire not in ("stream", "dense"):
        raise ValueError(f"unknown wire {wire!r} (expected 'stream' or 'dense')")
    mesh = None
    if devices is not None and devices > 1:
        if backend != "tpu":
            raise ValueError("--devices > 1 requires the tpu backend")
        from consensuscruncher_tpu.parallel.mesh import make_mesh

        try:
            faults.fault_point("mesh.unavailable")
            mesh = make_mesh(devices)
        except Exception as e:
            # Degraded mode: a missing/short mesh (preempted chips, tunnel
            # flap) costs throughput, never the run — outputs are
            # bit-identical at any mesh size (parity suite).
            print(f"WARNING: {devices}-device mesh unavailable ({e}); "
                  "degrading to single-device", file=sys.stderr, flush=True)
            mesh = None
    tracker = TimeTracker()
    stats = StageStats("SSCS")
    hist = FamilySizeHistogram()
    cum = Counters()
    recompiles_before = obs_metrics.recompiles()
    transfers_before = obs_metrics.transfer_bytes()
    io_before = bgzf.write_stats()
    cfg = ConsensusConfig(cutoff=cutoff, qual_threshold=qual_threshold, qual_cap=qual_cap)

    paths = output_paths(out_prefix)
    sscs_path, singleton_path, bad_path = paths["sscs"], paths["singleton"], paths["bad"]

    use_blocks_early = backend == "tpu" and wire == "stream"
    if prestaged is not None and (input_range is not None or not use_blocks_early):
        # A prestage that cannot be consumed must not silently leak its
        # producer thread + open reader — close it and decode normally.
        prestaged.close()
        prestaged = None
    if backend == "reference":
        # True reference-style run: per-read object decode + dict grouping
        # (the honest bench.py baseline denominator).
        if input_range is not None:
            raise ValueError("input_range requires a columnar backend")
        reader = BamReader(in_bam)
        header = reader.header
        source = stream_families(reader, header, bdelim)
    elif prestaged is not None:
        reader = prestaged.reader
        header = prestaged.header
        source = None
    else:
        # Production path: columnar batch decode + vectorized grouping
        # (same events, same order — stage outputs are byte-identical).
        # ``input_range``: a BAI coordinate range of the shared input
        # (--host_workers reads ranges directly, no slice files).
        from consensuscruncher_tpu.io.columnar import (ColumnarReader,
                                                       open_batch_source)

        if input_range is not None:
            reader = ColumnarReader(in_bam, bam_range=input_range)
        else:
            reader = open_batch_source(in_bam)
        header = reader.header
        source = None  # built below once the pipeline flavor is known
    use_blocks = backend == "tpu" and wire == "stream"
    if backend != "reference" and not use_blocks:
        from consensuscruncher_tpu.stages.grouping import stream_families_columnar

        source = stream_families_columnar(reader, header, bdelim)
    from consensuscruncher_tpu.io.columnar import SortingBamWriter

    bad_writer = BamWriter(bad_path, header, atomic=True)
    # In-memory sorting writers: records buffer as raw blobs and sort+write
    # once at close — no unsorted tmp file, no L1 deflate/inflate round trip
    sscs_writer = SortingBamWriter(sscs_path, header, level=level)
    singleton_writer = SortingBamWriter(singleton_path, header, level=level)

    pending: dict[int, tuple] = {}

    _chaos = faults.hook("sscs.midstage")  # None unless a chaos test arms it

    def events():
        """Route grouping events; yield consensus jobs for families >= 2."""
        next_id = 0
        for kind, a, b in source:
            if _chaos is not None:
                _chaos()
            if kind == "bad":
                stats.incr("total_reads")
                stats.incr(f"bad_{b}")
                stats.incr("bad_reads")
                bad_writer.write(a)
                continue
            tag, members = a, b
            stats.incr("total_reads", len(members))
            hist.add(len(members))
            stats.incr("families")
            if len(members) == 1:
                stats.incr("singletons")
                write_singleton(singleton_writer, tag, members)
                continue
            seqs, quals = _member_arrays(members)
            pending[next_id] = (tag, members)
            yield next_id, seqs, quals
            next_id += 1

    single_surgery = RenameRetagWriter(singleton_writer)
    _XF1 = struct.pack("<i", 1)

    def block_items():
        """Fully-vectorized producer: route FamilyBlock events and hand the
        device pipeline array-level items keyed by ``(block, j)``."""
        from consensuscruncher_tpu.stages.grouping import stream_family_blocks

        block_events = (prestaged.events if prestaged is not None
                        else stream_family_blocks(reader, header, bdelim))
        for kind, a, b in block_events:
            if _chaos is not None:
                _chaos()
            if kind == "bad":
                stats.incr("total_reads")
                stats.incr(f"bad_{b}")
                stats.incr("bad_reads")
                bad_writer.write(a)
                continue
            block = a
            sizes = block.sizes
            stats.incr("total_reads", int(sizes.sum()))
            stats.incr("families", block.n_fam)
            hist.add_array(sizes)
            multi = np.nonzero(sizes >= 2)[0]
            stats.incr("singletons", block.n_fam - len(multi))
            for j in np.nonzero(sizes == 1)[0]:
                j = int(j)
                batch, idx = block.tmpl_src(j)
                if batch.tags_start[idx] == batch.rec_off[idx + 1]:
                    # tag-less record: rename+retag as batched blob surgery
                    single_surgery.add(
                        batch, idx,
                        bytes(block.qname_data[block.qname_off[j]:block.qname_off[j + 1]]),
                        b"XTZ" + bytes(block.bcm[j, : block.bclen[j]]) + b"\x00XFi" + _XF1,
                    )
                    continue
                # existing tags: the object path's dict-replace semantics
                # (surgery only appends); flush first to preserve file order
                single_surgery.flush()
                out = batch.materialize(idx)
                out.qname = block.qname(j)
                out.tags = dict(out.tags)
                out.tags["XT"] = ("Z", block.barcode(j))
                out.tags["XF"] = ("i", 1)
                singleton_writer.write(out)
            if len(multi) == 0:
                continue
            keys = [(block, int(j)) for j in multi]
            yield block, multi, keys

    rec_writer = ConsensusRecordWriter(sscs_writer)

    def emit_batch(keys, lengths, out_b, out_q):
        """Array-level consensus emission: one encode pass per same-block
        run of a device batch (runs are contiguous — buckets fill in block
        order)."""
        from consensuscruncher_tpu.core.qnames import build_strings, const, fixed, ragged
        from consensuscruncher_tpu.utils.ragged import gather_runs

        n = len(keys)
        Lpad = out_b.shape[1]
        flat_b, flat_q = out_b.reshape(-1), out_q.reshape(-1)
        i = 0
        while i < n:
            block = keys[i][0]
            k = i + 1
            while k < n and keys[k][0] is block:
                k += 1
            js = np.fromiter((keys[t][1] for t in range(i, k)), np.int64, k - i)
            rows = np.arange(i, k, dtype=np.int64)
            lens = lengths[i:k]
            codes_data, _ = gather_runs(flat_b, rows * Lpad, lens)
            qual_data, _ = gather_runs(flat_q, rows * Lpad, lens)
            qn_lens = block.qname_off[js + 1] - block.qname_off[js]
            qn_data, _ = gather_runs(block.qname_data, block.qname_off[js], qn_lens)
            cig_lens = block.cigar_off[js + 1] - block.cigar_off[js]
            cig_data, _ = gather_runs(block.cigar_data, block.cigar_off[js], cig_lens)
            fam_sizes = block.sizes[js].astype("<i4")
            tag_data, tag_off = build_strings(k - i, [
                const(b"XTZ"),
                ragged(block.bcm.reshape(-1), block.bclen[js],
                       starts=js * block.bcm.shape[1]),
                const(b"\x00XFi"),
                fixed(fam_sizes.view(np.uint8).reshape(k - i, 4)),
            ])
            rec_writer.add_columns(
                qn_data, qn_lens,
                block.tmpl_flag[js] & _KEEP_FLAGS,
                block.tmpl_rid[js], block.tmpl_pos[js], block.mapq_max[js],
                cig_data, cig_lens,
                block.tmpl_mrid[js], block.tmpl_mpos[js], block.tmpl_tlen[js],
                codes_data, lens, qual_data,
                tag_data, np.diff(tag_off),
            )
            stats.incr("sscs_written", k - i)
            i = k

    def emit(fid, codes, quals):
        tag, members = pending.pop(fid)
        emit_consensus(rec_writer, sscs_writer, tag, members, codes, quals)
        stats.incr("sscs_written")

    from consensuscruncher_tpu.obs import qc as obs_qc

    ok = False
    qc_armed = qc is not None and backend == "tpu"
    if qc_armed:
        obs_qc.set_plane_sink(qc)
    # Install the vote policy for this stage's device loop only (same
    # arm/disarm discipline as the QC sink: concurrent gang jobs must
    # never inherit a foreign policy).
    prev_policy = policies_mod.installed_vote_policy()
    policies_mod.set_vote_policy(vote_policy)
    try:
        if backend == "tpu":
            if use_blocks:
                from consensuscruncher_tpu.ops.consensus_segment import (
                    consensus_blocks_stream_batched,
                )

                on_device_batch = None
                if residency is not None and mesh is None:
                    def on_device_batch(batch, handle):
                        # FIFO contract: handle rows 0..n_real-1 are the
                        # batch's keys in order; the store key per (block, j)
                        # is the grouping layer's consensus qname PLUS the
                        # record flag — each family qname appears twice in
                        # the SSCS BAM (R1 and R2 records), so the qname
                        # alone would collide and serve the wrong strand's
                        # plane.  Rescue/DCS build the same key from the BAM
                        # record's qname and flag (stages.dcs_maker.
                        # _qname_bytes).
                        n = batch.n_real
                        qnames = [
                            bytes(k[0].qname_data[
                                k[0].qname_off[k[1]]:k[0].qname_off[k[1] + 1]])
                            + b"\x00" + int(
                                k[0].tmpl_flag[k[1]] & _KEEP_FLAGS
                            ).to_bytes(2, "little")
                            for k in batch.keys
                        ]
                        residency.append(qnames, batch.lengths[:n], handle, n)

                # 4x the dense batch size: the packed wire makes bytes cheap,
                # and on a tunneled device per-dispatch roundtrip latency is
                # the cost that's left — fewer, larger batches win.
                stream = consensus_blocks_stream_batched(
                    block_items(), cfg, max_batch=4 * max_batch, mesh=mesh,
                    on_device_batch=on_device_batch,
                )
                try:
                    with sanitize.guarded_stage("sscs"), \
                            obs_trace.span("sscs.device_loop", wire="stream"):
                        for keys, lengths, out_b, out_q in stream:
                            sanitize.sync_probe("sscs.sync_probe")
                            cum.add("batches_dispatched")
                            cum.add("families_in", len(keys))
                            obs_trace.event("device.batch", n_real=len(keys))
                            emit_batch(keys, lengths, out_b, out_q)
                finally:
                    # Must run BEFORE the writers close below: closing the
                    # stream stops and joins the prefetch producer thread,
                    # which executes block_items() — i.e. the thread writing
                    # to bad_writer/singleton_writer.  Abandoning it to GC
                    # would race w.abort() against in-flight writes.
                    stream.close()
            else:
                def on_batch(batch):
                    sanitize.sync_probe("sscs.sync_probe")
                    cum.add("batches_dispatched")
                    cum.add("families_in", batch.n_real)
                    obs_trace.event("device.batch", n_real=batch.n_real)

                stream = consensus_families(
                    events(), cfg, max_batch=max_batch, mesh=mesh, on_batch=on_batch
                )
                try:
                    with sanitize.guarded_stage("sscs"), \
                            obs_trace.span("sscs.device_loop", wire="dense"):
                        for fid, codes, quals in stream:
                            emit(fid, codes, quals)
                finally:
                    stream.close()
        else:
            # "reference" = the per-position Counter loop
            # (``core.consensus_cpu.consensus_maker``, the pinned oracle of
            # ``consensus_helper.consensus_maker``) so ``bench.py`` can time
            # a true reference-style stage run as its vs_baseline
            # denominator; "cpu" = the vectorized numpy twin.  Identical
            # semantics by the parity suite.
            if backend == "reference":
                from consensuscruncher_tpu.core.consensus_cpu import consensus_maker

                vote = consensus_maker
            else:
                vote = consensus_maker_numpy
            for fid, seqs, quals in events():
                cum.add("families_in")
                rect_s, rect_q, _ = rectangularize(seqs, quals)
                codes, cquals = vote(
                    rect_s, rect_q, cutoff=cutoff, qual_threshold=qual_threshold, qual_cap=qual_cap
                )
                emit(fid, codes, cquals)
        rec_writer.flush()
        single_surgery.flush()
        ok = True
    finally:
        policies_mod.set_vote_policy(prev_policy)
        if qc_armed:
            obs_qc.set_plane_sink(None)
        if prestaged is not None:
            # join the prestage producer BEFORE closing the reader it decodes
            prestaged.close()
        reader.close()
        if not ok:
            # never promote a partial output on error
            for w in (bad_writer, sscs_writer, singleton_writer):
                w.abort()
    tracker.mark("consensus")
    # sorting writers do their lexsort + final BGZF write inside close()
    with obs_trace.span("writer.commit", stage="sscs"):
        bad_writer.close()
        if stream_out is not None:
            # Streaming hand-off: finish the sort in memory.  The SSCS BAM
            # is a final output (write-behind materialization); the
            # singleton BAM only exists to feed rescue, so it becomes a
            # debug tap.
            stream_out.capture("sscs", sscs_writer.close_to_memory(),
                               file_path=sscs_path, level=level)
            stream_out.capture(
                "singleton", singleton_writer.close_to_memory(),
                file_path=singleton_path if stream_out.taps else None,
                level=level)
        else:
            sscs_writer.close()
            singleton_writer.close()
    tracker.mark("sort")

    record_backend(stats, backend)
    jax_backend = stats.get("jax_backend")
    stats.set("cutoff", cutoff)
    if vote_policy.name != "majority":
        # non-default only: default-run stats sidecars stay byte-stable
        # against the committed goldens
        stats.set("policy", vote_policy.name)
    stats.write(paths["stats_txt"])
    hist.write(paths["families"])
    tracker.write(paths["time_tracker"])
    cum.add("families_out", stats.get("sscs_written"))
    cum.add("recompiles", obs_metrics.recompiles() - recompiles_before)
    transfers = obs_metrics.transfer_bytes()
    cum.add("bytes_h2d", transfers["h2d"] - transfers_before["h2d"])
    cum.add("bytes_d2h", transfers["d2h"] - transfers_before["d2h"])
    iostat = bgzf.write_stats()
    cum.add("deflate_wall_us",
            iostat["deflate_wall_us"] - io_before["deflate_wall_us"])
    cum.add("bytes_bam_written",
            iostat["bytes_written"] - io_before["bytes_written"])
    write_metrics(
        f"{out_prefix}.metrics.json", "SSCS", tracker.as_phases(),
        {"backend": backend, "jax_backend": jax_backend,
         "n_families": stats.get("families"),
         "n_reads": stats.get("total_reads")},
        cumulative=cum.snapshot(),
    )
    return SscsResult(sscs_path, singleton_path, bad_path, stats, hist)


def main(argv=None):
    """Standalone entry (reference: each stage script runs independently)."""
    import argparse

    p = argparse.ArgumentParser(description="Make single-strand consensus sequences")
    p.add_argument("--infile", required=True, help="coordinate-sorted input BAM")
    p.add_argument("--outfile", required=True, help="output prefix (files get suffixes)")
    p.add_argument("--cutoff", type=float, default=0.7, help="consensus base fraction cutoff")
    p.add_argument("--qualscore", type=int, default=0, help="Phred threshold; lower-quality bases vote N")
    p.add_argument("--backend", choices=("cpu", "tpu"), default="tpu")
    p.add_argument("--bdelim", default=tags_mod.DEFAULT_BDELIM, help="barcode delimiter in qnames")
    args = p.parse_args(argv)
    from consensuscruncher_tpu.utils.backend_probe import ensure_backend

    ensure_backend(args.backend)
    run_sscs(
        args.infile,
        args.outfile,
        cutoff=args.cutoff,
        qual_threshold=args.qualscore,
        backend=args.backend,
        bdelim=args.bdelim,
    )


if __name__ == "__main__":
    main()
