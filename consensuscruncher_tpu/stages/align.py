"""Built-in paired-end aligner: ``fastq2bam --bwa builtin``.

The reference delegates alignment to an external ``bwa mem`` subprocess
(``ConsensusCruncher.py`` fastq2bam, SURVEY.md §3.1) and so does this
framework by default.  This module exists for the environments the
reference cannot handle at all — no aligner installed — so the FULL
fastq2bam flow still runs: k-mer seeding against an in-memory reference
index + ungapped extension with mismatch counting, emitting the same
coordinate-sorted barcoded BAM the external path produces.

Scope is deliberate: exact-stride seeds and ungapped extension handle
substitution-style sequencing error (the consensus pipeline's whole
subject) but NOT indels/clipping/splicing — it is a test/demo aligner
with honest limits, not a bwa replacement.  CIGAR is always full-length
``M``; unalignable reads come out unmapped (flag 0x4) and flow to the
pipeline's badReads path.

The seeding/voting layout is array-friendly on purpose: reads are held as
uint8 code matrices and seed votes are numpy bincounts, so a batched
device port (classic systolic-array scoring) can slot in behind the same
interface.
"""

from __future__ import annotations

import os
import sys
from dataclasses import dataclass

import numpy as np

from consensuscruncher_tpu.io.fasta import read_fasta
from consensuscruncher_tpu.utils.faults import FaultError, fault_point

_CODE = np.full(256, 255, np.uint8)
for _i, _c in enumerate(b"ACGT"):
    _CODE[_c] = _i
    _CODE[ord(chr(_c).lower())] = _i
_COMP = {"A": "T", "C": "G", "G": "C", "T": "A", "N": "N"}
# code-space complement: A0<->T3, C1<->G2; N(4) and invalid bytes unchanged
_REVCOMP_LUT = np.arange(256, dtype=np.uint8)
_REVCOMP_LUT[:4] = [3, 2, 1, 0]


def revcomp(seq: str) -> str:
    return "".join(_COMP.get(c, "N") for c in reversed(seq))


def _encode(seq: str) -> np.ndarray:
    return _CODE[np.frombuffer(seq.encode(), np.uint8)]


@dataclass(frozen=True)
class Hit:
    ref: str
    pos: int  # 0-based leftmost
    reverse: bool
    nm: int  # mismatches
    mapq: int


class _SortedKmerIndex:
    """Vectorized reference k-mer index: one sorted int64 key array + the
    matching global positions, built with array passes only (the former
    ``dict[kmer] -> list`` form cost one Python dict insert per reference
    base, which at chromosome scale is minutes and gigabytes).

    Refs concatenate into ``gcodes`` with a single 0xFF separator byte
    between them — any k-window crossing a boundary contains the separator
    and is dropped by the validity mask, so no k-mer spans two refs.
    Equal keys keep position-ascending order (stable argsort), preserving
    the scan order the old dict-of-lists produced.
    """

    def __init__(self, ref_codes: list[np.ndarray], k: int):
        self.k = k
        lens = np.array([len(c) for c in ref_codes], np.int64)
        self.lens = lens
        bases, parts, off = [], [], 0
        for i, c in enumerate(ref_codes):
            bases.append(off)
            parts.append(c)
            off += len(c)
            if i < len(ref_codes) - 1:
                parts.append(np.full(1, 0xFF, np.uint8))
                off += 1
        self.gbase = np.asarray(bases, np.int64)
        self.gcodes = (np.concatenate(parts) if parts
                       else np.zeros(0, np.uint8))
        g = len(self.gcodes)
        if g >= k:
            valid = self.gcodes < 4
            nk = g - k + 1
            keys = np.zeros(nk, np.int64)
            ok = np.ones(nk, bool)
            for j in range(k):
                keys = (keys << 2) | self.gcodes[j:j + nk].astype(np.int64)
                ok &= valid[j:j + nk]
            pos = np.nonzero(ok)[0]
            keys = keys[pos] & ((np.int64(1) << (2 * k)) - 1)
            order = np.argsort(keys, kind="stable")
            self.skmers = keys[order]
            self.spos = pos[order]
        else:
            self.skmers = np.zeros(0, np.int64)
            self.spos = np.zeros(0, np.int64)
        # Prefix radix table: the first PREF_BITS levels of every binary
        # search collapse to one table lookup, and the remaining search
        # runs inside a ~|index|/2^pref_bits-entry window (cache-resident).
        # Plain np.searchsorted over a chromosome-scale index is a random
        # 25-probe cold-cache walk per seed — measured 70% of the whole
        # align leg at 30M reference bases.
        self.pref_bits = min(2 * k, max(10, int(np.log2(max(len(self.skmers), 2))) - 6))
        self._pref_shift = 2 * k - self.pref_bits
        pref = self.skmers >> self._pref_shift
        self.pref_table = np.searchsorted(
            pref, np.arange((np.int64(1) << self.pref_bits) + 1, dtype=np.int64))

    def lookup_batch(self, keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Vectorized equal-range over the sorted index: ``(lo, hi)`` per
        key, via the prefix table + a windowed binary search — native C++
        per-key (registers over a cache-resident window) when the codec
        library is available, else the numpy branchless lockstep loop."""
        pref = keys >> self._pref_shift
        lo_l = self.pref_table[pref]
        hi_l = self.pref_table[pref + 1]
        try:
            from consensuscruncher_tpu.io import native

            return native.equal_range_windowed(self.skmers, keys, lo_l, hi_l)
        except RuntimeError:
            pass
        lo_r, hi_r = lo_l.copy(), hi_l.copy()
        width = int((hi_l - lo_l).max(initial=0))
        steps = max(1, int(np.ceil(np.log2(width + 1)))) if width else 0
        guard = max(len(self.skmers) - 1, 0)
        for _ in range(steps):
            # Converged lanes (lo == hi) must FREEZE: the fixed-step loop
            # keeps running for the widest bucket, and a clamped re-read at
            # lo == hi == len(skmers) compares "go right" and would walk
            # the bound past the array (measured: top-key k-mers of a 100M
            # reference).
            # left bound: first index with skmers[i] >= key
            act = lo_l < hi_l
            mid = (lo_l + hi_l) >> 1
            v = self.skmers[np.minimum(mid, guard)]
            right = act & (v < keys)
            lo_l = np.where(right, mid + 1, lo_l)
            hi_l = np.where(act & ~right, mid, hi_l)
            # right bound: first index with skmers[i] > key
            act = lo_r < hi_r
            mid = (lo_r + hi_r) >> 1
            v = self.skmers[np.minimum(mid, guard)]
            right = act & (v <= keys)
            lo_r = np.where(right, mid + 1, lo_r)
            hi_r = np.where(act & ~right, mid, hi_r)
        return lo_l, lo_r

    def lookup(self, key: int) -> np.ndarray:
        """Global positions of one k-mer (position-ascending)."""
        lo = int(np.searchsorted(self.skmers, key))
        hi = int(np.searchsorted(self.skmers, key, side="right"))
        return self.spos[lo:hi]

    def ref_of(self, gpos: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Vectorized global position -> (ref_idx, local_pos)."""
        ri = np.searchsorted(self.gbase, gpos, side="right") - 1
        return ri, gpos - self.gbase[ri]


class BuiltinAligner:
    """K-mer seed + ungapped extend against an in-memory reference."""

    def __init__(self, fasta_path, k: int = 21, seed_stride: int = 7,
                 max_mismatch_frac: float = 0.1):
        self.k = k
        self.seed_stride = seed_stride
        self.max_mismatch_frac = max_mismatch_frac
        self.refs: list[tuple[str, int]] = []
        self._ref_codes: dict[str, np.ndarray] = {}
        codes_list: list[np.ndarray] = []
        for name, seq in read_fasta(fasta_path).items():
            codes = _encode(seq)
            self.refs.append((name, len(seq)))
            self._ref_codes[name] = codes
            codes_list.append(codes)
        self._sidx = _SortedKmerIndex(codes_list, k)

    def _seed_votes(self, codes: np.ndarray):
        """Candidate (ref, diagonal) offsets from strided seed lookups."""
        k = self.k
        votes: dict[tuple[str, int], int] = {}
        if len(codes) < k:
            return votes
        for start in range(0, len(codes) - k + 1, self.seed_stride):
            window = codes[start : start + k]
            if (window >= 4).any():
                continue
            key = 0
            for v in window:
                key = (key << 2) | int(v)
            hits = self._sidx.lookup(key)
            if len(hits):
                ris, lps = self._sidx.ref_of(hits)
                for ri, lp in zip(ris, lps):
                    diag = int(lp) - start
                    rk = (self.refs[int(ri)][0], diag)
                    votes[rk] = votes.get(rk, 0) + 1
        return votes

    def _extend(self, codes: np.ndarray, ref: str, pos: int) -> int | None:
        """Ungapped mismatch count at (ref, pos), or None if out of bounds."""
        rc = self._ref_codes[ref]
        if pos < 0 or pos + len(codes) > len(rc):
            return None
        window = rc[pos : pos + len(codes)]
        return int((window != codes).sum())

    def align(self, seq: str) -> Hit | None:
        """Best ungapped placement of ``seq`` on either strand."""
        max_nm = int(len(seq) * self.max_mismatch_frac)
        candidates: list[tuple[int, str, int, bool]] = []
        for reverse in (False, True):
            s = revcomp(seq) if reverse else seq
            codes = _encode(s)
            votes = self._seed_votes(codes)
            # Try diagonals by vote count; a few candidates suffice for
            # substitution-only error.
            for (ref, diag), _n in sorted(votes.items(), key=lambda kv: -kv[1])[:4]:
                nm = self._extend(codes, ref, diag)
                if nm is not None and nm <= max_nm:
                    candidates.append((nm, ref, diag, reverse))
        if not candidates:
            return None
        candidates.sort(key=lambda c: c[0])
        nm, ref, pos, reverse = candidates[0]
        # bwa-flavoured mapq: confident when the runner-up is clearly worse.
        mapq = 60 if len(candidates) == 1 else \
            max(0, min(60, 10 * (candidates[1][0] - nm)))
        return Hit(ref=ref, pos=pos, reverse=reverse, nm=nm, mapq=mapq)

    # -------------------------------------------------------------- batch
    _HIT_CAP = 64   # hits taken per seed (repetitive k-mers truncate here)
    _TOP_C = 4      # diagonals extended per strand (matches align())

    def align_batch(self, codes: np.ndarray) -> dict:
        """Vectorized :meth:`align` over a ``(B, L)`` uint8 code batch.

        One numpy pass replaces B per-read Python walks — the measured wall
        of the 100M-read fastq2bam flow (VERDICT r3 item 6).  Semantics
        match :meth:`align` (same seeds, same top-``_TOP_C``-by-votes
        candidate rule with first-seen tie order, same stable min-nm pick,
        same mapq) except that pathological repetitive seeds truncate at
        ``_HIT_CAP`` hits.  Returns ``(B,)`` arrays: ``mapped`` (bool),
        ``ref_idx``/``pos``/``nm``/``mapq`` (int32, -1/0 where unmapped),
        ``reverse`` (bool).
        """
        B, L = codes.shape
        k, stride = self.k, self.seed_stride
        out = {
            "mapped": np.zeros(B, bool),
            "ref_idx": np.full(B, -1, np.int32),
            "pos": np.full(B, -1, np.int64),
            "nm": np.zeros(B, np.int32),
            "mapq": np.zeros(B, np.int32),
            "reverse": np.zeros(B, bool),
        }
        if B == 0 or L < k or not len(self._sidx.skmers):
            return out
        max_nm = int(L * self.max_mismatch_frac)

        # Both strands as one (2B, L) block: row 2r = forward, 2r+1 = rev.
        rc = _REVCOMP_LUT[codes[:, ::-1]]
        allc = np.empty((2 * B, L), np.uint8)
        allc[0::2] = codes
        allc[1::2] = rc

        # --- strided seed keys ------------------------------------------
        starts = np.arange(0, L - k + 1, stride, dtype=np.int64)
        S = len(starts)
        keys = np.zeros((2 * B, S), np.int64)
        ok = np.ones((2 * B, S), bool)
        for j in range(k):
            col = allc[:, starts + j]
            keys = (keys << 2) | col.astype(np.int64)
            ok &= col < 4
        keys &= (np.int64(1) << (2 * k)) - 1

        # --- index lookups ----------------------------------------------
        flat_keys = keys.reshape(-1)
        flat_ok = ok.reshape(-1)
        lo, hi = self._sidx.lookup_batch(flat_keys)
        cnt = np.where(flat_ok, np.minimum(hi - lo, self._HIT_CAP), 0)
        H = int(cnt.sum())
        if H == 0:
            return out
        seed_of = np.repeat(np.arange(2 * B * S, dtype=np.int64), cnt)
        within = np.arange(H, dtype=np.int64) - np.repeat(
            np.concatenate([[0], np.cumsum(cnt)[:-1]]), cnt)
        gpos = self._sidx.spos[lo[seed_of] + within]
        row = seed_of // S
        sstart = starts[seed_of % S]
        diag = gpos - sstart                      # global candidate start
        # Vote key must carry the hit's REF, not just the global diagonal:
        # hits on two adjacent refs can share a diag value and align()
        # keeps their votes separate (per (ref, local_diag)).
        hit_ref = np.searchsorted(self._sidx.gbase, gpos, side="right") - 1
        vkey = (hit_ref << 44) | (diag + (np.int64(1) << 20))
        seen = np.arange(H, dtype=np.int64)       # first-seen order = scan order

        # --- vote per (row, ref, diag): run-length over the sorted pairs --
        o = np.lexsort((seen, vkey, row))
        row_s, vkey_s, seen_s = row[o], vkey[o], seen[o]
        new = np.empty(H, bool)
        new[0] = True
        new[1:] = (row_s[1:] != row_s[:-1]) | (vkey_s[1:] != vkey_s[:-1])
        run_start = np.nonzero(new)[0]
        votes = np.diff(np.concatenate([run_start, [H]]))
        c_row = row_s[run_start]
        c_diag = diag[o][run_start]
        c_seen = seen_s[run_start]  # min within run (seen sorted last key)

        # --- top _TOP_C per row by (votes desc, first-seen asc) ----------
        o2 = np.lexsort((c_seen, -votes, c_row))
        rr = c_row[o2]
        first = np.empty(len(rr), bool)
        first[0] = True
        first[1:] = rr[1:] != rr[:-1]
        rank = np.arange(len(rr)) - np.maximum.accumulate(
            np.where(first, np.arange(len(rr)), 0))
        keep = rank < self._TOP_C
        k_row = rr[keep]
        k_diag = c_diag[o2][keep]
        k_rank = rank[keep]

        # --- bounds + ungapped extension --------------------------------
        ri, lp = self._sidx.ref_of(k_diag)
        inb = (k_diag >= 0) & (lp >= 0) & (lp + L <= self._sidx.lens[ri])
        k_row, k_diag, k_rank, ri, lp = (a[inb] for a in
                                         (k_row, k_diag, k_rank, ri, lp))
        if not len(k_row):
            return out
        win = self._sidx.gcodes[k_diag[:, None] + np.arange(L, dtype=np.int64)]
        nm = (win != allc[k_row]).sum(1).astype(np.int64)
        good = nm <= max_nm
        k_row, k_diag, k_rank, ri, lp, nm = (a[good] for a in
                                             (k_row, k_diag, k_rank, ri, lp, nm))
        if not len(k_row):
            return out

        # --- stable min-nm per READ across both strands ------------------
        # candidate insertion order in align(): forward strand's top-4
        # first, then reverse's — i.e. (strand, vote-rank); pick by
        # (nm, order) like the stable sort in align().
        read = k_row >> 1
        order = (k_row & 1) * self._TOP_C + k_rank
        o3 = np.lexsort((order, nm, read))
        rd = read[o3]
        first = np.empty(len(rd), bool)
        first[0] = True
        first[1:] = rd[1:] != rd[:-1]
        best = np.nonzero(first)[0]
        n_cand = np.diff(np.concatenate([best, [len(rd)]]))
        b_read = rd[best]
        b_nm = nm[o3][best]
        runner_nm = np.where(n_cand > 1,
                             nm[o3][np.minimum(best + 1, len(rd) - 1)], 0)
        mapq = np.where(
            n_cand == 1, 60,
            np.clip(10 * (runner_nm - b_nm), 0, 60)).astype(np.int32)
        out["mapped"][b_read] = True
        out["ref_idx"][b_read] = ri[o3][best].astype(np.int32)
        out["pos"][b_read] = lp[o3][best]
        out["nm"][b_read] = b_nm.astype(np.int32)
        out["mapq"][b_read] = mapq
        out["reverse"][b_read] = (k_row[o3][best] & 1).astype(bool)
        return out


def _align_tasks(r1: str, r2: str, pair_chunk: int):
    """Yield compact per-chunk task tuples ``(seq1, qual1, seq2, qual2,
    tok, tok_lens)`` — equal-length byte matrices gathered out of the
    FASTQ batch buffers, so a task pickles as a few small arrays instead
    of dragging the whole batch through the pool pipe."""
    from consensuscruncher_tpu.stages.extract_barcodes import (_batch_zipper,
                                                               tok_matrix)

    for c1, c2 in _batch_zipper(r1, r2):
        d1, ns1, nl1, ss1, sl1, qs1 = c1
        d2, ns2, nl2, ss2, sl2, qs2 = c2
        tok1, tl1 = tok_matrix(d1, ns1, nl1)
        tok2, tl2 = tok_matrix(d2, ns2, nl2)
        w = max(tok1.shape[1], tok2.shape[1])
        p1 = np.zeros((len(tl1), w), np.uint8)
        p2 = np.zeros((len(tl2), w), np.uint8)
        p1[:, :tok1.shape[1]] = tok1
        p2[:, :tok2.shape[1]] = tok2
        bad = (tl1 != tl2) | (p1 != p2).any(1)
        if bad.any():
            i = int(np.nonzero(bad)[0][0])
            t1 = bytes(tok1[i, : tl1[i]]).decode(errors="replace")
            t2 = bytes(tok2[i, : tl2[i]]).decode(errors="replace")
            raise SystemExit(f"R1/R2 qname mismatch: {t1!r} vs {t2!r}")
        # equal-length buckets (usually exactly one for real runs)
        lkey = sl1.astype(np.int64) << 32 | sl2.astype(np.int64)
        for key in np.unique(lkey):
            sel = np.nonzero(lkey == key)[0]
            l1, l2 = int(key >> 32), int(key & 0xFFFFFFFF)
            a1 = np.arange(l1, dtype=np.int64)
            a2 = np.arange(l2, dtype=np.int64)
            for c0 in range(0, len(sel), pair_chunk):
                sc = sel[c0:c0 + pair_chunk]
                yield (d1[ss1[sc, None] + a1], d1[qs1[sc, None] + a1],
                       d2[ss2[sc, None] + a2], d2[qs2[sc, None] + a2],
                       np.ascontiguousarray(tok1[sc]), tl1[sc])


# Fork-pool worker state: set in the parent immediately before the pool
# forks, inherited copy-on-write by the children (the k-mer index is
# hundreds of MB at genome scale — pickling it per task is a non-starter).
_POOL_ALIGNER: "BuiltinAligner | None" = None
_POOL_EMIT_LUT: np.ndarray | None = None
_POOL_PRESTART_BARRIER = None


def _barrier_timeout_s() -> float:
    """Prestart-barrier wait budget.  120s absorbs a badly overloaded
    host's fork storm; chaos tests shrink it via the environment (which
    crosses the fork boundary) to exercise the REAL timeout path rather
    than a parent-side injected stand-in."""
    try:
        return float(os.environ.get("CCT_ALIGN_BARRIER_TIMEOUT_S", "120"))
    except ValueError:
        return 120.0


def _pool_prestart_wait():
    """Pin one pool worker until every worker has forked (see the prestart
    barrier in :func:`align_fastqs_columnar`)."""
    # chaos site in the CHILD: a stalled/dead worker here is what makes
    # the parent's barrier wait time out for real
    fault_point("align.barrier_worker")
    _POOL_PRESTART_BARRIER.wait(timeout=_barrier_timeout_s())


def _pool_bucket_blobs(task):
    from consensuscruncher_tpu.io.encode import encode_records

    fault_point("align.pool_worker")  # chaos site: injected worker death
    return _bucket_blobs(_POOL_ALIGNER, encode_records, _POOL_EMIT_LUT, *task)


def _shutdown_pool(pool, kill: bool) -> None:
    """``kill=True``: abort path — SIGTERM the forked workers so in-flight
    chunks stop NOW (executor shutdown only cancels queued futures; running
    chunks would otherwise burn CPU + the COW index until they drain).
    ``kill=False``: drained path — clean join."""
    if kill:
        pool.shutdown(wait=False, cancel_futures=True)
        # _processes is None once the executor is broken/shut down
        for p in list((getattr(pool, "_processes", None) or {}).values()):
            try:
                p.terminate()
            except Exception:
                pass
    else:
        pool.shutdown(wait=True)


def _start_pool(workers: int, aligner, emit_lut):
    """Fork + warm an align pool behind the prestart barrier.

    Returns the executor, or None when warm-up fails (barrier timeout on an
    overloaded host, injected fault) — the caller degrades to serial
    alignment with a warning instead of aborting a multi-hour run.  Output
    bytes are identical either way (the writer's order is content-keyed),
    so degradation costs wall-clock only.
    """
    import concurrent.futures as cf
    import multiprocessing as mp
    import threading

    global _POOL_ALIGNER, _POOL_EMIT_LUT, _POOL_PRESTART_BARRIER
    ctx = mp.get_context("fork")
    _POOL_ALIGNER, _POOL_EMIT_LUT = aligner, emit_lut
    _POOL_PRESTART_BARRIER = ctx.Barrier(workers + 1)
    pool = cf.ProcessPoolExecutor(workers, mp_context=ctx)
    try:
        # Force every worker to fork NOW: each barrier task pins the
        # worker that picks it up, so the executor's on-demand spawner
        # must create all `workers` processes before the parent (the
        # +1-th party) releases them — i.e. before the sorting writer
        # and its async BGZF thread exist.
        warm = [pool.submit(_pool_prestart_wait) for _ in range(workers)]
        fault_point("align.barrier")
        timeout = _barrier_timeout_s()
        _POOL_PRESTART_BARRIER.wait(timeout=timeout)
        for f in warm:
            f.result(timeout=timeout)
    except (threading.BrokenBarrierError, cf.TimeoutError, FaultError) as e:
        _shutdown_pool(pool, kill=True)
        _POOL_ALIGNER = _POOL_EMIT_LUT = _POOL_PRESTART_BARRIER = None
        print(f"WARNING: align pool warm-up failed ({e!r}); "
              "falling back to serial alignment", file=sys.stderr, flush=True)
        return None
    except BaseException:
        # anything else (KeyboardInterrupt, executor bug) must not leak the
        # executor or pin the COW index
        _shutdown_pool(pool, kill=True)
        _POOL_ALIGNER = _POOL_EMIT_LUT = _POOL_PRESTART_BARRIER = None
        raise
    return pool


def align_fastqs_columnar(aligner: BuiltinAligner, r1: str, r2: str,
                          out_bam: str, level: int = 6,
                          workers: int = 1,
                          pair_chunk: int = 16384) -> tuple[int, int]:
    """Columnar twin of :func:`align_pairs` over whole FASTQ batch pairs:
    ``align_batch`` for the placement and ``encode_records`` for emission —
    no per-read Python in the loop (the measured wall of the 100M-read
    fastq2bam flow).  Returns ``(n_reads, n_unmapped)``.  Record bytes are
    identical to the object path (tests pin digest parity).

    ``workers > 1`` fans the per-chunk align+encode compute (~85% of the
    leg's wall on one core) over a forked process pool; the parent writes
    each chunk's blobs as they complete, in submission order, through the
    one :class:`SortingBamWriter`.  Output bytes are IDENTICAL to the
    serial path regardless of ``workers``/``pair_chunk``: the writer's
    total order is content-keyed (rid, pos, qname, flag — never append
    order), which is the same property that lets the object and columnar
    paths byte-match.  ALL pool workers fork before the writer exists (a
    prestart barrier forces the executor's lazy spawns early), so no
    BGZF/codec thread state crosses any fork; the executor never re-forks
    replacements itself.  A worker death (e.g. OOM-kill at the 100M+-read
    scale this targets) surfaces as BrokenProcessPool at the next drain;
    the run then re-forks the pool ONCE and replays the lost chunks, and
    on a second death finishes the remaining chunks serially in the
    parent — the content-keyed order makes replay byte-transparent.
    """
    from consensuscruncher_tpu.io.bam import BamHeader
    from consensuscruncher_tpu.io.columnar import SortingBamWriter
    from consensuscruncher_tpu.io.encode import encode_records
    from consensuscruncher_tpu.utils.phred import encode_seq

    global _POOL_ALIGNER, _POOL_EMIT_LUT, _POOL_PRESTART_BARRIER
    # TWO code spaces on purpose: alignment compares in _CODE space
    # (non-ACGT -> 255, so read-N over ref-N matches, exactly like
    # align()/_encode), while emission uses pipeline codes (N -> 4) for
    # encode_records' seq nibbles.
    emit_lut = encode_seq(np.arange(256, dtype=np.uint8).tobytes())
    header = BamHeader.from_refs(aligner.refs)
    n_total = n_unmapped = 0
    tasks = _align_tasks(r1, r2, pair_chunk)

    pool = None
    if workers > 1:
        pool = _start_pool(workers, aligner, emit_lut)

    from consensuscruncher_tpu.io.columnar import single_writer_sort_buffer_bytes

    # The align leg holds exactly one sorting writer, so it may claim the
    # single-writer RAM budget — at the 100M-read class this keeps the
    # coordinate sort in memory instead of spilling (BASELINE.md round 4).
    writer = SortingBamWriter(out_bam, header, level=level,
                              max_raw_bytes=single_writer_sort_buffer_bytes())
    try:
        if pool is None:
            for task in tasks:
                blob1, blob2, un = _bucket_blobs(
                    aligner, encode_records, emit_lut, *task)
                n_total += 2 * len(task[0])
                n_unmapped += un
                writer.write_encoded(blob1)
                writer.write_encoded(blob2)
        else:
            from collections import deque
            from concurrent.futures.process import BrokenProcessPool

            pending: deque = deque()  # (future, task) — tasks kept for replay
            max_inflight = workers + 2
            refork_left = 1

            def run_serial(task):
                nonlocal n_unmapped
                blob1, blob2, un = _bucket_blobs(
                    aligner, encode_records, emit_lut, *task)
                n_unmapped += un
                writer.write_encoded(blob1)
                writer.write_encoded(blob2)

            def handle_pool_death(exc):
                # One worker death breaks EVERY in-flight future, so the
                # whole pending window must be replayed: re-fork the pool
                # once, and after a second death (or a failed re-fork
                # warm-up) finish in the parent.  Replay cannot duplicate
                # or reorder output — the writer's total order is
                # content-keyed and n_unmapped counts only at completion.
                nonlocal pool, refork_left
                global _POOL_ALIGNER, _POOL_EMIT_LUT, _POOL_PRESTART_BARRIER
                lost = [t for _f, t in pending]
                pending.clear()
                _shutdown_pool(pool, kill=True)
                pool = None
                _POOL_ALIGNER = _POOL_EMIT_LUT = _POOL_PRESTART_BARRIER = None
                if refork_left > 0:
                    refork_left -= 1
                    print(f"WARNING: align pool worker died ({exc!r}); "
                          f"re-forking once and replaying {len(lost)} "
                          "in-flight chunk(s)", file=sys.stderr, flush=True)
                    pool = _start_pool(workers, aligner, emit_lut)
                else:
                    print(f"WARNING: align pool died again ({exc!r}); "
                          "finishing the remaining chunks serially",
                          file=sys.stderr, flush=True)
                for t in lost:
                    submit_one(t)

            def submit_one(task):
                if pool is None:
                    run_serial(task)
                    return
                try:
                    pending.append((pool.submit(_pool_bucket_blobs, task), task))
                except BrokenProcessPool as e:
                    handle_pool_death(e)
                    if pool is None:
                        run_serial(task)
                    else:
                        pending.append((pool.submit(_pool_bucket_blobs, task), task))

            def drain_one():
                # result() raises BrokenProcessPool the moment any worker
                # dies (the executor marks every in-flight future) — recover
                # instead of blocking forever or aborting the run.
                nonlocal n_unmapped
                fut, task = pending.popleft()
                try:
                    blob1, blob2, un = fut.result()
                except BrokenProcessPool as e:
                    pending.appendleft((fut, task))  # still lost; replay it
                    handle_pool_death(e)
                    return
                n_unmapped += un
                writer.write_encoded(blob1)
                writer.write_encoded(blob2)

            for task in tasks:
                while pool is not None and len(pending) >= max_inflight:
                    drain_one()
                n_total += 2 * len(task[0])
                submit_one(task)
            while pending:
                drain_one()
    except BaseException:
        if pool is not None:
            _shutdown_pool(pool, kill=True)
            pool = None
            _POOL_ALIGNER = _POOL_EMIT_LUT = _POOL_PRESTART_BARRIER = None
        writer.abort()
        raise
    finally:
        if pool is not None:
            _shutdown_pool(pool, kill=False)
            _POOL_ALIGNER = _POOL_EMIT_LUT = _POOL_PRESTART_BARRIER = None
    writer.close()
    return n_total, n_unmapped


def _bucket_blobs(aligner, encode_records, emit_lut,
                  seq1, rq1, seq2, rq2, tok, tok_lens):
    """Align one equal-length chunk of pairs (raw seq/qual byte matrices)
    and build both mates' encoded record blobs.  Pure compute — no writer
    access — so it runs unchanged in a forked pool worker.  Returns
    ``(r1_blob, r2_blob, n_unmapped)``.
    """
    B, l1 = seq1.shape
    _, l2 = seq2.shape
    if B == 0:
        z = np.zeros(0, np.uint8)
        return z, z, 0
    # alignment space: non-ACGT -> 255 (see align_fastqs_columnar)
    codes1 = emit_lut[seq1]
    codes2 = emit_lut[seq2]
    acodes1 = _CODE[seq1]
    acodes2 = _CODE[seq2]
    qual1 = rq1 - 33
    qual2 = rq2 - 33
    h1 = aligner.align_batch(acodes1)
    h2 = aligner.align_batch(acodes2)

    m1, m2 = h1["mapped"], h2["mapped"]
    proper = m1 & m2 & (h1["ref_idx"] == h2["ref_idx"]) & (h1["reverse"] != h2["reverse"])
    # FR pair tlen: leftmost gets +, by align_pairs' exact tie rule
    lo = np.minimum(h1["pos"], h2["pos"])
    hi = np.maximum(h1["pos"] + l1, h2["pos"] + l2)
    span = np.where(proper, hi - lo, 0)
    tie = h1["pos"] == h2["pos"]
    tlen1 = np.where(proper, np.where(tie | (h1["pos"] == lo), span, -span), 0)
    tlen2 = np.where(proper, np.where(tie, -span,
                                      np.where(h2["pos"] == lo, span, -span)), 0)

    unmapped = 0
    blobs = []
    for this, mate, codes, qual, L, read1, tl in (
        (h1, h2, codes1, qual1, l1, True, tlen1),
        (h2, h1, codes2, qual2, l2, False, tlen2),
    ):
        tm, mm = this["mapped"], mate["mapped"]
        unmapped += int((~tm).sum())
        flag = np.full(B, 0x1 | (0x40 if read1 else 0x80), np.int32)
        flag |= np.where(proper, 0x2, 0)
        flag |= np.where(~tm, 0x4, 0)
        flag |= np.where(tm & this["reverse"], 0x10, 0)
        flag |= np.where(~mm, 0x8, 0)
        flag |= np.where(mm & mate["reverse"], 0x20, 0)
        rid = np.where(tm, this["ref_idx"], np.where(mm, mate["ref_idx"], -1))
        pos = np.where(tm, this["pos"], np.where(mm, mate["pos"], -1))
        mrid = np.where(mm, mate["ref_idx"], rid)
        mpos = np.where(mm, mate["pos"], pos)
        rev = tm & this["reverse"]
        out_codes = np.where(rev[:, None], _REVCOMP_LUT[codes[:, ::-1]], codes)
        out_qual = np.where(rev[:, None], qual[:, ::-1], qual)
        cig_lens = tm.astype(np.int64)
        cig_words = np.full(int(cig_lens.sum()), (L << 4) | 0, np.uint32)
        tag7 = np.zeros((B, 7), np.uint8)
        tag7[:, :3] = np.frombuffer(b"NMi", np.uint8)
        tag7[:, 3:] = this["nm"].astype("<i4").view(np.uint8).reshape(B, 4)
        tag_lens = np.where(tm, 7, 0).astype(np.int64)
        from consensuscruncher_tpu.utils.ragged import gather_runs

        tok_data, _ = gather_runs(
            tok.reshape(-1),
            np.arange(B, dtype=np.int64) * tok.shape[1], tok_lens)
        blob = encode_records(
            tok_data,
            tok_lens,
            flag, rid.astype(np.int64), pos.astype(np.int64),
            np.where(tm, this["mapq"], 0).astype(np.int64),
            cig_words, cig_lens,
            mrid.astype(np.int64), mpos.astype(np.int64), tl.astype(np.int64),
            np.ascontiguousarray(out_codes).reshape(-1),
            np.full(B, L, np.int64),
            np.ascontiguousarray(out_qual).reshape(-1),
            tag7[tm].reshape(-1), tag_lens,
        )
        blobs.append(blob)
    return blobs[0], blobs[1], unmapped


def align_pairs(aligner: BuiltinAligner, pairs, header):
    """Yield ``BamRead`` pairs for ``(qname, s1, q1, s2, q2)`` tuples.

    Sets the reference's expected flag layout for FR proper pairs: paired +
    proper (both mates placed on the same ref, opposite strands), mate
    strand/position/tlen cross-filled, read1/read2 bits, and unmapped flags
    when a mate fails to place (such reads flow to badReads downstream).
    """
    from consensuscruncher_tpu.io.bam import BamRead

    for qname, s1, q1, s2, q2 in pairs:
        h1, h2 = aligner.align(s1), aligner.align(s2)
        proper = (
            h1 is not None and h2 is not None and h1.ref == h2.ref
            and h1.reverse != h2.reverse
        )
        for this, mate, seq, qual, read1 in ((h1, h2, s1, q1, True), (h2, h1, s2, q2, False)):
            flag = 0x1 | (0x40 if read1 else 0x80)
            if proper:
                flag |= 0x2
            if this is None:
                flag |= 0x4
            elif this.reverse:
                flag |= 0x10
            if mate is None:
                flag |= 0x8
            elif mate.reverse:
                flag |= 0x20
            out_seq = revcomp(seq) if (this is not None and this.reverse) else seq
            out_qual = np.asarray(qual[::-1] if (this is not None and this.reverse) else qual,
                                  np.uint8)
            pos = this.pos if this is not None else (mate.pos if mate is not None else -1)
            ref = this.ref if this is not None else (mate.ref if mate is not None else None)
            mate_pos = mate.pos if mate is not None else pos
            mate_ref = mate.ref if mate is not None else ref
            tlen = 0
            if proper:
                lo = min(h1.pos, h2.pos)
                hi = max(h1.pos + len(s1), h2.pos + len(s2))
                if h1.pos == h2.pos:
                    # SAM convention: the two tlens must sum to zero — when
                    # both mates share the leftmost position, break the tie
                    # deterministically (read1 +, read2 -).
                    tlen = (hi - lo) if read1 else -(hi - lo)
                else:
                    tlen = (hi - lo) if this.pos == lo else -(hi - lo)
            yield BamRead(
                qname=qname,
                flag=flag,
                ref=ref, pos=pos,
                mapq=this.mapq if this is not None else 0,
                cigar=[("M", len(seq))] if this is not None else [],
                mate_ref=mate_ref, mate_pos=mate_pos, tlen=tlen,
                seq=out_seq, qual=out_qual,
                tags={"NM": ("i", this.nm)} if this is not None else {},
            )
