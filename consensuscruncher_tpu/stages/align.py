"""Built-in paired-end aligner: ``fastq2bam --bwa builtin``.

The reference delegates alignment to an external ``bwa mem`` subprocess
(``ConsensusCruncher.py`` fastq2bam, SURVEY.md §3.1) and so does this
framework by default.  This module exists for the environments the
reference cannot handle at all — no aligner installed — so the FULL
fastq2bam flow still runs: k-mer seeding against an in-memory reference
index + ungapped extension with mismatch counting, emitting the same
coordinate-sorted barcoded BAM the external path produces.

Scope is deliberate: exact-stride seeds and ungapped extension handle
substitution-style sequencing error (the consensus pipeline's whole
subject) but NOT indels/clipping/splicing — it is a test/demo aligner
with honest limits, not a bwa replacement.  CIGAR is always full-length
``M``; unalignable reads come out unmapped (flag 0x4) and flow to the
pipeline's badReads path.

The seeding/voting layout is array-friendly on purpose: reads are held as
uint8 code matrices and seed votes are numpy bincounts, so a batched
device port (classic systolic-array scoring) can slot in behind the same
interface.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from consensuscruncher_tpu.io.fasta import read_fasta

_CODE = np.full(256, 255, np.uint8)
for _i, _c in enumerate(b"ACGT"):
    _CODE[_c] = _i
    _CODE[ord(chr(_c).lower())] = _i
_COMP = {"A": "T", "C": "G", "G": "C", "T": "A", "N": "N"}


def revcomp(seq: str) -> str:
    return "".join(_COMP.get(c, "N") for c in reversed(seq))


def _encode(seq: str) -> np.ndarray:
    return _CODE[np.frombuffer(seq.encode(), np.uint8)]


@dataclass(frozen=True)
class Hit:
    ref: str
    pos: int  # 0-based leftmost
    reverse: bool
    nm: int  # mismatches
    mapq: int


class BuiltinAligner:
    """K-mer seed + ungapped extend against an in-memory reference."""

    def __init__(self, fasta_path, k: int = 21, seed_stride: int = 7,
                 max_mismatch_frac: float = 0.1):
        self.k = k
        self.seed_stride = seed_stride
        self.max_mismatch_frac = max_mismatch_frac
        self.refs: list[tuple[str, int]] = []
        self._ref_codes: dict[str, np.ndarray] = {}
        self._index: dict[int, list[tuple[str, int]]] = {}
        for name, seq in read_fasta(fasta_path).items():
            self.refs.append((name, len(seq)))
            codes = _encode(seq)
            self._ref_codes[name] = codes
            # Roll k-mers into ints (2 bits/base); skip any window with N.
            if len(codes) < k:
                continue
            valid = codes < 4
            kmers = np.zeros(len(codes) - k + 1, np.int64)
            ok = np.ones(len(codes) - k + 1, bool)
            for j in range(k):
                window = codes[j : j + len(kmers)]
                kmers = (kmers << 2) | window
                ok &= valid[j : j + len(kmers)]
            for p in range(0, len(kmers), 1):
                if ok[p]:
                    self._index.setdefault(int(kmers[p]), []).append((name, p))

    def _seed_votes(self, codes: np.ndarray):
        """Candidate (ref, diagonal) offsets from strided seed lookups."""
        k = self.k
        votes: dict[tuple[str, int], int] = {}
        if len(codes) < k:
            return votes
        for start in range(0, len(codes) - k + 1, self.seed_stride):
            window = codes[start : start + k]
            if (window >= 4).any():
                continue
            key = 0
            for v in window:
                key = (key << 2) | int(v)
            for ref, p in self._index.get(key, ()):
                diag = p - start
                votes[(ref, diag)] = votes.get((ref, diag), 0) + 1
        return votes

    def _extend(self, codes: np.ndarray, ref: str, pos: int) -> int | None:
        """Ungapped mismatch count at (ref, pos), or None if out of bounds."""
        rc = self._ref_codes[ref]
        if pos < 0 or pos + len(codes) > len(rc):
            return None
        window = rc[pos : pos + len(codes)]
        return int((window != codes).sum())

    def align(self, seq: str) -> Hit | None:
        """Best ungapped placement of ``seq`` on either strand."""
        max_nm = int(len(seq) * self.max_mismatch_frac)
        candidates: list[tuple[int, str, int, bool]] = []
        for reverse in (False, True):
            s = revcomp(seq) if reverse else seq
            codes = _encode(s)
            votes = self._seed_votes(codes)
            # Try diagonals by vote count; a few candidates suffice for
            # substitution-only error.
            for (ref, diag), _n in sorted(votes.items(), key=lambda kv: -kv[1])[:4]:
                nm = self._extend(codes, ref, diag)
                if nm is not None and nm <= max_nm:
                    candidates.append((nm, ref, diag, reverse))
        if not candidates:
            return None
        candidates.sort(key=lambda c: c[0])
        nm, ref, pos, reverse = candidates[0]
        # bwa-flavoured mapq: confident when the runner-up is clearly worse.
        mapq = 60 if len(candidates) == 1 else \
            max(0, min(60, 10 * (candidates[1][0] - nm)))
        return Hit(ref=ref, pos=pos, reverse=reverse, nm=nm, mapq=mapq)


def align_pairs(aligner: BuiltinAligner, pairs, header):
    """Yield ``BamRead`` pairs for ``(qname, s1, q1, s2, q2)`` tuples.

    Sets the reference's expected flag layout for FR proper pairs: paired +
    proper (both mates placed on the same ref, opposite strands), mate
    strand/position/tlen cross-filled, read1/read2 bits, and unmapped flags
    when a mate fails to place (such reads flow to badReads downstream).
    """
    from consensuscruncher_tpu.io.bam import BamRead

    for qname, s1, q1, s2, q2 in pairs:
        h1, h2 = aligner.align(s1), aligner.align(s2)
        proper = (
            h1 is not None and h2 is not None and h1.ref == h2.ref
            and h1.reverse != h2.reverse
        )
        for this, mate, seq, qual, read1 in ((h1, h2, s1, q1, True), (h2, h1, s2, q2, False)):
            flag = 0x1 | (0x40 if read1 else 0x80)
            if proper:
                flag |= 0x2
            if this is None:
                flag |= 0x4
            elif this.reverse:
                flag |= 0x10
            if mate is None:
                flag |= 0x8
            elif mate.reverse:
                flag |= 0x20
            out_seq = revcomp(seq) if (this is not None and this.reverse) else seq
            out_qual = np.asarray(qual[::-1] if (this is not None and this.reverse) else qual,
                                  np.uint8)
            pos = this.pos if this is not None else (mate.pos if mate is not None else -1)
            ref = this.ref if this is not None else (mate.ref if mate is not None else None)
            mate_pos = mate.pos if mate is not None else pos
            mate_ref = mate.ref if mate is not None else ref
            tlen = 0
            if proper:
                lo = min(h1.pos, h2.pos)
                hi = max(h1.pos + len(s1), h2.pos + len(s2))
                if h1.pos == h2.pos:
                    # SAM convention: the two tlens must sum to zero — when
                    # both mates share the leftmost position, break the tie
                    # deterministically (read1 +, read2 -).
                    tlen = (hi - lo) if read1 else -(hi - lo)
                else:
                    tlen = (hi - lo) if this.pos == lo else -(hi - lo)
            yield BamRead(
                qname=qname,
                flag=flag,
                ref=ref, pos=pos,
                mapq=this.mapq if this is not None else 0,
                cigar=[("M", len(seq))] if this is not None else [],
                mate_ref=mate_ref, mate_pos=mate_pos, tlen=tlen,
                seq=out_seq, qual=out_qual,
                tags={"NM": ("i", this.nm)} if this is not None else {},
            )
