"""Streaming UMI-family grouping from a coordinate-sorted BAM.

Reference parity: ``ConsensusCruncher/consensus_helper.py:read_bam`` (SURVEY.md
§3.2), which fills whole-chromosome ``tag -> [reads]`` dicts.  Rebuilt as a
**position-windowed stream**: every member of a family shares the read's own
``(ref, pos)`` (that pair is part of the family key), so once the sorted
stream advances past a position, all families anchored there are complete and
can be flushed.  Memory is bounded by one position window instead of one
chromosome, and no BAI index / per-region ``fetch`` is needed at all.

Read filtering (pinned; reference routes these to a "badRead" BAM):
unmapped, mate-unmapped, secondary, supplementary, QC-fail reads, and reads
whose qname carries no barcode delimiter.  Duplicate-flagged reads are kept —
UMI consensus is itself the deduplicator.

MAINTENANCE MAP — this module holds semantic twins of the same grouping
rules at three altitudes; a semantic change must land in ALL of them (the
byte-parity suite will catch a miss, this note is so you change them on
purpose, not by accident):

1. OBJECT PATH (``stream_families``, ``consensus_windows``) —
   **reference-only fence: do not optimize.**  Survives as the honest
   bench.py baseline denominator and the readable statement of the rules;
   perf work here is wasted (the production pipeline never runs it) and
   only risks parity drift.
2. COLUMNAR PER-FAMILY PATH (``stream_families_columnar``,
   ``consensus_windows_columnar``) — batch decode, per-family emission;
   used by the cpu backend and the dense wire.
3. BLOCK PATH (``stream_family_blocks`` / ``duplex_pair_blocks`` /
   ``singleton_rescue_blocks``) — the production vectorized producers.
"""

from __future__ import annotations

from typing import Iterable, Iterator

import numpy as np

from consensuscruncher_tpu.core import qnames as qnames_mod
from consensuscruncher_tpu.core import tags as tags_mod
from consensuscruncher_tpu.utils.ragged import gather_runs
from consensuscruncher_tpu.io.bam import (
    BamHeader,
    BamRead,
    FMUNMAP,
    FPAIRED,
    FQCFAIL,
    FREAD1,
    FREVERSE,
    FSECONDARY,
    FSUPPLEMENTARY,
    FUNMAP,
)


class NotCoordinateSorted(ValueError):
    pass


def derive_tag(read):
    """Reconstruct a consensus read's family tag (coords/flags + XT barcode).

    Consensus/singleton reads written by the SSCS stage carry their barcode
    in ``XT``; everything else in the family key lives on the read itself.
    """
    if "XT" not in read.tags:
        raise ValueError(f"consensus read {read.qname} lacks the XT barcode tag")
    return tags_mod.unique_tag(read, read.tags["XT"][1])


def consensus_windows(reader):
    """Group a coordinate-sorted consensus BAM into per-(ref,pos) windows.

    OBJECT PATH — reference-only fence (see module docstring): the readable
    statement of the windowing rule and the fallback for foreign tag
    layouts; do not optimize.

    Yields ``(key, {FamilyTag: read})`` with ``key = (ref_id, pos)``.  Shared
    by the DCS and singleton-correction stages (their pairing partners always
    share the anchor position).  Raises :class:`NotCoordinateSorted` on
    order violations — silent mispairing on unsorted input would complete
    "successfully" with everything unpaired.
    """
    window: dict = {}
    cur = None
    for read in reader:
        tag = derive_tag(read)
        key = (reader.header.ref_id(read.ref), read.pos)
        if cur is not None and key < cur:
            raise NotCoordinateSorted(
                f"consensus BAM is not coordinate-sorted: {read.qname} at "
                f"{read.ref}:{read.pos} after ref_id={cur[0]} pos={cur[1]}"
            )
        if cur is not None and key != cur:
            yield cur, window
            window = {}
        cur = key
        window[tag] = read
    if window:
        yield cur, window


def classify_bad(read: BamRead, bdelim: str) -> str | None:
    """Reason string if the read must be routed to the badRead BAM, else None."""
    if read.is_unmapped:
        return "unmapped"
    if not read.is_paired or read.mate_is_unmapped:
        return "mate_unmapped"
    if read.is_secondary:
        return "secondary"
    if read.is_supplementary:
        return "supplementary"
    if read.is_qcfail:
        return "qcfail"
    try:
        tags_mod.barcode_from_qname(read.qname, bdelim)
    except ValueError:
        return "no_barcode"
    return None


def stream_families(
    reads: Iterable[BamRead],
    header: BamHeader,
    bdelim: str = tags_mod.DEFAULT_BDELIM,
) -> Iterator[tuple[str, object, object]]:
    """Yield ``("bad", read, reason)`` and ``("family", tag, [reads])`` events.

    OBJECT PATH — reference-only fence (see module docstring): this is the
    bench.py baseline denominator's grouping walk; do not optimize.

    Families are emitted as soon as the sorted stream passes their anchor
    position (deterministic order: by position, then tag string).  Raises
    :class:`NotCoordinateSorted` if the input violates coordinate order.
    """
    pending: dict[tags_mod.FamilyTag, list[BamRead]] = {}
    cur: tuple[int, int] | None = None  # (ref_id, pos) high-water mark

    def flush() -> Iterator[tuple[str, object, object]]:
        for tag in sorted(pending, key=lambda t: (t.pos, str(t))):
            yield "family", tag, pending[tag]
        pending.clear()

    for read in reads:
        reason = classify_bad(read, bdelim)
        if reason is not None:
            yield "bad", read, reason
            continue
        key = (header.ref_id(read.ref), read.pos)
        if cur is not None and key < cur:
            raise NotCoordinateSorted(
                f"input BAM is not coordinate-sorted: {read.qname} at {read.ref}:{read.pos} "
                f"after ref_id={cur[0]} pos={cur[1]} — run sort first"
            )
        if cur is not None and key != cur:
            yield from flush()
        cur = key
        barcode = tags_mod.barcode_from_qname(read.qname, bdelim)
        tag = tags_mod.unique_tag(read, barcode)
        pending.setdefault(tag, []).append(read)
    yield from flush()


# ------------------------------------------------------------- columnar path
#
# Vectorized twin of stream_families over io.columnar batches (the host-side
# Amdahl fix, SURVEY.md §7 hard-part #3): per-READ work — decode, bad-read
# classification, barcode extraction, family-key building, sortedness
# checking — happens as numpy column operations over whole batches; Python
# objects exist only per FAMILY (the tag + one lightweight view per member).
# Event stream, filtering semantics, and emission order are identical to
# stream_families (same events, same flush-per-coordinate model, families
# sorted by str(tag) within a coordinate), so stage outputs are byte-equal.

# classify_bad reason codes, in classify_bad's priority order.
_BAD_REASONS = (None, "unmapped", "mate_unmapped", "secondary",
                "supplementary", "qcfail", "no_barcode")


class MemberView:
    """Zero-copy member of a columnar family: consensus inputs as views,
    template/BAM fields materialized lazily from the owning batch."""

    __slots__ = ("codes", "qual", "_batch", "_idx")

    def __init__(self, codes, qual, batch, idx):
        self.codes = codes
        self.qual = qual
        self._batch = batch
        self._idx = idx

    @property
    def seq_len(self) -> int:
        return self.codes.shape[0]

    @property
    def mapq(self) -> int:
        return int(self._batch.mapq[self._idx])

    @property
    def flag(self) -> int:
        return int(self._batch.flag[self._idx])

    @property
    def ref(self) -> str:
        return self._batch.header.ref_name(int(self._batch.ref_id[self._idx]))

    @property
    def pos(self) -> int:
        return int(self._batch.pos[self._idx])

    @property
    def mate_ref(self) -> str:
        return self._batch.header.ref_name(int(self._batch.mate_ref_id[self._idx]))

    @property
    def mate_pos(self) -> int:
        return int(self._batch.mate_pos[self._idx])

    @property
    def tlen(self) -> int:
        return int(self._batch.tlen[self._idx])

    @property
    def rid(self) -> int:
        return int(self._batch.ref_id[self._idx])

    @property
    def mrid(self) -> int:
        return int(self._batch.mate_ref_id[self._idx])

    def cigar_string(self) -> str:
        return self._batch.cigar_string(self._idx)

    def cigar_bytes(self) -> np.ndarray:
        """Raw little-endian cigar words as a byte view (cheap equality)."""
        b = self._batch
        start = int(b.cigar_start[self._idx])
        return b.buf[start : start + 4 * int(b.n_cigar[self._idx])]

    def cigar_words(self) -> np.ndarray:
        return np.ascontiguousarray(self.cigar_bytes()).view("<u4")

    def materialize(self) -> BamRead:
        """Full BamRead (singleton renames, bad-read writes)."""
        return self._batch.materialize(self._idx)


class _Seg:
    """Good-read rows of one coordinate, within one columnar batch."""

    __slots__ = ("batch", "gidx", "bcm", "bclen", "mate_rid", "mate_pos",
                 "rn", "rev", "codes_data", "codes_off", "qual_data", "qual_off")

    def __init__(self, batch, gidx, bcm, bclen, mate_rid, mate_pos, rn, rev,
                 codes_data, codes_off, qual_data, qual_off):
        self.batch = batch
        self.gidx = gidx
        self.bcm = bcm
        self.bclen = bclen
        self.mate_rid = mate_rid
        self.mate_pos = mate_pos
        self.rn = rn
        self.rev = rev
        self.codes_data = codes_data
        self.codes_off = codes_off
        self.qual_data = qual_data
        self.qual_off = qual_off

    def __len__(self):
        return len(self.gidx)


def _classify_batch(batch, bdelim_byte: int):
    """Vectorized classify_bad + barcode locate for one batch.

    Returns ``(reason, last, bclen)`` — reason 0 = good (codes index
    _BAD_REASONS), ``last`` the delimiter column in the qname matrix.
    """
    flag = batch.flag
    qm = batch.qname_matrix
    qlen = batch.l_qname - 1  # int64, actual qname length
    w = qm.shape[1]
    eq = qm == bdelim_byte
    has = eq.any(axis=1)
    last = np.where(has, w - 1 - np.argmax(eq[:, ::-1], axis=1), -1)
    bclen = np.where(has, qlen - last - 1, 0)
    reason = np.select(
        [
            (flag & FUNMAP) != 0,
            ((flag & FPAIRED) == 0) | ((flag & FMUNMAP) != 0),
            (flag & FSECONDARY) != 0,
            (flag & FSUPPLEMENTARY) != 0,
            (flag & FQCFAIL) != 0,
            ~(has & (bclen > 0)),
        ],
        [1, 2, 3, 4, 5, 6],
        default=0,
    ).astype(np.int8)
    return reason, last, bclen


def _good_segments(batch, reason, last, bclen):
    """Split a batch's good rows into per-coordinate _Seg runs (in stream
    order) and validate coordinate sortedness among them."""
    good = np.nonzero(reason == 0)[0]
    if good.size == 0:
        return [], None
    rid = batch.ref_id[good]
    pos = batch.pos[good]
    ok = (rid[1:] > rid[:-1]) | ((rid[1:] == rid[:-1]) & (pos[1:] >= pos[:-1]))
    if not ok.all():
        i = int(np.argmin(ok)) + 1
        read = batch.materialize(int(good[i]))
        raise NotCoordinateSorted(
            f"input BAM is not coordinate-sorted: {read.qname} at "
            f"{read.ref}:{read.pos} after ref_id={int(rid[i - 1])} "
            f"pos={int(pos[i - 1])} — run sort first"
        )
    # coordinate run boundaries among good rows
    change = np.nonzero((rid[1:] != rid[:-1]) | (pos[1:] != pos[:-1]))[0] + 1
    bounds = np.concatenate([[0], change, [good.size]])

    qm = batch.qname_matrix
    w = qm.shape[1]
    wb = int(bclen[good].max(initial=0))
    cols = np.arange(wb, dtype=np.int64)
    src = last[good][:, None] + 1 + cols[None, :]
    valid = cols[None, :] < bclen[good][:, None]
    bcm = np.where(valid, qm[good[:, None], np.minimum(src, w - 1)], 0).astype(np.uint8)

    codes_data, codes_off = batch.seq_codes()
    qual_data, qual_off = batch.quals()
    rn = np.where((batch.flag[good] & FREAD1) != 0, 1, 2).astype(np.int8)
    rev = ((batch.flag[good] & FREVERSE) != 0).astype(np.int8)
    segs = []
    for a, b in zip(bounds[:-1], bounds[1:]):
        g = good[a:b]
        segs.append(_Seg(
            batch, g, bcm[a:b], bclen[g],
            batch.mate_ref_id[g], batch.mate_pos[g], rn[a:b], rev[a:b],
            codes_data, codes_off, qual_data, qual_off,
        ))
    return segs, (int(rid[-1]), int(pos[-1]))


def _emit_group(segs: list[_Seg], header: BamHeader):
    """All families of one coordinate (possibly spanning batches): lexsort
    by key columns (stable -> members keep stream order), split runs, build
    one FamilyTag per family, emit sorted by str(tag) — exactly the object
    path's ``sorted(pending, key=(pos, str(tag)))`` within-coordinate order."""
    if len(segs) == 1:
        s = segs[0]
        bcm, bclen = s.bcm, s.bclen
        mate_rid, mate_pos = s.mate_rid, s.mate_pos
        rn, rev = s.rn, s.rev
    else:
        wb = max(s.bcm.shape[1] for s in segs)
        bcm = np.zeros((sum(len(s) for s in segs), wb), dtype=np.uint8)
        row = 0
        for s in segs:
            bcm[row : row + len(s), : s.bcm.shape[1]] = s.bcm
            row += len(s)
        bclen = np.concatenate([s.bclen for s in segs])
        mate_rid = np.concatenate([s.mate_rid for s in segs])
        mate_pos = np.concatenate([s.mate_pos for s in segs])
        rn = np.concatenate([s.rn for s in segs])
        rev = np.concatenate([s.rev for s in segs])

    n = bcm.shape[0]
    # lexsort: last key is primary; barcode bytes most-significant overall
    keys = [rev, rn, mate_pos, mate_rid]
    keys += [bcm[:, j] for j in range(bcm.shape[1] - 1, -1, -1)]
    order = np.lexsort(keys)

    kb = bcm[order]
    same = np.ones(n, dtype=bool)
    if n > 1:
        same[1:] = (
            (kb[1:] == kb[:-1]).all(axis=1)
            & (mate_rid[order][1:] == mate_rid[order][:-1])
            & (mate_pos[order][1:] == mate_pos[order][:-1])
            & (rn[order][1:] == rn[order][:-1])
            & (rev[order][1:] == rev[order][:-1])
        )
    starts = np.nonzero(~same)[0]
    bounds = np.concatenate([[0], starts, [n]])

    # map flat group-local row -> (segment, local row)
    seg_of = np.repeat(np.arange(len(segs)), [len(s) for s in segs])
    loc = np.concatenate([np.arange(len(s)) for s in segs])

    s0 = segs[0]
    anchor_ref = header.ref_name(int(s0.batch.ref_id[s0.gidx[0]]))
    anchor_pos = int(s0.batch.pos[s0.gidx[0]])

    families = []
    for a, b in zip(bounds[:-1], bounds[1:]):
        rows = order[a:b]
        first = rows[0]
        barcode = bcm[first, : bclen[first]].tobytes().decode("ascii")
        tag = tags_mod.FamilyTag(
            barcode=barcode,
            ref=anchor_ref,
            pos=anchor_pos,
            mate_ref=header.ref_name(int(mate_rid[first])),
            mate_pos=int(mate_pos[first]),
            read_number=int(rn[first]),
            orientation="rev" if rev[first] else "fwd",
        )
        members = []
        for r in rows:
            s = segs[seg_of[r]]
            i = int(s.gidx[loc[r]])
            codes = s.codes_data[s.codes_off[i] : s.codes_off[i + 1]]
            qual = s.qual_data[s.qual_off[i] : s.qual_off[i + 1]]
            members.append(MemberView(codes, qual, s.batch, i))
        families.append((str(tag), tag, members))
    families.sort(key=lambda t: t[0])
    for _, tag, members in families:
        yield "family", tag, members


def stream_families_columnar(
    creader,
    header: BamHeader,
    bdelim: str = tags_mod.DEFAULT_BDELIM,
) -> Iterator[tuple[str, object, object]]:
    """Columnar twin of :func:`stream_families` over a
    ``io.columnar.ColumnarReader`` — same events, same order guarantees."""
    bdelim_byte = ord(bdelim)
    carry: list[_Seg] = []
    carry_key: tuple[int, int] | None = None
    for batch in creader.batches():
        reason, last, bclen = _classify_batch(batch, bdelim_byte)
        bad = np.nonzero(reason != 0)[0]
        for i in bad:
            yield "bad", batch.materialize(int(i)), _BAD_REASONS[int(reason[i])]
        segs, _tail = _good_segments(batch, reason, last, bclen)
        if not segs:
            continue
        s0 = segs[0]
        first_key = (int(s0.batch.ref_id[s0.gidx[0]]), int(s0.batch.pos[s0.gidx[0]]))
        if carry and carry_key is not None:
            if first_key < carry_key:
                read = s0.batch.materialize(int(s0.gidx[0]))
                raise NotCoordinateSorted(
                    f"input BAM is not coordinate-sorted: {read.qname} at "
                    f"{read.ref}:{read.pos} after ref_id={carry_key[0]} "
                    f"pos={carry_key[1]} — run sort first"
                )
            if first_key == carry_key:
                carry.append(segs.pop(0))
            if segs:  # a later coordinate arrived: the carry is complete
                yield from _emit_group(carry, header)
                carry = []
        for seg in segs[:-1]:
            yield from _emit_group([seg], header)
        if segs:
            tail = segs[-1]
            carry.append(tail)
            carry_key = (
                int(tail.batch.ref_id[tail.gidx[0]]),
                int(tail.batch.pos[tail.gidx[0]]),
            )
    if carry:
        yield from _emit_group(carry, header)


# -------------------------------------------------- columnar consensus path
#
# Columnar twin of consensus_windows for the DCS stage: SSCS/consensus BAMs
# are read as columnar batches; the XT (family barcode) and XF (family
# size) tags the SSCS stage writes FIRST in every record's tag block are
# parsed vectorized from a fixed byte window, with a per-read object
# fallback for records whose tag block doesn't lead with XT (foreign BAMs).

_XT_WINDOW = 96  # tag-block prefix bytes scanned vectorized (barcode + XF)


class ConsensusReadView(MemberView):
    """A consensus read in a columnar batch: MemberView + parsed XT/XF."""

    __slots__ = ("xt", "xf")

    def __init__(self, codes, qual, batch, idx, xt: str, xf: int):
        super().__init__(codes, qual, batch, idx)
        self.xt = xt
        self.xf = xf

    @property
    def fam_size(self) -> int:
        return self.xf


def fam_size_of(read) -> int:
    """XF family size of a consensus read (BamRead or ConsensusReadView)."""
    xf = getattr(read, "xf", None)
    if xf is not None:
        return xf
    return read.tags.get("XF", ("i", 1))[1]


def _parse_xt_xf(batch):
    """Vectorized XT:Z + XF:i parse from each record's tag-block prefix.

    Returns ``(ok, bc_start, bc_len, xf)`` — rows with ``ok=False`` need the
    object fallback.  Offsets are into ``batch.buf`` so barcode bytes can be
    sliced per read without another gather.
    """
    ts = batch.tags_start
    te = batch.rec_off[1:]
    n = batch.n
    span = te - ts
    w = int(min(_XT_WINDOW, span.max(initial=0)))
    if w < 8:
        return np.zeros(n, bool), ts, np.zeros(n, np.int64), np.ones(n, np.int64)
    cols = np.arange(w, dtype=np.int64)
    idx = ts[:, None] + cols[None, :]
    win = np.where(idx < te[:, None], batch.buf[np.minimum(idx, len(batch.buf) - 1)], 0)
    ok = (win[:, 0] == ord("X")) & (win[:, 1] == ord("T")) & (win[:, 2] == ord("Z"))
    z = win[:, 3:] == 0
    has_nul = z.any(axis=1)
    zpos = np.argmax(z, axis=1).astype(np.int64)  # first NUL at/after byte 3
    ok &= has_nul
    # XF:i must follow the barcode NUL and fit inside the scanned window.
    xf_off = 3 + zpos + 1
    fits = xf_off + 7 <= w
    ok &= fits
    safe = np.where(ok, xf_off, 0)
    tag_ok = (
        (np.take_along_axis(win, safe[:, None], 1)[:, 0] == ord("X"))
        & (np.take_along_axis(win, (safe + 1)[:, None], 1)[:, 0] == ord("F"))
        & (np.take_along_axis(win, (safe + 2)[:, None], 1)[:, 0] == ord("i"))
    )
    ok &= tag_ok
    b = [np.take_along_axis(win, (safe + 3 + k)[:, None], 1)[:, 0].astype(np.int64)
         for k in range(4)]
    xf_raw = b[0] | (b[1] << 8) | (b[2] << 16) | (b[3] << 24)
    xf = np.where(xf_raw >= 1 << 31, xf_raw - (1 << 32), xf_raw)  # int32 LE
    return ok, ts + 3, zpos, xf


def consensus_windows_columnar(creader):
    """Columnar twin of :func:`consensus_windows` over a ColumnarReader.

    Yields ``(key, {FamilyTag: ConsensusReadView-or-BamRead})`` with the same
    semantics (last read wins a duplicate tag, NotCoordinateSorted on order
    violations, one window per distinct (ref_id, pos)).
    """
    header = creader.header
    window: dict = {}
    cur = None
    for batch in creader.batches():
        ok, bc_start, bc_len, xf = _parse_xt_xf(batch)
        codes_data, codes_off = batch.seq_codes()
        qual_data, qual_off = batch.quals()
        rid_col, pos_col = batch.ref_id, batch.pos
        flag_col = batch.flag
        buf = batch.buf
        for i in range(batch.n):
            if ok[i]:
                codes = codes_data[codes_off[i] : codes_off[i + 1]]
                qual = qual_data[qual_off[i] : qual_off[i + 1]]
                xt = buf[bc_start[i] : bc_start[i] + bc_len[i]].tobytes().decode("ascii")
                read = ConsensusReadView(codes, qual, batch, i, xt, int(xf[i]))
            else:  # foreign tag layout: full object decode
                read = batch.materialize(i)
                if "XT" not in read.tags:
                    raise ValueError(
                        f"consensus read {read.qname} lacks the XT barcode tag"
                    )
                xt = read.tags["XT"][1]
            rid = int(rid_col[i])
            tag = tags_mod.FamilyTag(
                barcode=xt,
                ref=header.ref_name(rid),
                pos=int(pos_col[i]),
                mate_ref=header.ref_name(int(batch.mate_ref_id[i])),
                mate_pos=int(batch.mate_pos[i]),
                read_number=1 if (int(flag_col[i]) & FREAD1) else 2,
                orientation="rev" if (int(flag_col[i]) & FREVERSE) else "fwd",
            )
            key = (rid, int(pos_col[i]))
            if cur is not None and key < cur:
                qname = batch.materialize(i).qname
                raise NotCoordinateSorted(
                    f"consensus BAM is not coordinate-sorted: {qname} at "
                    f"{tag.ref}:{tag.pos} after ref_id={cur[0]} pos={cur[1]}"
                )
            if cur is not None and key != cur:
                yield cur, window
                window = {}
            cur = key
            window[tag] = read
    if window:
        yield cur, window



# ------------------------------------------------------------ family blocks
#
# The fully-vectorized producer (v3): ONE FamilyBlock per columnar batch
# (the trailing coordinate defers to the next batch, exactly like the
# window carry above).  The coordinate is part of the family key, so
# grouping lexsorts it together with the barcode/mate/flag columns; runs of
# equal keys are families, stable sort preserves member stream order, and
# families emit sorted by (rid, pos, str(tag)) — the object path's global
# order.  Per-family Python shrinks to tag/qname strings and dict inserts;
# everything else is array passes.


class FamilyBlock:
    """All families of one columnar batch, as struct-of-arrays.

    Per family (emission order): ``sizes``, ``target_len`` (modal member
    length, ties -> longer), ``tmpl_*`` template fields, ``mapq_max``,
    barcode bytes (``bcm``/``bclen``), consensus qnames
    (``qname_data``/``qname_off`` — prebuilt ``sscs_qname`` strings),
    modal cigars (``cigar_data``/``cigar_off`` uint32 words), and the
    template source rows (``src_chunk``/``src_row`` into ``batches``).
    Per member (family-contiguous): ``mem_start``/``mem_len`` into
    ``data_chunks[mem_chunk[i]]`` (codes and quals share offsets), with
    ``fam_off`` boundaries.

    ``tags`` materializes ``FamilyTag`` objects lazily (tests, stats text) —
    the hot path never touches it.
    """

    __slots__ = ("sizes", "target_len", "tmpl_flag", "tmpl_rid",
                 "tmpl_pos", "tmpl_mrid", "tmpl_mpos", "tmpl_tlen",
                 "mapq_max", "bcm", "bclen", "qname_data", "qname_off",
                 "cigar_data", "cigar_off", "src_chunk", "src_row",
                 "batches", "ref_names", "data_chunks",
                 "mem_chunk", "mem_start", "mem_len", "fam_off",
                 "_tags_cache")

    @property
    def n_fam(self) -> int:
        return len(self.sizes)

    def qname(self, j: int) -> str:
        return bytes(
            self.qname_data[self.qname_off[j]:self.qname_off[j + 1]]
        ).decode("ascii")

    def barcode(self, j: int) -> str:
        return bytes(self.bcm[j, : self.bclen[j]]).decode("ascii")

    def cigar_words_of(self, j: int) -> np.ndarray:
        return self.cigar_data[self.cigar_off[j]:self.cigar_off[j + 1]]

    def tmpl_src(self, j: int):
        return self.batches[int(self.src_chunk[j])], int(self.src_row[j])

    @property
    def tags(self) -> list:
        """FamilyTag objects in emission order (lazy; cold paths only)."""
        if self._tags_cache is None:
            def _rname(i):
                return self.ref_names[i] if i >= 0 else "*"

            rn = np.where((self.tmpl_flag & FREAD1) != 0, 1, 2)
            rev = (self.tmpl_flag & FREVERSE) != 0
            self._tags_cache = [
                tags_mod.FamilyTag(
                    barcode=self.barcode(j),
                    ref=_rname(int(self.tmpl_rid[j])),
                    pos=int(self.tmpl_pos[j]),
                    mate_ref=_rname(int(self.tmpl_mrid[j])),
                    mate_pos=int(self.tmpl_mpos[j]),
                    read_number=int(rn[j]),
                    orientation="rev" if rev[j] else "fwd",
                )
                for j in range(self.n_fam)
            ]
        return self._tags_cache


class _BlockSrc:
    """Good rows of one batch contributing to a block (or carried over)."""

    __slots__ = ("batch", "rows", "bcm", "bclen", "codes_data", "codes_off",
                 "qual_data")

    def __init__(self, batch, rows, bcm, bclen):
        self.batch = batch
        self.rows = rows
        self.bcm = bcm
        self.bclen = bclen
        self.codes_data, self.codes_off = batch.seq_codes()
        self.qual_data, _ = batch.quals()


def _modal_lengths(fam_ids, lens, n_fam):
    """Per-family modal member length, ties -> longer (the pinned
    ``parallel.batching.consensus_length`` semantics), vectorized."""
    order = np.lexsort((lens, fam_ids))
    f, l = fam_ids[order], lens[order]
    new_run = np.ones(len(f), dtype=bool)
    new_run[1:] = (f[1:] != f[:-1]) | (l[1:] != l[:-1])
    run_idx = np.nonzero(new_run)[0]
    run_fam, run_len = f[run_idx], l[run_idx]
    counts = np.diff(np.concatenate([run_idx, [len(f)]]))
    # per family pick (max count, then max len): lexsort runs by
    # (fam, count, len) and take the LAST run of each family
    ro = np.lexsort((run_len, counts, run_fam))
    last = np.zeros(len(ro), dtype=bool)
    if len(ro):
        last[-1] = True
        last[:-1] = run_fam[ro][1:] != run_fam[ro][:-1]
    out = np.zeros(n_fam, dtype=np.int64)
    out[run_fam[ro][last]] = run_len[ro][last]
    return out


def _fill_rows_at(mat, row_idx, data, off, lens):
    """mat[row_idx[i], :lens[i]] = data[off[i]:off[i+1]] for all i.

    ``data``/``off`` come from gather_runs, so the source is packed tight —
    this is :func:`utils.ragged.scatter_runs` over the flattened matrix."""
    from consensuscruncher_tpu.utils.ragged import scatter_runs

    scatter_runs(mat.reshape(-1), row_idx.astype(np.int64) * mat.shape[1],
                 data, lens)


def _modal_cigars(sources, srci, gidx, fam_off, mem_len, target, n_fam):
    """Per-family modal cigar words (core.consensus_read.modal_cigar
    semantics), COLUMNAR: returns ``(words, nwords, off)`` — one packed
    ``<u4`` array with per-family word counts/offsets in family order —
    instead of a per-family list (the list form cost one np.array + one
    .view per family, ~8% of warm SSCS stage wall at 10M reads).

    Vectorized all-candidates-equal fast path; exact Counter-of-strings
    fallback for the rare mixed families.  ``srci``/``gidx``: per member
    (family-contiguous order) the source index and original batch row.
    """
    from consensuscruncher_tpu.io.columnar import ragged_gather
    from consensuscruncher_tpu.utils.ragged import scatter_runs

    n = len(srci)
    sizes = np.diff(fam_off)
    target_rep = np.repeat(target, sizes)
    cand = mem_len == target_rep

    nc = np.empty(n, dtype=np.int64)
    cstart = np.empty(n, dtype=np.int64)
    for k, s in enumerate(sources):
        m = srci == k
        rows = gidx[m]
        nc[m] = s.batch.n_cigar[rows]
        cstart[m] = s.batch.cigar_start[rows]

    BIG = n + 1
    idx = np.where(cand, np.arange(n), BIG)
    first_cand = np.minimum.reduceat(idx, fam_off[:-1]) if n_fam else idx[:0]
    has_cand = first_cand < BIG
    fc = np.where(has_cand, first_cand, 0)

    def assemble(nwords, fill_all_eq):
        """Pack per-family words: no-cand families emit [target << 4]."""
        off = np.zeros(n_fam + 1, dtype=np.int64)
        np.cumsum(nwords, out=off[1:])
        words = np.zeros(int(off[-1]), dtype=np.uint32)
        no_cand = np.nonzero(~has_cand)[0]
        words[off[no_cand]] = (target[no_cand].astype(np.int64) << 4).astype(np.uint32)
        fill_all_eq(words, off)
        return words, nwords, off

    wmax = int(nc[cand].max(initial=0)) if n else 0
    if wmax == 0:
        nwords = np.where(has_cand, 0, 1).astype(np.int64)
        return assemble(nwords, lambda words, off: None)

    # candidate cigar byte matrix; non-candidates copy their family's first
    # candidate so they can never break the equality test
    fc_rep = np.repeat(fc, sizes)
    eff = np.where(cand, np.arange(n), fc_rep)
    W = 4 * wmax
    mat = np.zeros((n, W), dtype=np.uint8)
    lens = 4 * nc[eff]
    for k, s in enumerate(sources):
        m = srci[eff] == k
        rows = np.nonzero(m)[0]
        if rows.size:
            data, off2 = ragged_gather(s.batch.buf, cstart[eff][rows], lens[rows])
            _fill_rows_at(mat, rows, data, off2, lens[rows])

    eq = (mat == mat[fc_rep]).all(axis=1) & (nc[eff] == nc[fc_rep])
    all_eq = np.logical_and.reduceat(eq, fam_off[:-1]) if n_fam else eq[:0]

    fallback = np.nonzero(has_cand & ~all_eq)[0]
    fb_words: dict[int, np.ndarray] = {}
    for j in fallback:  # rare: mixed candidate cigars inside one family
        from collections import Counter

        from consensuscruncher_tpu.io.bam import cigar_from_string
        from consensuscruncher_tpu.io.encode import cigar_string_to_words

        counts = Counter(
            sources[int(srci[i])].batch.cigar_string(int(gidx[i]))
            for i in range(fam_off[j], fam_off[j + 1])
            if cand[i]
        )
        fb_words[int(j)] = cigar_string_to_words(
            cigar_from_string(counts.most_common(1)[0][0])
        )

    nwords = np.where(has_cand, nc[fc], 1).astype(np.int64)
    for j, w in fb_words.items():
        nwords[j] = len(w)

    def fill(words, off):
        vec = np.nonzero(has_cand & all_eq)[0]
        if vec.size:
            flat = np.ascontiguousarray(mat).view("<u4").reshape(n, wmax)
            # one ragged gather-scatter: family j's words are row fc[j]'s
            # first nwords[j] uint32s
            data, d_off = ragged_gather(flat.reshape(-1),
                                        fc[vec] * wmax, nwords[vec])
            scatter_runs(words, off[vec], data, nwords[vec])
        for j, w in fb_words.items():
            words[off[j] : off[j] + len(w)] = w

    return assemble(nwords, fill)


def _header_name_pool(header: BamHeader):
    """(ref_names, qnames ref-name pool) for a header, cached on the header
    instance — rebuilt-per-block encode+sort of every contig name is pure
    waste on many-contig references (GRCh38 ~3.4k, transcriptomes 100k+)."""
    cached = getattr(header, "_cct_name_pool", None)
    if cached is None:
        ref_names = [header.ref_name(i) for i in range(len(header.refs))]
        cached = (ref_names, qnames_mod.ref_name_pool(ref_names))
        header._cct_name_pool = cached
    return cached


def _build_block(sources: list[_BlockSrc], header: BamHeader) -> FamilyBlock:
    """Vectorized family construction over one or more row sources."""
    def col(fn):
        return np.concatenate([fn(s) for s in sources])

    rid = col(lambda s: s.batch.ref_id[s.rows])
    pos = col(lambda s: s.batch.pos[s.rows])
    mrid = col(lambda s: s.batch.mate_ref_id[s.rows])
    mpos = col(lambda s: s.batch.mate_pos[s.rows])
    flag = col(lambda s: s.batch.flag[s.rows])
    mapq = col(lambda s: s.batch.mapq[s.rows].astype(np.int64))
    tlen = col(lambda s: s.batch.tlen[s.rows])
    mstart = col(lambda s: s.codes_off[s.rows])
    mlen = col(lambda s: s.codes_off[s.rows + 1] - s.codes_off[s.rows])
    gidx = col(lambda s: s.rows)
    srci = np.repeat(
        np.arange(len(sources), dtype=np.int64), [len(s.rows) for s in sources]
    )
    bclen = np.concatenate([s.bclen for s in sources])
    wb = max((s.bcm.shape[1] for s in sources), default=0)
    n = len(rid)
    bcm = np.zeros((n, wb), dtype=np.uint8)
    row = 0
    for s in sources:
        bcm[row : row + len(s.rows), : s.bcm.shape[1]] = s.bcm
        row += len(s.rows)

    rn = np.where((flag & FREAD1) != 0, 1, 2).astype(np.int8)
    rev = ((flag & FREVERSE) != 0).astype(np.int8)

    keys = [rev, rn, mpos, mrid]
    keys += [bcm[:, j] for j in range(wb - 1, -1, -1)]
    keys += [pos, rid]
    order = np.lexsort(keys)

    def srt(a):
        return a[order]

    kb = bcm[order]
    same = np.ones(n, dtype=bool)
    if n > 1:
        same[1:] = (
            (kb[1:] == kb[:-1]).all(axis=1)
            & (srt(rid)[1:] == srt(rid)[:-1])
            & (srt(pos)[1:] == srt(pos)[:-1])
            & (srt(mrid)[1:] == srt(mrid)[:-1])
            & (srt(mpos)[1:] == srt(mpos)[:-1])
            & (srt(rn)[1:] == srt(rn)[:-1])
            & (srt(rev)[1:] == srt(rev)[:-1])
        )
    fam_start = np.nonzero(~same)[0]
    fam_off = np.concatenate([[0], fam_start, [n]]) if n else np.zeros(1, np.int64)
    sizes = np.diff(fam_off)
    n_fam = len(sizes)
    fam_ids = np.repeat(np.arange(n_fam), sizes)

    mem_len_s = srt(mlen)
    target = _modal_lengths(fam_ids, mem_len_s, n_fam)
    mapq_max = np.maximum.reduceat(srt(mapq), fam_off[:-1]) if n else srt(mapq)

    first = order[fam_off[:-1]]
    cig_words, cig_nwords, cig_src_off = _modal_cigars(
        sources, srt(srci), srt(gidx), fam_off, mem_len_s, target, n_fam
    )

    # emission order (rid, pos, str(tag)) — the object path's global order —
    # via the vectorized tag-string builder; no per-family Python
    ref_names, pool = _header_name_pool(header)
    frid, fpos = rid[first], pos[first]
    fmrid, fmpos = mrid[first], mpos[first]
    frn = rn[first].astype(np.int64)
    frev = rev[first].astype(bool)
    fbcm, fbclen = bcm[first], bclen[first].astype(np.int64)
    tag_data, tag_off = qnames_mod.tag_strings_columnar(
        fbcm, fbclen, frid, fpos, fmrid, fmpos, frn, frev, pool
    )
    perm_arr = qnames_mod.lexsort_strings(tag_data, tag_off, leaders=[frid, fpos])

    blk = FamilyBlock()
    blk._tags_cache = None
    blk.ref_names = ref_names
    blk.sizes = sizes[perm_arr]
    blk.target_len = target[perm_arr]
    blk.tmpl_flag = flag[first][perm_arr]
    blk.tmpl_rid = frid[perm_arr]
    blk.tmpl_pos = fpos[perm_arr]
    blk.tmpl_mrid = fmrid[perm_arr]
    blk.tmpl_mpos = fmpos[perm_arr]
    blk.tmpl_tlen = tlen[first][perm_arr]
    blk.mapq_max = mapq_max[perm_arr]
    blk.bcm = fbcm[perm_arr]
    blk.bclen = fbclen[perm_arr]
    blk.qname_data, blk.qname_off = qnames_mod.sscs_qnames_columnar(
        blk.bcm, blk.bclen, blk.tmpl_rid, blk.tmpl_pos, blk.tmpl_mrid,
        blk.tmpl_mpos, frn[perm_arr], frev[perm_arr], pool,
    )
    cig_lens = cig_nwords[perm_arr]
    blk.cigar_off = np.zeros(n_fam + 1, dtype=np.int64)
    np.cumsum(cig_lens, out=blk.cigar_off[1:])
    blk.cigar_data, _ = gather_runs(cig_words, cig_src_off[perm_arr], cig_lens)
    blk.src_chunk = srci[first][perm_arr]
    blk.src_row = gidx[first][perm_arr]
    blk.batches = [s.batch for s in sources]
    blk.data_chunks = [(s.codes_data, s.qual_data) for s in sources]
    # permute member geometry to emission order without per-family slicing:
    # rank families by perm, stable-argsort members by their family's rank
    fam_rank = np.empty(n_fam, dtype=np.int64)
    fam_rank[perm_arr] = np.arange(n_fam)
    msel = np.argsort(fam_rank[fam_ids], kind="stable")
    final = order[msel]
    blk.mem_start = mstart[final]
    blk.mem_len = mlen[final]
    blk.mem_chunk = srci[final].astype(np.int32)  # >256 carry sources is legal
    new_off = np.zeros(n_fam + 1, dtype=np.int64)
    np.cumsum(blk.sizes, out=new_off[1:])
    blk.fam_off = new_off
    return blk


def stream_family_blocks(
    creader,
    header: BamHeader,
    bdelim: str = tags_mod.DEFAULT_BDELIM,
) -> Iterator[tuple[str, object, object]]:
    """Block producer: ``("bad", read, reason)`` / ``("block", FamilyBlock,
    None)`` events with stream_families' grouping/order/filter semantics."""
    bdelim_byte = ord(bdelim)
    carry: list[_BlockSrc] = []
    carry_key: tuple[int, int] | None = None
    for batch in creader.batches():
        reason, last, bclen = _classify_batch(batch, bdelim_byte)
        bad = np.nonzero(reason != 0)[0]
        for i in bad:
            yield "bad", batch.materialize(int(i)), _BAD_REASONS[int(reason[i])]
        good = np.nonzero(reason == 0)[0]
        if good.size == 0:
            continue
        rid = batch.ref_id[good]
        pos = batch.pos[good]
        ok = (rid[1:] > rid[:-1]) | ((rid[1:] == rid[:-1]) & (pos[1:] >= pos[:-1]))
        if not ok.all():
            i = int(np.argmin(ok)) + 1
            read = batch.materialize(int(good[i]))
            raise NotCoordinateSorted(
                f"input BAM is not coordinate-sorted: {read.qname} at "
                f"{read.ref}:{read.pos} after ref_id={int(rid[i - 1])} "
                f"pos={int(pos[i - 1])} — run sort first"
            )
        first_key = (int(rid[0]), int(pos[0]))
        if carry_key is not None and first_key < carry_key:
            read = batch.materialize(int(good[0]))
            raise NotCoordinateSorted(
                f"input BAM is not coordinate-sorted: {read.qname} at "
                f"{read.ref}:{read.pos} after ref_id={carry_key[0]} "
                f"pos={carry_key[1]} — run sort first"
            )
        # barcode matrix for good rows
        qm = batch.qname_matrix
        w = qm.shape[1]
        wb = int(bclen[good].max(initial=0))
        cols = np.arange(wb, dtype=np.int64)
        src = last[good][:, None] + 1 + cols[None, :]
        valid = cols[None, :] < bclen[good][:, None]
        bcm = np.where(valid, qm[good[:, None], np.minimum(src, w - 1)], 0).astype(np.uint8)

        # defer the trailing coordinate (it may continue in the next batch)
        tail_mask = (rid == rid[-1]) & (pos == pos[-1])
        n_tail = int(tail_mask.sum())
        body_n = good.size - n_tail
        body_src = (
            _BlockSrc(batch, good[:body_n], bcm[:body_n], bclen[good[:body_n]])
            if body_n else None
        )
        tail_src = _BlockSrc(batch, good[body_n:], bcm[body_n:], bclen[good[body_n:]])

        if body_n:
            if carry and first_key == carry_key:
                # carry's coordinate continues into this batch's body
                yield "block", _build_block(carry + [body_src], header), None
            elif carry:
                yield "block", _build_block(carry, header), None
                yield "block", _build_block([body_src], header), None
            else:
                yield "block", _build_block([body_src], header), None
            carry = [tail_src]
        else:  # whole batch is one coordinate
            if carry and first_key == carry_key:
                carry.append(tail_src)
            else:
                if carry:
                    yield "block", _build_block(carry, header), None
                carry = [tail_src]
        carry_key = (int(rid[-1]), int(pos[-1]))
    if carry:
        yield "block", _build_block(carry, header), None


# --------------------------------------------------------- duplex pair blocks
#
# Vectorized DCS pairing: the per-read tag/dict/str walk of
# consensus_windows_columnar costs ~40 us/read; this producer pairs whole
# batches at once.  A read's duplex partner has the mirrored barcode, the
# flipped read number, and identical coordinates/orientation — so the
# CANONICAL key (lexicographic min of barcode and its mirror, read number
# flipped accordingly; palindromic barcodes normalize the read number to 1)
# is equal for exactly a tag and its partner.  One lexsort over
# (coordinate, canonical key) groups pairs; runs dedupe by full tag (dict
# last-wins semantics) and split into pairs / unpaired singles.  Emission
# order inside a coordinate window reproduces the object path's
# sorted-by-str(tag) walk exactly (pair order by the smaller member str,
# unpaired and length-mismatch reads interleaved by the same keys).


class PairBlock:
    """Pairing results for one batch of consensus reads.

    ``pair_*``: per pair in emission order — (source, row) of the
    canonical-strand read and its partner, the canonical barcode bytes
    (``pair_bcm``/``pair_bclen``), the prebuilt ``dcs_qname`` strings
    (``qname_data``/``qname_off``), and the combined family size.
    ``unpaired``: (source, row) in emission order.  ``sources``: the
    ColumnarBatches rows refer to.
    """

    __slots__ = ("sources", "pair_canon_src", "pair_canon_row",
                 "pair_other_src", "pair_other_row", "pair_bcm",
                 "pair_bclen", "qname_data", "qname_off", "pair_xf",
                 "unpaired_src", "unpaired_row", "stats_total",
                 "stats_unpaired", "stats_pairs", "stats_mismatch")

    @property
    def n_pairs(self) -> int:
        return len(self.pair_xf)


def _mirror_bcm(bcm: np.ndarray, bclen: np.ndarray):
    """Vectorized barcode mirror: ``"A.B" -> "B.A"`` per row (rows without
    a separator mirror to themselves, like tags_mod.mirror_barcode)."""
    n, w = bcm.shape
    sep_byte = ord(tags_mod.BARCODE_SEP)
    is_sep = bcm == sep_byte
    has = is_sep.any(axis=1)
    sep = np.where(has, np.argmax(is_sep, axis=1), bclen)  # first '.'
    rlen = np.where(has, bclen - sep - 1, 0)
    # mirror_barcode parity: no separator OR an empty right half ("AB.")
    # both mirror to themselves
    mirrors_self = ~has | (rlen == 0)
    cols = np.arange(w, dtype=np.int64)
    # output col j: j < rlen -> right half; j == rlen -> '.'; else left half
    src = np.where(
        cols[None, :] < rlen[:, None],
        sep[:, None] + 1 + cols[None, :],
        cols[None, :] - rlen[:, None] - 1,
    )
    out = np.take_along_axis(bcm, np.clip(src, 0, w - 1), axis=1)
    out[cols[None, :] == rlen[:, None]] = sep_byte
    out[cols[None, :] >= bclen[:, None]] = 0
    mirrored = np.where(mirrors_self[:, None], bcm, out)
    return mirrored


def duplex_pair_blocks(creader, header: BamHeader) -> Iterator[PairBlock]:
    """Yield one :class:`PairBlock` per columnar batch of a consensus BAM
    (trailing coordinate carried, exactly like the family-block producer).

    Requires every record's tag block to lead with XT:Z + XF:i (true for
    all BAMs this pipeline writes); the caller probes the first batch and
    falls back to the object path otherwise.
    """
    carry: list[tuple] | None = []
    carry_key = None
    for batch in creader.batches():
        ok, bc_start, bc_len, xf = _parse_xt_xf(batch)
        if not ok.all():
            raise ValueError("foreign tag layout (no XT/XF prefix)")
        n = batch.n
        rid, pos = batch.ref_id, batch.pos
        if n:
            sorted_ok = (rid[1:] > rid[:-1]) | ((rid[1:] == rid[:-1]) & (pos[1:] >= pos[:-1]))
            if not sorted_ok.all():
                i = int(np.argmin(sorted_ok)) + 1
                read = batch.materialize(i)
                raise NotCoordinateSorted(
                    f"consensus BAM is not coordinate-sorted: {read.qname} at "
                    f"{read.ref}:{read.pos}"
                )
        first_key = (int(rid[0]), int(pos[0])) if n else None
        if carry_key is not None and first_key is not None and first_key < carry_key:
            read = batch.materialize(0)
            raise NotCoordinateSorted(
                f"consensus BAM is not coordinate-sorted: {read.qname} at "
                f"{read.ref}:{read.pos} after ref_id={carry_key[0]} pos={carry_key[1]}"
            )
        # barcode matrix for the whole batch
        bcm = _barcode_matrix(batch.buf, bc_start, bc_len)

        rows = np.arange(n, dtype=np.int64)
        tail_mask = (rid == rid[-1]) & (pos == pos[-1]) if n else np.zeros(0, bool)
        n_tail = int(tail_mask.sum())
        body_n = n - n_tail
        src_new_body = (batch, rows[:body_n], bcm[:body_n], bc_len[:body_n], xf[:body_n])
        src_new_tail = (batch, rows[body_n:], bcm[body_n:], bc_len[body_n:], xf[body_n:])

        if body_n:
            if carry and first_key == carry_key:
                yield _build_pair_block(carry + [src_new_body], header)
            elif carry:
                yield _build_pair_block(carry, header)
                yield _build_pair_block([src_new_body], header)
            else:
                yield _build_pair_block([src_new_body], header)
            carry = [src_new_tail]
        else:
            if carry and first_key == carry_key:
                carry.append(src_new_tail)
            else:
                if carry:
                    yield _build_pair_block(carry, header)
                carry = [src_new_tail]
        if n:
            carry_key = (int(rid[-1]), int(pos[-1]))
    if carry:
        yield _build_pair_block(carry, header)


def _build_pair_block(sources: list[tuple], header: BamHeader) -> PairBlock:
    def col(fn):
        return np.concatenate([fn(s) for s in sources])

    batches = [s[0] for s in sources]
    rows_of = [s[1] for s in sources]
    rid = col(lambda s: s[0].ref_id[s[1]])
    pos = col(lambda s: s[0].pos[s[1]])
    mrid = col(lambda s: s[0].mate_ref_id[s[1]])
    mpos = col(lambda s: s[0].mate_pos[s[1]])
    flag = col(lambda s: s[0].flag[s[1]])
    lseq = col(lambda s: s[0].l_seq[s[1]])
    xf = col(lambda s: s[4])
    bclen = np.concatenate([s[3] for s in sources])
    wb = max((s[2].shape[1] for s in sources), default=0)
    n = len(rid)
    bcm = np.zeros((n, wb), dtype=np.uint8)
    r0 = 0
    for s in sources:
        bcm[r0 : r0 + len(s[1]), : s[2].shape[1]] = s[2]
        r0 += len(s[1])
    srci = np.repeat(np.arange(len(sources), dtype=np.int64),
                     [len(s[1]) for s in sources])
    grow = col(lambda s: s[1])

    rn = np.where((flag & FREAD1) != 0, 1, 2).astype(np.int8)
    rev = ((flag & FREVERSE) != 0).astype(np.int8)

    mirror = _mirror_bcm(bcm, bclen)
    a = np.ascontiguousarray(bcm).view(f"S{max(wb,1)}").ravel()
    b = np.ascontiguousarray(mirror).view(f"S{max(wb,1)}").ravel()
    bc_lt = a < b
    bc_eq = a == b
    canon_is_self = bc_lt | bc_eq
    canon_bcm = np.where(bc_lt[:, None] | bc_eq[:, None], bcm, mirror)
    canon_rn = np.where(bc_eq, 1, np.where(bc_lt, rn, 3 - rn)).astype(np.int8)

    keys = [rev, canon_rn, mpos, mrid]
    keys += [canon_bcm[:, j] for j in range(wb - 1, -1, -1)]
    keys += [pos, rid]
    order = np.lexsort(keys)

    def srt(arr):
        return arr[order]

    kb = canon_bcm[order]
    same = np.ones(n, dtype=bool)
    if n > 1:
        same[1:] = (
            (kb[1:] == kb[:-1]).all(axis=1)
            & (srt(rid)[1:] == srt(rid)[:-1])
            & (srt(pos)[1:] == srt(pos)[:-1])
            & (srt(mrid)[1:] == srt(mrid)[:-1])
            & (srt(mpos)[1:] == srt(mpos)[:-1])
            & (srt(canon_rn)[1:] == srt(canon_rn)[:-1])
            & (srt(rev)[1:] == srt(rev)[:-1])
        )
    # ---- vectorized run walk (the object semantics, no per-run Python) ----
    # run id per SORTED element; stable lexsort => within-run order is
    # stream order (srci, grow)
    run_id = np.cumsum(~same) if n else np.zeros(0, np.int64)
    n_runs = int(run_id[-1]) + 1 if n else 0
    rn_sorted = rn[order].astype(np.int64)

    # Dedupe by full tag, dict last-wins.  Within a run the full tag is
    # uniquely determined by the read number: non-palindromic barcodes put
    # rn == canon_rn on the canonical side and 3 - canon_rn on the mirror
    # side; palindromic barcodes have one bcm and rn in {1,2}.  So "last
    # stream occurrence of each (barcode, rn)" == "last sorted occurrence
    # of each (run, rn)".
    gk = run_id * 2 + (rn_sorted - 1)
    keep = np.ones(n, dtype=bool)
    if n > 1:
        s2 = np.lexsort((np.arange(n), gk))
        g = gk[s2]
        last = np.ones(n, dtype=bool)
        last[:-1] = g[1:] != g[:-1]
        keep = np.zeros(n, dtype=bool)
        keep[s2[last]] = True
    sidx = np.nonzero(keep)[0]            # sorted-domain survivor indices
    srun = run_id[sidx]                   # non-decreasing
    counts = np.bincount(srun, minlength=n_runs) if n_runs else np.zeros(0, np.int64)
    run_start = np.searchsorted(srun, np.arange(n_runs))
    stats_total = int(len(sidx))

    orig = order[sidx]                    # original-domain survivor rows
    _ref_names, pool = _header_name_pool(header)
    tag_data, tag_off = qnames_mod.tag_strings_columnar(
        bcm[orig], bclen[orig].astype(np.int64), rid[orig], pos[orig],
        mrid[orig], mpos[orig], rn[orig].astype(np.int64),
        rev[orig].astype(bool), pool,
    )
    tag_starts, tag_lens = tag_off[:-1], np.diff(tag_off)

    singles = np.nonzero(counts == 1)[0]
    doubles = np.nonzero(counts >= 2)[0]
    i_s = run_start[doubles]              # survivor slots of each candidate pair
    j_s = i_s + 1
    i_o, j_o = orig[i_s], orig[j_s]       # original-domain rows
    cmp = qnames_mod.compare_string_rows(
        tag_data, tag_starts[i_s], tag_lens[i_s], tag_starts[j_s], tag_lens[j_s]
    ) if len(doubles) else np.zeros(0, np.int8)
    i_first = cmp <= 0                    # str(tag_i) <= str(tag_j)
    mism = lseq[i_o] != lseq[j_o]

    # ---- unpaired events: single survivors (key: own str, tiebreak 0) and
    # both members of length-mismatched pairs (key: min str, tiebreak 0/1) —
    # merged and sorted by ((rid, pos), key_str, tiebreak), the walk's order
    mm = np.nonzero(mism)[0]
    first_slot = np.where(i_first[mm], i_s[mm], j_s[mm])
    second_slot = np.where(i_first[mm], j_s[mm], i_s[mm])
    up_slot = np.concatenate([run_start[singles], first_slot, second_slot])
    # sort-key string: own tag for singles, the pair's min str for both
    # mismatch members
    key_slot = np.concatenate([run_start[singles], first_slot, first_slot])
    up_k = np.concatenate([
        np.zeros(len(singles), np.int64),
        np.zeros(len(mm), np.int64),
        np.ones(len(mm), np.int64),
    ])
    up_orig = orig[up_slot]
    up_perm = qnames_mod.lexsort_string_refs(
        tag_data, tag_starts[key_slot], tag_lens[key_slot],
        leaders=[rid[up_orig], pos[up_orig]], trailers=[up_k],
    )
    up_rows = up_orig[up_perm]

    # ---- pair events: canonical member first, sorted by ((rid,pos), min str)
    ok = np.nonzero(~mism)[0]
    pi_s, pj_s = i_s[ok], j_s[ok]
    pi_o, pj_o = i_o[ok], j_o[ok]
    cs_i, cs_j = canon_is_self[pi_o], canon_is_self[pj_o]
    pick_i = np.where(cs_i & cs_j, i_first[ok], cs_i)
    canon_o = np.where(pick_i, pi_o, pj_o)
    other_o = np.where(pick_i, pj_o, pi_o)
    pkey_slot = np.where(i_first[ok], pi_s, pj_s)  # min-str member
    pair_perm = qnames_mod.lexsort_string_refs(
        tag_data, tag_starts[pkey_slot], tag_lens[pkey_slot],
        leaders=[rid[canon_o], pos[canon_o]],
    )
    canon_o, other_o = canon_o[pair_perm], other_o[pair_perm]
    pair_xf = (xf[pi_o] + xf[pj_o])[pair_perm].astype(np.int64)

    blk = PairBlock()
    blk.sources = batches
    blk.pair_canon_src = srci[canon_o]
    blk.pair_canon_row = grow[canon_o]
    blk.pair_other_src = srci[other_o]
    blk.pair_other_row = grow[other_o]
    blk.pair_xf = pair_xf
    # canonical barcode + prebuilt dcs qnames (emission order) for the
    # columnar record writer
    blk.pair_bcm = canon_bcm[canon_o]
    blk.pair_bclen = bclen[canon_o].astype(np.int64)
    blk.qname_data, blk.qname_off = qnames_mod.dcs_qnames_columnar(
        blk.pair_bcm, blk.pair_bclen, rid[canon_o], pos[canon_o],
        mrid[canon_o], mpos[canon_o], pool,
    )
    blk.unpaired_src = srci[up_rows]
    blk.unpaired_row = grow[up_rows]
    blk.stats_total = stats_total
    blk.stats_unpaired = int(len(up_rows))
    blk.stats_pairs = int(len(canon_o))
    blk.stats_mismatch = int(mism.sum())
    return blk


# ---------------------------------------------------------------------------
#
# Vectorized singleton rescue (stages/singleton_correction.py's exact-match
# path).  Mirrors the object window-walk's pinned semantics — including its
# order-dependent quirks — as array passes over canonical duplex-key runs
# spanning BOTH inputs (the singleton BAM and the SSCS BAM):
#
# Within one canonical-key run (same coords/orientation, barcode == or
# mirror-of, read number on either side) at most four distinct members can
# exist — {singleton, SSCS} x {read number 1, 2} — because a full tag is
# either a >=2 family (SSCS) or a size-1 family (singleton), never both.
# The walk processes singletons in sorted-str order per (ref, pos) window:
#   1. partner = mirrored tag in the SSCS dict, else the singleton dict
#      (not itself, not already consumed); no partner -> remaining.
#   2. unequal read lengths -> remaining (+length_mismatch), partner NOT
#      consumed.
#   3. SSCS rescue writes the corrected singleton; singleton-singleton
#      rescue writes BOTH corrected reads and consumes the partner.
# Order-dependent quirk reproduced deliberately: when the second-processed
# singleton of a mutual pair was NOT consumed (because the first took an
# SSCS partner), it can re-rescue the first against itself — double-writing
# the first read.  The emitted categories below encode exactly that table.


class RescueBlock:
    """Rescue decisions for one coordinate-complete slab of both inputs.

    ``sources``: ColumnarBatches referenced by (src, row) pairs.
    ``remaining_*``: uncorrected singletons, raw-blob passthrough order.
    ``rescue_*`` (parallel arrays, emission order): the read to correct,
    its vote partner, and the route — 0 = SSCS rescue, 1 = singleton-
    singleton.  Stats fields mirror the object walk's counters.
    """

    __slots__ = ("sources", "remaining_src", "remaining_row",
                 "rescue_src", "rescue_row", "partner_src", "partner_row",
                 "rescue_route", "partner_xf", "stats_total", "stats_sscs",
                 "stats_singleton", "stats_remaining", "stats_mismatch")


def _barcode_matrix(buf: np.ndarray, bc_start: np.ndarray, bc_len: np.ndarray) -> np.ndarray:
    """``(n, max(bc_len))`` zero-padded barcode byte matrix (clamped gather)
    — shared by the duplex-pair and rescue block builders."""
    wb = int(bc_len.max(initial=0))
    cols = np.arange(wb, dtype=np.int64)
    idx = bc_start[:, None] + cols[None, :]
    return np.where(
        cols[None, :] < bc_len[:, None],
        buf[np.minimum(idx, len(buf) - 1)], 0,
    ).astype(np.uint8)


def _rescue_src_prep(batch) -> tuple:
    """(rows, bcm, bclen, xf) of the XT/XF-parsed rows of a batch."""
    ok, bc_start, bc_len, xf = _parse_xt_xf(batch)
    if not ok.all():
        raise ValueError("foreign tag layout (no XT/XF prefix)")
    n = batch.n
    bcm = _barcode_matrix(batch.buf, bc_start, bc_len)
    return np.arange(n, dtype=np.int64), bcm, bc_len.astype(np.int64), xf.astype(np.int64)


def singleton_rescue_blocks(s_creader, x_creader, header: BamHeader) -> Iterator[RescueBlock]:
    """Yield :class:`RescueBlock`s over the singleton BAM (``s``) and the
    SSCS BAM (``x``), pulling batches from both in coordinate lockstep so
    every (ref, pos) anchor is complete within one block."""
    def batches_with_meta(creader, srctype):
        prev_key = None
        for batch in creader.batches():
            rid, pos = batch.ref_id, batch.pos
            if batch.n:
                sorted_ok = (rid[1:] > rid[:-1]) | ((rid[1:] == rid[:-1]) & (pos[1:] >= pos[:-1]))
                first_key = (int(rid[0]), int(pos[0]))
                if not sorted_ok.all() or (prev_key is not None and first_key < prev_key):
                    i = int(np.argmin(sorted_ok)) + 1 if not sorted_ok.all() else 0
                    read = batch.materialize(i)
                    raise NotCoordinateSorted(
                        f"input BAM is not coordinate-sorted: {read.qname} at "
                        f"{read.ref}:{read.pos}"
                    )
                prev_key = (int(rid[-1]), int(pos[-1]))
            yield srctype, batch

    streams = [batches_with_meta(s_creader, 1), batches_with_meta(x_creader, 0)]
    heads: list = [next(st, None) for st in streams]
    # carry: list of (srctype, batch, rows, bcm, bclen, xf) with rows >= the
    # emitted boundary
    carry: list[tuple] = []

    def last_key(item):
        _t, b = item
        return (int(b.ref_id[-1]), int(b.pos[-1])) if b.n else None

    while heads[0] is not None or heads[1] is not None:
        # take every stream whose current batch is present; boundary = the
        # smallest last-key among them (keys >= boundary may continue)
        live = [h for h in heads if h is not None]
        bkeys = [k for k in (last_key(h) for h in live) if k is not None]
        boundary = min(bkeys) if bkeys else None
        pieces = list(carry)
        carry = []
        for si in (0, 1):
            h = heads[si]
            if h is None:
                continue
            srctype, batch = h
            if batch.n:
                rows, bcm, bclen, xf = _rescue_src_prep(batch)
                pieces.append((srctype, batch, rows, bcm, bclen, xf))
            heads[si] = next(streams[si], None)
        done_streams = heads[0] is None and heads[1] is None
        emit_pieces: list[tuple] = []
        for srctype, batch, rows, bcm, bclen, xf in pieces:
            if done_streams or boundary is None:
                emit_pieces.append((srctype, batch, rows, bcm, bclen, xf))
                continue
            key_ge = (batch.ref_id[rows] > boundary[0]) | (
                (batch.ref_id[rows] == boundary[0]) & (batch.pos[rows] >= boundary[1])
            )
            cut = int(np.argmax(key_ge)) if key_ge.any() else len(rows)
            if cut:
                emit_pieces.append((srctype, batch, rows[:cut], bcm[:cut], bclen[:cut], xf[:cut]))
            if cut < len(rows):
                carry.append((srctype, batch, rows[cut:], bcm[cut:], bclen[cut:], xf[cut:]))
        if emit_pieces:
            yield _build_rescue_block(emit_pieces, header)
    if carry:
        yield _build_rescue_block(carry, header)


def _build_rescue_block(pieces: list[tuple], header: BamHeader) -> RescueBlock:
    def col(fn):
        return np.concatenate([fn(p) for p in pieces])

    batches = [p[1] for p in pieces]
    srct = np.concatenate([
        np.full(len(p[2]), p[0], dtype=np.int8) for p in pieces
    ])
    rid = col(lambda p: p[1].ref_id[p[2]])
    pos = col(lambda p: p[1].pos[p[2]])
    mrid = col(lambda p: p[1].mate_ref_id[p[2]])
    mpos = col(lambda p: p[1].mate_pos[p[2]])
    flag = col(lambda p: p[1].flag[p[2]])
    lseq = col(lambda p: p[1].l_seq[p[2]])
    xf = np.concatenate([p[5] for p in pieces])
    bclen = np.concatenate([p[4] for p in pieces])
    grow = col(lambda p: p[2])
    srci = np.repeat(np.arange(len(pieces), dtype=np.int64),
                     [len(p[2]) for p in pieces])
    wb = max((p[3].shape[1] for p in pieces), default=0)
    n = len(rid)
    bcm = np.zeros((n, wb), dtype=np.uint8)
    r0 = 0
    for p in pieces:
        bcm[r0 : r0 + len(p[2]), : p[3].shape[1]] = p[3]
        r0 += len(p[2])

    rn = np.where((flag & FREAD1) != 0, 1, 2).astype(np.int8)
    rev = ((flag & FREVERSE) != 0).astype(np.int8)
    mirror = _mirror_bcm(bcm, bclen)
    a = np.ascontiguousarray(bcm).view(f"S{max(wb, 1)}").ravel()
    b = np.ascontiguousarray(mirror).view(f"S{max(wb, 1)}").ravel()
    bc_lt, bc_eq = a < b, a == b
    canon_bcm = np.where((bc_lt | bc_eq)[:, None], bcm, mirror)
    canon_rn = np.where(bc_eq, 1, np.where(bc_lt, rn, 3 - rn)).astype(np.int8)

    keys = [rev, canon_rn, mpos, mrid]
    keys += [canon_bcm[:, j] for j in range(wb - 1, -1, -1)]
    keys += [pos, rid]
    order = np.lexsort(keys)

    def srt(arr):
        return arr[order]

    kb = canon_bcm[order]
    same = np.ones(n, dtype=bool)
    if n > 1:
        same[1:] = (
            (kb[1:] == kb[:-1]).all(axis=1)
            & (srt(rid)[1:] == srt(rid)[:-1])
            & (srt(pos)[1:] == srt(pos)[:-1])
            & (srt(mrid)[1:] == srt(mrid)[:-1])
            & (srt(mpos)[1:] == srt(mpos)[:-1])
            & (srt(canon_rn)[1:] == srt(canon_rn)[:-1])
            & (srt(rev)[1:] == srt(rev)[:-1])
        )
    run_id = np.cumsum(~same) if n else np.zeros(0, np.int64)
    n_runs = int(run_id[-1]) + 1 if n else 0

    # slot per (srctype, rn): last stream occurrence wins (window-dict
    # last-wins semantics; duplicates are impossible for pipeline outputs)
    orig = order  # sorted-domain -> original-domain
    slot = np.full((4, n_runs), -1, dtype=np.int64)
    sl_of = (srct[orig].astype(np.int64) * 2 + (rn[orig].astype(np.int64) - 1))
    slot[sl_of, run_id] = orig
    x1, x2, s1, s2 = slot[0], slot[1], slot[2], slot[3]

    # tag strings for singleton members (the walk's processing order)
    _ref_names, pool = _header_name_pool(header)
    sing_members = np.concatenate([s1[s1 >= 0], s2[s2 >= 0]])
    tag_pos = np.full(n, -1, dtype=np.int64)
    tag_pos[sing_members] = np.arange(len(sing_members))
    if len(sing_members):
        tag_data, tag_off = qnames_mod.tag_strings_columnar(
            bcm[sing_members], bclen[sing_members], rid[sing_members],
            pos[sing_members], mrid[sing_members], mpos[sing_members],
            rn[sing_members].astype(np.int64), rev[sing_members].astype(bool),
            pool,
        )
        tag_starts, tag_lens = tag_off[:-1], np.diff(tag_off)
    else:
        tag_data = np.empty(0, np.uint8)
        tag_starts = tag_lens = np.empty(0, np.int64)

    # ---- decision table over runs ----
    p_s1, p_s2 = s1 >= 0, s2 >= 0
    p_x1, p_x2 = x1 >= 0, x2 >= 0
    L = np.zeros(n + 1, dtype=np.int64)
    L[:n] = lseq
    lx1, lx2 = L[x1], L[x2]
    ls1, ls2 = L[s1], L[s2]

    # events: (order_key, read, partner, route) collected per category then
    # emission-sorted.  route: 0 sscs, 1 singleton.  remaining: (order_key,
    # read).  order keys reproduce the walk: windows ascend (rid,pos), then
    # sorted-str of the PROCESSED singleton's tag; a singleton-pair write
    # emits corrected self then corrected partner adjacently.
    rescue_read: list[np.ndarray] = []
    rescue_partner: list[np.ndarray] = []
    rescue_route: list[np.ndarray] = []
    rescue_key: list[np.ndarray] = []     # (member whose str orders the event)
    rescue_sub: list[np.ndarray] = []     # intra-event sequence (0 self, 1 partner)
    remaining: list[np.ndarray] = []
    remaining_key: list[np.ndarray] = []
    n_mismatch = 0
    n_pair_events = 0
    n_pair_c = 0  # pairs whose partner was already processed (case c)

    def cmp_str(mem_a, mem_b):
        return qnames_mod.compare_string_rows(
            tag_data,
            tag_starts[tag_pos[mem_a]], tag_lens[tag_pos[mem_a]],
            tag_starts[tag_pos[mem_b]], tag_lens[tag_pos[mem_b]],
        )

    # -- runs with exactly one singleton --
    for s_slot, x_m, l_s, l_xm, has_s, has_other_s in (
        (s1, x2, ls1, lx2, p_s1, p_s2),
        (s2, x1, ls2, lx1, p_s2, p_s1),
    ):
        only = has_s & ~has_other_s
        xm_p = only & (x_m >= 0)
        ok_len = xm_p & (l_s == l_xm)
        rescue_read.append(s_slot[ok_len])
        rescue_partner.append(x_m[ok_len])
        rescue_route.append(np.zeros(int(ok_len.sum()), np.int8))
        rescue_key.append(s_slot[ok_len])
        rescue_sub.append(np.zeros(int(ok_len.sum()), np.int8))
        mm = xm_p & (l_s != l_xm)
        n_mismatch += int(mm.sum())
        rem = only & ((~xm_p) | mm)
        remaining.append(s_slot[rem])
        remaining_key.append(s_slot[rem])

    # -- runs with both singletons --
    both = p_s1 & p_s2
    bi = np.nonzero(both)[0]
    if len(bi):
        c = cmp_str(s1[bi], s2[bi]) <= 0
        first = np.where(c, s1[bi], s2[bi])
        second = np.where(c, s2[bi], s1[bi])
        # mirror sscs of a singleton with read number r is slot x[3-r]
        fx = np.where(c, x2[bi], x1[bi])
        sx = np.where(c, x1[bi], x2[bi])
        lf, lsec = L[first], L[second]
        lfx, lsx = L[fx], L[sx]

        f_has_x = fx >= 0
        A = f_has_x & (lf == lfx)          # first sscs-rescued
        B = f_has_x & (lf != lfx)          # first mismatch-remaining
        CD = ~f_has_x
        C = CD & (lf == lsec)              # singleton pair; second consumed
        D = CD & (lf != lsec)              # first mismatch-remaining

        rescue_read.append(first[A])
        rescue_partner.append(fx[A])
        rescue_route.append(np.zeros(int(A.sum()), np.int8))
        rescue_key.append(first[A])
        rescue_sub.append(np.zeros(int(A.sum()), np.int8))
        n_mismatch += int(B.sum()) + int(D.sum())
        remaining.append(first[B | D])
        remaining_key.append(first[B | D])
        # case C: corrected(first vs second) + corrected(second vs first),
        # ordered by first's str
        for sub, rd, pt in ((0, first, second), (1, second, first)):
            rescue_read.append(rd[C])
            rescue_partner.append(pt[C])
            rescue_route.append(np.ones(int(C.sum()), np.int8))
            rescue_key.append(first[C])
            rescue_sub.append(np.full(int(C.sum()), sub, np.int8))
        n_pair_events += int(C.sum())

        # step 2: second processes unless case C consumed it
        live = ~C
        s_has_x = live & (sx >= 0)
        a_m = s_has_x & (lsec == lsx)
        rescue_read.append(second[a_m])
        rescue_partner.append(sx[a_m])
        rescue_route.append(np.zeros(int(a_m.sum()), np.int8))
        rescue_key.append(second[a_m])
        rescue_sub.append(np.zeros(int(a_m.sum()), np.int8))
        b_m = s_has_x & (lsec != lsx)
        c_m = live & (sx < 0) & (lsec == lf)   # pairs with already-processed first
        d_m = live & (sx < 0) & (lsec != lf)
        n_mismatch += int(b_m.sum()) + int(d_m.sum())
        remaining.append(second[b_m | d_m])
        remaining_key.append(second[b_m | d_m])
        for sub, rd, pt in ((0, second, first), (1, first, second)):
            rescue_read.append(rd[c_m])
            rescue_partner.append(pt[c_m])
            rescue_route.append(np.ones(int(c_m.sum()), np.int8))
            rescue_key.append(second[c_m])
            rescue_sub.append(np.full(int(c_m.sum()), sub, np.int8))
        n_pair_events += int(c_m.sum())
        n_pair_c += int(c_m.sum())

    def emission_order(keys_members, subs=None):
        """Sort events by (rid, pos, str(key member), sub)."""
        if len(keys_members) == 0:
            return np.empty(0, np.int64)
        trail = [subs] if subs is not None else None
        return qnames_mod.lexsort_string_refs(
            tag_data,
            tag_starts[tag_pos[keys_members]], tag_lens[tag_pos[keys_members]],
            leaders=[rid[keys_members], pos[keys_members]],
            trailers=trail,
        )

    blk = RescueBlock()
    blk.sources = batches
    if rescue_read:
        rr = np.concatenate(rescue_read)
        rp = np.concatenate(rescue_partner)
        rt = np.concatenate(rescue_route)
        rk = np.concatenate(rescue_key)
        rs = np.concatenate(rescue_sub)
        perm = emission_order(rk, rs)
        rr, rp, rt = rr[perm], rp[perm], rt[perm]
    else:
        rr = rp = np.empty(0, np.int64)
        rt = np.empty(0, np.int8)
    blk.rescue_src = srci[rr] if len(rr) else np.empty(0, np.int64)
    blk.rescue_row = grow[rr] if len(rr) else np.empty(0, np.int64)
    blk.partner_src = srci[rp] if len(rp) else np.empty(0, np.int64)
    blk.partner_row = grow[rp] if len(rp) else np.empty(0, np.int64)
    blk.rescue_route = rt
    # the XR tag derives from the PARTNER's family size (object rule:
    # XF > 1 -> "sscs"), not from the route
    blk.partner_xf = xf[rp] if len(rp) else np.empty(0, np.int64)
    if remaining:
        rm = np.concatenate(remaining)
        rmk = np.concatenate(remaining_key)
        perm = emission_order(rmk)
        rm = rm[perm]
    else:
        rm = np.empty(0, np.int64)
    blk.remaining_src = srci[rm] if len(rm) else np.empty(0, np.int64)
    blk.remaining_row = grow[rm] if len(rm) else np.empty(0, np.int64)
    n_singles = int(len(sing_members))
    blk.stats_total = n_singles + n_pair_c
    blk.stats_sscs = int((rt == 0).sum())
    blk.stats_singleton = int((rt == 1).sum())
    blk.stats_remaining = int(len(rm))
    blk.stats_mismatch = n_mismatch
    return blk
