"""Streaming UMI-family grouping from a coordinate-sorted BAM.

Reference parity: ``ConsensusCruncher/consensus_helper.py:read_bam`` (SURVEY.md
§3.2), which fills whole-chromosome ``tag -> [reads]`` dicts.  Rebuilt as a
**position-windowed stream**: every member of a family shares the read's own
``(ref, pos)`` (that pair is part of the family key), so once the sorted
stream advances past a position, all families anchored there are complete and
can be flushed.  Memory is bounded by one position window instead of one
chromosome, and no BAI index / per-region ``fetch`` is needed at all.

Read filtering (pinned; reference routes these to a "badRead" BAM):
unmapped, mate-unmapped, secondary, supplementary, QC-fail reads, and reads
whose qname carries no barcode delimiter.  Duplicate-flagged reads are kept —
UMI consensus is itself the deduplicator.
"""

from __future__ import annotations

from typing import Iterable, Iterator

from consensuscruncher_tpu.core import tags as tags_mod
from consensuscruncher_tpu.io.bam import BamHeader, BamRead


class NotCoordinateSorted(ValueError):
    pass


def derive_tag(read):
    """Reconstruct a consensus read's family tag (coords/flags + XT barcode).

    Consensus/singleton reads written by the SSCS stage carry their barcode
    in ``XT``; everything else in the family key lives on the read itself.
    """
    if "XT" not in read.tags:
        raise ValueError(f"consensus read {read.qname} lacks the XT barcode tag")
    return tags_mod.unique_tag(read, read.tags["XT"][1])


def consensus_windows(reader):
    """Group a coordinate-sorted consensus BAM into per-(ref,pos) windows.

    Yields ``(key, {FamilyTag: read})`` with ``key = (ref_id, pos)``.  Shared
    by the DCS and singleton-correction stages (their pairing partners always
    share the anchor position).  Raises :class:`NotCoordinateSorted` on
    order violations — silent mispairing on unsorted input would complete
    "successfully" with everything unpaired.
    """
    window: dict = {}
    cur = None
    for read in reader:
        tag = derive_tag(read)
        key = (reader.header.ref_id(read.ref), read.pos)
        if cur is not None and key < cur:
            raise NotCoordinateSorted(
                f"consensus BAM is not coordinate-sorted: {read.qname} at "
                f"{read.ref}:{read.pos} after ref_id={cur[0]} pos={cur[1]}"
            )
        if cur is not None and key != cur:
            yield cur, window
            window = {}
        cur = key
        window[tag] = read
    if window:
        yield cur, window


def classify_bad(read: BamRead, bdelim: str) -> str | None:
    """Reason string if the read must be routed to the badRead BAM, else None."""
    if read.is_unmapped:
        return "unmapped"
    if not read.is_paired or read.mate_is_unmapped:
        return "mate_unmapped"
    if read.is_secondary:
        return "secondary"
    if read.is_supplementary:
        return "supplementary"
    if read.is_qcfail:
        return "qcfail"
    try:
        tags_mod.barcode_from_qname(read.qname, bdelim)
    except ValueError:
        return "no_barcode"
    return None


def stream_families(
    reads: Iterable[BamRead],
    header: BamHeader,
    bdelim: str = tags_mod.DEFAULT_BDELIM,
) -> Iterator[tuple[str, object, object]]:
    """Yield ``("bad", read, reason)`` and ``("family", tag, [reads])`` events.

    Families are emitted as soon as the sorted stream passes their anchor
    position (deterministic order: by position, then tag string).  Raises
    :class:`NotCoordinateSorted` if the input violates coordinate order.
    """
    pending: dict[tags_mod.FamilyTag, list[BamRead]] = {}
    cur: tuple[int, int] | None = None  # (ref_id, pos) high-water mark

    def flush() -> Iterator[tuple[str, object, object]]:
        for tag in sorted(pending, key=lambda t: (t.pos, str(t))):
            yield "family", tag, pending[tag]
        pending.clear()

    for read in reads:
        reason = classify_bad(read, bdelim)
        if reason is not None:
            yield "bad", read, reason
            continue
        key = (header.ref_id(read.ref), read.pos)
        if cur is not None and key < cur:
            raise NotCoordinateSorted(
                f"input BAM is not coordinate-sorted: {read.qname} at {read.ref}:{read.pos} "
                f"after ref_id={cur[0]} pos={cur[1]} — run sort first"
            )
        if cur is not None and key != cur:
            yield from flush()
        cur = key
        barcode = tags_mod.barcode_from_qname(read.qname, bdelim)
        tag = tags_mod.unique_tag(read, barcode)
        pending.setdefault(tag, []).append(read)
    yield from flush()
