"""Plot generation from stage stats files.

Reference parity: ``ConsensusCruncher/generate_plots.py`` (SURVEY.md §2) —
matplotlib PNGs of the family-size distribution and read-recovery summary,
read back from the stats files on disk (not from memory, so plots can be
regenerated standalone, exactly like the reference).
"""

from __future__ import annotations

import json
import os

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

from consensuscruncher_tpu.utils.stats import FamilySizeHistogram  # noqa: E402


def plot_family_size(read_families_txt: str, out_png: str) -> None:
    counts = FamilySizeHistogram.read(read_families_txt)
    sizes = sorted(counts)
    fig, ax = plt.subplots(figsize=(7, 4.5))
    ax.bar(sizes, [counts[s] for s in sizes], color="#4477aa")
    ax.set_xlabel("UMI family size")
    ax.set_ylabel("families")
    ax.set_yscale("log")
    ax.set_title("UMI family-size distribution")
    fig.tight_layout()
    fig.savefig(out_png, dpi=120)
    plt.close(fig)


def plot_read_recovery(stats_jsons: list[str], out_png: str) -> None:
    labels, values = [], []
    for path in stats_jsons:
        with open(path) as fh:
            data = json.load(fh)
        stage = data.pop("stage", os.path.basename(path))
        for key in ("sscs_written", "singletons", "dcs_written", "rescued_by_sscs",
                    "rescued_by_singleton", "remaining", "bad_reads"):
            if key in data:
                labels.append(f"{stage}:{key}")
                values.append(data[key])
    fig, ax = plt.subplots(figsize=(8, 4.5))
    ax.barh(labels, values, color="#66ccee")
    ax.set_xlabel("reads")
    ax.set_title("read recovery by stage")
    fig.tight_layout()
    fig.savefig(out_png, dpi=120)
    plt.close(fig)


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description="Generate stats plots")
    p.add_argument("--families", help="read_families.txt path")
    p.add_argument("--stats", nargs="*", default=[], help="stage *_stats.json paths")
    p.add_argument("--outdir", required=True)
    args = p.parse_args(argv)
    os.makedirs(args.outdir, exist_ok=True)
    if args.families:
        plot_family_size(args.families, os.path.join(args.outdir, "family_size.png"))
    if args.stats:
        plot_read_recovery(args.stats, os.path.join(args.outdir, "read_recovery.png"))


if __name__ == "__main__":
    main()
