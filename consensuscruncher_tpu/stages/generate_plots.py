"""Plot generation from stage stats files.

Reference parity: ``ConsensusCruncher/generate_plots.py`` (SURVEY.md §2) —
matplotlib PNGs read back from the stats files on disk (not from memory, so
plots can be regenerated standalone, exactly like the reference).  The
reference's exact plot set is unverifiable against the empty mount; this
module pins a superset of what its stats files can express:

- ``family_size.png``   families per size AND reads per size (two panels —
  the read-weighted view is what shows where the sequencing depth went),
  plus the cumulative read fraction by family size.
- ``read_recovery.png`` pipeline-ordered read-accounting funnel across all
  stage stats files.
- ``stage_times.png``   per-stage wall-clock from ``*.metrics.json``
  (framework-native observability; no reference counterpart).

Design notes: every chart encodes one magnitude, so each uses a single hue
(no categorical cycling); values are direct-labeled where the bar count is
small; log scales are labeled explicitly.
"""

from __future__ import annotations

import json
import os

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

from consensuscruncher_tpu.utils.stats import FamilySizeHistogram  # noqa: E402

# Single sequential hue (all plots encode magnitude) + neutral accents.
_BAR = "#4477aa"
_ACCENT = "#b0b7c3"


def plot_family_size(read_families_txt: str, out_png: str) -> None:
    counts = FamilySizeHistogram.read(read_families_txt)
    sizes = sorted(counts)
    fams = [counts[s] for s in sizes]
    reads = [s * counts[s] for s in sizes]
    total_reads = sum(reads) or 1

    fig, (ax1, ax2, ax3) = plt.subplots(
        3, 1, figsize=(7.5, 8.5), sharex=True,
        gridspec_kw={"height_ratios": [3, 3, 2]},
    )
    ax1.bar(sizes, fams, color=_BAR)
    ax1.set_ylabel("families (log)")
    ax1.set_yscale("log")
    ax1.set_title("UMI family-size distribution")

    ax2.bar(sizes, reads, color=_BAR)
    ax2.set_ylabel("reads (log)")
    ax2.set_yscale("log")

    cum = []
    acc = 0
    for r in reads:
        acc += r
        cum.append(acc / total_reads)
    ax3.plot(sizes, cum, color=_BAR, linewidth=2)
    ax3.set_ylim(0, 1.02)
    ax3.set_ylabel("cum. read fraction")
    ax3.set_xlabel("UMI family size")
    ax3.grid(True, alpha=0.3)

    for ax in (ax1, ax2, ax3):
        ax.spines[["top", "right"]].set_visible(False)
    fig.tight_layout()
    fig.savefig(out_png, dpi=120)
    plt.close(fig)


# Pipeline-ordered read-accounting keys (stage, key, human label).
_RECOVERY_KEYS = (
    ("SSCS", "total_reads", "input reads"),
    ("SSCS", "bad_reads", "bad reads"),
    ("SSCS", "families", "UMI families"),
    ("SSCS", "sscs_written", "SSCS consensus"),
    ("SSCS", "singletons", "singletons"),
    ("singleton_correction", "rescued_by_sscs", "rescued by SSCS"),
    ("singleton_correction", "rescued_by_singleton", "rescued by singleton"),
    ("singleton_correction", "remaining", "unrescued singletons"),
    ("DCS", "pairs", "duplex pairs"),
    ("DCS", "dcs_written", "DCS consensus"),
    ("DCS", "sscs_unpaired", "unpaired SSCS"),
)


def plot_read_recovery(stats_jsons: list[str], out_png: str) -> None:
    by_stage: dict[str, dict] = {}
    for path in stats_jsons:
        with open(path) as fh:
            data = json.load(fh)
        by_stage[data.get("stage", os.path.basename(path))] = data

    labels, values = [], []
    for stage, key, label in _RECOVERY_KEYS:
        data = by_stage.get(stage)
        if data is not None and key in data:
            labels.append(label)
            values.append(data[key])
    if not labels:  # nothing recognizable: fall back to every numeric key
        for stage, data in by_stage.items():
            for key, val in data.items():
                if isinstance(val, (int, float)) and key != "stage":
                    labels.append(f"{stage}:{key}")
                    values.append(val)

    fig, ax = plt.subplots(figsize=(8, 0.45 * len(labels) + 1.8))
    y = range(len(labels))[::-1]  # pipeline order top-to-bottom
    ax.barh(list(y), values, color=_BAR)
    ax.set_yticks(list(y), labels)
    vmax = max(values) if values else 1
    for yi, v in zip(y, values):
        ax.text(v + vmax * 0.01, yi, f"{v:,}", va="center", fontsize=8)
    ax.set_xlim(0, vmax * 1.12)
    ax.set_xlabel("count")
    ax.set_title("read recovery by stage")
    ax.spines[["top", "right"]].set_visible(False)
    fig.tight_layout()
    fig.savefig(out_png, dpi=120)
    plt.close(fig)


def plot_stage_times(metrics_jsons: list[str], out_png: str) -> None:
    """Per-stage wall-clock breakdown from ``*.metrics.json`` files."""
    labels, values = [], []
    for path in metrics_jsons:
        if not os.path.exists(path):
            continue
        with open(path) as fh:
            data = json.load(fh)
        stage = data.get("stage", os.path.basename(path))
        for name, seconds in data.get("phases_s", {}).items():
            labels.append(f"{stage}: {name}")
            values.append(seconds)
    if not labels:
        return
    fig, ax = plt.subplots(figsize=(8, 0.45 * len(labels) + 1.8))
    y = range(len(labels))[::-1]
    ax.barh(list(y), values, color=_BAR)
    ax.set_yticks(list(y), labels)
    vmax = max(values)
    for yi, v in zip(y, values):
        ax.text(v + vmax * 0.01, yi, f"{v:.2f}s", va="center", fontsize=8)
    ax.set_xlim(0, vmax * 1.14)
    ax.set_xlabel("wall-clock seconds")
    ax.set_title("stage timing")
    ax.spines[["top", "right"]].set_visible(False)
    fig.tight_layout()
    fig.savefig(out_png, dpi=120)
    plt.close(fig)


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description="Generate stats plots")
    p.add_argument("--families", help="read_families.txt path")
    p.add_argument("--stats", nargs="*", default=[], help="stage *_stats.json paths")
    p.add_argument("--metrics", nargs="*", default=[], help="stage *.metrics.json paths")
    p.add_argument("--outdir", required=True)
    args = p.parse_args(argv)
    os.makedirs(args.outdir, exist_ok=True)
    if args.families:
        plot_family_size(args.families, os.path.join(args.outdir, "family_size.png"))
    if args.stats:
        plot_read_recovery(args.stats, os.path.join(args.outdir, "read_recovery.png"))
    if args.metrics:
        plot_stage_times(args.metrics, os.path.join(args.outdir, "stage_times.png"))


if __name__ == "__main__":
    main()
