"""DCS stage: pair complementary-strand SSCSes into duplex consensus reads.

Reference parity: ``ConsensusCruncher/DCS_maker.py`` (SURVEY.md §3.2).
Outputs:

- ``<p>.dcs.sorted.bam``             duplex consensus reads (one per strand
  pair per mate — both R1-side and R2-side DCS, pairable by qname)
- ``<p>.sscs.singleton.sorted.bam``  SSCSes with no complementary partner
- ``<p>.dcs_stats.txt|.json``

Pairing model (see core/tags.py): an SSCS's family tag is re-derived from the
read itself plus its ``XT`` barcode tag; the partner is ``duplex_tag(tag)``
(mirrored barcode halves, flipped read number) and is anchored at the SAME
``(ref, pos)`` — so pairing streams through one position window at a time
(O(window) memory, no whole-BAM dicts, no index).

Pinned semantics: a pair produces ONE duplex read, emitted under the qname
``dcs_qname(tag)`` with the template taken from the strand whose barcode is
the canonical (lexicographically smaller) arrangement; both members must have
equal length (unequal-length partners are left unpaired — a deliberate,
documented tightening; the mount was empty).  The duplex vote is the pinned
agree-or-N formula of ``core.duplex_cpu``/``ops.duplex_tpu``.
"""

from __future__ import annotations

from dataclasses import dataclass
import numpy as np

import struct
import sys

from consensuscruncher_tpu.core import tags as tags_mod
from consensuscruncher_tpu.obs import metrics as obs_metrics
from consensuscruncher_tpu.obs import trace as obs_trace
from consensuscruncher_tpu.utils import faults, sanitize
from consensuscruncher_tpu.core.consensus_read import _KEEP_FLAGS
from consensuscruncher_tpu.core.duplex_cpu import duplex_consensus
from consensuscruncher_tpu.io import bgzf
from consensuscruncher_tpu.io.bam import BamWriter
from consensuscruncher_tpu.io.encode import ConsensusRecordWriter
from consensuscruncher_tpu.ops.duplex_tpu import duplex_batch_host
from consensuscruncher_tpu.utils.backend_probe import record_backend
from consensuscruncher_tpu.utils.stats import StageStats


@dataclass
class DcsResult:
    dcs_bam: str
    sscs_singleton_bam: str
    stats: StageStats | None  # None when reconstructed from a resume skip

    @classmethod
    def from_prefix(cls, out_prefix: str) -> "DcsResult":
        """Path-only result for a stage skipped by --resume."""
        p = output_paths(out_prefix)
        return cls(p["dcs"], p["unpaired"], None)


def output_paths(out_prefix: str) -> dict[str, str]:
    """Canonical output paths for a prefix — the single naming authority
    shared by the stage body and the CLI's resume manifest."""
    return {
        "dcs": f"{out_prefix}.dcs.sorted.bam",
        "unpaired": f"{out_prefix}.sscs.singleton.sorted.bam",
        "stats_txt": f"{out_prefix}.dcs_stats.txt",
        "stats_json": f"{out_prefix}.dcs_stats.json",
    }


# Shared with singleton_correction (re-exported for stage symmetry).
from consensuscruncher_tpu.stages.grouping import (  # noqa: E402,F401
    consensus_windows,
    consensus_windows_columnar,
    derive_tag,
    fam_size_of,
)


class _PinnedMember:
    """Self-contained snapshot of a ConsensusReadView for deferred batching.

    Views hold a reference to their whole source ColumnarBatch (tens of MB);
    buffering them until a length bucket fills would pin every touched batch
    in memory.  This copies exactly what the duplex sink needs (~2L bytes +
    a few scalars) so the batch can be released."""

    __slots__ = ("codes", "qual", "flag", "rid", "pos", "mrid",
                 "mate_pos", "tlen", "mapq", "xf", "cigar")

    def __init__(self, view):
        self.codes = np.array(view.codes)
        self.qual = np.array(view.qual)
        self.flag = view.flag
        self.pos = view.pos
        self.mate_pos = view.mate_pos
        self.tlen = view.tlen
        self.mapq = view.mapq
        self.xf = fam_size_of(view)
        self.rid = view.rid
        self.mrid = view.mrid
        self.cigar = np.array(view.cigar_words())

    @classmethod
    def from_bam_read(cls, read, header):
        """Foreign-tag-layout fallback: consensus_windows_columnar yields a
        plain BamRead when a record's tag block doesn't lead with XT."""
        self = cls.__new__(cls)
        self.codes = read.codes
        q = read.qual
        self.qual = q if q.size else np.zeros(len(read.seq), dtype=np.uint8)
        self.flag = read.flag
        self.pos = read.pos
        self.mate_pos = read.mate_pos
        self.tlen = read.tlen
        self.mapq = read.mapq
        self.xf = fam_size_of(read)
        self.rid = header.ref_id(read.ref)
        self.mrid = header.ref_id(read.mate_ref)
        from consensuscruncher_tpu.io.encode import cigar_string_to_words

        self.cigar = cigar_string_to_words(read.cigar)
        return self

    @property
    def seq_len(self) -> int:
        return self.codes.shape[0]


def _duplex_vote_batch(s1, q1, s2, q2, qual_cap: int, backend: str, mesh=None):
    """One duplex vote over stacked (P, L) pairs — the single backend
    dispatch shared by the window-walk batcher and the vectorized path.
    ``mesh`` shards the pair axis (elementwise vote — no collectives)."""
    if backend == "tpu":
        if mesh is not None:
            from consensuscruncher_tpu.parallel.mesh import duplex_batch_host_sharded

            return duplex_batch_host_sharded(s1, q1, s2, q2, mesh, qual_cap)
        return duplex_batch_host(s1, q1, s2, q2, qual_cap)
    out_b = np.empty_like(s1)
    out_q = np.empty_like(q1)
    for i in range(s1.shape[0]):
        out_b[i], out_q[i] = duplex_consensus(s1[i], q1[i], s2[i], q2[i], qual_cap)
    return out_b, out_q


class _DuplexBatcher:
    """Accumulate strand pairs per read length; flush through the device
    kernel in batches (keeps device dispatches large and few)."""

    def __init__(self, qual_cap: int, header, flush_at: int = 16384,
                 backend: str = "tpu", mesh=None):
        self.qual_cap = qual_cap
        self.header = header
        self.flush_at = flush_at
        self.backend = backend
        self.mesh = mesh
        self._by_len: dict[int, list] = {}

    def _pin(self, read):
        if hasattr(read, "_batch"):  # columnar view: snapshot to unpin
            return _PinnedMember(read)
        if not hasattr(read, "xf"):  # BamRead (foreign tag layout fallback)
            return _PinnedMember.from_bam_read(read, self.header)
        return read

    def add(self, canon_tag, canon_read, other_read, sink) -> None:
        canon_read = self._pin(canon_read)
        other_read = self._pin(other_read)
        L = canon_read.seq_len
        self._by_len.setdefault(L, []).append((canon_tag, canon_read, other_read, sink))
        if len(self._by_len[L]) >= self.flush_at:
            self._flush_len(L)

    def _flush_len(self, L: int) -> None:
        entries = self._by_len.pop(L, [])
        if not entries:
            return
        s1 = np.stack([e[1].codes for e in entries])  # BamRead or columnar view
        s2 = np.stack([e[2].codes for e in entries])
        q1 = np.stack([e[1].qual for e in entries])
        q2 = np.stack([e[2].qual for e in entries])
        out_b, out_q = _duplex_vote_batch(s1, q1, s2, q2, self.qual_cap,
                                          self.backend, self.mesh)
        for i, (tag, canon, other, entry_sink) in enumerate(entries):
            entry_sink(tag, canon, other, out_b[i], out_q[i])

    def flush(self) -> None:
        for L in sorted(self._by_len):
            self._flush_len(L)


def _run_dcs_windows(reader, stats, unpaired_writer, rec_writer,
                     qual_cap: int, backend: str, mesh=None) -> None:
    """Object-window pairing walk (foreign consensus BAMs: records whose
    tag block doesn't lead with XT:Z+XF:i)."""
    _chaos = faults.hook("dcs.midstage")  # None unless a chaos test arms it
    batcher = _DuplexBatcher(qual_cap, reader.header, backend=backend, mesh=mesh)

    def sink(tag, canon, other, codes, quals):
        fam_size = canon.xf + other.xf
        L = codes.shape[0]
        words = canon.cigar if canon.seq_len == L else np.array([L << 4], np.uint32)
        tag_blob = (
            b"XTZ" + tag.barcode.encode("ascii")
            + b"\x00XFi" + struct.pack("<i", fam_size)
        )
        rec_writer.add(
            tags_mod.dcs_qname(tag), canon.flag & _KEEP_FLAGS, canon.rid,
            canon.pos, canon.mapq, words, canon.mrid, canon.mate_pos,
            canon.tlen, codes, quals, tag_blob,
        )
        stats.incr("dcs_written")

    for _key, window in consensus_windows_columnar(reader):
        if _chaos is not None:
            _chaos()
        paired: set = set()
        for tag in sorted(window, key=str):
            if tag in paired:
                continue
            stats.incr("sscs_total")
            partner = tags_mod.duplex_tag(tag)
            other = window.get(partner)
            if other is None or partner in paired:
                stats.incr("sscs_unpaired")
                unpaired_writer.write(window[tag].materialize())
                continue
            stats.incr("sscs_total")  # partner consumed here
            paired.add(tag)
            paired.add(partner)
            read, oread = window[tag], other
            if read.seq_len != oread.seq_len:
                stats.incr("sscs_unpaired", 2)
                stats.incr("length_mismatch_pairs")
                unpaired_writer.write(read.materialize())
                unpaired_writer.write(oread.materialize())
                continue
            # canonical strand: barcode lexicographically <= its mirror
            if tag.barcode <= partner.barcode:
                batcher.add(tag, read, oread, sink)
            else:
                batcher.add(partner, oread, read, sink)
            stats.incr("pairs")
    batcher.flush()


def _qname_bytes(sources, src_arr, row_arr, ps):
    """Store key per selected row: qname bytes (no trailing NUL) + NUL +
    little-endian record flag — the keys ``ops.residency`` indexes SSCS
    consensus planes by.  The flag disambiguates the R1/R2 records that
    share a family qname in the SSCS BAM; the capture side
    (``stages.sscs_maker``) builds the identical key from the grouping
    block's qname and template flag."""
    out = [b""] * len(ps)
    for si, batch in enumerate(sources):
        m = np.nonzero(src_arr[ps] == si)[0]
        if m.size == 0:
            continue
        rows = row_arr[ps[m]]
        starts = batch.qname_start[rows]
        lens = batch.l_qname[rows] - 1
        flags = batch.flag[rows]
        buf = batch.buf
        for j, s, ln, fl in zip(m, starts, lens, flags):
            out[int(j)] = (bytes(buf[int(s):int(s) + int(ln)])
                           + b"\x00" + int(fl).to_bytes(2, "little"))
    return out


def _consume_pair_blocks(reader, stats, unpaired_writer, rec_writer,
                         qual_cap: int, backend: str, mesh=None,
                         resident=None, cum=None) -> None:
    """Vectorized pairing (grouping.duplex_pair_blocks): unpaired reads pass
    through as raw blobs, pairs vote in one device batch per length group,
    and duplex records assemble through the columnar record writer.

    ``resident``: an ``ops.residency.ResidentPlanes`` store filled by the
    SSCS stage.  Pairs whose BOTH members are resident vote as a device-side
    gather (h2d = two index vectors); the rest — and everything, when the
    store is empty or broken — take the staged re-upload path.  Identical
    bytes either way (pinned by tests/test_residency.py)."""
    from consensuscruncher_tpu.stages.grouping import duplex_pair_blocks
    from consensuscruncher_tpu.utils.ragged import gather_runs

    _chaos = faults.hook("dcs.midstage")  # None unless a chaos test arms it
    header = reader.header
    for blk in duplex_pair_blocks(reader, header):
        if _chaos is not None:
            _chaos()
        # guard zero increments: the window walk only creates keys it touches
        if blk.stats_total:
            stats.incr("sscs_total", blk.stats_total)
        if blk.stats_unpaired:
            stats.incr("sscs_unpaired", blk.stats_unpaired)
        if blk.stats_pairs:
            stats.incr("pairs", blk.stats_pairs)
        if blk.stats_mismatch:
            stats.incr("length_mismatch_pairs", blk.stats_mismatch)

        # unpaired: raw length-prefixed blob passthrough, in emission order
        # (byte-equal to re-encoding for self-produced BAMs, which is the
        # only kind this path sees)
        k = 0
        nu = len(blk.unpaired_row)
        while k < nu:
            si = int(blk.unpaired_src[k])
            k2 = k
            while k2 < nu and blk.unpaired_src[k2] == si:
                k2 += 1
            batch = blk.sources[si]
            rows = blk.unpaired_row[k:k2]
            data, _ = gather_runs(
                batch.buf, batch.rec_off[rows],
                batch.rec_off[rows + 1] - batch.rec_off[rows],
            )
            unpaired_writer.write_encoded(data)
            k = k2

        n_pairs = blk.n_pairs
        if n_pairs == 0:
            continue
        # per-pair canon columns (vectorized per source)
        flagc = np.empty(n_pairs, np.int64)
        ridc = np.empty(n_pairs, np.int64)
        posc = np.empty(n_pairs, np.int64)
        mridc = np.empty(n_pairs, np.int64)
        mposc = np.empty(n_pairs, np.int64)
        tlenc = np.empty(n_pairs, np.int64)
        mapqc = np.empty(n_pairs, np.int64)
        lseqc = np.empty(n_pairs, np.int64)
        ncigc = np.empty(n_pairs, np.int64)
        cstartc = np.empty(n_pairs, np.int64)
        for si, batch in enumerate(blk.sources):
            m = blk.pair_canon_src == si
            rows = blk.pair_canon_row[m]
            flagc[m] = batch.flag[rows]
            ridc[m] = batch.ref_id[rows]
            posc[m] = batch.pos[rows]
            mridc[m] = batch.mate_ref_id[rows]
            mposc[m] = batch.mate_pos[rows]
            tlenc[m] = batch.tlen[rows]
            mapqc[m] = batch.mapq[rows]
            lseqc[m] = batch.l_seq[rows]
            ncigc[m] = batch.n_cigar[rows]
            cstartc[m] = batch.cigar_start[rows]

        def member_rows(src_arr, row_arr, sel, L):
            out_c = np.empty((int(sel.sum()), L), np.uint8)
            out_q = np.empty_like(out_c)
            pos_sel = np.nonzero(sel)[0]
            lens = np.full(0, L, np.int64)
            for si, batch in enumerate(blk.sources):
                m = src_arr[pos_sel] == si
                if not m.any():
                    continue
                rows = row_arr[pos_sel[m]]
                codes, coff = batch.seq_codes()
                quals, _ = batch.quals()
                if len(lens) != int(m.sum()):
                    lens = np.full(int(m.sum()), L, np.int64)
                # native ragged gather (uniform-run fast path) beats the
                # (n, L) fancy index by ~2-3x at stage scale
                data, _off = gather_runs(codes, coff[rows], lens)
                out_c[m] = data.reshape(-1, L)
                data, _off = gather_runs(quals, coff[rows], lens)
                out_q[m] = data.reshape(-1, L)
            return out_c, out_q

        from consensuscruncher_tpu.core.qnames import build_strings, const, fixed, ragged

        for L in np.unique(lseqc):
            L = int(L)
            sel = lseqc == L
            ps = np.nonzero(sel)[0]
            out_b = out_q = None
            if resident is not None and not resident.broken:
                qn1 = _qname_bytes(blk.sources, blk.pair_canon_src,
                                   blk.pair_canon_row, ps)
                qn2 = _qname_bytes(blk.sources, blk.pair_other_src,
                                   blk.pair_other_row, ps)
                idx1 = resident.rows_for(qn1, L)
                idx2 = resident.rows_for(qn2, L)
                if idx1 is not None and idx2 is not None:
                    hit = (idx1 >= 0) & (idx2 >= 0)
                    if hit.any():
                        res = resident.duplex_pairs(idx1[hit], idx2[hit], L,
                                                    qual_cap=qual_cap)
                        if res is not None:
                            out_b = np.empty((len(ps), L), np.uint8)
                            out_q = np.empty_like(out_b)
                            out_b[hit], out_q[hit] = res
                            if cum is not None:
                                cum.add("resident_pair_votes", int(hit.sum()))
                            if not hit.all():
                                sel_miss = np.zeros_like(sel)
                                sel_miss[ps[~hit]] = True
                                s1, q1 = member_rows(blk.pair_canon_src,
                                                     blk.pair_canon_row,
                                                     sel_miss, L)
                                s2, q2 = member_rows(blk.pair_other_src,
                                                     blk.pair_other_row,
                                                     sel_miss, L)
                                mb, mq = _duplex_vote_batch(
                                    s1, q1, s2, q2, qual_cap, backend, mesh)
                                out_b[~hit], out_q[~hit] = mb, mq
                                if cum is not None:
                                    cum.add("staged_pair_votes",
                                            int((~hit).sum()))
            if out_b is None:
                s1, q1 = member_rows(blk.pair_canon_src, blk.pair_canon_row, sel, L)
                s2, q2 = member_rows(blk.pair_other_src, blk.pair_other_row, sel, L)
                out_b, out_q = _duplex_vote_batch(s1, q1, s2, q2, qual_cap, backend, mesh)
                if cum is not None:
                    cum.add("staged_pair_votes", len(ps))
            k = len(ps)
            # modal cigar bytes per pair, gathered per source batch
            cig_lens = ncigc[ps]
            cig_data = np.empty(int(cig_lens.sum()) * 4, np.uint8)
            dst = np.zeros(k, np.int64)
            np.cumsum(4 * cig_lens[:-1], out=dst[1:])
            for si, batch in enumerate(blk.sources):
                m = blk.pair_canon_src[ps] == si
                if not m.any():
                    continue
                gather_to = dst[m]
                from consensuscruncher_tpu.utils.ragged import scatter_runs

                scatter_runs(cig_data, gather_to, batch.buf,
                             4 * cig_lens[m], src_starts=cstartc[ps[m]])
            qn_lens = blk.qname_off[ps + 1] - blk.qname_off[ps]
            qn_data, _ = gather_runs(blk.qname_data, blk.qname_off[ps], qn_lens)
            xf_le = blk.pair_xf[ps].astype("<i4").view(np.uint8).reshape(k, 4)
            tag_data, tag_off = build_strings(k, [
                const(b"XTZ"),
                ragged(blk.pair_bcm.reshape(-1), blk.pair_bclen[ps],
                       starts=ps * blk.pair_bcm.shape[1]),
                const(b"\x00XFi"),
                fixed(xf_le),
            ])
            rec_writer.add_columns(
                qn_data, qn_lens,
                flagc[ps] & _KEEP_FLAGS, ridc[ps], posc[ps], mapqc[ps],
                np.ascontiguousarray(cig_data).view("<u4"), cig_lens,
                mridc[ps], mposc[ps], tlenc[ps],
                out_b.reshape(-1), np.full(k, L, np.int64), out_q.reshape(-1),
                tag_data, np.diff(tag_off),
            )
            stats.incr("dcs_written", k)


def run_dcs(
    sscs_bam: str,
    out_prefix: str,
    qual_cap: int = 60,
    backend: str = "tpu",
    devices: int | None = None,
    level: int = 6,
    residency=None,
    stream_out=None,
) -> DcsResult:
    """``devices``: shard the duplex vote's pair axis across this many chips
    (``parallel.mesh``); None/1 = single device.  tpu backend only.

    ``residency``: the SSCS stage's ``ops.packing.resident_planes()`` store;
    pairs found resident vote on device without re-uploading their planes
    (tentpole h2d saving).  Ignored on the windows fallback path (foreign
    BAMs were never produced by this pipeline's SSCS stage).

    ``stream_out``: a ``core.streamgraph.StreamOut``; the DCS and
    unpaired-SSCS outputs (both finals) hand off in memory for the
    all-unique merges while materializing on the write-behind pool.
    ``sscs_bam`` may then be an in-memory batch source."""
    mesh = None
    if devices is not None and devices > 1:
        if backend != "tpu":
            raise ValueError("--devices > 1 requires the tpu backend")
        from consensuscruncher_tpu.parallel.mesh import make_mesh

        try:
            faults.fault_point("mesh.unavailable")
            mesh = make_mesh(devices)
        except Exception as e:
            # Same degraded mode as run_sscs: mesh loss costs throughput,
            # never the run (outputs bit-identical at any mesh size).
            print(f"WARNING: {devices}-device mesh unavailable ({e}); "
                  "degrading to single-device", file=sys.stderr, flush=True)
            mesh = None
    from consensuscruncher_tpu.utils.stats import TimeTracker

    tracker = TimeTracker()
    stats = StageStats("DCS")
    paths = output_paths(out_prefix)
    dcs_path, unpaired_path = paths["dcs"], paths["unpaired"]

    from consensuscruncher_tpu.io.columnar import (SortingBamWriter,
                                                   open_batch_source)

    reader = open_batch_source(sscs_bam)
    dcs_writer = SortingBamWriter(dcs_path, reader.header, level=level)
    unpaired_writer = SortingBamWriter(unpaired_path, reader.header, level=level)
    rec_writer = ConsensusRecordWriter(dcs_writer)

    from consensuscruncher_tpu.utils.profiling import Counters

    cum = Counters()
    recompiles_before = obs_metrics.recompiles()
    transfers_before = obs_metrics.transfer_bytes()
    io_before = bgzf.write_stats()
    ok = False
    try:
        try:
            with sanitize.guarded_stage("dcs"), \
                    obs_trace.span("dcs.device_loop", wire="blocks"):
                _consume_pair_blocks(
                    reader, stats, unpaired_writer, rec_writer, qual_cap, backend, mesh,
                    resident=residency, cum=cum,
                )
        except ValueError as e:
            if "foreign tag layout" not in str(e):
                raise
            # foreign consensus BAM: restart from scratch on the object path
            # (nothing promoted yet; the buffered writers are simply dropped)
            reader.close()
            dcs_writer.abort()
            unpaired_writer.abort()
            stats = StageStats("DCS")
            reader = open_batch_source(sscs_bam)
            dcs_writer = SortingBamWriter(dcs_path, reader.header, level=level)
            unpaired_writer = SortingBamWriter(unpaired_path, reader.header,
                                               level=level)
            rec_writer = ConsensusRecordWriter(dcs_writer)
            with sanitize.guarded_stage("dcs"), \
                    obs_trace.span("dcs.device_loop", wire="windows"):
                _run_dcs_windows(
                    reader, stats, unpaired_writer, rec_writer, qual_cap, backend, mesh,
                )
        rec_writer.flush()
        ok = True
    finally:
        reader.close()
        if not ok:
            dcs_writer.abort()
            unpaired_writer.abort()

    tracker.mark("pairing")
    with obs_trace.span("writer.commit", stage="dcs"):
        if stream_out is not None:
            # Both outputs are finals: hand off for the all-unique merges
            # while the write-behind pool materializes the files.
            stream_out.capture("dcs", dcs_writer.close_to_memory(),
                               file_path=dcs_path, level=level)
            stream_out.capture("unpaired", unpaired_writer.close_to_memory(),
                               file_path=unpaired_path, level=level)
        else:
            dcs_writer.close()
            unpaired_writer.close()
    tracker.mark("sort")
    record_backend(stats, backend)
    stats.write(paths["stats_txt"])
    tracker.write(f"{out_prefix}.dcs.time_tracker.txt")
    from consensuscruncher_tpu.utils.profiling import write_metrics

    cum.add("recompiles", obs_metrics.recompiles() - recompiles_before)
    transfers = obs_metrics.transfer_bytes()
    cum.add("bytes_h2d", transfers["h2d"] - transfers_before["h2d"])
    cum.add("bytes_d2h", transfers["d2h"] - transfers_before["d2h"])
    iostat = bgzf.write_stats()
    cum.add("deflate_wall_us",
            iostat["deflate_wall_us"] - io_before["deflate_wall_us"])
    cum.add("bytes_bam_written",
            iostat["bytes_written"] - io_before["bytes_written"])
    write_metrics(
        f"{out_prefix}.dcs.metrics.json", "DCS", tracker.as_phases(),
        {"backend": backend, "jax_backend": stats.get("jax_backend"),
         "pairs": stats.get("pairs"), "sscs_total": stats.get("sscs_total"),
         "recompiles": obs_metrics.recompiles() - recompiles_before},
        cumulative=cum.snapshot(),
    )
    return DcsResult(dcs_path, unpaired_path, stats)


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser(description="Make duplex consensus sequences")
    p.add_argument("--infile", required=True, help="sorted SSCS BAM")
    p.add_argument("--outfile", required=True, help="output prefix")
    p.add_argument("--backend", choices=("cpu", "tpu"), default="tpu")
    args = p.parse_args(argv)
    from consensuscruncher_tpu.utils.backend_probe import ensure_backend

    ensure_backend(args.backend)
    run_dcs(args.infile, args.outfile, backend=args.backend)


if __name__ == "__main__":
    main()
