"""Run manifest: explicit checkpoint/resume over the stage-file model.

The reference pipeline's recovery story is implicit — every stage persists
full BAM outputs, so a crash loses at most the running stage and "resume" is
re-running by hand (SURVEY.md §5 "Checkpoint / resume": the rebuild makes it
explicit with a manifest of stage outputs + hashes).  This module is that
manifest:

- each completed stage records fingerprints of its inputs, outputs, and the
  parameters that shaped them;
- on ``--resume``, a stage is skipped iff its recorded inputs, outputs, and
  parameters all still match — inputs are re-fingerprinted so an upstream
  change invalidates everything downstream, and outputs are re-fingerprinted
  so a half-written file (non-atomic writer, disk-full) never masquerades as
  a checkpoint;
- the manifest file itself is committed durably (write tmp, fsync, rename,
  fsync dir), the same discipline the BAM writers use via
  :func:`commit_file` below.

Fingerprints are ``(size, sha256(head 1 MiB), sha256(tail 1 MiB))`` —
content-based (mtime survives copies/rsync badly) but O(1) in file size, so
resuming a 100M-read run never re-hashes hundreds of GB.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile

_CHUNK = 1 << 20  # head/tail bytes hashed per file

MANIFEST_VERSION = 1


def commit_file(tmp_path: str, final_path: str) -> None:
    """Atomically and durably publish ``tmp_path`` as ``final_path``:
    fsync the data, rename into place, fsync the directory.

    This is THE stage-output commit point for the whole pipeline (BAM
    writers, columnar merges, the manifest itself).  The rename gives
    all-or-nothing visibility; the two fsyncs make the commit survive a
    power cut — without them a crash can leave a fully *renamed* but
    zero-length file, which would then fingerprint as a valid checkpoint.
    """
    fd = os.open(tmp_path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp_path, final_path)
    dirname = os.path.dirname(os.path.abspath(final_path)) or "."
    try:
        dfd = os.open(dirname, os.O_RDONLY)
    except OSError:
        return  # exotic fs that refuses O_RDONLY on dirs: rename still atomic
    try:
        os.fsync(dfd)
    except OSError:
        pass
    finally:
        os.close(dfd)


def fingerprint(path: str) -> dict | None:
    """Content fingerprint of ``path``; None if it doesn't exist."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return None
    head = hashlib.sha256()
    tail = hashlib.sha256()
    with open(path, "rb") as fh:
        head.update(fh.read(_CHUNK))
        if size > _CHUNK:
            fh.seek(max(size - _CHUNK, _CHUNK))
            tail.update(fh.read(_CHUNK))
    return {"size": size, "head": head.hexdigest(), "tail": tail.hexdigest()}


class RunManifest:
    """Stage-completion ledger for one pipeline run directory."""

    def __init__(self, path: str):
        self.path = path
        self._stages: dict[str, dict] = {}
        if os.path.exists(path):
            try:
                with open(path) as fh:
                    data = json.load(fh)
                if isinstance(data, dict) and data.get("version") == MANIFEST_VERSION:
                    stages = data.get("stages")
                    if isinstance(stages, dict):
                        self._stages = {
                            name: entry
                            for name, entry in stages.items()
                            if isinstance(entry, dict)
                            and isinstance(entry.get("params"), dict)
                            and isinstance(entry.get("inputs"), dict)
                            and isinstance(entry.get("outputs"), dict)
                        }
            except (OSError, json.JSONDecodeError):
                # A corrupt manifest only disables skipping, never the run.
                self._stages = {}

    # ------------------------------------------------------------- recording

    def record(self, stage: str, inputs: list[str], outputs: list[str], params: dict) -> None:
        """Mark ``stage`` complete; fingerprints are taken now (outputs must
        already be fully written — call after the stage's atomic renames)."""
        entry = {
            "params": dict(params),
            "inputs": {p: fingerprint(p) for p in inputs},
            "outputs": {p: fingerprint(p) for p in outputs},
        }
        missing = [p for p, f in entry["outputs"].items() if f is None]
        if missing:
            raise FileNotFoundError(f"stage {stage!r} recorded missing outputs: {missing}")
        self._stages[stage] = entry
        self._flush()

    def _flush(self) -> None:
        data = {"version": MANIFEST_VERSION, "stages": self._stages}
        d = os.path.dirname(self.path) or "."
        fd, tmp = tempfile.mkstemp(prefix=".manifest.", dir=d)
        try:
            with os.fdopen(fd, "w") as fh:
                json.dump(data, fh, indent=2, sort_keys=True)
                fh.write("\n")
            commit_file(tmp, self.path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    # ------------------------------------------------------------- skipping

    def can_skip(self, stage: str, inputs: list[str], params: dict) -> bool:
        """True iff ``stage`` completed with these exact inputs + params and
        every recorded output is still intact on disk."""
        entry = self._stages.get(stage)
        if entry is None:
            return False
        if entry["params"] != params:
            return False
        if set(entry["inputs"]) != set(inputs):
            return False
        for p, recorded in entry["inputs"].items():
            if recorded is None or fingerprint(p) != recorded:
                return False
        for p, recorded in entry["outputs"].items():
            if fingerprint(p) != recorded:
                return False
        return True

    def outputs_of(self, stage: str) -> list[str]:
        entry = self._stages.get(stage)
        return list(entry["outputs"]) if entry else []

    def invalidate(self, stage: str) -> None:
        if self._stages.pop(stage, None) is not None:
            self._flush()
