"""Fail-fast TPU backend probe for user-facing entry points.

SURVEY.md §5 (failure detection): the reference fails loudly when bwa or
samtools is missing; the analogous failure here is a sick TPU backend.  The
axon PJRT plugin's init can hang *indefinitely* (not error) when the tunnel
is down, so a try/except is not enough — the first device touch needs a
watchdog.  ``ensure_backend`` runs the init in the calling process under a
timer: on timeout it prints an actionable message (naming ``--backend cpu``
as the workaround) and hard-exits, instead of hanging silently forever.

The watchdog costs nothing when the backend is healthy — the init the CLI
would do anyway simply happens here, first, and jit reuses it.
"""

from __future__ import annotations

import os
import sys
import threading

def _default_timeout_s() -> float:
    try:
        return float(os.environ.get("CCT_TPU_INIT_TIMEOUT", 120.0))
    except ValueError:
        print(
            f"WARNING: ignoring non-numeric CCT_TPU_INIT_TIMEOUT="
            f"{os.environ['CCT_TPU_INIT_TIMEOUT']!r}; using 120s",
            file=sys.stderr,
        )
        return 120.0


def force_cpu_platform() -> None:
    """Pin this process's JAX to the XLA-CPU backend BEFORE any device
    touch: env + config + dropping the axon PJRT factory (whose init hangs
    indefinitely on a dead tunnel — an env var alone does not stop its
    registration hooks).  Same dance as bench.py's workers and
    tests/conftest.py."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:  # private API, but the only way to unregister a sick PJRT plugin
        import jax._src.xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass


def jax_platform_for(code_path: str) -> str:
    """The actual silicon a stage's code path ran on, for stats/metrics.

    VERDICT r2 weak #2: an ``xla_cpu`` run used to record ``backend=tpu``
    with nothing durable saying the kernels executed on CPU.  Stages now
    record two keys — ``backend`` (the CODE PATH: tpu/cpu/reference) and
    ``jax_backend`` (this function: the real ``jax.default_backend()``
    platform).  The numpy paths (``cpu``/``reference``) never touch JAX, so
    for them this returns ``"none"`` without triggering a backend init.

    Strictly observational: if JAX's backend was never initialized in this
    process (possible even on the ``tpu`` code path — e.g. exact-match
    singleton rescue never touches the device), returns ``"uninitialized"``
    rather than triggering an init that could hang on a sick tunnel.
    """
    if code_path != "tpu":
        return "none"
    if "jax" not in sys.modules:
        return "uninitialized"
    try:
        from jax._src import xla_bridge as _xb

        if not _xb._backends:  # init never happened; don't cause it
            return "uninitialized"
        import jax

        return jax.default_backend()
    except Exception:
        return "unknown"


def record_backend(stats, backend: str) -> None:
    """Record the code path AND the silicon it ran on in one place — the
    single authority for the two-key convention (VERDICT r2 weak #2)."""
    stats.set("backend", backend)  # code path: tpu / cpu / reference
    stats.set("jax_backend", jax_platform_for(backend))  # actual silicon


def ensure_backend(backend: str, timeout_s: float | None = None) -> None:
    """Initialize the device backend now, bounded by a watchdog.

    No-op for ``backend="cpu"``/``"reference"`` (pure numpy paths — nothing
    to probe).  ``backend="xla_cpu"`` pins the process to the XLA-CPU
    platform (the production jitted kernels, CPU silicon — the sick-tunnel
    fallback) and returns.  For ``"tpu"``, touches ``jax.devices()`` under
    a timer:

    - init hangs  -> message + ``os._exit(3)`` (only way out of a hung
      C-extension call; Python exceptions can't interrupt it)
    - init raises -> ``SystemExit`` with the cause and the workaround
    - init works  -> returns; the warmed backend is reused by the stages
    """
    if backend == "xla_cpu":
        force_cpu_platform()
        return
    if backend != "tpu":
        return
    if timeout_s is None:
        timeout_s = _default_timeout_s()
    done = threading.Event()

    def watchdog() -> None:
        if not done.wait(timeout_s):
            print(
                f"ERROR: TPU backend init did not complete within {timeout_s:.0f}s — "
                "the TPU (or its tunnel) looks unavailable.\n"
                "  workarounds: --backend xla_cpu (same jitted kernels, CPU "
                "silicon)\n"
                "               --backend cpu (pure-numpy reference path)\n"
                "  or wait longer: CCT_TPU_INIT_TIMEOUT=<seconds>",
                file=sys.stderr,
                flush=True,
            )
            os._exit(3)

    threading.Thread(target=watchdog, daemon=True).start()
    try:
        import jax

        devices = jax.devices()
    except Exception as exc:
        done.set()
        raise SystemExit(
            f"TPU backend unavailable ({exc}) — re-run with --backend cpu"
        ) from None
    done.set()
    if not devices:
        raise SystemExit("TPU backend reports no devices — re-run with --backend cpu")
    if devices[0].platform not in ("tpu", "axon"):
        # Don't fail — running the device path on XLA-CPU is legitimate
        # (tests, sick-chip fallback) — but never let it be silent: the
        # stats will say backend=tpu while the silicon is something else.
        print(
            f"WARNING: --backend tpu resolved to platform "
            f"{devices[0].platform!r} ({len(devices)} device(s)) — the jitted "
            "kernels will run there, not on a TPU",
            file=sys.stderr,
            flush=True,
        )
