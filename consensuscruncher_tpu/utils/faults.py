"""Fault-injection registry + bounded retry: the pipeline's chaos harness.

The north star is a run that survives the failures a multi-hour,
multi-process pipeline actually sees — a pool worker OOM-killed mid-chunk,
an external aligner flaking with rc!=0, a BGZF input truncated by a
died-mid-copy upload, a SIGTERM landing between stages.  Those paths are
worthless untested, and untestable without a way to *cause* the failure on
demand inside a hermetic CPU test.  This module is that switchboard: every
recovery path in the codebase guards a named **site**, and a test arms the
site through the environment, so the exact production code path (including
forked pool workers and CLI subprocesses, which inherit the environment)
fires the fault.

Spec (env, so it crosses fork/exec boundaries for free):

  CCT_FAULTS       comma-separated ``site=kind[@times][:arg]`` directives,
                   e.g. ``align.pool_worker=exit@1,subprocess.bwa=fail@2``
  CCT_FAULTS_DIR   optional ledger directory.  When set, each site's firing
                   budget is counted ACROSS PROCESSES via O_CREAT|O_EXCL
                   marker files — "exactly one pool worker dies, once" is
                   expressible even though every forked worker sees the
                   same spec.  Without it, budgets are per-process.

Kinds:

  fail    raise :class:`FaultError` (arg unused)
  exit    ``os._exit(arg or 137)`` — an un-catchable worker death
  kill    ``os.kill(self, SIG<arg or TERM>)`` — real signal delivery
  stall   ``time.sleep(arg or 0.05)`` — slow-I/O; correctness must hold

Sites wired in this codebase (grep for ``fault_point``/``faults.hook``):

  align.barrier        prestart-barrier warm-up failure -> serial fallback
  align.barrier_worker worker-side prestart stall -> real barrier timeout
  align.pool_worker    fork-pool worker death -> re-fork once, then serial
  subprocess.bwa       external aligner failure -> bounded retry + backoff
  bgzf.truncated_eof   reader sees a truncated block -> clear error/salvage
  bgzf.read_stall      slow input device (stall kind)
  mesh.unavailable     device mesh creation -> single-device fallback
  sscs.midstage        crash/SIGTERM inside the SSCS loop (atomicity proof)
  dcs.midstage         crash/SIGTERM inside the DCS loop (atomicity proof)
  watch.job            TPU watcher row job nonzero rc -> retry + backoff
  serve.accept         daemon connection accept/handling -> error reply
  serve.dispatch       scheduler gang dispatch -> jobs retried solo
  serve.worker         per-job worker execution -> retry via --resume
  serve.journal_write  journal append -> submit refused, nothing half-acked
  serve.journal_replay corrupt journal record -> skip + log, rest recovers
  serve.sigterm        shutdown handler -> immediate stop, replay recovers
  serve.shed           deadline admission check -> forced shed
  stream.channel_full  streaming backpressure engaged -> clean abort, not
                       deadlock (CLI falls back to the staged pipeline)
  stream.operator_fail mid-stream producer fault -> channel poisoned ->
                       staged-pipeline fallback, byte-identical outputs
  route.member_down    router forward hits a dead member -> ring failover
  route.steal          steal decision fails -> job stays on its home node
  route.resubmit       failover resubmission fails -> retried, idempotent
  route.router_down    standby's probe of the active router -> takeover
  route.adopt          journal adoption fails -> no tombstone, sweep retries
  route.fence          worker epoch admission -> stale router demoted
  serve.poison         deterministic poison job -> budget-capped re-runs,
                       then durable quarantine; honest jobs unharmed
  serve.enospc         journal append ENOSPC -> cache evicts, retry once,
                       then read-only brownout (polls still served)
  serve.oom            memory watermark breach -> shed scavenger -> batch
                       -> interactive; running jobs never killed

Everything here is stdlib-only and import-cheap: io/bgzf.py and the
tools/ scripts (whose parents must never import jax) both import it.
"""

from __future__ import annotations

import os
import signal
import sys
import time


class FaultError(RuntimeError):
    """An injected failure.  Never raised outside fault-injection runs."""


class FaultInjector:
    """Parsed CCT_FAULTS spec + firing budgets (see module docstring)."""

    def __init__(self, spec: str, ledger_dir: str | None = None):
        self.spec = spec
        self.ledger_dir = ledger_dir
        self._sites: dict[str, dict] = {}
        self._fired: dict[str, int] = {}
        for part in spec.split(","):
            part = part.strip()
            if not part or "=" not in part:
                continue
            site, rhs = part.split("=", 1)
            arg = None
            if ":" in rhs:
                rhs, arg = rhs.split(":", 1)
            times = 1
            if "@" in rhs:
                rhs, t = rhs.split("@", 1)
                times = int(t)
            self._sites[site.strip()] = {
                "kind": rhs.strip(), "times": times, "arg": arg,
            }

    def armed(self, site: str) -> bool:
        return site in self._sites

    def fire(self, site: str) -> dict | None:
        """Consume one firing of ``site``.  Returns the directive while the
        budget lasts, then None forever — this is what makes "fail twice,
        then succeed" expressible."""
        d = self._sites.get(site)
        if d is None:
            return None
        if self.ledger_dir:
            # Cross-process budget: claiming marker file i < times wins
            # exactly once across every process sharing the ledger.
            os.makedirs(self.ledger_dir, exist_ok=True)
            for i in range(d["times"]):
                marker = os.path.join(self.ledger_dir, f"{site}.{i}")
                try:
                    fd = os.open(marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                except FileExistsError:
                    continue
                os.close(fd)
                return d
            return None
        n = self._fired.get(site, 0)
        if n >= d["times"]:
            return None
        self._fired[site] = n + 1
        return d


_cached: tuple[str, str | None, FaultInjector] | None = None


def get() -> FaultInjector:
    """The process-wide injector, re-parsed whenever the env spec changes
    (so monkeypatch.setenv works without reload, and forked children that
    mutate nothing share the parent's budgets)."""
    global _cached
    spec = os.environ.get("CCT_FAULTS", "")
    ledger = os.environ.get("CCT_FAULTS_DIR") or None
    if _cached is None or _cached[0] != spec or _cached[1] != ledger:
        _cached = (spec, ledger, FaultInjector(spec, ledger))
    return _cached[2]


def _perform(site: str, d: dict) -> None:
    kind = d["kind"]
    if kind == "fail":
        raise FaultError(f"injected fault at {site}")
    if kind == "exit":
        os._exit(int(d["arg"] or 137))
    if kind == "kill":
        sig = getattr(signal, d["arg"]) if d["arg"] else signal.SIGTERM
        os.kill(os.getpid(), sig)
        # Default-disposition signals deliver asynchronously: block so the
        # code after the injection point never runs in the dying process.
        time.sleep(30)
        return
    if kind == "stall":
        time.sleep(float(d["arg"] or 0.05))
        return
    raise ValueError(f"unknown fault kind {kind!r} at site {site!r}")


def _notify(site: str, d: dict) -> None:
    """Every fault firing, whichever entry point consumed it, lands in the
    observability layer: a trace event (the obscov lint's CCT601 contract)
    and a flight-recorder entry.  Fatal kinds dump the ring before the
    process disappears — the only post-mortem an ``exit``/``kill`` leaves.
    Lazy import: faults must stay import-cheap for io/ and tools/ parents,
    and obs must be free to import faults-adjacent utils."""
    kind = d.get("kind", "?")
    try:
        from consensuscruncher_tpu.obs import flight, trace
        trace.event("fault.fire", site=site, kind=kind)
        flight.record("fault", site=site, fault=kind)
        if kind in ("exit", "kill"):
            flight.dump(reason=f"fault-{kind}:{site}")
    except Exception as e:  # never let observability break the injection
        print(f"WARNING: fault notify failed at {site}: {e}",
              file=sys.stderr, flush=True)


def _consume(site: str) -> dict | None:
    """Shared budget-consume path for :func:`fault_point` and :func:`fire`:
    returns the armed directive (after notifying observers) or None."""
    inj = get()
    if not inj._sites:
        return None
    d = inj.fire(site)
    if d is not None:
        _notify(site, d)
    return d


def fault_point(site: str) -> None:
    """The one call a subsystem plants at an injection point.  No-op (two
    dict lookups) unless CCT_FAULTS arms ``site``."""
    d = _consume(site)
    if d is not None:
        _perform(site, d)


def fire(site: str) -> dict | None:
    """Like :func:`fault_point` but returns the directive instead of acting,
    for call sites that express the fault in their own vocabulary (e.g. the
    watcher swapping in a known-failing command)."""
    return _consume(site)


def hook(site: str):
    """Resolve an injection point ONCE for a hot loop: None when ``site``
    is not armed (so the loop pays a single ``if`` per iteration), else a
    zero-arg callable that consumes budget and performs the directive."""
    if not get().armed(site):
        return None
    return lambda: fault_point(site)


def retrying(fn, *, site: str, attempts: int = 3, base_delay: float | None = None,
             max_delay: float = 30.0, retriable: tuple = (Exception,),
             describe: str | None = None, sleep=time.sleep):
    """Call ``fn()`` with bounded retry + exponential backoff.

    ``site`` doubles as the injection point: an armed ``site=fail@k``
    directive makes the first k attempts fail synthetically, which is how
    tests express "flake twice, then succeed" against the real retry loop.
    ``base_delay=None`` reads CCT_RETRY_BASE_S (default 0.5 s; tests set it
    to ~0 so backoff is exercised without wall-clock cost).
    """
    if base_delay is None:
        base_delay = float(os.environ.get("CCT_RETRY_BASE_S", "0.5"))
    if attempts < 1:
        raise ValueError("attempts must be >= 1")
    catch = tuple(retriable) + (FaultError,)
    for attempt in range(attempts):
        try:
            fault_point(site)
            return fn()
        except catch as e:
            if attempt + 1 >= attempts:
                raise
            delay = min(max_delay, base_delay * (2.0 ** attempt))
            print(f"WARNING: {describe or site} failed ({e}); "
                  f"retry {attempt + 2}/{attempts} in {delay:.1f}s",
                  file=sys.stderr, flush=True)
            sleep(delay)
    raise AssertionError("unreachable")


def backoff_delay(attempt: int, base: float, cap: float) -> float:
    """Exponential backoff schedule shared by the retry loops: delay before
    attempt ``attempt+1`` after ``attempt`` failures (attempt >= 1)."""
    return min(cap, base * (2.0 ** max(0, attempt - 1)))
