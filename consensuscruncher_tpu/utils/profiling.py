"""Tracing/profiling (SURVEY.md §5 "Tracing / profiling").

The reference's only instrumentation is the coarse wall-clock
``*.time_tracker.txt`` the SSCS stage writes.  The rebuild keeps that file
for parity (``utils.stats.TimeTracker``) and adds the TPU-era pieces:

- :func:`maybe_profile` — wrap any region in a ``jax.profiler.trace``
  (XLA + host timeline, viewable in TensorBoard/Perfetto) when a trace
  directory is given; zero overhead when not.
- :func:`write_metrics` — structured per-stage metrics JSON
  (phase wall-clock + derived throughput such as families/sec, the
  BASELINE.json driver metric), sitting next to the human-readable
  tracker file.  Run-specific by nature, so excluded from golden digests
  exactly like the tracker.
"""

from __future__ import annotations

import json
import threading
from contextlib import contextmanager

#: One shared counter schema for the one-shot CLI metrics sidecars and the
#: serve daemon's ``metrics`` endpoint.  Missing keys default to 0 so readers
#: can rely on the full set being present wherever ``cumulative`` appears.
#: The canonical definition (names + help text) lives in
#: ``obs.registry.COUNTERS`` next to the histogram registry; re-exported
#: here so existing importers keep working.
from consensuscruncher_tpu.obs.registry import CUMULATIVE_KEYS


class Counters:
    """Thread-safe cumulative counters over :data:`CUMULATIVE_KEYS`.

    ``add`` accumulates, ``high_water`` keeps a running max (for gauges like
    queue depth), ``snapshot`` returns a plain dict with every key present.
    Keys outside the registry raise ``KeyError`` — an unregistered counter
    would silently vanish from ``snapshot``'s normalised schema, which is
    exactly the drift the registry exists to prevent.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._values = {k: 0 for k in CUMULATIVE_KEYS}

    @staticmethod
    def _check(key: str) -> None:
        if key not in CUMULATIVE_KEYS:
            raise KeyError(
                f"unknown counter {key!r}; register it in "
                f"consensuscruncher_tpu/obs/registry.py COUNTERS")

    def add(self, key: str, amount: int = 1) -> None:
        self._check(key)
        with self._lock:
            self._values[key] += int(amount)

    def high_water(self, key: str, value: int) -> None:
        self._check(key)
        with self._lock:
            if int(value) > self._values[key]:
                self._values[key] = int(value)

    def snapshot(self) -> dict[str, int]:
        with self._lock:
            out = {k: 0 for k in CUMULATIVE_KEYS}
            out.update(self._values)
            return out


@contextmanager
def maybe_profile(trace_dir: str | None):
    """``jax.profiler.trace(trace_dir)`` when ``trace_dir`` is set, else a
    no-op.  Imports jax lazily so pure-CPU tools don't pay for it."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield


def metrics_doc(stage: str, phases: dict[str, float],
                counters: dict[str, object],
                cumulative: dict[str, int] | None = None) -> dict[str, object]:
    """The metrics document shared by stage sidecars and the serve daemon's
    ``metrics`` endpoint: ``{stage, phases_s, total_s, **counters}`` plus
    derived ``<unit>_per_sec`` rates for any counter named ``n_<unit>``, and
    a ``cumulative`` block normalised over :data:`CUMULATIVE_KEYS`."""
    total = sum(phases.values())
    doc: dict[str, object] = {"stage": stage, "phases_s": {
        k: round(v, 6) for k, v in phases.items()
    }, "total_s": round(total, 6)}
    doc.update(counters)
    if total > 0:
        for key, value in counters.items():
            if key.startswith("n_") and isinstance(value, (int, float)):
                doc[f"{key[2:]}_per_sec"] = round(value / total, 2)
    if cumulative is not None:
        block = {k: 0 for k in CUMULATIVE_KEYS}
        block.update({k: int(v) for k, v in cumulative.items()})
        doc["cumulative"] = block
    return doc


def write_metrics(path, stage: str, phases: dict[str, float],
                  counters: dict[str, object],
                  cumulative: dict[str, int] | None = None) -> None:
    """Write :func:`metrics_doc` as an indented-JSON sidecar."""
    doc = metrics_doc(stage, phases, counters, cumulative=cumulative)
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
