"""Tracing/profiling (SURVEY.md §5 "Tracing / profiling").

The reference's only instrumentation is the coarse wall-clock
``*.time_tracker.txt`` the SSCS stage writes.  The rebuild keeps that file
for parity (``utils.stats.TimeTracker``) and adds the TPU-era pieces:

- :func:`maybe_profile` — wrap any region in a ``jax.profiler.trace``
  (XLA + host timeline, viewable in TensorBoard/Perfetto) when a trace
  directory is given; zero overhead when not.
- :func:`write_metrics` — structured per-stage metrics JSON
  (phase wall-clock + derived throughput such as families/sec, the
  BASELINE.json driver metric), sitting next to the human-readable
  tracker file.  Run-specific by nature, so excluded from golden digests
  exactly like the tracker.
"""

from __future__ import annotations

import json
from contextlib import contextmanager


@contextmanager
def maybe_profile(trace_dir: str | None):
    """``jax.profiler.trace(trace_dir)`` when ``trace_dir`` is set, else a
    no-op.  Imports jax lazily so pure-CPU tools don't pay for it."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield


def write_metrics(path, stage: str, phases: dict[str, float],
                  counters: dict[str, object]) -> None:
    """Structured metrics sidecar: ``{stage, phases_s, **counters}`` plus
    derived ``<unit>_per_sec`` rates for any counter named ``n_<unit>``
    against the total phase time."""
    total = sum(phases.values())
    doc: dict[str, object] = {"stage": stage, "phases_s": {
        k: round(v, 6) for k, v in phases.items()
    }, "total_s": round(total, 6)}
    doc.update(counters)
    if total > 0:
        for key, value in counters.items():
            if key.startswith("n_") and isinstance(value, (int, float)):
                doc[f"{key[2:]}_per_sec"] = round(value / total, 2)
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
