"""Opt-in runtime sanitizers (``CCT_SANITIZE=1``) — cctlint's dynamic half.

The static passes in ``tools/cctlint`` prove what the AST can prove; this
module catches what only execution can: an *implicit* host->device transfer
sneaking into a hot stage (a raw numpy array fed to a jitted call), an
explicit mid-stage ``jax.device_get`` arriving through a call chain the
lint can't see, and lock-order inversions that only manifest under real
thread interleavings.  Three pieces:

- :func:`guarded_stage` — wraps the SSCS/DCS device loops in JAX's
  ``transfer_guard_host_to_device("disallow")`` plus a thread-local shim
  over ``jax.device_get`` / ``jax.block_until_ready``, converting any
  mid-stage sync into an actionable :class:`StageTransferError`.
  Device->host drains via ``np.asarray(handle)`` stay legal by design —
  the streaming fetch IS the sanctioned d2h path; the static host-sync
  pass polices everything else.
- :func:`allow_transfer` — sanctioned-region escape hatch, mirroring the
  static pragma ``# cct: allow-transfer(reason)``.
- :func:`tracked_lock` / :func:`tracked_condition` — drop-in lock wrappers
  recording per-thread acquisition stacks; under ``CCT_SANITIZE=1`` an
  acquisition that inverts a previously-seen order raises
  :class:`LockOrderError` at the faulty acquire, not as a production hang.

Import-cheap and jax-free at module level (the scheduler imports this; jax
loads lazily on first guarded stage).  All state is process-local.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time


class StageTransferError(RuntimeError):
    """A host<->device sync happened inside a guarded stage."""


class LockOrderError(RuntimeError):
    """Two locks were acquired in opposite orders on different paths."""


def enabled() -> bool:
    """Read dynamically so tests can flip CCT_SANITIZE via monkeypatch."""
    return os.environ.get("CCT_SANITIZE", "") == "1"


# --------------------------------------------------------------- stage guard

_tls = threading.local()


def _depth() -> int:
    return getattr(_tls, "depth", 0)


def _allow_depth() -> int:
    return getattr(_tls, "allow", 0)


_shim_lock = threading.Lock()
_shim_installed = False


def _install_sync_shim() -> None:
    """Patch ``jax.device_get`` / ``jax.block_until_ready`` once per process
    with thread-local-depth-checking wrappers.  Zero effect on threads not
    inside a guarded stage."""
    global _shim_installed
    with _shim_lock:
        if _shim_installed:
            return
        import jax

        def _blocked(what: str):
            stage = getattr(_tls, "stage", "?")
            raise StageTransferError(
                f"[CCT_SANITIZE] '{what}' inside guarded stage '{stage}' — "
                "a mid-stage host sync serialises the async dispatch "
                "pipeline. Move the sync to the stage boundary, or wrap a "
                "sanctioned region in sanitize.allow_transfer(reason)."
            )

        orig_get = jax.device_get

        def guarded_device_get(x):
            if _depth() > 0 and _allow_depth() == 0:
                _blocked("jax.device_get")
            return orig_get(x)

        guarded_device_get._cct_orig = orig_get  # type: ignore[attr-defined]
        jax.device_get = guarded_device_get

        orig_block = getattr(jax, "block_until_ready", None)
        if orig_block is not None:
            def guarded_block(x):
                if _depth() > 0 and _allow_depth() == 0:
                    _blocked("jax.block_until_ready")
                return orig_block(x)

            guarded_block._cct_orig = orig_block  # type: ignore[attr-defined]
            jax.block_until_ready = guarded_block
        _shim_installed = True


@contextlib.contextmanager
def guarded_stage(name: str):
    """No-op unless ``CCT_SANITIZE=1``; then: implicit h2d transfers raise
    (XLA transfer guard) and explicit sync calls raise (shim), both as
    :class:`StageTransferError` naming the stage and the fix."""
    if not enabled():
        yield
        return
    import jax

    _install_sync_shim()
    _tls.depth = _depth() + 1
    prev_stage = getattr(_tls, "stage", None)
    _tls.stage = name
    try:
        with jax.transfer_guard_host_to_device("disallow"):
            yield
    except StageTransferError:
        raise
    except Exception as exc:
        msg = str(exc)
        if "transfer" in msg.lower() and "disallow" in msg.lower():
            raise StageTransferError(
                f"[CCT_SANITIZE] implicit host->device transfer inside "
                f"guarded stage '{name}': {msg}\nFix: make the transfer "
                "explicit at the dispatch boundary (jnp.asarray / "
                "jax.device_put on the batch arrays), or wrap a sanctioned "
                "region in sanitize.allow_transfer(reason)."
            ) from exc
        raise
    finally:
        _tls.depth = _depth() - 1
        _tls.stage = prev_stage


@contextlib.contextmanager
def allow_transfer(reason: str):
    """Sanctioned transfer region inside a guarded stage.  ``reason`` is
    mandatory, mirroring the static pragma's non-empty-reason rule."""
    if not reason or not reason.strip():
        raise ValueError("allow_transfer() requires a non-empty reason")
    if not enabled() or _depth() == 0:
        yield
        return
    import jax

    _tls.allow = _allow_depth() + 1
    try:
        with jax.transfer_guard("allow"):
            yield
    finally:
        _tls.allow = _allow_depth() - 1


def sync_probe(site: str) -> None:
    """Chaos hook proving the guard catches mid-stage syncs: when the fault
    site ``site`` is armed (``CCT_FAULTS=<site>=fail``), perform a real
    ``jax.device_get`` right here — under ``CCT_SANITIZE=1`` inside a
    guarded stage that raises :class:`StageTransferError`; otherwise it is
    a harmless no-op sync.  Unarmed cost: two dict lookups."""
    yield_point(site)
    from . import faults

    if faults.fire(site) is None:
        return
    import jax

    jax.device_get(0)


# --------------------------------------------------------- interleave hooks
#
# The deterministic model checker (``utils/interleave.py``) drives real
# threads through one-at-a-time cooperative scheduling.  Its yield points
# are exactly the operations this module already wraps: TrackedLock /
# TrackedCondition acquire+release, ``sync_probe`` sites, and explicit
# ``yield_point`` calls on the serve plane's protocol boundaries.  The
# hook is process-global but must ignore threads it does not manage —
# that filtering is the hook object's job, so unmanaged production
# threads pay only a None check.

_interleave_hook = None


def set_interleave_hook(hook) -> None:
    """Install (or clear, with ``None``) the cooperative scheduler hook.
    The hook sees ``before_acquire(name, lock)`` / ``after_release(name,
    lock)`` around every tracked lock operation, ``on_wait(name, cond)``
    before a condition wait, and ``yield_point(tag)`` at explicit sites."""
    global _interleave_hook
    _interleave_hook = hook


def yield_point(tag: str) -> None:
    """A schedule point for the model checker; no-op outside model runs.
    Placed where the serve protocol's ordering matters but no lock edge
    exists (journal replay reads, ack boundaries, view scans)."""
    h = _interleave_hook
    if h is not None:
        h.yield_point(tag)


# ------------------------------------------------------------ lock tracking

#: (earlier lock, later lock) -> "file-free" first-seen marker.  Guarded by
#: _edges_lock; held only for dict ops, never while user locks are taken.
_edges: dict[tuple[str, str], bool] = {}
_edges_lock = threading.Lock()


def _held() -> list[str]:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _note_acquire(name: str, check: bool = True) -> None:
    held = _held()
    if check and enabled():
        with _edges_lock:
            for h in held:
                if h == name:
                    continue
                _edges[(h, name)] = True
                if (name, h) in _edges:
                    raise LockOrderError(
                        f"[CCT_SANITIZE] lock order inversion: acquiring "
                        f"'{name}' while holding '{h}', but the opposite "
                        f"order '{name}' -> '{h}' was taken earlier — "
                        "pick one global order for these locks."
                    )
    held.append(name)


def _note_release(name: str) -> None:
    held = _held()
    if name in held:
        # remove the innermost occurrence (out-of-order release is legal
        # for plain Locks, rare in practice)
        for i in range(len(held) - 1, -1, -1):
            if held[i] == name:
                del held[i]
                break


def reset_lock_tracking() -> None:
    """Test hook: forget every recorded ordering edge."""
    with _edges_lock:
        _edges.clear()


# -------------------------------------------------------- contention ledger
#
# Opt-in (``CCT_LOCK_LEDGER=1``) hold/wait timing per named lock, feeding
# the critpath antagonist view and the ``lock_wait_us`` / ``lock_hold_us``
# / ``lock_waits`` labeled counters the scheduler composes into its metrics
# doc at read time.  Off by default: the production fast path pays one
# cached env check per acquire.  Contention is detected with a free
# non-blocking acquire first — only the acquires that actually block pay
# the clock, so uncontended hot paths stay unmeasured and cheap.

#: name -> [wait_us, hold_us, waits, acquires]; guarded by _ledger_lock.
_ledger: dict[str, list[int]] = {}
#: name -> thread name currently holding the lock (antagonist attribution).
_holders: dict[str, str] = {}
_ledger_lock = threading.Lock()
_ledger_env: tuple[str, bool] = ("\x00", False)


def ledger_enabled() -> bool:
    """Cached on the raw env string so monkeypatch.setenv invalidates."""
    global _ledger_env
    raw = os.environ.get("CCT_LOCK_LEDGER", "")
    if raw != _ledger_env[0]:
        _ledger_env = (raw, raw == "1")
    return _ledger_env[1]


def _ledger_note(name: str, wait_us: int = 0, hold_us: int = 0,
                 contended: bool = False, acquired: bool = False) -> None:
    with _ledger_lock:
        row = _ledger.get(name)
        if row is None:
            row = _ledger[name] = [0, 0, 0, 0]
        row[0] += wait_us
        row[1] += hold_us
        if contended:
            row[2] += 1
        if acquired:
            row[3] += 1


def _holder_set(name: str) -> None:
    with _ledger_lock:
        _holders[name] = threading.current_thread().name


def _holder_clear(name: str) -> None:
    with _ledger_lock:
        _holders.pop(name, None)


def ledger_snapshot() -> dict[str, dict[str, int]]:
    """Totals per lock name since process start (or :func:`reset_ledger`)."""
    with _ledger_lock:
        return {
            name: {"wait_us": row[0], "hold_us": row[1],
                   "waits": row[2], "acquires": row[3]}
            for name, row in sorted(_ledger.items())
        }


def current_holders() -> dict[str, str]:
    """lock name -> holder thread name, for the antagonist view."""
    with _ledger_lock:
        return dict(_holders)


def reset_ledger() -> None:
    """Test hook: zero every ledger row and forget holders."""
    with _ledger_lock:
        _ledger.clear()
        _holders.clear()


class TrackedLock:
    """Drop-in ``threading.Lock`` recording acquisition order per thread."""

    def __init__(self, name: str, factory=threading.Lock):
        self._name = name
        self._lock = factory()
        self._acq_t = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        h = _interleave_hook
        if h is not None:
            h.before_acquire(self._name, self)
        _note_acquire(self._name)
        if ledger_enabled():
            ok = self._lock.acquire(False)
            if not ok and blocking:
                t0 = time.monotonic_ns()
                ok = self._lock.acquire(True, timeout)
                _ledger_note(self._name, contended=True, acquired=ok,
                             wait_us=(time.monotonic_ns() - t0) // 1000)
            elif ok:
                _ledger_note(self._name, acquired=True)
            if ok:
                self._acq_t = time.monotonic_ns()
                _holder_set(self._name)
        else:
            ok = self._lock.acquire(blocking, timeout)
        if not ok:
            _note_release(self._name)
        return ok

    def release(self) -> None:
        if self._acq_t:
            _ledger_note(self._name,
                         hold_us=(time.monotonic_ns() - self._acq_t) // 1000)
            self._acq_t = 0
            _holder_clear(self._name)
        self._lock.release()
        _note_release(self._name)
        h = _interleave_hook
        if h is not None:
            h.after_release(self._name, self)

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class TrackedCondition:
    """Drop-in ``threading.Condition`` with the same order tracking.
    ``wait`` pops the condition from the held stack for its release window
    and re-pushes (without re-checking) on wake."""

    def __init__(self, name: str):
        self._name = name
        self._cond = threading.Condition()
        self._acq_t = 0

    def acquire(self, *args) -> bool:
        h = _interleave_hook
        if h is not None:
            h.before_acquire(self._name, self)
        _note_acquire(self._name)
        if ledger_enabled():
            ok = self._cond.acquire(False)
            if not ok and (not args or args[0]):
                t0 = time.monotonic_ns()
                ok = self._cond.acquire(*args)
                _ledger_note(self._name, contended=True, acquired=ok,
                             wait_us=(time.monotonic_ns() - t0) // 1000)
            elif ok:
                _ledger_note(self._name, acquired=True)
            if ok:
                self._acq_t = time.monotonic_ns()
                _holder_set(self._name)
            return ok
        return self._cond.acquire(*args)

    def release(self) -> None:
        self._close_hold()
        self._cond.release()
        _note_release(self._name)
        h = _interleave_hook
        if h is not None:
            h.after_release(self._name, self)

    def _close_hold(self) -> None:
        if self._acq_t:
            _ledger_note(self._name,
                         hold_us=(time.monotonic_ns() - self._acq_t) // 1000)
            self._acq_t = 0
            _holder_clear(self._name)

    def _reopen_hold(self) -> None:
        # Woken from cond.wait holding the lock again; the parked interval
        # was idle, not contention, so it lands in neither wait nor hold.
        if ledger_enabled():
            self._acq_t = time.monotonic_ns()
            _holder_set(self._name)

    def wait(self, timeout: float | None = None) -> bool:
        h = _interleave_hook
        if h is not None:
            h.on_wait(self._name, self)
        _note_release(self._name)
        self._close_hold()
        try:
            return self._cond.wait(timeout)
        finally:
            _note_acquire(self._name, check=False)
            self._reopen_hold()

    def wait_for(self, predicate, timeout: float | None = None):
        h = _interleave_hook
        if h is not None:
            h.on_wait(self._name, self)
        _note_release(self._name)
        self._close_hold()
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            _note_acquire(self._name, check=False)
            self._reopen_hold()

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


def tracked_lock(name: str) -> TrackedLock:
    """A named lock whose acquisition order is asserted under
    ``CCT_SANITIZE=1`` (always safe to use; passthrough cost otherwise)."""
    return TrackedLock(name)


def tracked_condition(name: str) -> TrackedCondition:
    return TrackedCondition(name)
