"""Base/quality encodings shared by the CPU oracle and the TPU kernels.

Encoding contract (load-bearing for CPU<->TPU bit parity):

- Bases are small ints: A=0, C=1, G=2, T=3, N=4.  PAD=5 marks padding slots in
  batched tensors (never a real base).  Any IUPAC ambiguity code other than
  ACGT maps to N, matching how the reference treats them (everything non-ACGT
  is just an uncounted/modal-losing base in ``collections.Counter``).
- Qualities are raw Phred ints (0..93) as stored in BAM ``qual`` bytes; the
  Sanger ASCII offset (33) only appears at FASTQ/SAM text boundaries.
"""

from __future__ import annotations

import numpy as np

SANGER_OFFSET = 33

A, C, G, T, N = 0, 1, 2, 3, 4
PAD = 5
NUM_BASES = 5  # A C G T N participate in voting

BASE_CHARS = "ACGTN"

# uint8 ascii -> code lookup (everything unknown -> N)
_ENCODE_LUT = np.full(256, N, dtype=np.uint8)
for _i, _ch in enumerate(BASE_CHARS):
    _ENCODE_LUT[ord(_ch)] = _i
    _ENCODE_LUT[ord(_ch.lower())] = _i

_DECODE_LUT = np.frombuffer(b"ACGTN?", dtype=np.uint8)


def encode_seq(seq: str | bytes) -> np.ndarray:
    """str/bytes sequence -> uint8 codes (A=0..N=4)."""
    if isinstance(seq, str):
        seq = seq.encode("ascii")
    return _ENCODE_LUT[np.frombuffer(seq, dtype=np.uint8)]


def decode_seq(codes: np.ndarray) -> str:
    """uint8 codes -> str sequence ('?' for PAD, which should never leak out)."""
    return _DECODE_LUT[np.asarray(codes, dtype=np.uint8)].tobytes().decode("ascii")


def quals_to_array(quals) -> np.ndarray:
    """List/iterable of Phred ints -> uint8 array."""
    return np.asarray(quals, dtype=np.uint8)


def qual_string_to_array(qual_str: str | bytes) -> np.ndarray:
    """Sanger-encoded ASCII quality string -> Phred uint8 array."""
    if isinstance(qual_str, str):
        qual_str = qual_str.encode("ascii")
    arr = np.frombuffer(qual_str, dtype=np.uint8)
    return (arr - SANGER_OFFSET).astype(np.uint8)


def array_to_qual_string(arr: np.ndarray) -> str:
    """Phred uint8 array -> Sanger ASCII quality string."""
    return (np.asarray(arr, dtype=np.uint8) + SANGER_OFFSET).tobytes().decode("ascii")


def complement_codes(codes: np.ndarray) -> np.ndarray:
    """A<->T, C<->G, N->N on the integer encoding."""
    lut = np.array([T, G, C, A, N, PAD], dtype=np.uint8)
    return lut[np.asarray(codes, dtype=np.uint8)]


def revcomp_str(seq: str) -> str:
    tbl = str.maketrans("ACGTNacgtn", "TGCANtgcan")
    return seq.translate(tbl)[::-1]
