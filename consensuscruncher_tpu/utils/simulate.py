"""Synthetic duplex-sequencing dataset generator (test fixtures + benchmarks).

SURVEY.md §4.3 calls for "synthetic BAM fixtures ... with controlled family
sizes, strands, errors"; this module is that generator, and also feeds
``bench.py``'s scale configs.  It fabricates duplex fragments the same way
the wet lab does: a true molecule sequence, two strands, R1/R2 per strand,
per-read sequencing errors, barcodes recorded in swapped order on opposite
strands (see core/tags.py's physical model).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from consensuscruncher_tpu.core.tags import BARCODE_SEP, DEFAULT_BDELIM
from consensuscruncher_tpu.io.bam import BamHeader, BamRead, BamWriter, sort_bam

BASES = "ACGT"


@dataclass
class SimConfig:
    n_fragments: int = 100
    read_len: int = 100
    umi_len: int = 6
    ref_len: int = 100_000
    ref_name: str = "chr1"
    mean_family_size: float = 3.0
    duplex_fraction: float = 0.8  # fraction of fragments with both strands
    error_rate: float = 0.005
    # Per-read probability of one substitution error INSIDE the UMI — such
    # reads split off as spurious singleton families whose barcode is
    # Hamming-1 from the true family's, the exact population
    # --max_mismatch rescue exists to reclaim.
    barcode_error_rate: float = 0.0
    seed: int = 0
    bdelim: str = DEFAULT_BDELIM


@dataclass
class SimTruth:
    """Ground truth for assertions: fragment -> molecule sequence + families."""

    molecules: dict = field(default_factory=dict)  # frag id -> (start, seq)
    family_sizes: dict = field(default_factory=dict)  # frag id -> (a_size, b_size)


def _rand_seq(rng, n):
    return "".join(BASES[i] for i in rng.integers(0, 4, n))


def simulate_bam(path: str, cfg: SimConfig) -> SimTruth:
    """Write a coordinate-sorted, barcode-extracted BAM of duplex families."""
    rng = np.random.default_rng(cfg.seed)
    header = BamHeader.from_refs([(cfg.ref_name, cfg.ref_len)])
    truth = SimTruth()
    tmp = path + ".unsorted"
    serial = 0
    with BamWriter(tmp, header) as w:
        for frag in range(cfg.n_fragments):
            lo = int(rng.integers(1000, cfg.ref_len - 3 * cfg.read_len))
            hi = lo + 2 * cfg.read_len + int(rng.integers(0, cfg.read_len))
            mol = _rand_seq(rng, hi + cfg.read_len - lo)
            umi_a = _rand_seq(rng, cfg.umi_len)
            umi_b = _rand_seq(rng, cfg.umi_len)
            a_size = max(1, int(rng.poisson(cfg.mean_family_size)))
            b_size = (
                max(1, int(rng.poisson(cfg.mean_family_size)))
                if rng.random() < cfg.duplex_fraction
                else 0
            )
            truth.molecules[frag] = (lo, mol)
            truth.family_sizes[frag] = (a_size, b_size)
            r1_seq = mol[: cfg.read_len]
            r2_seq = mol[hi - lo : hi - lo + cfg.read_len]
            for strand, size in (("A", a_size), ("B", b_size)):
                bc = (
                    f"{umi_a}{BARCODE_SEP}{umi_b}"
                    if strand == "A"
                    else f"{umi_b}{BARCODE_SEP}{umi_a}"
                )
                for _ in range(size):
                    serial += 1
                    bc_read = bc
                    # Short-circuit keeps the rng stream identical to older
                    # datasets when the rate is 0 (golden stability).
                    if cfg.barcode_error_rate > 0 and rng.random() < cfg.barcode_error_rate:
                        chars = list(bc_read)
                        pool = [i for i, c in enumerate(chars) if c != BARCODE_SEP]
                        i = pool[int(rng.integers(0, len(pool)))]
                        chars[i] = BASES[
                            (BASES.index(chars[i]) + 1 + int(rng.integers(0, 3))) % 4
                        ]
                        bc_read = "".join(chars)
                    qname = f"sim:{frag}:{strand}:{serial}{cfg.bdelim}{bc_read}"
                    s1 = _mutate(rng, r1_seq, cfg.error_rate)
                    s2 = _mutate(rng, r2_seq, cfg.error_rate)
                    q1 = rng.integers(25, 41, cfg.read_len).astype(np.uint8)
                    q2 = rng.integers(25, 41, cfg.read_len).astype(np.uint8)
                    # strand A: R1 fwd@lo / R2 rev@hi ; strand B mirrored
                    r1_read1 = strand == "A"
                    w.write(BamRead(
                        qname=qname,
                        flag=(0x1 | 0x2 | 0x20 | (0x40 if r1_read1 else 0x80)),
                        ref=cfg.ref_name, pos=lo, mapq=60,
                        cigar=[("M", cfg.read_len)],
                        mate_ref=cfg.ref_name, mate_pos=hi, tlen=hi - lo + cfg.read_len,
                        seq=s1, qual=q1,
                    ))
                    w.write(BamRead(
                        qname=qname,
                        flag=(0x1 | 0x2 | 0x10 | (0x80 if r1_read1 else 0x40)),
                        ref=cfg.ref_name, pos=hi, mapq=60,
                        cigar=[("M", cfg.read_len)],
                        mate_ref=cfg.ref_name, mate_pos=lo, tlen=-(hi - lo + cfg.read_len),
                        seq=s2, qual=q2,
                    ))
    sort_bam(tmp, path)
    import os

    os.unlink(tmp)
    return truth


def _mutate(rng, seq: str, rate: float) -> str:
    if rate <= 0:
        return seq
    arr = list(seq)
    for i in np.nonzero(rng.random(len(arr)) < rate)[0]:
        arr[i] = BASES[int(rng.integers(0, 4))]
    return "".join(arr)
