"""Synthetic duplex-sequencing dataset generator (test fixtures + benchmarks).

SURVEY.md §4.3 calls for "synthetic BAM fixtures ... with controlled family
sizes, strands, errors"; this module is that generator, and also feeds
``bench.py``'s scale configs.  It fabricates duplex fragments the same way
the wet lab does: a true molecule sequence, two strands, R1/R2 per strand,
per-read sequencing errors, barcodes recorded in swapped order on opposite
strands (see core/tags.py's physical model).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from consensuscruncher_tpu.core.tags import BARCODE_SEP, DEFAULT_BDELIM
from consensuscruncher_tpu.io.bam import BamHeader, BamRead, BamWriter, sort_bam

BASES = "ACGT"


@dataclass
class SimConfig:
    n_fragments: int = 100
    read_len: int = 100
    umi_len: int = 6
    ref_len: int = 100_000
    ref_name: str = "chr1"
    mean_family_size: float = 3.0
    duplex_fraction: float = 0.8  # fraction of fragments with both strands
    error_rate: float = 0.005
    # Per-read probability of one substitution error INSIDE the UMI — such
    # reads split off as spurious singleton families whose barcode is
    # Hamming-1 from the true family's, the exact population
    # --max_mismatch rescue exists to reclaim.
    barcode_error_rate: float = 0.0
    # Low-quality regime (ISSUE 17): each read independently degrades
    # with this probability — its Phred scores drop into ``degraded_qual``
    # (below the delegation policy's Phred-20 floor; the healthy band at
    # 25-40 stays above it) and its bases pick up extra substitutions at
    # ``degraded_error_rate`` on top of ``error_rate``.  All draws
    # short-circuit at rate 0 so the rng stream — and every committed
    # golden — is untouched by default, exactly like barcode_error_rate.
    degraded_read_rate: float = 0.0
    degraded_error_rate: float = 0.08
    degraded_qual: tuple = (3, 16)
    seed: int = 0
    bdelim: str = DEFAULT_BDELIM


@dataclass
class SimTruth:
    """Ground truth for assertions: fragment -> molecule sequence + families."""

    molecules: dict = field(default_factory=dict)  # frag id -> (start, seq)
    family_sizes: dict = field(default_factory=dict)  # frag id -> (a_size, b_size)


def _rand_seq(rng, n):
    return "".join(BASES[i] for i in rng.integers(0, 4, n))


def simulate_bam(path: str, cfg: SimConfig) -> SimTruth:
    """Write a coordinate-sorted, barcode-extracted BAM of duplex families."""
    rng = np.random.default_rng(cfg.seed)
    header = BamHeader.from_refs([(cfg.ref_name, cfg.ref_len)])
    truth = SimTruth()
    tmp = path + ".unsorted"
    serial = 0
    with BamWriter(tmp, header) as w:
        for frag in range(cfg.n_fragments):
            lo = int(rng.integers(1000, cfg.ref_len - 3 * cfg.read_len))
            hi = lo + 2 * cfg.read_len + int(rng.integers(0, cfg.read_len))
            mol = _rand_seq(rng, hi + cfg.read_len - lo)
            umi_a = _rand_seq(rng, cfg.umi_len)
            umi_b = _rand_seq(rng, cfg.umi_len)
            a_size = max(1, int(rng.poisson(cfg.mean_family_size)))
            b_size = (
                max(1, int(rng.poisson(cfg.mean_family_size)))
                if rng.random() < cfg.duplex_fraction
                else 0
            )
            truth.molecules[frag] = (lo, mol)
            truth.family_sizes[frag] = (a_size, b_size)
            r1_seq = mol[: cfg.read_len]
            r2_seq = mol[hi - lo : hi - lo + cfg.read_len]
            for strand, size in (("A", a_size), ("B", b_size)):
                bc = (
                    f"{umi_a}{BARCODE_SEP}{umi_b}"
                    if strand == "A"
                    else f"{umi_b}{BARCODE_SEP}{umi_a}"
                )
                for _ in range(size):
                    serial += 1
                    bc_read = bc
                    # Short-circuit keeps the rng stream identical to older
                    # datasets when the rate is 0 (golden stability).
                    if cfg.barcode_error_rate > 0 and rng.random() < cfg.barcode_error_rate:
                        chars = list(bc_read)
                        pool = [i for i, c in enumerate(chars) if c != BARCODE_SEP]
                        i = pool[int(rng.integers(0, len(pool)))]
                        chars[i] = BASES[
                            (BASES.index(chars[i]) + 1 + int(rng.integers(0, 3))) % 4
                        ]
                        bc_read = "".join(chars)
                    qname = f"sim:{frag}:{strand}:{serial}{cfg.bdelim}{bc_read}"
                    s1 = _mutate(rng, r1_seq, cfg.error_rate)
                    s2 = _mutate(rng, r2_seq, cfg.error_rate)
                    q1 = rng.integers(25, 41, cfg.read_len).astype(np.uint8)
                    q2 = rng.integers(25, 41, cfg.read_len).astype(np.uint8)
                    if (cfg.degraded_read_rate > 0
                            and rng.random() < cfg.degraded_read_rate):
                        qlo, qhi = cfg.degraded_qual
                        s1 = _mutate(rng, s1, cfg.degraded_error_rate)
                        s2 = _mutate(rng, s2, cfg.degraded_error_rate)
                        q1 = rng.integers(qlo, qhi, cfg.read_len).astype(np.uint8)
                        q2 = rng.integers(qlo, qhi, cfg.read_len).astype(np.uint8)
                    # strand A: R1 fwd@lo / R2 rev@hi ; strand B mirrored
                    r1_read1 = strand == "A"
                    w.write(BamRead(
                        qname=qname,
                        flag=(0x1 | 0x2 | 0x20 | (0x40 if r1_read1 else 0x80)),
                        ref=cfg.ref_name, pos=lo, mapq=60,
                        cigar=[("M", cfg.read_len)],
                        mate_ref=cfg.ref_name, mate_pos=hi, tlen=hi - lo + cfg.read_len,
                        seq=s1, qual=q1,
                    ))
                    w.write(BamRead(
                        qname=qname,
                        flag=(0x1 | 0x2 | 0x10 | (0x80 if r1_read1 else 0x40)),
                        ref=cfg.ref_name, pos=hi, mapq=60,
                        cigar=[("M", cfg.read_len)],
                        mate_ref=cfg.ref_name, mate_pos=lo, tlen=-(hi - lo + cfg.read_len),
                        seq=s2, qual=q2,
                    ))
    sort_bam(tmp, path)
    import os

    os.unlink(tmp)
    return truth


def _mutate(rng, seq: str, rate: float) -> str:
    if rate <= 0:
        return seq
    arr = list(seq)
    for i in np.nonzero(rng.random(len(arr)) < rate)[0]:
        arr[i] = BASES[int(rng.integers(0, 4))]
    return "".join(arr)


# --------------------------------------------------------------------------
# Vectorized generator for benchmark-scale datasets
# --------------------------------------------------------------------------

def _mix64(x: np.ndarray) -> np.ndarray:
    """SplitMix64 finalizer: uint64 array -> well-mixed uint64 array."""
    z = x + np.uint64(0x9E3779B97F4A7C15)
    z ^= z >> np.uint64(30)
    z *= np.uint64(0xBF58476D1CE4E5B9)
    z ^= z >> np.uint64(27)
    z *= np.uint64(0x94D049BB133111EB)
    z ^= z >> np.uint64(31)
    return z


@dataclass
class SimTruthFast:
    """Array-form ground truth from ``simulate_bam_fast`` (no per-fragment
    dicts — at benchmark scale those would dominate memory)."""

    lo: np.ndarray
    hi: np.ndarray
    a_size: np.ndarray
    b_size: np.ndarray

    @property
    def n_reads(self) -> int:
        return 2 * int(self.a_size.sum() + self.b_size.sum())


def simulate_bam_fast(
    path: str, cfg: SimConfig, chunk_reads: int = 2_000_000, level: int = 6
) -> SimTruthFast:
    """Vectorized twin of ``simulate_bam`` for benchmark-scale datasets.

    Same statistical model (fragment endpoints, Poisson family sizes, duplex
    dropout, per-base substitution errors, swapped-half barcodes), but the
    whole dataset is a pure function of ``(cfg.seed, chunk_reads)``:
    per-fragment draws are vectorized ``default_rng`` arrays, family
    templates derive from a counter-based SplitMix64 stream (chunk-
    independent), and per-read errors/quals burn the sequential rng stream
    chunk by chunk — so ``chunk_reads`` is part of the dataset identity;
    keep the default when regenerating a dataset byte-for-byte.  Reads are emitted directly
    in coordinate order (sort key: pos, qname, flag — same total order as
    ``sort_bam`` on a single-ref BAM) and encoded with the vectorized
    ``encode_records`` path, so there is no unsorted temp file and no
    object-path encode.  ~100x the throughput of ``simulate_bam``; the
    object path remains the oracle for golden fixtures.

    ``cfg.barcode_error_rate`` is supported: affected reads get one UMI base
    substituted, splitting them into Hamming-1 singleton families exactly
    like the object path.
    """
    from consensuscruncher_tpu.io.bam import _sorted_header
    from consensuscruncher_tpu.io.encode import encode_records

    rng = np.random.default_rng(cfg.seed)
    L, U = cfg.read_len, cfg.umi_len
    nF = cfg.n_fragments
    if cfg.ref_len < 1000 + 4 * L:
        raise ValueError("ref_len too small for read placement")

    # --- per-fragment draws (vectorized; order differs from simulate_bam's
    # interleaved stream by design — this is a different dataset family) ---
    lo = rng.integers(1000, cfg.ref_len - 3 * L, nF, dtype=np.int64)
    hi = lo + 2 * L + rng.integers(0, L, nF, dtype=np.int64)
    umi_a = rng.integers(0, 4, (nF, U), dtype=np.int8).astype(np.uint8)
    umi_b = rng.integers(0, 4, (nF, U), dtype=np.int8).astype(np.uint8)
    a_size = np.maximum(1, rng.poisson(cfg.mean_family_size, nF)).astype(np.int32)
    duplex = rng.random(nF) < cfg.duplex_fraction
    b_size = np.where(
        duplex, np.maximum(1, rng.poisson(cfg.mean_family_size, nF)), 0
    ).astype(np.int32)

    # --- member table (frag-major, strand A then B) ---
    counts = (a_size + b_size).astype(np.int64)
    M = int(counts.sum())
    frag_of = np.repeat(np.arange(nF, dtype=np.int64), counts)
    starts = np.zeros(nF, dtype=np.int64)
    np.cumsum(counts[:-1], out=starts[1:])
    idx_in_frag = np.arange(M, dtype=np.int64) - starts[frag_of]
    strand_b = idx_in_frag >= a_size[frag_of]

    # barcode errors: one substituted UMI base on the read's recorded barcode
    if cfg.barcode_error_rate > 0:
        bc_err = rng.random(M) < cfg.barcode_error_rate
        bc_err_pos = rng.integers(0, 2 * U, M, dtype=np.int64)
        bc_err_delta = rng.integers(1, 4, M, dtype=np.uint8)
    else:
        bc_err = np.zeros(M, dtype=bool)
        bc_err_pos = bc_err_delta = None

    # --- read table (2 reads per member) + global coordinate order ---
    n_reads = 2 * M
    frag_r = np.repeat(frag_of, 2)
    readno = np.tile(np.array([0, 1], dtype=np.int64), M)
    pos_r = np.where(readno == 0, lo[frag_r], hi[frag_r])
    # sort_bam's total order on one ref: (pos, qname bytes, flag).  qnames
    # are fixed-width with zero-padded digits, so lexicographic qname order
    # == (frag, strand, serial) numeric order; serial == member index, which
    # frag-major layout already encodes.  flag ordering within a pair:
    # both reads share the qname, readno 0 vs 1 differ only in flag, and
    # flags below are chosen so read0's flag < read1's on strand A
    # (0x63 < 0x93) while strand B needs the swap (0xA3 > 0x53).
    member_r = np.repeat(np.arange(M, dtype=np.int64), 2)
    flag_key = np.where(
        strand_b[member_r], 1 - readno, readno
    )
    perm = np.lexsort((flag_key, member_r, pos_r))

    seed64 = np.uint64(np.int64(cfg.seed)) ^ np.uint64(0xC0FFEE5EED)
    qname_w = 4 + 9 + 1 + 1 + 1 + 9 + len(cfg.bdelim) + (2 * U + len(BARCODE_SEP))
    digits0 = np.uint8(ord("0"))
    base_bytes = np.frombuffer(BASES.encode(), np.uint8)
    sep_bytes = np.frombuffer(BARCODE_SEP.encode(), np.uint8)
    bdelim_bytes = np.frombuffer(cfg.bdelim.encode(), np.uint8)

    header = _sorted_header(BamHeader.from_refs([(cfg.ref_name, cfg.ref_len)]))
    writer = BamWriter(path, header, atomic=True, level=level)
    try:
        for c0 in range(0, n_reads, chunk_reads):
            ridx = perm[c0 : c0 + chunk_reads]
            C = len(ridx)
            mem = member_r[ridx]
            frag = frag_of[mem]
            rno = readno[ridx]
            sb = strand_b[mem]

            # flags / coords (strand A: read0 fwd@lo R1, read1 rev@hi R2;
            # strand B: read0 fwd@lo R2, read1 rev@hi R1)
            flags = np.where(
                rno == 0,
                np.where(sb, 0xA3, 0x63),
                np.where(sb, 0x53, 0x93),
            ).astype(np.int64)
            p = np.where(rno == 0, lo[frag], hi[frag])
            mp = np.where(rno == 0, hi[frag], lo[frag])
            span = hi[frag] - lo[frag] + L
            tlen = np.where(rno == 0, span, -span)

            # sequence codes: per-(frag, readno) template + per-read errors.
            # The template must be identical wherever a family member lands
            # (members of one family can straddle chunk boundaries), so it is
            # a counter-based hash of (frag, readno, position) — computed
            # once per UNIQUE row in the chunk, then gathered.  Per-read
            # draws (errors, quals) burn the sequential rng stream instead:
            # each read is emitted exactly once in deterministic order, so
            # the stream is reproducible without keyed hashing.
            jj = np.arange(L, dtype=np.uint64)
            uniq, inv = np.unique(frag * 2 + rno, return_inverse=True)
            tk = (uniq.astype(np.uint64) * np.uint64(L))[:, None] + jj[None, :]
            codes = (_mix64(tk ^ seed64) & np.uint64(3)).astype(np.uint8)[inv]
            if cfg.error_rate > 0:
                # Sparse error placement: k ~ Binomial(C*L, rate) positions
                # drawn with replacement (collisions are ~rate^2-rare), vs a
                # dense float draw over every base.
                k = rng.binomial(C * L, cfg.error_rate)
                epos = rng.integers(0, C * L, k)
                codes = np.ascontiguousarray(codes)
                codes.ravel()[epos] = rng.integers(0, 4, k, dtype=np.uint8)
            quals = rng.integers(25, 41, (C, L), dtype=np.uint8)

            # qnames: "sim:FFFFFFFFF:S:MMMMMMMMM<bdelim><bc1>.<bc2>"
            qm = np.empty((C, qname_w), dtype=np.uint8)
            qm[:, 0:4] = np.frombuffer(b"sim:", np.uint8)
            col = 4
            f10 = frag.copy()
            for d in range(8, -1, -1):
                qm[:, col + d] = digits0 + (f10 % 10).astype(np.uint8)
                f10 //= 10
            col += 9
            qm[:, col] = ord(":")
            col += 1
            qm[:, col] = np.where(sb, ord("B"), ord("A"))
            col += 1
            qm[:, col] = ord(":")
            col += 1
            m10 = mem + 1  # serial: 1-based member id (unique, stable)
            for d in range(8, -1, -1):
                qm[:, col + d] = digits0 + (m10 % 10).astype(np.uint8)
                m10 //= 10
            col += 9
            qm[:, col : col + len(bdelim_bytes)] = bdelim_bytes
            col += len(bdelim_bytes)
            # barcode halves in strand order (A: a.b, B: b.a)
            left = np.where(sb[:, None], umi_b[frag], umi_a[frag])
            right = np.where(sb[:, None], umi_a[frag], umi_b[frag])
            bc = np.empty((C, 2 * U), dtype=np.uint8)
            bc[:, :U] = left
            bc[:, U:] = right
            if bc_err.any():
                hit = np.nonzero(bc_err[mem])[0]
                if hit.size:
                    ppos = bc_err_pos[mem[hit]]
                    bc[hit, ppos] = (bc[hit, ppos] + bc_err_delta[mem[hit]]) % 4
            qm[:, col : col + U] = base_bytes[bc[:, :U]]
            qm[:, col + U : col + U + len(sep_bytes)] = sep_bytes
            qm[:, col + U + len(sep_bytes) :] = base_bytes[bc[:, U:]]

            blob = encode_records(
                qm.ravel(),
                np.full(C, qname_w, np.int64),
                flags,
                np.zeros(C, np.int64),
                p.astype(np.int64),
                np.full(C, 60, np.int64),
                np.full(C, (L << 4) | 0, np.uint32),
                np.ones(C, np.int64),
                np.zeros(C, np.int64),
                mp.astype(np.int64),
                tlen.astype(np.int64),
                codes.ravel(),
                np.full(C, L, np.int64),
                quals.ravel(),
                np.empty(0, np.uint8),
                np.zeros(C, np.int64),
            )
            writer.write_encoded(blob)
        writer.close()
    except BaseException:
        writer.abort()
        raise
    return SimTruthFast(lo=lo, hi=hi, a_size=a_size, b_size=b_size)


# --------------------------------------------------------------------------
# Adversarial generator: real-data hostility on synthetic ground truth
# --------------------------------------------------------------------------

def simulate_bam_adversarial(path: str, seed: int = 0,
                             bdelim: str = DEFAULT_BDELIM) -> dict:
    """Write a small coordinate-sorted barcoded BAM stuffed with the edge
    cases real sequencing data throws at a pipeline (VERDICT r2 missing #5:
    no real BAM can reach this offline environment, so the simulator is
    extended adversarially instead): indel/soft-clip/hard-clip cigars,
    mixed and odd read lengths inside one family, ambiguity bases, missing
    quals, exotic-but-legal tag types, long qnames, flag soup
    (secondary/supplementary/qcfail/duplicate), placed-unmapped mates and
    fully-unplaced pairs, families anchored at position 0 and at the
    reference edge.

    Returns a dict of expected stage-routing counts for assertions:
    ``bad_reads`` (reads the SSCS stage must route to badReads.bam) and
    ``good_reads`` (reads that must enter family grouping).
    """
    rng = np.random.default_rng(seed)
    ref_name, ref_len = "chrAdv", 400_000
    header = BamHeader.from_refs([(ref_name, ref_len)])
    reads: list[BamRead] = []
    expect = {"bad_reads": 0, "good_reads": 0}

    def qual(n, lo=25, hi=41):
        return rng.integers(lo, hi, n).astype(np.uint8)

    def add_pair(qname, pos, mpos, seq1, seq2, cigar1, cigar2, flag_extra1=0,
                 flag_extra2=0, q1=None, q2=None, tags1=None, tags2=None,
                 good=True, r1_first=True):
        # r1_first mirrors simulate_bam's strand model: strand A reads are
        # (read1 fwd @ pos, read2 rev @ mpos); the complementary strand B
        # flips the read-number bits — the flip the duplex tag pairs on.
        tlen = mpos - pos + len(seq2)
        reads.append(BamRead(
            qname=qname,
            flag=0x1 | 0x2 | 0x20 | (0x40 if r1_first else 0x80) | flag_extra1,
            ref=ref_name, pos=pos, mapq=60, cigar=cigar1,
            mate_ref=ref_name, mate_pos=mpos, tlen=tlen,
            seq=seq1, qual=qual(len(seq1)) if q1 is None else q1,
            tags=dict(tags1 or {}),
        ))
        reads.append(BamRead(
            qname=qname,
            flag=0x1 | 0x2 | 0x10 | (0x80 if r1_first else 0x40) | flag_extra2,
            ref=ref_name, pos=mpos, mapq=60, cigar=cigar2,
            mate_ref=ref_name, mate_pos=pos, tlen=-tlen,
            seq=seq2, qual=qual(len(seq2)) if q2 is None else q2,
            tags=dict(tags2 or {}),
        ))
        bad_flags = 0x4 | 0x8 | 0x100 | 0x200 | 0x800
        for fx in (flag_extra1, flag_extra2):
            if good and not (fx & bad_flags):
                expect["good_reads"] += 1
            else:
                expect["bad_reads"] += 1

    def bc(u1, u2):
        return f"{u1}{BARCODE_SEP}{u2}"

    serial = 0

    def qn(tag, barcode, extra=""):
        nonlocal serial
        serial += 1
        return f"adv:{tag}:{serial}{extra}{bdelim}{barcode}"

    # 1. plain duplex families (baseline population, incl. one at pos 0 and
    #    one at the reference edge)
    for i, lo in enumerate([0, 5_000, 12_345, ref_len - 260]):
        hi = lo + 150
        u1, u2 = _rand_seq(rng, 6), _rand_seq(rng, 6)
        mol1, mol2 = _rand_seq(rng, 100), _rand_seq(rng, 100)
        for strand, barcode in (("A", bc(u1, u2)), ("B", bc(u2, u1))):
            for _ in range(3):
                name = qn(f"base{i}{strand}", barcode)
                add_pair(name, lo, hi, mol1, mol2,
                         [("M", 100)], [("M", 100)], r1_first=strand == "A")

    # 2. indel/clip cigar families: query-consuming ops sum to seq length;
    #    members disagree on cigar (modal-cigar path) and lengths vary
    u1, u2 = _rand_seq(rng, 6), _rand_seq(rng, 6)
    mol1, mol2 = _rand_seq(rng, 100), _rand_seq(rng, 100)
    cigs = [
        [("S", 5), ("M", 90), ("S", 5)],
        [("M", 40), ("I", 4), ("M", 56)],
        [("M", 30), ("D", 7), ("M", 70)],
        [("H", 12), ("M", 100)],
        [("M", 25), ("N", 500), ("M", 75)],
    ]
    for k, cig in enumerate(cigs):
        name = qn("indel", bc(u1, u2))
        add_pair(name, 20_000, 20_180, mol1, mol2, cig, [("M", 100)])

    # 3. mixed/odd read lengths inside one family + ambiguity bases
    u1, u2 = _rand_seq(rng, 6), _rand_seq(rng, 6)
    for ln in (99, 100, 100, 97):
        s1 = _rand_seq(rng, ln)
        s1 = s1[:10] + "NRYK"[: max(0, min(4, ln - 10))] + s1[14:]
        name = qn("mixlen", bc(u1, u2))
        add_pair(name, 30_000, 30_200, s1, _rand_seq(rng, 100),
                 [("M", ln)], [("M", 100)])

    # 4. missing quals (SAM '*'): qual arrays of size 0
    u1, u2 = _rand_seq(rng, 6), _rand_seq(rng, 6)
    for _ in range(2):
        name = qn("noqual", bc(u1, u2))
        add_pair(name, 40_000, 40_150, _rand_seq(rng, 80), _rand_seq(rng, 80),
                 [("M", 80)], [("M", 80)],
                 q1=np.zeros(0, np.uint8), q2=np.zeros(0, np.uint8))

    # 5. exotic-but-legal tags on every member
    u1, u2 = _rand_seq(rng, 6), _rand_seq(rng, 6)
    tag_soup = {
        "XA": ("A", "c"), "Xc": ("c", -12), "XC": ("C", 250),
        "Xs": ("s", -30000), "XS": ("S", 65000), "Xi": ("i", -(1 << 30)),
        "XI": ("I", (1 << 31) + 7), "Xf": ("f", 1.5), "XZ": ("Z", "free text"),
        "XH": ("H", "DEADBEEF"),
        "XB": ("B", ("i", [-1, 0, 1 << 20])),
        "XD": ("B", ("f", [0.5, -2.25])),
    }
    for _ in range(3):
        name = qn("tags", bc(u1, u2))
        add_pair(name, 50_000, 50_160, _rand_seq(rng, 100), _rand_seq(rng, 100),
                 [("M", 100)], [("M", 100)], tags1=tag_soup, tags2=tag_soup)

    # 6. qname edge cases: near-the-255-limit names, punctuation-rich
    u1, u2 = _rand_seq(rng, 6), _rand_seq(rng, 6)
    long_tail = ":".join(["x" * 9] * 18)  # ~180 chars of qname
    for _ in range(2):
        name = qn("longq", bc(u1, u2), extra=":" + long_tail)
        add_pair(name, 60_000, 60_140, _rand_seq(rng, 100), _rand_seq(rng, 100),
                 [("M", 100)], [("M", 100)])
    name = qn("punct.q-n+m=e", bc(u1, u2))
    add_pair(name, 60_500, 60_640, _rand_seq(rng, 100), _rand_seq(rng, 100),
             [("M", 100)], [("M", 100)])

    # 7. flag soup -> badReads routing: secondary, supplementary, qcfail,
    #    mate-unmapped, and fully-unplaced pairs; duplicate-flagged KEPT
    u1, u2 = _rand_seq(rng, 6), _rand_seq(rng, 6)
    add_pair(qn("dup", bc(u1, u2)), 70_000, 70_150,
             _rand_seq(rng, 100), _rand_seq(rng, 100),
             [("M", 100)], [("M", 100)], flag_extra1=0x400, flag_extra2=0x400)
    add_pair(qn("sec", bc(u1, u2)), 70_000, 70_150,
             _rand_seq(rng, 100), _rand_seq(rng, 100),
             [("M", 100)], [("M", 100)], flag_extra1=0x100, flag_extra2=0x800)
    add_pair(qn("qcf", bc(u1, u2)), 70_000, 70_150,
             _rand_seq(rng, 100), _rand_seq(rng, 100),
             [("M", 100)], [("M", 100)], flag_extra1=0x200, flag_extra2=0x200)
    # placed-unmapped mate: R1 mapped but mate-unmapped bit -> bad
    reads.append(BamRead(
        qname=qn("mu", bc(u1, u2)), flag=0x1 | 0x8 | 0x40, ref=ref_name,
        pos=71_000, mapq=60, cigar=[("M", 60)], mate_ref=ref_name,
        mate_pos=71_000, tlen=0, seq=_rand_seq(rng, 60), qual=qual(60),
    ))
    reads.append(BamRead(  # its unmapped mate, placed at same pos
        qname=qn("mu2", bc(u1, u2)), flag=0x1 | 0x4 | 0x80, ref=ref_name,
        pos=71_000, mapq=0, cigar=[], mate_ref=ref_name, mate_pos=71_000,
        tlen=0, seq=_rand_seq(rng, 60), qual=qual(60),
    ))
    expect["bad_reads"] += 2
    # fully-unplaced pair
    for fl in (0x1 | 0x4 | 0x8 | 0x40, 0x1 | 0x4 | 0x8 | 0x80):
        reads.append(BamRead(
            qname=qn("nc", bc(u1, u2)), flag=fl, ref=None, pos=-1, mapq=0,
            cigar=[], mate_ref=None, mate_pos=-1, tlen=0,
            seq=_rand_seq(rng, 50), qual=qual(50),
        ))
        expect["bad_reads"] += 1
    # barcode-less qname -> bad
    reads.append(BamRead(
        qname="adv:nobc:999", flag=0x1 | 0x2 | 0x40, ref=ref_name, pos=72_000,
        mapq=60, cigar=[("M", 50)], mate_ref=ref_name, mate_pos=72_100,
        tlen=150, seq=_rand_seq(rng, 50), qual=qual(50),
    ))
    expect["bad_reads"] += 1

    # 8. singleton + complementary-strand singleton with indel cigars
    #    (rescue over non-trivial cigars)
    u1, u2 = _rand_seq(rng, 6), _rand_seq(rng, 6)
    mol = _rand_seq(rng, 100)
    add_pair(qn("resA", bc(u1, u2)), 80_000, 80_170, mol, _rand_seq(rng, 100),
             [("S", 3), ("M", 94), ("S", 3)], [("M", 100)])
    add_pair(qn("resB", bc(u2, u1)), 80_000, 80_170, mol, _rand_seq(rng, 100),
             [("S", 3), ("M", 94), ("S", 3)], [("M", 100)], r1_first=False)

    tmp = path + ".unsorted"
    with BamWriter(tmp, header) as w:
        for read in reads:
            w.write(read)
    sort_bam(tmp, path)
    import os

    os.unlink(tmp)
    return expect


def simulate_fastq_pairs(out_prefix: str, cfg: SimConfig,
                         chunk_members: int = 500_000,
                         level: int = 4) -> tuple[str, str, str]:
    """Vectorized raw paired-FASTQ generator for the fastq2bam flow
    (SURVEY.md §3.1 at benchmark scale — VERDICT r3 item 6).

    Emits ``<prefix>_R1.fastq.gz`` / ``<prefix>_R2.fastq.gz`` (BGZF) plus
    ``<prefix>.ref.fa``: every read is ``UMI + 'T' spacer + genomic
    insert-end`` — the ``--bpattern NNNNNNT``-shaped inline-barcode layout
    extract_barcodes exists to strip — with substitution errors at
    ``cfg.error_rate`` on the genomic part only, so the builtin
    (substitutions-only) aligner can place every read.  Family structure
    (Poisson sizes, duplex dropout, swapped-half barcodes on strand B)
    matches ``simulate_bam_fast``'s statistical model.

    Pure numpy byte assembly: whole chunks of fixed-width FASTQ records are
    built as one (n, rec_len) matrix and BGZF-deflated in batches.
    """
    from consensuscruncher_tpu.io import bgzf

    rng = np.random.default_rng(cfg.seed)
    L, U = cfg.read_len, cfg.umi_len
    Lg = L - U - 1  # genomic bases per read (after UMI + 'T' spacer)
    if Lg < 30:
        raise ValueError("read_len too short for UMI + spacer + useful insert")
    nF = cfg.n_fragments
    if cfg.ref_len < 1000 + 4 * L:
        raise ValueError("ref_len too small for read placement")

    base_lut = np.frombuffer(BASES.encode(), np.uint8)
    ref_codes = rng.integers(0, 4, cfg.ref_len, dtype=np.int8).astype(np.uint8)
    fasta_path = f"{out_prefix}.ref.fa"
    # vectorized FASTA body (write_fasta's per-line loop is minutes at 100M)
    with open(fasta_path, "wb") as fh:
        fh.write(f">{cfg.ref_name}\n".encode())
        width = 70
        pad = (-len(ref_codes)) % width
        mat = np.full(len(ref_codes) + pad, ord("A"), np.uint8)
        mat[: len(ref_codes)] = base_lut[ref_codes]
        mat = mat.reshape(-1, width)
        out = np.full((mat.shape[0], width + 1), ord("\n"), np.uint8)
        out[:, :width] = mat
        body = out.reshape(-1)
        if pad:
            # drop the padding of the final line, keep its newline
            body = np.concatenate([body[: -(pad + 1)], body[-1:]])
        fh.write(body.tobytes())

    # --- fragment/member tables (vectorized) -----------------------------
    max_insert = 2 * Lg + Lg // 2  # hi = lo + insert must stay on the ref
    if cfg.ref_len < max_insert + 1000:
        raise ValueError("ref_len too small for the insert-size jitter")
    lo = rng.integers(500, cfg.ref_len - max_insert - 500, nF, dtype=np.int64)
    insert = 2 * Lg + rng.integers(0, Lg // 2, nF, dtype=np.int64)
    hi = lo + insert  # exclusive end
    umi_a = rng.integers(0, 4, (nF, U), dtype=np.int8).astype(np.uint8)
    umi_b = rng.integers(0, 4, (nF, U), dtype=np.int8).astype(np.uint8)
    a_size = np.maximum(1, rng.poisson(cfg.mean_family_size, nF)).astype(np.int32)
    duplex = rng.random(nF) < cfg.duplex_fraction
    b_size = np.where(duplex, np.maximum(1, rng.poisson(cfg.mean_family_size, nF)),
                      0).astype(np.int32)
    counts = (a_size + b_size).astype(np.int64)
    M = int(counts.sum())
    frag_of = np.repeat(np.arange(nF, dtype=np.int64), counts)
    starts = np.zeros(nF, dtype=np.int64)
    np.cumsum(counts[:-1], out=starts[1:])
    strand_b = (np.arange(M, dtype=np.int64) - starts[frag_of]) >= a_size[frag_of]

    comp = np.array([3, 2, 1, 0], np.uint8)
    qname_w = 2 + 9 + 1 + 1  # '@f' + 9-digit member serial + ':' + strand
    r1_path, r2_path = f"{out_prefix}_R1.fastq.gz", f"{out_prefix}_R2.fastq.gz"
    w1 = bgzf.BgzfWriter(r1_path, level=level)
    w2 = bgzf.BgzfWriter(r2_path, level=level)
    try:
        for c0 in range(0, M, chunk_members):
            c1 = min(M, c0 + chunk_members)
            n = c1 - c0
            fr = frag_of[c0:c1]
            sb = strand_b[c0:c1]
            # genomic inserts: R1 end = fragment start (fwd), R2 end =
            # fragment end (revcomp); strand B swaps the physical ends.
            fwd = ref_codes[lo[fr, None] + np.arange(Lg, dtype=np.int64)]
            rev = comp[ref_codes[(hi[fr, None] - 1) - np.arange(Lg, dtype=np.int64)]]
            g1 = np.where(sb[:, None], rev, fwd)
            g2 = np.where(sb[:, None], fwd, rev)
            # substitution errors on genomic parts (delta 1..3 mod 4)
            for g in (g1, g2):
                err = rng.random((n, Lg)) < cfg.error_rate
                delta = rng.integers(1, 4, (n, Lg), dtype=np.int8).astype(np.uint8)
                g[err] = (g[err] + delta[err]) & 3
            u1 = np.where(sb[:, None], umi_b[fr], umi_a[fr])
            u2 = np.where(sb[:, None], umi_a[fr], umi_b[fr])

            # fixed-width records: @f<serial>:<A|B>\n SEQ\n +\n QUAL\n
            serial = np.arange(c0, c1, dtype=np.int64)
            qn = np.full((n, qname_w), ord("0"), np.uint8)
            qn[:, 0] = ord("@")
            qn[:, 1] = ord("f")
            digits = serial[:, None] // 10 ** np.arange(8, -1, -1, dtype=np.int64) % 10
            qn[:, 2:11] = (ord("0") + digits).astype(np.uint8)
            qn[:, 11] = ord(":")
            qn[:, 12] = np.where(sb, ord("B"), ord("A")).astype(np.uint8)
            rec_len = qname_w + 1 + L + 1 + 2 + L + 1
            for w, u, g in ((w1, u1, g1), (w2, u2, g2)):
                rec = np.empty((n, rec_len), np.uint8)
                rec[:, :qname_w] = qn
                col = qname_w
                rec[:, col] = ord("\n"); col += 1
                rec[:, col:col + U] = base_lut[u]
                rec[:, col + U] = ord("T")
                rec[:, col + U + 1:col + L] = base_lut[g]
                col += L
                rec[:, col] = ord("\n"); col += 1
                rec[:, col] = ord("+"); col += 1
                rec[:, col] = ord("\n"); col += 1
                rec[:, col:col + L] = 33 + 35  # Q35 flat
                col += L
                rec[:, col] = ord("\n")
                w.write(rec.reshape(-1).tobytes())
    finally:
        w1.close()
        w2.close()
    return r1_path, r2_path, fasta_path
