"""Deterministic wire-fault layer for the serve fleet (netchaos).

Every fleet socket — client->router submits, router->worker forwards,
the standby's probes of the active — is opened by :class:`ServeClient`,
which passes each freshly-connected socket through :func:`maybe_wrap`.
With ``CCT_NETCHAOS`` unset that is a no-op returning the raw socket;
with a spec armed the socket comes back wrapped in a
:class:`ChaosSocket` that injects **seeded, per-link** wire faults:

  partition    frames vanish (connects refused outbound, reads starve
               inbound) — directional, so ``a->b`` alone is an
               *asymmetric* partition
  latency:MS   fixed delay before every send/recv on the link
  jitter:MS    seeded 0..MS delay per frame
  torn:OFF     the frame is cut at byte OFF and the write side
               half-closed — the peer holds a torn tail
  truncate     a frame prefix is delivered and the rest never comes
               (half-frame stall; the read deadline reaps it)
  dup          the frame is delivered twice (the seq envelope must
               absorb the duplicate below the idempotency layer)
  corrupt      one seeded byte of the frame is flipped (the crc
               envelope must catch it before anything parses it)
  reset        half the frame, then a connection reset mid-message
  blackhole    the connection accepts and the request is sent, but no
               answer ever arrives

Spec grammar (``;``-separated entries)::

  CCT_NETCHAOS="seed=7;client->r0=corrupt@3;r1->r0=partition;r0<->w1=latency:50"

- ``seed=N`` seeds every per-frame decision (byte offsets, jitter) —
  the schedule is a pure function of (seed, link, kind, firing index);
- ``A->B=kind[@times][:arg]`` arms ``kind`` on frames **from A to B**
  (``A<->B`` arms both directions); ``*`` is a wildcard on either side;
  ``@times`` caps how often the rule fires in this process.
- ``CCT_NETCHAOS=@/path/to/spec`` reads the spec from a file,
  re-checked on every access — a conductor partitions and heals links
  live by rewriting one file the whole fleet watches.  A rewrite
  re-parses the spec, so ``@times`` budgets restart with it.

Identity: a process knows itself via ``CCT_NETCHAOS_NODE`` (default
``client``); the peer name is derived from the address being dialed —
a unix socket path's basename minus ``.sock`` (the fleet convention:
``w0.sock``, ``r1.sock``), or ``host:port`` for TCP.

The layer attacks the WIRE, never the protocol: everything it injects
must be survivable by the deadline/envelope/idempotency machinery, and
the chaos-conductor invariants (no acked job lost, goldens
byte-identical, epochs monotone) hold under any spec.
"""

from __future__ import annotations

import os
import socket
import time
import zlib

KINDS = ("partition", "latency", "jitter", "torn", "truncate", "dup",
         "corrupt", "reset", "blackhole")

#: kinds whose effect needs a numeric argument
_ARG_KINDS = ("latency", "jitter", "torn")


class NetChaosSpecError(ValueError):
    """A malformed CCT_NETCHAOS spec — refused loudly, never guessed at."""


class Rule:
    """One armed fault: ``src -> dst = kind[@times][:arg]``."""

    def __init__(self, src: str, dst: str, kind: str,
                 times: int | None = None, arg: float | None = None):
        if kind not in KINDS:
            raise NetChaosSpecError(
                f"netchaos: unknown fault kind {kind!r} "
                f"(known: {', '.join(KINDS)})")
        if arg is None and kind in _ARG_KINDS:
            raise NetChaosSpecError(
                f"netchaos: kind {kind!r} needs an argument "
                f"({kind}:<number>)")
        self.src = src
        self.dst = dst
        self.kind = kind
        self.times = times
        self.arg = arg
        self.fired = 0

    def matches(self, src: str, dst: str) -> bool:
        return (self.src in ("*", src)) and (self.dst in ("*", dst))

    def active(self) -> bool:
        return self.times is None or self.fired < self.times

    def fire(self) -> int:
        """Consume one firing; returns the firing ordinal (0-based)."""
        n = self.fired
        self.fired += 1
        return n

    @property
    def link(self) -> str:
        return f"{self.src}->{self.dst}"


def parse_spec(text: str) -> tuple[int, list[Rule]]:
    """``(seed, rules)`` from a spec string; empty/blank -> no rules."""
    seed = 0
    rules: list[Rule] = []
    for raw in str(text or "").split(";"):
        entry = raw.strip()
        if not entry:
            continue
        if "=" not in entry:
            raise NetChaosSpecError(
                f"netchaos: bad entry {entry!r} (want link=kind or seed=N)")
        left, right = entry.split("=", 1)
        left, right = left.strip(), right.strip()
        if left == "seed":
            seed = int(right)
            continue
        if "<->" in left:
            a, b = (p.strip() for p in left.split("<->", 1))
            pairs = [(a, b), (b, a)]
        elif "->" in left:
            a, b = (p.strip() for p in left.split("->", 1))
            pairs = [(a, b)]
        else:
            raise NetChaosSpecError(
                f"netchaos: bad link {left!r} (want a->b or a<->b)")
        arg: float | None = None
        if ":" in right:
            right, argtext = right.split(":", 1)
            arg = float(argtext)
        times: int | None = None
        if "@" in right:
            right, timestext = right.split("@", 1)
            times = int(timestext)
        kind = right.strip()
        for src, dst in pairs:
            if not src or not dst:
                raise NetChaosSpecError(
                    f"netchaos: empty endpoint in {entry!r}")
            rules.append(Rule(src, dst, kind, times=times, arg=arg))
    return seed, rules


def peer_name(address) -> str:
    """Link endpoint name for an address: unix socket basename minus
    ``.sock`` (fleet convention), or ``host:port`` for TCP."""
    if isinstance(address, (tuple, list)):
        return f"{address[0]}:{address[1]}"
    base = os.path.basename(str(address))
    return base[:-5] if base.endswith(".sock") else base


def self_name() -> str:
    return os.environ.get("CCT_NETCHAOS_NODE") or "client"


class ChaosLayer:
    """A parsed spec plus its per-rule firing state (process-local)."""

    def __init__(self, spec_text: str):
        self.spec_text = str(spec_text or "")
        self.seed, self.rules = parse_spec(self.spec_text)

    def decide(self, rule: Rule, ordinal: int, salt: str = "") -> int:
        """Deterministic per-firing integer — a pure function of
        (seed, link, kind, ordinal), independent of process timing."""
        token = f"{self.seed}|{rule.link}|{rule.kind}|{ordinal}|{salt}"
        return zlib.crc32(token.encode()) & 0x7FFFFFFF

    def wrap(self, sock, peer: str):
        """The interposition point: returns ``sock`` untouched when no
        rule names the (self, peer) link in either direction."""
        me = self_name()
        out_rules = [r for r in self.rules if r.matches(me, peer)]
        in_rules = [r for r in self.rules if r.matches(peer, me)]
        if not out_rules and not in_rules:
            return sock
        return ChaosSocket(sock, self, out_rules, in_rules)


class ChaosSocket:
    """Socket proxy applying the layer's rules to this connection.

    Outbound rules (self -> peer) act on :meth:`connect`/:meth:`sendall`;
    inbound rules (peer -> self) act on :meth:`recv`.  Everything else
    delegates to the wrapped socket."""

    def __init__(self, sock, layer: ChaosLayer,
                 out_rules: list[Rule], in_rules: list[Rule]):
        self._sock = sock
        self._layer = layer
        self._out = out_rules
        self._in = in_rules
        self._blackholed = False    # request sent into a void
        self._reset_after = None    # bytes delivered, then reset
        self._eof_after = False     # truncate(in): prefix then silence
        self._pending = b""         # dup(in) second copy

    def __getattr__(self, name):
        return getattr(self._sock, name)

    # ------------------------------------------------------------ helpers

    def _first(self, rules: list[Rule], *kinds: str) -> Rule | None:
        for r in rules:
            if r.kind in kinds and r.active():
                return r
        return None

    def _delay(self, rules: list[Rule]) -> None:
        r = self._first(rules, "latency")
        if r is not None:
            r.fire()
            time.sleep(float(r.arg) / 1000.0)
        r = self._first(rules, "jitter")
        if r is not None:
            n = r.fire()
            ms = self._layer.decide(r, n) % (int(r.arg) + 1)
            time.sleep(ms / 1000.0)

    @staticmethod
    def _flip(data: bytes, idx: int) -> bytes:
        b = data[idx]
        x = b ^ 0x20
        if x in (0x0A, 0x0D):
            x = b ^ 0x21
        return data[:idx] + bytes([x]) + data[idx + 1:]

    # --------------------------------------------------------------- wire

    def connect(self, address):
        r = self._first(self._out, "partition")
        if r is not None:
            r.fire()
            raise ConnectionRefusedError(
                f"netchaos: link {r.link} partitioned")
        self._delay(self._out)
        return self._sock.connect(address)

    def sendall(self, data: bytes):
        self._delay(self._out)
        r = self._first(self._out, "partition")
        if r is not None:
            r.fire()
            return None  # the frame vanishes; the reply deadline notices
        r = self._first(self._out, "blackhole")
        if r is not None:
            r.fire()
            self._blackholed = True
            return self._sock.sendall(data)
        r = self._first(self._out, "torn")
        if r is not None:
            r.fire()
            cut = max(0, min(len(data), int(r.arg)))
            if cut:
                self._sock.sendall(data[:cut])
            try:
                self._sock.shutdown(socket.SHUT_WR)
            except OSError:
                pass
            return None  # the peer holds a torn tail and must reap it
        r = self._first(self._out, "truncate")
        if r is not None:
            n = r.fire()
            cut = 1 + self._layer.decide(r, n) % max(1, len(data) - 1)
            return self._sock.sendall(data[:cut])
        r = self._first(self._out, "reset")
        if r is not None:
            r.fire()
            half = len(data) // 2
            if half:
                self._sock.sendall(data[:half])
            try:
                self._sock.setsockopt(
                    socket.SOL_SOCKET, socket.SO_LINGER,
                    b"\x01\x00\x00\x00\x00\x00\x00\x00")
            except OSError:
                pass
            self._sock.close()
            raise ConnectionResetError(
                f"netchaos: link {r.link} reset mid-send")
        r = self._first(self._out, "corrupt")
        if r is not None and len(data) > 1:
            n = r.fire()
            idx = self._layer.decide(r, n) % (len(data) - 1)
            data = self._flip(data, idx)
        r = self._first(self._out, "dup")
        if r is not None:
            r.fire()
            self._sock.sendall(data)
        return self._sock.sendall(data)

    def recv(self, bufsize: int) -> bytes:
        if self._pending:
            out, self._pending = self._pending[:bufsize], \
                self._pending[bufsize:]
            return out
        if self._eof_after:
            return b""
        if self._reset_after is not None:
            raise ConnectionResetError("netchaos: connection reset by peer")
        r = self._first(self._in, "partition", "blackhole")
        if r is not None or self._blackholed:
            if r is not None:
                r.fire()
            raise socket.timeout(
                "netchaos: no answer will ever arrive on this link")
        self._delay(self._in)
        chunk = self._sock.recv(bufsize)
        if not chunk:
            return chunk
        r = self._first(self._in, "reset")
        if r is not None:
            r.fire()
            self._reset_after = True
            return chunk[:max(1, len(chunk) // 2)]
        r = self._first(self._in, "truncate")
        if r is not None:
            n = r.fire()
            cut = 1 + self._layer.decide(r, n) % max(1, len(chunk) - 1)
            self._eof_after = True
            return chunk[:cut]
        r = self._first(self._in, "corrupt")
        if r is not None and len(chunk) > 1:
            n = r.fire()
            idx = self._layer.decide(r, n) % (len(chunk) - 1)
            chunk = self._flip(chunk, idx)
        r = self._first(self._in, "dup")
        if r is not None:
            r.fire()
            self._pending = chunk
        return chunk


# --------------------------------------------------------- process layer

_cached: tuple | None = None   # (cache key, ChaosLayer | None)


def _spec_source() -> tuple[object, str] | None:
    """``(cache_key, spec_text)`` for the current environment, or None
    when netchaos is unarmed.  ``@file`` specs key on (path, mtime,
    size) so a conductor's rewrite is picked up on the next access."""
    spec = os.environ.get("CCT_NETCHAOS") or ""
    if not spec.strip():
        return None
    if spec.startswith("@"):
        path = spec[1:]
        try:
            st = os.stat(path)
            with open(path, "r", encoding="utf-8") as fh:
                text = fh.read()
        except OSError:
            return None  # spec file not there (yet): unarmed
        return (path, st.st_mtime_ns, st.st_size, text), text
    return spec, spec


def get() -> ChaosLayer | None:
    """The process's chaos layer, or None when unarmed.  Cached on the
    spec source so per-rule ``@times`` budgets persist across sockets;
    a changed env value or rewritten spec file re-parses (and restarts
    the budgets — the documented live-control contract)."""
    global _cached
    source = _spec_source()
    if source is None:
        _cached = None
        return None
    key, text = source
    if _cached is not None and _cached[0] == key:
        return _cached[1]
    layer = ChaosLayer(text)
    _cached = (key, layer)
    return layer


def reset() -> None:
    """Drop the cached layer (tests arm/disarm specs mid-process)."""
    global _cached
    _cached = None


def maybe_wrap(sock, address):
    """The one call sites use: wrap ``sock`` for the link to ``address``
    when a spec is armed and names it; the raw socket otherwise."""
    layer = get()
    if layer is None:
        return sock
    return layer.wrap(sock, peer_name(address))
