"""Shared ragged-run primitives: the one home of the cumsum/repeat index math.

Every columnar subsystem moves data as "runs" — per-record byte spans of
varying length. Five near-identical arange-minus-repeat implementations had
accreted across io/columnar, io/encode, parallel/batching and
stages/grouping; this module owns the pattern (and its fast paths) so a fix
or optimization lands everywhere at once.

- :func:`gather_runs` — pull runs out of a buffer into one packed array.
- :func:`scatter_runs` — write runs into a flat output (packed or per-run
  addressed source), with a uniform-length fast path and a strided-slice
  fast path for evenly spaced destinations (matrix rows).
- :func:`fill_runs` — constant-fill runs.
"""

from __future__ import annotations

import numpy as np

from consensuscruncher_tpu.io import native as _native


def _native_ok(*arrays: np.ndarray) -> bool:
    """Native memcpy path applies to C-contiguous arrays (any itemsize —
    element offsets scale to bytes) when the codec library is loadable."""
    return _native.available() and all(a.flags.c_contiguous for a in arrays)


def _run_index(starts: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Flat index array covering run i at starts[i] for lens[i] elements."""
    total = int(lens.sum())
    off = np.zeros(len(lens), dtype=np.int64)
    np.cumsum(lens[:-1], out=off[1:])
    return (
        np.arange(total, dtype=np.int64)
        - np.repeat(off, lens)
        + np.repeat(starts.astype(np.int64), lens)
    )


def gather_runs(buf: np.ndarray, starts: np.ndarray, lengths: np.ndarray):
    """Gather ``n`` variable-length runs into one packed array.

    Returns ``(data, offsets)`` with ``offsets`` shaped ``(n+1,)`` — run
    ``i`` is ``data[offsets[i]:offsets[i+1]]``.
    """
    lengths = lengths.astype(np.int64)
    off = np.zeros(len(lengths) + 1, dtype=np.int64)
    np.cumsum(lengths, out=off[1:])
    total = int(off[-1])
    if total == 0:
        return np.empty(0, dtype=buf.dtype), off
    n = len(lengths)
    if _native_ok(buf):
        out = np.empty(total, dtype=buf.dtype)
        _native.copy_runs(buf, starts, out, off[:-1], lengths)
        return out, off
    # Uniform-length fast path (fixed-length reads dominate real BAMs): one
    # 2-D gather instead of three total-length int64 index arrays.
    if n and int(lengths[0]) and (lengths == lengths[0]).all():
        l0 = int(lengths[0])
        out = buf[starts.astype(np.int64)[:, None] + np.arange(l0, dtype=np.int64)]
        return out.reshape(-1), off
    return buf[_run_index(starts, lengths)], off


def scatter_runs(out: np.ndarray, dst_starts: np.ndarray, src: np.ndarray,
                 lens: np.ndarray, src_starts: np.ndarray | None = None) -> None:
    """``out[dst_starts[i]:+lens[i]] = src run i``.

    Source runs are packed tight in ``src`` (cumsum offsets) when
    ``src_starts`` is None, else addressed per run at ``src_starts[i]``.
    """
    lens = lens.astype(np.int64)
    total = int(lens.sum())
    if total == 0:
        return
    n = len(lens)
    if out.dtype == src.dtype and _native_ok(out, src):
        if src_starts is None:
            src_starts = np.zeros(n, dtype=np.int64)
            np.cumsum(lens[:-1], out=src_starts[1:])
        _native.copy_runs(src, src_starts, out, dst_starts, lens)
        return
    if n and (lens == lens[0]).all():
        l0 = int(lens[0])
        if src_starts is None:
            vals = src[:total].reshape(n, l0)
        else:
            vals = src[src_starts.astype(np.int64)[:, None] + np.arange(l0)]
        d = dst_starts.astype(np.int64)
        # evenly strided destinations (rows of a matrix) write as one
        # strided slice assignment — near-memcpy
        if n > 1:
            step = np.diff(d)
            if (step == step[0]).all() and int(step[0]) >= l0:
                view = np.lib.stride_tricks.as_strided(
                    out[int(d[0]):], shape=(n, l0),
                    strides=(int(step[0]) * out.itemsize, out.itemsize),
                    writeable=True,
                )
                view[:] = vals
                return
        out[d[:, None] + np.arange(l0)] = vals
        return
    if src_starts is None:  # tight runs: flattened source order is sequential
        out[_run_index(dst_starts, lens)] = src[:total]
        return
    out[_run_index(dst_starts, lens)] = src[_run_index(src_starts, lens)]


def fill_runs(out: np.ndarray, dst_starts: np.ndarray, lens: np.ndarray,
              value) -> None:
    """``out[dst_starts[i]:+lens[i]] = value`` for every run."""
    lens = lens.astype(np.int64)
    if int(lens.sum()) == 0:
        return
    if out.dtype.itemsize == 1 and _native_ok(out):
        _native.fill_runs_native(out, dst_starts, lens, int(value))
        return
    out[_run_index(dst_starts, lens)] = value
