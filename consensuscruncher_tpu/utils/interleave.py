"""Deterministic interleaving model checker core (loom-lite).

Chaos runs sample schedules; this module *enumerates* them.  A
:class:`Runner` executes a scripted scenario's tasks on real threads but
cooperatively: exactly one task runs at a time, and control returns to
the scheduler at every **yield point** — TrackedLock / TrackedCondition
acquire and release, ``sync_probe`` sites, and explicit
``sanitize.yield_point(tag)`` calls on the serve plane's protocol
boundaries (journal replay, ack boundaries, ring-view scans).  Because
the scheduler never runs a task whose next lock is owned by another
task, the underlying acquires never block, so a *schedule* — the list
of "which ready task goes next" decisions — fully determines the
execution.  An :class:`Explorer` then walks the schedule tree
depth-first: run once following defaults, and for every decision point
branch into each not-taken alternative whose pending action could have
*conflicted* with the chosen one (DPOR-lite — independent actions
commute, so permuting them cannot change any reachable state and the
branch is pruned).  Conflicts are judged by resource family (the first
dotted component of the yield tag or lock name), deliberately coarse:
``journal.replay`` and ``journal.lock`` conflict even though one is a
file read and the other a mutex, because they meet at the journal file.

Virtual time: ``Runner.clock`` counts scheduling steps; scenarios that
need timestamps read it instead of the wall clock, so a schedule replays
bit-identically.

Supported scenario shape: tasks that run to completion through lock
regions and yield points.  ``Condition.wait`` is rejected with a clear
error — a parked waiter needs a notion of notify-edges this model
doesn't have (scenarios drive schedulers with ``start=False`` and never
park).  Deadlock (no runnable task, live tasks remain) is detected,
reported as a violation, and the run is aborted by raising
:class:`TaskAbort` through every parked task so no threads leak.

Stdlib-only, jax-free, import-cheap; the only repo import is
``utils.sanitize`` for hook installation.
"""

from __future__ import annotations

import random
import threading

from . import sanitize


class InterleaveError(RuntimeError):
    """Scenario used an operation the cooperative model cannot schedule."""


class TaskAbort(BaseException):
    """Raised inside parked tasks to unwind an abandoned run.  Derives
    from BaseException so scenario-level ``except Exception`` handlers
    (retry loops, error replies) cannot swallow the unwind."""


def _family(resource: str) -> str:
    return resource.split(".", 1)[0]


class _Task:
    __slots__ = ("name", "fn", "thread", "gate", "state", "pending",
                 "error", "result")

    def __init__(self, name: str, fn):
        self.name = name
        self.fn = fn
        self.thread: threading.Thread | None = None
        self.gate = threading.Event()
        self.state = "ready"  # ready | done | failed | aborted
        #: the action this task performs when next scheduled:
        #: ("lock", name, lock_id) | ("yield", tag, None) | None (unknown)
        self.pending: tuple | None = None
        self.error: BaseException | None = None
        self.result = None


class Runner:
    """Execute one schedule of a multi-task scenario cooperatively.

    ``schedule`` is a list of indices into the sorted runnable-task list
    at each step; steps beyond the list take index 0 (the "default
    path").  After :meth:`run`, ``decisions`` records every step's
    runnable set and choice — the Explorer's branching input — and
    ``trace`` the chosen task names.
    """

    def __init__(self, schedule: list[int] | None = None,
                 max_steps: int = 20000):
        self.schedule = list(schedule or [])
        self.max_steps = max_steps
        self.clock = 0
        self.trace: list[str] = []
        #: per step: (names of runnable tasks, their pending families,
        #: chosen index)
        self.decisions: list[tuple[tuple[str, ...], tuple[str, ...], int]] = []
        self.deadlocked = False
        self.ran_off_steps = False
        self._tasks: list[_Task] = []
        self._by_ident: dict[int, _Task] = {}
        self._owners: dict[int, _Task] = {}
        self._control = threading.Event()
        self._aborting = False

    # ------------------------------------------------------------ tasks

    def spawn(self, name: str, fn) -> None:
        """Register a task; threads start inside :meth:`run`."""
        self._tasks.append(_Task(name, fn))

    def now(self) -> int:
        """Virtual time: scheduling steps taken so far."""
        return self.clock

    @property
    def failures(self) -> dict[str, BaseException]:
        return {t.name: t.error for t in self._tasks
                if t.state == "failed" and t.error is not None}

    def results(self) -> dict[str, object]:
        return {t.name: t.result for t in self._tasks if t.state == "done"}

    # ------------------------------------------- hook protocol (task side)

    def _current(self) -> _Task | None:
        return self._by_ident.get(threading.get_ident())

    def _park(self, task: _Task) -> None:
        self._control.set()
        task.gate.wait()
        task.gate.clear()
        if self._aborting:
            raise TaskAbort()

    def before_acquire(self, name: str, lock) -> None:
        task = self._current()
        if task is None or self._aborting:
            # during abort, unwinding tasks run concurrently; real lock
            # acquires resolve as their peers unwind and release
            return
        if self._owners.get(id(lock)) is task:
            raise InterleaveError(
                f"task {task.name!r} re-acquiring non-reentrant lock "
                f"{name!r} it already holds — guaranteed self-deadlock")
        task.pending = ("lock", name, id(lock))
        self._park(task)
        # single-threaded here: the scheduler only wakes a task whose
        # pending lock is unowned, so this claim cannot race
        self._owners[id(lock)] = task
        task.pending = None

    def after_release(self, name: str, lock) -> None:
        task = self._current()
        if task is None or self._aborting:
            return
        self._owners.pop(id(lock), None)
        task.pending = ("yield", name, None)
        self._park(task)
        task.pending = None

    def on_wait(self, name: str, cond) -> None:
        if self._current() is None or self._aborting:
            return
        raise InterleaveError(
            f"condition wait on {name!r} under the interleave runner — "
            "parked waiters are not schedulable in this model; drive the "
            "scenario with start=False schedulers and wait-free paths")

    def yield_point(self, tag: str) -> None:
        task = self._current()
        if task is None or self._aborting:
            return
        task.pending = ("yield", tag, None)
        self._park(task)
        task.pending = None

    # --------------------------------------------------- scheduler side

    def _body(self, task: _Task) -> None:
        self._by_ident[threading.get_ident()] = task
        task.gate.wait()
        task.gate.clear()
        try:
            if self._aborting:
                task.state = "aborted"
                return
            task.result = task.fn()
            task.state = "done"
        except TaskAbort:
            task.state = "aborted"
        except BaseException as e:  # recorded, judged by the scenario check
            task.state = "failed"
            task.error = e
        finally:
            self._control.set()

    def _step_into(self, task: _Task) -> None:
        self._control.clear()
        task.gate.set()
        self._control.wait()

    def _runnable(self, live: list[_Task]) -> list[_Task]:
        out = []
        for t in live:
            if t.pending is not None and t.pending[0] == "lock":
                owner = self._owners.get(t.pending[2])
                if owner is not None and owner is not t:
                    continue
            out.append(t)
        return out

    def run(self) -> None:
        for task in self._tasks:
            task.thread = threading.Thread(
                target=self._body, args=(task,),
                name=f"interleave-{task.name}", daemon=True)
            task.thread.start()
        try:
            while True:
                live = [t for t in self._tasks if t.state == "ready"]
                if not live:
                    break
                runnable = sorted(self._runnable(live),
                                  key=lambda t: t.name)
                if not runnable:
                    self.deadlocked = True
                    break
                step = len(self.decisions)
                idx = self.schedule[step] if step < len(self.schedule) else 0
                idx = min(idx, len(runnable) - 1)
                chosen = runnable[idx]
                self.decisions.append((
                    tuple(t.name for t in runnable),
                    tuple("*" if t.pending is None else _family(t.pending[1])
                          for t in runnable),
                    idx))
                self.trace.append(chosen.name)
                self._step_into(chosen)
                self.clock += 1
                if self.clock > self.max_steps:
                    self.ran_off_steps = True
                    break
        finally:
            self._abort_parked()

    def _abort_parked(self) -> None:
        """Unwind every still-parked task so no threads leak; no-op when
        all tasks already finished."""
        parked = [t for t in self._tasks if t.state == "ready"]
        if parked:
            self._aborting = True
            # wake everyone at once: unwinds run concurrently so a task
            # blocked on a peer's real lock resolves as the peer unwinds
            for task in parked:
                task.gate.set()
        for task in self._tasks:
            if task.thread is not None:
                task.thread.join(timeout=5.0)


def run_schedule(build, schedule: list[int] | None = None,
                 max_steps: int = 20000) -> tuple[Runner, list[str]]:
    """Run one scenario under one schedule.  ``build(runner)`` spawns the
    tasks against fresh state and returns a ``check() -> list[str]``
    callable evaluated after the run; scheduler-level violations
    (deadlock, step blow-up) are prepended to its result."""
    runner = Runner(schedule, max_steps=max_steps)
    check = build(runner)
    sanitize.set_interleave_hook(runner)
    try:
        runner.run()
    finally:
        sanitize.set_interleave_hook(None)
    msgs: list[str] = []
    if runner.deadlocked:
        held = {name: t.pending for t in runner._tasks
                for name in [t.name] if t.pending is not None}
        msgs.append(f"deadlock: no runnable task (waiting: {held})")
    if runner.ran_off_steps:
        msgs.append(f"schedule exceeded {runner.max_steps} steps")
    msgs.extend(check() or [])
    return runner, msgs


class Explorer:
    """DFS over the schedule tree with seeded ordering and DPOR-lite
    pruning.  ``build`` is the scenario factory passed to
    :func:`run_schedule`; each run gets fresh state, so schedules are
    independent and replayable."""

    def __init__(self, build, *, seed: int = 0, max_schedules: int = 1000,
                 max_steps: int = 20000, dpor: bool = True):
        self.build = build
        self.rng = random.Random(int(seed))
        self.max_schedules = max_schedules
        self.max_steps = max_steps
        self.dpor = dpor

    def _alternatives(self, prefix_len: int, runner: Runner):
        """Branch points discovered by one run: for every decision at or
        beyond the forced prefix, each not-taken runnable whose pending
        family conflicts with the chosen task's (DPOR-lite; ``*`` =
        unknown action = conservative conflict)."""
        taken = [d[2] for d in runner.decisions]
        for step in range(prefix_len, len(runner.decisions)):
            names, families, chosen = runner.decisions[step]
            if len(names) < 2:
                continue
            chosen_fam = families[chosen]
            for alt in range(len(names)):
                if alt == chosen:
                    continue
                if self.dpor and "*" not in (chosen_fam, families[alt]) \
                        and families[alt] != chosen_fam:
                    continue
                yield taken[:step] + [alt]

    def explore(self) -> dict:
        """Returns ``{"schedules", "violations", "deadlocks", "pruned"}``
        where ``violations`` is ``[(schedule, [messages])]`` — replay any
        entry with :func:`run_schedule`."""
        stack: list[list[int]] = [[]]
        seen: set[tuple[int, ...]] = set()
        out = {"schedules": 0, "violations": [], "deadlocks": 0,
               "max_depth": 0}
        while stack and out["schedules"] < self.max_schedules:
            prefix = stack.pop()
            runner, msgs = run_schedule(self.build, prefix,
                                        max_steps=self.max_steps)
            full = tuple(d[2] for d in runner.decisions)
            if full in seen:
                continue
            seen.add(full)
            out["schedules"] += 1
            out["max_depth"] = max(out["max_depth"], len(full))
            if runner.deadlocked:
                out["deadlocks"] += 1
            if msgs:
                out["violations"].append((list(full), msgs))
            branches = list(self._alternatives(len(prefix), runner))
            self.rng.shuffle(branches)
            stack.extend(branches)
        return out
