"""Stage statistics, family-size distributions, and wall-clock tracking.

Reference parity: the per-stage ``*_stats.txt``, ``*.read_families.txt`` and
``*.time_tracker.txt`` outputs (SURVEY.md §5 "Metrics/logging").  Formats are
pinned here (mount was empty): stats files are ``key: value`` lines, family
files are ``size<TAB>count`` sorted by size, and every stage also emits a
structured JSON sidecar (``*_stats.json``) for machines — the TPU-era
addition (families/sec/chip etc.).
"""

from __future__ import annotations

import json
import os
import time
from collections import Counter


class StageStats:
    """Ordered key->value stats with text + JSON emission."""

    def __init__(self, stage: str):
        self.stage = stage
        self._items: dict[str, object] = {}

    def set(self, key: str, value) -> None:
        self._items[key] = value

    def incr(self, key: str, by: int = 1) -> None:
        self._items[key] = self._items.get(key, 0) + by

    def get(self, key: str, default=0):
        return self._items.get(key, default)

    def write(self, path) -> None:
        # Sorted keys: backends touch counters in different orders (e.g. the
        # tpu path batches sscs_written increments), and stats files are
        # parity artifacts — emission order must not encode execution order.
        with open(path, "w") as fh:
            fh.write(f"# {self.stage} stats\n")
            for key in sorted(self._items):
                fh.write(f"{key}: {self._items[key]}\n")
        root, ext = os.path.splitext(str(path))
        json_path = root + ".json" if ext == ".txt" else str(path) + ".json"
        with open(json_path, "w") as fh:
            json.dump({"stage": self.stage, **dict(sorted(self._items.items()))},
                      fh, indent=2)
            fh.write("\n")


class FamilySizeHistogram:
    def __init__(self):
        self._counts: Counter = Counter()

    def add(self, size: int) -> None:
        self._counts[size] += 1

    def add_array(self, sizes) -> None:
        """Bulk add (one bincount instead of a per-family loop)."""
        import numpy as np

        b = np.bincount(np.asarray(sizes, dtype=np.int64))
        for s in np.nonzero(b)[0]:
            self._counts[int(s)] += int(b[s])

    def write(self, path) -> None:
        with open(path, "w") as fh:
            fh.write("family_size\tcount\n")
            for size in sorted(self._counts):
                fh.write(f"{size}\t{self._counts[size]}\n")

    @property
    def counts(self) -> Counter:
        return self._counts

    @staticmethod
    def read(path) -> Counter:
        out: Counter = Counter()
        with open(path) as fh:
            next(fh)
            for line in fh:
                size, count = line.split("\t")
                out[int(size)] = int(count)
        return out


class TimeTracker:
    """Human-readable wall-clock tracker (reference: ``*.time_tracker.txt``)."""

    def __init__(self):
        self._t0 = time.time()
        self._marks: list[tuple[str, float]] = []

    def mark(self, label: str) -> None:
        self._marks.append((label, time.time() - self._t0))

    def as_phases(self) -> dict[str, float]:
        """Per-phase durations (seconds) between consecutive marks."""
        out, prev = {}, 0.0
        for label, t in self._marks:
            out[label] = t - prev
            prev = t
        return out

    def write(self, path) -> None:
        with open(path, "w") as fh:
            prev = 0.0
            for label, t in self._marks:
                fh.write(f"{label}: {t - prev:.2f} s (cumulative {t:.2f} s)\n")
                prev = t
