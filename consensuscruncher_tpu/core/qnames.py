"""Vectorized tag-string and consensus-qname construction.

The block pipeline (stages/grouping.FamilyBlock) carries family identity as
columnar fields; materializing a ``FamilyTag`` object + ``str(tag)`` +
``sscs_qname(tag)`` per family was the last per-family Python in the SSCS
hot path (~10 us/family).  This module builds the same byte strings as
array passes:

- :func:`format_ints` — variable-width decimal rendering (no zero padding,
  byte-identical to ``str(int)`` for non-negative values).
- :func:`build_strings` — assemble per-row byte strings from a mix of
  constant, ragged, and fixed-width segments via native scatter passes.
- :func:`sscs_qnames_columnar` / :func:`tag_strings_columnar` — the exact
  ``core.tags.sscs_qname`` / ``str(FamilyTag)`` byte strings, columnar.
- :func:`lexsort_strings` — emission-order permutation: sort rows by
  arbitrary-length byte strings (padded-and-packed uint64 lexsort), used
  with (rid, pos) numeric leaders to reproduce the object path's
  ``sorted(..., key=(rid, pos, str(tag)))`` order bit-for-bit.

Parity with the scalar oracles is pinned by tests/test_qnames_vec.py.
"""

from __future__ import annotations

import numpy as np

from consensuscruncher_tpu.utils.ragged import scatter_runs

_POW10 = np.array([10**k for k in range(19)], dtype=np.int64)


def format_ints(vals: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Decimal bytes of non-negative ints: returns ``(digit_data, widths)``.

    ``digit_data`` is the tight concatenation of each value's ASCII digits;
    ``widths`` its per-value lengths (``len(str(v))``).  Negative input is a
    contract violation (family coordinates are non-negative once bad reads
    are filtered) and raises.
    """
    vals = np.asarray(vals, dtype=np.int64)
    if vals.size and int(vals.min()) < 0:
        raise ValueError("format_ints: negative values are not representable here")
    widths = np.ones(len(vals), dtype=np.int64)
    for p in _POW10[1:]:
        widths += vals >= p
    off = np.zeros(len(vals) + 1, dtype=np.int64)
    np.cumsum(widths, out=off[1:])
    out = np.empty(int(off[-1]), dtype=np.uint8)
    # digit d (from the least significant): lands at off[i] + widths[i]-1-d
    maxw = int(widths.max(initial=0))
    for d in range(maxw):
        m = widths > d
        idx = off[:-1][m] + widths[m] - 1 - d
        out[idx] = (vals[m] // _POW10[d]) % 10 + ord("0")
    return out, widths


class Seg:
    """One segment of :func:`build_strings` — see factory helpers below."""

    __slots__ = ("kind", "a", "b", "c")

    def __init__(self, kind, a, b=None, c=None):
        self.kind, self.a, self.b, self.c = kind, a, b, c


def const(text: bytes) -> Seg:
    """Same literal bytes on every row."""
    return Seg("const", np.frombuffer(text, np.uint8))


def ragged(data: np.ndarray, lens: np.ndarray, starts: np.ndarray | None = None) -> Seg:
    """Per-row variable-length bytes (tight concat unless ``starts`` given)."""
    return Seg("ragged", np.asarray(data, dtype=np.uint8), np.asarray(lens, dtype=np.int64),
               None if starts is None else np.asarray(starts, dtype=np.int64))


def fixed(matrix: np.ndarray) -> Seg:
    """Per-row fixed-width bytes ((n, w) uint8)."""
    return Seg("fixed", np.asarray(matrix, dtype=np.uint8))


def ints(vals: np.ndarray) -> Seg:
    """Per-row decimal rendering of non-negative ints."""
    data, widths = format_ints(vals)
    return Seg("ragged", data, widths, None)


def build_strings(n: int, segments: list[Seg]) -> tuple[np.ndarray, np.ndarray]:
    """Concatenate segments row-wise: returns ``(data, offsets)`` with row i
    at ``data[offsets[i]:offsets[i+1]]``."""
    widths = np.zeros(n, dtype=np.int64)
    for s in segments:
        if s.kind == "const":
            widths += len(s.a)
        elif s.kind == "fixed":
            widths += s.a.shape[1]
        else:
            widths += s.b
    off = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(widths, out=off[1:])
    out = np.empty(int(off[-1]), dtype=np.uint8)
    cur = off[:-1].copy()
    for s in segments:
        if s.kind == "const":
            w = len(s.a)
            for k in range(w):
                out[cur + k] = s.a[k]
            cur = cur + w
        elif s.kind == "fixed":
            w = s.a.shape[1]
            scatter_runs(out, cur, s.a.reshape(-1), np.full(n, w, np.int64))
            cur = cur + w
        else:
            scatter_runs(out, cur, s.a, s.b, src_starts=s.c)
            cur = cur + s.b
    return out, off


def ref_name_pool(ref_names: list[str]) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Encode reference names (plus the rid==-1 ``"*"`` sentinel in slot -1
    == last) as a byte pool: returns (data, starts, lens, rank) where
    ``rank`` orders names by Python string comparison (used for the
    lower-coordinate-end test in ``sscs_qname``)."""
    names = list(ref_names) + ["*"]
    blobs = [s.encode("ascii") for s in names]
    lens = np.array([len(b) for b in blobs], dtype=np.int64)
    starts = np.zeros(len(blobs), dtype=np.int64)
    np.cumsum(lens[:-1], out=starts[1:])
    data = np.frombuffer(b"".join(blobs), np.uint8)
    order = sorted(range(len(names)), key=lambda i: names[i])
    rank = np.empty(len(names), dtype=np.int64)
    rank[order] = np.arange(len(names))
    return data, starts, lens, rank


def sscs_qnames_columnar(
    bcm: np.ndarray, bclen: np.ndarray,
    rid: np.ndarray, pos: np.ndarray, mrid: np.ndarray, mpos: np.ndarray,
    rn: np.ndarray, rev: np.ndarray,
    pool: tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray],
) -> tuple[np.ndarray, np.ndarray]:
    """Byte-exact ``core.tags.sscs_qname`` over columnar families.

    ``bcm``/``bclen``: per-family barcode byte matrix + lengths; ``rid`` may
    be -1 (renders ``"*"``); ``rn`` in {1,2}; ``rev`` boolean (orientation
    "rev"/"fwd").  Returns (data, offsets).
    """
    data, starts, lens, rank = pool
    rid = np.asarray(rid, dtype=np.int64)
    mrid = np.asarray(mrid, dtype=np.int64)
    pos = np.asarray(pos, dtype=np.int64)
    mpos = np.asarray(mpos, dtype=np.int64)
    rn = np.asarray(rn, dtype=np.int64)
    rev = np.asarray(rev, dtype=bool)
    # low end: (ref, pos) <= (mate_ref, mate_pos) under string-name compare
    r_rank, m_rank = rank[rid], rank[mrid]
    low_is_self = (r_rank < m_rank) | ((r_rank == m_rank) & (pos <= mpos))
    lo_rid = np.where(low_is_self, rid, mrid)
    hi_rid = np.where(low_is_self, mrid, rid)
    lo_pos = np.where(low_is_self, pos, mpos)
    hi_pos = np.where(low_is_self, mpos, pos)
    low_rn = np.where(low_is_self, rn, 3 - rn)
    low_rev = np.where(low_is_self, rev, ~rev)

    n = len(rid)
    bclen = np.asarray(bclen, dtype=np.int64)
    w = bcm.shape[1] if bcm.ndim == 2 else 0
    bc_starts = np.arange(n, dtype=np.int64) * w
    ori = np.where(low_rev[:, None],
                   np.frombuffer(b"rev", np.uint8)[None, :],
                   np.frombuffer(b"fwd", np.uint8)[None, :])
    rn_chr = (low_rn + ord("0")).astype(np.uint8)[:, None]
    segs = [
        ragged(bcm.reshape(-1), bclen, starts=bc_starts),
        const(b":"),
        ragged(data, lens[lo_rid], starts=starts[lo_rid]),
        const(b":"),
        ints(lo_pos),
        const(b":"),
        ragged(data, lens[hi_rid], starts=starts[hi_rid]),
        const(b":"),
        ints(hi_pos),
        const(b":R"),
        fixed(rn_chr),
        const(b":"),
        fixed(ori),
    ]
    return build_strings(n, segs)


def tag_strings_columnar(
    bcm: np.ndarray, bclen: np.ndarray,
    rid: np.ndarray, pos: np.ndarray, mrid: np.ndarray, mpos: np.ndarray,
    rn: np.ndarray, rev: np.ndarray,
    pool: tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray],
) -> tuple[np.ndarray, np.ndarray]:
    """Byte-exact ``str(FamilyTag)`` (the stats/text form, also the object
    path's emission sort key)."""
    data, starts, lens, _rank = pool
    rid = np.asarray(rid, dtype=np.int64)
    mrid = np.asarray(mrid, dtype=np.int64)
    n = len(rid)
    w = bcm.shape[1] if bcm.ndim == 2 else 0
    bc_starts = np.arange(n, dtype=np.int64) * w
    rn_chr = (np.asarray(rn, np.int64) + ord("0")).astype(np.uint8)[:, None]
    ori = np.where(np.asarray(rev, bool)[:, None],
                   np.frombuffer(b"rev", np.uint8)[None, :],
                   np.frombuffer(b"fwd", np.uint8)[None, :])
    segs = [
        ragged(bcm.reshape(-1), np.asarray(bclen, np.int64), starts=bc_starts),
        const(b"_"),
        ragged(data, lens[rid], starts=starts[rid]),
        const(b"_"),
        ints(pos),
        const(b"_"),
        ragged(data, lens[mrid], starts=starts[mrid]),
        const(b"_"),
        ints(mpos),
        const(b"_R"),
        fixed(rn_chr),
        const(b"_"),
        fixed(ori),
    ]
    return build_strings(n, segs)


def dcs_qnames_columnar(
    canon_bcm: np.ndarray, canon_bclen: np.ndarray,
    rid: np.ndarray, pos: np.ndarray, mrid: np.ndarray, mpos: np.ndarray,
    pool: tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray],
) -> tuple[np.ndarray, np.ndarray]:
    """Byte-exact ``core.tags.dcs_qname`` over columnar pairs.

    ``canon_bcm``/``canon_bclen`` must already hold the canonical barcode
    (lexicographic min of the barcode and its mirror — what
    ``stages.grouping._build_pair_block`` computes as ``canon_bcm``).
    """
    data, starts, lens, rank = pool
    rid = np.asarray(rid, dtype=np.int64)
    mrid = np.asarray(mrid, dtype=np.int64)
    pos = np.asarray(pos, dtype=np.int64)
    mpos = np.asarray(mpos, dtype=np.int64)
    r_rank, m_rank = rank[rid], rank[mrid]
    low_is_self = (r_rank < m_rank) | ((r_rank == m_rank) & (pos <= mpos))
    lo_rid = np.where(low_is_self, rid, mrid)
    hi_rid = np.where(low_is_self, mrid, rid)
    lo_pos = np.where(low_is_self, pos, mpos)
    hi_pos = np.where(low_is_self, mpos, pos)
    n = len(rid)
    w = canon_bcm.shape[1] if canon_bcm.ndim == 2 else 0
    segs = [
        ragged(canon_bcm.reshape(-1), np.asarray(canon_bclen, np.int64),
               starts=np.arange(n, dtype=np.int64) * w),
        const(b":"),
        ragged(data, lens[lo_rid], starts=starts[lo_rid]),
        const(b":"),
        ints(lo_pos),
        const(b":"),
        ragged(data, lens[hi_rid], starts=starts[hi_rid]),
        const(b":"),
        ints(hi_pos),
    ]
    return build_strings(n, segs)


def compare_string_rows(
    data: np.ndarray,
    starts_a: np.ndarray, lens_a: np.ndarray,
    starts_b: np.ndarray, lens_b: np.ndarray,
) -> np.ndarray:
    """Row-wise lexicographic compare of two string columns drawn from the
    same pool: returns int8 per row (-1 a<b, 0 equal, +1 a>b), with Python
    str semantics (shorter prefix sorts first)."""
    lens_a = np.asarray(lens_a, dtype=np.int64)
    lens_b = np.asarray(lens_b, dtype=np.int64)
    starts_a = np.asarray(starts_a, dtype=np.int64)
    starts_b = np.asarray(starts_b, dtype=np.int64)
    n = len(starts_a)
    w = int(max(lens_a.max(initial=0), lens_b.max(initial=0), 1))
    ma = np.zeros((n, w), dtype=np.uint8)
    mb = np.zeros((n, w), dtype=np.uint8)
    scatter_runs(ma.reshape(-1), np.arange(n, dtype=np.int64) * w, data, lens_a,
                 src_starts=starts_a)
    scatter_runs(mb.reshape(-1), np.arange(n, dtype=np.int64) * w, data, lens_b,
                 src_starts=starts_b)
    diff = ma != mb
    has = diff.any(axis=1)
    first = np.argmax(diff, axis=1)
    rows = np.arange(n)
    out = np.zeros(n, dtype=np.int8)
    lt = ma[rows, first] < mb[rows, first]
    out[has & lt] = -1
    out[has & ~lt] = 1
    return out


def lexsort_strings(
    data: np.ndarray, off: np.ndarray,
    leaders: list[np.ndarray] | None = None,
    trailers: list[np.ndarray] | None = None,
) -> np.ndarray:
    """Stable sort permutation by (leaders..., byte string, trailers...).

    Strings sort like Python str on ASCII (shorter prefix first — rows are
    zero-padded and NUL sorts before every ASCII byte).  ``leaders`` are
    most-significant-first numeric keys applied before the string;
    ``trailers`` break ties after it.
    """
    return lexsort_string_refs(data, off[:-1], np.diff(off), leaders, trailers)


def lexsort_string_refs(
    data: np.ndarray, starts: np.ndarray, lens: np.ndarray,
    leaders: list[np.ndarray] | None = None,
    trailers: list[np.ndarray] | None = None,
) -> np.ndarray:
    """:func:`lexsort_strings` over arbitrarily-addressed rows of a pool
    (``starts``/``lens`` need not be contiguous or unique)."""
    n = len(starts)
    lens = np.asarray(lens, dtype=np.int64)
    wmax = int(lens.max(initial=0))
    wpad = max(8, -(-wmax // 8) * 8)
    mat = np.zeros((n, wpad), dtype=np.uint8)
    scatter_runs(mat.reshape(-1), np.arange(n, dtype=np.int64) * wpad, data, lens,
                 src_starts=np.asarray(starts, dtype=np.int64))
    packed = mat.view(">u8")  # (n, wpad//8) big-endian words: numeric == lexicographic
    keys: list[np.ndarray] = []
    if trailers:
        keys.extend(reversed([np.asarray(x) for x in trailers]))
    keys.extend(packed[:, k] for k in range(packed.shape[1] - 1, -1, -1))
    if leaders:
        keys.extend(reversed([np.asarray(x) for x in leaders]))
    return np.lexsort(keys)
