"""CPU oracle for duplex consensus and singleton correction math.

Reference parity target: ``ConsensusCruncher/DCS_maker.py:duplex_consensus``
and the per-base correction step of ``singleton_correction.py`` (both flagged
"(unverified)" in SURVEY.md §2 — the mount was empty; formulas PINNED here).

Pinned semantics, per position ``i`` over two strand sequences:

- base kept iff both strands agree AND the agreed base is not N:
  ``out[i] = s1[i] if s1[i] == s2[i] != N else N``.
- quality of a kept base is the summed evidence of the two strands, capped:
  ``q[i] = min(q1[i] + q2[i], qual_cap)``; disagreeing/N positions get 0.

Singleton correction uses the *same* formula (a singleton corrected against a
complementary-strand partner is exactly a 2-deep duplex vote), so
``duplex_consensus`` is the single source of truth for both stages.
"""

from __future__ import annotations

import numpy as np

from consensuscruncher_tpu.core.consensus_cpu import DEFAULT_QUAL_CAP
from consensuscruncher_tpu.utils.phred import N


def duplex_consensus(
    seq1: np.ndarray,
    qual1: np.ndarray,
    seq2: np.ndarray,
    qual2: np.ndarray,
    qual_cap: int = DEFAULT_QUAL_CAP,
) -> tuple[np.ndarray, np.ndarray]:
    """Elementwise two-strand agreement vote.

    Args: four ``(L,)`` uint8 arrays (base codes / Phred scores).
    Returns: ``(codes, quals)`` — two ``(L,)`` uint8 arrays.
    """
    seq1 = np.asarray(seq1, dtype=np.uint8)
    seq2 = np.asarray(seq2, dtype=np.uint8)
    qual1 = np.asarray(qual1, dtype=np.uint8)
    qual2 = np.asarray(qual2, dtype=np.uint8)
    if not (seq1.shape == seq2.shape == qual1.shape == qual2.shape):
        raise ValueError("duplex inputs must share one (L,) shape")
    if (seq1.size and seq1.max() > N) or (seq2.size and seq2.max() > N):
        raise ValueError("base codes above N (4) — strip PAD before duplex consensus")
    agree = (seq1 == seq2) & (seq1 < N)
    out_base = np.where(agree, seq1, np.uint8(N))
    qsum = qual1.astype(np.int64) + qual2.astype(np.int64)
    out_qual = np.where(agree, np.minimum(qsum, qual_cap), 0).astype(np.uint8)
    return out_base, out_qual


correct_singleton = duplex_consensus
