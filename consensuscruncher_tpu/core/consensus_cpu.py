"""CPU oracle for consensus calling — the semantic ground truth.

Reference parity target: ``ConsensusCruncher/consensus_helper.py:consensus_maker``
(THE hot loop, SURVEY.md §3.3).  The /root/reference mount was empty at build
time, so the quality-aggregation formula and Phred-filter behaviour flagged
"(unverified)" in SURVEY.md are PINNED here as the framework's defined
semantics.  Every backend (numpy fast path, jitted TPU kernel, Pallas kernel,
sharded multi-chip path) must reproduce this function bit-for-bit; the test
suite enforces that.

Pinned semantics, per position ``i`` over a family of ``F`` reads:

1. **Effective base**: read ``j``'s base ``b[j,i]``, demoted to ``N`` when
   ``qual[j,i] < qual_threshold`` (low-quality bases vote for N, keeping the
   denominator at ``F`` — they count *against* every real base).
2. **Modal base**: the effective base with the highest count; ties broken by
   first occurrence in read-list order (CPython ``collections.Counter``
   insertion-order semantics — reproduced exactly on TPU via a first-seen
   index, see ops/consensus_tpu.py).
3. **Cutoff**: the vote passes iff ``count * den >= num * F`` where
   ``cutoff = num/den`` as an exact rational (``cutoff_fraction``).  Exact
   integer comparison makes CPU float64 and TPU float32 agree at boundaries
   like ``0.7 * 10 == 7``.
4. **Output**: if passed and modal base is not N → consensus base = modal
   base, consensus qual = ``min(sum of quals of reads whose effective base is
   the modal base, qual_cap)``.  Otherwise base = N, qual = 0.

Defaults: ``cutoff=0.7`` (reference SSCS_maker ``--cutoff`` default),
``qual_threshold=0`` (no Phred masking unless requested via the
``--qualscore`` surface), ``qual_cap=60`` (duplex-sequencing convention for
summed-evidence caps; unverified upstream, pinned here).
"""

from __future__ import annotations

from collections import Counter
from fractions import Fraction

import numpy as np

from consensuscruncher_tpu.utils.phred import N, NUM_BASES

DEFAULT_CUTOFF = 0.7
DEFAULT_QUAL_CAP = 60
DEFAULT_QUAL_THRESHOLD = 0


def cutoff_fraction(cutoff: float) -> tuple[int, int]:
    """Exact rational ``(num, den)`` for a float cutoff.

    ``limit_denominator(1000)`` recovers the human-entered decimal (0.7 →
    7/10, and 0.333... → 1/3) rather than the float's binary expansion, so
    the integer comparison ``count * den >= num * F`` matches the intent of
    ``count/F >= cutoff``.  The small denominator bound also keeps the
    cross-multiply int32-safe on device for family buckets up to ~2M reads.
    """
    frac = Fraction(cutoff).limit_denominator(1000)
    return frac.numerator, frac.denominator


def _validate_family(seqs: np.ndarray, quals: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Shared input contract for every consensus backend.

    Inputs must be un-padded ``(F, L)`` code arrays with codes in A..N (0..4);
    PAD (5) is a *tensor-layout* artifact that batching layers must mask out
    before consensus (the TPU kernel does this internally via member masks).
    Enforcing the contract here keeps the oracle and the vectorized backends
    bit-identical on every input they can both legally see.
    """
    seqs = np.asarray(seqs, dtype=np.uint8)
    quals = np.asarray(quals, dtype=np.uint8)
    if seqs.ndim != 2 or seqs.shape != quals.shape:
        raise ValueError(f"seqs/quals must be matching (F, L) arrays, got {seqs.shape}/{quals.shape}")
    if seqs.shape[0] == 0:
        raise ValueError("empty family")
    if seqs.size and seqs.max() > N:
        raise ValueError("base codes above N (4) — strip PAD before consensus")
    return seqs, quals


def consensus_maker(
    seqs: np.ndarray,
    quals: np.ndarray,
    cutoff: float = DEFAULT_CUTOFF,
    qual_threshold: int = DEFAULT_QUAL_THRESHOLD,
    qual_cap: int = DEFAULT_QUAL_CAP,
) -> tuple[np.ndarray, np.ndarray]:
    """Collapse one UMI family to a consensus sequence + qualities.

    Args:
      seqs:  ``(F, L)`` uint8 base codes (A=0..N=4), one row per family member.
      quals: ``(F, L)`` uint8 Phred scores.
      cutoff / qual_threshold / qual_cap: see module docstring.

    Returns:
      ``(consensus_codes, consensus_quals)`` — two ``(L,)`` uint8 arrays.

    This is the readable, obviously-correct oracle (Counter-based, Python
    loops).  Use ``ops.consensus_numpy``/``ops.consensus_tpu`` for speed.
    """
    seqs, quals = _validate_family(seqs, quals)
    fam, length = seqs.shape
    num, den = cutoff_fraction(cutoff)

    out_base = np.full(length, N, dtype=np.uint8)
    out_qual = np.zeros(length, dtype=np.uint8)

    for i in range(length):
        counter: Counter = Counter()
        for j in range(fam):
            b = seqs[j, i]
            eff = N if quals[j, i] < qual_threshold else int(b)
            counter[eff] += 1
        modal, count = counter.most_common(1)[0]
        if modal != N and count * den >= num * fam:
            qsum = 0
            for j in range(fam):
                if seqs[j, i] == modal and quals[j, i] >= qual_threshold:
                    qsum += int(quals[j, i])
            out_base[i] = modal
            out_qual[i] = min(qsum, qual_cap)
    return out_base, out_qual


def consensus_maker_numpy(
    seqs: np.ndarray,
    quals: np.ndarray,
    cutoff: float = DEFAULT_CUTOFF,
    qual_threshold: int = DEFAULT_QUAL_THRESHOLD,
    qual_cap: int = DEFAULT_QUAL_CAP,
) -> tuple[np.ndarray, np.ndarray]:
    """Vectorized CPU backend, bit-identical to ``consensus_maker``.

    Same algorithm as the TPU kernel (one-hot counts, first-seen tie-break,
    rational cutoff) expressed in numpy — this is the ``--backend cpu`` fast
    path and doubles as an executable spec for ops/consensus_tpu.py.
    """
    seqs, quals = _validate_family(seqs, quals)
    fam, length = seqs.shape
    num, den = cutoff_fraction(cutoff)

    eff = np.where(quals < qual_threshold, np.uint8(N), seqs)  # (F, L)
    onehot = eff[:, :, None] == np.arange(NUM_BASES, dtype=np.uint8)  # (F, L, 5)
    counts = onehot.sum(axis=0, dtype=np.int64)  # (L, 5)
    member_idx = np.arange(fam, dtype=np.int64)[:, None, None]
    first_seen = np.where(onehot, member_idx, fam).min(axis=0)  # (L, 5)
    # Lexicographic (count desc, first_seen asc) via a single integer score.
    score = counts * (fam + 1) + (fam - first_seen)
    modal = score.argmax(axis=1)  # (L,) — ties impossible: distinct first_seen
    modal_count = np.take_along_axis(counts, modal[:, None], axis=1)[:, 0]
    passed = (modal != N) & (modal_count * den >= num * fam)
    # Quality sum over reads whose ORIGINAL base equals the modal base and
    # passes the threshold (matches the oracle's agreeing-read definition;
    # for modal != N these are exactly the reads whose effective base agrees).
    agree = (seqs == modal[None, :].astype(np.uint8)) & (quals >= qual_threshold)
    qsum = np.where(agree, quals.astype(np.int64), 0).sum(axis=0)
    out_base = np.where(passed, modal, N).astype(np.uint8)
    out_qual = np.where(passed, np.minimum(qsum, qual_cap), 0).astype(np.uint8)
    return out_base, out_qual
