"""Streaming dataflow primitives: bounded channels, operator threads, taps.

ROADMAP item 2 (the stage→BAM→stage materialization collapse): in
``--pipeline streaming`` mode the consensus chain moves sorted record
batches between stages as bounded in-memory flows instead of writing,
BGZF-deflating, re-reading and re-sorting an intermediate BAM at every
stage boundary.  The pieces here are deliberately small:

- :class:`Channel` — a bounded queue with backpressure.  ``put`` blocks
  once ``capacity`` items are in flight; ``fail`` poisons the channel so
  errors cross thread boundaries exactly once and promptly.
- :class:`Operator` — a daemon producer thread pumping an iterable into
  a channel, converting its exceptions (including injected faults) into
  channel poison rather than silent thread death.
- :class:`BatchStream` — bounded read-ahead over an in-memory BAM,
  duck-compatible with ``ColumnarReader`` (``.header`` / ``.batches()``
  / ``.close()``) so unchanged stage code consumes it transparently.
- :class:`StreamOut` — the capture surface stages hand their sorted
  outputs to: keeps the in-memory BAM for the next stage and schedules
  any file materialization (finals always, intermediates only as debug
  taps) on a bounded write-behind pool, overlapping deflate+IO with the
  next stage's device compute.

Fault sites: ``stream.channel_full`` fires at the moment backpressure
engages (a wedged consumer must abort the run, not deadlock it) and
``stream.operator_fail`` fires once per pumped item (a mid-stream
producer fault must poison the channel and surface at the consumer).
Both are the trip wires the CLI's fall-back-to-staged path is tested
against.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Iterable, Iterator

from consensuscruncher_tpu.parallel.prefetch import WriteBehind
from consensuscruncher_tpu.utils import faults

_SENTINEL = object()


class ChannelClosed(RuntimeError):
    """``put()`` on a channel whose consumer side has gone away."""


class Channel:
    """Bounded producer→consumer channel with backpressure.

    Single-consumer, any number of producers.  ``close()`` ends iteration
    once queued items drain; ``fail(exc)`` drops queued items and
    re-raises ``exc`` at the consumer's next pull (fail-fast: a poisoned
    stage must not keep feeding the stage downstream).
    """

    def __init__(self, capacity: int = 2):
        self._cap = max(1, int(capacity))
        self._q: deque = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._exc: BaseException | None = None

    def _check_open(self) -> None:
        if self._closed or self._exc is not None:
            raise ChannelClosed("channel closed under the producer")

    def put(self, item) -> None:
        with self._cond:
            self._check_open()
            full = len(self._q) >= self._cap
        if full:
            # Backpressure engaged: visible to fault injection so chaos
            # tests can prove the slow-consumer path aborts cleanly.
            faults.fault_point("stream.channel_full")
        with self._cond:
            while len(self._q) >= self._cap:
                self._check_open()
                self._cond.wait(0.5)
            self._check_open()
            self._q.append(item)
            self._cond.notify_all()

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def fail(self, exc: BaseException) -> None:
        with self._cond:
            if self._exc is None:
                self._exc = exc
            self._closed = True
            self._q.clear()
            self._cond.notify_all()

    def get(self):
        """Next item, ``_SENTINEL`` at clean end, raises on poison."""
        with self._cond:
            while True:
                if self._exc is not None:
                    raise self._exc
                if self._q:
                    item = self._q.popleft()
                    self._cond.notify_all()
                    return item
                if self._closed:
                    return _SENTINEL
                self._cond.wait(0.5)

    def __iter__(self) -> Iterator:
        while True:
            item = self.get()
            if item is _SENTINEL:
                return
            yield item


class Operator:
    """Daemon thread pumping ``source`` into ``out``.

    ``source`` is an iterable or a zero-arg callable returning one (use a
    callable when building the iterator itself is expensive — it then
    runs on the operator thread, not the caller's).  The thread starts
    immediately, so read-ahead begins before the consumer's first pull.
    Exceptions poison ``out``; a consumer that walks away (``fail`` on
    the channel) just ends the pump quietly.
    """

    def __init__(self, name: str,
                 source: Iterable | Callable[[], Iterable],
                 out: Channel):
        self.name = name
        self._src = source
        self._out = out
        self._thread = threading.Thread(
            target=self._run, name=f"cct-stream-{name}", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        try:
            it = self._src() if callable(self._src) else self._src
            for item in it:
                faults.fault_point("stream.operator_fail")
                self._out.put(item)
        except ChannelClosed:
            pass  # consumer closed first: normal teardown
        except BaseException as exc:
            self._out.fail(exc)
        else:
            self._out.close()

    def join(self, timeout: float | None = None) -> None:
        self._thread.join(timeout)


class BatchStream:
    """Bounded read-ahead over an in-memory BAM's record batches.

    Wraps anything exposing ``.header`` / ``.batches()`` / ``.close()``
    (a :class:`~consensuscruncher_tpu.io.columnar.MemoryBam` between
    stages) and serves the same interface, with an :class:`Operator`
    slicing batches ``capacity`` ahead of the consumer — the host-side
    gather/copy overlaps the consumer's device compute, mirroring
    ``parallel.prefetch``'s double-buffering on the input side.
    """

    def __init__(self, source, capacity: int = 2,
                 batch_bytes: int | None = None):
        self._source = source
        self.header = source.header
        self._capacity = max(1, int(capacity))
        self._batch_bytes = batch_bytes
        self._chan: Channel | None = None
        self._op: Operator | None = None

    def batches(self) -> Iterator:
        chan = Channel(self._capacity)
        src = self._source
        if self._batch_bytes is None:
            op = Operator("batches", src.batches, chan)
        else:
            bb = self._batch_bytes
            op = Operator("batches", lambda: src.batches(batch_bytes=bb), chan)
        self._chan, self._op = chan, op
        return iter(chan)

    def close(self) -> None:
        if self._chan is not None:
            # Release a producer blocked on a full channel before closing
            # the underlying source it is reading from.
            self._chan.fail(ChannelClosed("stream consumer closed"))
            if self._op is not None:
                self._op.join(timeout=30.0)
        self._source.close()


class StreamOut:
    """Capture surface for stage outputs in streaming mode.

    Stages call ``capture(name, mem, file_path=...)`` with the sorted
    in-memory BAM they would otherwise have committed to disk.  The
    memory is kept for the next stage; when ``file_path`` is given (final
    outputs always; intermediates only when the run asked for debug taps)
    the BGZF materialization runs on a bounded write-behind pool so
    deflate+IO overlaps downstream compute.  ``drain()`` re-raises the
    first background write failure — the CLI treats that as a fault-site
    trip and falls back to the staged pipeline (atomic tmp+rename writes
    make half-written finals invisible).
    """

    def __init__(self, taps: bool = False, depth: int = 2):
        self.taps = bool(taps)
        self.memory: dict[str, object] = {}
        self._wb = WriteBehind(depth=depth)

    def capture(self, name: str, mem, file_path=None, level: int = 6,
                index: bool = True) -> None:
        self.memory[name] = mem
        if file_path is not None:
            self._wb.submit(mem.write, file_path, level=level, index=index)

    def submit(self, fn, *args, **kwargs) -> None:
        """Run ``fn`` on the write-behind pool (e.g. an all_unique merge
        that can overlap the next stage's device compute)."""
        self._wb.submit(fn, *args, **kwargs)

    def drain(self) -> None:
        self._wb.drain()

    def abort(self) -> None:
        self._wb.abort()
