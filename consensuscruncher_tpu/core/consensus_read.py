"""Build output BAM records from consensus results.

Reference parity: ``ConsensusCruncher/consensus_helper.py:create_aligned_segment``
(SURVEY.md §2 — builds the output ``pysam.AlignedSegment`` from a template
read).  Pinned semantics (mount empty):

- **template** = first read of the family in stream order (deterministic:
  grouping emits reads in coordinate order).
- **flag** keeps only the pairing/strand/readnumber bits (paired, proper,
  reverse, mate-reverse, read1, read2); consensus reads are never secondary/
  supplementary/dup/qcfail by construction.
- **cigar** = modal cigar string over the family (ties → first seen in family
  order), matching the Counter semantics used everywhere else.
- **mapq** = max over the family (best evidence for the mapping).
- coordinates/tlen from the template; qname supplied by the caller
  (``sscs_qname``/``dcs_qname``).

Framework-native BAM tags on every consensus read (self-contained lineage —
the TPU-era replacement for re-deriving tags from qnames):

- ``XT:Z`` the family tag string (lets DCS/singleton stages mirror without
  re-parsing qnames),
- ``XF:i`` the family size (evidence depth).
"""

from __future__ import annotations

from collections import Counter

import numpy as np

from consensuscruncher_tpu.core.tags import FamilyTag
from consensuscruncher_tpu.io.bam import (
    BamRead,
    FMREVERSE,
    FPAIRED,
    FPROPER,
    FREAD1,
    FREAD2,
    FREVERSE,
    cigar_from_string,
)
from consensuscruncher_tpu.utils.phred import decode_seq

_KEEP_FLAGS = FPAIRED | FPROPER | FREVERSE | FMREVERSE | FREAD1 | FREAD2


def modal_cigar(members: list[BamRead], seq_length: int) -> list[tuple[str, int]]:
    """Modal cigar among members whose read length matches the consensus
    length (ties → first seen).  Restricting to length-matched members keeps
    the cigar's query span consistent with the consensus seq — a cigar from a
    shorter/longer member would make a malformed record.

    ``members`` may be ``io.bam.BamRead`` or the columnar ``MemberView`` —
    both expose ``seq_len`` / ``cigar_string()`` / ``mapq``."""
    candidates = [m for m in members if m.seq_len == seq_length]
    if not candidates:  # all members truncated (target longer than every read)
        return [("M", seq_length)]
    counts = Counter(m.cigar_string() for m in candidates)
    return cigar_from_string(counts.most_common(1)[0][0])


def build_consensus_read(
    tag: FamilyTag,
    members: list[BamRead],
    codes: np.ndarray,
    quals: np.ndarray,
    qname: str,
    extra_tags: dict | None = None,
) -> BamRead:
    template = members[0]
    bam_tags = {
        "XT": ("Z", str(tag)),
        "XF": ("i", len(members)),
    }
    if extra_tags:
        bam_tags.update(extra_tags)
    return BamRead(
        qname=qname,
        flag=template.flag & _KEEP_FLAGS,
        ref=template.ref,
        pos=template.pos,
        mapq=max(m.mapq for m in members),
        cigar=modal_cigar(members, len(codes)),
        mate_ref=template.mate_ref,
        mate_pos=template.mate_pos,
        tlen=template.tlen,
        seq=decode_seq(codes),
        qual=np.asarray(quals, dtype=np.uint8),
        tags=bam_tags,
    )
